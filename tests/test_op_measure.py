"""Per-op measured cost grounding (VERDICT r3 #6; reference
measure_operator_cost model.cu:20-62)."""

import os

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, Strategy, make_mesh
from flexflow_tpu.search import op_measure
from flexflow_tpu.search.simulator import Simulator


@pytest.fixture(autouse=True)
def isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("FLEXFLOW_TPU_CACHE", str(tmp_path))
    op_measure.clear_memo()
    yield
    op_measure.clear_memo()


def build(measure_n=0, layers=3, width=256):
    cfg = FFConfig(batch_size=64)
    cfg.measure_top_ops = measure_n
    ff = FFModel(cfg)
    x = ff.create_tensor((64, width), name="input")
    t = x
    for i in range(layers):
        t = ff.dense(t, width, activation="relu", name=f"fc{i}")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    return ff


def test_measure_op_returns_positive_times_and_caches():
    ff = build()
    op = next(o for o in ff.ops if o.name == "fc0")
    m1 = op_measure.measure_op(op, sample_shard=1, repeats=3)
    assert m1 is not None and m1["fwd"] > 0 and m1["bwd"] > 0
    # memoized: second call returns the identical dict
    assert op_measure.measure_op(op, sample_shard=1) is m1
    # persisted: a fresh process-level memo reloads from disk
    kind = op_measure._device_kind()
    assert os.path.exists(op_measure._cache_path(kind))
    op_measure._MEMO.clear()
    op_measure._DISK_LOADED.clear()
    m2 = op_measure.measure_op(op, sample_shard=1)
    assert m2 == m1


def test_signature_distinguishes_shapes_not_names():
    ff = build()
    fc0 = next(o for o in ff.ops if o.name == "fc0")
    fc1 = next(o for o in ff.ops if o.name == "fc1")
    head = next(o for o in ff.ops if o.name == "head")
    # same shapes -> same measurement key (one timing covers both)
    assert op_measure.op_signature(fc0, 1) == \
        op_measure.op_signature(fc1, 1)
    assert op_measure.op_signature(fc0, 1) != \
        op_measure.op_signature(head, 1)
    # sharded batch is part of the key
    assert op_measure.op_signature(fc0, 1) != \
        op_measure.op_signature(fc0, 2)


def test_simulator_overrides_top_ops_with_measurements():
    mesh = make_mesh((8,), ("data",))
    ff_a = build(measure_n=0)
    ff_m = build(measure_n=2)
    sim_a = Simulator(ff_a, mesh)
    sim_m = Simulator(ff_m, mesh)
    assert sim_a._measured_set == set()
    # N caps measurement SIGNATURES (jit compiles), not ops: the three
    # same-shape fc layers share one signature, so 2 signatures cover
    # fc0/fc1/fc2 + head (4 ops, 2 compiles)
    assert sim_m._measured_set == {"fc0", "fc1", "fc2", "head"}
    # measured costs differ from analytic (TPU roofline vs real CPU)
    s = Strategy()
    big = next(iter(sorted(sim_m._measured_set)))
    op = next(o for o in ff_m.ops if o.name == big)
    ca = sim_a._op_cost(op, s)
    cm = sim_m._op_cost(op, s)
    assert cm.fwd != ca.fwd
    assert cm.fwd > 0
    # comm/sync/memory terms keep the analytic model
    assert cm.sync == ca.sync and cm.mem == ca.mem


def test_unmeasurable_op_keeps_analytic_cost():
    ff = build()
    op = next(o for o in ff.ops if o.name == "fc0")

    def boom(*a, **k):
        raise RuntimeError("no device")

    orig = op.forward
    op.forward = boom
    try:
        assert op_measure.measure_op(op, sample_shard=1) is None
        # cached as unmeasurable: no retry storm
        assert op_measure.measure_op(op, sample_shard=1) is None
    finally:
        op.forward = orig


def test_integer_input_ops_are_measurable():
    """Embedding-style ops (int index inputs) must measure — grad runs
    w.r.t. params/float inputs only (the -74% dlrm residual's cause)."""
    import jax.numpy as jnp
    from flexflow_tpu import FFModel
    ff = FFModel(FFConfig(batch_size=32))
    ids = ff.create_tensor((32, 4), dtype=jnp.int32, name="ids")
    t = ff.embedding(ids, 1000, 16, aggr="sum", name="emb")
    ff.softmax(ff.dense(t, 4, name="head"))
    op = next(o for o in ff.ops if o.op_type == "embedding")
    m = op_measure.measure_op(op, sample_shard=1, repeats=3)
    assert m is not None and m["fwd"] > 0 and m["bwd"] > 0


def test_native_table_gets_measured_costs():
    """Both engines rank on the same grounded numbers: the native cost
    table routes through Simulator.measured_adjust."""
    from flexflow_tpu.parallel.pconfig import OpStrategy
    mesh = make_mesh((8,), ("data",))
    ff = build(measure_n=2)
    sim = Simulator(ff, mesh)
    op = next(o for o in ff.ops
              if o.name in sorted(sim._measured_set))
    s = OpStrategy({"sample": "data"})
    from flexflow_tpu.search.cost_model import op_cost
    analytic = op_cost(op, s, mesh, sim.mm)
    adjusted = sim.measured_adjust(op, s, analytic)
    assert adjusted.fwd != analytic.fwd


def test_failed_measurement_not_persisted():
    """In-process memo remembers a failure; the DISK cache must not (a
    transient failure would otherwise pin the analytic cost forever —
    measure.py's calibration has the same policy)."""
    import json as _json
    ff = build()
    ok_op = next(o for o in ff.ops if o.name == "fc0")
    bad_op = next(o for o in ff.ops if o.name == "fc1")
    orig = bad_op.forward
    bad_op.forward = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("transient"))
    try:
        assert op_measure.measure_op(bad_op, sample_shard=2) is None
        assert op_measure.measure_op(ok_op, repeats=2) is not None
    finally:
        bad_op.forward = orig
    kind = op_measure._device_kind()
    with open(op_measure._cache_path(kind)) as f:
        assert None not in _json.load(f).values()
    # a fresh process retries the failed signature and now succeeds
    op_measure._MEMO.clear()
    op_measure._DISK_LOADED.clear()
    assert op_measure.measure_op(bad_op, sample_shard=2,
                                 repeats=2) is not None


def test_stateful_op_is_measurable():
    """BatchNorm reads ctx.state_in (running stats); measure_op must
    feed init-valued state rather than cache the op as unmeasurable —
    conv nets put a BN after every conv, so an unmeasurable BN leaves
    a third of the graph's memory-bound ops at the analytic price."""
    cfg = FFConfig(batch_size=8)
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16, 8, 8), name="input")
    t = ff.conv2d(x, 16, 3, 3, 1, 1, 1, 1, name="c0")
    t = ff.batch_norm(t, name="bn0")
    ff.softmax(ff.dense(ff.flat(t), 10, name="head"))
    bn = next(o for o in ff.ops if o.name == "bn0")
    assert bn.state_specs()  # the premise: BN is stateful
    m = op_measure.measure_op(bn, sample_shard=1, repeats=3)
    assert m is not None and m["fwd"] > 0 and m["bwd"] > 0


@pytest.fixture
def _clean_insitu_memo():
    """The memo is module-global and keyed by the REAL device kind — a
    leaked fake factor would silently scale conv costs for any later
    test that grounds ops in this process, even when THIS test fails
    mid-way."""
    op_measure._INSITU.clear()
    yield
    op_measure._INSITU.clear()


def test_conv_in_situ_factor_cached_and_clamped(tmp_path, monkeypatch,
                                                _clean_insitu_memo):
    """The isolated->in-situ conv correction: measured once, persisted
    per device kind, clamped to [1, 3], and 1.0 on failure (grounding
    must degrade to uncorrected, never break the search)."""
    monkeypatch.setattr(op_measure, "_insitu_path",
                        lambda kind: str(tmp_path / f"insitu_{kind}.json"))
    monkeypatch.setattr(op_measure, "_measure_insitu_factor",
                        lambda: 1.8)
    f = op_measure.conv_in_situ_factor()
    assert f == 1.8
    # second call: memo, no re-measure
    monkeypatch.setattr(op_measure, "_measure_insitu_factor",
                        lambda: (_ for _ in ()).throw(AssertionError))
    assert op_measure.conv_in_situ_factor() == 1.8
    # fresh process analog: memo cleared, disk cache serves
    op_measure._INSITU.clear()
    assert op_measure.conv_in_situ_factor() == 1.8
    # failure path -> 1.0 in-process AND NOT persisted (a cached
    # failure would defeat re-measurement forever)
    op_measure._INSITU.clear()
    fail_path = tmp_path / "other.json"
    monkeypatch.setattr(op_measure, "_insitu_path",
                        lambda kind: str(fail_path))
    monkeypatch.setattr(op_measure, "_measure_insitu_factor",
                        lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert op_measure.conv_in_situ_factor() == 1.0
    assert not fail_path.exists()

    # corrupt/out-of-range disk values clamp on load: 100 -> 3, 0 -> 1,
    # NaN -> 1
    import json as _json
    for raw, want in ((100.0, 3.0), (0.0, 1.0), (float("nan"), 1.0)):
        op_measure._INSITU.clear()
        fail_path.write_text(_json.dumps({"factor": raw}))
        assert op_measure.conv_in_situ_factor() == want

    # out-of-range MEASURED values clamp before persisting
    op_measure._INSITU.clear()
    fail_path.unlink()
    monkeypatch.setattr(op_measure, "_measure_insitu_factor",
                        lambda: 40.0)
    assert op_measure.conv_in_situ_factor() == 3.0
    assert _json.loads(fail_path.read_text())["factor"] == 3.0
