"""Fault-tolerance suite (docs/robustness.md).

Layered like the serve suites:
  * harness — FaultSpec parsing and FaultInjector determinism: a chaos
    run must replay bit-for-bit from (spec, seed).
  * serve — injected transient dispatch errors are retried invisibly
    (outputs stay token-identical to generate_reference, zero
    recompiles); a fatal mid-batch error fails ONLY the in-flight
    requests and the engine keeps serving on the same compiled program
    (the engine.py hard-brick regression); cancels and deadlines abort
    at chunk boundaries with pages reclaimed; injected page-pool
    pressure climbs the degradation ladder without ever changing a
    surviving token.
  * chaos — a seeded random interleaving of cancels, deadlines,
    transient faults and page exhaustion; check_invariants after every
    engine step; survivors exactly equal the reference.
  * crash-safe state — kill-mid-save leaves no truncated checkpoint
    visible and a restarted fit resumes to a bit-identical loss
    trajectory; loader-state and cost-cache files share the
    temp-then-os.replace contract.
"""

import os

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.serve import RequestOutcome, ServeEngine
from flexflow_tpu.utils import faults
from flexflow_tpu.utils.faults import (FaultInjector, FaultSpec,
                                       InjectedFault, SimulatedKill,
                                       TransientError)


# ------------------------------------------------------------- harness
def test_fault_spec_parsing():
    spec = FaultSpec("serve.mixed:transient@2,5-7,%4;"
                     "serve.page_pressure:exhaust:0.6@3+;"
                     "ckpt.commit:kill@1")
    assert set(spec.by_site) == {"serve.mixed", "serve.page_pressure",
                                 "ckpt.commit"}
    cl = spec.by_site["serve.mixed"][0]
    assert cl.kind == "transient"
    hits = [n for n in range(1, 13) if cl.matches(n, None)]
    assert hits == [2, 4, 5, 6, 7, 8, 12]
    ex = spec.by_site["serve.page_pressure"][0]
    assert ex.kind == "exhaust" and ex.value == 0.6
    assert [n for n in range(1, 6) if ex.matches(n, None)] == [3, 4, 5]
    assert not FaultSpec("")
    for bad in ("site@3", "site:bogus@1", "site:fatal@0", "site:fatal",
                "site:transient@~1.5", "site:transient@5-2"):
        with pytest.raises(ValueError):
            FaultSpec(bad)


def test_injector_kinds_and_counters():
    inj = FaultInjector("a:transient@2;b:fatal@1;c:kill@1;"
                        "p:exhaust:0.5@2")
    inj.fire("a")                      # hit 1: clean
    with pytest.raises(TransientError):
        inj.fire("a")                  # hit 2: fires
    inj.fire("a")                      # hit 3: clean again
    assert inj.hits("a") == 3
    with pytest.raises(InjectedFault):
        inj.fire("b")
    # SimulatedKill must NOT be an Exception: `except Exception`
    # recovery code cannot observe a kill -9
    with pytest.raises(SimulatedKill):
        inj.fire("c")
    assert not issubclass(SimulatedKill, Exception)
    assert inj.level("p") == 0.0 and inj.level("p") == 0.5
    assert inj.fired["a"]["transient"] == 1
    inj.fire("unknown.site")           # spec-less sites are free no-ops
    assert inj.hits("unknown.site") == 0


def test_injector_probability_seeded():
    a = FaultInjector("s:transient@~0.3", seed=7)
    b = FaultInjector("s:transient@~0.3", seed=7)

    def pattern(inj):
        out = []
        for _ in range(64):
            try:
                inj.fire("s")
                out.append(0)
            except TransientError:
                out.append(1)
        return out

    pa = pattern(a)
    assert pa == pattern(b), "same (spec, seed) must replay exactly"
    assert 0 < sum(pa) < 64
    c = FaultInjector("s:transient@~0.3", seed=8)
    assert pattern(c) != pa


def test_config_validates_fault_spec():
    FFConfig(fault_spec="serve.mixed:transient@1")   # well-formed: fine
    with pytest.raises(ValueError):
        FFConfig(fault_spec="serve.mixed:bogus@1")
    with pytest.raises(ValueError):
        FFConfig(serve_max_retries=-1)
    with pytest.raises(ValueError):
        FFConfig(serve_request_deadline=-0.5)


# ------------------------------------------------------------- serve
@pytest.fixture(scope="module")
def lm():
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   serve_retry_backoff_s=0.0)
    return build_transformer_lm(cfg, vocab_size=89, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


@pytest.fixture(scope="module")
def eng(lm):
    """A fault-free engine for cancel/deadline tests (aborts must not
    dirty it — that is part of what the tests assert)."""
    e = ServeEngine(lm)
    e.warmup()
    return e


def _prompts(rng, n, vocab=89, lo=4, hi=28):
    return [list(rng.randint(1, vocab, size=rng.randint(lo, hi)))
            for _ in range(n)]


def _assert_clean(engine):
    engine.cache.check_invariants()
    assert engine.cache.free_slots == engine.cache_cfg.max_seqs
    assert engine.cache.free_pages == engine.cache_cfg.usable_pages


def test_transient_dispatch_retried_exact(lm):
    # warmup is serve.mixed hit 1; hits 3 and 5 fail once each and the
    # bounded retry (serve_max_retries=3 default) absorbs both
    e = ServeEngine(lm, faults=FaultInjector("serve.mixed:transient@3,5"))
    e.warmup()
    rng = np.random.RandomState(0)
    prompts = _prompts(rng, 5)
    before = e.compile_counts()
    out = e.generate(prompts, 6)
    assert e.compile_counts() == before, "retries must not recompile"
    assert out == e.generate_reference(prompts, 6)
    assert e.last_stats["retries"] == 2
    assert all(r["outcome"] == RequestOutcome.COMPLETED
               for r in e.last_stats["requests"])
    _assert_clean(e)


def test_transient_exhausts_retries_then_engine_survives(lm):
    # hits 2-6 fail: the first generate burns 1 + 3 retries (hits 2-5)
    # and raises; the next generate hits 6 (fail) then 7 (success) —
    # the batch after a failure serves normally with one retry
    e = ServeEngine(lm, faults=FaultInjector("serve.mixed:transient@2-6"))
    e.warmup()
    rng = np.random.RandomState(1)
    prompts = _prompts(rng, 4)
    with pytest.raises(TransientError):
        e.generate(prompts, 4)
    _assert_clean(e)
    out = e.generate(prompts, 4)
    assert out == e.generate_reference(prompts, 4)
    assert e.last_stats["retries"] == 1
    _assert_clean(e)


def test_fatal_midbatch_fails_requests_not_engine(lm):
    """The engine.py hard-brick regression (ISSUE satellite): an
    exception mid-generate() must fail only the in-flight requests;
    the SAME engine then serves a fresh batch token-identical to the
    reference on the same compiled program."""
    e = ServeEngine(lm, faults=FaultInjector("serve.mixed:fatal@4"))
    counts = e.warmup()
    rng = np.random.RandomState(2)
    with pytest.raises(InjectedFault):
        e.generate(_prompts(rng, 6), 8)
    _assert_clean(e)
    prompts = _prompts(rng, 6)
    out = e.generate(prompts, 6)
    assert out == e.generate_reference(prompts, 6)
    assert e.compile_counts() == counts, "recovery must not recompile"
    _assert_clean(e)


def test_orphaned_slots_self_heal(eng):
    """Slots leaked by a crashed driver (or a user poking the cache)
    are reclaimed at the next generate() instead of the old
    'build a fresh ServeEngine' RuntimeError."""
    cache = eng.cache
    s = cache.alloc_slot()
    cache.ensure_capacity(s, 20)
    cache.advance(s, 20)
    assert cache.free_slots != eng.cache_cfg.max_seqs
    prompts = [[3, 5, 7, 11], [13, 17]]
    out = eng.generate(prompts, 5)      # heals, then serves
    assert out == eng.generate_reference(prompts, 5)
    assert cache.stats["slots_reclaimed"] >= 1
    _assert_clean(eng)


def test_cancel_mid_generate(eng):
    rng = np.random.RandomState(3)
    prompts = _prompts(rng, 4, lo=4, hi=12)
    ref = eng.generate_reference(prompts, 12)
    cancelled = {1}

    def on_step(step):
        if step == 3:
            assert eng.cancel(1)
        eng.cache.check_invariants()

    out = eng.generate(prompts, 12, on_step=on_step)
    st = eng.last_stats
    for i in range(4):
        if i in cancelled:
            n = len(out[i])
            assert n < 12, "cancel must land before completion"
            assert out[i] == ref[i][:n], "partial stream must be a " \
                "prefix of the reference"
            assert st["requests"][i]["outcome"] == RequestOutcome.CANCELLED
        else:
            assert out[i] == ref[i]
            assert st["requests"][i]["outcome"] == RequestOutcome.COMPLETED
    assert st["cancelled"] == 1
    assert eng.cancel(999) is False     # stale rid outside a batch
    _assert_clean(eng)


def test_deadline_expires_structured(eng):
    rng = np.random.RandomState(4)
    prompts = _prompts(rng, 3, lo=4, hi=10)
    ref = eng.generate_reference(prompts, 6)
    # request 0: immediate expiry (swept before its first chunk);
    # request 1: no deadline; request 2: generous deadline
    out = eng.generate(prompts, 6, deadline_s=[1e-9, None, 60.0])
    st = eng.last_stats
    assert out[0] == [] and \
        st["requests"][0]["outcome"] == RequestOutcome.DEADLINE_EXPIRED
    assert st["requests"][0]["ttft_s"] is None
    assert out[1] == ref[1] and out[2] == ref[2]
    assert st["deadline_expired"] == 1
    # the report renders aborted rows (None ttft/latency) and the
    # robustness counters
    from flexflow_tpu.utils.profiling import serve_report
    rep = serve_report(st)
    assert "deadline_expired" in rep and "robustness:" in rep
    _assert_clean(eng)


def test_default_deadline_from_config(eng):
    prev = eng.default_deadline
    eng.default_deadline = 1e-9
    try:
        out = eng.generate([[5, 6, 7], [11, 3]], 4)
    finally:
        eng.default_deadline = prev
    assert out == [[], []]
    assert eng.last_stats["deadline_expired"] == 2
    _assert_clean(eng)


def test_page_pressure_climbs_ladder_exact(lm):
    """Injected page-pool exhaustion (70% of the pool hidden from
    planning) must climb the degradation ladder — shedding speculation
    and prefix matching — while every surviving token stays identical
    to the reference."""
    e = ServeEngine(
        lm, faults=FaultInjector("serve.page_pressure:exhaust:0.7@1+"))
    counts = e.warmup()
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, 8, lo=8, hi=28)
    out = e.generate(prompts, 8, on_step=lambda s:
                     e.cache.check_invariants())
    assert out == e.generate_reference(prompts, 8)
    st = e.last_stats
    assert st["degradation_rung_max"] >= 1
    assert sum(st["rung_steps"][1:]) > 0
    assert e.compile_counts() == counts
    _assert_clean(e)


def test_full_exhaustion_rejects_structured(lm):
    """With the whole pool hidden, requests that cannot get even one
    chunk's pages are REJECTED (structured outcome) instead of
    deadlocking the step or raising out of the batch — and the engine
    serves the next batch normally."""
    e = ServeEngine(
        lm, faults=FaultInjector("serve.page_pressure:exhaust:1.0@1"))
    e.warmup()
    prompts = [[3, 4, 5], [6, 7]]
    out = e.generate(prompts, 4)
    st = e.last_stats
    assert out == [[], []]
    assert st["rejected"] == 2
    assert len(st["rejected_requests"]) == 2
    assert all(r["outcome"] == RequestOutcome.REJECTED
               for r in st["requests"])
    assert st["degradation_rung_max"] == 4
    _assert_clean(e)
    # the pressure clause hit only the first scheduling step: normal
    # service resumes on the very next batch
    out = e.generate(prompts, 4)
    assert out == e.generate_reference(prompts, 4)
    assert e.last_stats["rejected"] == 0
    _assert_clean(e)


def test_ladder_disabled_freezes_rung(lm):
    e = ServeEngine(
        lm, faults=FaultInjector("serve.page_pressure:exhaust:0.7@1+"))
    e.degrade_ladder = False
    e.warmup()
    rng = np.random.RandomState(6)
    prompts = _prompts(rng, 4)
    out = e.generate(prompts, 5)
    assert out == e.generate_reference(prompts, 5)
    assert e.last_stats["degradation_rung_max"] == 0
    _assert_clean(e)


def test_ladder_disabled_keeps_pool_too_small_raise(lm):
    """--no-degrade-ladder keeps the pre-ladder contract: an
    unservable head RAISES instead of being silently rejected — and
    crash containment still leaves the engine serving."""
    e = ServeEngine(
        lm, faults=FaultInjector("serve.page_pressure:exhaust:1.0@1"))
    e.degrade_ladder = False
    e.warmup()
    with pytest.raises(RuntimeError, match="page pool too small"):
        e.generate([[3, 4, 5]], 4)
    _assert_clean(e)
    out = e.generate([[3, 4, 5]], 4)        # pressure clause spent
    assert out == e.generate_reference([[3, 4, 5]], 4)
    _assert_clean(e)


def test_rung_steps_is_per_step_histogram(lm):
    """rung_steps sums to the number of scheduling steps even when one
    step rejects several requests (a rejection step counts once, as
    rung 4)."""
    e = ServeEngine(
        lm, faults=FaultInjector("serve.page_pressure:exhaust:1.0@1"))
    e.warmup()
    e.generate([[3, 4, 5], [6, 7], [8, 9, 10]], 4)
    st = e.last_stats
    assert st["rejected"] == 3
    assert st["rung_steps"][4] == 1, (
        "one rejecting step must count once in the histogram")
    assert sum(st["rung_steps"]) == st["steps"] + 1  # +1: empty-plan step
    _assert_clean(e)


# ------------------------------------------------------------- chaos
def test_chaos_interleaving_survivors_exact(lm):
    """The ISSUE's chaos property test: a seeded interleaving of a
    cancel storm, deadlines, injected transient dispatch errors and
    page exhaustion. After every engine step check_invariants holds;
    at the end every completed request is token-identical to the
    reference, every aborted request's partial stream is a reference
    prefix, and nothing recompiled."""
    e = ServeEngine(lm, faults=FaultInjector(
        "serve.mixed:transient@~0.25;"
        "serve.page_pressure:exhaust:0.9@%3", seed=11))
    counts = e.warmup()
    rng = np.random.RandomState(12)
    n = 10
    prompts = _prompts(rng, n, lo=4, hi=24)
    max_new = [int(rng.randint(4, 14)) for _ in range(n)]
    ref = e.generate_reference(prompts, max_new)
    # two immediate deadlines, the rest unbounded
    deadlines = [None] * n
    deadlines[2] = 1e-9
    deadlines[7] = 1e-9
    # a cancel storm at fixed steps (deterministic given the seed)
    storm = {2: [1], 4: [5, 6], 7: [9]}

    def on_step(step):
        for rid in storm.get(step, ()):
            e.cancel(rid)
        e.cache.check_invariants()      # after EVERY event

    out = e.generate(prompts, max_new, deadline_s=deadlines,
                     on_step=on_step)
    st = e.last_stats
    assert e.compile_counts() == counts, "chaos must not recompile"
    aborted = completed = 0
    for i in range(n):
        o = st["requests"][i]["outcome"]
        if o == RequestOutcome.COMPLETED:
            assert out[i] == ref[i]
            completed += 1
        else:
            assert o in (RequestOutcome.CANCELLED,
                         RequestOutcome.DEADLINE_EXPIRED,
                         RequestOutcome.REJECTED)
            assert out[i] == ref[i][:len(out[i])]
            aborted += 1
    assert completed >= 3, "chaos should leave survivors"
    assert aborted >= 3, "chaos should abort some requests"
    assert st["retries"] > 0, "transient faults should have fired"
    assert st["degradation_rung_max"] >= 1
    _assert_clean(e)
    # and the same engine serves a clean batch afterwards
    clean = _prompts(rng, 4)
    assert e.generate(clean, 4) == e.generate_reference(clean, 4)
    _assert_clean(e)


# ---------------------------------------------------- crash-safe state
def _ckpt_model(seed=0):
    from flexflow_tpu import AdamOptimizer, FFModel
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.seed = seed
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 8), name="input")
    t = ff.dense(x, 16, activation="relu")
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def test_kill_mid_checkpoint_resume_bit_exact(tmp_path):
    """The ISSUE's kill-mid-save satellite: a process killed while
    committing a checkpoint leaves NO truncated epoch visible; the
    restarted run resumes from the newest committed epoch and its loss
    trajectory equals the uninterrupted run's exactly."""
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    ckdir = str(tmp_path / "ck")

    ff_ref = _ckpt_model()
    h_ref = ff_ref.fit({"input": x}, y, epochs=4, verbose=False)

    # fit's async saver commits epoch k when epoch k+1's save starts:
    # ckpt.commit hit 1 promotes epoch_0, hit 2 would promote epoch_1 —
    # kill there AND on every later commit attempt (a dead process
    # cannot run fit's finally-block either)
    with faults.active("ckpt.commit:kill@2+"):
        with pytest.raises(SimulatedKill):
            _ckpt_model().fit({"input": x}, y, epochs=4, verbose=False,
                              checkpoint_dir=ckdir)
    visible = [d for d in os.listdir(ckdir)
               if d.startswith("epoch_") and d[len("epoch_"):].isdigit()]
    assert visible == ["epoch_0"], (
        f"only fully-committed checkpoints may be visible: {visible}")

    # restart: fresh process, same command — resumes at epoch 1 and
    # lands exactly where the uninterrupted run does
    ff_b = _ckpt_model()
    h_b = ff_b.fit({"input": x}, y, epochs=4, verbose=False,
                   checkpoint_dir=ckdir)
    assert [m["epoch"] for m in h_b] == [1, 2, 3]
    for m_ref, m_b in zip(h_ref[1:], h_b):
        assert m_b["loss"] == pytest.approx(m_ref["loss"], abs=1e-6)
    np.testing.assert_allclose(ff_ref.get_weights("dense")["kernel"],
                               ff_b.get_weights("dense")["kernel"],
                               atol=1e-6)


def test_sync_save_kill_leaves_previous_checkpoint(tmp_path):
    from flexflow_tpu.core.checkpoint import (restore_checkpoint,
                                              save_checkpoint)
    rng = np.random.RandomState(1)
    batch = {"input": rng.randn(16, 8).astype(np.float32),
             "label": rng.randint(0, 4, 16).astype(np.int32)}
    ff = _ckpt_model()
    ff.train_batch(batch)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ff.state)
    w_old = np.asarray(ff.get_weights("dense")["kernel"]).copy()
    step_old = int(ff.state.step)

    ff.train_batch(batch)
    with faults.active("ckpt.commit:kill@1"):
        with pytest.raises(SimulatedKill):
            save_checkpoint(path, ff.state)
    # the kill landed between the complete tmp write and the promote:
    # the OLD checkpoint is still what `path` restores
    restored = restore_checkpoint(path, ff.state)
    assert int(restored.step) == step_old
    np.testing.assert_allclose(
        np.asarray(restored.params["dense"]["kernel"]), w_old)
    # a clean re-save commits the new state (and sweeps the stale tmp)
    save_checkpoint(path, ff.state)
    restored = restore_checkpoint(path, ff.state)
    assert int(restored.step) == step_old + 1


def test_kill_inside_promote_window_recovers_old(tmp_path):
    """A kill INSIDE _promote's two-rename window (old checkpoint
    moved aside, new one not yet swung in) must not lose the previous
    checkpoint: readers recover it from `.old`."""
    from flexflow_tpu.core.checkpoint import (restore_checkpoint,
                                              save_checkpoint)
    rng = np.random.RandomState(3)
    batch = {"input": rng.randn(16, 8).astype(np.float32),
             "label": rng.randint(0, 4, 16).astype(np.int32)}
    ff = _ckpt_model()
    ff.train_batch(batch)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ff.state)
    step_old = int(ff.state.step)
    ff.train_batch(batch)
    # the first save ran outside active(), so this context's injector
    # sees the re-save's swap as hit 1
    with faults.active("ckpt.swap:kill@1"):
        with pytest.raises(SimulatedKill):
            save_checkpoint(path, ff.state)
    assert not os.path.isdir(path)            # the window, frozen
    assert os.path.isdir(path + ".old")
    restored = restore_checkpoint(path, ff.state)   # recovers .old
    assert int(restored.step) == step_old
    assert os.path.isdir(path)


def test_fit_resume_skips_corrupt_newest_epoch(tmp_path):
    """Out-of-band damage to the newest committed epoch must not kill
    the run: resume warns and falls back to the previous epoch."""
    rng = np.random.RandomState(2)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    ckdir = tmp_path / "ck"
    ff = _ckpt_model()
    ff.fit({"input": x}, y, epochs=2, verbose=False,
           checkpoint_dir=str(ckdir))
    # vandalize epoch_1 (committed, then damaged out-of-band)
    victim = ckdir / "epoch_1"
    assert victim.is_dir()
    for root, _, files in os.walk(victim):
        for f in files:
            (open(os.path.join(root, f), "wb")).close()   # truncate
    ff2 = _ckpt_model()
    with pytest.warns(UserWarning, match="epoch_1 unreadable"):
        h = ff2.fit({"input": x}, y, epochs=3, verbose=False,
                    checkpoint_dir=str(ckdir))
    assert [m["epoch"] for m in h] == [1, 2]


def test_loader_state_checkpoint_atomic(tmp_path):
    from flexflow_tpu.core.dataloader import DataLoaderSet
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    y = np.arange(64, dtype=np.int32)
    path = str(tmp_path / "loader.json")

    ds = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                       shuffle=True, seed=3, prefetch=False)
    list(ds)                         # epoch 0 consumes one permutation
    ds.save_state(path)
    epoch1 = [np.asarray(b["label"]).tolist() for b in ds]

    # a clone restored from the state file replays epoch 1 exactly
    ds2 = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                        shuffle=True, seed=99, prefetch=False)
    assert ds2.load_state(path)
    assert [np.asarray(b["label"]).tolist() for b in ds2] == epoch1

    # kill mid-save: the previous complete state file survives
    old = open(path).read()
    with faults.active("loader.commit:kill@1"):
        with pytest.raises(SimulatedKill):
            ds.save_state(path)
    assert open(path).read() == old
    assert not ds2.load_state(str(tmp_path / "absent.json"))

    # a malformed file must leave the loader UNTOUCHED (parse fully
    # before applying anything)
    import json
    bad = json.loads(old)
    bad["rng"][2] = "not-an-int"
    badpath = str(tmp_path / "bad.json")
    with open(badpath, "w") as f:
        json.dump(bad, f)
    before = ds2.state_dict()
    assert not ds2.load_state(badpath)
    after = ds2.state_dict()
    assert after["rng"] == before["rng"], "rejected file mutated the rng"


def test_cost_cache_corrupt_load_warns_and_rebuilds(tmp_path):
    from flexflow_tpu.search.cost_cache import CostCache
    from flexflow_tpu.search.cost_model import OpCost
    path = str(tmp_path / "costcache.json")
    with open(path, "w") as f:
        f.write('{"fp": {"abc": [1.0, 2.0')      # truncated mid-write
    cc = CostCache(path)
    with pytest.warns(UserWarning, match="rebuilding"):
        assert cc.get("fp", "abc") is None
    cost = OpCost(fwd=1.0, bwd=2.0, fwd_comm=0.1, bwd_comm=0.2,
                  sync=0.3, mem=4.0, update=0.5)
    cc.put("fp", "abc", cost)
    with pytest.warns(UserWarning, match="corrupt at flush"):
        cc.flush()                               # rebuilds wholesale
    cc2 = CostCache(path)
    got = cc2.get("fp", "abc")
    assert got is not None and got.fwd == 1.0 and got.update == 0.5
    # malformed rows inside a parseable store miss instead of crashing
    import json
    with open(path) as f:
        data = json.load(f)
    data["fp"]["bad"] = [1.0]
    with open(path, "w") as f:
        json.dump(data, f)
    cc3 = CostCache(path)
    assert cc3.get("fp", "bad") is None
    assert cc3.get("fp", "abc") is not None
