"""Flash-attention Pallas kernels vs XLA reference (interpret mode on CPU).

Reference analog: tests/ops golden tests (SURVEY.md section 4.3) — same
computation in plain numpy/XLA, assert_allclose on outputs AND gradients.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.kernels.flash_attention import flash_attention_bshd


def xla_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 128), (128, 256), (256, 128)])
def test_flash_forward_matches_xla(rng, causal, sq, sk):
    b, h, d = 2, 2, 64
    q = jnp.asarray(rng.randn(b, sq, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))
    out = flash_attention_bshd(q, k, v, causal=causal, interpret=True)
    ref = xla_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk,d", [(128, 128, 64), (128, 256, 64),
                                     (256, 128, 64), (128, 128, 32)])
def test_flash_grads_match_xla(rng, causal, sq, sk, d):
    b, h = 2, 2
    q = jnp.asarray(rng.randn(b, sq, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))

    def loss_flash(q, k, v):
        o = flash_attention_bshd(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o.astype(jnp.float32)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(xla_attention(q, k, v, causal)
                               .astype(jnp.float32)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_head_dim_padding(rng):
    # d=32 pads to 128 lanes; padding must be exact
    b, s, h, d = 1, 128, 2, 32
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    out = flash_attention_bshd(q, k, v, interpret=True)
    ref = xla_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_bf16(rng):
    b, s, h, d = 2, 128, 2, 64
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    out = flash_attention_bshd(q, k, v, causal=True, interpret=True)
    ref = xla_attention(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_flash_unpadded_lanes_matches_xla(rng):
    # d=64 with pad_lanes=False: Mosaic sub-128-lane path (interpret here)
    b, s, h, d = 1, 128, 2, 64
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    out = flash_attention_bshd(q, q, q, causal=True, interpret=True,
                               pad_lanes=False)
    ref = xla_attention(q, q, q, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_fused_qkv_under_remat_matches_no_remat():
    """The fused self-attention QKV projection is decided at GRAPH level
    (same tensor wired to q/k/v), so remat — which re-flattens the
    duplicated runtime leaves into distinct tracers — must not change
    the path or the numerics (review regression, r3)."""
    import numpy as np
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    def build(remat):
        cfg = FFConfig()
        cfg.batch_size = 4
        cfg.remat = remat
        ff = FFModel(cfg)
        x = ff.create_tensor((4, 8, 32), name="input")
        a = ff.multihead_attention(x, x, x, 32, 4, name="attn")
        t = ff.add(a, x)
        t = ff.reshape(t, (4, 8 * 32))
        ff.softmax(ff.dense(t, 4))
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    ff1, ff2 = build(False), build(True)
    attn = next(o for o in ff1.ops if o.op_type == "multihead_attention")
    assert attn._fused_qkv
    for name in ("attn", "dense"):
        ff2.set_weights(name, ff1.get_weights(name))
    rng = np.random.RandomState(0)
    b = {"input": rng.randn(4, 8, 32).astype(np.float32),
         "label": rng.randint(0, 4, 4).astype(np.int32)}
    for _ in range(3):
        l1 = float(ff1.train_batch(b)["loss"])
        l2 = float(ff2.train_batch(b)["loss"])
        np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_fused_kv_cross_attention_matches_separate():
    """Cross-attention with k is v (seq2seq decoder over encoder
    output) uses the fused 2x-wide KV projection; numerics must equal
    a graph where k and v are distinct tensors with identical values."""
    import numpy as np
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    def build(share_kv):
        cfg = FFConfig()
        cfg.batch_size = 4
        ff = FFModel(cfg)
        q = ff.create_tensor((4, 6, 32), name="q")
        kv = ff.create_tensor((4, 9, 32), name="kv")
        if share_kv:
            a = ff.multihead_attention(q, kv, kv, 32, 4, name="xattn")
        else:
            kv2 = ff.create_tensor((4, 9, 32), name="kv2")
            a = ff.multihead_attention(q, kv, kv2, 32, 4, name="xattn")
        t = ff.reshape(a, (4, 6 * 32))
        ff.softmax(ff.dense(t, 4, name="head"))
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    ff1, ff2 = build(True), build(False)
    attn1 = next(o for o in ff1.ops if o.op_type == "multihead_attention")
    attn2 = next(o for o in ff2.ops if o.op_type == "multihead_attention")
    assert attn1._fused_kv and not attn1._fused_qkv
    assert not attn2._fused_kv
    for name in ("xattn", "head"):
        ff2.set_weights(name, ff1.get_weights(name))
    rng = np.random.RandomState(0)
    qv = rng.randn(4, 6, 32).astype(np.float32)
    kvv = rng.randn(4, 9, 32).astype(np.float32)
    y = rng.randint(0, 4, 4).astype(np.int32)
    for _ in range(3):
        l1 = float(ff1.train_batch({"q": qv, "kv": kvv, "label": y})["loss"])
        l2 = float(ff2.train_batch({"q": qv, "kv": kvv, "kv2": kvv,
                                    "label": y})["loss"])
        np.testing.assert_allclose(l1, l2, rtol=1e-5)
