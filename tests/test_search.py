"""Auto-parallelization tests: cost model, simulator, MCMC search,
strategy file I/O (reference text format)."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy, megatron_strategy
from flexflow_tpu.parallel.strategy_io import (
    load_strategies_from_file,
    op_parallel_config,
    save_strategies_to_file,
)
from flexflow_tpu.search.machine_model import default_machine_model
from flexflow_tpu.search.mcmc import candidate_maps, optimize
from flexflow_tpu.search.simulator import Simulator


def build_big_mlp(batch=32, hidden=4096):
    """TP-friendly: huge dense layers, small batch -> model parallelism
    should beat pure DP on a (1, 8) data x model mesh."""
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((batch, hidden), name="input")
    t = ff.dense(x, hidden, activation="relu", name="big1")
    t = ff.dense(t, hidden, activation="relu", name="big2")
    t = ff.dense(t, 10, name="head")
    t = ff.softmax(t)
    return ff


def test_simulator_monotonic_in_dp():
    """For a compute-bound model (batch large enough that per-step compute
    dominates the fixed gradient all-reduce), DP must beat replication.
    (At small batch the simulator correctly prefers replication — the
    all-reduce is a fixed cost while compute scales with batch.)"""
    ff = build_big_mlp(batch=32768, hidden=512)
    mesh = make_mesh((8,), ("data",))
    sim = Simulator(ff, mesh)
    t_dp = sim.simulate(Strategy())  # sample -> data
    t_repl = sim.simulate(Strategy(default=OpStrategy({})))  # replicated
    assert t_dp < t_repl, (t_dp, t_repl)


def test_simulator_tp_beats_dp_for_big_layers():
    ff = build_big_mlp(batch=8, hidden=8192)
    mesh = make_mesh((1, 8), ("data", "model"))
    sim = Simulator(ff, mesh)
    t_dp = sim.simulate(Strategy())
    t_tp = sim.simulate(megatron_strategy())
    assert t_tp < t_dp, (t_tp, t_dp)


def test_memory_penalty_applies():
    ff = build_big_mlp(batch=8, hidden=8192)
    mesh = make_mesh((1, 8), ("data", "model"))
    mm = default_machine_model(mesh)
    mm.spec.hbm_capacity = 1e6  # absurdly small: everything over budget
    sim_small = Simulator(ff, mesh, mm)
    sim_big = Simulator(ff, mesh)
    assert sim_small.simulate(Strategy()) > sim_big.simulate(Strategy())


def test_candidate_maps_respect_gates():
    ff = build_big_mlp()
    mesh = make_mesh((1, 8), ("data", "model"))
    op = ff.ops[0]  # big dense
    cfg = ff.config
    cfg.enable_parameter_parallel = False
    cands = candidate_maps(op, mesh, cfg)
    assert all("channel_out" not in c for c in cands)
    cfg.enable_parameter_parallel = True
    cands = candidate_maps(op, mesh, cfg)
    assert any(c.get("channel_out") == "model" for c in cands)


def test_mcmc_finds_tp_for_big_layers():
    ff = build_big_mlp(batch=8, hidden=8192)
    mesh = make_mesh((1, 8), ("data", "model"))
    ff.mesh = mesh
    best = optimize(ff, budget=300, alpha=0.05, mesh=mesh, seed=0)
    sim = Simulator(ff, mesh)
    t_best = sim.simulate(best)
    t_dp = sim.simulate(Strategy())
    assert t_best <= t_dp
    # the big layers should end up model-parallel
    big_maps = [best.for_op(n).axis_map for n in ("big1", "big2")]
    assert any(m.get("channel_out") == "model" for m in big_maps), big_maps


def test_search_wired_into_compile_and_trains():
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.search_budget = 50
    cfg.enable_parameter_parallel = True
    mesh = make_mesh((2, 4), ("data", "model"))
    ff = FFModel(cfg, mesh=mesh)
    x = ff.create_tensor((16, 64), name="input")
    t = ff.dense(x, 256, activation="relu")
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 64).astype(np.float32)
    ys = rng.randint(0, 4, 64).astype(np.int32)
    hist = ff.fit({"input": xs}, ys, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])


def test_reference_strategy_file_roundtrip(tmp_path):
    ff = build_big_mlp(batch=8, hidden=512)
    mesh = make_mesh((2, 4), ("data", "model"))
    strat = megatron_strategy()
    path = str(tmp_path / "strategy.txt")
    save_strategies_to_file(ff, strat, mesh, path)
    text = open(path).read().splitlines()
    assert text[0] == str(len(ff.ops))
    # big1 line: name tpu ndims dims... -> (batch split 2, channel 4)
    big1 = next(l for l in text if l.startswith("big1"))
    parts = big1.split()
    assert parts[1] == "tpu" and parts[2] == "2"
    assert parts[3:5] == ["2", "4"], parts

    loaded = load_strategies_from_file(ff, mesh, path)
    m = loaded.for_op("big1").axis_map
    assert m.get("sample") == "data" and m.get("channel_out") == "model", m


def test_simulator_dot_export(tmp_path):
    ff = build_big_mlp(batch=8, hidden=256)
    mesh = make_mesh((8,), ("data",))
    sim = Simulator(ff, mesh)
    dot = str(tmp_path / "graph.dot")
    sim.simulate(Strategy(), dot_path=dot)
    content = open(dot).read()
    assert "digraph taskgraph" in content
    assert "big1:fwd" in content and "grad_sync" in content


def test_taskgraph_flag_exports_dot(tmp_path, mesh8):
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.search.mcmc import optimize
    cfg = FFConfig()
    cfg.parse_args(["--taskgraph", str(tmp_path / "tg.dot"),
                    "--seq-length", "16"])
    assert cfg.iter_config.seq_length == 16
    ff = FFModel(cfg, mesh=mesh8)
    x = ff.create_tensor((16, 8), name="input")
    ff.softmax(ff.dense(x, 4, name="fc"), name="sm")
    optimize(ff, budget=5)
    dot = (tmp_path / "tg.dot").read_text()
    assert "digraph" in dot and ":fwd" in dot
