"""conv_layout="NHWC" must be a pure layout change: identical numerics
to the default NCHW compute path (reference examples are NCHW; on TPU
the NHWC compute form puts channels on the 128-lane minor dim and XLA
cancels the per-op transpose pairs inside conv chains)."""

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def _build(layout):
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.conv_layout = layout
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 3, 16, 16), name="input")
    t = ff.conv2d(x, 16, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.batch_norm(t, relu=True)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, pool_type="avg")
    t = ff.flat(t)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff

def test_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    batches = [{"input": rng.randn(8, 3, 16, 16).astype(np.float32),
                "label": rng.randint(0, 4, (8,))} for _ in range(3)]
    a, b = _build("NCHW"), _build("NHWC")
    for batch in batches:
        la = float(a.train_batch(batch)["loss"])
        lb = float(b.train_batch(batch)["loss"])
        np.testing.assert_allclose(la, lb, rtol=2e-5)
    for op in a.ops:
        if not op.weight_specs():
            continue
        wa = a.get_weights(op.name)
        wb = b.get_weights(op.name)
        for k in wa:
            np.testing.assert_allclose(wa[k], wb[k], rtol=2e-4,
                                       atol=2e-5)


def test_nhwc_residency_multi_device_matches_single_nchw(mesh8):
    """NHWC residency (values flow channels-last BETWEEN conv-family
    ops, executor._compute_nhwc_resident) under 8-way DP must match the
    single-device NCHW walk — including the permuted sharding
    constraints on resident values and the Concat channel-axis remap."""
    from flexflow_tpu.parallel.pconfig import OpStrategy, Strategy

    def run(layout, mesh=None):
        strategy = (Strategy(default=OpStrategy({"sample": "data"}))
                    if mesh is not None else None)
        cfg = FFConfig()
        cfg.batch_size = 16
        cfg.conv_layout = layout
        ff = FFModel(cfg, mesh=mesh, strategy=strategy)
        x = ff.create_tensor((16, 8, 16, 16), name="input")
        b1 = ff.conv2d(x, 12, 1, 1, 1, 1, 0, 0, activation="relu")
        b2 = ff.conv2d(x, 6, 1, 1, 1, 1, 0, 0, activation="relu")
        t = ff.concat([b1, b2], axis=1)
        t = ff.batch_norm(t)
        t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
        ff.softmax(ff.dense(ff.flat(t), 4))
        ff.compile(optimizer=SGDOptimizer(lr=0.005),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        if layout == "NHWC":
            assert ff.executor._nhwc_resident  # the pass is active
        rng = np.random.RandomState(0)
        d = {"input": rng.randn(16, 8, 16, 16).astype(np.float32),
             "label": rng.randint(0, 4, (16,)).astype(np.int32)}
        return [float(ff.train_batch(d)["loss"]) for _ in range(3)]

    np.testing.assert_allclose(run("NCHW"), run("NHWC", mesh8),
                               rtol=2e-5)
