"""Multi-host modeling (VERDICT round-1 weak #8 / missing #8): DCN-tier
collective pricing with shared-NIC congestion, a simulated 2-host mesh
driving the search toward DCN-light strategies, and launcher flag
validation. Reference: EnhancedMachineModel congestion
(machine_model.cc:172+, machine_config_example), mpirun bootstrap
(python/flexflow.py)."""

import os
import subprocess
import sys

import pytest

from flexflow_tpu import FFConfig, FFModel, Strategy, make_mesh
from flexflow_tpu.parallel.mesh import MachineSpec
from flexflow_tpu.parallel.pconfig import OpStrategy, megatron_strategy
from flexflow_tpu.search.machine_model import TPUMachineModel
from flexflow_tpu.search.simulator import Simulator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def two_host_mm(chips_per_host=4):
    """8 chips = 2 hosts x 4: the `data` axis crosses hosts (DCN), the
    `model` axis stays inside a host (ICI)."""
    spec = MachineSpec.v5e(num_chips=8)
    spec.chips_per_host = chips_per_host
    return TPUMachineModel(spec=spec, dcn_axes=("data",))


def test_dcn_axis_prices_above_ici():
    mm = two_host_mm()
    nbytes = 64 * 2 ** 20
    t_dcn = mm.all_reduce(nbytes, 2, axis="data")
    t_ici = mm.all_reduce(nbytes, 2, axis="model")
    # v5e: ICI 45GB/s*0.75 vs DCN 25GB/s / 4 sharers ~ 5.4x
    assert t_dcn > 4 * t_ici, (t_dcn, t_ici)


def test_shared_nic_congestion_scales_with_local_chips():
    """4 chips sharing one NIC see 1/4 the per-chip DCN bandwidth
    (reference shared-NIC congestion)."""
    nbytes = 64 * 2 ** 20
    t1 = two_host_mm(chips_per_host=1).all_reduce(nbytes, 2, axis="data")
    t4 = two_host_mm(chips_per_host=4).all_reduce(nbytes, 2, axis="data")
    # bandwidth term quadruples; latency term unchanged
    assert 3.0 < t4 / t1 <= 4.0, (t1, t4)


def test_dcn_flips_factorization_preference_on_two_hosts():
    """2 hosts x 4 chips: on a single ICI domain the best factorization
    of this MLP is pure dp8 (small weights, big batch); when the `data`
    axis crosses hosts (DCN + shared-NIC congestion), the gradient
    all-reduce becomes the bottleneck and dp2(x)tp4 — heavy traffic on
    intra-host ICI — must win instead. This is the decision the two-tier
    machine model exists to get right (SURVEY 2.5 TPU-equivalent row)."""
    cfg = FFConfig()
    cfg.batch_size = 4096
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((4096, 1024), name="input")
    t = ff.dense(x, 1024, activation="relu", name="big1")
    t = ff.dense(t, 1024, activation="relu", name="big2")
    t = ff.softmax(ff.dense(t, 10, name="head"))
    mesh_dp = make_mesh((8,), ("data",))
    mesh_tp = make_mesh((2, 4), ("data", "model"))

    def step_times(mm_factory):
        t_dp = Simulator(ff, mesh_dp, mm_factory()).simulate(Strategy())
        t_tp = Simulator(ff, mesh_tp,
                         mm_factory()).simulate(megatron_strategy())
        return t_dp, t_tp

    t_dp, t_tp = step_times(
        lambda: TPUMachineModel(spec=MachineSpec.v5e(num_chips=8)))
    assert t_dp < t_tp, (t_dp, t_tp)           # one host: dp8 wins

    t_dp, t_tp = step_times(two_host_mm)
    assert t_tp < t_dp, (t_dp, t_tp)           # two hosts: dp2xtp4 wins


def test_machine_file_overrides_chips_per_host(tmp_path):
    """--machine-model-file JSON can describe the cluster topology
    (reference machine_config_example)."""
    import json

    from flexflow_tpu.search.machine_model import default_machine_model

    path = tmp_path / "machine.json"
    path.write_text(json.dumps({"chips_per_host": 8,
                                "dcn_bandwidth": 50e9}))
    mm = default_machine_model(machine_file=str(path))
    assert mm.spec.chips_per_host == 8
    assert mm.spec.dcn_bandwidth == 50e9


def test_launcher_rejects_partial_multihost_flags():
    """--coordinator without --num-processes/--process-id must exit with
    a clear launcher error, not a deep jax.distributed traceback
    (ADVICE round-1 #3)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu",
         "--coordinator", "127.0.0.1:9999", "-c", "pass"],
        capture_output=True, text=True, cwd=REPO, timeout=120, env=env)
    assert r.returncode == 2, (r.returncode, r.stderr)
    assert "--num-processes" in r.stderr
