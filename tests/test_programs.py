"""Program registry + AOT compile cache (core/programs.py).

Layers, mirroring the module's contracts:

  * registry — exact per-family compile counting on a toy jitted
    program: one new signature per family pins the per-family
    increment (and ONLY that family's); restored executables count
    zero; corrupt stores warn and boot cold; a foreign fingerprint
    under the same dir is a silent miss.
  * fingerprint — every folded field the issue names (kv dtype,
    adapter rank, tp degree, jax version string) flips the hash AND
    misses the store; the same config reloads and hits.
  * engine — a warm reload is bit-identical (greedy tokens equal
    across the save/load boundary) on f32 AND int8 KV pools with zero
    warm compiles; export/import/adapter warmup compiles are counted
    exactly (the monitoring-snapshot coverage gap: compiles inside
    warmup_handoff / adapter load could hide from the old proxy).
"""

import glob

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.programs import ProgramRegistry, fingerprint_hash
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.serve import ServeEngine

VOCAB = 89
FAMILIES = ("prefill", "decode", "mixed", "adapter", "export", "import")


def _engine(cache_dir=None, **kw):
    """The tests/test_serve.py engine idiom, with the program cache
    armed when a dir is given."""
    if cache_dir is not None:
        kw["program_cache_dir"] = str(cache_dir)
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=4, serve_prefill_budget=48, **kw)
    lm = build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    return ServeEngine(lm)


PROMPTS = [[3, 5, 7, 11, 2, 9, 4, 1], [6, 6, 8, 2]]


# ------------------------------------------------------------ registry
def test_per_family_increment_is_exact():
    """One new signature per program family -> that family's count
    increments by EXACTLY one and no other family moves (the registry
    replaces the max-of-two-proxies counter, so the increment must be
    exact, not >=)."""
    reg = ProgramRegistry({"kind": "test"})
    f = jax.jit(lambda x: x * 2)
    for fam in FAMILIES:
        reg.register(fam)
    for i, fam in enumerate(FAMILIES):
        x = jnp.zeros((i + 1,), jnp.float32)
        before = reg.compile_counts()
        y = reg.call(fam, f, x)                 # new signature
        assert np.array_equal(np.asarray(y), np.zeros((i + 1,)))
        after = reg.compile_counts()
        assert after[fam] == before[fam] + 1
        assert {k: v for k, v in after.items() if k != fam} \
            == {k: v for k, v in before.items() if k != fam}
        reg.call(fam, f, x)                     # same signature: cached
        assert reg.compile_counts() == after
    # a second fresh signature per family is again exactly +1
    for i, fam in enumerate(FAMILIES):
        reg.call(fam, f, jnp.zeros((i + 100,), jnp.float32))
    assert reg.compile_counts() == {fam: 2 for fam in FAMILIES}


def test_signature_keys_values_and_dtypes():
    """The signature keys on shape, dtype, static VALUES and the
    extra_key — each flip is a distinct program; repeats are not."""
    reg = ProgramRegistry({"kind": "test"})
    x = jnp.zeros((4,), jnp.float32)
    base = reg.signature((x,))
    assert reg.signature((x,)) == base
    assert reg.signature((jnp.zeros((5,), jnp.float32),)) != base
    assert reg.signature((jnp.zeros((4,), jnp.int32),)) != base
    assert reg.signature((x,), extra_key="variant") != base
    assert reg.signature((3, x)) != reg.signature((4, x))  # static value


def test_restored_executables_count_zero(tmp_path):
    """save -> load in a fresh registry: the restored executable
    dispatches bit-identically and compile_counts() stays zero (the
    warm-boot contract monitoring snapshots could never promise)."""
    fp = {"kind": "test", "v": 1}
    a = ProgramRegistry(fp, cache_dir=str(tmp_path))
    f = jax.jit(lambda x: jnp.cumsum(x) * 3)
    x = jnp.arange(6, dtype=jnp.float32)
    y = a.call("fam", f, x)
    assert a.save() == 1
    b = ProgramRegistry(fp, cache_dir=str(tmp_path))
    assert b.load_warm() == 1
    y2 = b.call("fam", f, x)
    assert np.array_equal(np.asarray(y), np.asarray(y2))
    assert sum(b.compile_counts().values()) == 0
    assert b.restored_counts()["fam"] == 1
    # a signature the store never saw still compiles (and counts)
    b.call("fam", f, jnp.arange(9, dtype=jnp.float32))
    assert b.compile_counts()["fam"] == 1


def test_corrupt_store_warns_and_boots_cold(tmp_path):
    """cost_cache.py discipline: truncated/garbage stores cost a
    warning and a cold compile, never a crash — and save() afterwards
    replaces the bad file with a good one."""
    fp = {"kind": "test", "v": 2}
    a = ProgramRegistry(fp, cache_dir=str(tmp_path))
    f = jax.jit(lambda x: x - 1)
    a.call("fam", f, jnp.zeros((3,), jnp.float32))
    a.save()
    path = a._store_path()
    with open(path, "wb") as fh:
        fh.write(b"not a program snapshot")
    b = ProgramRegistry(fp, cache_dir=str(tmp_path))
    with pytest.warns(UserWarning, match="program cache"):
        assert b.load_warm() == 0
    b.call("fam", f, jnp.zeros((3,), jnp.float32))
    assert b.compile_counts()["fam"] == 1      # compiled cold
    assert b.save() == 1                        # store healed
    c = ProgramRegistry(fp, cache_dir=str(tmp_path))
    assert c.load_warm() == 1


def test_fingerprint_flip_misses_store(tmp_path):
    """Flipping any folded field must miss the snapshot; the same
    fingerprint must hit. (The file name IS the fingerprint hash, so a
    foreign-fingerprint dir read is a silent miss, not corruption.)"""
    fp = {"kind": "test", "jax": jax.__version__, "kv_dtype": "float32",
          "adapter_rank": 0, "tp": 1}
    a = ProgramRegistry(fp, cache_dir=str(tmp_path))
    a.call("fam", jax.jit(lambda x: x + 1), jnp.zeros((3,), jnp.float32))
    a.save()
    for field, val in [("jax", "0.0.0-not-this-jax"),
                       ("kv_dtype", "int8"),
                       ("adapter_rank", 8),
                       ("tp", 2)]:
        flipped = dict(fp)
        flipped[field] = val
        assert fingerprint_hash(flipped) != fingerprint_hash(fp), field
        b = ProgramRegistry(flipped, cache_dir=str(tmp_path))
        assert b.load_warm() == 0, field
    assert ProgramRegistry(dict(fp),
                           cache_dir=str(tmp_path)).load_warm() == 1


# --------------------------------------------------------- fingerprint
def test_engine_fingerprint_folds_serving_knobs():
    """The engine fingerprint flips on kv dtype, adapter rank and tp
    degree (the config knobs that change compiled programs without
    changing the model), and folds the jax version string."""
    base = _engine()
    h0 = fingerprint_hash(base.programs.fingerprint)
    assert base.programs.fingerprint["jax"] == jax.__version__
    assert fingerprint_hash(
        _engine(kv_dtype="int8").programs.fingerprint) != h0
    assert fingerprint_hash(
        _engine(adapter_rank=4).programs.fingerprint) != h0
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=4, serve_prefill_budget=48)
    lm = build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    tp = ServeEngine(lm, tensor_parallel=4)
    assert fingerprint_hash(tp.programs.fingerprint) != h0
    assert tp.programs.fingerprint["tp"] == 4
    # equal configs agree — the hit side of the contract
    assert fingerprint_hash(_engine().programs.fingerprint) == h0


# -------------------------------------------------------------- engine
@pytest.mark.parametrize("kv", ["float32", "int8"])
def test_warm_boot_is_bit_identical_and_compile_free(tmp_path, kv):
    """The tentpole gate at test scale, on BOTH pool formats: a cold
    engine populates --program-cache-dir; a second engine over the
    same config restores every program, performs ZERO compiles through
    warmup AND generation, and emits bit-identical greedy tokens."""
    d = tmp_path / kv
    cold = _engine(cache_dir=d, kv_dtype=kv)
    cold.warmup()
    assert sum(cold.compile_counts().values()) > 0   # non-vacuous
    assert cold.boot_stats is not None and not cold.boot_stats["warm"]
    out_cold = cold.generate(PROMPTS, max_new_tokens=6)
    warm = _engine(cache_dir=d, kv_dtype=kv)
    assert warm.programs_restored > 0
    warm.warmup()
    assert warm.boot_stats["warm"] is True
    assert warm.boot_stats["compile_s"] == 0.0
    assert sum(warm.compile_counts().values()) == 0
    out_warm = warm.generate(PROMPTS, max_new_tokens=6)
    assert out_warm == out_cold
    assert sum(warm.compile_counts().values()) == 0


def test_engine_corrupt_store_falls_back(tmp_path):
    """A corrupted snapshot on a live engine boots cold with the
    'program cache' warning and serves identical tokens."""
    cold = _engine(cache_dir=tmp_path)
    cold.warmup()
    out = cold.generate(PROMPTS, max_new_tokens=4)
    stores = glob.glob(str(tmp_path / "*.ffprog"))
    assert len(stores) == 1
    with open(stores[0], "wb") as fh:
        fh.write(b"garbage")
    with pytest.warns(UserWarning, match="program cache"):
        bad = _engine(cache_dir=tmp_path)
    assert bad.programs_restored == 0
    bad.warmup()
    assert sum(bad.compile_counts().values()) > 0
    assert bad.generate(PROMPTS, max_new_tokens=4) == out


def test_handoff_and_adapter_compiles_counted_exactly():
    """The coverage gap the registry closes: export/import (handoff)
    and adapter-load compiles used to happen outside the snapshotted
    window on a jax without the monitoring module. Now each costs
    exactly one counted compile, and re-running costs zero."""
    eng = _engine()
    eng.warmup()
    c0 = eng.compile_counts()
    assert c0["export"] == 0 and c0["import"] == 0
    eng.warmup_handoff()
    c1 = eng.compile_counts()
    assert c1["export"] == c0["export"] + 1
    assert c1["import"] == c0["import"] + 1
    eng.warmup_handoff()                     # cached: exact, no drift
    assert eng.compile_counts() == c1

    from flexflow_tpu.serve.adapters import make_tenant_adapters
    ae = _engine(adapter_rank=4)
    counts = ae.warmup()
    assert counts["adapter"] == 1            # warmed inside warmup()
    adapters = make_tenant_adapters(num_layers=2, hidden=32,
                                    num_heads=4, head_dim=8, ff_dim=64,
                                    rank=4, tenants=1, seed=3)
    w, sc = adapters[1]
    ae.register_adapter(1, w, scale=sc)
    assert ae.adapters.acquire(1) is not None
    ae._drain_adapter_loads()                # real load reuses warmup's
    assert ae.compile_counts()["adapter"] == 1
