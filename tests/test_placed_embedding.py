"""Executable device-explicit placement (reference ParallelConfig.
device_ids, executed by FFMapper::slice_task mapper.cc:346-440; DLRM's
per-GPU table strategies dlrm_strategy.cc:1-50).

A per-table device-id tuple in an OpStrategy now CHANGES WHAT RUNS:
DistributedEmbedding lowers it to a device-ordered slot layout whose
stacked axis shards over the full mesh, so table t's rows live exactly
on mesh.devices.flat[device_ids[t]]. These tests prove (a) numerics are
identical to the unplaced model for arbitrary scattered/skewed
assignments, (b) the weights physically reside on the assigned devices,
(c) search-produced placements compile and train, (d) placements
round-trip through strategy files.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    SGDOptimizer,
    AdamOptimizer,
    Strategy,
    make_mesh,
)
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy

TABLES, VOCAB, DIM, BS = 8, 64, 8, 16


def build(mesh=None, strategy=None, sparse=True, opt=None, tables=TABLES):
    cfg = FFConfig()
    cfg.batch_size = BS
    cfg.sparse_embedding_updates = sparse
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    ins = [ff.create_tensor((BS, 2), dtype=jnp.int32, name=f"sparse_{i}")
           for i in range(tables)]
    embs = ff.distributed_embedding(ins, VOCAB, DIM, aggr="sum",
                                    name="tables")
    t = ff.concat(embs, axis=1)
    t = ff.dense(t, 4, name="dense")
    ff.softmax(t)
    ff.compile(optimizer=opt or SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh, strategy=strategy)
    return ff


def batches(n=3, tables=TABLES, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        b = {f"sparse_{i}": rng.randint(0, VOCAB, (BS, 2)).astype(np.int32)
             for i in range(tables)}
        b["label"] = rng.randint(0, 4, BS).astype(np.int32)
        out.append(b)
    return out


def place_weights(ff_placed, kern_table_order, dense):
    """get/set_weights speak TABLE order regardless of placement (the
    slot permutation is internal), so a copy from an unplaced model is
    just set_weights."""
    op = next(o for o in ff_placed.ops if o.op_type == "distributed_embedding")
    ff_placed.set_weights("tables", {"kernel": kern_table_order})
    ff_placed.set_weights("dense", dense)
    return op


PLACEMENTS = [
    tuple((3, 1, 4, 1, 5, 0, 2, 6)),          # scattered + skewed (dev 7 idle)
    tuple(t % 8 for t in range(TABLES)),      # round-robin, balanced
    (0,) * TABLES,                            # everything on one device
]


@pytest.mark.parametrize("sparse", [False, True])
@pytest.mark.parametrize("ids", PLACEMENTS)
def test_placed_matches_unplaced(ids, sparse):
    mesh = make_mesh((2, 4), ("data", "model"))
    ref = build(sparse=sparse)
    kern = np.asarray(ref.get_weights("tables")["kernel"])
    dense = ref.get_weights("dense")

    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    with warnings.catch_warnings():
        # placed dist-emb must NOT hit the GSPMD-replication fallback
        # (the pad-inflation advisory for the one-device variant is fine)
        warnings.filterwarnings("error", message=".*replication.*")
        ff = build(mesh=mesh, strategy=strat, sparse=sparse)
    op = place_weights(ff, kern, dense)
    assert op.placement == ids
    assert op.num_slots % mesh.size == 0

    for b in batches():
        lp = float(ff.train_batch(b)["loss"])
        lr = float(ref.train_batch(b)["loss"])
        np.testing.assert_allclose(lp, lr, rtol=1e-5)
    # get_weights returns TABLE order for placed ops too: direct compare
    got = np.asarray(ff.get_weights("tables")["kernel"])
    want = np.asarray(ref.get_weights("tables")["kernel"])
    assert got.shape == want.shape == (TABLES, VOCAB, DIM)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_placed_weight_residency():
    """Slot block d physically lives on mesh.devices.flat[d]."""
    mesh = make_mesh((8,), ("data",))
    ids = (3, 1, 4, 1, 5, 0, 2, 6)
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    ff = build(mesh=mesh, strategy=strat)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    w = ff.state.params["tables"]["kernel"]
    k = op.num_slots // mesh.size
    assert k >= 1
    flat = list(np.asarray(mesh.devices).flat)
    for shard in w.addressable_shards:
        d = flat.index(shard.device)
        lo = shard.index[0].start or 0
        assert lo == d * k, (d, shard.index)
    # every table's rows are on its ASSIGNED device
    for t, dev in enumerate(ids):
        slot = op._slot_of_table[t]
        assert slot // k == dev


def test_skewed_placement_pads():
    """5 tables on an 8-device mesh: slots pad to one per device."""
    mesh = make_mesh((8,), ("data",))
    ids = (2, 2, 2, 0, 7)
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    ff = build(mesh=mesh, strategy=strat, tables=5)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    assert op.num_slots == 8 * 3  # device 2 holds 3 tables -> K=3
    ref = build(tables=5)
    kern = np.asarray(ref.get_weights("tables")["kernel"])
    place_weights(ff, kern, ref.get_weights("dense"))
    for b in batches(tables=5):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_meshless_placement_warns_and_resets():
    """A placed strategy on a meshless compile cannot execute: it must
    warn and fall back to plain stacking, NOT build the padded slot
    layout (ADVICE r3: high device ids would silently multiply kernel
    memory with zero benefit)."""
    strat = Strategy(default=OpStrategy({}))
    strat.set("tables", OpStrategy({DEVICE_KEY: (7, 0, 7, 0, 7, 0, 7, 0)}))
    with pytest.warns(UserWarning, match="no mesh"):
        ff = build(mesh=None, strategy=strat)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    assert op.placement is None
    assert op.num_slots == TABLES  # plain stacking, no padding
    ref = build()
    kern = np.asarray(ref.get_weights("tables")["kernel"])
    place_weights(ff, kern, ref.get_weights("dense"))
    for b in batches(1):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_adam_sparse_placed():
    """Lazy/exact-mode interplay: Adam (dense fallback) still matches."""
    mesh = make_mesh((4,), ("data",))
    ids = tuple(t % 4 for t in range(TABLES))
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    ref = build(opt=AdamOptimizer(lr=0.01))
    ff = build(mesh=mesh, strategy=strat, opt=AdamOptimizer(lr=0.01))
    place_weights(ff, np.asarray(ref.get_weights("tables")["kernel"]),
                  ref.get_weights("dense"))
    for b in batches():
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_search_offers_and_executes_per_table_placement():
    """--enable-device-placement: candidate_maps offers per-table ids
    for distributed_embedding, and a strategy built from them runs."""
    from flexflow_tpu.search.mcmc import candidate_maps

    mesh = make_mesh((8,), ("data",))
    cfg = FFConfig()
    cfg.batch_size = BS
    cfg.enable_device_placement = True
    ff = FFModel(cfg, mesh=mesh)
    ins = [ff.create_tensor((BS, 2), dtype=jnp.int32, name=f"sparse_{i}")
           for i in range(TABLES)]
    ff.distributed_embedding(ins, VOCAB, DIM, name="tables")
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    cands = candidate_maps(op, mesh, cfg)
    per_table = [c for c in cands
                 if DEVICE_KEY in c and len(c[DEVICE_KEY]) == TABLES]
    assert per_table, cands
    assert tuple(t % 8 for t in range(TABLES)) in [
        c[DEVICE_KEY] for c in per_table]


def test_placed_strategy_file_roundtrip(tmp_path):
    ids = (3, 1, 4, 1, 5, 0, 2, 6)
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    p = str(tmp_path / "strategy.json")
    strat.save(p)
    loaded = Strategy.load(p)
    assert loaded.for_op("tables").device_ids == ids
    # and the loaded strategy still executes
    mesh = make_mesh((2, 4), ("data", "model"))
    ff = build(mesh=mesh, strategy=loaded)
    assert float(ff.train_batch(batches(n=1)[0])["loss"]) > 0


def test_placed_strategy_text_format_roundtrip(tmp_path):
    """Reference text format (strategy.cc): a per-table placement
    exports as a tpu_pin line with the literal id list and imports back
    to an executable DEVICE_KEY strategy."""
    from flexflow_tpu.parallel.strategy_io import (
        load_strategies_from_file,
        save_strategies_to_file,
    )

    ids = (3, 1, 4, 1, 5, 0, 2, 6)
    mesh = make_mesh((8,), ("data",))
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
    ff = build(mesh=mesh, strategy=strat)
    p = str(tmp_path / "strategy.txt")
    save_strategies_to_file(ff, strat, mesh, p)
    text = open(p).read()
    assert "tpu_pin" in text and "3 1 4 1 5 0 2 6" in text
    loaded = load_strategies_from_file(ff, mesh, p)
    assert loaded.for_op("tables").device_ids == ids
    ff2 = build(mesh=mesh, strategy=loaded)
    op = next(o for o in ff2.ops if o.op_type == "distributed_embedding")
    assert op.placement == ids
    assert np.isfinite(float(ff2.train_batch(batches(n=1)[0])["loss"]))


def test_dlrm_strategy_generator(tmp_path):
    """tools/gen_dlrm_strategy.py (the reference dlrm_strategy.py/
    gen_strategy.sh analog): generated files load into executable
    placements in both formats."""
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "gen_dlrm_strategy.py")
    out_json = str(tmp_path / "s.json")
    r = subprocess.run(
        [sys.executable, tool, "--tables", "8", "--devices", "4",
         "--scheme", "blocked", "--op-name", "tables",
         "--out", out_json],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    loaded = Strategy.load(out_json)
    assert loaded.for_op("tables").device_ids == (0, 0, 1, 1, 2, 2, 3, 3)
    mesh = make_mesh((4,), ("data",))
    ff = build(mesh=mesh, strategy=loaded)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    assert op.placement == (0, 0, 1, 1, 2, 2, 3, 3)
    assert np.isfinite(float(ff.train_batch(batches(n=1)[0])["loss"]))

    # text format: the tpu_pin line parses back to the same placement
    out_txt = str(tmp_path / "s.txt")
    r = subprocess.run(
        [sys.executable, tool, "--tables", "8", "--devices", "4",
         "--scheme", "blocked", "--op-name", "tables",
         "--format", "text", "--out", out_txt],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    from flexflow_tpu.parallel.strategy_io import (
        load_strategies_from_file,
    )
    loaded_txt = load_strategies_from_file(ff, mesh, out_txt)
    assert loaded_txt.for_op("tables").device_ids \
        == (0, 0, 1, 1, 2, 2, 3, 3)

    # invalid device counts fail loudly, never emit negative ids
    r = subprocess.run(
        [sys.executable, tool, "--devices", "0"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0 and ">= 1" in r.stdout + r.stderr


def test_simulator_pricing_stable_after_placement_applied():
    """Pricing a candidate must not depend on whether the LIVE op
    already carries an applied placement (weight_specs then reflects
    the padded slot count): simulate-after-compile — the placement_ab
    pattern — must cost identically to simulate-before-compile, and
    the whole-op pin shorthand (one id) must price like its expanded
    per-table form."""
    from flexflow_tpu.search.simulator import Simulator

    mesh = make_mesh((8,), ("data",))
    ids = (0,) * TABLES  # maximal padding: 8 tables -> 64 slots
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({DEVICE_KEY: ids}))

    ff1 = build()  # never compiled with a placement
    t_before = Simulator(ff1, mesh).simulate(strat)
    ff2 = build(mesh=mesh, strategy=strat)  # placement APPLIED
    op = next(o for o in ff2.ops if o.op_type == "distributed_embedding")
    assert op.num_slots == 8 * TABLES
    t_after = Simulator(ff2, mesh).simulate(strat)
    assert t_before == pytest.approx(t_after, rel=1e-9)

    # one-id shorthand == expanded per-table pin
    strat_short = Strategy(default=OpStrategy({"sample": "data"}))
    strat_short.set("tables", OpStrategy({DEVICE_KEY: (0,)}))
    t_short = Simulator(ff1, mesh).simulate(strat_short)
    assert t_short == pytest.approx(t_before, rel=1e-9)
