"""Disaggregated prefill/decode serving (PR 12).

Layers:
  * handoff — PagedKVCache.export_pages/import_pages move whole-page
    chain-keyed content between pools refcount-correctly (imported
    pages park hashed/refcount-0/matchable; dedupe by key; invariants
    extended to imported pages), and ServeEngine.export_kv/import_kv
    ship the device rows (+ scale rows on quantized pools) through ONE
    fixed-shape program each.
  * cluster — DisaggCluster (prefill role -> page handoff -> decode
    role) is token-identical to the unified engine through prefix
    hits, chunked prefill, preemption pressure, speculation+rollback,
    and int8/fp8 pages (bounded-error + greedy-tie-parity gates
    transfer), with zero recompiles after warmup and check_invariants
    on BOTH roles' pools after every step. Backpressure (the
    degradation-ladder watermark) skips imports instead of squeezing
    a loaded pool, degrading to recompute — still exact.
  * search — serve_step_tasks prices the page-transfer link on the
    host link (a KV-dtype flip changes the priced transfer cost and
    is a guaranteed cost-cache miss), and optimize_serve(...,
    disaggregated=True) returns the prefill:decode ratio table with a
    >= 1.3x simulated TPOT reduction for the production-scale arch.
"""

import dataclasses

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.parallel.mesh import MachineSpec
from flexflow_tpu.search.cost_model import (ServeArch,
                                            kv_handoff_bytes,
                                            serve_step_tasks)
from flexflow_tpu.search.machine_model import TPUMachineModel
from flexflow_tpu.search.serve_place import (DisaggPlacement,
                                             optimize_serve,
                                             optimize_serve_disagg,
                                             price_disagg_candidate)
from flexflow_tpu.search.simulator import (simulate_serve_step,
                                           simulate_serve_tasks)
from flexflow_tpu.serve import DisaggCluster, ServeEngine
from flexflow_tpu.serve.kv_cache import PagedKVCache, prefix_page_keys


# --------------------------------------------------------------- helpers
def _lm(kv_dtype="float32", *, page_size=4, pool_pages=None,
        budget=32, max_seqs=4, max_seq_len=64, **cfg_kw):
    cfg = FFConfig(
        batch_size=1, kv_page_size=page_size,
        kv_num_pages=pool_pages or (1 + 16 * max_seqs),
        kv_dtype=kv_dtype, serve_max_seqs=max_seqs,
        serve_prefill_budget=budget, **cfg_kw)
    return build_transformer_lm(cfg, vocab_size=61,
                                max_seq_len=max_seq_len, hidden=32,
                                num_heads=4, num_layers=2, ff_dim=72)


def _prompts(rng, n, lo=4, hi=28):
    return [list(rng.randint(1, 61, size=rng.randint(lo, hi)))
            for _ in range(n)]


def _big_arch(**over):
    kw = dict(num_layers=48, hidden=6144, num_heads=48, head_dim=128,
              ff_dim=24576, vocab=256128, decode_lanes=32,
              prefill_lanes=512, context=2048, decode_tokens=128,
              kv_dtype="int8", kv_itemsize=1.0, kv_scales=True,
              act_itemsize=2.0, act_dtype="bfloat16",
              param_itemsize=2.0)
    kw.update(over)
    return ServeArch(**kw)


def _per_step_invariants(cluster):
    def hook(role, w, step):
        cluster.check_invariants()
    return hook


# ------------------------------------------------------- pool-level handoff
def test_export_import_pages_refcount_correct():
    """Host bookkeeping round trip: exported full pages re-register on
    the importer as parked (hashed, refcount-0, matchable) pages; the
    partial tail never crosses; invariants hold on both pools."""
    from flexflow_tpu.serve.kv_cache import KVCacheConfig
    cfg = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                        page_size=4, num_pages=33, max_seqs=2,
                        max_seq_len=64)
    src = PagedKVCache(cfg)
    dst = PagedKVCache(cfg)
    tokens = list(range(1, 12))          # 11 tokens: 2 full pages + tail
    slot = src.alloc_slot()
    src.ensure_capacity(slot, len(tokens))
    src.advance(slot, len(tokens))
    pages, keys, ntok = src.export_pages(slot, tokens)
    assert len(pages) == 2 and ntok == 8
    assert keys == prefix_page_keys(tokens, 4, 2)
    todo = dst.import_pages(keys)
    assert [i for i, _ in todo] == [0, 1]
    assert dst.imported_pages() == tuple(sorted(p for _, p in todo))
    # parked state: refcount 0, hashed, matchable
    for _, p in todo:
        assert dst.ref(p) == 0
    assert dst.match_prefix(keys) == [p for _, p in todo]
    src.check_invariants()
    dst.check_invariants()
    # re-import dedupes fully
    assert dst.import_pages(keys) == []
    assert dst.stats["import_dedup_pages"] == 2
    # attach to a slot, free it, and the invariants/imported set survive
    s2 = dst.alloc_slot()
    dst.attach_prefix(s2, [p for _, p in todo], 8)
    dst.check_invariants()
    dst.free_slot(s2)
    dst.check_invariants()
    # eviction drops the key AND the imported marking atomically
    dst.shrink_lru(0)
    assert dst.imported_pages() == ()
    dst.check_invariants()


def test_import_pages_requires_prefix_cache():
    from flexflow_tpu.serve.kv_cache import KVCacheConfig
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=17, max_seqs=1,
                        max_seq_len=32)
    pool = PagedKVCache(cfg, prefix_cache=False)
    with pytest.raises(RuntimeError, match="prefix cache"):
        pool.import_pages([b"k" * 32])


def test_engine_export_import_rows_bit_equal():
    """Device rows survive the hop bit-for-bit: export from a prefill
    engine mid-serve, import into a fresh engine, and the destination
    pool's rows at the imported pages equal the source's."""
    rng = np.random.RandomState(0)
    ff = _lm()
    src = ServeEngine(ff, spec_tokens=0)
    src.warmup()
    dst = ServeEngine(ff, spec_tokens=0)
    dst.warmup()
    dst.warmup_handoff()
    prompt = list(rng.randint(1, 61, size=13))
    ships = []
    src.generate([prompt], 1,
                 on_finish=lambda r: ships.append(
                     src.export_kv(r.slot, r.context)))
    (ship,) = ships
    assert ship is not None and ship.num_pages == len(prompt) // 4
    written = dst.import_kv(ship)
    assert written == ship.num_pages
    dst.cache.check_invariants()
    pages = [dst.cache._page_of_hash[k] for k in ship.keys]
    got_k = np.asarray(dst._k_pages)[:, pages]
    got_v = np.asarray(dst._v_pages)[:, pages]
    np.testing.assert_array_equal(got_k, ship.k_rows)
    np.testing.assert_array_equal(got_v, ship.v_rows)
    # geometry mismatch is rejected loudly
    bad = dataclasses.replace(ship, page_size=8)
    with pytest.raises(ValueError, match="geometry"):
        dst.import_kv(bad)


def test_export_import_sharded_tp2():
    """The shard_map handoff path: head-sharded (t=2) engines round-
    trip page rows bit-exactly and a sharded cluster stays token-
    identical to the sharded unified engine, zero recompiles."""
    rng = np.random.RandomState(10)
    ff = _lm(serve_mesh="2")
    src = ServeEngine(ff, spec_tokens=0)
    assert src.tp == 2
    src.warmup()
    dst = ServeEngine(ff, spec_tokens=0)
    dst.warmup()
    dst.warmup_handoff()
    prompt = list(rng.randint(1, 61, size=14))
    ships = []
    src.generate([prompt], 1,
                 on_finish=lambda r: ships.append(
                     src.export_kv(r.slot, r.context)))
    (ship,) = ships
    assert dst.import_kv(ship) == ship.num_pages
    pages = [dst.cache._page_of_hash[k] for k in ship.keys]
    np.testing.assert_array_equal(
        np.asarray(dst._k_pages)[:, pages], ship.k_rows)
    dst.cache.check_invariants()
    # sharded cluster == sharded unified engine, token for token
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 6, hi=40)
    ref = uni.generate(prompts, 5)
    cl = DisaggCluster(ff, spec_tokens=0)
    counts = cl.warmup()
    assert all(e.tp == 2 for _, e in cl.engines())
    out = cl.generate(prompts, 5)
    assert out == ref
    assert cl.compile_counts() == counts
    cl.check_invariants()


# ------------------------------------------------------- cluster exactness
def test_disagg_token_identity_f32():
    """The acceptance gate: a disaggregated cluster is token-identical
    to the unified engine (and the no-cache reference) on f32 pages,
    zero recompiles after warmup on both roles, invariants on both
    pools after every step."""
    rng = np.random.RandomState(1)
    ff = _lm()
    uni = ServeEngine(ff)
    uni.warmup()
    prompts = _prompts(rng, 8, hi=50)
    ref = uni.generate(prompts, 6)
    cl = DisaggCluster(ff)
    counts = cl.warmup()
    out = cl.generate(prompts, 6, on_step=_per_step_invariants(cl))
    assert out == ref
    assert out == uni.generate_reference(prompts, 6)
    assert cl.compile_counts() == counts
    assert cl.stats["handoff_requests"] > 0
    # every role's pool drained clean
    for _, eng in cl.engines():
        assert eng.cache.free_pages == eng.cache_cfg.usable_pages


def test_disagg_prefix_hits_and_dedup():
    """Shared prompt prefixes cross the link ONCE: the second batch's
    imports dedupe against resident keys, and the decode role admits
    handed-off requests as prefix hits (near-zero recomputed prefill
    beyond tail chunks)."""
    rng = np.random.RandomState(2)
    ff = _lm()
    cl = DisaggCluster(ff)
    cl.warmup()
    prefix = list(rng.randint(1, 61, size=24))
    prompts = [prefix + list(rng.randint(1, 61, size=4))
               for _ in range(6)]
    uni = ServeEngine(ff)
    uni.warmup()
    ref = uni.generate(prompts, 4)
    out = cl.generate(prompts, 4)
    assert out == ref
    assert cl.stats["handoff_dedup_pages"] > 0
    dec = cl.last_stats["roles"]["decode"][0]
    # the decode role prefix-matched the imported pages: computed far
    # fewer prefill tokens than the prompts carry
    assert dec["prefix_hit_tokens"] > 0
    assert dec["prefill_tokens_computed"] < dec["prompt_tokens_total"]


def test_disagg_speculation_and_eos():
    """Speculation+rollback on the decode role and eos termination on
    BOTH sides of the split stay token-identical to the unified
    engine."""
    rng = np.random.RandomState(3)
    ff = _lm()
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 6, hi=40)
    eos = 7
    ref = uni.generate(prompts, 10, eos_token=eos)
    cl = DisaggCluster(ff, spec_tokens=3)
    counts = cl.warmup()
    out = cl.generate(prompts, 10, eos_token=eos,
                      on_step=_per_step_invariants(cl))
    assert out == ref
    assert cl.compile_counts() == counts
    # max_new=1 requests never reach the decode role
    out1 = cl.generate(prompts, 1, eos_token=eos)
    assert out1 == [r[:1] for r in ref]


def test_disagg_preemption_pressure_exact():
    """A pool tight enough to churn admissions/preemptions on the
    decode role: outputs still identical, pools still clean."""
    rng = np.random.RandomState(4)
    ff = _lm(pool_pages=1 + 16 * 2, max_seq_len=64)
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 10, lo=20, hi=55)
    ref = uni.generate(prompts, 5)
    cl = DisaggCluster(ff, spec_tokens=0)
    cl.warmup()
    out = cl.generate(prompts, 5, on_step=_per_step_invariants(cl))
    assert out == ref
    cl.check_invariants()


def test_disagg_backpressure_skips_not_breaks():
    """With the admission watermark raised past a shipment's headroom,
    the cluster SKIPS imports (counted) instead of squeezing the pool
    — and the decode role recomputes, keeping outputs exact."""
    rng = np.random.RandomState(5)
    ff = _lm(pool_pages=17, max_seq_len=64,
             serve_admit_watermark=0.5)  # wm > post-import headroom
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 4, lo=40, hi=55)
    ref = uni.generate(prompts, 3)
    cl = DisaggCluster(ff, spec_tokens=0)
    cl.warmup()
    out = cl.generate(prompts, 3, on_step=_per_step_invariants(cl))
    assert out == ref
    assert cl.stats["handoff_skipped"] > 0
    assert cl.metrics.counter("kv_handoff_skipped_total") > 0


@pytest.mark.parametrize("kv_dtype", ["int8", "float8_e4m3"])
def test_disagg_quantized_pages(kv_dtype):
    """Quantized pools ship their int8/fp8 rows + f32 scale rows
    bit-exactly: the cluster equals the unified engine token-for-token
    (transfer is lossless over already-quantized content), and the
    no-cache reference comparison holds through the usual tie-margin
    gate."""
    rng = np.random.RandomState(6)
    ff = _lm(kv_dtype)
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 6, lo=8, hi=40)
    ref_q = uni.generate(prompts, 5)
    cl = DisaggCluster(ff, spec_tokens=0)
    counts = cl.warmup()
    out = cl.generate(prompts, 5, on_step=_per_step_invariants(cl))
    assert out == ref_q, "disagg diverged from unified on " + kv_dtype
    assert cl.compile_counts() == counts
    for _, eng in cl.engines():
        eng.check_kv_scales()
    oracle = uni.generate_reference(prompts, 5)
    uni.assert_token_parity(prompts, out, oracle,
                            what=f"disagg {kv_dtype} outputs")


def test_disagg_sampled_streams_survive_the_split():
    """Seeded sampling crosses the prefill->decode handoff (the PR-12
    follow-up): draws key on the stream-id carried with the request /
    PageShipment — NOT the local scheduler's rid/token index — with
    the decode role resuming at offset 1, so unified and disaggregated
    token streams are identical at one seed for temperature/top-k
    sampling (the mixes that used to be refused loudly)."""
    rng = np.random.RandomState(3)
    ff = _lm()
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    cl = DisaggCluster(ff, spec_tokens=0)
    cl.warmup()
    prompts = _prompts(rng, 6, hi=24)
    # mixed per-request sampling: greedy, top_k=1, and real top-k
    # temperature streams in one batch, crossing 2 decode waves
    temps = [0.0, 0.7, 0.9, 0.8, 1.3, 0.6]
    tks = [None, 1, 5, 8, 3, None]
    for seed in (0, 7):
        ref = uni.generate(prompts, 6, temperature=temps, top_k=tks,
                           sample_seed=seed)
        out = cl.generate(prompts, 6, temperature=temps, top_k=tks,
                          sample_seed=seed)
        assert out == ref, (
            f"disagg sampled streams diverged from unified at seed "
            f"{seed}")
    # a DIFFERENT seed must move the sampled streams (the equality
    # above is not vacuous greedy collapse)
    alt = cl.generate(prompts, 6, temperature=temps, top_k=tks,
                      sample_seed=11)
    assert alt != out
    # eos emitted mid-stream by a SAMPLED request truncates identically
    eos = int(ref[2][1]) if len(ref[2]) > 1 else 7
    assert cl.generate(prompts, 6, temperature=temps, top_k=tks,
                       sample_seed=0, eos_token=eos) == \
        uni.generate(prompts, 6, temperature=temps, top_k=tks,
                     sample_seed=0, eos_token=eos)
    # the unified engine's submit contract still holds up front
    with pytest.raises(ValueError, match="max_new_tokens"):
        cl.generate([[1, 2], [3, 4]], [4, 0])
    assert cl.stats["handoff_requests"] > 0


def test_disagg_per_request_args_slice_per_wave():
    """Per-request lists survive the wave split: a batch whose decode
    wave is a proper subset (one max_new=1 request) with per-request
    greedy args and 2 prefill engines must serve, identically."""
    rng = np.random.RandomState(9)
    ff = _lm()
    uni = ServeEngine(ff, spec_tokens=0)
    uni.warmup()
    prompts = _prompts(rng, 5, hi=30)
    mnt = [6, 1, 6, 1, 6]
    ref = uni.generate(prompts, mnt, temperature=[0.0] * 5,
                       top_k=[1] * 5)
    cl = DisaggCluster(ff, prefill_engines=2, spec_tokens=0)
    cl.warmup()
    out = cl.generate(prompts, mnt, temperature=[0.0] * 5,
                      top_k=[1] * 5)
    assert out == ref
    # done-at-first-token requests ship nothing: only the 3 decoding
    # requests' shipments crossed the link
    assert cl.stats["handoff_requests"] <= 3


def test_disagg_ratio_and_cli_config():
    """serve_disagg_ratio parses/validates; from_config builds the
    requested engine counts; engine_for consumes --serve-disagg; the
    decode-budget floor is enforced."""
    from flexflow_tpu.serve import engine_for
    ff = _lm(serve_disagg_ratio="2:1")
    cl = DisaggCluster.from_config(ff)
    assert (len(cl.prefill), len(cl.decode)) == (2, 1)
    # the config-driven entry point: --serve-disagg picks the cluster
    assert isinstance(engine_for(_lm()), ServeEngine)
    srv = engine_for(_lm(serve_disagg=True, serve_disagg_ratio="1:2"))
    assert isinstance(srv, DisaggCluster)
    assert (len(srv.prefill), len(srv.decode)) == (1, 2)
    # "auto" resolves through the ratio search and keeps the winning
    # placement on the cluster
    cla = DisaggCluster.from_config(
        _lm(serve_disagg_ratio="auto", serve_disagg_decode_budget=24),
        num_devices=2)
    assert cla.placement is not None
    assert (len(cla.prefill) == cla.placement.prefill_engines
            and len(cla.decode) == cla.placement.decode_engines)
    assert cla.decode_budget == 24
    cfg = FFConfig(argv=["--serve-disagg", "--serve-disagg-ratio",
                         "3:2", "--serve-disagg-decode-budget", "64"])
    assert cfg.serve_disagg and cfg.serve_disagg_ratio == "3:2"
    assert cfg.serve_disagg_decode_budget == 64
    with pytest.raises(ValueError, match="serve_disagg_ratio"):
        FFConfig(serve_disagg_ratio="0:2")
    with pytest.raises(ValueError, match="decode_budget"):
        DisaggCluster(_lm(), decode_budget=2)  # < one page


def test_disagg_report_and_metrics_split():
    """The per-role TTFT/TPOT split renders from the cluster's own
    exported registry (the no-drift rule) and the handoff counters
    land in it."""
    from flexflow_tpu.utils.profiling import disagg_report
    rng = np.random.RandomState(7)
    ff = _lm()
    cl = DisaggCluster(ff)
    cl.warmup()
    cl.generate(_prompts(rng, 6), 6)
    m = cl.metrics
    assert m.hist_count("serve_tpot_seconds", role="decode") > 0
    assert m.hist_count("serve_ttft_seconds", role="prefill") > 0
    assert m.counter("kv_transfer_pages_total") > 0
    assert m.counter("kv_transfer_bytes_total") > 0
    assert m.counter("kv_handoff_requests_total") > 0
    rep = disagg_report(cl.last_stats, m)
    assert "prefill role (lifetime):" in rep \
        and "decode role (lifetime):" in rep
    assert "kv handoff:" in rep
    # rebuilding the fold from the stats dict gives the same split
    rep2 = disagg_report(cl.last_stats, None)
    assert "decode role:" in rep2
    # last_stats carries THIS call's handoff delta (self.stats is
    # lifetime): a fully-deduped second call ships 0 pages
    first_pages = cl.last_stats["handoff"]["handoff_pages"]
    assert first_pages > 0
    cl.generate(_prompts(np.random.RandomState(7), 6), 6)
    assert cl.last_stats["handoff"]["handoff_pages"] == 0
    assert cl.stats["handoff_pages"] == first_pages


def test_disagg_memory_ledger_covers_both_roles():
    """The cluster ledger sums BOTH roles' pools (the
    don't-undercount satellite): cluster totals equal the per-role
    sums and every role's kv pool is accounted."""
    ff = _lm()
    cl = DisaggCluster(ff, prefill_engines=1, decode_engines=2)
    cl.warmup()
    led = cl.memory_ledger()
    roles = led["roles"]
    assert len(roles) == 3
    assert led["kv_pool_bytes"] == pytest.approx(
        sum(r["kv_pool_bytes"] for r in roles.values()))
    assert led["params_bytes"] == pytest.approx(
        sum(r["params_bytes"] for r in roles.values()))
    assert led["total_bytes"] > max(
        r["total_bytes"] for r in roles.values())


def test_disagg_telemetry_spans_and_gauges():
    """With a live bus: kv_handoff spans land on the cluster track,
    transfer counters on the registry, and the role-labeled HBM
    gauges cover the cluster."""
    from flexflow_tpu.utils.telemetry import Telemetry
    rng = np.random.RandomState(8)
    tel = Telemetry()
    ff = _lm()
    cl = DisaggCluster(ff, telemetry=tel)
    cl.warmup()
    cl.generate(_prompts(rng, 4, lo=8, hi=30), 4)
    names = {(ev[1], ev[2]) for ev in tel.events}
    assert (("serve", "cluster"), "kv_handoff") in names, names
    assert tel.metrics.counter("kv_transfer_bytes_total") > 0
    cl.memory_ledger()
    assert tel.metrics.gauge("serve_hbm_bytes", component="kv_pool",
                             role="cluster") > 0


# ------------------------------------------------------- search pricing
def test_transfer_link_priced_and_dtype_sensitive():
    """The page-transfer link: kv_handoff_bytes follows the storage
    itemsize (f32 -> int8 is the 4x byte lever, minus scale rows), the
    transfer task rides BESIDE the chain (makespan = max, not sum),
    and simulate_serve_step grows only when the link dominates."""
    arch = _big_arch()
    f32 = dataclasses.replace(arch, kv_dtype="float32",
                              kv_itemsize=4.0, kv_scales=False)
    assert kv_handoff_bytes(f32) > 3.5 * kv_handoff_bytes(arch)
    mm = TPUMachineModel(spec=MachineSpec.v5e(16))
    tasks = serve_step_tasks(arch, 8, mm, lanes=arch.decode_lanes,
                             transfer_tokens=arch.context)
    (xfer,) = [t for t in tasks if t.kind == "transfer"]
    assert xfer.name == "kv_handoff" and not xfer.deps
    chain = sum(t.seconds for t in tasks if t.kind != "transfer")
    assert simulate_serve_tasks(tasks) == pytest.approx(
        max(chain, xfer.seconds))
    base = simulate_serve_step(arch, 8, mm)
    small = simulate_serve_step(arch, 8, mm, transfer_tokens=8)
    assert small == pytest.approx(base)   # link hidden behind compute
    huge = simulate_serve_step(arch, 8, mm,
                               transfer_tokens=64 * arch.context)
    assert huge > base                    # link became the bottleneck


def test_disagg_placement_ratio_table_and_gate():
    """optimize_serve(..., disaggregated=True) returns the ratio
    table; the winner beats every tabled ratio; simulated TPOT
    reduction >= 1.3x for the production arch (the ci.sh 1m simulated
    half)."""
    mm = TPUMachineModel(spec=MachineSpec.v5e(16))
    place = optimize_serve(_big_arch(), 16, mm=mm, disaggregated=True)
    assert isinstance(place, DisaggPlacement)
    assert place.ratio in place.ratio_table
    assert place.prefill_engines >= 1 and place.decode_engines >= 1
    assert (place.prefill_engines * place.prefill_tensor
            + place.decode_engines * place.decode_tensor) <= 16
    assert min(place.ratio_table.values()) <= place.bottleneck_s * (
        1 + 1e-9)
    assert place.tpot_reduction_vs_unified() >= 1.3
    # the decode step never pays the prefill budget's lanes
    assert place.decode_step_s < place.prefill_step_s


def test_disagg_transfer_cost_cache_miss_on_dtype_flip(tmp_path):
    """The acceptance regression: a KV-dtype flip (f32 -> int8)
    changes the priced transfer cost AND is a guaranteed cost-cache
    miss (different fingerprint + different entry key)."""
    from flexflow_tpu.search.cost_cache import CostCache
    from flexflow_tpu.search.serve_place import _serve_fingerprint
    mm = TPUMachineModel(spec=MachineSpec.v5e(16))
    arch_q = _big_arch()
    arch_f = dataclasses.replace(arch_q, kv_dtype="float32",
                                 kv_itemsize=4.0, kv_scales=False)
    cache = CostCache(str(tmp_path / "cc.json"))
    fp_q = _serve_fingerprint(mm, arch_q)
    fp_f = _serve_fingerprint(mm, arch_f)
    assert fp_q != fp_f
    pre_q, dec_q, xfer_q = price_disagg_candidate(
        arch_q, 8, 8, mm, cache=cache, fingerprint=fp_q)
    pre_f, dec_f, xfer_f = price_disagg_candidate(
        arch_f, 8, 8, mm, cache=cache, fingerprint=fp_f)
    assert xfer_f > 3.5 * xfer_q          # the 4x byte lever
    # cached rows round-trip under their own fingerprints
    assert price_disagg_candidate(
        arch_q, 8, 8, mm, cache=cache,
        fingerprint=fp_q) == (pre_q, dec_q, xfer_q)
    # the f32 row cannot be served for the int8 arch: its key lives
    # under a different fingerprint AND a different signature
    key_q = cache.entry_key("serve_disagg", (8, 8),
                            extra=arch_q.signature())
    key_f = cache.entry_key("serve_disagg", (8, 8),
                            extra=arch_f.signature())
    assert key_q != key_f
    assert cache.get(fp_q, key_f) is None


# =======================================================================
# continuous pipelining + cross-process transport (wall-clock fabric)
# =======================================================================
def test_disagg_pipelined_token_identity_and_hook_arities():
    """generate_pipelined drives BOTH roles' steppable sessions from
    one event loop (no batch wave barrier) yet stays token-identical
    to the phased path and the unified engine — only WHEN steps run
    changes, never what they compute. Both on_step arities work via
    normalize_on_step; a 2-arg hook is rejected at arming time."""
    from flexflow_tpu.serve import normalize_on_step
    rng = np.random.RandomState(11)
    ff = _lm(pool_pages=64)
    prompts = _prompts(rng, 8)
    max_new = [int(x) for x in rng.randint(1, 8, size=8)]
    temps = [0.8 if i % 3 == 0 else None for i in range(8)]
    tks = [3 if i % 3 == 0 else None for i in range(8)]
    uni = ServeEngine(_lm(pool_pages=64))
    ref = uni.generate(prompts, max_new, temperature=temps,
                       top_k=tks, sample_seed=5)
    uni.close()
    with DisaggCluster(ff, prefill_engines=2, decode_engines=2) as cl:
        phased = cl.generate(prompts, max_new, temperature=temps,
                             top_k=tks, sample_seed=5)
        assert phased == ref
        assert cl.last_stats["pipelined"] is False
        steps = []
        piped = cl.generate_pipelined(
            prompts, max_new, temperature=temps, top_k=tks,
            sample_seed=5, on_step=lambda role, w, s: (
                steps.append((role, w)), cl.check_invariants()))
        assert piped == ref
        assert cl.last_stats["pipelined"] is True
        assert cl.last_stats["handoff"]["handoff_requests"] == 8
        assert {r for r, _ in steps} == {"prefill", "decode"}
        # 1-arg hook through the same adapter
        one = []
        piped2 = cl.generate_pipelined(prompts, max_new,
                                       temperature=temps, top_k=tks,
                                       sample_seed=5,
                                       on_step=lambda s: one.append(1))
        assert piped2 == ref and len(one) > 0
        # max_new == 1 everywhere: pipelined must not submit empty
        # decode work (prefill emits the only token)
        assert cl.generate_pipelined(prompts, 1, sample_seed=5) \
            == cl.generate(prompts, 1, sample_seed=5)
        cl.check_invariants()
        for _, eng in cl.engines():
            assert eng.cache.free_pages == eng.cache_cfg.usable_pages
    with pytest.raises(TypeError, match="on_step"):
        normalize_on_step(lambda a, b: None)
    assert normalize_on_step(None) is None


def test_disagg_tcp_transport_token_identity():
    """--transport tcp: shipments really cross a loopback socket
    (length-prefixed frames, CRC, synchronous acks) and the cluster
    stays token-identical to the in-process handoff on BOTH the
    phased and pipelined paths — including quantized pages with
    scale rows."""
    rng = np.random.RandomState(13)
    prompts = _prompts(rng, 6)
    max_new = [int(x) for x in rng.randint(2, 7, size=6)]
    temps = [0.8 if i % 2 == 0 else None for i in range(6)]
    tks = [3 if i % 2 == 0 else None for i in range(6)]
    with DisaggCluster(_lm(pool_pages=64)) as cl:
        ref = cl.generate(prompts, max_new, temperature=temps,
                          top_k=tks, sample_seed=2)
        assert cl.last_stats["transport"] == "inproc"
    ff = _lm(pool_pages=64, serve_transport="tcp")
    with DisaggCluster(ff) as cl:
        assert cl._receiver is not None and cl._sender is not None
        out = cl.generate(prompts, max_new, temperature=temps,
                          top_k=tks, sample_seed=2)
        assert out == ref
        assert cl.last_stats["transport"] == "tcp"
        frames0 = cl._receiver.stats["frames"]
        assert frames0 > 0
        assert cl._receiver.stats["accepted"] == frames0
        assert cl._receiver.stats["wire_errors"] == 0
        piped = cl.generate_pipelined(prompts, max_new,
                                      temperature=temps, top_k=tks,
                                      sample_seed=2)
        assert piped == ref
        assert cl._receiver.stats["frames"] > frames0
        cl.check_invariants()
    # quantized pages cross the socket bit-exactly (scale rows ride
    # in the same frame)
    with DisaggCluster(_lm("int8", pool_pages=64)) as cl:
        ref_q = cl.generate(prompts, max_new, sample_seed=2)
    ffq = _lm("int8", pool_pages=64, serve_transport="tcp")
    with DisaggCluster(ffq) as cl:
        assert cl.generate(prompts, max_new, sample_seed=2) == ref_q
        assert cl._receiver.stats["wire_errors"] == 0
