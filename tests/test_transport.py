"""Cross-process PageShipment transport (serve/transport.py).

Layers:
  * wire — dumps_shipment/loads_shipment round-trips bit-exactly:
    every page byte (float32, int8, fp8 storage), scale rows, chain
    keys, geometry stamp and stream/tenant/trace ids; any malformed
    frame (truncated, bad magic/version, flipped payload byte, header
    overrun, trailing bytes) raises ShipmentWireError instead of
    admitting garbage pages.
  * socket — ShipmentSender/ShipmentReceiver move frames over a real
    TCP connection with synchronous acks; the receiver's import_fn is
    the admission authority (watermark skip and import failure both
    come back as acks, never as wedged streams).
  * cluster — a DisaggCluster with --transport tcp serves
    token-identically to the in-process handoff (asserted in
    test_disagg.py; here the loopback endpoints are exercised raw).
"""

import threading

import numpy as np
import pytest

from flexflow_tpu.serve import (PageShipment, ShipmentReceiver,
                                ShipmentSender, ShipmentWireError,
                                dumps_shipment, loads_shipment)
from flexflow_tpu.serve.transport import (_CRC, _HDR, MAGIC,
                                          WIRE_VERSION)

# --------------------------------------------------------------- helpers
_GEOM = dict(layers=2, pages=3, page=4, heads=2, hd=8)


def _rows(rng, dtype):
    g = _GEOM
    shape = (g["layers"], g["pages"], g["page"], g["heads"], g["hd"])
    if dtype == "int8":
        return rng.integers(-128, 128, size=shape).astype(np.int8)
    if dtype.startswith("float8"):
        import ml_dtypes
        return rng.standard_normal(shape).astype(
            np.dtype(ml_dtypes.float8_e4m3fn))
    return rng.standard_normal(shape).astype(np.float32)


def _ship(dtype="float32", *, scales=False, seed=0, stream_id=7,
          tenant_id=2, trace_id=12345):
    rng = np.random.default_rng(seed)
    g = _GEOM
    scale = None
    if scales:
        scale = rng.standard_normal(
            (g["layers"], g["pages"], g["page"], g["heads"])
        ).astype(np.float32)
    return PageShipment(
        keys=[bytes([i] * 16) for i in range(g["pages"])],
        ntokens=g["pages"] * g["page"] - 1,
        k_rows=_rows(rng, dtype), v_rows=_rows(rng, dtype),
        k_scale_rows=scale,
        v_scale_rows=None if scale is None else scale * 2.0,
        page_size=g["page"], num_layers=g["layers"],
        num_heads=g["heads"], head_dim=g["hd"], kv_dtype=dtype,
        stream_id=stream_id, tenant_id=tenant_id, trace_id=trace_id)


def _bits(a):
    """Bit-exact comparison view (NaN-safe for fp8/float payloads)."""
    return np.asarray(a).view(np.uint8)


def _assert_identical(a: PageShipment, b: PageShipment) -> None:
    assert b.keys == a.keys
    assert b.ntokens == a.ntokens
    assert b.signature() == a.signature()
    assert (b.stream_id, b.tenant_id, b.trace_id) == \
        (a.stream_id, a.tenant_id, a.trace_id)
    assert b.k_rows.dtype == a.k_rows.dtype
    assert b.k_rows.shape == a.k_rows.shape
    assert np.array_equal(_bits(b.k_rows), _bits(a.k_rows))
    assert np.array_equal(_bits(b.v_rows), _bits(a.v_rows))
    for name in ("k_scale_rows", "v_scale_rows"):
        sa, sb = getattr(a, name), getattr(b, name)
        assert (sa is None) == (sb is None)
        if sa is not None:
            assert sb.dtype == sa.dtype
            assert np.array_equal(_bits(sb), _bits(sa))


# =======================================================================
# wire round trip
# =======================================================================
@pytest.mark.parametrize("dtype,scales", [
    ("float32", False),
    ("int8", True),
    ("float8_e4m3fn", True),
])
def test_wire_round_trip_bit_exact(dtype, scales):
    ship = _ship(dtype, scales=scales)
    back = loads_shipment(dumps_shipment(ship))
    _assert_identical(ship, back)
    # decoded arrays own writable storage (frombuffer views don't)
    back.k_rows[0, 0, 0, 0, 0] = back.k_rows[0, 0, 0, 0, 0]


def test_wire_none_ids_and_nbytes():
    ship = _ship(stream_id=None, trace_id=None, tenant_id=0)
    back = loads_shipment(dumps_shipment(ship))
    assert back.stream_id is None and back.trace_id is None
    assert back.nbytes == ship.nbytes
    assert back.num_pages == ship.num_pages


def test_wire_rejects_malformed_frames():
    frame = bytearray(dumps_shipment(_ship("int8", scales=True)))
    # truncation at several depths
    for cut in (0, 3, _HDR.size, _HDR.size + 10, len(frame) - 1):
        with pytest.raises(ShipmentWireError):
            loads_shipment(bytes(frame[:cut]))
    # bad magic
    bad = bytes(b"XXXX") + bytes(frame[4:])
    with pytest.raises(ShipmentWireError, match="magic"):
        loads_shipment(bad)
    # future version
    bad = bytearray(frame)
    bad[4] = WIRE_VERSION + 1
    with pytest.raises(ShipmentWireError, match="version"):
        loads_shipment(bytes(bad))
    # a flipped payload byte must fail the CRC, not import garbage
    bad = bytearray(frame)
    bad[len(bad) - _CRC.size - 5] ^= 0x40
    with pytest.raises(ShipmentWireError, match="CRC"):
        loads_shipment(bytes(bad))
    # trailing bytes after the declared envelope
    with pytest.raises(ShipmentWireError):
        loads_shipment(bytes(frame) + b"\x00")
    # sanity: the untouched frame still decodes
    loads_shipment(bytes(frame))


def test_wire_header_must_describe_payload():
    import json
    from flexflow_tpu.serve.transport import _LEN
    frame = dumps_shipment(_ship())
    _magic, _ver, body_len = _HDR.unpack_from(frame, 0)
    body = bytearray(frame[_HDR.size:_HDR.size + body_len])
    (hlen,) = _LEN.unpack_from(bytes(body), 0)
    header = json.loads(bytes(body[_LEN.size:_LEN.size + hlen]))
    # declare a wider array than the payload carries
    header["arrays"]["v_rows"]["shape"][1] += 7
    hjson = json.dumps(header, separators=(",", ":")).encode()
    body2 = _LEN.pack(len(hjson)) + hjson \
        + bytes(body[_LEN.size + hlen:])
    import zlib
    frame2 = (_HDR.pack(MAGIC, WIRE_VERSION, len(body2)) + body2
              + _CRC.pack(zlib.crc32(body2) & 0xFFFFFFFF))
    with pytest.raises(ShipmentWireError):
        loads_shipment(frame2)


# =======================================================================
# socket endpoints
# =======================================================================
def test_socket_round_trip_and_acks():
    got = []

    def import_fn(ship):
        got.append(ship)
        return {"accepted": True, "pages_written": ship.num_pages}

    with ShipmentReceiver(import_fn) as rx:
        with ShipmentSender(rx.host, rx.port) as tx:
            for seed in range(3):
                ship = _ship("int8", scales=True, seed=seed,
                             stream_id=seed)
                ack = tx.send(ship)
                assert ack["accepted"] is True
                assert ack["pages_written"] == ship.num_pages
        assert len(got) == 3
        for seed, back in enumerate(got):
            _assert_identical(_ship("int8", scales=True, seed=seed,
                                    stream_id=seed), back)
        assert rx.stats["frames"] == 3
        assert rx.stats["accepted"] == 3
        assert rx.stats["wire_errors"] == 0


def test_socket_receiver_backpressure_and_errors():
    """The receiver's import_fn is the admission authority: a
    watermark skip and an import crash BOTH come back as acks — the
    stream stays usable and nothing imports."""
    verdicts = iter([
        {"accepted": False, "pages_written": 0},   # watermark skip
        RuntimeError("pool exploded"),             # import crash
        {"accepted": True, "pages_written": 3},
    ])

    def import_fn(ship):
        v = next(verdicts)
        if isinstance(v, Exception):
            raise v
        return v

    with ShipmentReceiver(import_fn) as rx:
        with ShipmentSender(rx.host, rx.port) as tx:
            a1 = tx.send(_ship())
            assert a1["accepted"] is False
            a2 = tx.send(_ship())
            assert a2["accepted"] is False
            assert "pool exploded" in a2["error"]
            a3 = tx.send(_ship())
            assert a3["accepted"] is True and a3["pages_written"] == 3
        assert rx.stats["skipped"] == 2 and rx.stats["accepted"] == 1


def test_socket_concurrent_senders():
    """Per-connection receiver threads: N senders shipping in parallel
    all get correct acks and every frame lands exactly once."""
    seen = []
    lock = threading.Lock()

    def import_fn(ship):
        with lock:
            seen.append(ship.stream_id)
        return {"accepted": True, "pages_written": ship.num_pages}

    n = 4
    with ShipmentReceiver(import_fn) as rx:
        errs = []

        def one(sid):
            try:
                with ShipmentSender(rx.host, rx.port) as tx:
                    for j in range(5):
                        ack = tx.send(_ship(seed=sid * 10 + j,
                                            stream_id=sid))
                        assert ack["accepted"] is True
            except Exception as e:   # surface in the main thread
                errs.append(e)

        threads = [threading.Thread(target=one, args=(sid,))
                   for sid in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errs
        assert sorted(seen) == sorted(
            [sid for sid in range(n) for _ in range(5)])
        assert rx.stats["frames"] == n * 5
