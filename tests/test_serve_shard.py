"""Tensor-parallel sharded serving (PR 9).

Layers:
  * engine — the head-sharded mixed program over a 4-device "tensor"
    mesh produces greedy outputs TOKEN-IDENTICAL to the single-device
    engine on f32 (per-head bit identity + exact psums + the one
    logits all-gather), through prefix hits, chunked prefill,
    preemption, speculation+rollback and quantized (int8) pages, with
    zero recompiles after warmup and clean invariants/scales per step.
  * pool — head-sharded per-device accounting: page bytes divide
    exactly by the tensor degree, a kv_pool_mb budget is per-DEVICE
    HBM (so a sharded pool holds ~t× pages at the same per-chip
    budget), watermark/ladder fractions stay per-device-identical.
  * search — the paper's loop closed for inference:
    serve_place.optimize_serve prices the serve program per tensor
    degree on the v5e machine model (>= 1.5x simulated decode step at
    t=4 for the production-scale arch — the acceptance gate), resolves
    --serve-mesh auto, and a placement/dtype flip is a guaranteed
    cost-cache miss.
"""

import dataclasses

import numpy as np
import pytest

import jax

from flexflow_tpu.config import FFConfig
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.parallel.mesh import MachineSpec, serve_tensor_mesh
from flexflow_tpu.search.cost_model import ServeArch, serve_step_tasks
from flexflow_tpu.search.machine_model import TPUMachineModel
from flexflow_tpu.search.serve_place import (candidate_degrees,
                                             optimize_serve,
                                             price_placement)
from flexflow_tpu.search.simulator import (simulate_serve_step,
                                           simulate_serve_tasks)
from flexflow_tpu.serve import ServeEngine
from flexflow_tpu.serve.kv_cache import KVCacheConfig


# --------------------------------------------------------------- helpers
def _lm(kv_dtype="float32", *, page_size=4, pool_pages=None,
        kv_pool_mb=0.0, budget=32, max_seqs=4, max_seq_len=64,
        spec=True, **cfg_kw):
    cfg = FFConfig(
        batch_size=1, kv_page_size=page_size,
        kv_num_pages=pool_pages or (1 + 16 * max_seqs),
        kv_pool_mb=kv_pool_mb, kv_dtype=kv_dtype,
        serve_max_seqs=max_seqs, serve_prefill_budget=budget,
        serve_spec_decode=spec, **cfg_kw)
    # vocab 61 and ff_dim 72 deliberately do NOT divide by 4: the
    # sharded engine must pad them (zero ff columns, -inf vocab bias)
    # without perturbing a single token
    return build_transformer_lm(cfg, vocab_size=61,
                                max_seq_len=max_seq_len, hidden=32,
                                num_heads=4, num_layers=2, ff_dim=72)


def _prompts(rng, n, lo=4, hi=28):
    return [list(rng.randint(1, 61, size=rng.randint(lo, hi)))
            for _ in range(n)]


def _big_arch(**over):
    """The Gemma-31B-class serving arch the acceptance gate prices
    (PAPERS.md: the inference-placement decision that dominates TPU
    serving cost — too big for one v5e chip at bf16)."""
    kw = dict(num_layers=48, hidden=6144, num_heads=48, head_dim=128,
              ff_dim=24576, vocab=256128, decode_lanes=32,
              prefill_lanes=512, context=2048, kv_dtype="int8",
              kv_itemsize=1.0, kv_scales=True, act_itemsize=2.0,
              act_dtype="bfloat16", param_itemsize=2.0)
    kw.update(over)
    return ServeArch(**kw)


# --------------------------------------------------- sharded engine parity
def test_sharded_token_identity_f32():
    """The tentpole gate: tp=4 greedy outputs == single-device greedy
    outputs, token for token, on f32 pages — including a warm second
    pass (prefix-cache hits attach pages another pass committed) — with
    zero recompiles after warmup."""
    ff = _lm()
    e1 = ServeEngine(ff)
    e1.warmup()
    e4 = ServeEngine(ff, tensor_parallel=4)
    counts = e4.warmup()
    rng = np.random.RandomState(0)
    prompts = _prompts(rng, 6)
    out1 = e1.generate(prompts, 6)
    out4 = e4.generate(prompts, 6)
    assert out4 == out1
    # warm pass: prefix hits on the SHARDED pool must replay the same
    # head-sharded page content
    again = e4.generate(prompts, 6)
    assert again == out1
    assert e4.last_stats["prefix_hit_tokens"] > 0
    assert e4.compile_counts() == counts
    e4.cache.check_invariants()
    # and the reference oracle transfers unchanged
    assert out4 == e4.generate_reference(prompts, 6)


def test_sharded_chunking_preemption_speculation_identity():
    """Execution-path invariance under sharding: a tight pool (page
    pressure -> watermark blocking + preemption) with speculation on
    (rejected drafts -> rollbacks) and a small chunk budget must still
    produce the single-device engine's exact stream, invariants
    checked every step."""
    rng = np.random.RandomState(1)
    prompts = _prompts(rng, 8, lo=6, hi=30)
    base_eng = ServeEngine(_lm(spec=False), spec_tokens=0)
    base_eng.warmup()
    base = base_eng.generate(prompts, 8)
    eng = ServeEngine(_lm(pool_pages=1 + 30, budget=8), spec_tokens=3,
                      tensor_parallel=4)
    eng.warmup()

    def on_step(i):
        eng.cache.check_invariants()

    assert eng.generate(prompts, 8, on_step=on_step) == base
    assert eng.last_stats["compile_counts"]["mixed"] == 1


def test_sharded_int8_pages_bit_match_single_device():
    """Quantized pools under sharding: per-row quantization is
    per-head, so each device's int8 rows are the unsharded engine's
    bits for its heads — tp=4 int8 must equal single-device int8
    token for token, with live scale audits passing per step."""
    ff = _lm("int8")
    e1 = ServeEngine(ff)
    e1.warmup()
    e4 = ServeEngine(ff, tensor_parallel=4)
    e4.warmup()
    rng = np.random.RandomState(2)
    prompts = _prompts(rng, 6)
    out1 = e1.generate(prompts, 5)
    out4 = e4.generate(prompts, 5,
                       on_step=lambda s: e4.check_kv_scales())
    assert out4 == out1
    e4.check_kv_scales()   # post-run: prefix-parked pages
    e4.cache.check_invariants()
    # the relaxed quantized gate vs the reference transfers verbatim
    e4.assert_token_parity(prompts, out4,
                           e4.generate_reference(prompts, 5),
                           what="sharded int8 outputs")


def test_sharded_mesh_validation():
    ff = _lm()
    with pytest.raises(ValueError, match="divisible"):
        ServeEngine(ff, tensor_parallel=3)   # 4 heads % 3 != 0
    with pytest.raises(ValueError, match="tensor"):
        from flexflow_tpu.parallel.mesh import make_mesh
        ServeEngine(ff, mesh=make_mesh((2,), ("data",)))
    with pytest.raises(ValueError, match="single-device"):
        ServeEngine(_lm(), tensor_parallel=2, chunked_prefill=False)
    # an explicit 1-D tensor mesh is accepted
    eng = ServeEngine(ff, mesh=serve_tensor_mesh(2))
    assert eng.tp == 2


def test_serve_mesh_config_and_cli():
    ff = _lm(serve_mesh="2")
    eng = ServeEngine(ff)
    assert eng.tp == 2 and eng.tp_mesh is not None
    rng = np.random.RandomState(3)
    prompts = _prompts(rng, 3)
    eng.warmup()
    ref = ServeEngine(_lm())
    ref.warmup()
    assert eng.generate(prompts, 4) == ref.generate(prompts, 4)
    # CLI flag and validation
    cfg = FFConfig(argv=["--serve-mesh", "auto"])
    assert cfg.serve_mesh == "auto"
    with pytest.raises(ValueError, match="serve_mesh"):
        FFConfig(serve_mesh="three")
    with pytest.raises(ValueError, match="serve_mesh"):
        FFConfig(serve_mesh="0")


def test_serve_mesh_auto_resolves_through_search():
    """--serve-mesh auto closes the loop: the engine asks
    optimize_serve which degree minimizes the simulated decode step.
    For this test-sized LM the collectives dominate any compute win,
    so the search must keep it single-device — the same pricing that
    shards the 31B-class arch (test_optimize_serve_speedup_gate)."""
    eng = ServeEngine(_lm(serve_mesh="auto"))
    assert eng.serve_placement is not None
    assert eng.tp == eng.serve_placement.tensor_parallel
    assert eng.tp == 1   # tiny model: sharding cannot pay
    assert 1 in eng.serve_placement.decode_by_degree


# ----------------------------------------------------- per-device pool math
def test_head_sharded_pool_accounting():
    c = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                      page_size=4, num_pages=33, max_seqs=2,
                      max_seq_len=32, tensor_parallel=4)
    assert c.heads_per_device == 1
    assert c.page_device_bytes * 4 == c.page_bytes
    assert c.pool_device_bytes * 4 == c.pool_bytes
    c.validate()
    with pytest.raises(ValueError, match="divisible"):
        KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                      page_size=4, num_pages=33, max_seqs=2,
                      max_seq_len=32, tensor_parallel=3).validate()
    # quantized pages shard their scale rows on the same head axis:
    # device bytes still divide exactly
    q = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                      page_size=4, num_pages=33, max_seqs=2,
                      max_seq_len=32, kv_dtype="int8",
                      tensor_parallel=2)
    assert q.page_device_bytes * 2 == q.page_bytes


def test_kv_pool_mb_is_per_device_budget():
    """The watermark satellite: kv_pool_mb is per-DEVICE HBM, so the
    same budget holds ~t× the pages under head sharding — and every
    page-count-fraction threshold (admission watermark, ladder rungs)
    fires at the same relative per-device pressure."""
    def cfg_for(tp):
        c = FFConfig(kv_page_size=8, kv_pool_mb=0.5)
        return KVCacheConfig.from_ff(c, num_layers=2, num_heads=4,
                                     head_dim=8, max_seq_len=128,
                                     tensor_parallel=tp)
    c1, c4 = cfg_for(1), cfg_for(4)
    assert c4.usable_pages >= 4 * c1.usable_pages - 4
    # per-device bytes never exceed the budget
    assert c4.pool_device_bytes <= 0.5 * (1 << 20) + c4.page_device_bytes
    from flexflow_tpu.serve.kv_cache import PagedKVCache
    from flexflow_tpu.serve.scheduler import ContinuousBatchingScheduler
    s1 = ContinuousBatchingScheduler(PagedKVCache(c1),
                                     admit_watermark=0.1)
    s4 = ContinuousBatchingScheduler(PagedKVCache(c4),
                                     admit_watermark=0.1)
    # watermark pages scale WITH the pool: same relative pressure
    assert s4.watermark_pages >= 4 * s1.watermark_pages - 4


def test_sharding_stats_and_report():
    from flexflow_tpu.utils.profiling import serve_report
    eng = ServeEngine(_lm(), tensor_parallel=2)
    eng.warmup()
    rng = np.random.RandomState(4)
    eng.generate(_prompts(rng, 3), 3)
    sh = eng.last_stats["sharding"]
    for key in ("mesh", "tensor_parallel", "heads_per_device",
                "kv_pool_device_bytes", "collective_bytes_per_step"):
        assert key in sh, key
    assert sh["tensor_parallel"] == 2 and sh["heads_per_device"] == 2
    assert sh["kv_pool_device_bytes"] * 2 == eng.cache_cfg.pool_bytes
    assert "sharding: mesh" in serve_report(eng.last_stats)
    # single-device engines carry no sharding block
    e1 = ServeEngine(_lm())
    e1.warmup()
    e1.generate(_prompts(rng, 2), 2)
    assert e1.last_stats["sharding"] is None


# ------------------------------------------------- placement search / cost
def test_serve_step_tasks_structure():
    arch = _big_arch()
    mm = TPUMachineModel(spec=MachineSpec.v5e(8))
    t1 = serve_step_tasks(arch, 1, mm, lanes=arch.decode_lanes)
    t4 = serve_step_tasks(arch, 4, mm, lanes=arch.decode_lanes)
    assert not any(t.kind == "collective" for t in t1)
    # t>1: 2 all-reduces per layer + the embed psum + ONE all-gather
    colls = [t for t in t4 if t.kind == "collective"]
    assert len(colls) == 2 * arch.num_layers + 2
    assert sum(t.name == "logits_gather" for t in colls) == 1
    # the serve chain's critical path == its sum (strictly sequential)
    assert simulate_serve_tasks(t4) == pytest.approx(
        sum(t.seconds for t in t4))
    # compute time strictly shrinks with the degree
    c1 = sum(t.seconds for t in t1 if t.kind == "compute")
    c4 = sum(t.seconds for t in t4 if t.kind == "compute")
    assert c4 < c1 / 2


def test_optimize_serve_speedup_gate():
    """The acceptance criterion: on the v5e machine model the
    placement search's simulated decode step at t=4 is >= 1.5x better
    than t=1 for the production-scale arch, and the returned placement
    is at least as good as every degree it priced."""
    mm = TPUMachineModel(spec=MachineSpec.v5e(8))
    place = optimize_serve(_big_arch(), 8, mm=mm)
    table = place.decode_by_degree
    assert set(candidate_degrees(_big_arch(), 8)) <= set(table)
    assert table[1] / table[4] >= 1.5
    assert place.tensor_parallel > 1
    assert place.decode_step_s <= min(table.values()) + 1e-12
    assert place.speedup_vs_single() >= table[1] / table[4]


def test_optimize_serve_axis_assignment():
    """With physical torus dims on the spec, the search may lay the
    serve axis over multiple link sets — and must never return an
    assignment worse than the flat ring it also priced."""
    spec = dataclasses.replace(MachineSpec.v5e(16),
                               ici_torus_dims=(4, 4))
    mm = TPUMachineModel(spec=spec)
    arch = _big_arch(num_heads=64)
    place = optimize_serve(arch, 16, mm=mm)
    flat = simulate_serve_step(arch, place.tensor_parallel, mm)
    assert place.decode_step_s <= flat + 1e-12
    if place.tensor_parallel == 16:
        assert place.axis_dims in ((4, 4), ())


def test_serve_placement_cost_cache_miss_on_flip(tmp_path):
    """Guaranteed-miss acceptance: a placement flip changes the entry
    key, a KV/activation dtype flip changes the serve fingerprint —
    cached serve costs can never cross either boundary."""
    from flexflow_tpu.search.cost_cache import CostCache
    from flexflow_tpu.search.serve_place import _serve_fingerprint
    mm = TPUMachineModel(spec=MachineSpec.v5e(8))
    arch = _big_arch()
    # a private store: other tests in this process share the default
    # path and would have pre-warmed these very entries
    cache = CostCache.open(str(tmp_path / "serve_costcache.json"))
    fp = _serve_fingerprint(mm, arch)
    h0, m0 = cache.hits, cache.misses
    d1, p1 = price_placement(arch, 4, mm, cache=cache, fingerprint=fp)
    assert cache.misses == m0 + 1
    d2, p2 = price_placement(arch, 4, mm, cache=cache, fingerprint=fp)
    assert (d2, p2) == (d1, p1) and cache.hits == h0 + 1
    # placement flip: entry-key miss
    price_placement(arch, 8, mm, cache=cache, fingerprint=fp)
    assert cache.misses == m0 + 2
    # dtype flip: fingerprint miss (and a distinct fingerprint)
    arch_f32 = dataclasses.replace(arch, kv_dtype="float32",
                                   kv_itemsize=4.0, kv_scales=False)
    fp2 = _serve_fingerprint(mm, arch_f32)
    assert fp2 != fp
    price_placement(arch_f32, 4, mm, cache=cache, fingerprint=fp2)
    assert cache.misses == m0 + 3


def test_memory_penalty_prices_hbm_fit():
    """What makes a too-big model shard itself: at t=1 the 31B-class
    bf16 weights exceed one v5e chip's HBM, so the simulated step
    carries the reference's 1ms/MB penalty; at t=8 it fits clean."""
    from flexflow_tpu.search.cost_model import serve_device_bytes
    arch = _big_arch()
    spec = MachineSpec.v5e(8)
    assert serve_device_bytes(arch, 1) > spec.hbm_capacity
    assert serve_device_bytes(arch, 8) < spec.hbm_capacity
    mm = TPUMachineModel(spec=spec)
    assert simulate_serve_step(arch, 1, mm) > 100 * \
        simulate_serve_step(arch, 8, mm)
