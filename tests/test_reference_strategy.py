"""Importing REFERENCE strategy artifacts (VERDICT r3 #10).

The reference persists strategies as FFProtoBuf.Strategy protobufs
(examples/cpp/DLRM/strategies/*.pb; schema embedded in
dlrm_strategy.py) and as strategy.cc:95-189's plain-text token stream.
Both now load onto `OpStrategy` — the shipped DLRM artifacts replay
directly, with per-table pins executing via the slot layout.
"""

import os

import jax
import numpy as np
import pytest

from flexflow_tpu import FFConfig, SGDOptimizer, make_mesh
from flexflow_tpu.models import build_dlrm
from flexflow_tpu.parallel.strategy_io import (
    load_reference_strategy_file,
    parse_reference_pb,
    parse_reference_text,
)

REF_PB = ("/root/reference/examples/cpp/DLRM/strategies/"
          "dlrm_strategy_8embs_8gpus.pb")

needs_ref = pytest.mark.skipif(not os.path.exists(REF_PB),
                               reason="reference artifacts unavailable")


def build(bs=64):
    return build_dlrm(FFConfig(batch_size=bs),
                      embedding_vocab_sizes=(1000,) * 8,
                      embedding_dim=16, bot_mlp=(64, 16),
                      top_mlp=(64, 2), stacked_tables=True)


@needs_ref
def test_parse_shipped_dlrm_pb():
    entries = parse_reference_pb(REF_PB)
    names = [e[0] for e in entries]
    assert names[:8] == [f"embedding{i}" for i in range(8)]
    assert set(names[8:]) == {"linear", "mse_loss", "concat"}
    # per-table round-robin pins; shared family entries 8-way DP
    for i in range(8):
        assert entries[i][2] == [1, 1] and entries[i][3] == [i]
    lin = next(e for e in entries if e[0] == "linear")
    assert lin[2] == [1, 8] and lin[3] == list(range(8))


@needs_ref
def test_shipped_dlrm_pb_replays_and_trains():
    ff = build()
    mesh = make_mesh((8,), ("data",))
    strat = load_reference_strategy_file(ff, mesh, REF_PB)
    # per-GPU table pins collapse onto the stacked op's __devices__
    assert strat.for_op("emb_tables").device_ids == tuple(range(8))
    # the shared "linear" entry lands on every dense op as 8-way DP
    assert strat.for_op("bot_mlp_0").axis_map == {"sample": "data"}
    assert strat.for_op("top_out").axis_map == {"sample": "data"}
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[],
               mesh=mesh, strategy=strat)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    assert op.placement == tuple(range(8))  # pins EXECUTE (slot layout)
    rng = np.random.RandomState(0)
    b = {"dense_features": rng.randn(64, 13).astype(np.float32),
         "label": rng.randint(0, 2, 64).astype(np.int32)}
    for i in range(8):
        b[f"sparse_{i}"] = rng.randint(0, 1000, (64, 1)).astype(np.int32)
    assert np.isfinite(float(ff.train_batch(b)["loss"]))


def test_text_format_token_stream(tmp_path):
    """strategy.cc's writer format: newline/tab layout must not matter
    (the reference loader reads with operator>>)."""
    p = tmp_path / "ref.txt"
    p.write_text("2\n"
                 "embedding0\n0\n2\n1\t1\t\n1\n3\t\n"
                 "linear 0 2 1 4 4 0 1 2 3\n")
    entries = parse_reference_text(str(p))
    assert entries == [("embedding0", 0, [1, 1], [3]),
                       ("linear", 0, [1, 4], [0, 1, 2, 3])]


def test_text_format_loads_onto_model(tmp_path):
    ff = build()
    mesh = make_mesh((4, 2), ("data", "model"))
    lines = ["9"]
    for i in range(8):
        lines.append(f"embedding{i} 0 2 1 1 1 {i % 4}")
    lines.append("linear 0 2 1 4 4 0 1 2 3")
    p = tmp_path / "ref.txt"
    p.write_text("\n".join(lines) + "\n")
    strat = load_reference_strategy_file(ff, mesh, str(p))
    assert strat.for_op("emb_tables").device_ids == \
        (0, 1, 2, 3, 0, 1, 2, 3)
    # dims reversed to NumPy order: sample split 4 -> data axis
    assert strat.for_op("bot_mlp_0").axis_map == {"sample": "data"}


def test_exact_entry_wins_over_family(tmp_path):
    """Reference hash lookup gives each op ONE entry; a family entry
    must not clobber an earlier (or later) exact-name entry."""
    ff = build()
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "ref.txt"
    p.write_text("2\n"
                 "linear 0 2 1 4 4 0 1 2 3\n"
                 "bot_mlp_0 0 2 2 1 2 0 1\n")
    strat = load_reference_strategy_file(ff, mesh, str(p))
    # exact entry: channel split 2 -> model axis (Legion order reversed)
    assert strat.for_op("bot_mlp_0").axis_map == {"channel_out": "model"}
    assert strat.for_op("top_out").axis_map == {"sample": "data"}


def test_indexed_embedding_binding_no_suffix_alias(tmp_path):
    """embedding1 must NOT bind to emb_11 (endswith aliasing)."""
    from flexflow_tpu import FFModel
    ff = FFModel(FFConfig(batch_size=8))
    import jax.numpy as jnp
    ins = [ff.create_tensor((8, 1), dtype=jnp.int32, name=f"s{i}")
           for i in range(12)]
    embs = [ff.embedding(s, 50, 4, aggr="sum", name=f"emb_{i}")
            for i, s in enumerate(ins)]
    t = ff.concat(embs, axis=1)
    ff.softmax(ff.dense(t, 4, name="head"))
    mesh = make_mesh((4,), ("data",))
    p = None
    import tempfile, os as _os
    with tempfile.NamedTemporaryFile("w", suffix=".txt", dir=tmp_path,
                                     delete=False) as f:
        f.write("1\nembedding1 0 2 1 1 1 3\n")
        p = f.name
    strat = load_reference_strategy_file(ff, mesh, p)
    assert strat.for_op("emb_1").device_ids == (3,)
    assert strat.for_op("emb_11").device_ids is None


def test_exact_distributed_embedding_entry(tmp_path):
    """An exact entry naming the stacked op must apply even though no
    embedding<N> collapse ran."""
    ff = build()
    mesh = make_mesh((8,), ("data",))
    p = tmp_path / "ref.txt"
    p.write_text("1\nemb_tables 0 2 1 1 8 3 1 2 0 7 5 6 4\n")
    strat = load_reference_strategy_file(ff, mesh, str(p))
    assert strat.for_op("emb_tables").device_ids == \
        (3, 1, 2, 0, 7, 5, 6, 4)


def test_non_strategy_pb_fails_loud(tmp_path):
    p = tmp_path / "bogus.pb"
    p.write_bytes(bytes([0x08, 0x07]))  # field 1 as varint (ONNX-style)
    with pytest.raises(ValueError, match="wire type"):
        parse_reference_pb(str(p))


@needs_ref
def test_import_strategy_flag_dispatches_pb():
    cfg = FFConfig(batch_size=64)
    cfg.import_strategy_file = REF_PB
    ff = build_dlrm(cfg, embedding_vocab_sizes=(1000,) * 8,
                    embedding_dim=16, bot_mlp=(64, 16),
                    top_mlp=(64, 2), stacked_tables=True)
    mesh = make_mesh((8,), ("data",))
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[],
               mesh=mesh)
    op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
    assert op.placement == tuple(range(8))
