"""Op golden tests vs PyTorch (CPU).

Reference: tests/ops/ — standalone binaries dump op outputs and
tests/ops/test_harness.py builds the same computation in numpy/torch and
asserts allclose (epsilon 1e-5, test_harness.py:1-60). Here the ops are
called directly and compared against torch.nn equivalents, including a
gradient check for the trainable ops.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.op import OpContext


def _ctx():
    return OpContext(training=False, rng=None, seq_length=-1,
                     state_in={}, mesh=None, op_strategy=None)


def _model_with(build):
    ff = FFModel(FFConfig())
    return build(ff)


def test_linear_matches_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((4, 16), name="input")
    ff.dense(x, 8, name="fc")
    op = ff.ops[0]
    xs = rng.randn(4, 16).astype(np.float32)
    w = rng.randn(16, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    (y,) = op.forward({"kernel": jnp.asarray(w), "bias": jnp.asarray(b)},
                      [jnp.asarray(xs)], _ctx())
    ref = F.linear(torch.from_numpy(xs), torch.from_numpy(w.T),
                   torch.from_numpy(b))
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_conv2d_matches_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((2, 3, 16, 16), name="input")
    ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="conv")
    op = ff.ops[0]
    xs = rng.randn(2, 3, 16, 16).astype(np.float32)
    w = rng.randn(8, 3, 3, 3).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    (y,) = op.forward({"kernel": jnp.asarray(w), "bias": jnp.asarray(b)},
                      [jnp.asarray(xs)], _ctx())
    ref = F.conv2d(torch.from_numpy(xs), torch.from_numpy(w),
                   torch.from_numpy(b), stride=1, padding=1)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_pool2d_matches_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((2, 4, 8, 8), name="input")
    ff.pool2d(x, 2, 2, 2, 2, 0, 0, name="pool")
    op = ff.ops[0]
    xs = rng.randn(2, 4, 8, 8).astype(np.float32)
    (y,) = op.forward({}, [jnp.asarray(xs)], _ctx())
    ref = F.max_pool2d(torch.from_numpy(xs), 2, 2)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-6, atol=1e-6)


def test_batch_norm_eval_matches_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((4, 6, 5, 5), name="input")
    ff.batch_norm(x, relu=False, name="bn")
    op = ff.ops[0]
    xs = rng.randn(4, 6, 5, 5).astype(np.float32)
    scale = rng.rand(6).astype(np.float32) + 0.5
    bias = rng.randn(6).astype(np.float32)
    mean = rng.randn(6).astype(np.float32)
    var = rng.rand(6).astype(np.float32) + 0.5
    ctx = _ctx()
    ctx.state_in = {"running_mean": jnp.asarray(mean),
                    "running_var": jnp.asarray(var)}
    (y,) = op.forward({"scale": jnp.asarray(scale),
                       "bias": jnp.asarray(bias)}, [jnp.asarray(xs)], ctx)
    ref = F.batch_norm(torch.from_numpy(xs), torch.from_numpy(mean),
                       torch.from_numpy(var), torch.from_numpy(scale),
                       torch.from_numpy(bias), training=False,
                       eps=op.EPS)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_lstm_matches_torch(rng):
    b, t, d, h = 2, 5, 8, 12
    ff = FFModel(FFConfig())
    x = ff.create_tensor((b, t, d), name="input")
    ff.lstm(x, h, return_sequences=True, name="lstm")
    op = ff.ops[0]
    xs = rng.randn(b, t, d).astype(np.float32)
    # torch packs gates as [i, f, g, o] rows of weight_ih (4h, d)
    w_ih = rng.randn(4 * h, d).astype(np.float32) * 0.2
    w_hh = rng.randn(4 * h, h).astype(np.float32) * 0.2
    bias = rng.randn(4 * h).astype(np.float32) * 0.1
    (y,) = op.forward({"wx": jnp.asarray(w_ih.T), "wh": jnp.asarray(w_hh.T),
                       "b": jnp.asarray(bias)}, [jnp.asarray(xs)], _ctx())
    lstm = torch.nn.LSTM(d, h, batch_first=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.from_numpy(w_ih))
        lstm.weight_hh_l0.copy_(torch.from_numpy(w_hh))
        lstm.bias_ih_l0.copy_(torch.from_numpy(bias))
        lstm.bias_hh_l0.zero_()
        ref, _ = lstm(torch.from_numpy(xs))
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_attention_matches_torch(rng):
    b, s, e, h = 2, 6, 16, 4
    ff = FFModel(FFConfig())
    x = ff.create_tensor((b, s, e), name="input")
    ff.multihead_attention(x, x, x, e, h, bias=False, use_flash=False,
                           name="attn")
    op = ff.ops[0]
    xs = rng.randn(b, s, e).astype(np.float32)
    wq = rng.randn(e, e).astype(np.float32) * 0.3
    wk = rng.randn(e, e).astype(np.float32) * 0.3
    wv = rng.randn(e, e).astype(np.float32) * 0.3
    wo = rng.randn(e, e).astype(np.float32) * 0.3
    d = e // h
    params = {
        "wq": jnp.asarray(wq.reshape(e, h, d)),
        "wk": jnp.asarray(wk.reshape(e, h, d)),
        "wv": jnp.asarray(wv.reshape(e, h, d)),
        "wo": jnp.asarray(wo.reshape(h, d, e)),
    }
    (y,) = op.forward(params, [jnp.asarray(xs)] * 3, _ctx())

    mha = torch.nn.MultiheadAttention(e, h, bias=False, batch_first=True)
    with torch.no_grad():
        # torch packs q/k/v projections as (3e, e) applied as x @ W^T
        mha.in_proj_weight.copy_(torch.from_numpy(
            np.concatenate([wq.T, wk.T, wv.T], axis=0)))
        # torch out_proj computes heads_concat @ wo^T; our wo is
        # (h, d, e) applied as o . wo over (h, d)
        mha.out_proj.weight.copy_(torch.from_numpy(
            wo.reshape(e, e).T))
        ref, _ = mha(torch.from_numpy(xs), torch.from_numpy(xs),
                     torch.from_numpy(xs), need_weights=False)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_linear_grads_match_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((4, 16), name="input")
    ff.dense(x, 8, name="fc")
    op = ff.ops[0]
    xs = rng.randn(4, 16).astype(np.float32)
    w = rng.randn(16, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)

    def loss(params, x):
        (y,) = op.forward(params, [x], _ctx())
        return jnp.sum(jnp.tanh(y))

    grads = jax.grad(loss)({"kernel": jnp.asarray(w), "bias": jnp.asarray(b)},
                           jnp.asarray(xs))

    tw = torch.from_numpy(w).requires_grad_()
    tb = torch.from_numpy(b).requires_grad_()
    torch.sum(torch.tanh(torch.from_numpy(xs) @ tw + tb)).backward()
    np.testing.assert_allclose(np.asarray(grads["kernel"]), tw.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(grads["bias"]), tb.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_layer_norm_matches_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((4, 6, 16), name="input")
    ff.layer_norm(x, name="ln")
    op = ff.ops[0]
    xs = rng.randn(4, 6, 16).astype(np.float32)
    scale = rng.randn(16).astype(np.float32)
    bias = rng.randn(16).astype(np.float32)
    (y,) = op.forward({"scale": jnp.asarray(scale),
                       "bias": jnp.asarray(bias)},
                      [jnp.asarray(xs)], _ctx())
    ref = F.layer_norm(torch.from_numpy(xs), (16,),
                       torch.from_numpy(scale), torch.from_numpy(bias))
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_layer_norm_grads_match_torch(rng):
    ff = FFModel(FFConfig())
    x = ff.create_tensor((4, 16), name="input")
    ff.layer_norm(x, name="ln")
    op = ff.ops[0]
    xs = rng.randn(4, 16).astype(np.float32)
    scale = rng.randn(16).astype(np.float32)
    bias = rng.randn(16).astype(np.float32)

    def loss_fn(params, xv):
        (y,) = op.forward(params, [xv], _ctx())
        return jnp.sum(y * y)

    params = {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)}
    gp, gx = jax.grad(loss_fn, argnums=(0, 1))(params, jnp.asarray(xs))

    xt = torch.from_numpy(xs).requires_grad_(True)
    st = torch.from_numpy(scale).requires_grad_(True)
    bt = torch.from_numpy(bias).requires_grad_(True)
    out = F.layer_norm(xt, (16,), st, bt)
    (out * out).sum().backward()
    np.testing.assert_allclose(np.asarray(gx), xt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp["scale"]), st.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp["bias"]), bt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_torchfx_embedding_mean_model():
    """fx import of an embedding + .mean(dim) classifier, golden vs the
    torch forward (the nn.Embedding path the ONNX importer also covers
    via Gather/ReduceMean) — incl. the .ff text round trip."""
    import torch
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.fc = nn.Linear(16, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(dim=1))

    torch.manual_seed(0)
    m = M()
    m.eval()

    def run(ptm):
        cfg = FFConfig()
        cfg.batch_size = 4
        ff = FFModel(cfg)
        ids_t = ff.create_tensor((4, 7), dtype=np.int32, name="input")
        (out,) = ptm.apply(ff, [ids_t])
        assert tuple(out.shape) == (4, 4)
        ff.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        ptm.module = m  # .ff files carry no weights (reference same)
        ptm.import_weights(ff)
        ids = np.random.RandomState(0).randint(0, 50, (4, 7))
        with torch.no_grad():
            want = m(torch.from_numpy(ids)).numpy()
        got = np.asarray(ff.forward({"input": ids.astype(np.int32)}))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    run(PyTorchModel(m))
    # .ff text round trip (reference torch/model.py replay path)
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".ff", mode="w") as f:
        export_ff(m, f.name)
        run(PyTorchModel(f.name))
