"""Hierarchical prefix-cache tier (serve/host_tier.py + the spill /
reload wiring; docs/serving.md "Hierarchical prefix cache").

Layered like the subsystem:
  * store — HostPageStore is a byte-budgeted, geometry-pinned LRU:
    budget eviction from the cold end, re-put refresh, chain matching
    stops at the first gap, and the router's probe_chain is PURE
    (no LRU touch, no stat count).
  * engine — spill -> evict -> reload churn on f32/int8/fp8 pools is
    token-identical to an ample-pool reference with
    check_invariants (and scale audits) at every step, spills and
    priced reloads actually happen, and the decision lands on the
    request for explain_request.
  * config — the --host-tier-mb / --no-host-tier flags arm and
    disarm the tier; an explicit shared store wins over the config.
  * router — a ReplicaPool shares ONE store across replicas, a host
    hit routes below an HBM prefix hit (least-loaded target), and
    route() never perturbs the store.
  * telemetry — host_reload is an attribution component; the span
    class tables stay consistent and the breakdown still sums.
"""

import dataclasses

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.serve import ReplicaPool, ServeEngine
from flexflow_tpu.serve.host_tier import HostPageStore
from flexflow_tpu.serve.kv_cache import prefix_page_keys
from flexflow_tpu.utils.telemetry import (REQUEST_COMPONENTS,
                                          Telemetry,
                                          _CLASS_PRIORITY,
                                          _SPAN_CLASS)


# --------------------------------------------------------------- helpers
def _rows(seed=0, scale=False, shape=(2, 4, 4, 8)):
    """One page's export rows: (k, v) f32, plus f32 scale rows when
    `scale` (the quantized-pool layout)."""
    rng = np.random.RandomState(seed)
    out = [rng.randn(*shape).astype(np.float32) for _ in range(2)]
    if scale:
        out += [rng.randn(*shape[:-1]).astype(np.float32)
                for _ in range(2)]
    return tuple(out)


def _lm(kv_dtype="float32", *, page_size=4, pool_pages=20, budget=8,
        max_seqs=2, max_seq_len=64, spec=True, **cfg_kw):
    cfg = FFConfig(batch_size=1, kv_page_size=page_size,
                   kv_num_pages=1 + pool_pages, kv_dtype=kv_dtype,
                   serve_max_seqs=max_seqs,
                   serve_prefill_budget=budget,
                   serve_spec_decode=spec, **cfg_kw)
    return build_transformer_lm(cfg, vocab_size=61,
                                max_seq_len=max_seq_len, hidden=32,
                                num_heads=4, num_layers=2, ff_dim=64)


def _prompts(rng, n, lo=30, hi=40):
    return [list(rng.randint(1, 61, size=rng.randint(lo, hi)))
            for _ in range(n)]


# =======================================================================
# store
# =======================================================================
def test_store_budget_lru_eviction():
    rows = _rows()
    page_b = sum(r.nbytes for r in rows)
    store = HostPageStore(3 * page_b / (1 << 20))
    keys = [bytes([i]) * 8 for i in range(5)]
    for i, k in enumerate(keys):
        assert store.put(k, _rows(i))
    # budget holds 3 pages: the two oldest fell off the cold end
    assert len(store) == 3 and store.bytes_used == 3 * page_b
    assert store.stats["evictions"] == 2
    assert [store.contains(k) for k in keys] == \
        [False, False, True, True, True]
    rep = store.report()
    assert rep["pages"] == 3 and rep["spills"] == 5
    assert rep["occupancy"] == pytest.approx(
        store.bytes_used / store.budget_bytes)
    dbg = store.debug_state(max_keys=2)
    assert dbg["lru_keys"] == [keys[2].hex()[:16], keys[3].hex()[:16]]
    assert dbg["lru_truncated"] == 1


def test_store_rejects_geometry_drift_and_oversize():
    store = HostPageStore(1.0)
    assert store.put(b"a" * 8, _rows())
    # the first put pinned (shape, dtype); anything else is refused
    assert not store.put(b"b" * 8, _rows(shape=(2, 8, 4, 8)))
    assert not store.put(b"c" * 8, tuple(
        r.astype(np.float16) for r in _rows()))
    assert store.stats["rejects"] == 2 and len(store) == 1
    # a single page larger than the whole budget can never be held
    big = HostPageStore(1e-5)
    assert not big.put(b"d" * 8, _rows())
    assert big.stats["rejects"] == 1 and len(big) == 0


def test_store_match_chain_stops_at_gap_probe_is_pure():
    store = HostPageStore(1.0)
    keys = [bytes([i]) * 8 for i in range(4)]
    for i, k in enumerate(keys):
        store.put(k, _rows(i))
    store.discard([keys[2]])
    before = dict(store.stats)
    # probe: longest leading run, NO stat movement, NO LRU touch
    assert store.probe_chain(keys) == 2
    assert store.probe_chain([b"x" * 8] + keys) == 0
    assert dict(store.stats) == before
    lru_before = store.debug_state()["lru_keys"]
    store.probe_chain(keys)
    assert store.debug_state()["lru_keys"] == lru_before
    # match: same run, but counts hits/misses and refreshes recency
    assert store.match_chain(keys) == 2
    assert store.stats["hits"] == before["hits"] + 2
    assert store.stats["misses"] == before["misses"] + 1
    assert store.debug_state()["lru_keys"][-1] == keys[1].hex()[:16]


def test_store_reput_refreshes_and_discard_is_not_eviction():
    rows = _rows()
    page_b = sum(r.nbytes for r in rows)
    store = HostPageStore(2 * page_b / (1 << 20))
    store.put(b"a" * 8, _rows(0))
    store.put(b"b" * 8, _rows(1))
    # re-putting the old key moves it to MRU without double-counting
    store.put(b"a" * 8, _rows(2))
    assert store.bytes_used == 2 * page_b
    store.put(b"c" * 8, _rows(3))   # evicts "b", the true LRU
    assert store.contains(b"a" * 8) and not store.contains(b"b" * 8)
    assert store.discard([b"a" * 8, b"zz"]) == 1
    assert store.stats["evictions"] == 1   # the "b" budget eviction
    got = store.get(b"c" * 8)
    assert all(np.array_equal(a, b) for a, b in zip(got, _rows(3)))
    assert store.get(b"a" * 8) is None


def test_store_budget_must_be_positive():
    with pytest.raises(ValueError):
        HostPageStore(0.0)
    with pytest.raises(ValueError):
        HostPageStore(-1.0)


# =======================================================================
# engine: spill -> evict -> reload churn
# =======================================================================
@pytest.mark.parametrize("kv_dtype",
                         ["float32", "int8", "float8_e4m3"])
def test_spill_reload_token_identity_under_churn(kv_dtype):
    """The acceptance property: alternating working sets over a pool
    too small to hold both force parked chains through the full
    spill -> host-evict -> reload cycle, interleaved with preemption
    (tight pool) and speculation rollback — and every emitted token
    stays identical to an ample-pool engine that never spills, with
    pool invariants (and, on quantized pools, the scale-row audit)
    holding after every step."""
    rng = np.random.RandomState(3)
    a, b = _prompts(rng, 2), _prompts(rng, 2)

    ref = ServeEngine(_lm(kv_dtype, pool_pages=64, max_seqs=2,
                          spec=False, serve_host_tier=False),
                      spec_tokens=0)
    ref.warmup()

    eng = ServeEngine(_lm(kv_dtype, pool_pages=20, max_seqs=2,
                          host_tier_mb=4.0), spec_tokens=3)
    counts = eng.warmup()
    assert eng.host_tier is not None
    # pin the recompute price above the DMA so every host match
    # reloads: this property is about the MACHINERY (spill -> evict
    # -> reload never changes a token), not the pricing threshold —
    # the toy model's real per-step price sits near the PCIe latency
    # floor and would flip decisions on margins, not correctness
    eng._host_step_price = lambda ctx: 1e-3

    def audit(_):
        eng.cache.check_invariants()
        if kv_dtype != "float32":
            eng.check_kv_scales()

    for round_i, prompts in enumerate((a, b, a, b, a)):
        expect = ref.generate(prompts, 6)
        assert eng.generate(prompts, 6, on_step=audit) == expect, \
            f"round {round_i} diverged on {kv_dtype}"
        eng.cache.check_invariants()

    host = eng.last_stats["host_tier"]
    assert host["spills"] > 0, "pool never spilled a parked chain"
    assert host["reload_pages"] > 0, "no repeat ever reloaded"
    assert eng.compile_counts() == counts, \
        "spill/reload must reuse the warmed export/import programs"
    # quantized pools ship their f32 scale rows with the page
    n_rows = {"float32": 2}.get(kv_dtype, 4)
    rows = next(iter(eng.host_tier._pages.values()))
    assert len(rows) == n_rows
    if n_rows == 4:
        assert rows[2].dtype == np.float32 \
            and rows[3].dtype == np.float32


def test_priced_decision_recorded_and_counted():
    """Every host-tier consult leaves the priced decision on the
    request (the explain_request surface), both sides non-negative
    and consistent with the choice — and on this toy model the REAL
    price correctly refuses the DMA (a ~5us PCIe latency floor beats
    five sub-microsecond prefill steps), while a pinned expensive
    recompute flips the same match to a reload that shows up in the
    engine's stats block."""
    rng = np.random.RandomState(5)
    a, b = _prompts(rng, 2), _prompts(rng, 2)
    eng = ServeEngine(_lm(pool_pages=20, host_tier_mb=4.0),
                      spec_tokens=0)
    eng.warmup()
    for prompts in (a, b, a):
        eng.generate(prompts, 6)
    decisions = [getattr(r, "host_reload", None)
                 for r in eng._last_reqs.values()]
    decisions = [d for d in decisions if d]
    assert decisions, "the repeat round never consulted the tier"
    for d in decisions:
        assert d["dma_s"] >= 0.0 and d["recompute_s"] >= 0.0
        assert d["chose"] in ("none", "reload", "recompute",
                              "store_miss")
        if d["chose"] == "recompute":
            assert d["dma_s"] >= d["recompute_s"], d
    # the honest direction on the tiny model: recompute wins
    assert any(d["chose"] == "recompute" for d in decisions)
    assert eng._host_reload_stats["reload_events"] == 0

    # same store content, recompute priced expensive: reload wins
    eng._host_step_price = lambda ctx: 1e-3
    eng.generate(b, 6)
    eng.generate(a, 6)
    decisions = [d for d in (getattr(r, "host_reload", None)
                             for r in eng._last_reqs.values()) if d]
    assert any(d["chose"] == "reload" for d in decisions)
    for d in decisions:
        if d["chose"] == "reload":
            assert d["dma_s"] < d["recompute_s"], d
    st = eng._host_reload_stats
    assert st["reload_events"] > 0
    # the engine counters are lifetime; decisions are last-run only
    assert st["reload_pages"] >= \
        sum(d["reloaded_pages"] for d in decisions) > 0
    assert st["reload_priced_s"] > 0.0
    # the stats block merges store report + engine reload counters
    host = eng.last_stats["host_tier"]
    assert host["reload_pages"] == st["reload_pages"]
    assert host["spilled_pages"] == st["spilled_pages"]
    # and the post-mortem debug view carries LRU-ordered keys
    dbg = eng.cache.debug_state()
    assert dbg["host_tier"]["pages"] == host["pages"]
    assert dbg["host_tier"]["lru_keys"]


# =======================================================================
# config / arming
# =======================================================================
def test_flags_and_arming_matrix():
    cfg = FFConfig()
    assert cfg.host_tier_mb == 0.0 and cfg.serve_host_tier
    cfg.parse_args(["--host-tier-mb", "64", "--no-host-tier"])
    assert cfg.host_tier_mb == 64.0 and not cfg.serve_host_tier
    with pytest.raises(ValueError):
        FFConfig(host_tier_mb=-1.0).validate()

    # mb=0 (the default) leaves the tier off
    eng0 = ServeEngine(_lm(pool_pages=16, spec=False), spec_tokens=0)
    assert eng0.host_tier is None
    # --no-host-tier disarms even with a budget
    eng1 = ServeEngine(_lm(pool_pages=16, spec=False,
                           host_tier_mb=8.0, serve_host_tier=False),
                       spec_tokens=0)
    assert eng1.host_tier is None
    # an explicit (shared) store wins over the config budget
    shared = HostPageStore(1.0)
    eng2 = ServeEngine(_lm(pool_pages=16, spec=False,
                           host_tier_mb=8.0),
                       spec_tokens=0, host_tier=shared)
    assert eng2.host_tier is shared
    assert eng2.cache.host_tier is shared


# =======================================================================
# router: one shared store, host-hit affinity tier
# =======================================================================
def test_pool_shares_one_store_and_routes_host_hits():
    lm = _lm(pool_pages=24, max_seqs=2, spec=False, host_tier_mb=4.0)
    pool = ReplicaPool(lm, 2, policy="affinity")
    try:
        assert pool.host_tier is not None
        for r in pool.replicas:
            assert r.engine.host_tier is pool.host_tier
            assert r.engine.cache.host_tier is pool.host_tier

        prompt = list(np.random.RandomState(0).randint(
            1, 61, size=33))
        ps = pool.replicas[0].engine.cache_cfg.page_size
        keys = prefix_page_keys(prompt, ps, (len(prompt) - 1) // ps)
        # nothing anywhere: tenant-hash fallback
        _, info = pool.route(prompt, tenant=7)
        assert info["fallback"] and not info["host_hit"]
        # seed the SHARED store under the same chain keys the router
        # probes: the host tier is now the best (and only) affinity
        for i, k in enumerate(keys):
            pool.host_tier.put(k, _rows(i))
        before = dict(pool.host_tier.stats)
        target, info = pool.route(prompt, tenant=7)
        assert info["host_hit"] and not info["fallback"]
        assert info["matched_tokens"] == len(keys) * ps
        assert target.idx == min(
            r.idx for r in pool.routable())   # least-loaded tie -> 0
        # route() is pure observation on the store too
        assert dict(pool.host_tier.stats) == before
        assert pool.stats["host_hits"] == 0   # counted at submit()
    finally:
        pool.close()


def test_pool_run_spills_and_reloads_across_replicas():
    """A 2-replica pool under alternating tenant working sets: the
    shared store absorbs both replicas' spills, repeats reload, the
    router counts host-tier hits, and the pool still drains to full
    page reclamation with zero recompiles and exact tokens."""
    from flexflow_tpu.serve import TrafficSpec, make_traffic
    lm = _lm(pool_pages=26, max_seqs=2, spec=False, max_seq_len=96,
             host_tier_mb=4.0)
    pool = ReplicaPool(lm, 2, policy="affinity")
    try:
        for r in pool.replicas:   # make every host match reload
            r.engine._host_step_price = lambda ctx: 1e-3
        price = pool.price_probe(48)
        traffic = make_traffic(TrafficSpec(
            requests=24, seed=2, arrival="poisson",
            rate_rps=0.08 / price, tenants=4, prefix_tokens=48,
            tail_mean=4.0, output_mean=4.0, max_prompt=72,
            max_new_cap=6, vocab=61))
        res = pool.run(traffic, slo_ttft_s=15 * price,
                       slo_tpot_s=8 * price)
        pool.assert_zero_recompiles()
        pool.check_drained()
        host = res["host_tier"]
        assert host is not None and host["spills"] > 0
        assert host["reload_pages"] > 0
        # single-engine token identity (the chaos-test gate)
        ref = ServeEngine(_lm(pool_pages=64, max_seqs=2, spec=False,
                              max_seq_len=96), spec_tokens=0)
        ref.warmup()
        expect = ref.generate(
            [t.prompt for t in traffic],
            [t.max_new for t in traffic],
            stream_ids=[t.stream_id for t in traffic])
        for rec, want in zip(res["requests"], expect):
            if rec["outcome"] == "completed":
                assert rec["tokens"] == want
    finally:
        pool.close()


# =======================================================================
# telemetry
# =======================================================================
def test_host_reload_attribution_component():
    """host_reload is a first-class attribution component: the class
    tables agree, priorities stay distinct (the fold compares with
    strict >), and an armed engine's breakdown still sums to the
    measured latency with the reload DMA attributed."""
    assert "host_reload" in REQUEST_COMPONENTS
    assert _SPAN_CLASS["host_reload"] == "host_reload"
    assert set(_SPAN_CLASS.values()) <= set(_CLASS_PRIORITY)
    prios = list(_CLASS_PRIORITY.values())
    assert len(prios) == len(set(prios))
    # the reload span overlaps queue_wait: it must outrank "queue"
    assert _CLASS_PRIORITY["host_reload"] > _CLASS_PRIORITY["queue"]

    rng = np.random.RandomState(9)
    a, b = _prompts(rng, 2), _prompts(rng, 2)
    tel = Telemetry()
    eng = ServeEngine(_lm(pool_pages=20, host_tier_mb=4.0,
                          spec=False),
                      spec_tokens=0, telemetry=tel)
    eng.warmup()
    eng._host_step_price = lambda ctx: 1e-3
    for prompts in (a, b, a):
        eng.generate(prompts, 6)
    reloaded = total = 0.0
    for row in eng.last_stats["requests"]:
        bd = eng.explain_request(row["rid"])
        assert set(bd["components"]) == set(REQUEST_COMPONENTS)
        lat = bd["latency_s"]
        assert abs(sum(bd["components"].values()) - lat) \
            <= 1e-9 + 0.01 * lat
        reloaded += bd["components"]["host_reload"]
        total += lat
        if bd["host_reload"] and \
                bd["host_reload"]["chose"] == "reload":
            assert bd["components"]["host_reload"] > 0.0
    assert eng._host_reload_stats["reload_events"] > 0
    assert 0.0 < reloaded < total
