"""Serve throughput v2: prefix caching, chunked prefill, on-demand
paged allocation, preemption, and sampling.

Layered like tests/test_serve.py:
  * kernel — paged_attention_ragged (the mixed-step kernel) equals
    full-prefill attention BIT-FOR-BIT per lane on CPU, its Pallas
    form (interpret mode) agrees with the jnp fallback, and a
    one-lane-per-sequence call IS paged_attention_decode.
  * cache — refcounted sharing, commit/match/evict life cycle, and a
    property test driving random submit/decode/finish/preempt traffic
    against check_invariants.
  * engine — prefix-cached, chunked, preempted generation stays
    token-for-token identical to the no-cache greedy reference with
    zero recompiles; sampling is seeded and reproducible.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.config import FFConfig
from flexflow_tpu.kernels.flash_attention import (
    paged_attention_decode,
    paged_attention_ragged,
)
from flexflow_tpu.serve import (
    ContinuousBatchingScheduler,
    KVCacheConfig,
    PagedKVCache,
    prefix_page_keys,
)


# --------------------------------------------------------------- helpers
def _ragged_setup(batch, seed, page_size=4, pages_per_seq=6):
    """Random ragged K/V histories scattered into pages (same layout as
    tests/test_serve.py) plus the contiguous copies full-prefill
    attention reads."""
    rng = np.random.RandomState(seed)
    h, d = 4, 8
    max_len = pages_per_seq * page_size
    num_pages = 1 + batch * pages_per_seq
    lens = rng.randint(1, max_len + 1, size=batch)
    k_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    v_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    table = np.zeros((batch, pages_per_seq), np.int32)
    k_full = np.zeros((batch, max_len, h, d), np.float32)
    v_full = np.zeros((batch, max_len, h, d), np.float32)
    pool = list(rng.permutation(np.arange(1, num_pages)))
    for b, L in enumerate(lens):
        k_full[b, :L] = rng.randn(L, h, d)
        v_full[b, :L] = rng.randn(L, h, d)
        for i in range(-(-int(L) // page_size)):
            p = int(pool.pop())
            table[b, i] = p
            chunk = slice(i * page_size, min((i + 1) * page_size, int(L)))
            n = chunk.stop - chunk.start
            k_pages[p, :n] = k_full[b, chunk]
            v_pages[p, :n] = v_full[b, chunk]
    return k_pages, v_pages, table, lens, k_full, v_full


def _lanes_for(lens, rng, lanes_per_seq=3):
    """Random (slot, position) lanes — several per sequence, the mixed
    step's shape — always including each sequence's last position."""
    slots, poss = [], []
    for s, L in enumerate(lens):
        picks = {int(L) - 1} | {int(p) for p in
                                rng.randint(0, int(L), size=lanes_per_seq)}
        for p in sorted(picks):
            slots.append(s)
            poss.append(p)
    return np.asarray(slots, np.int32), np.asarray(poss, np.int32)


def _full_prefill_attention(q, k_full, v_full, seq_lens, scale):
    """Last-position attention on the CONTIGUOUS layout with the exact
    op sequence of the paged path (dot_general dims,
    divide-after-matmul) so equality is bitwise when the page
    indirection is exact. Copied from tests/test_serve.py — per-lane
    here: each 'batch' row is one lane."""
    b, t, h, d = k_full.shape
    s = jax.lax.dot_general(
        q, k_full, (((2,), (3,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32) * scale
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, 1, t), 2)
    s = jnp.where(pos < seq_lens[:, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v_full.astype(jnp.float32), (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32)
    return (o / l).astype(q.dtype)


# ------------------------------------------------- ragged kernel parity
@pytest.mark.parametrize("batch", [1, 3, 8])
def test_paged_ragged_bitwise_vs_full_prefill(batch):
    """Every lane — an arbitrary (sequence, position) query — must
    equal full-prefill attention at that position bit-for-bit: the
    slot indirection and per-lane masking are pure data movement."""
    rng = np.random.RandomState(10 + batch)
    kp, vp, table, lens, k_full, v_full = _ragged_setup(batch, batch)
    slots, poss = _lanes_for(lens, rng)
    t = len(slots)
    q = rng.randn(t, 4, 8).astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    out = paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(slots), jnp.asarray(poss + 1),
        scale=scale, use_pallas=False)
    ref = _full_prefill_attention(
        jnp.asarray(q), jnp.asarray(k_full[slots]),
        jnp.asarray(v_full[slots]), jnp.asarray(poss + 1), scale)
    assert out.dtype == ref.dtype
    assert np.array_equal(np.asarray(out), np.asarray(ref)), (
        np.abs(np.asarray(out) - np.asarray(ref)).max())


@pytest.mark.parametrize("batch", [1, 3])
def test_paged_ragged_pallas_interpret_matches_jnp(batch):
    rng = np.random.RandomState(60 + batch)
    kp, vp, table, lens, _, _ = _ragged_setup(batch, 200 + batch)
    slots, poss = _lanes_for(lens, rng)
    t = len(slots)
    q = rng.randn(t, 4, 8).astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    ref = paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(slots), jnp.asarray(poss + 1),
        scale=scale, use_pallas=False)
    out = paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(slots), jnp.asarray(poss + 1),
        scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_paged_ragged_one_lane_is_decode():
    """A one-lane-per-sequence ragged call at each sequence's tail is
    exactly the decode kernel — same math, same bits."""
    rng = np.random.RandomState(33)
    kp, vp, table, lens, _, _ = _ragged_setup(4, 44)
    q = rng.randn(4, 4, 8).astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    slots = np.arange(4, dtype=np.int32)
    ragged = paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(slots),
        jnp.asarray(lens.astype(np.int32)), scale=scale, use_pallas=False)
    decode = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lens.astype(np.int32)),
        scale=scale, use_pallas=False)
    assert np.array_equal(np.asarray(ragged), np.asarray(decode))


# --------------------------------------------------- prefix cache (host)
def test_kv_cache_prefix_share_lifecycle():
    """Commit -> match -> attach (refcount 2) -> free one owner (page
    survives) -> free both (page parks in the LRU, still matchable) ->
    pool pressure evicts it (hash dropped)."""
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=7, max_seqs=3,
                        max_seq_len=24)
    cache = PagedKVCache(cfg)
    tokens = list(range(100, 108))          # 2 full pages
    keys = prefix_page_keys(tokens, 4, 2)
    s0 = cache.alloc_slot()
    cache.ensure_capacity(s0, 8)
    cache.advance(s0, 8)
    assert cache.match_prefix(keys) == []   # nothing committed yet
    cache.commit_page(s0, 0, keys[0])
    cache.commit_page(s0, 1, keys[1])
    pages = cache.match_prefix(keys)
    assert len(pages) == 2
    s1 = cache.alloc_slot()
    cache.attach_prefix(s1, pages, 8)
    cache.check_invariants()
    assert cache.ref(pages[0]) == 2
    assert cache.free_pages == 4
    cache.free_slot(s0)                     # shared pages survive
    cache.check_invariants()
    assert cache.ref(pages[0]) == 1
    assert cache.match_prefix(keys) == pages
    cache.free_slot(s1)                     # refcount 0: parked, not freed
    cache.check_invariants()
    assert cache.match_prefix(keys) == pages
    assert cache.free_pages == cfg.usable_pages  # still reclaimable
    # pool pressure evicts parked pages and drops their hashes
    s2 = cache.alloc_slot()
    cache.ensure_capacity(s2, 24)           # all 6 usable pages
    cache.check_invariants()
    assert cache.match_prefix(keys) == []
    assert cache.stats["prefix_evictions"] >= 2


def test_kv_pool_stress_property():
    """Random submit/chunk/decode/finish/preempt traffic against
    check_invariants: refcounts sum correctly, no page leaks or
    double-frees, exhaustion preempts and later admits again. Prompts
    draw from a few shared prefixes so the run exercises real sharing,
    and the pool is sized to force preemptions."""
    rng = np.random.RandomState(11)
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=17, max_seqs=4,
                        max_seq_len=40)
    cache = PagedKVCache(cfg)
    sched = ContinuousBatchingScheduler(cache, prefill_token_budget=16)
    prefixes = [list(rng.randint(0, 9, size=12)) for _ in range(3)]
    reqs = []
    steps = 0
    while sched.has_work() or len(reqs) < 40:
        steps += 1
        assert steps < 5000, "stress driver wedged"
        if len(reqs) < 40 and rng.rand() < 0.4:
            pre = prefixes[rng.randint(len(prefixes))]
            prompt = pre + list(rng.randint(0, 9,
                                            size=rng.randint(1, 8)))
            reqs.append(sched.submit(prompt, int(rng.randint(1, 14))))
        if not sched.has_work():
            continue
        plan = sched.schedule()
        assert plan.chunks
        for ch in plan.chunks:
            sched.complete_chunk(ch)
        for ch in plan.chunks:
            if ch.emits:
                ch.req.out_tokens.append(int(rng.randint(0, 9)))
                if ch.req.is_done():
                    sched.finish(ch.req)
        cache.check_invariants()
    assert all(len(r.out_tokens) == r.max_new_tokens for r in reqs)
    assert cache.free_pages == cfg.usable_pages
    assert cache.free_slots == cfg.max_seqs
    # the pool is tight enough to preempt and the prompts share
    # prefixes — both paths must actually have run
    assert sched.stats["preemptions"] > 0
    assert sched.stats["prefix_hit_tokens"] > 0
    assert cache.stats["prefix_evictions"] >= 0  # counter sane


def test_kv_pool_stress_with_rollback():
    """The stress property test with SPECULATION in the traffic:
    random drafts ride on decode chunks, a simulated verifier accepts
    random prefixes (so complete_spec_chunk advances + rolls back every
    step), and gratuitous ensure_capacity/rollback pairs are
    interleaved — refcount partition, hash bijection and the
    hashed-page coverage rule (no rolled-back page is
    prefix-matchable) must hold at every quiescent point."""
    from flexflow_tpu.serve import Drafter

    rng = np.random.RandomState(23)

    class RandomDrafter(Drafter):
        def draft(self, tokens, k):
            n = int(rng.randint(0, k + 1))
            return [int(t) for t in rng.randint(0, 9, size=n)]

    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=17, max_seqs=4,
                        max_seq_len=40)
    cache = PagedKVCache(cfg)
    sched = ContinuousBatchingScheduler(cache, prefill_token_budget=16,
                                        spec_tokens=3,
                                        drafter=RandomDrafter())
    prefixes = [list(rng.randint(0, 9, size=12)) for _ in range(3)]
    reqs = []
    steps = 0
    while sched.has_work() or len(reqs) < 40:
        steps += 1
        assert steps < 5000, "stress driver wedged"
        if len(reqs) < 40 and rng.rand() < 0.4:
            pre = prefixes[rng.randint(len(prefixes))]
            prompt = pre + list(rng.randint(0, 9,
                                            size=rng.randint(1, 8)))
            reqs.append(sched.submit(prompt, int(rng.randint(1, 14))))
        if not sched.has_work():
            continue
        plan = sched.schedule()
        assert plan.chunks
        for ch in plan.chunks:
            if not ch.draft_tokens:
                sched.complete_chunk(ch)
        for ch in plan.chunks:
            if ch.draft_tokens:
                # simulated verification: the engine's emit_spec rules
                req, k = ch.req, len(ch.draft_tokens)
                matched = 0
                for j in range(k + 1):
                    if j < k and rng.rand() < 0.6:
                        tok = ch.draft_tokens[j]
                    else:
                        tok = int(rng.randint(0, 9))
                    req.out_tokens.append(tok)
                    ok = j < k and tok == ch.draft_tokens[j]
                    if ok:
                        matched += 1
                    if req.is_done() or not ok:
                        break
                sched.complete_spec_chunk(ch, matched)
                if req.is_done():
                    sched.finish(req)
            elif ch.emits:
                ch.req.out_tokens.append(int(rng.randint(0, 9)))
                if ch.req.is_done():
                    sched.finish(ch.req)
        # gratuitous speculative mapping rolled straight back: a
        # no-op for residency, never for the allocator's books
        if sched.running and rng.rand() < 0.3:
            req = list(sched.running.values())[
                rng.randint(len(sched.running))]
            cur = int(cache.seq_lens[req.slot])
            if cur > 0:
                room = cfg.pages_per_seq * cfg.page_size
                ahead = min(cur + int(rng.randint(1, 6)), room)
                if cache.pages_to_extend(req.slot, ahead) \
                        <= len(cache._free) + len(cache._lru):
                    cache.ensure_capacity(req.slot, ahead)
                    cache.rollback(req.slot, max(cur, req.num_computed))
        cache.check_invariants()
    assert all(len(r.out_tokens) >= r.max_new_tokens
               or (r.eos_token is not None) for r in reqs)
    assert cache.free_pages == cfg.usable_pages
    assert cache.free_slots == cfg.max_seqs
    assert sched.stats["spec_drafted_tokens"] > 0
    assert sched.stats["spec_accepted_tokens"] > 0
    assert cache.stats["rollback_pages"] > 0
    assert sched.stats["preemptions"] > 0
    assert sched.stats["prefix_hit_tokens"] > 0


def test_scheduler_many_slots_fast_partition():
    """Satellite regression for the O(n^2) membership scan: with many
    slots the prefill/decode partition must stay correct (sets, not
    identity scans over a list)."""
    n = 128
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=1 + 2 * n, max_seqs=n,
                        max_seq_len=8)
    cache = PagedKVCache(cfg)
    sched = ContinuousBatchingScheduler(cache, prefill_token_budget=4 * n)
    for i in range(n):
        sched.submit([i % 7 + 1, i % 5 + 1], 3)
    plan = sched.schedule()
    assert len(plan.admitted) == n
    assert plan.num_prefill_lanes == 2 * n and plan.num_decode_lanes == 0
    for ch in plan.chunks:
        sched.complete_chunk(ch)
        ch.req.out_tokens.append(0)
    plan2 = sched.schedule()
    # every slot decodes; the partition is exact and disjoint
    assert plan2.num_decode_lanes == n and plan2.num_prefill_lanes == 0
    assert set(r.rid for r in plan2.decodes) == set(range(n))
    assert not plan2.prefills


# --------------------------------------------------------- engine e2e
@pytest.fixture(scope="module")
def lm():
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48)
    return build_transformer_lm(cfg, vocab_size=89, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


@pytest.fixture(scope="module")
def v2_engine(lm):
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(lm)
    eng.warmup()
    return eng


def _shared_prompts(rng, n, prefix_len=24, tail=4, vocab=89):
    prefix = list(rng.randint(1, vocab, size=prefix_len))
    return [prefix + list(rng.randint(1, vocab, size=tail))
            for _ in range(n)]


def test_prefix_cache_exact_with_hits(v2_engine):
    """A shared-prefix batch must hit the cache HARD (>= 2x fewer
    prefill tokens) and still match the no-cache reference token for
    token, without compiling anything."""
    rng = np.random.RandomState(1)
    prompts = _shared_prompts(rng, 6)
    before = v2_engine.compile_counts()
    out = v2_engine.generate(prompts, 5)
    assert v2_engine.compile_counts() == before, "serving recompiled"
    assert out == v2_engine.generate_reference(prompts, 5)
    st = v2_engine.last_stats
    assert st["prefix_hit_tokens"] > 0
    assert st["prompt_tokens_total"] >= 2 * st["prefill_tokens_computed"]


def test_prefix_cache_persists_across_generates(v2_engine):
    """The cache outlives generate(): a repeated prompt re-prefills
    only its tail (the partial last page + final token)."""
    rng = np.random.RandomState(2)
    prompts = [list(rng.randint(1, 89, size=27))]
    first = v2_engine.generate(prompts, 4)
    computed_first = v2_engine.last_stats["prefill_tokens_computed"]
    again = v2_engine.generate(prompts, 4)
    st = v2_engine.last_stats
    assert again == first
    assert st["prefix_hit_tokens"] >= 16   # two full pages of 8
    assert st["prefill_tokens_computed"] < computed_first


def test_prefix_cache_off_still_exact(lm):
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(lm, prefix_cache=False)
    eng.warmup()
    rng = np.random.RandomState(3)
    prompts = _shared_prompts(rng, 4)
    out = eng.generate(prompts, 4)
    assert out == eng.generate_reference(prompts, 4)
    st = eng.last_stats
    assert st["prefix_hit_tokens"] == 0
    assert st["prefill_tokens_computed"] == st["prompt_tokens_total"]


def test_chunked_prefill_long_prompt_exact():
    """A prompt longer than the whole prefill budget must chunk across
    steps (no oversized-bucket escape) and still match the reference,
    with decode lanes of other requests interleaved."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=49,
                   serve_max_seqs=4, serve_prefill_budget=16)
    ff = build_transformer_lm(cfg, vocab_size=61, max_seq_len=96,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(ff)
    eng.warmup()
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(1, 61, size=70)),   # >> budget of 16
               list(rng.randint(1, 61, size=5)),
               list(rng.randint(1, 61, size=40))]
    before = eng.compile_counts()
    out = eng.generate(prompts, [6, 12, 6])
    assert eng.compile_counts() == before
    assert out == eng.generate_reference(prompts, [6, 12, 6])
    # the 70-token prompt needed ceil(70/16) = 5 chunked steps minimum
    assert eng.last_stats["steps"] >= 5


def test_preemption_exact_and_counted():
    """A pool too small for the whole batch must preempt (youngest
    first), resume via the prefix cache, and still produce the exact
    reference streams."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=4, kv_num_pages=14,
                   serve_max_seqs=4, serve_prefill_budget=16)
    ff = build_transformer_lm(cfg, vocab_size=61, max_seq_len=48,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(ff)
    eng.warmup()
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(1, 61, size=rng.randint(8, 20)))
               for _ in range(4)]
    max_new = [int(rng.randint(8, 16)) for _ in range(4)]
    out = eng.generate(prompts, max_new)
    assert out == eng.generate_reference(prompts, max_new)
    assert eng.last_stats["preemptions"] > 0
    assert any(r["preemptions"] > 0
               for r in eng.last_stats["requests"])


def test_legacy_path_exact(lm):
    """serve_chunked_prefill=False keeps the PR 1 per-bucket prefill +
    full-width decode pair working against the same scheduler."""
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(lm, chunked_prefill=False)
    counts = eng.warmup()
    assert counts["mixed"] == 0 and counts["decode"] == 1
    rng = np.random.RandomState(9)
    prompts = [list(rng.randint(1, 89, size=rng.randint(1, 30)))
               for _ in range(5)]
    max_new = [int(rng.randint(1, 8)) for _ in range(5)]
    before = eng.compile_counts()
    out = eng.generate(prompts, max_new)
    assert eng.compile_counts() == before
    assert out == eng.generate_reference(prompts, max_new)


def test_unaligned_max_seq_len_reference_not_nan_poisoned():
    """Regression: with max_seq_len NOT page-aligned (40 over 16-token
    pages) the bucket ladder used to round up past the learned
    positions (48 > 40), and jnp.take's "fill" OOB default made the
    padded position rows NaN — which poisoned every attended lane
    through 0 * NaN in the p.v product, so generate_reference emitted
    argmax-of-all-NaN (token 0) while the paged engine was right.
    Buckets now cap at max_seq_len and embeds clip, so decoding right
    up to the cap stays exact."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=16, kv_num_pages=25,
                   serve_max_seqs=2, serve_prefill_budget=16)
    ff = build_transformer_lm(cfg, vocab_size=61, max_seq_len=40,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(ff)
    assert eng.buckets[-1] == 40
    eng.warmup()
    rng = np.random.RandomState(31)
    prompts = [list(rng.randint(1, 61, size=16)),
               list(rng.randint(1, 61, size=7))]
    out = eng.generate(prompts, [24, 33])   # both reach the 40 cap
    ref = eng.generate_reference(prompts, [24, 33])
    assert out == ref
    assert [len(o) for o in out] == [24, 33]  # ran to the cap, no eos


# --------------------------------------------------------- sampling
def test_sampling_seeded_reproducible(v2_engine):
    rng = np.random.RandomState(21)
    prompts = [list(rng.randint(1, 89, size=rng.randint(2, 12)))
               for _ in range(3)]
    a = v2_engine.generate(prompts, 8, temperature=0.9, top_k=16,
                           sample_seed=42)
    b = v2_engine.generate(prompts, 8, temperature=0.9, top_k=16,
                           sample_seed=42)
    c = v2_engine.generate(prompts, 8, temperature=0.9, top_k=16,
                           sample_seed=43)
    assert a == b, "fixed seed must reproduce the streams exactly"
    assert a != c, "a different seed should diverge (vanishingly rare)"
    # sampling must not break the zero-recompile contract: the top-k
    # head is part of the one mixed program
    assert v2_engine.compile_counts()["mixed"] == 1


def test_sampling_topk1_is_greedy(v2_engine):
    """top_k=1 at any temperature is argmax — an exactness bridge
    between the sampling path and the greedy parity tests."""
    prompts = [[5, 6, 7], [11, 3]]
    greedy = v2_engine.generate(prompts, 6)
    sampled = v2_engine.generate(prompts, 6, temperature=1.7, top_k=1)
    assert sampled == greedy


def test_sampling_per_request_and_validation(v2_engine):
    prompts = [[5, 6, 7], [11, 3]]
    greedy = v2_engine.generate(prompts, 6)
    mixed = v2_engine.generate(prompts, 6, temperature=[0.0, 0.8],
                               top_k=[None, 8], sample_seed=1)
    assert mixed[0] == greedy[0], "temperature 0 lane stays greedy"
    with pytest.raises(ValueError):
        v2_engine.generate(prompts, 2, temperature=0.5,
                           top_k=v2_engine.topk_cap + 1)
    with pytest.raises(ValueError):
        v2_engine.generate(prompts, 2, temperature=-0.1)
