"""Model zoo smoke + accuracy tests (reference pattern:
tests/accuracy_tests.sh — small problems, few epochs, assert learning)."""

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.models import (
    build_alexnet,
    build_candle_uno,
    build_dlrm,
    build_inception_v3,
    build_moe_fused,
    build_moe_reference,
    build_nmt_lstm,
    build_resnet,
    build_transformer,
)


def _cfg(bs):
    cfg = FFConfig()
    cfg.batch_size = bs
    return cfg


def _train_steps(ff, batch, n=2):
    for _ in range(n):
        m = ff.train_batch(batch)
    assert np.isfinite(float(m["loss"])), m
    return m


def test_alexnet_smoke():
    ff = build_alexnet(_cfg(8), batch_size=8, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    _train_steps(ff, {"input": rng.randn(8, 3, 32, 32).astype(np.float32),
                      "label": rng.randint(0, 10, 8).astype(np.int32)})


def test_alexnet_bf16_mixed_precision_trains():
    """bf16 activations / f32 weights (the mode bench.py measures in):
    the conv gradient transpose must accept the mixed pair (regression:
    preferred_element_type=f32 made jax.grad of conv raise on bf16
    inputs)."""
    import jax.numpy as jnp

    ff = build_alexnet(_cfg(8), batch_size=8, image_size=32,
                       dtype=jnp.bfloat16)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    assert ff.state.params["conv2d"]["kernel"].dtype == jnp.float32
    rng = np.random.RandomState(0)
    m = _train_steps(
        ff, {"input": rng.randn(8, 3, 32, 32).astype(np.float32),
             "label": rng.randint(0, 10, 8).astype(np.int32)})
    assert np.isfinite(float(m["loss"]))


def test_resnet18_smoke():
    ff = build_resnet(_cfg(4), depth=18, batch_size=4, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    _train_steps(ff, {"input": rng.randn(4, 3, 32, 32).astype(np.float32),
                      "label": rng.randint(0, 10, 4).astype(np.int32)})


def test_resnet50_builds():
    ff = build_resnet(_cfg(2), depth=50, batch_size=2, image_size=32)
    assert any(op.name == "s3b2_conv3" for op in ff.ops)
    n_params = sum(
        int(np.prod(s.shape))
        for op in ff.ops for s in op.weight_specs().values())
    assert 20e6 < n_params < 30e6, n_params  # ~23.5M for resnet50


def test_inception_v3_smoke_small():
    ff = build_inception_v3(_cfg(2), batch_size=2, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    _train_steps(ff, {"input": rng.randn(2, 3, 32, 32).astype(np.float32),
                      "label": rng.randint(0, 10, 2).astype(np.int32)},
                 n=1)


def test_dlrm_smoke():
    ff = build_dlrm(_cfg(16), batch_size=16,
                    embedding_vocab_sizes=(100, 100, 100),
                    embedding_dim=16, bot_mlp=(32, 16),
                    top_mlp=(32, 1))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="mean_squared_error", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"dense_features": rng.randn(16, 13).astype(np.float32),
             "label": rng.rand(16, 1).astype(np.float32)}
    for i in range(3):
        batch[f"sparse_{i}"] = rng.randint(0, 100, (16, 1)).astype(np.int32)
    _train_steps(ff, batch)


def test_moe_reference_pipeline_smoke():
    ff = build_moe_reference(_cfg(32), batch_size=32, input_dim=64,
                             num_experts=4, k=2, expert_hidden=32)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    _train_steps(ff, {"input": rng.randn(32, 64).astype(np.float32),
                      "label": rng.randint(0, 10, 32).astype(np.int32)})


def test_candle_uno_smoke():
    ff = build_candle_uno(_cfg(8), batch_size=8,
                          feature_shapes={"dose1": 1, "rnaseq": 64,
                                          "drug": 128},
                          tower_layers=(32, 16), final_layers=(32, 16))
    ff.compile(optimizer=AdamOptimizer(lr=0.001),
               loss_type="mean_squared_error", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"dose1": rng.randn(8, 1).astype(np.float32),
             "rnaseq": rng.randn(8, 64).astype(np.float32),
             "drug": rng.randn(8, 128).astype(np.float32),
             "label": rng.randn(8, 1).astype(np.float32)}
    _train_steps(ff, batch)


def test_nmt_lstm_smoke_and_learns():
    ff = build_nmt_lstm(_cfg(16), batch_size=16, seq_len=8,
                        vocab_size=50, embed_dim=32, hidden=32,
                        num_layers=2)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    # learnable task: next token = first token
    xs = rng.randint(0, 50, (128, 8)).astype(np.int32)
    ys = xs[:, 0].astype(np.int32)
    hist = ff.fit({"input": xs}, ys, epochs=20, verbose=False)
    assert hist[-1]["accuracy"] > 0.7, hist[-1]


def test_transformer_learns():
    ff = build_transformer(_cfg(16), batch_size=16, seq_len=8, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=4)
    ff.compile(optimizer=AdamOptimizer(lr=0.003),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    xs = rng.randn(128, 8, 32).astype(np.float32)
    ys = (xs[:, 0, 0] > 0).astype(np.int32)  # depends on CLS position
    hist = ff.fit({"input": xs}, ys, epochs=10, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]


def test_nmt_seq2seq_learns():
    """Encoder-decoder with cross-attention (the reference nmt/
    framework's full shape, rnn.h:91-160) memorizes a tiny corpus;
    per-position sequence labels exercise the seq generalization of
    sparse-CCE + accuracy."""
    from flexflow_tpu.models import build_nmt_seq2seq

    cfg = FFConfig()
    cfg.batch_size = 8
    ff = build_nmt_seq2seq(cfg, batch_size=8, src_len=6, tgt_len=5,
                           vocab_size=32, embed_dim=16, hidden=16)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    b = {"src": rng.randint(0, 32, (8, 6)).astype(np.int32),
         "tgt": rng.randint(0, 32, (8, 5)).astype(np.int32),
         "label": rng.randint(0, 32, (8, 5)).astype(np.int32)}
    first = float(ff.train_batch(b)["loss"])
    for _ in range(60):
        m = ff.train_batch(b)
    last = float(m["loss"])
    assert last < first * 0.5, (first, last)
    # per-position accuracy counts every (batch, position) slot
    assert int(m["count"]) == 8 * 5


def test_transformer_pre_ln_learns():
    from flexflow_tpu.models import build_transformer

    cfg = FFConfig()
    cfg.batch_size = 16
    ff = build_transformer(cfg, batch_size=16, seq_len=8, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=4, layer_norm=True)
    assert any(op.op_type == "layer_norm" for op in ff.ops)
    ff.compile(optimizer=AdamOptimizer(lr=0.003),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 8, 32).astype(np.float32)
    ys = (xs[:, 0, 0] > 0).astype(np.int32)
    first = float(ff.train_batch({"input": xs[:16],
                                  "label": ys[:16]})["loss"])
    for _ in range(40):
        m = ff.train_batch({"input": xs[:16], "label": ys[:16]})
    assert float(m["loss"]) < first
