"""Pure-Python DataLoaderSet prefetch: the background-thread
double-buffered epoch iterator must be a pure overlap optimization.

Lives in its own (fast-profile) module: test_data_checkpoint.py is a
SLOW_MODULES member (orbax round trips), and the prefetch path needs
coverage in the default CI gate — a threading bug there would corrupt
every pure-Python training run.
"""

import numpy as np

from flexflow_tpu.core.dataloader import DataLoaderSet


def test_dataloader_prefetch_epochs_order_identical():
    """Every epoch's batch ORDER and CONTENT equal the synchronous
    (prefetch=False escape hatch) path's, across multiple shuffled
    epochs, including iterators abandoned early."""
    rng = np.random.RandomState(4)
    x = rng.randn(54, 3).astype(np.float32)   # 54/16: a ragged tail
    y = np.arange(54).astype(np.int32)
    pre = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                        shuffle=True, seed=9, use_native=False)
    syn = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                        shuffle=True, seed=9, use_native=False,
                        prefetch=False)
    assert pre.prefetch and not syn.prefetch
    for _ in range(3):
        got_pre = list(pre)
        got_syn = list(syn)
        assert len(got_pre) == len(got_syn) == pre.num_batches
        for a, b in zip(got_pre, got_syn):
            np.testing.assert_array_equal(np.asarray(a["input"]),
                                          np.asarray(b["input"]))
            np.testing.assert_array_equal(np.asarray(a["label"]),
                                          np.asarray(b["label"]))
    # an abandoned iterator must not wedge the worker or later epochs
    it = iter(pre)
    next(it)
    del it
    assert len(list(pre)) == pre.num_batches
    # explicit-order epochs (the fit() path) agree too
    order = np.random.RandomState(11).permutation(54)
    a = [np.asarray(b["label"])
         for b in pre.iter_with_order(order)]
    b = [np.asarray(bb["label"])
         for bb in syn.iter_with_order(order)]
    for xa, xb in zip(a, b):
        np.testing.assert_array_equal(xa, xb)
