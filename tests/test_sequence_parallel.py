"""Sequence-parallel (ring attention) correctness tests.

SP is a new axis vs the reference (SURVEY.md 2.4); correctness bar:
seq-sharded results == unsharded results."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, SGDOptimizer, make_mesh
from flexflow_tpu.parallel.pconfig import sequence_parallel_strategy
from flexflow_tpu.parallel.ring_attention import ring_attention
from flexflow_tpu.models.transformer import build_transformer


def reference_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(d))
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh((2, 4), ("data", "seq"))
    rng = np.random.RandomState(0)
    b, s, h, d = 4, 16, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))

    ref = reference_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_uneven_heads_one_device_per_shard():
    mesh = make_mesh((1, 8), ("data", "seq"))
    rng = np.random.RandomState(1)
    b, s, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    ref = reference_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_sp_transformer_matches_unsharded():
    """Full transformer training step with seq sharded over 4 devices
    matches the single-device run."""
    def build(mesh=None, strategy=None):
        cfg = FFConfig()
        cfg.batch_size = 8
        ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                               num_heads=4, num_layers=2, ff_dim=64,
                               num_classes=4, mesh=mesh, strategy=strategy)
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"], mesh=mesh, strategy=strategy)
        return ff

    rng = np.random.RandomState(0)
    x = rng.randn(64, 16, 32).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.int32)

    ff1 = build()
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((2, 4), ("data", "seq"))
    ff2 = build(mesh=mesh, strategy=sequence_parallel_strategy())
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1[-1], h2[-1])
    w1 = ff1.get_weights("layer0_attn")["wq"]
    w2 = ff2.get_weights("layer0_attn")["wq"]
    np.testing.assert_allclose(w1, w2, atol=2e-4)


def test_sp_non_divisible_seq_falls_back():
    """Review regression: seq_len % seq_axis != 0 must fall back to the
    XLA path instead of crashing shard_map."""
    from flexflow_tpu import FFModel
    mesh = make_mesh((1, 8), ("data", "seq"))
    cfg = FFConfig()
    cfg.batch_size = 4
    ff = FFModel(cfg, mesh=mesh, strategy=sequence_parallel_strategy())
    x = ff.create_tensor((4, 12, 16), name="input")  # 12 % 8 != 0
    t = ff.multihead_attention(x, x, x, 16, 2, name="attn")
    head, _ = ff.split(t, [1, 11], axis=1)
    head = ff.reshape(head, (4, 16))
    ff.softmax(ff.dense(head, 4))
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    m = ff.train_batch({"input": rng.randn(4, 12, 16).astype(np.float32),
                        "label": np.zeros(4, np.int32)})
    assert np.isfinite(float(m["loss"]))


# ----------------------------------------- all-to-all (Ulysses) SP mode
def test_alltoall_attention_matches_reference():
    from flexflow_tpu.parallel.ulysses import alltoall_attention
    mesh = make_mesh((2, 4), ("data", "seq"))
    rng = np.random.RandomState(2)
    b, s, h, d = 4, 16, 4, 8  # h % seq_size == 0
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    for causal in (False, True):
        ref = reference_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: alltoall_attention(
            q, k, v, mesh, causal=causal))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_alltoall_rejects_indivisible_heads():
    from flexflow_tpu.parallel.ulysses import alltoall_attention
    mesh = make_mesh((1, 8), ("data", "seq"))
    x = jnp.zeros((2, 32, 4, 8))  # 4 heads over 8-way seq axis
    with pytest.raises(ValueError, match="divisible"):
        alltoall_attention(x, x, x, mesh, causal=True)


def test_sp_mode_policy():
    """auto: alltoall when heads divide AND scores fit, else ring;
    explicit modes pass through (alltoall still needs divisibility)."""
    from flexflow_tpu.parallel.ulysses import sp_mode_for

    def mode(m, heads, s, skv=None):
        return sp_mode_for(m, num_heads=heads, seq_size=4,
                           batch_local=8, seq_q=s,
                           seq_kv=s if skv is None else skv)

    assert mode("auto", 8, 1024) == "alltoall"
    assert mode("auto", 6, 1024) == "ring"  # 6 % 4 != 0
    assert mode("auto", 8, 512 * 1024) == "ring"  # scores blow the limit
    # cross-attention: the (sq x skv) product decides, not sq^2
    assert mode("auto", 8, 128, 512 * 1024) == "ring"
    assert mode("auto", 8, 512 * 1024, 128) == "ring"
    assert mode("ring", 8, 64) == "ring"
    assert mode("alltoall", 8, 512 * 1024) == "alltoall"
    assert mode("alltoall", 6, 64) == "ring"  # forced but indivisible


def test_alltoall_causal_cross_attention():
    """Review regression: causal cross-attention (sq != sk) must mask
    over the global (sq x sk) block, matching the ring path."""
    from flexflow_tpu.parallel.ulysses import alltoall_attention
    mesh = make_mesh((1, 4), ("data", "seq"))
    rng = np.random.RandomState(4)
    b, h, d = 2, 4, 8
    q = jnp.asarray(rng.randn(b, 8, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, 16, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, 16, h, d).astype(np.float32))
    ref = reference_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: alltoall_attention(
        q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_sp_transformer_alltoall_matches_unsharded():
    """Same end-to-end parity as the ring test, forced through the
    all-to-all lowering."""
    def build(mesh=None, strategy=None):
        cfg = FFConfig()
        cfg.batch_size = 8
        cfg.sp_attention = "alltoall"
        ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                               num_heads=4, num_layers=2, ff_dim=64,
                               num_classes=4, mesh=mesh, strategy=strategy)
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"], mesh=mesh, strategy=strategy)
        return ff

    rng = np.random.RandomState(3)
    x = rng.randn(32, 16, 32).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    ff1 = build()
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)
    mesh = make_mesh((2, 4), ("data", "seq"))
    ff2 = build(mesh=mesh, strategy=sequence_parallel_strategy())
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)
    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1[-1], h2[-1])


def test_sp_cost_model_prices_both_modes():
    """The cost model consults the same policy the op executes: forced
    modes produce different comm costs (a2a vs ring hops)."""
    from flexflow_tpu import FFModel
    from flexflow_tpu.search.cost_model import op_cost
    from flexflow_tpu.search.machine_model import default_machine_model
    from flexflow_tpu.parallel.pconfig import OpStrategy
    mesh = make_mesh((2, 4), ("data", "seq"))
    costs = {}
    for mode in ("ring", "alltoall"):
        cfg = FFConfig(batch_size=8)
        cfg.sp_attention = mode
        ff = FFModel(cfg, mesh=mesh)
        x = ff.create_tensor((8, 64, 32), name="input")
        ff.multihead_attention(x, x, x, 32, 8, name="attn")
        op = next(o for o in ff.ops if o.name == "attn")
        c = op_cost(op, OpStrategy({"sample": "data", "seq": "seq"}),
                    mesh, default_machine_model(mesh))
        costs[mode] = c.fwd_comm
    assert costs["ring"] > 0 and costs["alltoall"] > 0
    assert costs["ring"] != costs["alltoall"]
