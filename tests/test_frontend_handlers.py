"""Executable frontend-handler tests (VERDICT round-1 item 7).

The reference CI runs tests/onnx/test_onnx_import.py against real onnx;
this image has neither onnx nor tensorflow, so these tests drive the
SAME handler tables through their dependency-free entry points:

- ONNX: `ONNXModel.from_graph` with hand-built `GraphNode`s — a
  conv/pool/gemm/concat/BN graph imports, matches a torch forward with
  identical weights, and trains.
- keras_exp: `from_tf_keras` on duck-typed stand-ins for tf.keras model
  and layer objects (the importer only uses the object protocol), which
  proves the HWIO->OIHW conv transpose, BN gamma/beta/mean/var staging,
  and the fail-loudly paths.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.frontends.onnx import GraphNode, ONNXModel
from flexflow_tpu.frontends.keras_exp import from_tf_keras


# --------------------------------------------------------------------------
# ONNX handler table (no onnx package)
# --------------------------------------------------------------------------

class TorchRef(nn.Module):
    """conv -> relu -> maxpool -> BN -> flatten -> gemm, mirroring the
    ONNX graph below."""

    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2d(3, 8, 3, padding=1)
        self.pool = nn.MaxPool2d(2, 2)
        self.bn = nn.BatchNorm2d(8).eval()
        self.fc = nn.Linear(8 * 8 * 8, 4)

    def forward(self, x):
        x = self.pool(torch.relu(self.conv(x)))
        x = self.bn(x)
        return self.fc(torch.flatten(x, 1))


def _onnx_graph_from_torch(tm: TorchRef):
    """Hand-build the GraphNode list + initializers for TorchRef."""
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    nodes = [
        GraphNode("Conv", ["x", "conv_w", "conv_b"], ["c1"], "conv",
                  {"kernel_shape": [3, 3], "strides": [1, 1],
                   "pads": [1, 1, 1, 1]}),
        GraphNode("Relu", ["c1"], ["r1"], "relu1"),
        GraphNode("MaxPool", ["r1"], ["p1"], "pool",
                  {"kernel_shape": [2, 2], "strides": [2, 2]}),
        GraphNode("BatchNormalization",
                  ["p1", "bn_scale", "bn_bias", "bn_mean", "bn_var"],
                  ["b1"], "bn"),
        GraphNode("Flatten", ["b1"], ["f1"], "flatten"),
        GraphNode("Gemm", ["f1", "fc_w", "fc_b"], ["out"], "fc",
                  {"transB": 1}),
    ]
    inits = {
        "conv_w": sd["conv.weight"],          # OIHW, framework layout
        "conv_b": sd["conv.bias"],
        "bn_scale": sd["bn.weight"],
        "bn_bias": sd["bn.bias"],
        "bn_mean": sd["bn.running_mean"],
        "bn_var": sd["bn.running_var"],
        "fc_w": sd["fc.weight"],              # (out, in), transB=1
        "fc_b": sd["fc.bias"],
    }
    return nodes, inits


def test_onnx_graph_matches_torch_and_trains():
    torch.manual_seed(0)
    tm = TorchRef().eval()
    # give BN non-trivial running stats
    with torch.no_grad():
        tm.bn.running_mean.uniform_(-0.5, 0.5)
        tm.bn.running_var.uniform_(0.5, 1.5)
    nodes, inits = _onnx_graph_from_torch(tm)
    om = ONNXModel.from_graph(nodes, inits)

    cfg = FFConfig()
    cfg.batch_size = 4
    ff = FFModel(cfg)
    x = ff.create_tensor((4, 3, 16, 16), name="x")
    out = om.apply(ff, {"x": x})
    assert out.shape == (4, 4)
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 3, 16, 16).astype(np.float32)
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states, {"x": xv}, False, None)
    got = np.asarray(values[out.uid])
    want = tm(torch.from_numpy(xv)).detach().numpy()
    np.testing.assert_allclose(got, want, atol=2e-3)

    # and it trains
    m = ff.train_batch({"x": xv,
                        "label": rng.randint(0, 4, (4,)).astype(np.int32)})
    assert np.isfinite(float(m["loss"]))


def test_onnx_concat_split_elementwise_handlers():
    nodes = [
        GraphNode("Split", ["x"], ["s0", "s1"], "split", {"axis": 1}),
        GraphNode("Relu", ["s0"], ["r0"], "relu0"),
        GraphNode("Tanh", ["s1"], ["t1"], "tanh1"),
        GraphNode("Concat", ["r0", "t1"], ["cat"], "cat", {"axis": 1}),
        GraphNode("Add", ["cat", "x"], ["add"], "add"),
        GraphNode("Softmax", ["add"], ["sm"], "sm"),
    ]
    om = ONNXModel.from_graph(nodes, {})
    cfg = FFConfig()
    cfg.batch_size = 2
    ff = FFModel(cfg)
    x = ff.create_tensor((2, 8), name="x")
    out = om.apply(ff, {"x": x})
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    xv = np.random.RandomState(1).randn(2, 8).astype(np.float32)
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states, {"x": xv}, False, None)
    got = np.asarray(values[out.uid])
    want = np.concatenate([np.maximum(xv[:, :4], 0),
                           np.tanh(xv[:, 4:])], axis=1) + xv
    want = np.exp(want - want.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_onnx_asymmetric_pad_rejected():
    nodes = [GraphNode("Conv", ["x", "w"], ["y"], "conv",
                       {"kernel_shape": [2, 2], "strides": [1, 1],
                        "pads": [0, 0, 1, 1]})]
    om = ONNXModel.from_graph(
        nodes, {"w": np.zeros((4, 3, 2, 2), np.float32)})
    ff = FFModel(FFConfig())
    x = ff.create_tensor((2, 3, 8, 8), name="x")
    with pytest.raises(NotImplementedError, match="asymmetric"):
        om.apply(ff, {"x": x})


# --------------------------------------------------------------------------
# keras_exp handler table (no tensorflow package) — duck-typed tf.keras
# --------------------------------------------------------------------------

class FakeTensor:
    def __init__(self, name, shape):
        self.name = name
        self.shape = shape  # tf convention: (None, ...features)

    def ref(self):
        return id(self)


class _FakeLayer:
    def __init__(self, name, cfg, weights, inputs, output):
        self.name = name
        self._cfg = cfg
        self._weights = weights
        self.input = inputs if len(inputs) > 1 else inputs[0]
        self.output = output

    def get_config(self):
        return dict(self._cfg)

    def get_weights(self):
        return list(self._weights)


# handler dispatch is on type(layer).__name__, so mint one class per type
def _layer_cls(tname):
    return type(tname, (_FakeLayer,), {})


class FakeKerasModel:
    def __init__(self, inputs, layers):
        self.inputs = inputs
        self.layers = layers


def _build_fake_tf_cnn(torch_cnn):
    """Duck-typed tf.keras model mirroring conv->relu->pool->bn->flatten
    ->dense, with tf-layout weights taken from the torch module."""
    sd = {k: v.detach().numpy() for k, v in torch_cnn.state_dict().items()}
    inp = FakeTensor("input", (None, 3, 16, 16))
    c1 = FakeTensor("conv_out", (None, 8, 16, 16))
    p1 = FakeTensor("pool_out", (None, 8, 8, 8))
    b1 = FakeTensor("bn_out", (None, 8, 8, 8))
    f1 = FakeTensor("flat_out", (None, 512))
    d1 = FakeTensor("dense_out", (None, 4))
    conv_hwio = np.transpose(sd["conv.weight"], (2, 3, 1, 0))  # OIHW->HWIO
    layers = [
        _layer_cls("Conv2D")(
            "conv", {"filters": 8, "kernel_size": (3, 3),
                     "strides": (1, 1), "padding": "same",
                     "activation": "relu", "use_bias": True},
            [conv_hwio, sd["conv.bias"]], [inp], c1),
        _layer_cls("MaxPooling2D")(
            "pool", {"pool_size": (2, 2), "strides": (2, 2),
                     "padding": "valid"}, [], [c1], p1),
        _layer_cls("BatchNormalization")(
            "bn", {"scale": True, "center": True},
            [sd["bn.weight"], sd["bn.bias"], sd["bn.running_mean"],
             sd["bn.running_var"]], [p1], b1),
        _layer_cls("Flatten")("flatten", {}, [], [b1], f1),
        _layer_cls("Dense")(
            "fc", {"units": 4, "activation": "linear", "use_bias": True},
            [sd["fc.weight"].T, sd["fc.bias"]], [f1], d1),
    ]
    return FakeKerasModel([inp], layers), d1


def test_keras_exp_imports_tf_layouts_and_matches_torch():
    torch.manual_seed(1)
    tm = TorchRef().eval()
    with torch.no_grad():
        tm.bn.running_mean.uniform_(-0.5, 0.5)
        tm.bn.running_var.uniform_(0.5, 1.5)
    fake, _out = _build_fake_tf_cnn(tm)

    cfg = FFConfig()
    cfg.batch_size = 4
    ff = from_tf_keras(fake, config=cfg, batch_size=4)
    # conv kernel must be staged back in OIHW
    assert ff.imported_weights["conv"]["kernel"].shape == (8, 3, 3, 3)
    np.testing.assert_allclose(ff.imported_weights["conv"]["kernel"],
                               tm.conv.weight.detach().numpy())
    # BN running stats staged as state, not silently dropped
    np.testing.assert_allclose(ff.imported_states["bn"]["running_mean"],
                               tm.bn.running_mean.numpy())
    ff.softmax(ff.ops[-1].outputs[0])
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 3, 16, 16).astype(np.float32)
    dense_out = ff.ops[-2].outputs[0]  # pre-softmax logits
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states, {"input": xv}, False, None)
    got = np.asarray(values[dense_out.uid])
    want = tm(torch.from_numpy(xv)).detach().numpy()
    np.testing.assert_allclose(got, want, atol=2e-3)


def test_keras_exp_unmappable_weight_raises():
    inp = FakeTensor("input", (None, 8))
    out = FakeTensor("dense_out", (None, 4))
    bad = _layer_cls("Dense")(
        "fc", {"units": 4, "activation": "linear", "use_bias": True},
        [np.zeros((9, 4), np.float32)], [inp], out)  # wrong in_dim
    fake = FakeKerasModel([inp], [bad])
    with pytest.raises(ValueError, match="does not match"):
        from_tf_keras(fake, batch_size=2)


def test_keras_exp_same_pad_stride_fails_loudly():
    inp = FakeTensor("input", (None, 3, 16, 16))
    out = FakeTensor("conv_out", (None, 8, 8, 8))
    conv = _layer_cls("Conv2D")(
        "conv", {"filters": 8, "kernel_size": (3, 3), "strides": (2, 2),
                 "padding": "same", "activation": None, "use_bias": False},
        [], [inp], out)
    fake = FakeKerasModel([inp], [conv])
    with pytest.raises(NotImplementedError, match="asymmetric"):
        from_tf_keras(fake, batch_size=2)


# ---- real-TF leg (TF ships in the bench image; skip cleanly without) ----
# NOTE: guarded per-test, NOT via module-level importorskip — that would
# skip the deps-free stub tests above whenever TF is absent.

try:
    import tensorflow as tf
    _HAS_TF = True
except ImportError:
    tf = None
    _HAS_TF = False

needs_tf = pytest.mark.skipif(not _HAS_TF, reason="tensorflow not installed")


@needs_tf
def test_keras_exp_real_tf_dense_model_matches_predict():
    """Import a REAL tf.keras model (Keras 2 or 3 symbolic tensors both
    go through _tref) and match tf's own forward numerics."""
    tfk = tf.keras
    inp = tfk.Input((12,))
    t = tfk.layers.Dense(16, activation="relu", name="fc1")(inp)
    out = tfk.layers.Dense(4, name="fc2")(t)
    tf_model = tfk.Model(inp, out)

    cfg = FFConfig()
    cfg.batch_size = 8
    ff = from_tf_keras(tf_model, config=cfg, batch_size=8)
    ff.softmax(ff.ops[-1].outputs[0])
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])

    rng = np.random.RandomState(0)
    xv = rng.randn(8, 12).astype(np.float32)
    want = tf_model.predict(xv, verbose=0)
    logits = ff.ops[-2].outputs[0]
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states,
        {ff.input_tensors[0].name: xv}, False, None)
    np.testing.assert_allclose(np.asarray(values[logits.uid]), want,
                               atol=1e-4)


@needs_tf
def test_keras_exp_real_tf_nested_model_matches_predict():
    """A tf.keras Model used as a LAYER inside another Model (reference
    keras_exp func_cifar10_cnn_nested pattern) inlines: call-site
    tensors bind through the inbound node, internal weights import, and
    forward numerics match tf's own predict."""
    tfk = tf.keras
    feat_in = tfk.Input((12,))
    ft = tfk.layers.Dense(16, activation="relu", name="feat_fc")(feat_in)
    features = tfk.Model(feat_in, ft)

    inp = tfk.Input((12,), name="input")
    t = features(inp)
    out = tfk.layers.Dense(4, name="head")(t)
    tf_model = tfk.Model(inp, out)

    cfg = FFConfig()
    cfg.batch_size = 8
    ff = from_tf_keras(tf_model, config=cfg, batch_size=8)
    ff.softmax(ff.ops[-1].outputs[0])
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])

    rng = np.random.RandomState(0)
    xv = rng.randn(8, 12).astype(np.float32)
    want = tf_model.predict(xv, verbose=0)
    logits = ff.ops[-2].outputs[0]
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states,
        {ff.input_tensors[0].name: xv}, False, None)
    np.testing.assert_allclose(np.asarray(values[logits.uid]), want,
                               atol=1e-4)


@needs_tf
def test_keras_exp_real_tf_channels_last_conv_fails_loudly():
    tfk = tf.keras
    inp = tfk.Input((16, 16, 3))
    out = tfk.layers.Conv2D(8, 3, name="conv")(inp)  # channels_last
    tf_model = tfk.Model(inp, out)
    with pytest.raises(NotImplementedError, match="channels_last"):
        from_tf_keras(tf_model, batch_size=2)


def test_onnx_layer_norm_handler():
    scale = np.linspace(0.5, 1.5, 8).astype(np.float32)
    bias = np.linspace(-1, 1, 8).astype(np.float32)
    nodes = [
        GraphNode("LayerNormalization", ["x", "w", "b"], ["ln"], "ln",
                  {"epsilon": 1e-5, "axis": -1}),
        GraphNode("Relu", ["ln"], ["r"], "relu"),
    ]
    om = ONNXModel.from_graph(nodes, {"w": scale, "b": bias})
    cfg = FFConfig()
    cfg.batch_size = 2
    ff = FFModel(cfg)
    x = ff.create_tensor((2, 8), name="x")
    out = om.apply(ff, {"x": x})
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    xv = np.random.RandomState(2).randn(2, 8).astype(np.float32)
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states, {"x": xv}, False, None)
    got = np.asarray(values[out.uid])
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    want = np.maximum((xv - mu) / np.sqrt(var + 1e-5) * scale + bias, 0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@needs_tf
def test_keras_exp_real_tf_embedding_gap_layernorm_matches_predict():
    """Real tf.keras text-classifier head: Embedding (sequence output,
    tf semantics) -> GlobalAveragePooling1D -> LayerNormalization ->
    Dense, imported with weights and matching tf's forward."""
    tfk = tf.keras
    inp = tfk.Input((10,), dtype="int32")
    t = tfk.layers.Embedding(50, 8, name="emb")(inp)
    t = tfk.layers.GlobalAveragePooling1D(name="gap")(t)
    t = tfk.layers.LayerNormalization(name="ln")(t)
    out = tfk.layers.Dense(4, name="head")(t)
    tf_model = tfk.Model(inp, out)

    cfg = FFConfig()
    cfg.batch_size = 8
    ff = from_tf_keras(tf_model, config=cfg, batch_size=8)
    ff.softmax(ff.ops[-1].outputs[0])
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, (8, 10)).astype(np.int32)
    want = tf_model.predict(ids, verbose=0)
    logits = ff.ops[-2].outputs[0]
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states,
        {ff.input_tensors[0].name: ids}, False, None)
    np.testing.assert_allclose(np.asarray(values[logits.uid]), want,
                               atol=1e-4)
