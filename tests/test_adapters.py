"""Multi-tenant LoRA adapter serving (serve/adapters.py).

Layered like tests/test_serve_v2.py:
  * pool — slot-state property test driving random register/acquire/
    release/evict churn against AdapterPool.check_invariants, plus the
    admission-block and re-registration contracts.
  * exactness — rank padding contributes exactly zero; a mixed-tenant
    batch (>= 3 adapters + base lanes in ONE step) is token-identical
    to each tenant's merged-weight reference, greedy and top_k=1,
    across arrival orders, under eviction pressure, and with zero
    recompiles.
  * tenancy — tenant-salted prefix keys are disjoint, so equal
    prompts under different adapters never share cache pages.
  * search — the cost model prices the adapter gather + matmuls and
    the pool's HBM term, and the cost-cache fingerprint misses when
    either adapter knob changes (stale pre-adapter rows cannot
    resurrect).
"""

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.serve.adapters import (
    AdapterConfig,
    AdapterPool,
    make_tenant_adapters,
    merge_adapter_params,
    tenant_prefix_salt,
)


def _pool_cfg(slots=4, rank=4):
    return AdapterConfig(num_layers=2, hidden=32, num_heads=4,
                         head_dim=8, ff_dim=64, rank=rank,
                         num_slots=slots + 1)


def _weights(rank=4, ff=64, seed=0):
    return make_tenant_adapters(num_layers=2, hidden=32, num_heads=4,
                                head_dim=8, ff_dim=ff, rank=rank,
                                tenants=1, seed=seed)[1][0]


# ------------------------------------------------------------- pool
def test_pool_lifecycle_hit_miss_evict():
    pool = AdapterPool(_pool_cfg(slots=2))
    pool.register(1, _weights(), scale=0.5)
    pool.register(2, _weights(seed=1), scale=0.5)
    pool.register(3, _weights(seed=2), scale=0.5)
    s1 = pool.acquire(1)                  # miss -> load
    assert s1 is not None and pool.take_pending() == [(s1, 1)]
    assert pool.acquire(1) == s1          # hit, refcount 2
    s2 = pool.acquire(2)                  # second slot
    assert s2 is not None and s2 != s1
    assert pool.acquire(3) is None        # both mapped: admission blocks
    assert pool.stats["blocked_admissions"] == 1
    pool.release(2)                       # slot 2 parks in the LRU
    s3 = pool.acquire(3)                  # evicts tenant 2's slot
    assert s3 == s2 and pool.stats["evictions"] == 1
    assert not pool.resident(2) and pool.resident(3)
    # the evicted-then-reassigned slot must load tenant 3, and ONLY 3
    assert pool.take_pending() == [(s3, 3)]
    pool.check_invariants()


def test_pool_register_contracts():
    pool = AdapterPool(_pool_cfg())
    with pytest.raises(ValueError):
        pool.register(0, _weights())      # tenant 0 is the base model
    pool.register(1, _weights(rank=2), scale=0.5)   # true rank <= pool
    with pytest.raises(ValueError):
        pool.register(2, _weights(rank=8))          # rank > pool rank
    s = pool.acquire(1)
    assert s is not None
    with pytest.raises(ValueError):
        pool.register(1, _weights(seed=3))  # resident: slab would stale
    pool.release(1)
    with pytest.raises(KeyError):
        pool.acquire(9)                   # unregistered tenant
    assert pool.registered() == (1,)


def test_pool_property_random_churn():
    """Seeded random interleaving of every pool operation; the
    free/cached/mapped partition, refcounts, and registry bijection
    must hold after each step (the PagedKVCache property-test
    idiom)."""
    rng = np.random.RandomState(1234)
    pool = AdapterPool(_pool_cfg(slots=3))
    live = []                             # acquired (tenant) multiset
    registered = set()
    next_tenant = 1
    for step in range(400):
        op = rng.randint(4)
        if op == 0 and len(registered) < 12:
            pool.register(next_tenant, _weights(seed=next_tenant),
                          scale=0.25)
            registered.add(next_tenant)
            next_tenant += 1
        elif op == 1 and registered:
            t = int(rng.choice(sorted(registered)))
            s = pool.acquire(t)
            if s is not None:
                live.append(t)
        elif op == 2 and live:
            t = live.pop(rng.randint(len(live)))
            pool.release(t)
        elif op == 3:
            pool.take_pending()
        pool.check_invariants()
    for t in live:
        pool.release(t)
    pool.check_invariants()


def test_pool_byte_budget_sizes_slots():
    cfg = FFConfig(adapter_rank=4, adapter_pool_mb=0.5,
                   serve_max_seqs=8)
    ac = AdapterConfig.from_ff(cfg, num_layers=2, hidden=32,
                               num_heads=4, head_dim=8, ff_dim=64)
    assert ac.usable_slots == int(0.5 * (1 << 20)) // ac.slot_device_bytes
    assert ac.pool_bytes == ac.num_slots * ac.slot_bytes
    # sharded pools hold more tenants at the same per-chip budget
    ac2 = AdapterConfig.from_ff(cfg, num_layers=2, hidden=32,
                                num_heads=4, head_dim=8, ff_dim=64,
                                tensor_parallel=2)
    assert ac2.usable_slots > ac.usable_slots


# --------------------------------------------------------- engine e2e
VOCAB = 89


@pytest.fixture(scope="module")
def base_setup():
    """One adapter-armed engine + 3 registered tenants + the shared
    base params every merged-weight reference folds from."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   adapter_rank=4)
    lm = build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(lm)
    eng.warmup()
    adapters = make_tenant_adapters(num_layers=2, hidden=32,
                                    num_heads=4, head_dim=8, ff_dim=64,
                                    rank=4, tenants=3, seed=7)
    for t, (w, sc) in adapters.items():
        eng.register_adapter(t, w, scale=sc)
    return eng, adapters


def _merged_refs(eng, adapters, prompts, tenants, max_new):
    """Per-request greedy streams from the per-tenant merged-weight
    oracle (what a weight-swap server would emit)."""
    base = eng.params
    out = []
    try:
        for p, t in zip(prompts, tenants):
            if t == 0:
                eng.params = base
            else:
                w, sc = adapters[t]
                eng.params = merge_adapter_params(base, w, sc)
            out.append(eng.generate_reference([p], [max_new])[0])
    finally:
        eng.params = base
    return out


def test_mixed_tenant_batch_matches_merged_references(base_setup):
    """>= 3 adapters + base lanes decode in ONE mixed step and every
    stream equals its tenant's merged-weight reference, with zero
    recompiles — the tentpole acceptance gate."""
    eng, adapters = base_setup
    rng = np.random.RandomState(11)
    prompts = [list(rng.randint(1, VOCAB, size=rng.randint(4, 20)))
               for _ in range(6)]
    tenants = [1, 2, 3, 0, 2, 1]
    before = eng.compile_counts()
    out = eng.generate(prompts, 6, tenant_ids=tenants)
    assert eng.compile_counts() == before, "adapter serving recompiled"
    assert out == _merged_refs(eng, adapters, prompts, tenants, 6)
    st = eng.last_stats["adapter_pool"]
    assert st["resident_tenants"] == 3 and st["loads"] >= 3
    eng.adapters.check_invariants()


def test_arrival_order_invariant_and_topk1(base_setup):
    """Shuffled arrival order changes nothing: same per-tenant streams,
    still zero recompiles; top_k=1 sampling (argmax by construction)
    matches the greedy oracle through the sampling path."""
    eng, adapters = base_setup
    rng = np.random.RandomState(13)
    prompts = [list(rng.randint(1, VOCAB, size=rng.randint(4, 16)))
               for _ in range(5)]
    tenants = [3, 0, 1, 2, 3]
    refs = _merged_refs(eng, adapters, prompts, tenants, 5)
    before = eng.compile_counts()
    order = [4, 2, 0, 3, 1]
    out = eng.generate([prompts[i] for i in order], 5,
                       tenant_ids=[tenants[i] for i in order])
    assert out == [refs[i] for i in order]
    sampled = eng.generate(prompts, 5, tenant_ids=tenants,
                           temperature=0.7, top_k=1, sample_seed=3)
    assert sampled == refs
    assert eng.compile_counts() == before


def test_prefix_hits_stay_tenant_local(base_setup):
    """Two tenants sharing a byte-identical prompt prefix must NOT
    share pages (adapted K/V differs), while a same-tenant repeat
    still hits — and every stream stays exact."""
    eng, adapters = base_setup
    rng = np.random.RandomState(17)
    prefix = list(rng.randint(1, VOCAB, size=24))
    prompts = [prefix + list(rng.randint(1, VOCAB, size=4))
               for _ in range(4)]
    tenants = [1, 1, 2, 0]
    out = eng.generate(prompts, 5, tenant_ids=tenants)
    assert out == _merged_refs(eng, adapters, prompts, tenants, 5)
    # same-tenant pair shares the prefix; cross-tenant pairs must not,
    # so hits stay strictly below the all-shared ceiling
    st = eng.last_stats
    assert 0 < st["prefix_hit_tokens"] <= 24


def test_eviction_pressure_and_preemption_stay_exact():
    """A 2-slot pool serving 4 tenants over a KV pool small enough to
    preempt: adapter slots churn (evictions + blocked admissions),
    requests bounce and resume, and every stream still matches its
    merged-weight reference with zero recompiles."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=4, kv_num_pages=18,
                   serve_max_seqs=4, serve_prefill_budget=16,
                   adapter_rank=4, adapter_pool_mb=0.03)
    lm = build_transformer_lm(cfg, vocab_size=61, max_seq_len=48,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(lm)
    assert eng.adapter_cfg.usable_slots == 2
    eng.warmup()
    adapters = make_tenant_adapters(num_layers=2, hidden=32,
                                    num_heads=4, head_dim=8, ff_dim=64,
                                    rank=4, tenants=4, seed=23)
    for t, (w, sc) in adapters.items():
        eng.register_adapter(t, w, scale=sc)
    rng = np.random.RandomState(29)
    prompts = [list(rng.randint(1, 61, size=rng.randint(6, 16)))
               for _ in range(8)]
    tenants = [1, 2, 3, 4, 1, 3, 4, 2]
    max_new = [int(rng.randint(4, 10)) for _ in range(8)]
    before = eng.compile_counts()
    out = eng.generate(prompts, max_new, tenant_ids=tenants)
    assert eng.compile_counts() == before
    base = eng.params
    for i, (p, t) in enumerate(zip(prompts, tenants)):
        w, sc = adapters[t]
        eng.params = merge_adapter_params(base, w, sc)
        assert out[i] == eng.generate_reference([p], [max_new[i]])[0]
    eng.params = base
    pool = eng.last_stats["adapter_pool"]
    assert pool["evictions"] > 0
    eng.adapters.check_invariants()


def test_rank_padding_exact(base_setup):
    """A true-rank-2 adapter registered into the rank-4 pool decodes
    identically to its (unpadded) rank-2 merged reference: the padded
    rows/columns of zeros contribute exactly nothing."""
    eng, _ = base_setup
    w, sc = make_tenant_adapters(num_layers=2, hidden=32, num_heads=4,
                                 head_dim=8, ff_dim=64, rank=2,
                                 tenants=1, seed=41)[1]
    eng.register_adapter(7, w, scale=sc)
    rng = np.random.RandomState(43)
    prompts = [list(rng.randint(1, VOCAB, size=10)) for _ in range(2)]
    out = eng.generate(prompts, 6, tenant_ids=[7, 0])
    base = eng.params
    eng.params = merge_adapter_params(base, w, sc)
    ref = eng.generate_reference([prompts[0]], [6])[0]
    eng.params = base
    assert out[0] == ref
    assert out[1] == eng.generate_reference([prompts[1]], [6])[0]


def test_unregistered_tenant_rejected_at_submit(base_setup):
    eng, _ = base_setup
    with pytest.raises(ValueError, match="no registered adapter"):
        eng.generate([[1, 2, 3]], 3, tenant_ids=[99])
    # the failed submit must not leak pool state
    eng.adapters.check_invariants()


def test_legacy_path_refuses_adapters():
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=33,
                   serve_max_seqs=4, serve_prefill_budget=16,
                   adapter_rank=4)
    lm = build_transformer_lm(cfg, vocab_size=61, max_seq_len=32,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    with pytest.raises(ValueError, match="chunked"):
        ServeEngine(lm, chunked_prefill=False)


# ----------------------------------------------------------- tenancy
def test_tenant_salt_disjoint_keys():
    from flexflow_tpu.serve import prefix_page_keys
    toks = list(range(1, 33))
    base = prefix_page_keys(toks, 8, 4)
    t1 = prefix_page_keys(toks, 8, 4, prev=tenant_prefix_salt(1))
    t2 = prefix_page_keys(toks, 8, 4, prev=tenant_prefix_salt(2))
    assert tenant_prefix_salt(0) == b""
    assert base == prefix_page_keys(toks, 8, 4,
                                    prev=tenant_prefix_salt(0))
    assert not (set(base) & set(t1)) and not (set(t1) & set(t2))


# ------------------------------------------------------------ search
def test_cost_model_prices_adapters():
    from flexflow_tpu.search.cost_model import ServeArch, \
        serve_step_tasks, serve_device_bytes
    from flexflow_tpu.search.machine_model import (
        MachineSpec, TPUMachineModel)
    mm = TPUMachineModel(spec=MachineSpec.v5e(8))
    base = ServeArch(num_layers=2, hidden=256, num_heads=8,
                     head_dim=32, ff_dim=1024, vocab=32000)
    armed = ServeArch(num_layers=2, hidden=256, num_heads=8,
                      head_dim=32, ff_dim=1024, vocab=32000,
                      adapter_rank=8, adapter_slots=16)
    t_base = serve_step_tasks(base, 1, mm, lanes=8)
    t_armed = serve_step_tasks(armed, 1, mm, lanes=8)
    names = {t.name for t in t_armed}
    assert "adapter_gather" in names
    assert "adapter_gather" not in {t.name for t in t_base}
    # the LoRA matmul flops fold into the existing layer tasks
    by_name = {t.name: t for t in t_base}
    for t in t_armed:
        if t.name in by_name and t.name.startswith("l0"):
            assert t.seconds >= by_name[t.name].seconds
    assert sum(t.seconds for t in t_armed) \
        > sum(t.seconds for t in t_base)
    # the pool's HBM term scales with slots and shrinks with sharding
    assert serve_device_bytes(armed, 1) > serve_device_bytes(base, 1)
    assert serve_device_bytes(armed, 1) > serve_device_bytes(armed, 4)


def test_fingerprint_misses_on_adapter_knobs():
    """Regression gate: the cost-cache fingerprint folds both adapter
    knobs, so rows priced pre-adapters (or at another pool size) can
    never resurrect."""
    from flexflow_tpu.search.cost_model import ServeArch
    from flexflow_tpu.search.serve_place import _serve_fingerprint
    from flexflow_tpu.search.machine_model import (
        MachineSpec, TPUMachineModel)
    mm = TPUMachineModel(spec=MachineSpec.v5e(8))
    kw = dict(num_layers=2, hidden=256, num_heads=8, head_dim=32,
              ff_dim=1024, vocab=32000)
    fp0 = _serve_fingerprint(mm, ServeArch(**kw))
    fp1 = _serve_fingerprint(mm, ServeArch(adapter_rank=8,
                                           adapter_slots=16, **kw))
    fp2 = _serve_fingerprint(mm, ServeArch(adapter_rank=8,
                                           adapter_slots=32, **kw))
    assert len({fp0, fp1, fp2}) == 3
    # signature() carries the knobs too — the per-row key side
    s0 = ServeArch(**kw).signature()
    s1 = ServeArch(adapter_rank=8, adapter_slots=16, **kw).signature()
    assert s0 != s1


# ----------------------------------------------------- observability
def test_serve_metrics_tenant_label_and_adapter_counters(base_setup):
    from flexflow_tpu.utils.telemetry import serve_metrics
    eng, adapters = base_setup
    rng = np.random.RandomState(47)
    prompts = [list(rng.randint(1, VOCAB, size=8)) for _ in range(3)]
    eng.generate(prompts, 4, tenant_ids=[1, 2, 0])
    st = eng.last_stats
    m = serve_metrics(st)
    assert m.counter("serve_adapter_loads_total") \
        == st["adapter_pool"]["loads"]
    assert m.counter("serve_adapter_evictions_total") \
        == st["adapter_pool"]["evictions"]
    assert m.gauge("serve_adapter_registered_tenants") \
        == st["adapter_pool"]["registered_tenants"]
    # the tenant label folds like role=/replica=: labeled series only,
    # no double-count of the unlabeled aggregates
    m2 = serve_metrics(st, registry=m, tenant="1")
    assert m2.counter("serve_tokens_generated_total", tenant="1") \
        == st["total_new_tokens"]
    assert m2.counter("serve_tokens_generated_total") \
        == st["total_new_tokens"]


def test_serve_report_renders_adapter_block(base_setup):
    from flexflow_tpu.utils.profiling import serve_report
    eng, _ = base_setup
    rng = np.random.RandomState(53)
    eng.generate([list(rng.randint(1, VOCAB, size=8))], 3,
                 tenant_ids=[1])
    text = serve_report(eng.last_stats)
    assert "adapter pool:" in text and "adapter churn:" in text
