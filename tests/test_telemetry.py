"""Unified-telemetry suite (utils/telemetry.py, docs/observability.md).

Layered like the subsystem:
  * bus — ring-buffer bounding, metrics registry semantics, the
    nearest-rank quantile definition, Prometheus text parseability.
  * serve — telemetry on vs off is bit-identical tokens with ZERO
    recompiles (recording is pure host-side observation); the Chrome
    trace-event export is schema-valid (ts/dur/pid/tid well-formed,
    X spans nest per thread) with per-request-slot and per-engine-step
    tracks; lifecycle events survive preemption, speculation, retry,
    cancel and deadline — chaos runs stay traceable.
  * train — fit() with telemetry on trains to a bit-identical loss
    history; dispatch/fetch spans and the train drift sample land.
  * drift — the calibrator's predicted/measured accounting against a
    rigged cost model, threshold flagging both directions, and the
    regime cap.
  * reports — serve_report/train_report render FROM the canonical
    metrics fold, so the string numbers equal the exported snapshot.
  * profiling.trace — configurable log dir, returns the path, and
    degrades to a warning no-op when jax.profiler is unavailable.
"""

import json

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.serve import ServeEngine
from flexflow_tpu.utils.telemetry import (MetricsRegistry, Telemetry,
                                          pct, pow2_bucket,
                                          serve_metrics, telemetry_for)

VOCAB = 89


# --------------------------------------------------------------- bus
def test_ring_buffer_bounds_under_long_run():
    tel = Telemetry(max_events=64)
    for i in range(1000):
        tel.span(("p", "t"), f"s{i}", 0.0, 1.0)
        tel.metrics.inc("steps_total")
    assert len(tel.events) == 64
    assert tel.dropped_events == 1000 - 64
    # aggregates are NEVER dropped with events
    assert tel.metrics.counter("steps_total") == 1000


def test_disabled_telemetry_records_nothing():
    tel = Telemetry(enabled=False)
    tel.span(("p", "t"), "s", 0.0, 1.0)
    tel.instant(("p", "t"), "i")
    tel.counter(("p", "t"), "c", 1.0)
    tel.record_drift("d", "r", 1.0, 2.0)
    with tel.timed(("p", "t"), "x"):
        pass
    assert len(tel.events) == 0 and not tel.drift_snapshot()


def test_metrics_registry_semantics():
    m = MetricsRegistry()
    m.inc("a_total")
    m.inc("a_total", 2)
    m.inc("a_total", 5, site="x")
    m.set("g", 3.5)
    m.counter_set("abs_total", 7)
    m.counter_set("abs_total", 9)          # absolute, not additive
    for v in range(1, 101):
        m.observe("h_seconds", v / 100.0)
    assert m.counter("a_total") == 3
    assert m.counter("a_total", site="x") == 5
    assert m.gauge("g") == 3.5
    assert m.counter("abs_total") == 9
    assert m.hist_count("h_seconds") == 100
    # nearest-rank over the window — the shared pct() definition
    win = sorted(v / 100.0 for v in range(1, 101))
    assert m.quantile("h_seconds", 50) == pct(win, 50)
    assert m.quantile("h_seconds", 99) == pct(win, 99)
    snap = m.snapshot()
    assert snap["histograms"]["h_seconds"]["count"] == 100
    assert snap["histograms"]["h_seconds"]["p99"] == pct(win, 99)


def test_metrics_thread_safety_hammer():
    """The wall-clock fabric's contract: counter/gauge/histogram
    mutation and ring-buffer emission are lock-guarded — N threads
    hammering the SAME telemetry bus lose no counts, and concurrent
    snapshot/scrape reads never see a mid-iteration mutation."""
    import threading

    tel = Telemetry(max_events=256)
    m = tel.metrics
    n_threads, n_iter = 8, 400
    stop = threading.Event()
    read_errs = []

    def reader():
        # concurrent scrapes (the MetricsServer's live behavior):
        # any "dict changed size during iteration" lands here
        while not stop.is_set():
            try:
                m.snapshot()
                m.to_prometheus()
                tel.drift_snapshot()
            except Exception as e:
                read_errs.append(e)
                return

    def writer(t):
        for i in range(n_iter):
            m.inc("hammer_total")
            m.inc("hammer_total", 2, thread=str(t))
            m.set("hammer_gauge", float(i), thread=str(t))
            m.observe("hammer_seconds", i / n_iter)
            tel.span(("p", f"t{t}"), "s", 0.0, 1.0)
            tel.record_drift("hammer", "r", 1.0, 1.0 + i % 3)

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    stop.set()
    rt.join(timeout=10.0)
    assert not read_errs, read_errs
    # no lost counts, anywhere
    assert m.counter("hammer_total") == n_threads * n_iter
    for t in range(n_threads):
        assert m.counter("hammer_total", thread=str(t)) == 2 * n_iter
    assert m.hist_count("hammer_seconds") == n_threads * n_iter
    # ring stayed bounded, and drops were accounted exactly
    assert len(tel.events) == 256
    assert tel.dropped_events == n_threads * n_iter - 256
    d = tel.drift_snapshot()["hammer"]["r"]
    assert d["count"] == n_threads * n_iter


def test_prometheus_text_parses():
    import re
    m = MetricsRegistry()
    m.inc("serve_tokens_total", 42)
    m.inc("fault_fired_total", 2, site="serve.mixed", kind="transient")
    m.set("serve_tokens_per_sec", 123.4)
    for v in (0.1, 0.2, 0.3):
        m.observe("serve_ttft_seconds", v)
    text = m.to_prometheus()
    line_re = re.compile(
        r'^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* '
        r'(counter|gauge|summary)'
        r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9eE.+-]+)$')
    for line in text.splitlines():
        if line:
            assert line_re.match(line), line
    assert "serve_tokens_total 42" in text
    assert 'fault_fired_total{kind="transient",site="serve.mixed"} 2' \
        in text
    assert 'serve_ttft_seconds{quantile="0.5"}' in text
    assert "serve_ttft_seconds_count 3" in text


def test_pow2_bucket():
    assert [pow2_bucket(n) for n in (0, 1, 2, 3, 4, 5, 63, 64, 65)] \
        == [0, 1, 2, 4, 4, 8, 64, 64, 128]


def test_telemetry_for_config_resolution():
    assert not telemetry_for(None).enabled
    assert not telemetry_for(FFConfig()).enabled
    t = telemetry_for(FFConfig(telemetry=True,
                               telemetry_buffer_events=128,
                               telemetry_drift_threshold=0.25))
    assert t.enabled and t.max_events == 128 \
        and t.drift_threshold == 0.25
    # --trace-out alone also enables
    assert telemetry_for(FFConfig(trace_out="/tmp/t.json")).enabled
    # each enabled resolution is a FRESH bus; disabled is shared
    assert telemetry_for(FFConfig(telemetry=True)) is not t
    assert telemetry_for(FFConfig()) is telemetry_for(FFConfig())


def test_config_cli_flags():
    cfg = FFConfig(argv=["--telemetry", "--trace-out", "/tmp/x.json",
                         "--trace-dir", "/tmp/prof",
                         "--telemetry-buffer", "512",
                         "--drift-threshold", "0.75"])
    assert cfg.telemetry and cfg.trace_out == "/tmp/x.json"
    assert cfg.trace_dir == "/tmp/prof"
    assert cfg.telemetry_buffer_events == 512
    assert cfg.telemetry_drift_threshold == 0.75
    with pytest.raises(ValueError):
        FFConfig(telemetry_buffer_events=0)
    with pytest.raises(ValueError):
        FFConfig(telemetry_drift_threshold=-0.1)


# --------------------------------------------------------------- serve
@pytest.fixture(scope="module")
def lm():
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   serve_retry_backoff_s=0.0)
    return build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


def _prompts(rng, n, lo=4, hi=28):
    return [list(rng.randint(1, VOCAB, size=rng.randint(lo, hi)))
            for _ in range(n)]


def test_serve_on_off_identical_zero_recompiles(lm):
    """The observability contract: telemetry is pure observation —
    bit-identical tokens, zero recompiles, no state left behind."""
    rng = np.random.RandomState(0)
    prompts = _prompts(rng, 8)
    eng_off = ServeEngine(lm)
    eng_off.warmup()
    out_off = eng_off.generate(prompts, 6)
    tel = Telemetry()
    eng_on = ServeEngine(lm, telemetry=tel)
    counts = eng_on.warmup()
    out_on = eng_on.generate(prompts, 6)
    assert out_on == out_off
    assert eng_on.compile_counts() == counts
    assert len(tel.events) > 0
    # a second batch ACCUMULATES counters in the engine registry
    toks1 = tel.metrics.counter("serve_tokens_generated_total")
    out2 = eng_on.generate(prompts, 6)
    assert out2 == eng_off.generate(prompts, 6)
    assert tel.metrics.counter("serve_tokens_generated_total") > toks1
    assert eng_on.compile_counts() == counts


def _span_nesting_ok(events):
    """On each (pid, tid), X spans must be disjoint or properly
    nested — the Chrome trace model."""
    by_tid = {}
    for ev in events:
        if ev["ph"] == "X":
            by_tid.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], ev["ts"] + ev["dur"]))
    for spans in by_tid.values():
        spans.sort()
        stack = []
        for s, e in spans:
            while stack and s >= stack[-1] - 1e-6:
                stack.pop()
            assert not stack or e <= stack[-1] + 1e-6, (
                "spans overlap without nesting")
            stack.append(e)
    return True


def test_chrome_trace_schema_and_tracks(lm, tmp_path):
    tel = Telemetry()
    eng = ServeEngine(lm, telemetry=tel)
    eng.warmup()
    rng = np.random.RandomState(1)
    eng.generate(_prompts(rng, 6), 5)
    path = tel.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert evs and doc["displayTimeUnit"] == "ms"
    for ev in evs:
        assert ev["ph"] in ("X", "i", "M", "C", "b", "e")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str) and ev["name"]
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) \
                and ev["dur"] >= 0
    assert _span_nesting_ok(evs)
    threads = {ev["args"]["name"] for ev in evs
               if ev["ph"] == "M" and ev["name"] == "thread_name"}
    # one track per engine step stream + one per request slot + queue
    assert "engine" in threads and "queue" in threads
    assert any(t.startswith("slot ") for t in threads)
    names = {ev["name"] for ev in evs}
    assert {"step", "queue_wait"} <= names
    assert "prefill" in names or "decode" in names


def test_spans_through_preempt_spec_retry_cancel(lm):
    """Lifecycle events stay correct through the adversarial paths —
    and everything keeps working under fault injection (chaos runs are
    traceable)."""
    from flexflow_tpu.utils.faults import FaultInjector
    # tiny pool forces preemption; injected transients force retries
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=17,
                   serve_max_seqs=4, serve_prefill_budget=24,
                   serve_retry_backoff_s=0.0)
    from flexflow_tpu.models.transformer import build_transformer_lm
    ff = build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=40,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    tel = Telemetry()
    inj = FaultInjector("serve.mixed:transient@3,5", seed=0)
    eng = ServeEngine(ff, telemetry=tel, faults=inj, spec_tokens=4)
    eng.warmup()
    rng = np.random.RandomState(2)
    prompts = _prompts(rng, 8, lo=12, hi=30)
    deadlines = [None] * 8
    deadlines[3] = 1e-9

    def on_step(step):
        if step == 1:
            eng.cancel(2)  # rid 2: third submission of this batch

    out = eng.generate(prompts, 8, deadline_s=deadlines,
                       on_step=on_step)
    assert len(out) == 8
    st = eng.last_stats
    names = [e[2] for e in tel.events]
    if st["preemptions"]:
        assert "preempt" in names
        # a re-admitted victim emits a preempt->readmit span, NOT a
        # duplicate of its original queue_wait
        assert "requeue_wait" in names
    qb = [e for e in tel.events if e[0] == "b" and e[2] == "queue_wait"]
    idents = [e[5] for e in qb]
    assert len(idents) == len(set(idents)), (
        "duplicate queue_wait spans for one request")
    assert st["retries"] >= 1 and "retry" in names
    assert st["cancelled"] == 1 and "cancel" in names
    assert st["deadline_expired"] == 1 and "deadline_expired" in names
    if st["spec_drafted_tokens"]:
        assert "spec_verify" in names
    # fault observability satellite: fired sites land in the registry
    assert tel.metrics.counter("fault_fired_total", site="serve.mixed",
                               kind="transient") >= 2
    assert tel.metrics.counter("fault_site_hits_total",
                               site="serve.mixed") > 0
    # rung histogram exported per rung
    assert tel.metrics.counter("serve_rung_steps_total", rung=0) > 0
    # abort outcomes in the requests counter
    assert tel.metrics.counter("serve_requests_total",
                               outcome="cancelled") == 1
    assert tel.metrics.counter("serve_requests_total",
                               outcome="deadline_expired") == 1


def test_serve_drift_report_against_rigged_cost_model(lm, monkeypatch):
    """Rig the engine's per-regime predictor to a constant so the
    drift ratio is measured/constant exactly — and the flag fires on
    the configured threshold."""
    tel = Telemetry(drift_threshold=0.5)
    eng = ServeEngine(lm, telemetry=tel)
    eng.warmup()
    monkeypatch.setattr(  # 1 s/step predicted, no breakdown
        ServeEngine, "_drift_predicted",
        lambda self, *key: (1.0, None))
    rng = np.random.RandomState(3)
    eng.generate(_prompts(rng, 4), 4)
    snap = tel.drift_snapshot()
    assert snap.get("serve"), "no serve drift regimes"
    for reg, d in snap["serve"].items():
        assert d["predicted_ms_per_step"] == pytest.approx(1000.0)
        # CPU steps are milliseconds, so measured/predicted << 1/1.5
        assert d["ratio"] < 1.0 and d["flagged"]
        assert d["ratio"] == pytest.approx(
            d["measured_ms_per_step"] / d["predicted_ms_per_step"])
    rep = tel.drift_report()
    assert "DRIFT" in rep and "serve" in rep


def test_drift_threshold_flags_both_directions():
    tel = Telemetry(drift_threshold=0.5)
    tel.record_drift("d", "slow", predicted_s=1.0, measured_s=2.0)
    tel.record_drift("d", "fast", predicted_s=2.0, measured_s=1.0)
    tel.record_drift("d", "ok", predicted_s=1.0, measured_s=1.2)
    snap = tel.drift_snapshot()["d"]
    assert snap["slow"]["flagged"] and snap["fast"]["flagged"]
    assert not snap["ok"]["flagged"]
    # caller-supplied threshold overrides construction-time
    assert not tel.drift_snapshot(threshold=2.0)["d"]["slow"]["flagged"]
    assert tel.drift_report(threshold=2.0).count("DRIFT") == 0


def test_drift_regime_cap():
    tel = Telemetry()
    for i in range(Telemetry.MAX_DRIFT_REGIMES + 10):
        tel.record_drift("d", f"r{i}", 1.0, 1.0)
    assert len(tel.drift_snapshot()["d"]) == Telemetry.MAX_DRIFT_REGIMES
    assert tel.drift_regimes_dropped == 10
    assert "dropped" in tel.drift_report()


# --------------------------------------------------------------- train
def _fit_transformer(telemetry: bool):
    from flexflow_tpu import SGDOptimizer
    from flexflow_tpu.models.transformer import build_transformer
    cfg = FFConfig(batch_size=8)
    cfg.telemetry = telemetry
    ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=10)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    x = {"input": rng.randn(48, 16, 32).astype(np.float32)}
    y = rng.randint(0, 10, (48,)).astype(np.int32)
    hist = ff.fit(x, y, epochs=2, verbose=False)
    return ff, hist


def test_train_on_off_identical_with_spans_and_drift():
    ff_off, h_off = _fit_transformer(False)
    ff_on, h_on = _fit_transformer(True)
    assert [h["loss"] for h in h_on] == [h["loss"] for h in h_off]
    assert not ff_off.telemetry.enabled
    tel = ff_on.telemetry
    assert tel.enabled and len(tel.events) > 0
    names = [e[2] for e in tel.events]
    assert "dispatch" in names and "fetch_wait" in names
    assert any(n.startswith("epoch") for n in names)
    # train metrics folded into the registry train_report reads
    assert tel.metrics.counter("train_dispatches_total") == \
        ff_on.last_train_stats["dispatches"]
    # the train drift sample: measured wall/step vs the overlap graph.
    # Epoch 0 contains the cold jit compile and records NO sample
    # (compile seconds are not step time) — only epoch 1 lands.
    drift = tel.drift_snapshot().get("train", {})
    assert drift, "no train drift regime recorded"
    for d in drift.values():
        assert d["count"] == 1 and d["measured_ms_per_step"] > 0


def test_fit_trace_out_writes_chrome_trace(tmp_path):
    from flexflow_tpu import SGDOptimizer
    from flexflow_tpu.models.transformer import build_transformer
    path = str(tmp_path / "train_trace.json")
    cfg = FFConfig(batch_size=8)
    cfg.trace_out = path  # --trace-out implies telemetry
    ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=10)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    x = {"input": rng.randn(32, 16, 32).astype(np.float32)}
    y = rng.randint(0, 10, (32,)).astype(np.int32)
    ff.fit(x, y, epochs=1, verbose=False)
    with open(path) as f:
        doc = json.load(f)
    assert any(ev["name"] == "dispatch"
               for ev in doc["traceEvents"] if ev["ph"] == "X")


# --------------------------------------------------------------- reports
def test_serve_report_renders_from_metrics(lm):
    """The string report and the exported snapshot share one source:
    the percentile line is exactly the histogram's quantiles, the
    totals exactly the counters."""
    from flexflow_tpu.utils.profiling import serve_percentiles, \
        serve_report
    eng = ServeEngine(lm)
    eng.warmup()
    rng = np.random.RandomState(4)
    eng.generate(_prompts(rng, 6), 6)
    stats = eng.last_stats
    m = serve_metrics(stats)
    rep = serve_report(stats)
    p50 = m.quantile("serve_tpot_seconds", 50)
    p99 = m.quantile("serve_tpot_seconds", 99)
    assert f"p50={p50*1e3:.3f} ms" in rep
    assert f"p99={p99*1e3:.3f} ms" in rep
    assert (f"total: {m.counter('serve_tokens_generated_total'):.0f} "
            f"tokens") in rep
    assert serve_percentiles(stats) == {50: p50, 99: p99}
    # and the same fold feeds the Prometheus page
    assert "serve_tokens_per_sec" in m.to_prometheus()


def test_train_report_renders_from_metrics():
    from flexflow_tpu.utils.profiling import train_report
    from flexflow_tpu.utils.telemetry import train_metrics
    ff, _ = _fit_transformer(False)
    st = ff.last_train_stats
    m = train_metrics(st)
    rep = train_report(st)
    assert (f"train: {m.counter('train_dispatches_total'):.0f} "
            f"dispatches") in rep
    assert train_report({}) == "train: no stats recorded"


# --------------------------------------------------------------- trace()
def test_profiling_trace_resolves_dir_and_degrades(tmp_path,
                                                   monkeypatch):
    from flexflow_tpu.utils import profiling

    # graceful no-op when jax.profiler refuses (e.g. backend without
    # trace support): one warning, the context still yields the path
    def boom(path):
        raise RuntimeError("no profiler on this backend")

    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.warns(UserWarning, match="no-op"):
        with profiling.trace(str(tmp_path / "t")) as got:
            assert got == str(tmp_path / "t")
    # config-resolved dir (the --trace-dir satellite)
    cfg = FFConfig(trace_dir=str(tmp_path / "cfg_dir"))
    with pytest.warns(UserWarning):
        with profiling.trace(config=cfg) as got:
            assert got == str(tmp_path / "cfg_dir")
    # default when nothing is configured
    with pytest.warns(UserWarning):
        with profiling.trace() as got:
            assert got == profiling.DEFAULT_TRACE_DIR


def test_profiling_trace_real_backend(tmp_path):
    """On the CPU backend jax.profiler works: the trace directory is
    created and the path returned."""
    import os
    import warnings as w

    from flexflow_tpu.utils import profiling
    d = str(tmp_path / "real")
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        with profiling.trace(d) as got:
            assert got == d
    if any("no-op" in str(r.message) for r in rec):
        pytest.skip("jax.profiler unavailable in this environment")
    assert os.path.isdir(d)


# --------------------------------------------------------------- chaos
def test_chaos_run_emits_trace_and_fault_metrics(lm, tmp_path):
    """docs/robustness.md: chaos runs emit traces — the full seeded
    chaos interleaving with telemetry on stays token-correct for the
    survivors and leaves an inspectable trace + fault registry."""
    from flexflow_tpu.utils.faults import FaultInjector
    tel = Telemetry()
    inj = FaultInjector(
        "serve.mixed:transient@2,4;serve.page_pressure:exhaust:0.8@2-6",
        seed=7)
    eng = ServeEngine(lm, telemetry=tel, faults=inj)
    eng.warmup()
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, 6)
    out = eng.generate(prompts, 5, on_step=lambda s:
                       eng.cache.check_invariants())
    ref = ServeEngine(lm).generate_reference(prompts, 5)
    st = eng.last_stats
    for o, r, rec in zip(out, ref, st["requests"]):
        if rec["outcome"] == "completed":
            assert o == r
    assert st["retries"] >= 1
    assert tel.metrics.counter("fault_fired_total", site="serve.mixed",
                               kind="transient") >= 1
    assert tel.metrics.counter("fault_fired_total",
                               site="serve.page_pressure",
                               kind="exhaust") >= 1
    path = tel.export_chrome_trace(str(tmp_path / "chaos.json"))
    with open(path) as f:
        doc = json.load(f)
    assert any(ev["name"] == "retry" for ev in doc["traceEvents"])


def test_unwritable_trace_out_does_not_fail_generate(lm, tmp_path):
    """An unwritable --trace-out path must not fail a generate() that
    already produced tokens (the same promise fit() makes)."""
    tel = Telemetry()
    eng = ServeEngine(lm, telemetry=tel)
    eng.warmup()
    eng.trace_out = str(tmp_path / "no_such_dir" / "trace.json")
    rng = np.random.RandomState(11)
    prompts = _prompts(rng, 4)
    out = eng.generate(prompts, 4)
    assert out == ServeEngine(lm).generate_reference(prompts, 4)


def test_fault_aborted_generate_still_flushes_trace(lm, tmp_path):
    """A run a fatal fault kills mid-flight still leaves the Chrome
    trace and the fault registry behind — the failing chaos replay is
    inspectable post-hoc (docs/robustness.md)."""
    from flexflow_tpu.utils.faults import FaultInjector, InjectedFault
    tel = Telemetry()
    inj = FaultInjector("serve.mixed:fatal@2", seed=0)
    eng = ServeEngine(lm, telemetry=tel)
    eng.warmup()
    eng.faults = inj  # armed after warmup: step 1 runs, step 2 dies
    path = str(tmp_path / "aborted.json")
    eng.trace_out = path
    rng = np.random.RandomState(12)
    with pytest.raises(InjectedFault):
        eng.generate(_prompts(rng, 4), 6)
    with open(path) as f:
        doc = json.load(f)
    assert any(ev["name"] == "step" for ev in doc["traceEvents"])
    assert tel.metrics.counter("fault_fired_total", site="serve.mixed",
                               kind="fatal") == 1
