"""Round-2 auto-parallelization upgrades (VERDICT items 4/5/6):

- device-explicit placement in the strategy space (reference
  `ParallelConfig.device_ids`, include/config.h:47-73; DLRM per-table
  strategies examples/cpp/DLRM/strategies/dlrm_strategy.cc:1-50),
- per-device compute resources + GPipe event-loop expansion in the
  simulator (reference event loop simulator.cc:330-629),
- mesh-factorization ("degree") search (reference
  get_random_parallel_config samples part counts, model.cc:512).
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, Strategy, make_mesh
from flexflow_tpu.models import build_dlrm
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy
from flexflow_tpu.search.cost_model import PipelineCost
from flexflow_tpu.search.mcmc import (
    enumerate_mesh_shapes,
    optimize,
    optimize_with_mesh,
)
from flexflow_tpu.search.simulator import Simulator, TaskGraph


# ---------------------------------------------------------------- pipeline

def build_pipe_model(num_layers=4, num_microbatches=4, batch=64,
                     hidden=256):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.enable_pipeline_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((batch, hidden), name="input")

    def block(sub, t):
        h = sub.dense(t, hidden, activation="relu", name="blk_ff")
        return sub.add(h, t, name="blk_res")

    t = ff.pipeline_blocks(x, block, num_layers,
                           num_microbatches=num_microbatches)
    t = ff.softmax(ff.dense(t, 4, name="head"), name="sm")
    return ff


def pp_strategy():
    return Strategy(default=OpStrategy({"sample": "data",
                                        "layer": "pipe"}))


def test_gpipe_expansion_exact_makespan():
    """The event-loop expansion must reproduce the GPipe schedule exactly:
    with uniform stages and no hop cost, forward takes (M+S-1) ticks and
    backward another (M+S-1) ticks after the forward join.

    Note on wall-clock validation: on the forced 8-device CPU platform
    all "devices" share the same physical cores, so the bubble the
    schedule models never appears in measured step time (measured
    M=2 vs M=8 ratio ~1.05 where disjoint hardware would show ~1.8) —
    schedule structure is validated exactly here, and absolute
    simulator-vs-real time is validated on real hardware by the
    TPU-gated calibration test (test_calibration_tpu.py)."""
    S, M, f, b = 4, 6, 1.0, 2.0
    pc = PipelineCost(stages=S, microbatches=M, fwd_stage=f, bwd_stage=b,
                      hop=0.0)
    sim = Simulator.__new__(Simulator)  # only the expansion methods used
    g = TaskGraph()
    exits = {}
    join_f = sim._expand_pipeline_fwd(g, "u", pc, [], exits)
    sim._expand_pipeline_bwd(g, "u", pc, [join_f], exits["u"])
    makespan = g.simulate()
    assert makespan == pytest.approx((M + S - 1) * (f + b)), makespan


def test_pipeline_sim_bubble_shrinks_with_microbatches():
    """At compute-dominant shapes more microbatches shrink the bubble.
    (At tiny shapes the per-hop ICI latency rightly dominates and MORE
    microbatches lose — the tradeoff the event loop models and the old
    closed form could not.)"""
    mesh = make_mesh((2, 4), ("data", "pipe"))
    times = {}
    for m in (2, 8):
        ff = build_pipe_model(num_layers=8, num_microbatches=m,
                              batch=1024, hidden=4096)
        sim = Simulator(ff, mesh)
        times[m] = sim.simulate(pp_strategy())
    assert times[8] < times[2], times


def test_pipeline_sim_pp_speeds_up_deep_stack():
    """Mapping layer->pipe divides per-device compute by the stage count;
    the simulated step must improve despite the bubble (the pre-round-2
    closed form priced PP as a pure slowdown — VERDICT weak #4)."""
    ff = build_pipe_model(num_layers=8, num_microbatches=8, batch=4096,
                          hidden=4096)
    mesh = make_mesh((1, 4), ("data", "pipe"))
    sim = Simulator(ff, mesh)
    t_pp = sim.simulate(pp_strategy())
    t_stack = sim.simulate(Strategy())  # layer unmapped: one-device scan
    assert t_pp < t_stack, (t_pp, t_stack)


def test_pipeline_event_loop_close_to_closed_form():
    """The native engine keeps the closed-form GPipe makespan; the Python
    event loop must stay close on a pure pipeline (same model, bounded
    divergence) so the engines rank candidates consistently."""
    ff = build_pipe_model(num_layers=8, num_microbatches=4, batch=256,
                          hidden=1024)
    mesh = make_mesh((1, 4), ("data", "pipe"))
    sim = Simulator(ff, mesh)
    strat = pp_strategy()
    t_loop = sim.simulate(strat)
    # closed form from the op costs (what the native lowering sees)
    total = 0.0
    for op in ff.ops:
        c = sim._op_cost(op, strat)
        total += c.fwd + c.bwd + c.fwd_comm + c.bwd_comm
    # the loop schedules M*(S-1) real hops vs the form's (M+S-1), so a
    # comm-heavy shape diverges upward; a compute-heavy one downward
    # (overlap). Bounded either way keeps the engines' rankings close.
    assert total * 0.5 <= t_loop <= total * 1.5, (t_loop, total)


# ------------------------------------------------------- device placement

def vocab_sharded(ff):
    s = Strategy()
    for op in ff.ops:
        if op.op_type == "embedding":
            s.set(op.name, OpStrategy({"vocab": "model"}))
    return s


def table_placed(ff, n_dev):
    s = Strategy()
    k = 0
    for op in ff.ops:
        if op.op_type == "embedding":
            s.set(op.name, OpStrategy({DEVICE_KEY: (k % n_dev,)}))
            k += 1
    return s


def build_dlrm_for_search(vocab=100_000, batch=1024):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.enable_parameter_parallel = True
    # device-explicit candidates are opt-in (they execute as replication
    # under GSPMD; the executable form is distributed_embedding)
    cfg.enable_device_placement = True
    # the placement economics being tested are the REFERENCE's: dense
    # table-gradient updates (its scatter-add grad region + optimizer
    # sweep). With the executor's sparse-update path the cost model
    # prices embeddings at touched-row traffic and placement stops
    # mattering — which is the correct answer, but not this scenario.
    cfg.sparse_embedding_updates = False
    return build_dlrm(cfg, batch_size=batch,
                      embedding_vocab_sizes=(vocab,) * 8)


def test_per_table_placement_beats_vocab_sharding_simulated():
    """The reference's DLRM headline: one table per device beats sharding
    every table (concurrent lookups + an all-gather instead of a
    serialized psum per table)."""
    ff = build_dlrm_for_search()
    mesh = make_mesh((1, 8), ("data", "model"))
    sim = Simulator(ff, mesh)
    t_vocab = sim.simulate(vocab_sharded(ff))
    t_placed = sim.simulate(table_placed(ff, 8))
    assert t_placed < t_vocab, (t_placed, t_vocab)


def test_search_places_tables_across_devices():
    """VERDICT #4 done-condition: search places the 8 tables across the 8
    devices and beats vocab-sharding in simulated time."""
    ff = build_dlrm_for_search()
    mesh = make_mesh((1, 8), ("data", "model"))
    ff.mesh = mesh
    best = optimize(ff, budget=600, alpha=0.05, mesh=mesh, seed=0)
    sim = Simulator(ff, mesh)
    assert sim.simulate(best) <= sim.simulate(vocab_sharded(ff))
    placed_devs = [best.for_op(op.name).device_ids
                   for op in ff.ops if op.op_type == "embedding"]
    placed_devs = [d for d in placed_devs if d]
    assert len(placed_devs) >= 4, placed_devs
    # round-robin candidates spread over distinct devices
    assert len({d[0] for d in placed_devs}) == len(placed_devs)


def test_placed_strategy_roundtrips_via_json(tmp_path):
    ff = build_dlrm_for_search()
    s = table_placed(ff, 8)
    path = str(tmp_path / "strategy.json")
    s.save(path)
    loaded = Strategy.load(path)
    emb = next(op.name for op in ff.ops if op.op_type == "embedding")
    assert loaded.for_op(emb).device_ids == s.for_op(emb).device_ids
    assert isinstance(loaded.for_op(emb).device_ids, tuple)


def test_placed_strategy_roundtrips_via_reference_text(tmp_path):
    """The reference text format carries explicit device ids natively
    (strategy.cc:95-189; DLRM strategy files pin tables by id) — placed
    strategies must survive export/import through it."""
    from flexflow_tpu.parallel.strategy_io import (
        load_strategies_from_file,
        save_strategies_to_file,
    )

    ff = build_dlrm_for_search()
    mesh = make_mesh((1, 8), ("data", "model"))
    s = table_placed(ff, 8)
    path = str(tmp_path / "strategy.txt")
    save_strategies_to_file(ff, s, mesh, path)
    loaded = load_strategies_from_file(ff, mesh, path)
    for op in ff.ops:
        if op.op_type == "embedding":
            assert loaded.for_op(op.name).device_ids == \
                s.for_op(op.name).device_ids, op.name


def run_native_parity(ff, mesh, seed, rounds=6, require=None):
    """Shared native-vs-Python engine parity harness: lower the
    candidate space, draw `rounds` random assignments, assert identical
    simulated cost through both engines.

    `require=(predicate, label)`: at least one draw per run MUST
    exercise a candidate matching the predicate — the matching index is
    FORCED into every draw for some op that has one, so enumeration
    reorders or RNG-consumption changes can never silently void the
    coverage the test exists for."""
    from flexflow_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    from flexflow_tpu.native.wrappers import simulate_assignment
    from flexflow_tpu.search.mcmc import candidate_maps
    from flexflow_tpu.search.native_search import lower_to_arrays

    sim = Simulator(ff, mesh)
    cands = {op.name: candidate_maps(op, mesh, ff.config, op_index=i)
             for i, op in enumerate(ff.ops)}
    table, edges, _, _, cand_lists = lower_to_arrays(
        ff, sim, cands, Strategy())

    forced = None  # (op_index, [matching candidate indices])
    if require is not None:
        pred, label = require
        for oi, lst in enumerate(cand_lists):
            matches = [j for j, m in enumerate(lst) if pred(m)]
            if matches:
                forced = (oi, matches)
                break
        assert forced is not None, f"no candidate matches {label!r}"

    rng = np.random.RandomState(seed)
    for r in range(rounds):
        assign = [rng.randint(len(lst)) for lst in cand_lists]
        if forced is not None:
            assign[forced[0]] = forced[1][r % len(forced[1])]
        strat = Strategy()
        for i, op in enumerate(ff.ops):
            strat.set(op.name, OpStrategy(dict(cand_lists[i][assign[i]])))
        want = sim.simulate(strat)
        got = simulate_assignment(table, edges, assign, sim.overlap,
                                  sim.mm.spec.hbm_capacity,
                                  sim.time_scale,
                                  step_overhead=sim.step_overhead)
        assert got == pytest.approx(want, rel=1e-9), assign


def test_native_engine_parity_with_placement_candidates():
    """The native engine mirrors the Python simulator task-for-task,
    including per-device resources for placed candidates — random
    assignments over the DLRM placement space must cost identically in
    both engines (csrc/mcmc.cc simulate_assignment)."""
    ff = build_dlrm_for_search()
    mesh = make_mesh((1, 8), ("data", "model"))
    run_native_parity(ff, mesh, seed=7,
                      require=(lambda m: DEVICE_KEY in m, "placed"))


def test_native_engine_parity_with_pipeline_expansion():
    """GPipe event-loop expansion parity: pipelined candidates must cost
    identically through the native and Python engines."""
    ff = build_pipe_model(num_layers=4, num_microbatches=4)
    mesh = make_mesh((2, 4), ("data", "pipe"))
    run_native_parity(ff, mesh, seed=3, rounds=8,
                      require=(lambda m: m.get("layer") == "pipe",
                               "pipelined"))


# ----------------------------------------------------------- degree search

def build_tp_heavy(batch=8, hidden=8192):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((batch, hidden), name="input")
    t = ff.dense(x, hidden, activation="relu", name="big1")
    t = ff.dense(t, hidden, activation="relu", name="big2")
    t = ff.dense(t, 10, name="head")
    t = ff.softmax(t)
    return ff


def test_enumerate_mesh_shapes_uses_gates():
    ff = build_tp_heavy()
    shapes = enumerate_mesh_shapes(8, ff, ff.config)
    assert {"data": 8} in shapes
    assert {"data": 4, "model": 2} in shapes
    assert {"data": 1, "model": 8} in shapes
    ff.config.enable_parameter_parallel = False
    assert enumerate_mesh_shapes(8, ff, ff.config) == [{"data": 8}]


def test_mesh_shape_search_finds_tp_degree():
    """VERDICT #5 done-condition: given 8 devices and a TP-heavy model,
    the search returns a mesh with a model axis (dp4xtp2 / dp2xtp4 /
    tp8) over pure dp8 without the user pre-choosing the mesh."""
    ff = build_tp_heavy()
    strat, mesh = optimize_with_mesh(ff, budget=400, seed=0)
    assert mesh.shape.get("model", 1) >= 2, dict(mesh.shape)
    big_maps = [strat.for_op(n).axis_map for n in ("big1", "big2")]
    assert any(m.get("channel_out") == "model" for m in big_maps), big_maps


def test_mesh_shape_search_wired_into_compile():
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.search_budget = 60
    cfg.search_mesh_shapes = True
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 64), name="input")
    t = ff.dense(x, 256, activation="relu")
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    assert ff.mesh is not None and int(ff.mesh.size) == 8
    rng = np.random.RandomState(0)
    m = ff.train_batch({"input": rng.randn(16, 64).astype(np.float32),
                        "label": rng.randint(0, 4, 16).astype(np.int32)})
    assert np.isfinite(float(m["loss"]))


def test_conv_specific_efficiency_prices_conv_ops():
    """Conv strategies are ranked by a conv-specific MEASURED factor,
    not the big-GEMM guess (VERDICT r2 #3; reference conv_2d.cu:173-260
    measures per-shape conv algorithms)."""
    from flexflow_tpu.search.cost_model import op_cost
    from flexflow_tpu.search.machine_model import default_machine_model
    from flexflow_tpu.parallel.pconfig import OpStrategy
    from flexflow_tpu import make_mesh

    cfg = FFConfig()
    cfg.batch_size = 64
    ff = FFModel(cfg)
    # channel-heavy shape so the op is MXU-bound (a 3-channel input conv
    # is memory-bound and the MXU factor never shows in the roofline max)
    x = ff.create_tensor((64, 64, 32, 32), name="input")
    ff.conv2d(x, 128, 3, 3, 1, 1, 1, 1, name="c1")
    conv = ff.ops[0]
    mesh = make_mesh((8,), ("data",))
    mm = default_machine_model(mesh)
    mm.efficiency["conv"] = 0.45
    base = op_cost(conv, OpStrategy({"sample": "data"}), mesh, mm).fwd
    mm.efficiency["conv"] = 0.9  # doubling conv efficiency must show up
    fast = op_cost(conv, OpStrategy({"sample": "data"}), mesh, mm).fwd
    assert fast < base, (fast, base)
    # and the matmul factor alone must NOT move conv cost
    mm.efficiency["matmul"] = 0.05
    still = op_cost(conv, OpStrategy({"sample": "data"}), mesh, mm).fwd
    assert still == fast, (still, fast)


def test_measure_conv_efficiency_smoke():
    """The conv microbenchmark itself runs (CPU: value meaningless but
    must be a sane fraction and not crash the calibration ladder)."""
    from flexflow_tpu.search import measure
    from flexflow_tpu.search.machine_model import default_machine_model

    mm = default_machine_model(None)
    eff = measure.measure_conv_efficiency(mm, repeats=1)
    assert 0.0 < eff <= 1.0


def test_native_engine_parity_with_per_table_placement():
    """Per-TABLE device-id tuples (the executable DLRM placement form,
    r3) must cost identically through the native and Python engines —
    the tuple length (num_tables) differs from whole-op pins and from
    n_devices, exercising the native placement arrays' general case."""
    cfg = FFConfig()
    cfg.batch_size = 1024
    cfg.enable_parameter_parallel = True
    cfg.enable_device_placement = True
    cfg.sparse_embedding_updates = False
    ff = build_dlrm(cfg, batch_size=1024,
                    embedding_vocab_sizes=(100_000,) * 8,
                    stacked_tables=True)
    mesh = make_mesh((1, 8), ("data", "model"))
    run_native_parity(
        ff, mesh, seed=11,
        require=(lambda m: DEVICE_KEY in m and len(m[DEVICE_KEY]) == 8,
                 "per-table placement"))
