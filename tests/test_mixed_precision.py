"""Mixed-precision policy (FFConfig.compute_dtype / param_dtype).

What the policy promises (docs/performance.md):
  * bf16-vs-f32 LOSS PARITY within tolerance on transformer + DLRM —
    f32 master weights keep the walk on the f32 trajectory;
  * master params and optimizer state VERIFIABLY stay f32 while
    step-internal activations/params run at compute_dtype;
  * flash attention takes bf16 inputs with f32 LSE/accumulation on
    both the pallas-interpret and jnp paths;
  * the cost stack prices dtypes (per-dtype peak, itemsize bytes) and
    the persistent cost cache MISSES on a precision flip.
"""

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.core.optimizers import AdamOptimizer  # noqa: E402
from flexflow_tpu.models.dlrm import build_dlrm  # noqa: E402
from flexflow_tpu.models.transformer import build_transformer  # noqa: E402

PARITY_TOL = 0.05  # relative to the running loss (see tools/mp_bench.py)


def small_transformer(compute_dtype, **cfg_kw):
    cfg = FFConfig(batch_size=8)
    cfg.compute_dtype = compute_dtype
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    ff = build_transformer(cfg, batch_size=8, seq_len=32, hidden=64,
                           num_heads=4, num_layers=2, ff_dim=128,
                           num_classes=10, layer_norm=True)
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def transformer_batch(seed=0):
    rng = np.random.RandomState(seed)
    return {"input": rng.randn(8, 32, 64).astype(np.float32),
            "label": rng.randint(0, 10, 8).astype(np.int32)}


def train_curve(ff, batch, steps=8):
    out = [float(ff.train_batch(batch)["loss"]) for _ in range(steps)]
    assert all(np.isfinite(out)), out
    return out


def assert_f32_masters(ff):
    for leaf in jax.tree_util.tree_leaves(ff.state.params):
        assert str(leaf.dtype) == "float32", leaf.dtype
    for leaf in jax.tree_util.tree_leaves(ff.state.opt_state):
        assert str(leaf.dtype) == "float32", leaf.dtype


# ---------------------------------------------------------------- parity

def test_transformer_bf16_parity_and_f32_masters():
    batch = transformer_batch()
    cf = train_curve(small_transformer("float32"), batch)
    ffb = small_transformer("bfloat16")
    cb = train_curve(ffb, batch)
    assert_f32_masters(ffb)
    for a, b in zip(cf, cb):
        assert abs(a - b) <= PARITY_TOL * max(1.0, abs(a)), (cf, cb)
    # training actually happened (not two flat curves agreeing)
    assert cb[-1] < cb[0] - 0.5


def test_dlrm_bf16_parity_sparse_embeddings():
    """DLRM exercises the sparse-embedding row-update path: the row
    gather feeds bf16 forward, row grads scatter into the f32 master
    table."""
    rng = np.random.RandomState(0)
    batch = {"dense_features": rng.randn(32, 13).astype(np.float32),
             "label": rng.randint(0, 2, (32, 1)).astype(np.float32)}
    for i in range(8):
        batch[f"sparse_{i}"] = rng.randint(0, 1000, (32, 1)).astype(
            np.int32)

    def build(dt):
        cfg = FFConfig(batch_size=32)
        cfg.compute_dtype = dt
        ff = build_dlrm(cfg, batch_size=32,
                        embedding_vocab_sizes=(1000,) * 8)
        ff.compile(loss_type="binary_crossentropy", metrics=[])
        assert ff.executor._sparse_table_ops(), \
            "sparse-update path must be active for this test"
        return ff

    cf = train_curve(build("float32"), batch)
    ffb = build("bfloat16")
    cb = train_curve(ffb, batch)
    assert_f32_masters(ffb)
    for a, b in zip(cf, cb):
        assert abs(a - b) <= PARITY_TOL * max(1.0, abs(a)), (cf, cb)


def test_adam_masters_stay_f32_under_bf16():
    cfg = FFConfig(batch_size=8)
    cfg.compute_dtype = "bfloat16"
    ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                           num_heads=2, num_layers=1, ff_dim=64,
                           num_classes=4, layer_norm=True)
    ff.compile(optimizer=AdamOptimizer(lr=1e-3),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"input": rng.randn(8, 16, 32).astype(np.float32),
             "label": rng.randint(0, 4, 8).astype(np.int32)}
    for _ in range(3):
        ff.train_batch(batch)
    assert_f32_masters(ff)
    # Adam's m/v advanced (they are live f32 state, not dead zeros)
    m_norm = sum(float(jnp.abs(a).sum()) for a in
                 jax.tree_util.tree_leaves(ff.state.opt_state["m"]))
    assert m_norm > 0.0


# --------------------------------------------- step-internal activations

def test_step_internals_run_at_compute_dtype():
    """forward_values (the walked graph inside every jitted step) casts
    master params + float inputs down, so intermediate tensor values
    carry compute_dtype."""
    ff = small_transformer("bfloat16")
    ex = ff.executor
    batch = ex.shard_batch(transformer_batch())
    # the loader-side cast already happened: declared float inputs are
    # compute-dtype on device
    assert batch["input"].dtype == jnp.bfloat16
    values, _ = ex.forward_values(ff.state.params, ff.state.states,
                                  batch, training=False, rng=None)
    float_dts = {str(v.dtype) for v in values.values()
                 if jnp.issubdtype(v.dtype, jnp.floating)}
    assert float_dts == {"bfloat16"}, float_dts
    # while the masters it read stayed f32
    assert_f32_masters(ff)

    # embedding-bearing graph: Embedding pins an out_dtype (f32 by
    # default) — the walk must keep the value stream at compute_dtype
    # or everything downstream of a table silently upcasts
    cfg = FFConfig(batch_size=8)
    cfg.compute_dtype = "bfloat16"
    ffd = build_dlrm(cfg, batch_size=8,
                     embedding_vocab_sizes=(100,) * 4)
    ffd.compile(loss_type="binary_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"dense_features": rng.randn(8, 13).astype(np.float32)}
    for i in range(4):
        batch[f"sparse_{i}"] = rng.randint(0, 100, (8, 1)).astype(
            np.int32)
    batch = ffd.executor.shard_batch(batch)
    values, _ = ffd.executor.forward_values(
        ffd.state.params, ffd.state.states, batch, training=False,
        rng=None)
    float_dts = {str(v.dtype) for v in values.values()
                 if jnp.issubdtype(v.dtype, jnp.floating)}
    assert float_dts == {"bfloat16"}, float_dts


def test_declared_input_dtypes_follow_policy():
    ff32 = small_transformer("float32")
    ffb = small_transformer("bfloat16")
    assert ff32.executor.declared_input_dtypes["input"] == jnp.float32
    assert ffb.executor.declared_input_dtypes["input"] == jnp.bfloat16


def test_bn_statistics_stay_f32_under_bf16():
    cfg = FFConfig(batch_size=8)
    cfg.compute_dtype = "bfloat16"
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 4, 8, 8), name="input")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c0")
    t = ff.batch_norm(t, name="bn0")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t, name="sm")
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"input": rng.randn(8, 4, 8, 8).astype(np.float32),
             "label": rng.randint(0, 4, 8).astype(np.int32)}
    ff.train_batch(batch)
    bn = ff.state.states["bn0"]
    assert str(bn["running_mean"].dtype) == "float32"
    assert str(bn["running_var"].dtype) == "float32"
    # and the stats moved off their init values
    assert float(jnp.abs(bn["running_mean"]).sum()) > 0.0


# ------------------------------------------------------------- pipelines

@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_bf16_parity(schedule):
    """GPipe and 1F1B over a dp2 x pp2 mesh: packed rows stay f32
    masters, the wire carries bf16 activations, losses track f32."""
    from flexflow_tpu import make_mesh

    def build(dt):
        cfg = FFConfig(batch_size=16)
        cfg.compute_dtype = dt
        cfg.pipeline_stages = 2
        cfg.pipeline_microbatches = 4
        cfg.pipeline_schedule = schedule
        mesh = make_mesh((2, 2), ("data", "pipe"))
        ff = FFModel(cfg, mesh=mesh)
        x = ff.create_tensor((16, 32), name="input")
        t = ff.dense(x, 64, activation="relu", name="fc1")
        t = ff.dense(t, 64, activation="relu", name="fc2")
        t = ff.dense(t, 48, activation="relu", name="fc3")
        t = ff.dense(t, 10, name="fc4")
        ff.softmax(t, name="sm")
        ff.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    rng = np.random.RandomState(0)
    batch = {"input": rng.randn(16, 32).astype(np.float32),
             "label": rng.randint(0, 10, 16).astype(np.int32)}
    cf = train_curve(build("float32"), batch, steps=3)
    ffb = build("bfloat16")
    cb = train_curve(ffb, batch, steps=3)
    for a, b in zip(cf, cb):
        assert abs(a - b) <= PARITY_TOL * max(1.0, abs(a)), (cf, cb)
    # packed master rows stay f32
    from flexflow_tpu.core.staged import PACKED
    for a in ffb.state.params[PACKED].values():
        assert str(a.dtype) == "float32"


def test_pipeline_wire_carries_compute_dtype():
    from flexflow_tpu.parallel.graph_pipeline import (_wire_layouts,
                                                      balanced_stages,
                                                      build_stage_plan)
    cfg = FFConfig(batch_size=8)
    cfg.compute_dtype = "bfloat16"
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), name="input")
    t = ff.dense(x, 16, name="a")
    t = ff.dense(t, 16, name="b")
    ff.softmax(t, name="sm")
    plan = build_stage_plan(ff, balanced_stages(ff, 2))
    _, widths = _wire_layouts(plan, ff)
    assert set(widths) == {"bfloat16"}, widths
    # and without a policy the wire stays at the declared dtype
    cfg2 = FFConfig(batch_size=8)
    ff2 = FFModel(cfg2)
    x = ff2.create_tensor((8, 16), name="input")
    t = ff2.dense(x, 16, name="a")
    t = ff2.dense(t, 16, name="b")
    ff2.softmax(t, name="sm")
    plan2 = build_stage_plan(ff2, balanced_stages(ff2, 2))
    _, widths2 = _wire_layouts(plan2, ff2)
    assert set(widths2) == {"float32"}, widths2


# -------------------------------------------------------- flash attention

def _mha_ref(q, k, v):
    s = jnp.einsum("bihd,bjhd->bhij", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    s = s / np.sqrt(q.shape[-1])
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhij,bjhd->bihd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("impl", ["interpret", "jnp"])
def test_flash_attention_bf16_fwd_bwd(impl):
    """bf16 q/k/v through both implementations: f32 LSE/accumulation
    keeps the result within bf16 tolerance of the f32 reference, and
    jax.grad works (the bwd kernels recompute from the f32 logsumexp)."""
    from flexflow_tpu.kernels.flash_attention import flash_attention_bshd

    rng = np.random.RandomState(0)
    b, s, h, d = 2, 128, 2, 32
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)

    if impl == "interpret":
        def f(q, k, v):
            return flash_attention_bshd(q, k, v, causal=False,
                                        interpret=True)
    else:
        # the executor's non-pallas path: XLA einsum attention with f32
        # softmax statistics — what ops/attention.py runs off-TPU
        def f(q, k, v):
            return _mha_ref(q, k, v).astype(q.dtype)

    o = f(q, k, v)
    assert o.dtype == jnp.bfloat16
    ref = _mha_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)

    def loss(q, k, v):
        return jnp.sum(f(q, k, v).astype(jnp.float32))

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert g.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
    # grads match the f32-reference gradient at bf16 tolerance
    gq32 = jax.grad(lambda q_: jnp.sum(_mha_ref(q_, k, v)))(q)
    np.testing.assert_allclose(np.asarray(gq, np.float32),
                               np.asarray(gq32, np.float32),
                               atol=6e-2, rtol=6e-2)


def test_paged_attention_bf16_pallas_vs_jnp():
    """The serving kernels accept bf16 queries against (f32) KV pages:
    interpret-pallas and jnp fallback agree bit-for-bit."""
    from flexflow_tpu.kernels.flash_attention import paged_attention_decode

    rng = np.random.RandomState(1)
    P, ps, hh, d = 9, 8, 2, 16
    B, pp = 3, 4
    q = jnp.asarray(rng.randn(B, hh, d), jnp.bfloat16)
    kp = jnp.asarray(rng.randn(P, ps, hh, d), jnp.float32)
    vp = jnp.asarray(rng.randn(P, ps, hh, d), jnp.float32)
    pt = jnp.asarray(rng.randint(1, P, (B, pp)), jnp.int32)
    sl = jnp.asarray([5, 17, 30], jnp.int32)
    a = paged_attention_decode(q, kp, vp, pt, sl, use_pallas=True,
                               interpret=True)
    b_ = paged_attention_decode(q, kp, vp, pt, sl, use_pallas=False)
    assert a.dtype == jnp.bfloat16 and b_.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(a, np.float32),
                                  np.asarray(b_, np.float32))


# ------------------------------------------------------------ cost stack

def test_machine_model_prices_dtypes():
    from flexflow_tpu.search.machine_model import default_machine_model

    mm = default_machine_model(None)
    flops = 1e12
    t_bf16 = mm.compute_time(flops, 0.0, dtype="bfloat16")
    t_f32 = mm.compute_time(flops, 0.0, dtype="float32")
    assert t_f32 == pytest.approx(2.0 * t_bf16)
    # legacy callers (dtype=None) keep the bf16-basis peak
    assert mm.compute_time(flops, 0.0) == pytest.approx(t_bf16)
    # a measured per-dtype factor overrides the family factor
    mm.efficiency["matmul:float32"] = 2 * mm.efficiency["matmul"]
    assert mm.compute_time(flops, 0.0, dtype="float32") == \
        pytest.approx(t_bf16)


def test_op_cost_dtype_aware():
    """bf16 policy halves a linear op's compute time (2x MXU rate) and
    its HBM/collective bytes; the DP grad sync stays at the f32 param
    dtype."""
    from flexflow_tpu import make_mesh
    from flexflow_tpu.parallel.pconfig import OpStrategy
    from flexflow_tpu.search.cost_model import op_cost
    from flexflow_tpu.search.machine_model import default_machine_model

    def linear_cost(dt):
        cfg = FFConfig(batch_size=256)
        cfg.compute_dtype = dt
        ff = FFModel(cfg)
        x = ff.create_tensor((256, 1024), name="input")
        ff.dense(x, 1024, name="fc")
        mesh = make_mesh((8,), ("data",))
        mm = default_machine_model(mesh)
        return op_cost(ff.ops[0], OpStrategy({"sample": "data"}), mesh,
                       mm)

    c32 = linear_cost("float32")
    cb = linear_cost("bfloat16")
    assert cb.fwd == pytest.approx(c32.fwd / 2, rel=1e-6)
    assert cb.bwd == pytest.approx(c32.bwd / 2, rel=1e-6)
    assert cb.sync == pytest.approx(c32.sync)  # f32 grads either way
    assert cb.mem < c32.mem  # bf16 activations


def test_cost_cache_misses_on_dtype_flip():
    """Regression for the cache-correctness satellite: the machine
    fingerprint folds in the precision policy, so entries written under
    f32 pricing can never be replayed into a bf16 search."""
    from flexflow_tpu.search.cost_cache import (CostCache,
                                                machine_fingerprint)
    from flexflow_tpu.search.cost_model import OpCost
    from flexflow_tpu.search.machine_model import default_machine_model

    mm = default_machine_model(None)
    fp32 = machine_fingerprint(mm, None,
                               precision=("float32", "float32"))
    fpb = machine_fingerprint(mm, None,
                              precision=("bfloat16", "float32"))
    assert fp32 != fpb
    cache = CostCache(path="/nonexistent/never-written.json")
    key = CostCache.entry_key("sig", ["axis"], ())
    cache.put(fp32, key, OpCost(fwd=1.0, bwd=2.0, fwd_comm=0.0,
                                bwd_comm=0.0, sync=0.0, mem=0.0))
    assert cache.get(fp32, key) is not None
    assert cache.get(fpb, key) is None  # dtype flip MUST miss


def test_simulator_fingerprint_separates_precision():
    from flexflow_tpu import make_mesh
    from flexflow_tpu.search.simulator import Simulator

    def fp(dt):
        cfg = FFConfig(batch_size=8)
        cfg.compute_dtype = dt
        ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                               num_heads=2, num_layers=1, ff_dim=64)
        sim = Simulator(ff, make_mesh((1,), ("data",)))
        return sim._fingerprint

    assert fp("float32") != fp("bfloat16")


# ------------------------------------------------------------ serve + IO

def test_serve_engine_bf16_exactness():
    from flexflow_tpu.serve.engine import ServeEngine
    from flexflow_tpu.models.transformer import build_transformer_lm

    cfg = FFConfig(batch_size=2)
    cfg.compute_dtype = "bfloat16"
    cfg.kv_page_size = 8
    cfg.kv_num_pages = 65
    cfg.serve_max_seqs = 2
    cfg.serve_prefill_budget = 32
    ff = build_transformer_lm(cfg, vocab_size=32, max_seq_len=32,
                              batch_size=2, hidden=32, num_heads=2,
                              num_layers=2, ff_dim=64)
    eng = ServeEngine(ff, use_pallas=False)
    assert eng.act_dtype == jnp.bfloat16
    eng.warmup()
    c0 = eng.compile_counts()
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 32, n)) for n in (4, 9)]
    out = eng.generate(prompts, max_new_tokens=6)
    assert out == eng.generate_reference(prompts, max_new_tokens=6)
    assert eng.compile_counts() == c0  # zero recompiles after warmup


def test_host_to_device_casts_in_transfer():
    """Satellite: the single-host path builds the numpy array at the
    target dtype and device_puts ONCE straight to the sharding."""
    from flexflow_tpu import make_mesh
    from flexflow_tpu.core.dataloader import host_to_device
    from flexflow_tpu.parallel.sharding import batch_sharding

    mesh = make_mesh((1,), ("data",))
    host = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    arr = host_to_device(host, mesh, dtype=jnp.bfloat16)
    assert arr.dtype == jnp.bfloat16
    assert arr.sharding == batch_sharding(mesh, 2)
    np.testing.assert_allclose(np.asarray(arr, np.float32), host,
                               atol=1e-2)
    # int dtype preserved with no cast requested
    ints = np.arange(8, dtype=np.int32)[:, None]
    arr = host_to_device(ints, mesh)
    assert arr.dtype == jnp.int32
    # meshless path unchanged
    arr = host_to_device(host, None, dtype=jnp.bfloat16)
    assert arr.dtype == jnp.bfloat16


def test_cli_flags_parse_dtypes():
    cfg = FFConfig(argv=["--compute-dtype", "bfloat16",
                         "--param-dtype", "float32"])
    assert cfg.compute_dtype == jnp.dtype(jnp.bfloat16)
    assert cfg.param_dtype == jnp.dtype(jnp.float32)
    with pytest.raises(ValueError):
        FFConfig(argv=["--compute-dtype", "int32"])
