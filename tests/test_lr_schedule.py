"""Runtime learning-rate control (reference keras LearningRateScheduler,
python/flexflow/keras/callbacks.py:49-62): the lr rides the jitted step
as a traced scalar, so schedules re-dispatch without recompiling."""

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer


def build(lr=0.1, opt="sgd"):
    cfg = FFConfig(batch_size=32)
    ff = FFModel(cfg)
    x = ff.create_tensor((32, 16), name="input")
    t = ff.dense(x, 32, activation="relu", name="fc0")
    ff.softmax(ff.dense(t, 4, name="head"))
    optimizer = (SGDOptimizer(lr=lr, momentum=0.9) if opt == "sgd"
                 else AdamOptimizer(lr=lr))
    ff.compile(optimizer=optimizer,
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def batch(seed=0):
    rng = np.random.RandomState(seed)
    return {"input": rng.randn(32, 16).astype(np.float32),
            "label": rng.randint(0, 4, 32).astype(np.int32)}


def test_zero_lr_freezes_weights():
    ff = build()
    ff.set_learning_rate(0.0)
    w0 = ff.get_weights("fc0")["kernel"]
    ff.train_batch(batch())
    np.testing.assert_array_equal(w0, ff.get_weights("fc0")["kernel"])


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_scaled_lr_matches_native_lr(opt):
    """set_learning_rate(2*base) must produce exactly the step an
    optimizer built with lr=2*base produces."""
    ff_a = build(lr=0.05, opt=opt)
    ff_b = build(lr=0.10, opt=opt)
    ff_b.set_weights("fc0", ff_a.get_weights("fc0"))
    ff_b.set_weights("head", ff_a.get_weights("head"))
    ff_a2 = build(lr=0.05, opt=opt)
    ff_a2.set_weights("fc0", ff_a.get_weights("fc0"))
    ff_a2.set_weights("head", ff_a.get_weights("head"))
    ff_a2.set_learning_rate(0.10)
    b = batch()
    ff_b.train_batch(b)
    ff_a2.train_batch(b)
    for n in ("fc0", "head"):
        np.testing.assert_allclose(ff_a2.get_weights(n)["kernel"],
                                   ff_b.get_weights(n)["kernel"],
                                   rtol=1e-6, atol=1e-7)


def test_schedule_changes_without_recompile():
    """Changing the lr between steps must not trigger a retrace: the
    program registry must count ONE train_step compile after steps at
    different lrs (the count would grow if lr ever became a
    static/value-keyed argument — the lr rides as a traced device
    scalar, so its signature is shape/dtype, never the value)."""
    ff = build()
    ff.train_batch(batch())
    assert ff.executor.compile_counts().get("train_step") == 1
    ff.set_learning_rate(0.01)
    ff.train_batch(batch(1))
    ff.set_learning_rate(0.002)
    ff.train_batch(batch(2))
    assert ff.executor.compile_counts().get("train_step") == 1
    assert ff.get_learning_rate() == pytest.approx(0.002)


def test_lr_scale_applies_under_grad_accum():
    """The accum path must honor the schedule too: zero lr through
    train_batch_accum leaves weights untouched."""
    ff = build()
    ff.set_learning_rate(0.0)
    w0 = ff.get_weights("fc0")["kernel"]
    b = batch()
    micro = [{k: v[i * 8:(i + 1) * 8] for k, v in b.items()}
             for i in range(4)]
    ff.train_batch_accum(micro)
    np.testing.assert_array_equal(w0, ff.get_weights("fc0")["kernel"])


def test_keras_lr_scheduler_callback():
    from flexflow_tpu.frontends.keras import (
        LearningRateScheduler, Model)
    from flexflow_tpu.frontends.keras.layers import Dense, Input
    x = Input(shape=(16,))
    t = Dense(32, activation="relu")(x)
    out = Dense(4, activation="softmax")(t)
    m = Model(inputs=[x], outputs=out)
    m.compile(optimizer=SGDOptimizer(lr=0.1),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = rng.randint(0, 4, 64).astype(np.int32)
    seen = []
    sched = LearningRateScheduler(lambda e: [0.1, 0.0][e])
    m.fit(xs, ys, batch_size=32, epochs=1, callbacks=[sched],
          shuffle=False, verbose=False)
    w_after_e0 = m.ffmodel.get_weights("dense_1")["kernel"].copy()
    m.fit(xs, ys, batch_size=32, epochs=1,
          callbacks=[LearningRateScheduler(lambda e: 0.0)],
          shuffle=False, verbose=False)
    np.testing.assert_array_equal(
        w_after_e0, m.ffmodel.get_weights("dense_1")["kernel"])


def test_lr_device_scalar_is_cached():
    """The lr scalar handed to every dispatch must be the SAME device
    buffer until set_learning_rate changes it: re-making it per dispatch
    put one synchronous host->device transfer on each train_batches
    call, serializing the async dispatch queue on (tunnel) round trips
    — the round-4 on-chip regression (alexnet 11.0 vs 5.0 ms/step,
    evidence/tpu_session_20260731T101421Z.log)."""
    ff = build(lr=0.1)
    ex = ff.executor
    a, b = ex._lr(), ex._lr()
    assert a is b
    ff.set_learning_rate(0.05)
    c = ex._lr()
    assert c is not a
    assert float(c) == pytest.approx(0.5)  # scale vs base lr 0.1
    assert ex._lr() is c
