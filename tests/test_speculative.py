"""Speculative decoding: multi-token verified decode in the mixed
program, with KV rollback and adaptive drafting.

Layered like the other serve suites:
  * drafter — prompt-lookup n-gram proposals (recency vs continuation
    fullness) and the adaptive draft-length controller (windowed
    acceptance rate, auto-disable, probe recovery), pure host units.
  * cache — rollback: page release past the verified boundary, hash
    hygiene (a rolled-back page is never prefix-matchable), invariants.
  * engine — speculative generation stays token-for-token identical to
    the no-cache greedy reference on repetitive AND adversarial
    workloads (speculation changes dispatch count, never tokens),
    through eos, preemption and sampling; k=0 degenerates to the plain
    engine; zero recompiles after warmup; and the compile-event counter
    (the anti-vacuous zero-recompile gate) sees a forced new program.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from flexflow_tpu.config import CompMode, FFConfig
from flexflow_tpu.serve import (
    DraftControl,
    Drafter,
    KVCacheConfig,
    PagedKVCache,
    PromptLookupDrafter,
    ServeEngine,
    prefix_page_keys,
)


# --------------------------------------------------------------- drafter
def test_prompt_lookup_basic_ngram():
    d = PromptLookupDrafter()
    # trailing [5, 6] last occurred earlier followed by 7, 8
    assert d.draft([5, 6, 7, 8, 1, 5, 6], 2) == [7, 8]
    # no earlier occurrence of anything -> no draft
    assert d.draft([1, 2, 3], 2) == []
    assert d.draft([1, 2, 3], 0) == []


def test_prompt_lookup_prefers_full_continuation():
    """On a constant run the nearest match clips its continuation at
    the end of history; an earlier occurrence must supply all k."""
    d = PromptLookupDrafter()
    assert d.draft([7] * 10, 4) == [7, 7, 7, 7]
    # periodic text: the full period is proposed, not a 1-token stub
    assert d.draft([1, 2, 3, 1, 2, 3, 1, 2, 3], 3) == [1, 2, 3]


def test_prompt_lookup_recency_wins_among_full():
    """Two occurrences can both supply k tokens: the most recent one's
    continuation is proposed (generated text drifts)."""
    d = PromptLookupDrafter(max_ngram=2)
    #         [9,1]->2        [9,1]->4 (more recent), both full
    ctx = [9, 1, 2, 0, 0, 9, 1, 4, 0, 9, 1]
    assert d.draft(ctx, 1) == [4]


def test_draft_control_adapts_and_disables():
    c = DraftControl(k_max=4, window=4, disable_below=0.25,
                     probe_every=8)
    assert c.next_k() == 4          # optimistic start
    for _ in range(4):
        c.record(4, 0)              # nothing ever accepted
    assert c.disabled
    # adversarial steady state: every drafted token is rejected; most
    # steps draft 0 and re-measure phases only ever risk 1-token drafts
    drafted = 0
    ks = []
    for _ in range(32):
        k = c.next_k()
        ks.append(k)
        if k:
            c.record(k, 0)
            drafted += k
    assert ks.count(0) >= len(ks) // 2
    assert max(ks) <= 1
    assert drafted <= 16            # vs 32 * k_max = 128 at full tilt


def test_draft_control_probe_recovers():
    c = DraftControl(k_max=4, window=4, disable_below=0.25,
                     probe_every=2)
    for _ in range(4):
        c.record(4, 0)
    assert c.disabled
    # a probe fires, its fresh measurement fully accepts -> re-enabled
    while c.next_k() == 0:
        pass
    c.record(1, 1)
    assert not c.disabled
    assert c.next_k() == 4          # rate 1.0 over the fresh window


def test_draft_control_scales_with_rate():
    c = DraftControl(k_max=8, window=4)
    c.record(8, 8)
    assert c.next_k() == 8
    c2 = DraftControl(k_max=8, window=4)
    for _ in range(4):
        c2.record(8, 2)             # rate 0.25 -> ceil(8 * 1.5 * .25)
    assert 1 <= c2.next_k() <= 4


# --------------------------------------------------------------- rollback
def _cache():
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=9, max_seqs=2,
                        max_seq_len=24)
    return PagedKVCache(cfg)


def test_rollback_frees_speculative_tail():
    cache = _cache()
    s = cache.alloc_slot()
    cache.ensure_capacity(s, 6)
    cache.advance(s, 6)
    free0 = cache.free_pages
    # map two pages ahead for 8 drafted tokens, then reject them all
    cache.ensure_capacity(s, 14)
    assert cache.free_pages == free0 - 2
    released = cache.rollback(s, 6)
    assert released == 2
    assert cache.free_pages == free0
    assert cache.mapped_pages(s) == 2   # ceil(6/4)
    assert int(cache.seq_lens[s]) == 6
    cache.check_invariants()
    # partial acceptance: keep one of the two speculative pages
    cache.ensure_capacity(s, 14)
    cache.advance(s, 9)
    assert cache.rollback(s, 9) == 1
    cache.check_invariants()
    cache.free_slot(s)
    cache.check_invariants()


def test_rollback_never_leaves_tail_matchable():
    """A hashed page past (or straddling) the rollback boundary must
    leave the prefix registry — matching it later would hand a new
    prompt unverified K/V."""
    cache = _cache()
    tokens = list(range(100, 108))
    keys = prefix_page_keys(tokens, 4, 2)
    s = cache.alloc_slot()
    cache.ensure_capacity(s, 8)
    cache.advance(s, 8)
    cache.commit_page(s, 0, keys[0])
    cache.commit_page(s, 1, keys[1])
    assert len(cache.match_prefix(keys)) == 2
    # rewind past page 1 entirely: its hash must drop with it
    cache.rollback(s, 4)
    assert len(cache.match_prefix(keys)) == 1
    cache.check_invariants()
    # re-grow, recommit, then rewind INTO page 1 (boundary mid-page):
    # the page stays mapped but its full-content hash now overclaims
    cache.ensure_capacity(s, 8)
    cache.advance(s, 8)
    cache.commit_page(s, 1, keys[1])
    cache.rollback(s, 6)
    assert len(cache.match_prefix(keys)) == 1
    cache.check_invariants()
    cache.free_slot(s)
    cache.check_invariants()


def test_rollback_shared_page_survives_for_other_owner():
    cache = _cache()
    tokens = list(range(50, 58))
    keys = prefix_page_keys(tokens, 4, 2)
    s0 = cache.alloc_slot()
    cache.ensure_capacity(s0, 8)
    cache.advance(s0, 8)
    cache.commit_page(s0, 0, keys[0])
    cache.commit_page(s0, 1, keys[1])
    pages = cache.match_prefix(keys)
    s1 = cache.alloc_slot()
    cache.attach_prefix(s1, pages, 8)
    # owner 1 rolls back; owner 0 still fully covers both pages, so
    # they stay mapped, hashed and matchable
    cache.rollback(s1, 4)
    assert cache.ref(pages[1]) == 1
    assert cache.match_prefix(keys) == pages
    cache.check_invariants()
    cache.free_slot(s0)
    cache.free_slot(s1)
    cache.check_invariants()


# --------------------------------------------------------------- engines
@pytest.fixture(scope="module")
def lm():
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=97,
                   serve_max_seqs=4, serve_prefill_budget=64)
    return build_transformer_lm(cfg, vocab_size=89, max_seq_len=192,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


@pytest.fixture(scope="module")
def echo_lm():
    """The bench's repetitive-text generator: residual writers zeroed,
    head tied to token embeddings — greedy decode echoes the trailing
    token (see tools/serve_bench._make_echo_lm)."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=97,
                   serve_max_seqs=4, serve_prefill_budget=64)
    ff = build_transformer_lm(cfg, vocab_size=89, max_seq_len=192,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    ff.compile(comp_mode=CompMode.INFERENCE)
    p = ff.state.params
    for i in range(2):
        attn = p[f"layer{i}_attn"]
        attn["wo"] = jnp.zeros_like(attn["wo"])
        attn["bo"] = jnp.zeros_like(attn["bo"])
        ff2 = p[f"layer{i}_ff2"]
        ff2["kernel"] = jnp.zeros_like(ff2["kernel"])
        ff2["bias"] = jnp.zeros_like(ff2["bias"])
    p["pos_embed"]["kernel"] = p["pos_embed"]["kernel"] * 0.15
    p["lm_head"]["kernel"] = 4.0 * p["tok_embed"]["kernel"].T
    p["lm_head"]["bias"] = jnp.zeros_like(p["lm_head"]["bias"])
    return ff


@pytest.fixture(scope="module")
def spec_engine(lm):
    eng = ServeEngine(lm, spec_tokens=6)
    eng.warmup()
    return eng


def test_spec_exact_on_repetitive_and_reduces_steps(echo_lm):
    """The headline contract: on repetitive text the speculative
    engine dispatches FAR fewer decode steps for the bit-identical
    token streams, compiling nothing after warmup."""
    eng = ServeEngine(echo_lm, spec_tokens=6)
    eng.warmup()
    base = ServeEngine(echo_lm, spec_tokens=0)
    base.warmup()
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(1, 89, size=rng.randint(4, 12)))
               for _ in range(4)]
    before = eng.compile_counts()
    out = eng.generate(prompts, 32)
    assert eng.compile_counts() == before, "speculation recompiled"
    ref = eng.generate_reference(prompts, 32)
    assert out == ref
    assert base.generate(prompts, 32) == ref
    st = eng.last_stats
    assert st["spec_accepted_tokens"] > 0
    assert st["decode_steps"] * 2 <= base.last_stats["decode_steps"]
    assert st["steps_per_decode_token"] < 0.6


def test_spec_exact_on_adversarial_and_autodisables(lm):
    """A drafter that is always wrong costs correctness nothing, and
    the windowed acceptance rate drives every request's draft length
    to 0 (speculation pays for itself or turns itself off)."""
    class WrongDrafter(Drafter):
        def draft(self, tokens, k):
            return [(tokens[-1] + 37) % 89 or 1] * k

    eng = ServeEngine(lm, spec_tokens=6, drafter=WrongDrafter())
    eng.warmup()
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(1, 89, size=rng.randint(4, 24)))
               for _ in range(4)]
    out = eng.generate(prompts, 48)
    assert out == eng.generate_reference(prompts, 48)
    st = eng.last_stats
    # (the +37 shift can collide with the true argmax only by accident;
    # what matters is that almost everything was rejected)
    assert st["spec_acceptance"] <= 0.1
    # auto-disable: after the first windows fill, steps mostly draft 0,
    # so drafted tokens stay FAR below steps * k_max
    assert st["spec_drafted_tokens"] < 0.3 * 6 * st["decode_steps"] * 4


def test_spec_natural_text_exact(spec_engine):
    """Random-weight LM, mixed ragged prompts: partially-accepted
    drafts, rejections and rollbacks — outputs stay the reference's."""
    rng = np.random.RandomState(11)
    prompts = [list(rng.randint(1, 89, size=rng.randint(2, 40)))
               for _ in range(6)]
    max_new = [int(rng.randint(1, 32)) for _ in range(6)]
    before = spec_engine.compile_counts()
    out = spec_engine.generate(prompts, max_new)
    assert spec_engine.compile_counts() == before
    assert out == spec_engine.generate_reference(prompts, max_new)
    assert spec_engine.cache.stats["rollback_pages"] >= 0


def test_spec_eos_inside_draft_exact(spec_engine):
    """EOS emitted from an ACCEPTED draft must stop the stream exactly
    where sequential decode would — accepted-after-eos tokens drop."""
    rng = np.random.RandomState(13)
    prompts = [[7, 7, 7, 7, 7, 7], list(rng.randint(1, 89, size=9))]
    ref_free = spec_engine.generate_reference(prompts, 12)
    eos = ref_free[0][min(2, len(ref_free[0]) - 1)]
    out = spec_engine.generate(prompts, 12, eos_token=eos)
    assert out == spec_engine.generate_reference(prompts, 12,
                                                 eos_token=eos)


def test_spec_k0_is_todays_engine(lm):
    """An engine with spec_tokens=0 and a spec-ENABLED engine whose
    drafter never proposes are the SAME engine: every decode chunk
    carries zero drafts, so token streams, step counts and stats all
    match bit-for-bit (speculation off == speculation inert)."""
    class NeverDrafter(Drafter):
        def draft(self, tokens, k):
            return []

    e_k0 = ServeEngine(lm, spec_tokens=0)
    e_k0.warmup()
    e_inert = ServeEngine(lm, spec_tokens=6, drafter=NeverDrafter())
    e_inert.warmup()
    rng = np.random.RandomState(17)
    prompts = [list(rng.randint(1, 89, size=rng.randint(2, 30)))
               for _ in range(5)]
    a = e_k0.generate(prompts, 16)
    b = e_inert.generate(prompts, 16)
    assert a == b
    sa, sb = e_k0.last_stats, e_inert.last_stats
    assert sa["steps"] == sb["steps"]
    assert sa["decode_steps"] == sb["decode_steps"]
    assert sa["spec_drafted_tokens"] == sb["spec_drafted_tokens"] == 0
    assert sa["steps_per_decode_token"] == sb["steps_per_decode_token"] \
        == 1.0


def test_no_spec_decode_config_resolves_to_zero():
    """--no-spec-decode / serve_spec_decode=False must reach the
    engine: spec_tokens resolves to 0 (no manual override), and the
    engine still serves exactly."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=49,
                   serve_max_seqs=4, serve_prefill_budget=32,
                   argv=["--no-spec-decode"])
    assert cfg.serve_spec_decode is False
    ff = build_transformer_lm(cfg, vocab_size=61, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(ff)
    assert eng.spec_tokens == 0
    eng.warmup()
    prompts = [[5, 6, 7, 5, 6, 7], [11, 3]]
    out = eng.generate(prompts, 6)
    assert out == eng.generate_reference(prompts, 6)
    st = eng.last_stats
    assert st["spec_drafted_tokens"] == 0
    assert st["steps_per_decode_token"] == 1.0
    # and the dial itself: serve_spec_tokens=0 with the switch ON
    cfg2 = FFConfig(argv=["--spec-tokens", "0"])
    assert cfg2.serve_spec_decode and cfg2.serve_spec_tokens == 0


def test_spec_preempt_resume_mid_speculation():
    """A pool too small for the batch preempts while speculation is
    active; resumed requests keep drafting and the streams still equal
    the reference's."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=4, kv_num_pages=14,
                   serve_max_seqs=4, serve_prefill_budget=16)
    ff = build_transformer_lm(cfg, vocab_size=61, max_seq_len=48,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(ff, spec_tokens=4)
    eng.warmup()
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(1, 61, size=rng.randint(8, 20)))
               for _ in range(4)]
    max_new = [int(rng.randint(8, 16)) for _ in range(4)]
    out = eng.generate(prompts, max_new)
    assert out == eng.generate_reference(prompts, max_new)
    st = eng.last_stats
    assert st["preemptions"] > 0
    assert st["spec_drafted_tokens"] > 0


def test_spec_topk1_sampling_speculates_exact(spec_engine):
    """top_k=1 sampling is deterministic (the drawn sample IS the top
    logit), so it speculates under the verify-against-the-drawn-sample
    rule and matches both greedy and its own non-speculative run."""
    prompts = [[7] * 8, [5, 6, 7, 5, 6, 7, 5, 6]]
    greedy = spec_engine.generate(prompts, 10)
    sampled = spec_engine.generate(prompts, 10, temperature=1.3, top_k=1)
    assert sampled == greedy
    # temperature>0 with top_k>1 must NOT speculate (k=0 this PR)
    spec_engine.generate(prompts, 6, temperature=0.8, top_k=8,
                         sample_seed=3)
    assert spec_engine.last_stats["spec_drafted_tokens"] == 0


def test_spec_zero_recompiles_after_warmup(spec_engine):
    """Speculation only changes how the fixed lanes are SPENT: no new
    shapes, no new programs, on any workload in this suite."""
    counts = spec_engine.compile_counts()
    assert counts == {"prefill": 0, "decode": 0, "mixed": 1,
                      "export": 0, "import": 0, "adapter": 0}


# ------------------------------------------------- compile-event counter
def test_compile_counter_sees_forced_new_signature(lm):
    """The anti-vacuous regression: a genuinely new program signature
    MUST increment compile_counts (jax.monitoring backend-compile
    events attributed to the call, with the shape-signature floor)."""
    eng = ServeEngine(lm)
    eng.warmup()
    c0 = eng.compile_counts()["mixed"]
    assert c0 == 1
    c = eng.cache_cfg
    kp, vp = eng.cache.alloc_device_cache()   # throwaway donated pair
    t = 2                                      # not the mixed width
    z = jnp.zeros((t,), jnp.int32)
    pts = jnp.zeros((c.max_seqs, c.pages_per_seq), jnp.int32)
    eng._call_counted("mixed", eng._mixed_jit, eng.params, kp, vp,
                      z, z, z, z, pts, z, jnp.ones((t,), jnp.int32))
    assert eng.compile_counts()["mixed"] == c0 + 1
    if eng._events_ok:   # jax.monitoring present: the EVENT path saw it
        assert eng._compiles["mixed"] == 2
