"""Test harness: force an 8-device CPU platform so every parallelism axis
(DP/TP/SP/EP/PP) is exercised without TPU hardware — the capability the
reference never had (its "distributed" CI needed 4 real GPUs,
SURVEY.md section 4)."""

import os

# Unconditional: the image pre-sets JAX_PLATFORMS (sitecustomize) to the
# TPU tunnel, but tests must run on a virtual 8-device CPU platform.
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))
os.environ["JAX_PLATFORMS"] = "cpu"

# Isolate every per-machine measurement/cost cache (calibration,
# op_measure, the persistent search cost cache) from the developer's
# real ~/.cache/flexflow_tpu: tests must neither read stale entries a
# previous checkout left there nor mutate user-level state.
import tempfile  # noqa: E402

os.environ.setdefault(
    "FLEXFLOW_TPU_CACHE",
    tempfile.mkdtemp(prefix="flexflow_tpu_test_cache_"))

import jax  # noqa: E402

# env var alone is overridden by the image's sitecustomize; force it.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---- fast/slow split (reference CI analog, .circleci/config.yml) ----
# The default profile (pyproject addopts = -m 'not slow') must finish
# <5 min on the 1-core CI host; whole modules that are integration
# suites land in SLOW_MODULES, individually expensive tests in
# SLOW_TESTS (node-id substring). tools/ci.sh runs the fast gate every
# time and the slow remainder when asked (--full).
SLOW_MODULES = {
    "test_examples",        # example-zoo subprocess integration (~9 min)
    "test_models",          # full-model smokes (inception alone 200s)
    "test_multiprocess",    # real OS-process jax.distributed (~2 min)
    "test_multihost",
    "test_graph_pipeline",  # staged-pipeline integration (~3 min)
    "test_data_checkpoint",  # orbax save/restore round trips (~1 min)
}
SLOW_TESTS = (
    "test_sorted_dispatch_matches_dense_bitwise",
    "test_dlrm_strategy_generator",
    "test_fused_qkv_under_remat_matches_no_remat",
    "test_pp_matches_unsharded",
    "test_stacked_blocks_train_single_device",
    "test_sp_transformer_alltoall_matches_unsharded",
    "test_shipped_dlrm_pb_replays_and_trains",
    "test_stacked_dlrm_trains_table_sharded",
    "test_zero_under_staged_pipeline",
    "test_sp_transformer_matches_unsharded",
    "test_sp_non_divisible_seq_falls_back",
    "test_skewed_placement_pads",
    "test_adam_sparse_placed",
    "test_nhwc_residency_multi_device_matches_single_nchw",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in SLOW_MODULES or any(s in item.nodeid
                                      for s in SLOW_TESTS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture
def mesh8():
    from flexflow_tpu.parallel.mesh import make_mesh
    return make_mesh((8,), ("data",))


@pytest.fixture
def mesh_2d():
    from flexflow_tpu.parallel.mesh import make_mesh
    return make_mesh((4, 2), ("data", "model"))


@pytest.fixture(autouse=True)
def _reset_keras_layer_names():
    """Layer auto-names feed the name-keyed weight-init rng; reset the
    global counter per test so keras-frontend models initialize
    identically regardless of suite order."""
    from flexflow_tpu.frontends.keras.layers import reset_layer_uids
    reset_layer_uids()
    yield
