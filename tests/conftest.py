"""Test harness: force an 8-device CPU platform so every parallelism axis
(DP/TP/SP/EP/PP) is exercised without TPU hardware — the capability the
reference never had (its "distributed" CI needed 4 real GPUs,
SURVEY.md section 4)."""

import os

# Unconditional: the image pre-sets JAX_PLATFORMS (sitecustomize) to the
# TPU tunnel, but tests must run on a virtual 8-device CPU platform.
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# env var alone is overridden by the image's sitecustomize; force it.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture
def mesh8():
    from flexflow_tpu.parallel.mesh import make_mesh
    return make_mesh((8,), ("data",))


@pytest.fixture
def mesh_2d():
    from flexflow_tpu.parallel.mesh import make_mesh
    return make_mesh((4, 2), ("data", "model"))


@pytest.fixture(autouse=True)
def _reset_keras_layer_names():
    """Layer auto-names feed the name-keyed weight-init rng; reset the
    global counter per test so keras-frontend models initialize
    identically regardless of suite order."""
    from flexflow_tpu.frontends.keras.layers import reset_layer_uids
    reset_layer_uids()
    yield
