"""Op golden tests vs numpy/torch references.

Pattern follows reference tests/ops/test_harness.py: generate the same
computation in numpy/torch and assert_allclose vs the framework op
(epsilon 1e-5, same as tests/ops/test_readme.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.op import OpContext


def run_op(op, params, xs, training=False, rng=None, state=None):
    ctx = OpContext(training=training, rng=rng, state_in=state or {})
    out = op.forward(params, [jnp.asarray(x) for x in xs], ctx)
    return [np.asarray(o) for o in out], ctx.state_out


def make_model():
    return FFModel(FFConfig())


def test_linear_matches_torch(rng):
    ff = make_model()
    x = rng.randn(4, 16).astype(np.float32)
    t = ff.create_tensor((4, 16))
    out = ff.dense(t, 8, activation="relu")
    op = ff.ops[0]
    w = rng.randn(16, 8).astype(np.float32) * 0.1
    b = rng.randn(8).astype(np.float32) * 0.1
    (y,), _ = run_op(op, {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}, [x])
    ref = F.relu(torch.from_numpy(x) @ torch.from_numpy(w)
                 + torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(y, ref, atol=1e-5)
    assert out.shape == (4, 8)


def test_conv2d_matches_torch(rng):
    ff = make_model()
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    t = ff.create_tensor((2, 3, 8, 8))
    ff.conv2d(t, 4, 3, 3, 1, 1, 1, 1)
    op = ff.ops[0]
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.1
    b = rng.randn(4).astype(np.float32) * 0.1
    (y,), _ = run_op(op, {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}, [x])
    ref = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                   torch.from_numpy(b), stride=1, padding=1).numpy()
    np.testing.assert_allclose(y, ref, atol=1e-4)


def test_pool2d_max_matches_torch(rng):
    ff = make_model()
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    t = ff.create_tensor((2, 3, 8, 8))
    ff.pool2d(t, 2, 2, 2, 2, 0, 0, pool_type="max")
    (y,), _ = run_op(ff.ops[0], {}, [x])
    ref = F.max_pool2d(torch.from_numpy(x), 2, 2).numpy()
    np.testing.assert_allclose(y, ref, atol=1e-5)


def test_pool2d_avg_matches_torch(rng):
    ff = make_model()
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    t = ff.create_tensor((2, 3, 8, 8))
    ff.pool2d(t, 2, 2, 2, 2, 0, 0, pool_type="avg")
    (y,), _ = run_op(ff.ops[0], {}, [x])
    ref = F.avg_pool2d(torch.from_numpy(x), 2, 2).numpy()
    np.testing.assert_allclose(y, ref, atol=1e-5)


def test_batch_norm_train_matches_torch(rng):
    ff = make_model()
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    t = ff.create_tensor((4, 3, 5, 5))
    ff.batch_norm(t, relu=False)
    op = ff.ops[0]
    state = {"running_mean": jnp.zeros(3), "running_var": jnp.ones(3)}
    params = {"scale": jnp.ones(3), "bias": jnp.zeros(3)}
    (y,), new_state = run_op(op, params, [x], training=True, state=state)
    tbn = torch.nn.BatchNorm2d(3, eps=1e-5, momentum=0.1)
    tbn.train()
    ref = tbn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(y, ref, atol=1e-4)
    # running stats updated (torch momentum 0.1 == our (1-MOMENTUM))
    np.testing.assert_allclose(np.asarray(new_state["running_mean"]),
                               tbn.running_mean.numpy(), atol=1e-4)


def test_embedding_sum(rng):
    ff = make_model()
    idx = rng.randint(0, 10, (4, 3)).astype(np.int32)
    t = ff.create_tensor((4, 3), dtype=jnp.int32)
    ff.embedding(t, 10, 6, aggr="sum")
    table = rng.randn(10, 6).astype(np.float32)
    (y,), _ = run_op(ff.ops[0], {"kernel": jnp.asarray(table)}, [idx])
    ref = table[idx].sum(axis=1)
    np.testing.assert_allclose(y, ref, atol=1e-5)


def test_batch_matmul_matches_torch(rng):
    ff = make_model()
    a = rng.randn(3, 4, 5).astype(np.float32)
    b = rng.randn(3, 5, 6).astype(np.float32)
    ta = ff.create_tensor((3, 4, 5))
    tb = ff.create_tensor((3, 5, 6))
    ff.batch_matmul(ta, tb)
    (y,), _ = run_op(ff.ops[0], {}, [a, b])
    ref = torch.bmm(torch.from_numpy(a), torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(y, ref, atol=1e-4)


def test_batch_matmul_seq_length_mask(rng):
    """seq_length truncation semantics (reference model.h:1029-1047)."""
    ff = make_model()
    a = rng.randn(2, 4, 5).astype(np.float32)
    b = rng.randn(2, 5, 6).astype(np.float32)
    ta = ff.create_tensor((2, 4, 5))
    tb = ff.create_tensor((2, 5, 6))
    ff.batch_matmul(ta, tb, a_seq_length_dim=1)
    op = ff.ops[0]
    ctx = OpContext(training=False, seq_length=2)
    y = np.asarray(op.forward({}, [jnp.asarray(a), jnp.asarray(b)], ctx)[0])
    ref = (torch.from_numpy(a[:, :2]) @ torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(y[:, :2], ref, atol=1e-4)
    np.testing.assert_allclose(y[:, 2:], 0.0, atol=1e-6)


def test_attention_matches_torch(rng):
    ff = make_model()
    b, s, e, h = 2, 6, 16, 4
    x = rng.randn(b, s, e).astype(np.float32)
    t = ff.create_tensor((b, s, e))
    ff.multihead_attention(t, t, t, e, h, bias=False)
    op = ff.ops[0]
    op.use_flash = False
    d = e // h
    wq = rng.randn(e, h, d).astype(np.float32) * 0.2
    wk = rng.randn(e, h, d).astype(np.float32) * 0.2
    wv = rng.randn(e, h, d).astype(np.float32) * 0.2
    wo = rng.randn(h, d, e).astype(np.float32) * 0.2
    params = {k: jnp.asarray(v) for k, v in
              dict(wq=wq, wk=wk, wv=wv, wo=wo).items()}
    (y,), _ = run_op(op, params, [x, x, x])

    mha = torch.nn.MultiheadAttention(e, h, bias=False, batch_first=True)
    with torch.no_grad():
        # torch packs qkv as (3e, e) row-major per head
        wq2 = torch.from_numpy(wq.reshape(e, e).T)
        wk2 = torch.from_numpy(wk.reshape(e, e).T)
        wv2 = torch.from_numpy(wv.reshape(e, e).T)
        mha.in_proj_weight.copy_(torch.cat([wq2, wk2, wv2], dim=0))
        mha.out_proj.weight.copy_(torch.from_numpy(wo.reshape(e, e).T))
    ref, _ = mha(torch.from_numpy(x), torch.from_numpy(x),
                 torch.from_numpy(x))
    np.testing.assert_allclose(y, ref.detach().numpy(), atol=1e-4)


def test_softmax_topk_concat_split_reshape_transpose_reverse(rng):
    ff = make_model()
    x = rng.randn(4, 10).astype(np.float32)
    t = ff.create_tensor((4, 10))
    ff.softmax(t)
    (y,), _ = run_op(ff.ops[0], {}, [x])
    np.testing.assert_allclose(
        y, F.softmax(torch.from_numpy(x), -1).numpy(), atol=1e-5)

    ff.top_k(t, 3)
    (vals, idxs), _ = run_op(ff.ops[1], {}, [x])
    tv, ti = torch.topk(torch.from_numpy(x), 3)
    np.testing.assert_allclose(vals, tv.numpy(), atol=1e-5)
    np.testing.assert_array_equal(idxs, ti.numpy())

    t2 = ff.create_tensor((4, 6))
    ff.concat([t, t2], axis=1)
    x2 = rng.randn(4, 6).astype(np.float32)
    (y,), _ = run_op(ff.ops[2], {}, [x, x2])
    np.testing.assert_allclose(y, np.concatenate([x, x2], 1))

    ff.split(t, [4, 6], axis=1)
    ys, _ = run_op(ff.ops[3], {}, [x])
    np.testing.assert_allclose(ys[0], x[:, :4])
    np.testing.assert_allclose(ys[1], x[:, 4:])

    ff.reshape(t, (2, 20))
    (y,), _ = run_op(ff.ops[4], {}, [x])
    np.testing.assert_allclose(y, x.reshape(2, 20))

    ff.transpose(t, [1, 0])
    (y,), _ = run_op(ff.ops[5], {}, [x])
    np.testing.assert_allclose(y, x.T)

    ff.reverse(t, axis=1)
    (y,), _ = run_op(ff.ops[6], {}, [x])
    np.testing.assert_allclose(y, x[:, ::-1])


def test_elementwise(rng):
    ff = make_model()
    a = rng.randn(4, 5).astype(np.float32)
    b = rng.randn(4, 5).astype(np.float32)
    ta = ff.create_tensor((4, 5))
    tb = ff.create_tensor((4, 5))
    for mode, fn in [("add", np.add), ("subtract", np.subtract),
                     ("multiply", np.multiply), ("divide", np.divide)]:
        op = getattr(ff, mode)(ta, tb)
        (y,), _ = run_op(ff.ops[-1], {}, [a, b])
        np.testing.assert_allclose(y, fn(a, b), rtol=1e-5)
    for mode, fn in [("relu", lambda v: np.maximum(v, 0)),
                     ("tanh", np.tanh), ("exp", np.exp),
                     ("sigmoid", lambda v: 1 / (1 + np.exp(-v)))]:
        getattr(ff, mode)(ta)
        (y,), _ = run_op(ff.ops[-1], {}, [a])
        np.testing.assert_allclose(y, fn(a), rtol=1e-4, atol=1e-5)


def test_lstm_matches_torch(rng):
    ff = make_model()
    b, t, d, h = 2, 5, 4, 6
    x = rng.randn(b, t, d).astype(np.float32)
    tin = ff.create_tensor((b, t, d))
    ff.lstm(tin, h)
    op = ff.ops[0]
    wx = rng.randn(d, 4 * h).astype(np.float32) * 0.2
    wh = rng.randn(h, 4 * h).astype(np.float32) * 0.2
    bias = rng.randn(4 * h).astype(np.float32) * 0.1
    params = {"wx": jnp.asarray(wx), "wh": jnp.asarray(wh),
              "b": jnp.asarray(bias)}
    (y,), _ = run_op(op, params, [x])

    lstm = torch.nn.LSTM(d, h, batch_first=True)
    # torch gate order [i, f, g, o] matches ours; torch stores (4h, d)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.from_numpy(wx.T))
        lstm.weight_hh_l0.copy_(torch.from_numpy(wh.T))
        lstm.bias_ih_l0.copy_(torch.from_numpy(bias))
        lstm.bias_hh_l0.zero_()
    ref, _ = lstm(torch.from_numpy(x))
    np.testing.assert_allclose(y, ref.detach().numpy(), atol=1e-4)


def test_moe_group_by_aggregate_roundtrip(rng):
    """Dispatch+combine with capacity ≥ all tokens reproduces a dense
    weighted mixture (reference group_by.cc/aggregate.cc semantics)."""
    ff = make_model()
    b, d, n, k = 8, 4, 4, 2
    x = rng.randn(b, d).astype(np.float32)
    gate = np.abs(rng.randn(b, k)).astype(np.float32)
    assign = rng.randint(0, n, (b, k)).astype(np.int32)

    td = ff.create_tensor((b, d))
    ta = ff.create_tensor((b, k), dtype=jnp.int32)
    exp_tensors = ff.group_by(td, ta, n, alpha=float(n))  # capacity = k*b
    gop = ff.ops[0]
    ys, _ = run_op(gop, {}, [x, assign])
    assert len(ys) == n and ys[0].shape == (gop.capacity, d)

    tg = ff.create_tensor((b, k))
    ff.aggregate(tg, ta, exp_tensors, n)
    aop = ff.ops[1]
    (out,), _ = run_op(aop, {}, [gate, assign] + ys)

    # reference combine: sum_k gate[b,k] * x[b] routed through its expert
    ref = (gate.sum(axis=1, keepdims=True)) * x
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_dropout_train_eval(rng):
    ff = make_model()
    x = np.ones((64, 64), np.float32)
    t = ff.create_tensor((64, 64))
    ff.dropout(t, 0.5)
    op = ff.ops[0]
    (y_eval,), _ = run_op(op, {}, [x], training=False)
    np.testing.assert_allclose(y_eval, x)
    (y_train,), _ = run_op(op, {}, [x], training=True,
                           rng=jax.random.PRNGKey(0))
    frac = (y_train == 0).mean()
    assert 0.3 < frac < 0.7
    kept = y_train[y_train != 0]
    np.testing.assert_allclose(kept, 2.0, atol=1e-6)


def test_reduce_op_modes():
    """Generic axis reduction (ONNX ReduceMean/Sum/Max lowering)."""
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    rng = np.random.RandomState(0)
    x = rng.randn(8, 5, 6).astype(np.float32)
    for mode, ref in (("mean", x.mean(axis=1)), ("sum", x.sum(axis=1)),
                      ("max", x.max(axis=1))):
        cfg = FFConfig()
        cfg.batch_size = 8
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 5, 6), name="input")
        out = getattr(ff, f"reduce_{mode}")(t, axis=1)
        assert tuple(out.shape) == (8, 6)
        ff.softmax(ff.dense(out, 4))
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        got = ff.executor.forward_values(
            ff.state.params, ff.state.states,
            {"input": jnp.asarray(x)}, False, None)[0]
        red = next(o for o in ff.ops if o.op_type == "reduce")
        # rtol covers XLA-vs-numpy f32 reduction-order noise (observed
        # up to ~4e-6 relative on this CPU build's mean reduction)
        np.testing.assert_allclose(
            np.asarray(got[red.outputs[0].uid]), ref, rtol=1e-5)
        # trains through the reduction (grad flows)
        m = ff.train_batch({"input": x,
                            "label": rng.randint(0, 4, 8).astype(np.int32)})
        assert np.isfinite(float(m["loss"]))
    # keepdims + negative axis
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    t = ff.create_tensor((8, 5, 6), name="input")
    out = ff.reduce_mean(t, axis=-1, keepdims=True)
    assert tuple(out.shape) == (8, 5, 1)
