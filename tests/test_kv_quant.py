"""Ragged paged attention v2 + int8 quantized KV pages (PR 8).

Layers:
  * kernel — v2's jnp fallback is BIT-identical to the v1 kernel on
    fp32 across random ragged mixes; the Pallas v2 form (interpret
    mode) agrees at f32 tolerance for every kv-block shape; int8
    dequant attention is bounded-error vs f32 with both
    implementations agreeing; the quantizer's row properties and the
    autotune-by-shape table behave.
  * engine — int8 serving holds greedy token parity with the no-cache
    reference on the base workload, and is TOKEN-IDENTICAL to itself
    through chunking, prefix hits, preemption, speculation and
    rollback (per-row write-local scales make quantized content
    execution-path invariant); scale bookkeeping survives the stress
    interleavings (check_invariants + check_kv_scales).
  * sizing — kv_pool_mb byte budgets derive pages from the configured
    kv_dtype itemsize (never a hardcoded 4), and the auto-tuned
    grad_bucket_mb satellite resolves identically in the executor and
    the simulator with explicit values authoritative.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.config import FFConfig
from flexflow_tpu.kernels.flash_attention import (
    paged_attention_ragged,
    paged_attention_ragged_v1,
)
from flexflow_tpu.kernels.paged_ragged_v2 import (
    _BLOCK_KV_TABLE,
    choose_block_kv,
    dequantize_kv,
    quantize_kv_rows,
    ragged_dispatch_passes,
    register_block_kv,
)
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.serve import ServeEngine
from flexflow_tpu.serve.kv_cache import KVCacheConfig, PagedKVCache


# --------------------------------------------------------------- helpers
def _ragged_setup(batch, seed, page_size=4, pages_per_seq=6, h=4, d=8):
    """Random ragged K/V histories scattered into pages (the
    tests/test_serve_v2.py layout)."""
    rng = np.random.RandomState(seed)
    max_len = pages_per_seq * page_size
    num_pages = 1 + batch * pages_per_seq
    lens = rng.randint(1, max_len + 1, size=batch)
    k_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    v_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    table = np.zeros((batch, pages_per_seq), np.int32)
    pool = list(rng.permutation(np.arange(1, num_pages)))
    for b, L in enumerate(lens):
        for i in range(-(-int(L) // page_size)):
            p = int(pool.pop())
            table[b, i] = p
            k_pages[p] = rng.randn(page_size, h, d)
            v_pages[p] = rng.randn(page_size, h, d)
    slots, poss = [], []
    for s, L in enumerate(lens):
        picks = {int(L) - 1} | {int(p) for p in
                                rng.randint(0, int(L), size=3)}
        for p in sorted(picks):
            slots.append(s)
            poss.append(p)
    q = rng.randn(len(slots), h, d).astype(np.float32)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(np.asarray(slots, np.int32)),
            jnp.asarray(np.asarray(poss, np.int32) + 1))


def _lm(kv_dtype="float32", *, page_size=4, pool_pages=None,
        kv_pool_mb=0.0, budget=32, max_seqs=4, max_seq_len=64,
        spec=True, **cfg_kw):
    cfg = FFConfig(
        batch_size=1, kv_page_size=page_size,
        kv_num_pages=pool_pages or (1 + 16 * max_seqs),
        kv_pool_mb=kv_pool_mb, kv_dtype=kv_dtype,
        serve_max_seqs=max_seqs, serve_prefill_budget=budget,
        serve_spec_decode=spec, **cfg_kw)
    return build_transformer_lm(cfg, vocab_size=61,
                                max_seq_len=max_seq_len, hidden=32,
                                num_heads=4, num_layers=2, ff_dim=64)


def _prompts(rng, n, lo=4, hi=28):
    return [list(rng.randint(1, 61, size=rng.randint(lo, hi)))
            for _ in range(n)]


# ----------------------------------------------- kernel v2 bit-equality
@pytest.mark.parametrize("seed", [0, 1, 2, 7])
def test_ragged_v2_jnp_bit_identical_to_v1(seed):
    """fp32 acceptance: the rebuilt kernel's fallback is bit-for-bit
    the old kernel across random ragged (slot, position) mixes — the
    whole serve parity ladder (full-prefill oracle, one-lane ==
    decode) transfers to v2 unchanged."""
    q, kp, vp, table, slots, lens = _ragged_setup(3 + seed % 3, seed)
    v1 = paged_attention_ragged_v1(q, kp, vp, table, slots, lens,
                                   use_pallas=False)
    v2 = paged_attention_ragged(q, kp, vp, table, slots, lens,
                                use_pallas=False)
    assert v1.dtype == v2.dtype
    assert np.array_equal(np.asarray(v1), np.asarray(v2))


@pytest.mark.parametrize("block_kv", [4, 8, 12, 24])
def test_ragged_v2_pallas_interpret_matches_jnp(block_kv):
    """The flattened-grid Pallas kernel agrees with the fallback at f32
    tolerance for every kv-block shape (whole pages, ragged tails,
    whole-table blocks)."""
    q, kp, vp, table, slots, lens = _ragged_setup(3, 60)
    ref = paged_attention_ragged(q, kp, vp, table, slots, lens,
                                 use_pallas=False)
    out = paged_attention_ragged(q, kp, vp, table, slots, lens,
                                 interpret=True, block_kv=block_kv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_ragged_v2_int8_bounded_error_and_path_agreement():
    """int8 pages: attention output error vs the f32 pages is bounded
    per element (the relaxed exactness gate's atol half), and the
    Pallas and jnp dequant paths agree at f32 tolerance."""
    q, kp, vp, table, slots, lens = _ragged_setup(4, 11)
    kq, ks = quantize_kv_rows(kp)
    vq, vs = quantize_kv_rows(vp)
    f32 = paged_attention_ragged(q, kp, vp, table, slots, lens,
                                 use_pallas=False)
    int8 = paged_attention_ragged(q, kq, vq, table, slots, lens,
                                  use_pallas=False, k_scales=ks,
                                  v_scales=vs)
    # bound: the output is a convex combination of dequantized V rows
    # (each within scale/2 of its f32 row) with softmax weights whose
    # perturbation is driven by the K rows' bounded error — at randn
    # scale the measured error is ~1e-2; 0.05 catches a mis-indexed
    # scale or stale page (O(1) error) with wide margin
    err = np.abs(np.asarray(int8) - np.asarray(f32)).max()
    assert err < 0.05, f"int8 attention error {err} exceeds the bound"
    assert err > 0, "int8 path suspiciously exact (not quantizing?)"
    pal = paged_attention_ragged(q, kq, vq, table, slots, lens,
                                 interpret=True, block_kv=8,
                                 k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(int8),
                               rtol=2e-6, atol=2e-6)


def test_quantize_rows_properties():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(5, 4, 8).astype(np.float32) * 3.0)
    qv, sc = quantize_kv_rows(x)
    assert qv.dtype == jnp.int8 and sc.shape == (5, 4)
    # roundtrip error is within half a quantization step per element
    err = np.abs(np.asarray(dequantize_kv(qv, sc)) - np.asarray(x))
    assert np.all(err <= np.asarray(sc)[..., None] / 2 + 1e-7)
    # the row amax is representable exactly at |q| = 127
    assert np.abs(np.asarray(qv)).max() == 127
    # all-zero rows: scale 0, content 0, dequant reproduces zero
    zq, zs = quantize_kv_rows(jnp.zeros((2, 3, 8)))
    assert np.all(np.asarray(zs) == 0) and np.all(np.asarray(zq) == 0)
    assert np.all(np.asarray(dequantize_kv(zq, zs)) == 0)


def test_quantize_rows_fp8_reuses_scale_machinery():
    """fp8 (e4m3) pages ride the int8 per-row machinery verbatim: same
    scale shape, rows scaled to the format's max finite (448), all-zero
    rows exact, roundtrip error within the format's relative step at
    amax scale — and NEVER a NaN/inf from the saturating cast."""
    import ml_dtypes
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(5, 4, 8).astype(np.float32) * 3.0)
    qv, sc = quantize_kv_rows(x, jnp.float8_e4m3fn)
    assert qv.dtype == jnp.dtype(ml_dtypes.float8_e4m3fn)
    assert sc.shape == (5, 4)
    deq = np.asarray(dequantize_kv(qv, sc))
    assert np.all(np.isfinite(deq))
    # e4m3's 3-bit mantissa: relative step 2^-3 at the top binade;
    # absolute error per element <= scale * 448 * 2^-4 = amax/16
    err = np.abs(deq - np.asarray(x))
    amax = np.abs(np.asarray(x)).max(axis=-1)
    assert np.all(err <= amax[..., None] / 16 + 1e-7)
    zq, zs = quantize_kv_rows(jnp.zeros((2, 3, 8)), jnp.float8_e4m3fn)
    assert np.all(np.asarray(zs) == 0)
    assert np.all(np.asarray(dequantize_kv(zq, zs)) == 0)


def test_fp8_attention_bounded_error():
    """fp8 pages through the ragged kernel: bounded per-element
    attention error vs f32 pages (coarser than int8 — e4m3 rounds at
    amax/16 vs amax/254 — but still far below the O(1) error of a
    mis-indexed scale)."""
    q, kp, vp, table, slots, lens = _ragged_setup(4, 21)
    kq, ks = quantize_kv_rows(kp, jnp.float8_e4m3fn)
    vq, vs = quantize_kv_rows(vp, jnp.float8_e4m3fn)
    f32 = paged_attention_ragged(q, kp, vp, table, slots, lens,
                                 use_pallas=False)
    fp8 = paged_attention_ragged(q, kq, vq, table, slots, lens,
                                 use_pallas=False, k_scales=ks,
                                 v_scales=vs)
    err = np.abs(np.asarray(fp8) - np.asarray(f32)).max()
    assert 0 < err < 0.25, f"fp8 attention error {err} out of bounds"


def test_choose_block_kv_table_and_dispatch_accounting():
    got = choose_block_kv(16, 16, 8, 64, 4)
    assert got % 16 == 0 and 16 <= got <= 16 * 16
    # int8 pages move 1/4 the bytes -> larger blocks to hit the same
    # DMA target
    assert choose_block_kv(16, 16, 8, 64, 1) >= got
    # a registered (measured) entry overrides the analytic pick
    register_block_kv(16, 8, 64, 4, 16, 48)
    try:
        assert choose_block_kv(16, 16, 8, 64, 4) == 48
    finally:
        _BLOCK_KV_TABLE.pop((16, 8, 64, 4, 16), None)
    passes = ragged_dispatch_passes(24, 16, 4)
    assert passes == {"v1": 24 * 16, "v2": 24 * 4}


# ------------------------------------------------------- engine parity
def test_int8_greedy_parity_base_workload():
    """The acceptance gate: int8 pages keep greedy token parity with
    the no-cache f32 reference on the (seeded, short) base workload —
    exactly, except at tie-margin argmax flips
    (ServeEngine.assert_token_parity, the same gate ci.sh runs) —
    with zero recompiles after warmup. The on_step audit inspects the
    live scale arrays while sequences are resident."""
    eng = ServeEngine(_lm("int8"))
    counts = eng.warmup()
    rng = np.random.RandomState(0)
    prompts = _prompts(rng, 8)
    out = eng.generate(prompts, 6,
                       on_step=lambda s: eng.check_kv_scales())
    eng.assert_token_parity(prompts, out,
                            eng.generate_reference(prompts, 6),
                            min_exact_frac=0.75)
    assert eng.compile_counts() == counts
    eng.check_kv_scales()
    eng.cache.check_invariants()


def test_int8_invariant_through_chunking_prefix_preempt_spec_rollback():
    """The quantized-parity stress: per-row write-local scales make
    the quantized content a pure function of (tokens, positions), so
    the SAME requests must decode token-identically no matter how the
    execution path slices them — different chunk budgets, prefix-cache
    hits on a warm engine, page pressure driving preemption, and
    speculation whose rejected drafts roll pages back."""
    rng = np.random.RandomState(1)
    prompts = _prompts(rng, 8, lo=6, hi=30)
    # ample pool, no speculation: the baseline stream
    eng_a = ServeEngine(_lm("int8", spec=False), spec_tokens=0)
    eng_a.warmup()
    base = eng_a.generate(prompts, 8)

    # different chunking (budget 8 vs 32) + speculation on (drafts on
    # random text are mostly rejected -> rollbacks every spec step)
    eng_b = ServeEngine(_lm("int8", budget=8), spec_tokens=3)
    eng_b.warmup()
    assert eng_b.generate(prompts, 8) == base
    # warm second pass: prefix hits attach previously committed
    # quantized pages instead of recomputing them
    out2 = eng_b.generate(prompts, 8)
    assert out2 == base
    assert eng_b.last_stats["prefix_hit_tokens"] > 0

    # tight pool: watermark blocking + preemption churn under the same
    # requests — still the same tokens
    eng_c = ServeEngine(_lm("int8", pool_pages=1 + 30, budget=16),
                        spec_tokens=2)
    eng_c.warmup()
    # audit the live scale rows mid-run, at peak residency — this is
    # the interleaving (preemption + rollback churn) most likely to
    # reuse a page slot without rewriting its scale
    assert eng_c.generate(
        prompts, 8, on_step=lambda s: eng_c.check_kv_scales()) == base
    for eng in (eng_a, eng_b, eng_c):
        eng.check_kv_scales()   # post-run: prefix-cache-parked pages
        eng.cache.check_invariants()


def test_int8_kv_stress_interleavings():
    """Scale bookkeeping through adversarial interleavings: repeated
    mixed batches over one warm engine (prefix attach/evict churn)
    under a pool small enough to preempt, with speculation rolling
    back pages, invariant-checked after every step."""
    eng = ServeEngine(_lm("int8", pool_pages=1 + 40, budget=12),
                      spec_tokens=3)
    eng.warmup()
    rng = np.random.RandomState(7)
    streams = {}
    for round_i in range(3):
        prompts = _prompts(rng, 6, lo=4, hi=24)

        def on_step(i):
            eng.cache.check_invariants()
            eng.check_kv_scales()   # live rows: residency + scales

        out = eng.generate(prompts, 6, on_step=on_step)
        eng.check_kv_scales()
        key = tuple(tuple(p) for p in prompts)
        # a replayed prompt set (same engine, different pool history)
        # must reproduce its stream exactly
        if key in streams:
            assert streams[key] == out
        streams[key] = out
    assert eng.last_stats["compile_counts"]["mixed"] == 1


def test_bf16_pages_run_and_report():
    eng = ServeEngine(_lm("bfloat16"))
    eng.warmup()
    assert not eng.kv_exact   # f32 activations round into bf16 pages
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, 4)
    out = eng.generate(prompts, 4)
    assert all(len(o) == 4 for o in out)
    pool = eng.last_stats["kv_pool"]
    assert pool["kv_dtype"] == "bfloat16"
    assert pool["bytes_per_page"] == pool["pool_bytes"] // (
        eng.cache_cfg.num_pages)
    assert pool["page_ratio_vs_f32"] == 2.0


def test_quantized_requires_chunked_prefill():
    with pytest.raises(ValueError, match="chunked"):
        ServeEngine(_lm("int8"), chunked_prefill=False)


# ------------------------------------------------- sizing / bookkeeping
def test_kv_pool_mb_sizes_pages_from_itemsize():
    """The hardcoded-4 fix: an equal byte budget yields page counts in
    the ratio of the per-page byte costs — f32 at 4 B/elem, bf16 at 2,
    int8 at 1 (+ its f32 scale rows) — so every page-fraction knob
    (watermark, ladder rungs) sees the larger effective pool."""
    def cfg_for(dtype):
        c = FFConfig(kv_page_size=8, kv_pool_mb=0.5, kv_dtype=dtype)
        return KVCacheConfig.from_ff(c, num_layers=2, num_heads=4,
                                     head_dim=8, max_seq_len=128)
    f32, bf16, int8 = (cfg_for(d) for d in ("float32", "bfloat16",
                                            "int8"))
    d = 8
    assert f32.page_bytes == 2 * 2 * 8 * 4 * d * 4
    assert bf16.page_bytes == f32.page_bytes // 2
    assert int8.page_bytes == 2 * 2 * 8 * 4 * (d + 4)  # values + scales
    assert int8.effective_page_ratio == pytest.approx(4 * d / (d + 4))
    assert int8.effective_page_ratio >= 1.9   # the capacity acceptance
    # equal budget -> proportionally more pages (floor rounding aside)
    assert bf16.usable_pages >= 2 * f32.usable_pages - 2
    assert int8.usable_pages >= int(1.9 * f32.usable_pages)
    # pool bytes never exceed the budget
    for c in (f32, bf16, int8):
        assert c.num_pages * c.page_bytes <= 0.5 * (1 << 20) \
            + c.page_bytes


def test_scale_meta_wired_into_check_invariants():
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=7, max_seqs=2,
                        max_seq_len=16, kv_dtype="int8")
    cache = PagedKVCache(cfg)
    cache.check_invariants()   # quantized, meta not yet registered: ok
    ks, vs = cache.alloc_scale_arrays()
    cache.register_scale_meta(ks, vs)
    cache.check_invariants()
    # geometry drift must be caught
    cache.register_scale_meta(ks[:, :3], vs)
    with pytest.raises(AssertionError, match="scale arrays"):
        cache.check_invariants()
    # a lossless pool must not carry scale bookkeeping
    plain = PagedKVCache(KVCacheConfig(
        num_layers=1, num_heads=2, head_dim=4, page_size=4,
        num_pages=7, max_seqs=2, max_seq_len=16))
    plain._scale_meta = ("bogus",) * 4
    with pytest.raises(AssertionError, match="scale bookkeeping"):
        plain.check_invariants()
    with pytest.raises(RuntimeError, match="int8"):
        plain.alloc_scale_arrays()


def test_kv_pool_stats_and_serve_report_line():
    from flexflow_tpu.utils.profiling import serve_report
    eng = ServeEngine(_lm("int8"))
    eng.warmup()
    rng = np.random.RandomState(2)
    eng.generate(_prompts(rng, 3), 3)
    pool = eng.last_stats["kv_pool"]
    for key in ("kv_dtype", "bytes_per_page", "effective_pages",
                "pool_bytes", "occupancy", "page_ratio_vs_f32",
                "pages_saved_vs_f32", "attn_block_kv",
                "attn_dispatch_passes"):
        assert key in pool, key
    assert pool["kv_dtype"] == "int8" and not pool["kv_exact"]
    dp = pool["attn_dispatch_passes"]
    assert dp["v1"] > dp["v2"] > 0
    report = serve_report(eng.last_stats)
    assert "kv pool: int8 pages" in report
    assert "ragged kernel v2" in report


def test_serve_attn_block_kv_knob():
    lm = _lm("float32", serve_attn_block_kv=8)
    eng = ServeEngine(lm)
    assert eng.attn_block_kv == 8
    rng = np.random.RandomState(4)
    prompts = _prompts(rng, 3)
    eng.warmup()
    out = eng.generate(prompts, 4)
    # fp32 + explicit block shape: still bit-exact vs the reference
    assert out == eng.generate_reference(prompts, 4)


def test_kv_cli_flags():
    cfg = FFConfig(argv=["--kv-dtype", "int8", "--kv-pool-mb", "2.5",
                         "--serve-attn-block-kv", "32"])
    assert cfg.kv_dtype == "int8"
    assert cfg.kv_pool_mb == 2.5
    assert cfg.serve_attn_block_kv == 32
    with pytest.raises(ValueError, match="kv_dtype"):
        FFConfig(kv_dtype="fp8")
    with pytest.raises(ValueError, match="kv_pool_mb"):
        FFConfig(kv_pool_mb=-1)
    with pytest.raises(ValueError, match="serve_attn_block_kv"):
        FFConfig(serve_attn_block_kv=-2)


# ------------------------------------------- auto grad_bucket_mb (PR 7)
def test_auto_grad_bucket_mb_resolution():
    """The ROADMAP leftover: an unset grad_bucket_mb auto-tunes from
    the machine model, identically in the executor and the simulator,
    with explicit values authoritative and the RESOLVED value folded
    into the cost-cache fingerprint."""
    from flexflow_tpu import SGDOptimizer, make_mesh
    from flexflow_tpu.core.overlap import resolve_bucket_mb
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.search.simulator import Simulator

    cfg = FFConfig(batch_size=8)
    assert cfg.grad_bucket_mb is None          # the new default
    ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=10)
    mesh = make_mesh((4, 2), ("data", "model"))
    auto = resolve_bucket_mb(cfg, ff, mesh=mesh)
    assert auto > 0
    # deterministic, and 0 (monolithic) without a data axis to sync
    assert resolve_bucket_mb(cfg, ff, mesh=mesh) == auto
    assert resolve_bucket_mb(cfg, ff, mesh=None) == 0.0
    # explicit values are authoritative, including 0
    cfg.grad_bucket_mb = 0.0
    assert resolve_bucket_mb(cfg, ff, mesh=mesh) == 0.0
    cfg.grad_bucket_mb = 9.5
    assert resolve_bucket_mb(cfg, ff, mesh=mesh) == 9.5
    cfg.grad_bucket_mb = None
    ff.compile(optimizer=SGDOptimizer(lr=0.05), mesh=mesh)
    assert ff.executor._grad_bucket_mb == auto
    sim = Simulator(ff, mesh)
    assert sim.bucket_mb == auto
    # the fingerprint sees the RESOLVED value, not the None sentinel
    assert sim.overlap_sig() == (True, auto)
