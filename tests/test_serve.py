"""flexflow_tpu.serve: paged KV-cache, continuous batching, ServeEngine.

Three layers of coverage, mirroring the subsystem's layering:
  * kernel — paged decode attention equals full-prefill attention
    BIT-FOR-BIT on CPU at ragged batch sizes {1, 3, 8} (the page
    indirection must be exact, not approximately right), and the
    Pallas kernel (interpret mode) agrees with the jnp fallback.
  * scheduler — property-style invariants over a randomized workload:
    no page leaks after eviction, the waiting queue drains, the
    prefill token budget is never exceeded.
  * engine — generate() on a ragged batch produces tokens identical
    to the naive no-cache greedy-decode reference, with ZERO
    recompiles after warmup.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.config import FFConfig
from flexflow_tpu.kernels.flash_attention import (
    _paged_decode_jnp,
    paged_attention_decode,
)
from flexflow_tpu.serve.kv_cache import KVCacheConfig, PagedKVCache
from flexflow_tpu.serve.scheduler import ContinuousBatchingScheduler


# --------------------------------------------------------------- helpers
def _ragged_setup(batch, seed, page_size=4, pages_per_seq=6):
    """Random ragged K/V histories scattered into pages. Returns
    (q, k_pages, v_pages, page_table, seq_lens, k_full, v_full) where
    k_full/v_full are the same histories laid out contiguously (padded
    with zeros), the layout full-prefill attention reads."""
    rng = np.random.RandomState(seed)
    h, d = 4, 8
    max_len = pages_per_seq * page_size
    num_pages = 1 + batch * pages_per_seq
    lens = rng.randint(1, max_len + 1, size=batch)
    k_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    v_pages = np.zeros((num_pages, page_size, h, d), np.float32)
    table = np.zeros((batch, pages_per_seq), np.int32)
    k_full = np.zeros((batch, max_len, h, d), np.float32)
    v_full = np.zeros((batch, max_len, h, d), np.float32)
    # shuffled pool: page tables are deliberately non-contiguous
    pool = list(rng.permutation(np.arange(1, num_pages)))
    for b, L in enumerate(lens):
        k_full[b, :L] = rng.randn(L, h, d)
        v_full[b, :L] = rng.randn(L, h, d)
        for i in range(-(-int(L) // page_size)):
            p = int(pool.pop())
            table[b, i] = p
            chunk = slice(i * page_size, min((i + 1) * page_size, int(L)))
            n = chunk.stop - chunk.start
            k_pages[p, :n] = k_full[b, chunk]
            v_pages[p, :n] = v_full[b, chunk]
    q = rng.randn(batch, h, d).astype(np.float32)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(lens.astype(np.int32)),
            jnp.asarray(k_full), jnp.asarray(v_full))


def _full_prefill_attention(q, k_full, v_full, seq_lens, scale):
    """The attention a full prefill computes at the last position, on
    the CONTIGUOUS layout, with the exact op sequence of the paged
    path (dot_general dims, divide-after-matmul) so equality is
    bitwise when the page indirection is exact."""
    b, t, h, d = k_full.shape
    s = jax.lax.dot_general(
        q, k_full, (((2,), (3,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32) * scale
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, 1, t), 2)
    s = jnp.where(pos < seq_lens[:, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v_full.astype(jnp.float32), (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32)
    return (o / l).astype(q.dtype)


# ------------------------------------------------------- kernel parity
@pytest.mark.parametrize("batch", [1, 3, 8])
def test_paged_decode_bitwise_vs_full_prefill(batch):
    q, kp, vp, table, lens, k_full, v_full = _ragged_setup(batch, batch)
    scale = 1.0 / np.sqrt(q.shape[-1])
    out = paged_attention_decode(q, kp, vp, table, lens, scale=scale,
                                 use_pallas=False)
    ref = _full_prefill_attention(q, k_full, v_full, lens, scale)
    assert out.dtype == ref.dtype
    # bit-for-bit: the page table is pure indirection, zero numerics
    assert np.array_equal(np.asarray(out), np.asarray(ref)), (
        np.abs(np.asarray(out) - np.asarray(ref)).max())


@pytest.mark.parametrize("batch", [1, 3])
def test_paged_decode_pallas_interpret_matches_jnp(batch):
    q, kp, vp, table, lens, _, _ = _ragged_setup(batch, 100 + batch)
    scale = 1.0 / np.sqrt(q.shape[-1])
    ref = _paged_decode_jnp(q, kp, vp, table, lens, scale)
    out = paged_attention_decode(q, kp, vp, table, lens, scale=scale,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


# --------------------------------------------------------- kv cache
def test_kv_cache_alloc_free_cycle():
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=9, max_seqs=2,
                        max_seq_len=16)
    cache = PagedKVCache(cfg)
    assert cache.free_pages == 8
    s0 = cache.alloc_slot()
    cache.ensure_capacity(s0, 5)       # 2 pages, on demand
    cache.advance(s0, 5)
    s1 = cache.alloc_slot()
    cache.ensure_capacity(s1, 3)       # 1 page — no worst-case reserve
    cache.advance(s1, 3)
    cache.check_invariants()
    assert cache.free_pages == 5
    assert cache.free_slots == 0
    # append across a page boundary allocates exactly when crossed
    assert cache.append_token(s0) == 5
    assert cache.append_token(s0) == 6
    assert cache.append_token(s0) == 7
    assert cache.free_pages == 5       # page 2 still has room
    assert cache.append_token(s0) == 8  # crosses into a third page
    assert cache.free_pages == 4
    cache.check_invariants()
    cache.free_slot(s0)
    cache.check_invariants()
    assert cache.free_pages == 7
    cache.free_slot(s1)
    assert cache.free_pages == 8
    assert cache.free_slots == 2


def test_kv_cache_exhaustion_recovers():
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=5, max_seqs=2,
                        max_seq_len=16)
    cache = PagedKVCache(cfg)
    s0 = cache.alloc_slot()
    cache.ensure_capacity(s0, 16)      # the whole pool (4 pages)
    cache.advance(s0, 16)
    s1 = cache.alloc_slot()
    with pytest.raises(RuntimeError):  # pool dry: scheduler must preempt
        cache.ensure_capacity(s1, 1)
    with pytest.raises(ValueError):    # past the page-table ceiling
        cache.ensure_capacity(s0, 17)
    cache.free_slot(s0)                # frees admit again
    cache.check_invariants()
    assert cache.ensure_capacity(s1, 4) == 1
    cache.free_slot(s1)
    assert cache.free_pages == cfg.usable_pages


# --------------------------------------------------------- scheduler
def _drive_step(sched, cache, plan):
    """What the engine does with a plan, minus the device work:
    bookkeeping first (complete_chunk), then emissions."""
    for ch in plan.chunks:
        sched.complete_chunk(ch)
    for ch in plan.chunks:
        if ch.emits:
            ch.req.out_tokens.append(0)
            if ch.req.is_done():
                sched.finish(ch.req)


def test_scheduler_invariants_random_workload():
    """Drive the scheduler host-side (no device work): FCFS admission
    under the token budget, chunked prefill progress, eviction +
    backfill, and page accounting hold for every step of a randomized
    ragged workload."""
    rng = np.random.RandomState(7)
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4,
                        page_size=4, num_pages=33, max_seqs=3,
                        max_seq_len=32)
    cache = PagedKVCache(cfg)
    budget = 12
    sched = ContinuousBatchingScheduler(cache, prefill_token_budget=budget)
    reqs = [sched.submit(list(rng.randint(0, 50, size=rng.randint(1, 20))),
                         int(rng.randint(1, 12)))
            for _ in range(20)]
    admitted_order = []
    steps = 0
    while sched.has_work():
        steps += 1
        assert steps < 2000, "scheduler wedged"
        plan = sched.schedule()
        assert plan.chunks, "a step with work must plan chunks"
        # chunked prefill: prefill lanes never exceed the budget, and
        # decode lanes (one per running sequence) never wait on them
        assert plan.num_prefill_lanes <= budget
        assert plan.num_decode_lanes <= cfg.max_seqs
        admitted_order += [r.rid for r in plan.admitted]
        _drive_step(sched, cache, plan)
        cache.check_invariants()
    # queue drained, every request ran to completion, FCFS order held
    # (this pool never fills, so no preemption re-admissions)
    assert not sched.waiting and not sched.running
    assert sched.stats["preemptions"] == 0
    assert admitted_order == sorted(admitted_order)
    assert all(len(r.out_tokens) == r.max_new_tokens for r in reqs)
    # eviction returned every page (hashed ones park reclaimable)
    assert cache.free_pages == cfg.usable_pages
    assert cache.free_slots == cfg.max_seqs


# --------------------------------------------------------- engine e2e
@pytest.fixture(scope="module")
def lm_engine():
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48)
    ff = build_transformer_lm(cfg, vocab_size=89, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    eng = ServeEngine(ff)
    eng.warmup()
    return eng


@pytest.mark.parametrize("batch", [1, 3, 8])
def test_generate_matches_nocache_reference(lm_engine, batch):
    """Ragged prompts, ragged max-new-tokens: continuous-batched paged
    decoding must produce the exact token streams of the naive
    re-forward-everything reference, without compiling anything new
    after warmup."""
    rng = np.random.RandomState(batch)
    prompts = [list(rng.randint(1, 89, size=rng.randint(1, 30)))
               for _ in range(batch)]
    max_new = [int(rng.randint(1, 10)) for _ in range(batch)]
    before = lm_engine.compile_counts()
    out = lm_engine.generate(prompts, max_new)
    assert lm_engine.compile_counts() == before, "serving recompiled"
    ref = lm_engine.generate_reference(prompts, max_new)
    assert out == ref
    assert [len(o) for o in out] == max_new
    stats = lm_engine.last_stats
    assert stats["total_new_tokens"] == sum(max_new)
    assert stats["tokens_per_sec"] > 0


def test_generate_more_requests_than_slots(lm_engine):
    """12 requests through 8 slots: the waiting queue must drain via
    finished-sequence eviction + backfill, still matching the
    reference."""
    rng = np.random.RandomState(42)
    prompts = [list(rng.randint(1, 89, size=rng.randint(1, 24)))
               for _ in range(12)]
    out = lm_engine.generate(prompts, 5)
    ref = lm_engine.generate_reference(prompts, 5)
    assert out == ref


def test_eos_stops_early(lm_engine):
    """Pick the token the model actually emits first as EOS: the
    request must finish at that point, shorter than max_new."""
    prompts = [[5, 6, 7]]
    free = lm_engine.generate(prompts, 8)
    eos = free[0][0]
    out = lm_engine.generate(prompts, 8, eos_token=eos)
    assert out[0] == [eos]


def test_serve_report_renders(lm_engine):
    from flexflow_tpu.utils.profiling import serve_report
    lm_engine.generate([[1, 2, 3], [4]], 4)
    rep = serve_report(lm_engine.last_stats)
    assert "tok/s" in rep and "p99" in rep
    assert "mixed=1" in rep  # ONE serving program compiled, ever
    assert "prefix" in rep and "preemptions" in rep
