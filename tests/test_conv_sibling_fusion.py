"""Sibling-conv batching must be a pure execution change: convs that
read the same tensor with the same geometry run as ONE merged conv
(kernels concatenated along channel-out, outputs sliced back), and the
losses/weights after training must match the unmerged walk. This is the
TPU-shaped counterpart of the reference's per-shape cuDNN algorithm
selection (src/ops/conv_2d.cu:173-260): there the fix for poor conv
shapes is a better algorithm, here it is better MXU lane packing."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.core.fusion import conv_sibling_groups


def _build_inception_module(fuse, layout="NCHW", remat=False):
    """An Inception-ish module: three 1x1 branch heads on one input
    (mergeable), one 1x1 on the pooled input (different tensor — NOT
    mergeable), a 3x3 on one branch, then concat."""
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.sibling_conv_fusion = fuse
    cfg.conv_layout = layout
    cfg.remat = remat
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16, 8, 8), name="input")
    b1 = ff.conv2d(x, 12, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = ff.conv2d(x, 6, 1, 1, 1, 1, 0, 0, activation="relu")
    b3 = ff.conv2d(x, 10, 1, 1, 1, 1, 0, 0, activation="relu")
    b3 = ff.conv2d(b3, 8, 3, 3, 1, 1, 1, 1, activation="relu")
    p = ff.pool2d(x, 3, 3, 1, 1, 1, 1)
    b4 = ff.conv2d(p, 4, 1, 1, 1, 1, 0, 0, activation="relu")
    t = ff.concat([b1, b2, b3, b4], axis=1)
    t = ff.flat(t)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def test_sibling_groups_found():
    ff = _build_inception_module(fuse=True)
    groups = conv_sibling_groups(ff)
    assert len(groups) == 1
    (g,) = groups
    # the three 1x1 heads on the module input — NOT the 3x3 (geometry),
    # NOT the pool-projection (different input tensor)
    assert [op.out_channels for op in g] == [12, 6, 10]
    assert ff.executor._conv_merge_leader  # wired into the walk


def test_different_stride_not_grouped():
    cfg = FFConfig()
    ff = FFModel(cfg)
    x = ff.create_tensor((4, 8, 8, 8), name="input")
    ff.conv2d(x, 4, 1, 1, 1, 1, 0, 0)
    ff.conv2d(x, 4, 1, 1, 2, 2, 0, 0)  # stride differs
    ff.conv2d(x, 4, 3, 3, 1, 1, 1, 1)  # kernel differs
    assert conv_sibling_groups(ff) == []


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_merged_matches_unmerged_training(layout):
    rng = np.random.RandomState(0)
    batches = [{"input": rng.randn(8, 16, 8, 8).astype(np.float32),
                "label": rng.randint(0, 4, (8,))} for _ in range(3)]
    a = _build_inception_module(fuse=False, layout=layout)
    b = _build_inception_module(fuse=True, layout=layout)
    for batch in batches:
        la = float(a.train_batch(batch)["loss"])
        lb = float(b.train_batch(batch)["loss"])
        np.testing.assert_allclose(la, lb, rtol=2e-5)
    for op in a.ops:
        if not op.weight_specs():
            continue
        wa = a.get_weights(op.name)
        wb = b.get_weights(op.name)
        for k in wa:
            np.testing.assert_allclose(
                wa[k], wb[k], rtol=2e-4, atol=2e-5,
                err_msg=f"{op.name}.{k} diverged under sibling fusion")


def test_remat_composes_with_sibling_fusion():
    rng = np.random.RandomState(1)
    batch = {"input": rng.randn(8, 16, 8, 8).astype(np.float32),
             "label": rng.randint(0, 4, (8,))}
    a = _build_inception_module(fuse=False)
    cfg_loss = float(a.train_batch(batch)["loss"])
    b = _build_inception_module(fuse=True, remat=True)
    np.testing.assert_allclose(
        float(b.train_batch(batch)["loss"]), cfg_loss, rtol=2e-5)
