"""Expert-parallel MoE tests: the fused MoEFFN op with the expert axis
sharded over the mesh — the all-to-all EP dispatch the reference lacked
(SURVEY.md 2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy


def expert_parallel_strategy():
    return Strategy(default=OpStrategy({"sample": "data",
                                        "expert": "expert"}))


def build_moe(cfg, mesh=None, strategy=None):
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.moe_ffn(t, num_experts=4, k=2, hidden_dim=64,
                   capacity_factor=2.0)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh, strategy=strategy)
    return ff


def data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def test_moe_ffn_trains_single_device():
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build_moe(cfg)
    x, y = data()
    hist = ff.fit({"input": x}, y, epochs=8, verbose=False)
    assert hist[-1]["accuracy"] > 0.9, hist[-1]
    assert np.isfinite(hist[-1]["loss"])


def test_moe_expert_weights_sharded():
    cfg = FFConfig()
    cfg.batch_size = 32
    mesh = make_mesh((2, 4), ("data", "expert"))
    ff = build_moe(cfg, mesh=mesh, strategy=expert_parallel_strategy())
    w1 = ff.state.params["moe_ffn"]["w1"]  # (4, 32, 64)
    assert w1.sharding.spec == P("expert",), w1.sharding.spec


def test_moe_ep_matches_unsharded():
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()
    ff1 = build_moe(cfg)
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((2, 4), ("data", "expert"))
    ff2 = build_moe(cfg, mesh=mesh, strategy=expert_parallel_strategy())
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)
    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1[-1], h2[-1])


def test_moe_aux_loss_present():
    """Training loss must include the load-balancing aux term."""
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build_moe(cfg)
    x, y = data(64)
    m_train = ff.train_batch({"input": x, "label": y})
    ev = ff.evaluate({"input": x}, y)
    # aux loss is only added in training mode; train loss > eval loss by
    # roughly the aux magnitude on the same params is hard to assert
    # exactly post-update, so just require both finite and positive.
    assert np.isfinite(float(m_train["loss"]))
    assert np.isfinite(ev["loss"])
