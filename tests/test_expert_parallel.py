"""Expert-parallel MoE tests: the fused MoEFFN op with the expert axis
sharded over the mesh — the all-to-all EP dispatch the reference lacked
(SURVEY.md 2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy


def expert_parallel_strategy():
    return Strategy(default=OpStrategy({"sample": "data",
                                        "expert": "expert"}))


def build_moe(cfg, mesh=None, strategy=None):
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.moe_ffn(t, num_experts=4, k=2, hidden_dim=64,
                   capacity_factor=2.0)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh, strategy=strategy)
    return ff


def data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def test_moe_ffn_trains_single_device():
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build_moe(cfg)
    x, y = data()
    hist = ff.fit({"input": x}, y, epochs=8, verbose=False)
    assert hist[-1]["accuracy"] > 0.9, hist[-1]
    assert np.isfinite(hist[-1]["loss"])


def test_moe_expert_weights_sharded():
    cfg = FFConfig()
    cfg.batch_size = 32
    mesh = make_mesh((2, 4), ("data", "expert"))
    ff = build_moe(cfg, mesh=mesh, strategy=expert_parallel_strategy())
    w1 = ff.state.params["moe_ffn"]["w1"]  # (4, 32, 64)
    assert w1.sharding.spec == P("expert",), w1.sharding.spec


def test_moe_ep_matches_unsharded():
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()
    ff1 = build_moe(cfg)
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((2, 4), ("data", "expert"))
    ff2 = build_moe(cfg, mesh=mesh, strategy=expert_parallel_strategy())
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)
    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1[-1], h2[-1])


def test_moe_aux_loss_present():
    """Training loss must include the load-balancing aux term."""
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build_moe(cfg)
    x, y = data(64)
    m_train = ff.train_batch({"input": x, "label": y})
    ev = ff.evaluate({"input": x}, y)
    # aux loss is only added in training mode; train loss > eval loss by
    # roughly the aux magnitude on the same params is hard to assert
    # exactly post-update, so just require both finite and positive.
    assert np.isfinite(float(m_train["loss"]))
    assert np.isfinite(ev["loss"])


# ---------------------------------------------- sorted-scatter dispatch
def build_moe_mode(cfg, mode):
    cfg.moe_dispatch = mode
    return build_moe(cfg)


def test_sorted_dispatch_matches_dense_bitwise():
    """The scalable argsort routing (VERDICT r3 #8) must reproduce the
    dense GShard mask exactly: same ranks (stable sort = cumsum order),
    same capacity drops, same combine."""
    x, y = data(64)
    outs, weights = {}, {}
    for mode in ("dense", "sorted"):
        cfg = FFConfig()
        cfg.batch_size = 64
        ff = build_moe_mode(cfg, mode)
        if weights:
            for op in ff.ops:
                if op.weight_specs():
                    ff.set_weights(op.name, weights[op.name])
        else:
            weights = {op.name: ff.get_weights(op.name)
                       for op in ff.ops if op.weight_specs()}
        outs[mode] = np.asarray(ff.forward({"input": x[:64]}))
        # two optimizer steps: gradients must match through the scatter
        for _ in range(2):
            m = ff.train_batch({"input": x[:64], "label": y[:64]})
        outs[mode + "_loss"] = float(m["loss"])
        outs[mode + "_w1"] = ff.get_weights("moe_ffn")["w1"]
    np.testing.assert_array_equal(outs["dense"], outs["sorted"])
    np.testing.assert_allclose(outs["dense_loss"], outs["sorted_loss"],
                               rtol=1e-6)
    np.testing.assert_allclose(outs["dense_w1"], outs["sorted_w1"],
                               rtol=1e-5, atol=1e-7)


def test_dispatch_indices_capacity_semantics():
    """Rank/drop parity with the dense mask on a hand-checkable case."""
    from flexflow_tpu.ops.moe import dispatch_indices, dispatch_mask
    assign = jnp.asarray([[0, 1], [0, 0], [2, 0], [0, 1]], jnp.int32)
    e, cap = 3, 2
    mask = np.asarray(dispatch_mask(assign, e, cap))  # (8, 3, 2)
    pos, keep = dispatch_indices(assign, e, cap)
    pos, keep = np.asarray(pos), np.asarray(keep)
    for s in range(8):
        if keep[s]:
            exp, rank = divmod(int(pos[s]), cap)
            assert mask[s, exp, rank] == 1.0, (s, exp, rank)
            assert mask[s].sum() == 1.0
        else:
            assert mask[s].sum() == 0.0, s  # dense dropped it too


def test_auto_switches_to_sorted_for_large_e():
    """auto: dense under the mask limit, sorted above it (E=64 at a
    few thousand tokens crosses DENSE_MASK_ELEMENT_LIMIT)."""
    from flexflow_tpu.ops.moe import (DENSE_MASK_ELEMENT_LIMIT,
                                      use_sorted_dispatch)

    class _M:
        config = FFConfig()

    m = _M()
    assert not use_sorted_dispatch(m, 64 * 2, 4, 32, False)
    big_slots = DENSE_MASK_ELEMENT_LIMIT // (64 * 128) + 1
    assert use_sorted_dispatch(m, big_slots, 64, 128, False)
    # EP sharding keeps the einsum/all-to-all lowering
    assert not use_sorted_dispatch(m, big_slots, 64, 128, True)


def test_group_by_sorted_parity():
    from flexflow_tpu.ops.moe import dispatch_indices, sorted_dispatch
    rng = np.random.RandomState(1)
    data_ = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    assign = jnp.asarray(rng.randint(0, 4, (32, 2)), jnp.int32)
    from flexflow_tpu.ops.moe import dispatch_mask
    cap = 16
    mask = dispatch_mask(assign, 4, cap)
    xrep = jnp.repeat(data_, 2, axis=0)
    dense = jnp.einsum("snc,sd->ncd", mask, xrep)
    pos, keep = dispatch_indices(assign, 4, cap)
    sorted_ = sorted_dispatch(xrep, pos, keep, 4, cap)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(sorted_),
                               rtol=1e-6, atol=1e-7)


def test_dispatch_indices_drops_invalid_expert_ids():
    """-1 padding (and out-of-range ids) must contribute nothing — the
    dense one_hot path zeroes them; the scatter path must not let a
    negative position wrap into the last expert's buffer."""
    from flexflow_tpu.ops.moe import (dispatch_indices, dispatch_mask,
                                      sorted_dispatch)
    assign = jnp.asarray([[0, -1], [2, 5], [-1, 1]], jnp.int32)  # E=3
    e, cap = 3, 2
    pos, keep = dispatch_indices(assign, e, cap)
    assert not bool(keep[1]) and not bool(keep[3])  # -1 and 5 dropped
    xrep = jnp.ones((6, 4), jnp.float32)
    buf = sorted_dispatch(xrep, pos, keep, e, cap)
    dense = jnp.einsum("snc,sd->ncd", dispatch_mask(assign, e, cap), xrep)
    np.testing.assert_array_equal(np.asarray(buf), np.asarray(dense))
