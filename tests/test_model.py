"""End-to-end training tests: graph building, compile, fit.

Pattern follows reference tests/accuracy_tests.sh — train few epochs on a
small problem and assert the model actually learns."""

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer


def make_mlp(config=None):
    ff = FFModel(config or FFConfig())
    x = ff.create_tensor((config.batch_size if config else 64, 16),
                         name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    return ff


def synthetic_classification(n=512, d=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, classes).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1).astype(np.int32)
    return x, y


def test_mlp_learns():
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = make_mlp(cfg)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_classification()
    hist = ff.fit({"input": x}, y, epochs=12, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_mlp_adam_learns():
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = make_mlp(cfg)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_classification()
    hist = ff.fit({"input": x}, y, epochs=8, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]


def test_cnn_trains_and_bn_state_updates():
    cfg = FFConfig()
    cfg.batch_size = 16
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 3, 8, 8), name="input")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.batch_norm(t, relu=True)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 3, 8, 8).astype(np.float32)
    ys = (xs.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    rm_before = np.asarray(
        ff.state.states["batch_norm"]["running_mean"]).copy()
    hist = ff.fit({"input": xs}, ys, epochs=3, verbose=False)
    rm_after = np.asarray(ff.state.states["batch_norm"]["running_mean"])
    assert not np.allclose(rm_before, rm_after), "BN stats must update"
    assert np.isfinite(hist[-1]["loss"])


def test_weight_get_set_roundtrip():
    cfg = FFConfig()
    ff = make_mlp(cfg)
    ff.compile()
    w = ff.get_weights("dense")
    assert w["kernel"].shape == (16, 32)
    neww = {k: np.zeros_like(v) for k, v in w.items()}
    ff.set_weights("dense", neww)
    w2 = ff.get_weights("dense")
    np.testing.assert_allclose(w2["kernel"], 0.0)


def test_mse_regression_learns():
    cfg = FFConfig()
    cfg.batch_size = 32
    ff = FFModel(cfg)
    x = ff.create_tensor((32, 8), name="input")
    t = ff.dense(x, 16, activation="tanh")
    t = ff.dense(t, 1)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="mean_squared_error", metrics=[])
    rng = np.random.RandomState(0)
    xs = rng.randn(256, 8).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
    hist = ff.fit({"input": xs}, ys, epochs=10, verbose=False)
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.5


def test_summary():
    cfg = FFConfig()
    ff = make_mlp(cfg)
    s = ff.summary()
    assert "dense" in s and "total params" in s


def test_hlo_cost_extraction(rng):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.utils.profiling import hlo_cost
    cfg = FFConfig(); cfg.batch_size = 8
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), name="input")
    h = ff.dense(x, 32, activation="relu", name="fc1")
    ff.softmax(ff.dense(h, 10, name="fc2"), name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    c = hlo_cost(ff, {"input": rng.randn(8, 16).astype(np.float32),
                      "label": rng.randint(0, 10, 8).astype(np.int32)})
    assert c.get("flops", 0) > 0


def test_imported_weights_applied_at_compile(rng):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    cfg = FFConfig(); cfg.batch_size = 4
    ff = FFModel(cfg)
    x = ff.create_tensor((4, 8), name="input")
    ff.softmax(ff.dense(x, 3, name="fc"), name="sm")
    w = rng.randn(8, 3).astype(np.float32)
    ff.imported_weights["fc"] = {"kernel": w}
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    np.testing.assert_allclose(ff.get_weights("fc")["kernel"], w)


def test_train_batches_matches_sequential():
    """The scanned multi-step dispatch (train_batches, the trace-replay
    analog of alexnet.cc:106-111 begin/end_trace) must reproduce the
    single-step stream EXACTLY: same rng fold_in sequence, same updates."""
    import jax

    rng = np.random.RandomState(3)
    batches = [{"input": rng.randn(8, 16).astype(np.float32),
                "label": rng.randint(0, 4, (8,))} for _ in range(4)]

    def build():
        cfg = FFConfig()
        cfg.batch_size = 8
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 16), name="input")
        h = ff.dense(t, 32, activation="relu")
        h = ff.dropout(h, 0.1)
        ff.dense(h, 4)
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"])
        return ff

    seq = build()
    seq_losses = [float(seq.train_batch(b)["loss"]) for b in batches]

    grouped = build()
    ms = grouped.train_batches(batches[:3])   # one dispatch, 3 steps
    tail = grouped.train_batch(batches[3])    # ragged tail, single step
    assert jax.device_get(ms["loss"]).shape == (3,)
    got = list(jax.device_get(ms["loss"])) + [float(tail["loss"])]
    np.testing.assert_allclose(seq_losses, got, rtol=1e-6)
    name = seq.ops[-1].name
    for k, v in seq.get_weights(name).items():
        np.testing.assert_allclose(v, grouped.get_weights(name)[k],
                                   rtol=1e-5)


def test_train_batches_unrolled_matches_scan():
    """config.multi_step_unroll=True (the big-param body that avoids the
    TPU scan carry's double-buffering — DLRM 26x1M tables OOM'd the
    scanned program on v5e, evidence/tpu_session_20260731T101421Z.log)
    must be bit-compatible with the scanned body."""
    import jax

    rng = np.random.RandomState(7)
    batches = [{"input": rng.randn(8, 16).astype(np.float32),
                "label": rng.randint(0, 4, (8,))} for _ in range(3)]

    def build(unroll):
        cfg = FFConfig()
        cfg.batch_size = 8
        cfg.multi_step_unroll = unroll
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 16), name="input")
        h = ff.dense(t, 32, activation="relu")
        ff.dense(h, 4)
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"])
        return ff

    scan, unrolled = build(False), build(True)
    ls = jax.device_get(scan.train_batches(batches)["loss"])
    lu = jax.device_get(unrolled.train_batches(batches)["loss"])
    assert ls.shape == lu.shape == (3,)
    np.testing.assert_allclose(ls, lu, rtol=1e-6)
    name = scan.ops[-1].name
    for k, v in scan.get_weights(name).items():
        np.testing.assert_allclose(v, unrolled.get_weights(name)[k],
                                   rtol=1e-5)


def test_fit_steps_per_dispatch():
    ff = make_mlp()
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_classification()
    h1 = ff.fit({"input": x}, y, epochs=2, steps_per_dispatch=4,
                verbose=False)
    assert len(h1) == 2
    assert h1[-1]["loss"] < h1[0]["loss"]


def test_fit_prefetch_matches_direct():
    """fit(prefetch=True) rides the (native, if available) double-
    buffered loader but must reproduce the direct path's losses exactly
    — same permutation stream, same batches, same updates."""
    x, y = synthetic_classification()

    def run(prefetch):
        ff = make_mlp()
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"])
        return ff.fit({"input": x}, y, epochs=3, verbose=False,
                      steps_per_dispatch=2, prefetch=prefetch)

    ha, hb = run(False), run(True)
    for ma, mb in zip(ha, hb):
        np.testing.assert_allclose(ma["loss"], mb["loss"], rtol=1e-6)
        np.testing.assert_allclose(ma.get("accuracy", 0),
                                   mb.get("accuracy", 0), rtol=1e-6)


def test_evaluate_steps_per_dispatch_matches():
    ff = make_mlp()
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_classification(n=320)
    ff.fit({"input": x}, y, epochs=2, verbose=False)
    a = ff.evaluate({"input": x}, y)
    b = ff.evaluate({"input": x}, y, steps_per_dispatch=3)  # ragged tail
    np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-6)
    np.testing.assert_allclose(a["accuracy"], b["accuracy"], rtol=1e-6)


def test_comp_mode_inference():
    """compile(comp_mode=INFERENCE): no optimizer slots are allocated
    (reference COMP_MODE_INFERENCE, ffconst.h), forward/evaluate work,
    and training fails with a clear error instead of a silent step."""
    from flexflow_tpu.config import CompMode

    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], comp_mode=CompMode.INFERENCE)
    assert ff.state.opt_state == {}  # no m/v slots
    rng = np.random.RandomState(0)
    b = {"input": rng.randn(8, 16).astype(np.float32),
         "label": rng.randint(0, 4, 8).astype(np.int32)}
    logits = ff.forward(b)
    assert logits.shape == (8, 4)
    m = ff.evaluate({"input": b["input"]}, b["label"])
    assert "loss" in m
    with pytest.raises(RuntimeError, match="INFERENCE"):
        ff.train_batch(b)
    # training compile of the same graph allocates the slots
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    assert ff.state.opt_state
    assert np.isfinite(float(ff.train_batch(b)["loss"]))
    # typos must fail loudly, not silently compile for training
    with pytest.raises(ValueError, match="comp_mode"):
        ff.compile(optimizer=AdamOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], comp_mode="Inference")


def test_inference_restores_training_checkpoint(tmp_path):
    """train -> checkpoint -> inference-compile -> restore: the on-disk
    optimizer slots are skipped (not structure-mismatched) and the
    restored forward matches the training model's."""
    from flexflow_tpu.config import CompMode
    from flexflow_tpu.core.checkpoint import restore_model, save_model

    def build(mode):
        cfg = FFConfig()
        cfg.batch_size = 8
        ff = FFModel(cfg)
        x = ff.create_tensor((8, 16), name="input")
        ff.softmax(ff.dense(ff.dense(x, 32, activation="relu"), 4))
        ff.compile(optimizer=AdamOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], comp_mode=mode)
        return ff

    rng = np.random.RandomState(0)
    b = {"input": rng.randn(8, 16).astype(np.float32),
         "label": rng.randint(0, 4, 8).astype(np.int32)}
    ff = build(CompMode.TRAINING)
    ff.train_batch(b)
    save_model(ff, str(tmp_path / "ckpt"))
    fi = build(CompMode.INFERENCE)
    restore_model(fi, str(tmp_path / "ckpt"))
    assert int(fi.state.step) == 1 and fi.state.opt_state == {}
    np.testing.assert_allclose(np.asarray(fi.forward(b)),
                               np.asarray(ff.forward(b)), rtol=1e-6)
