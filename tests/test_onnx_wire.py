"""Real ONNX wire-format import (VERDICT r2 #6/#7).

The files under test are REAL protobuf artifacts serialized by torch's
C++ ONNX exporter (an independent producer); the in-tree decoder
(frontends/onnx_wire.py) must read them with zero dependencies —
matching the reference CI's tests/onnx/test_onnx_import.py, which runs
its importer against real onnx files.
"""

import struct

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.frontends.onnx import (  # noqa: E402
    ONNXModel,
    export_torch_onnx,
)
from flexflow_tpu.frontends.onnx_wire import (  # noqa: E402
    load_model,
    parse_attribute,
    parse_tensor,
)


def export(tmp_path, module, x, name="m.onnx", **kw):
    p = str(tmp_path / name)
    export_torch_onnx(module, x, p, input_names=["input"],
                      output_names=["output"], **kw)
    return p


def test_mlp_wire_parse_matches_torch_state(tmp_path):
    torch.manual_seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    p = export(tmp_path, m, torch.randn(4, 16))
    parsed = load_model(p)
    assert parsed["producer_name"] == "pytorch"
    g = parsed["graph"]
    assert [n["op_type"] for n in g["nodes"]] == ["Gemm", "Relu", "Gemm"]
    assert g["inputs"][0] == {"name": "input", "elem_type": 1,
                              "shape": [4, 16]}
    # raw_data initializer decode must be bit-exact vs the torch source
    sd = m.state_dict()
    np.testing.assert_array_equal(g["initializers"]["0.weight"],
                                  sd["0.weight"].numpy())
    np.testing.assert_array_equal(g["initializers"]["2.bias"],
                                  sd["2.bias"].numpy())
    # Gemm attrs came through the attribute decoder
    gemm = g["nodes"][0]
    assert gemm["attrs"]["transB"] == 1
    assert gemm["attrs"]["alpha"] == pytest.approx(1.0)


def test_convnet_wire_import_trains(tmp_path):
    """Conv/BN/MaxPool/Flatten graph: parse the real file, emit onto
    FFModel, train a step — the full reference onnx-import flow
    (onnx/model.py:74-340) against genuine wire bytes."""
    torch.manual_seed(0)
    m = nn.Sequential(
        nn.Conv2d(3, 8, 3, stride=1, padding=1), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        nn.Conv2d(8, 16, 3, padding=1), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        nn.Flatten(),
        nn.Linear(16 * 8 * 8, 10),
    )
    m.eval()
    bs = 8
    p = export(tmp_path, m, torch.randn(bs, 3, 32, 32))
    om = ONNXModel(p)  # no onnx package in this image: wire decoder path

    cfg = FFConfig()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 32, 32), name="input")
    out = om.apply(ff, {"input": inp})
    assert tuple(out.shape) == (bs, 10)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    # imported weights -> forward must match torch exactly (fp32)
    x = np.random.RandomState(0).randn(bs, 3, 32, 32).astype(np.float32)
    with torch.no_grad():
        want = m(torch.from_numpy(x)).numpy()
    got = np.asarray(ff.forward({"input": x}))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    mtr = ff.train_batch({"input": x,
                          "label": np.zeros(bs, np.int32)})
    assert np.isfinite(float(mtr["loss"]))


def test_mnist_mlp_round_trip_accuracy(tmp_path):
    """The examples/python/onnx flow end-to-end: export, wire-parse,
    train to a separable-problem accuracy threshold (reference
    accuracy_tests.sh pattern)."""
    torch.manual_seed(0)
    bs = 64
    m = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                      nn.Linear(128, 4), nn.Softmax(dim=-1))
    p = export(tmp_path, m, torch.randn(bs, 64))
    om = ONNXModel(p)
    cfg = FFConfig()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 64), name="input")
    om.apply(ff, {"input": inp})
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(1024, 64).astype(np.float32)
    w = rng.randn(64, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    hist = ff.fit({"input": x}, y, epochs=8, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]


def test_reshape_via_constant_node(tmp_path):
    """torch emits Reshape shapes as Constant nodes / int64
    initializers; both must decode (int64 raw_data + tensor attr)."""
    class R(nn.Module):
        def forward(self, x):
            return x.reshape(x.shape[0], 4, 8).transpose(1, 2)

    p = export(tmp_path, R(), torch.randn(2, 32))
    g = load_model(p)["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Reshape" in ops and "Transpose" in ops
    tr = next(n for n in g["nodes"] if n["op_type"] == "Transpose")
    assert tr["attrs"]["perm"] == [0, 2, 1]
    # the shape constant decodes to int64 [2, 4, 8] wherever it landed
    consts = [n["attrs"]["value"] for n in g["nodes"]
              if n["op_type"] == "Constant"
              and isinstance(n["attrs"].get("value"), np.ndarray)]
    all_i64 = list(g["initializers"].values()) + consts
    assert any(v.dtype == np.int64 and v.tolist() == [2, 4, 8]
               for v in all_i64), all_i64

    # and the importer runs it (Constant folds into the init map)
    om = ONNXModel(p)
    cfg = FFConfig()
    cfg.batch_size = 2
    ff = FFModel(cfg)
    inp = ff.create_tensor((2, 32), name="input")
    out = om.apply(ff, {"input": inp})
    assert tuple(out.shape) == (2, 8, 4)


# --- decoder unit coverage for wire shapes torch doesn't emit ----------


def _varint_bytes(v):
    out = b""
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field_no, wt):
    # the tag itself is a varint (matters for field numbers >= 16)
    return _varint_bytes((field_no << 3) | wt)


def _ld(field_no, payload: bytes) -> bytes:
    return _tag(field_no, 2) + _varint_bytes(len(payload)) + payload


def test_unpacked_repeated_and_negative_ints():
    # dims as UNPACKED varints (old writers), negative int64 attr
    t = (_tag(1, 0) + _varint_bytes(2) + _tag(1, 0) + _varint_bytes(3)
         + _tag(2, 0) + _varint_bytes(1)
         + _ld(8, b"w")
         + _ld(9, np.arange(6, dtype=np.float32).tobytes()))
    name, arr = parse_tensor(t)
    assert name == "w" and arr.shape == (2, 3)
    np.testing.assert_array_equal(
        arr, np.arange(6, dtype=np.float32).reshape(2, 3))

    a = (_ld(1, b"axis") + _tag(3, 0) + _varint_bytes(-1)
         + _tag(20, 0) + _varint_bytes(2))  # type=INT
    k, v = parse_attribute(a)
    assert k == "axis" and v == -1


def test_float_data_and_f16_int32_data_fields():
    # float_data (packed field 4) instead of raw_data
    payload = struct.pack("<3f", 1.0, 2.0, 3.0)
    t = (_ld(4, payload) + _tag(1, 0) + _varint_bytes(3)
         + _tag(2, 0) + _varint_bytes(1) + _ld(8, b"f"))
    _, arr = parse_tensor(t)
    np.testing.assert_allclose(arr, [1.0, 2.0, 3.0])
    # float16 carried in int32_data per the schema
    h = np.asarray([1.5, -2.25], np.float16)
    ints = b"".join(_varint_bytes(int(x)) for x in h.view(np.uint16))
    t16 = (_ld(5, ints) + _tag(1, 0) + _varint_bytes(2)
           + _tag(2, 0) + _varint_bytes(10) + _ld(8, b"h"))
    _, a16 = parse_tensor(t16)
    assert a16.dtype == np.float16
    np.testing.assert_array_equal(a16, h)


def test_malformed_input_fails_loudly():
    with pytest.raises(ValueError):
        load_model(b"\x00\x01not a protobuf .onnx file\xff\xff")


def test_make_input_tensors_carries_dtype(tmp_path):
    """Graph inputs build with their declared ONNX elem_type: int64
    token ids must not silently become f32 tensors."""
    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(100, 16)
            self.fc = nn.Linear(16, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(dim=1))

    torch.manual_seed(0)
    m = M()
    m.eval()
    p = export(tmp_path, m, torch.randint(0, 100, (4, 7)))
    om = ONNXModel(p)
    assert len(om.graph_inputs) == 1
    name, shape, dtype = om.graph_inputs[0]
    assert shape == [4, 7] and np.dtype(dtype) == np.int64
    cfg = FFConfig()
    cfg.batch_size = 4
    ff = FFModel(cfg)
    tensors = om.make_input_tensors(ff)
    # declared int64 narrows to the dtype device arrays actually have
    assert np.dtype(tensors[name].dtype) == np.int32
    # ...and the whole embedding graph imports (Gather -> embedding,
    # ReduceMean -> reduce op) matching the torch forward exactly
    out = om.apply(ff, tensors)
    assert tuple(out.shape) == (4, 4)
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    ids = np.random.RandomState(0).randint(0, 100, (4, 7)).astype(np.int64)
    with torch.no_grad():
        want = m(torch.from_numpy(ids)).numpy()
    got = np.asarray(ff.forward({name: ids}))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
