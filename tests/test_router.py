"""Multi-replica serving tier (PR 14): prefix-affinity router, SLO
traffic harness, telemetry-driven autoscaler.

Layers:
  * session — ServeSession is the steppable form of generate(): same
    tokens whether requests are submitted up front or mid-stream
    (sampling keys on stream ids, not submission interleaving).
  * traffic — seeded synthesis is deterministic, heavy-tailed,
    multi-tenant, and validated.
  * router — affinity routes to the LONGEST chain-hash prefix match
    (block-boundary exact), tenant-sticky falls back, load spills off
    rung/occupancy pressure, routing is deterministic at one seed,
    and a cancel (even mid-QUEUE) reclaims the affinity pin.
  * autoscaler — decisions read only exported gauges, scale on SLO
    pressure, never flap on steady load, and replay exactly.
  * chaos — a seeded cancel+sampling storm over the pool holds
    cluster-wide check_invariants after EVERY replica step, full page
    reclamation, zero recompiles, and single-replica token exactness.
"""

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.serve import (Autoscaler, ReplicaPool, ServeEngine,
                                TrafficRequest, TrafficSpec,
                                make_traffic)
from flexflow_tpu.serve.scheduler import RequestOutcome
from flexflow_tpu.serve.traffic import tenant_prefixes
from flexflow_tpu.utils.profiling import router_report
from flexflow_tpu.utils.telemetry import Telemetry


# --------------------------------------------------------------- helpers
def _lm(*, page_size=4, pool_pages=48, budget=8, max_seqs=4,
        max_seq_len=96, **cfg_kw):
    cfg = FFConfig(batch_size=1, kv_page_size=page_size,
                   kv_num_pages=1 + pool_pages,
                   serve_max_seqs=max_seqs,
                   serve_prefill_budget=budget,
                   serve_spec_decode=False, **cfg_kw)
    return build_transformer_lm(cfg, vocab_size=61,
                                max_seq_len=max_seq_len, hidden=32,
                                num_heads=4, num_layers=2, ff_dim=72)


def _traffic(n=16, seed=0, **over):
    kw = dict(requests=n, seed=seed, rate_rps=2000.0, tenants=3,
              prefix_tokens=24, tail_mean=4.0, output_mean=4.0,
              max_prompt=48, max_new_cap=8, vocab=61)
    kw.update(over)
    return make_traffic(TrafficSpec(**kw))


def _drain(replica):
    while replica.session.step() is not None:
        pass


# =======================================================================
# traffic harness
# =======================================================================
def test_traffic_deterministic_and_shaped():
    spec = TrafficSpec(requests=64, seed=5, tenants=4,
                       prefix_tokens=24, max_prompt=48,
                       cancel_frac=0.2, sample_frac=0.3, vocab=61)
    a = make_traffic(spec)
    b = make_traffic(spec)
    assert [(t.t_arrival, t.prompt, t.max_new, t.cancel_after_tokens,
             t.temperature) for t in a] == \
        [(t.t_arrival, t.prompt, t.max_new, t.cancel_after_tokens,
          t.temperature) for t in b]
    # a different seed moves everything
    c = make_traffic(TrafficSpec(requests=64, seed=6, tenants=4,
                                 prefix_tokens=24, max_prompt=48,
                                 vocab=61))
    assert [t.prompt for t in a] != [t.prompt for t in c]
    # arrivals strictly ordered, stream ids in arrival order
    ts = [t.t_arrival for t in a]
    assert ts == sorted(ts) and [t.stream_id for t in a] == list(
        range(64))
    # every prompt = its tenant's shared prefix + a nonempty tail,
    # admissible under the cap
    prefixes = tenant_prefixes(spec)
    for t in a:
        assert t.prompt[:24] == prefixes[t.tenant]
        assert 24 < len(t.prompt) <= 48
        assert 1 <= t.max_new <= spec.max_new_cap
        if t.cancel_after_tokens is not None:
            assert 1 <= t.cancel_after_tokens < t.max_new
    # heavy tails actually produce outliers and cancels/samples fire
    tails = [len(t.prompt) - 24 for t in a]
    assert max(tails) >= 3 * (sum(tails) / len(tails)) * 0.8
    assert any(t.cancel_after_tokens for t in a)
    assert any(t.sampled for t in a)
    # Zipf skew: tenant 0 dominates
    counts = np.bincount([t.tenant for t in a], minlength=4)
    assert counts[0] == max(counts)


def test_traffic_bursty_and_validation():
    base = dict(requests=64, seed=1, prefix_tokens=24, max_prompt=48,
                vocab=61)
    po = make_traffic(TrafficSpec(arrival="poisson", **base))
    bu = make_traffic(TrafficSpec(arrival="bursty", burst_factor=8.0,
                                  **base))
    # bursty inter-arrival gaps are MORE dispersed at a comparable
    # mean (coefficient of variation strictly above poisson's)
    def cv(tr):
        gaps = np.diff([t.t_arrival for t in tr])
        return float(np.std(gaps) / np.mean(gaps))
    assert cv(bu) > cv(po)
    with pytest.raises(ValueError, match="arrival"):
        make_traffic(TrafficSpec(arrival="nope", **base))
    with pytest.raises(ValueError, match="prefix_tokens"):
        make_traffic(TrafficSpec(requests=4, prefix_tokens=48,
                                 max_prompt=48, vocab=61))
    with pytest.raises(ValueError, match="rate_rps"):
        make_traffic(TrafficSpec(requests=4, rate_rps=0.0,
                                 prefix_tokens=8, max_prompt=48))


# =======================================================================
# sessions (the engine hook)
# =======================================================================
def test_session_mid_stream_submit_matches_generate():
    """Tokens are a function of (prompt, sampling stream), not of
    WHEN a request was submitted: half the batch submitted up front,
    half after a few steps, must equal one generate() over the same
    stream ids."""
    ff = _lm()
    eng = ServeEngine(ff)
    eng.warmup()
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 61, size=rng.randint(4, 24)))
               for _ in range(6)]
    ref = eng.generate(prompts, 5, temperature=[0, 0.8, 0, 0.8, 0, 0],
                       top_k=[None, 4, None, 4, None, None],
                       sample_seed=3, stream_ids=list(range(6)))
    temps = [0, 0.8, 0, 0.8, 0, 0]
    tks = [None, 4, None, 4, None, None]
    session = eng.start_session()
    reqs = []
    for i in range(3):
        sp = eng._sample_params(temps[i], tks[i], 3, 1,
                                eng.topk_cap)[0]
        reqs.append(session.submit(prompts[i], 5, sample=sp,
                                   stream_id=i))
    for _ in range(2):
        session.step()
    for i in range(3, 6):
        sp = eng._sample_params(temps[i], tks[i], 3, 1,
                                eng.topk_cap)[0]
        reqs.append(session.submit(prompts[i], 5, sample=sp,
                                   stream_id=i))
    while session.step() is not None:
        pass
    session.close()
    assert [list(r.out_tokens) for r in reqs] == ref
    eng.cache.check_invariants()
    assert eng.cache.free_pages == eng.cache_cfg.usable_pages


def test_session_exclusive_and_legacy_refused():
    ff = _lm()
    eng = ServeEngine(ff)
    s = eng.start_session()
    with pytest.raises(RuntimeError, match="live ServeSession"):
        eng.start_session()
    s.close()
    eng.start_session().close()   # reopens after close
    leg = ServeEngine(ff, chunked_prefill=False)
    with pytest.raises(ValueError, match="chunked"):
        leg.start_session()


# =======================================================================
# routing
# =======================================================================
def test_longest_prefix_wins_across_block_boundaries():
    ff = _lm(page_size=4)
    pool = ReplicaPool(ff, 2, policy="affinity")
    base = list(range(1, 41))          # 40 shared tokens = 10 pages
    # replica 0 serves (and commits) 17 tokens -> 4 full pages;
    # replica 1 serves 33 tokens -> 8 full pages of the same chain
    r0, r1 = pool.replicas
    r0.session.submit(base[:17], 1)
    _drain(r0)
    r1.session.submit(base[:33], 1)
    _drain(r1)
    target, info = pool.route(base[:40] + [55, 56])
    assert target.idx == 1 and info["affinity_hit"]
    assert info["matched_tokens"] == 32     # 8 full pages
    # a prompt agreeing only through 1.5 pages matches ONE full page:
    # the chain key of page 2 commits to tokens 4..7, so a flip at
    # token 6 must kill every key from page 2 on
    probe = base[:6] + [59, 60] + base[8:20]
    target2, info2 = pool.route(probe)
    assert info2["matched_tokens"] == 4
    # a total miss falls back tenant-sticky, deterministically
    miss = [58] * 12
    t_a, info_a = pool.route(miss, tenant=7)
    t_b, info_b = pool.route(miss, tenant=7)
    assert info_a["fallback"] and t_a.idx == t_b.idx
    pool.close()


def test_router_pending_pins_colocate_before_commit():
    """Two same-tenant requests arriving back-to-back route together
    even though the first has not COMMITTED its pages yet — the
    router's pending-pin table covers the gap."""
    ff = _lm(page_size=4)
    pool = ReplicaPool(ff, 2, policy="affinity")
    prompt = list(range(1, 30))
    tr0 = TrafficRequest(stream_id=0, t_arrival=0.0, tenant=1,
                        prompt=prompt, max_new=2)
    tr1 = TrafficRequest(stream_id=1, t_arrival=0.0, tenant=1,
                        prompt=list(prompt) + [33], max_new=2)
    a = pool.submit(tr0)
    b = pool.submit(tr1)
    assert b["replica"] == a["replica"]
    assert b["affinity_hit"] and b["matched_tokens"] > 0
    pool.close()


def test_spill_under_rung_pressure():
    """An affinity hit pointing at a saturated replica spills to the
    least-loaded one instead of queueing (the degradation ladder /
    occupancy as the backpressure signal)."""
    ff = _lm(page_size=4, pool_pages=40)
    pool = ReplicaPool(ff, 2, policy="affinity",
                       spill_occupancy=0.5)
    prefix = list(range(1, 26))
    r0 = pool.replicas[0]
    # park the prefix AND enough live residency on replica 0 to push
    # occupancy past the spill ceiling (requests mid-flight: submit,
    # step once so pages map, don't drain)
    rng = np.random.RandomState(1)
    for k in range(3):
        r0.session.submit(prefix + list(rng.randint(40, 61, size=30)),
                          8)
    for _ in range(40):
        if r0.occupancy() >= 0.5:
            break
        assert r0.session.step() is not None
    assert r0.occupancy() >= 0.5
    target, info = pool.route(prefix + [59, 60])
    assert target.idx == 1 and info["spilled"]
    # with spill disabled (ceiling 1.0 + rung far) the hit sticks
    pool.spill_occupancy = 1.01
    target2, info2 = pool.route(prefix + [59, 60])
    assert target2.idx == 0 and not info2["spilled"]
    _drain(r0)
    pool.close()


def test_routing_deterministic_at_one_seed():
    ff = _lm()
    traffic = _traffic(n=20, seed=4, cancel_frac=0.1,
                       sample_frac=0.25)
    outs = []
    for _ in range(2):
        pool = ReplicaPool(ff, 2, policy="affinity")
        res = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0)
        outs.append([(r["stream_id"], r["replica"], r["outcome"],
                      tuple(r["tokens"])) for r in res["requests"]])
        pool.check_drained()
        pool.close()
    assert outs[0] == outs[1]


def test_cancel_mid_queue_reclaims_affinity_pin():
    ff = _lm()
    pool = ReplicaPool(ff, 2, policy="affinity")
    tr = TrafficRequest(stream_id=0, t_arrival=0.0, tenant=0,
                        prompt=list(range(1, 20)), max_new=4)
    tracked = pool.submit(tr)
    ridx = tracked["replica"]
    assert pool._pins[ridx], "submit did not pin the prefix"
    # cancelled while still WAITING in the scheduler queue (no step
    # has run): the pin must reclaim IMMEDIATELY so routing stops
    # steering this tenant at pages that will never commit
    assert pool.cancel(0)
    assert not pool._pins[ridx], "cancel left the affinity pin"
    _drain(pool.replicas[ridx])
    assert tracked["req"].outcome == RequestOutcome.CANCELLED
    pool.check_drained()
    # double-cancel / unknown stream are clean no-ops
    assert not pool.cancel(0)
    assert not pool.cancel(99)
    pool.close()


def test_round_robin_policy_cycles():
    ff = _lm()
    pool = ReplicaPool(ff, 3, policy="round_robin")
    seen = [pool.route([1, 2, 3])[0].idx for _ in range(6)]
    assert seen == [0, 1, 2, 0, 1, 2]
    pool.close()


# =======================================================================
# pool runs: exactness, labels, report
# =======================================================================
def test_pool_tokens_match_single_replica_and_labels():
    ff = _lm()
    traffic = _traffic(n=18, seed=2, sample_frac=0.3, tenants=2)
    tel = Telemetry()
    pool = ReplicaPool(ff, 2, policy="affinity", telemetry=tel)
    res = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0,
                   sample_seed=9)
    pool.assert_zero_recompiles()
    pool.check_drained()
    eng = ServeEngine(ff)
    eng.warmup()
    ref = eng.generate([t.prompt for t in traffic],
                       [t.max_new for t in traffic],
                       temperature=[t.temperature for t in traffic],
                       top_k=[t.top_k for t in traffic],
                       sample_seed=9,
                       stream_ids=[t.stream_id for t in traffic])
    for rec, r in zip(res["requests"], ref):
        assert rec["outcome"] == "completed" and rec["tokens"] == r
    # per-replica LABELED fold (the serve_metrics replica= satellite):
    # TTFT histograms and token counters split per replica without
    # double-counting the unlabeled aggregate
    m = pool.metrics
    per = [m.counter("serve_tokens_generated_total",
                     replica=str(i)) for i in (0, 1)]
    assert all(v > 0 for v in per)
    assert m.counter("serve_tokens_generated_total") == sum(per)
    assert m.hist_count("serve_ttft_seconds", replica="0") > 0
    assert m.counter("router_requests_total", replica="0") > 0
    assert m.counter("router_affinity_hits_total") > 0
    # router spans landed on the router track
    tracks = {ev[1] for ev in tel.events}
    assert ("serve", "router") in tracks
    # the report renders without error and carries the headline
    rep = router_report(res, m)
    assert "goodput-under-SLO" in rep and "affinity hits" in rep
    pool.close()


# =======================================================================
# autoscaler
# =======================================================================
def _scaler(pool, price, **over):
    kw = dict(slo_ttft_s=6 * price, slo_tpot_s=2 * price,
              min_replicas=1, max_replicas=3, interval_s=20 * price,
              up_patience=2, down_patience=6, cooldown_s=40 * price,
              decode_table={1: price}, tensor_parallel=1,
              decode_lanes=4)
    kw.update(over)
    return Autoscaler(pool.metrics, **kw)


def test_autoscaler_scales_up_and_replays():
    ff = _lm(pool_pages=40, max_seq_len=128)
    probe = ReplicaPool(ff, 1)
    price = probe.price_probe(64)
    probe.close()
    traffic = _traffic(n=40, seed=3, arrival="bursty",
                       rate_rps=0.2 / price, burst_factor=6.0,
                       tenants=5, prefix_tokens=40, max_prompt=64,
                       output_mean=8.0, max_new_cap=12)
    runs = []
    for _ in range(2):
        tel = Telemetry()
        pool = ReplicaPool(ff, 1, telemetry=tel)
        res = pool.run(traffic, slo_ttft_s=6 * price,
                       slo_tpot_s=2 * price,
                       autoscaler=_scaler(pool, price))
        pool.assert_zero_recompiles()
        pool.check_drained()
        runs.append([(e["t"], e["direction"], e["replica"])
                     for e in res["scale_events"]])
        pool.close()
    assert runs[0] and runs[0] == runs[1]
    assert runs[0][0][1] == "up"
    # every decision is visible as a telemetry SPAN with its reason
    spans = [e for e in tel.events
             if e[0] == "X" and e[2].startswith("scale_")]
    assert len(spans) == len(runs[0])
    assert all(e[6].get("reason") for e in spans)


def test_autoscaler_no_flap_on_steady_load():
    """Hysteresis: a comfortably-served steady stream produces ZERO
    scale decisions — and even under pressure, cooldown forbids an
    up/down flip-flop inside the dead time."""
    ff = _lm(pool_pages=48, max_seq_len=128)
    probe = ReplicaPool(ff, 2)
    price = probe.price_probe(64)
    probe.close()
    traffic = _traffic(n=30, seed=6, rate_rps=0.02 / price,
                       tenants=2, prefix_tokens=16, max_prompt=40,
                       output_mean=4.0)
    pool = ReplicaPool(ff, 2)
    scaler = _scaler(pool, price, min_replicas=2, max_replicas=4,
                     # generous SLOs: steady load sits well inside
                     slo_ttft_s=50 * price, slo_tpot_s=20 * price,
                     occ_lo=0.0)   # never "cold" either
    res = pool.run(traffic, slo_ttft_s=50 * price,
                   slo_tpot_s=20 * price, autoscaler=scaler)
    assert res["scale_events"] == []
    assert res["replicas_end"] == 2
    pool.close()
    # cooldown property on any event stream the bursty test produced:
    # consecutive decisions are separated by >= cooldown_s
    ff2 = _lm(pool_pages=40, max_seq_len=128)
    pool2 = ReplicaPool(ff2, 1)
    traffic2 = _traffic(n=40, seed=3, arrival="bursty",
                        rate_rps=0.2 / price, burst_factor=6.0,
                        tenants=5, prefix_tokens=40, max_prompt=64,
                        output_mean=8.0, max_new_cap=12)
    res2 = pool2.run(traffic2, slo_ttft_s=6 * price,
                     slo_tpot_s=2 * price,
                     autoscaler=_scaler(pool2, price,
                                        cooldown_s=40 * price))
    ts = [e["t"] for e in res2["scale_events"]]
    assert all(b - a >= 40 * price - 1e-12
               for a, b in zip(ts, ts[1:]))
    pool2.close()


def test_autoscaler_reads_only_gauges_and_prices_target():
    """The decision function sees nothing but the exported registry:
    rigged gauges alone drive it, and the decode-table pricing turns
    demand into a target count."""
    from flexflow_tpu.utils.telemetry import MetricsRegistry
    m = MetricsRegistry()
    a = Autoscaler(m, slo_ttft_s=0.1, slo_tpot_s=0.01,
                   min_replicas=1, max_replicas=4, interval_s=1.0,
                   up_patience=2, down_patience=2,
                   decode_table={1: 0.001}, tensor_parallel=1,
                   decode_lanes=4)   # capacity = 4000 tok/s
    assert a.target_replicas(9000.0) == 3
    m.set("serve_pool_replicas_live", 1)
    m.set("serve_pool_ttft_p99_window_s", 0.5)   # SLO blown
    m.set("serve_pool_occupancy_mean", 0.5)
    assert a.evaluate(1.0) is None                # patience 1/2
    d = a.evaluate(2.0)
    assert d is not None and d["direction"] == "up"
    assert "ttft" in d["reason"]
    # demand above priced capacity scales up even with latency OK
    b = Autoscaler(m, slo_ttft_s=0.0, slo_tpot_s=0.0,
                   min_replicas=1, max_replicas=4, interval_s=1.0,
                   up_patience=1, decode_table={1: 0.001},
                   tensor_parallel=1, decode_lanes=4)
    m.set("serve_pool_ttft_p99_window_s", 0.0)
    m.set("serve_pool_occupancy_mean", 0.2)
    m.set("serve_pool_decode_tokens_per_s_window", 9000.0)
    d2 = b.evaluate(1.0)
    assert d2 is not None and d2["direction"] == "up" \
        and d2["priced_target"] == 3
    # and a scale-down is REFUSED while the target needs the fleet
    m.set("serve_pool_replicas_live", 3)
    m.set("serve_pool_occupancy_mean", 0.0)
    m.set("serve_pool_queue_depth", 0.0)
    c = Autoscaler(m, slo_ttft_s=0.0, slo_tpot_s=0.0,
                   min_replicas=1, max_replicas=4, interval_s=1.0,
                   down_patience=1, decode_table={1: 0.001},
                   tensor_parallel=1, decode_lanes=4)
    assert c.evaluate(1.0) is None   # priced target 3 == live 3
    m.set("serve_pool_decode_tokens_per_s_window", 100.0)
    d3 = c.evaluate(2.0)
    assert d3 is not None and d3["direction"] == "down"


def test_autoscaler_validation_and_config():
    from flexflow_tpu.utils.telemetry import MetricsRegistry
    m = MetricsRegistry()
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(m, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="interval"):
        Autoscaler(m, interval_s=0.0)
    cfg = FFConfig(batch_size=1, serve_replicas=2, slo_ttft_ms=5.0,
                   slo_tpot_ms=2.0, serve_autoscale=True)
    a = Autoscaler.from_config(cfg, m)
    assert a.slo_ttft_s == 0.005 and a.slo_tpot_s == 0.002
    assert a.max_replicas == 4   # 2x serve_replicas default


# =======================================================================
# chaos
# =======================================================================
def test_seeded_chaos_invariants_every_step():
    """A seeded storm — cancels (router-driven mid-generation AND
    external mid-queue), sampling, bursty arrivals — holds
    check_invariants on EVERY replica after EVERY step, reclaims all
    pages, never recompiles, and every surviving stream matches the
    single-replica reference."""
    ff = _lm(pool_pages=40)
    traffic = _traffic(n=24, seed=8, arrival="bursty",
                       rate_rps=3000.0, cancel_frac=0.25,
                       sample_frac=0.3, tenants=4)
    pool = ReplicaPool(ff, 2, policy="affinity")
    external_cancel = {5, 11}

    def on_step(replica, ev):
        for r in pool.replicas:
            r.engine.cache.check_invariants()
        for sid in list(external_cancel):
            if sid in pool._inflight:
                pool.cancel(sid)
                external_cancel.discard(sid)

    res = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0,
                   on_step=on_step)
    pool.assert_zero_recompiles()
    pool.check_drained()
    assert res["cancelled"] > 0
    eng = ServeEngine(ff)
    eng.warmup()
    ref = eng.generate([t.prompt for t in traffic],
                       [t.max_new for t in traffic],
                       temperature=[t.temperature for t in traffic],
                       top_k=[t.top_k for t in traffic],
                       sample_seed=0,
                       stream_ids=[t.stream_id for t in traffic])
    for rec, r in zip(res["requests"], ref):
        if rec["outcome"] == "completed":
            assert rec["tokens"] == r
        else:
            assert rec["tokens"] == r[:len(rec["tokens"])]
    # no pin survives the run
    assert all(not pins for pins in pool._pins)
    pool.close()


def test_pool_rerun_does_not_double_count_metrics():
    """run() twice on one pool: sessions recycle per run, so the
    end-of-run registry fold covers THIS run only — counters after
    two identical runs are exactly 2x one run's, not 3x (the
    session-lifetime re-fold bug)."""
    ff = _lm()
    traffic = _traffic(n=8, seed=12)
    pool = ReplicaPool(ff, 2)
    r1 = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0)
    after1 = pool.metrics.counter("serve_tokens_generated_total")
    assert after1 == r1["tokens_total"] > 0
    r2 = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0)
    after2 = pool.metrics.counter("serve_tokens_generated_total")
    assert after2 == after1 + r2["tokens_total"] == 2 * after1
    # the second run reproduces the first (same traffic, fresh rids)
    assert [r["tokens"] for r in r2["requests"]] == \
        [r["tokens"] for r in r1["requests"]]
    # ...and reports PER-RUN routing/scale accounting, not the pool
    # lifetime (routed == this run's requests; self.stats keeps the
    # lifetime totals, the DisaggCluster idiom)
    assert r2["routing"]["routed"] == len(traffic)
    assert pool.stats["routed"] == 2 * len(traffic)
    assert r2["scale_events"] == []
    pool.check_drained()
    pool.close()
    # round-robin placement also restarts per run (reused pool ==
    # fresh pool, deterministically)
    ff_rr = _lm()
    pool_rr = ReplicaPool(ff_rr, 2, policy="round_robin")
    a = pool_rr.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0)
    b = pool_rr.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0)
    assert [r["replica"] for r in a["requests"]] == \
        [r["replica"] for r in b["requests"]]
    pool_rr.close()


def test_autoscale_flag_arms_config_autoscaler():
    """--autoscale is a LIVE knob: run() with no explicit autoscaler
    builds one from the config flags."""
    ff = _lm(serve_autoscale=True, slo_ttft_ms=1000.0,
             slo_tpot_ms=1000.0, serve_autoscale_max=2)
    traffic = _traffic(n=6, seed=13)
    pool = ReplicaPool(ff, 1)
    res = pool.run(traffic)
    assert res["autoscaled"]
    pool.close()
    ff2 = _lm()
    pool2 = ReplicaPool(ff2, 1)
    assert not pool2.run(traffic)["autoscaled"]
    pool2.close()


# =======================================================================
# config / CLI
# =======================================================================
def test_router_config_flags_and_validation():
    cfg = FFConfig(batch_size=1, argv=[
        "--serve-replicas", "3", "--router-policy", "round_robin",
        "--slo-ttft-ms", "5", "--slo-tpot-ms", "1.5", "--autoscale",
        "--autoscale-max", "6"])
    assert cfg.serve_replicas == 3
    assert cfg.router_policy == "round_robin"
    assert cfg.slo_ttft_ms == 5.0 and cfg.slo_tpot_ms == 1.5
    assert cfg.serve_autoscale and cfg.serve_autoscale_max == 6
    with pytest.raises(ValueError, match="router_policy"):
        FFConfig(batch_size=1, router_policy="random")
    with pytest.raises(ValueError, match="serve_replicas"):
        FFConfig(batch_size=1, serve_replicas=0)
    with pytest.raises(ValueError, match="slo_ttft_ms"):
        FFConfig(batch_size=1, slo_ttft_ms=-1.0)
    # from_config picks the flags up
    ff = _lm(serve_replicas=2, router_policy="round_robin")
    pool = ReplicaPool.from_config(ff)
    assert len(pool.replicas) == 2 and pool.policy == "round_robin"
    pool.close()


# =======================================================================
# wall-clock fabric
# =======================================================================
def test_wall_clock_token_identity_both_modes():
    """The fabric's core contract: the SAME traffic serves
    token-identically on the virtual clock, the threaded wall clock,
    and the single-threaded wall baseline — sampling keys on stream
    ids, never on the clock (cancel-free traffic: abandon points are
    clock-dependent by design)."""
    traffic = _traffic(n=14, seed=4, sample_frac=0.3, tenants=2,
                       cancel_frac=0.0, rate_rps=300.0)

    def toks(res):
        return {r["stream_id"]: r["tokens"] for r in res["requests"]}

    pool = ReplicaPool(_lm(), 2, policy="affinity")
    virt = pool.run(traffic, sample_seed=3)
    assert all(r["outcome"] == "completed" for r in virt["requests"])
    pool.close()

    pool = ReplicaPool(_lm(), 2, policy="affinity")
    wall = pool.run(traffic, sample_seed=3, wall_clock=True,
                    time_scale=0.2, dwell_s=0.002)
    assert toks(wall) == toks(virt)
    assert wall["clock"] == "wall" and wall["wall_threads"]
    # one coherent clock: every record's stamps are ordered and the
    # makespan covers them (satellite: no wall/virtual mixing)
    for rec in wall["requests"]:
        assert rec["t_arrival"] <= rec["t_finish"] \
            <= wall["makespan_s"] + 1e-9
        if rec["ttft_s"] is not None:
            assert rec["ttft_s"] >= 0.0
    # wall runs label their OWN histogram series; the virtual series
    # stays untouched on this pool
    assert pool.metrics.hist_count(
        "serve_router_ttft_wall_seconds") > 0
    assert pool.metrics.hist_count(
        "serve_router_ttft_virtual_seconds") == 0
    assert any(p["busy_wall_s"] > 0 for p in wall["per_replica"])
    pool.assert_zero_recompiles()
    pool.check_drained()
    # the same pool replays VIRTUAL after a wall run, identically
    virt2 = pool.run(traffic, sample_seed=3)
    assert toks(virt2) == toks(virt)
    pool.close()

    pool = ReplicaPool(_lm(), 2, policy="affinity")
    single = pool.run(traffic, sample_seed=3, wall_clock=True,
                      wall_threads=False, time_scale=0.2,
                      dwell_s=0.002)
    assert toks(single) == toks(virt)
    assert single["clock"] == "wall" and not single["wall_threads"]
    pool.close()


def test_wall_clock_attribution_sums_to_measured_latency():
    """Satellite bugfix gate: explain_request must still sum exactly
    to measured latency when the run is wall-clock — every span and
    the request stamps live on ONE clock (time.perf_counter)."""
    from flexflow_tpu.utils.telemetry import REQUEST_COMPONENTS
    tel = Telemetry()
    pool = ReplicaPool(_lm(), 2, policy="affinity", telemetry=tel)
    traffic = _traffic(n=10, seed=6, cancel_frac=0.0,
                       rate_rps=300.0)
    res = pool.run(traffic, sample_seed=1, wall_clock=True,
                   time_scale=0.2, dwell_s=0.002)
    att = res["attribution"]
    assert set(att) == set(REQUEST_COMPONENTS)
    for rec in res["requests"][:4]:
        b = pool.explain_request(rec["stream_id"])
        assert b["replica"] == rec["replica"]
        assert abs(sum(b["components"].values()) - b["latency_s"]) \
            <= 1e-9 + 0.01 * b["latency_s"]
    pool.close()


def test_wall_clock_refuses_autoscaler_and_reads_config():
    traffic = _traffic(n=4, seed=0, cancel_frac=0.0)
    pool = ReplicaPool(_lm(), 2)
    price = pool.price_probe(64)
    with pytest.raises(ValueError, match="virtual clock"):
        pool.run(traffic, wall_clock=True,
                 autoscaler=_scaler(pool, price))
    pool.close()
    # --wall-clock dispatches run() to the wall loop via config
    ff = _lm(serve_wall_clock=True)
    pool = ReplicaPool(ff, 2)
    res = pool.run(traffic, sample_seed=0, time_scale=0.1)
    assert res["clock"] == "wall"
    pool.close()
    cfg = FFConfig(batch_size=1, argv=["--wall-clock", "--transport",
                                       "tcp", "--transport-port",
                                       "0"])
    assert cfg.serve_wall_clock and cfg.serve_transport == "tcp"
    with pytest.raises(ValueError, match="serve_transport"):
        FFConfig(batch_size=1, serve_transport="udp")
    with pytest.raises(ValueError, match="mutually exclusive"):
        FFConfig(batch_size=1, serve_wall_clock=True,
                 serve_autoscale=True)


def test_rescale_arrivals_preserves_identity_fields():
    from flexflow_tpu.serve import rescale_arrivals
    traffic = _traffic(n=8, seed=2, cancel_frac=0.2, sample_frac=0.3)
    fast = rescale_arrivals(traffic, 0.25)
    assert [t.t_arrival * 0.25 for t in traffic] == \
        [t.t_arrival for t in fast]
    assert [(t.stream_id, t.prompt, t.max_new, t.temperature,
             t.cancel_after_tokens) for t in traffic] == \
        [(t.stream_id, t.prompt, t.max_new, t.temperature,
          t.cancel_after_tokens) for t in fast]
    assert traffic[0] is not fast[0]    # copies, originals untouched
    with pytest.raises(ValueError, match="scale"):
        rescale_arrivals(traffic, 0.0)
