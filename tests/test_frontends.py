"""Frontend tests: Keras API, torch.fx importer (+ weight import parity
vs torch forward)."""

import numpy as np
import pytest
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.frontends import keras
from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff


def test_keras_sequential_mnist_style():
    model = keras.Sequential([
        keras.layers.Dense(64, activation="relu", input_shape=(32,)),
        keras.layers.Dense(4, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(256, 32).astype(np.float32)
    w = rng.randn(32, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    hist = model.fit(x, y, batch_size=64, epochs=10, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]


def test_keras_functional_cnn():
    inp = keras.layers.Input((3, 16, 16))
    t = keras.layers.Conv2D(8, (3, 3), padding="same",
                            activation="relu")(inp)
    t = keras.layers.MaxPooling2D((2, 2))(t)
    t = keras.layers.Flatten()(t)
    t = keras.layers.Dense(4, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=t)
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(64, 3, 16, 16).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=1, verbose=False)
    assert np.isfinite(hist[-1]["loss"])
    preds = model.predict(x[:32], batch_size=32)
    assert preds.shape == (32, 4)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, atol=1e-4)


def test_keras_early_stopping():
    model = keras.Sequential([
        keras.layers.Dense(8, activation="relu", input_shape=(16,)),
        keras.layers.Dense(2, activation="softmax"),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 2, 64).astype(np.int32)
    es = keras.EarlyStopping(monitor="loss", patience=0, min_delta=10.0)
    hist = model.fit(x, y, batch_size=32, epochs=20, callbacks=[es],
                     verbose=False)
    assert len(hist) < 20, "early stopping must trigger"


class TorchCNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
        self.relu = nn.ReLU()
        self.pool = nn.MaxPool2d(2, 2)
        self.flatten = nn.Flatten()
        self.fc = nn.Linear(8 * 8 * 8, 4)

    def forward(self, x):
        x = self.pool(self.relu(self.conv1(x)))
        x = self.flatten(x)
        return self.fc(x)


def test_torchfx_import_matches_torch_forward():
    torch.manual_seed(0)
    tm = TorchCNN().eval()
    ptm = PyTorchModel(tm)

    cfg = FFConfig()
    cfg.batch_size = 4
    ff = FFModel(cfg)
    x = ff.create_tensor((4, 3, 16, 16), name="input")
    (out,) = ptm.apply(ff, [x])
    ff.softmax(out)  # head for compile; compare pre-softmax tensor
    ff.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    ptm.import_weights(ff)

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 3, 16, 16).astype(np.float32)
    values, _ = ff.executor.forward_values(
        ff.state.params, ff.state.states, {"input": xv}, False, None)
    got = np.asarray(values[out.uid])
    want = tm(torch.from_numpy(xv)).detach().numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_torchfx_ff_file_roundtrip(tmp_path):
    tm = TorchCNN()
    path = str(tmp_path / "model.ff")
    export_ff(tm, path)
    lines = open(path).read().splitlines()
    assert any("conv2d" in l for l in lines)
    ptm = PyTorchModel(path)  # parse back from the file
    cfg = FFConfig()
    cfg.batch_size = 2
    ff = FFModel(cfg)
    x = ff.create_tensor((2, 3, 16, 16), name="input")
    (out,) = ptm.apply(ff, [x])
    assert out.shape == (2, 4)


def test_onnx_file_load_zero_dep():
    """Loading a .onnx file needs NO onnx package (wire decoder,
    test_onnx_wire.py); a missing path fails with the filesystem error,
    not an import gate."""
    from flexflow_tpu.frontends import onnx as fonnx
    if not fonnx.HAS_ONNX:
        with pytest.raises(FileNotFoundError):
            fonnx.ONNXModel("nonexistent.onnx")
        with pytest.raises(ValueError):  # garbage bytes fail loudly
            fonnx.ONNXModel(b"\x00\x01garbage\xff")


def test_keras_nested_model_as_layer():
    """Models as layers (reference func_*_nested examples): the nested
    model's graph replays into the outer graph; reuse fails loudly
    (weight sharing is not implemented)."""
    from flexflow_tpu.frontends import keras

    inner_in = keras.layers.Input((8,))
    inner_out = keras.layers.Dense(16, activation="relu")(inner_in)
    inner = keras.Model(inputs=inner_in, outputs=inner_out, name="inner")

    outer_in = keras.layers.Input((8,))
    t = inner(outer_in)
    out = keras.layers.Dense(4, activation="softmax")(t)
    outer = keras.Model(inputs=outer_in, outputs=out)
    outer.compile(optimizer=keras.SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(256, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    h = outer.fit(x, y, batch_size=32, epochs=8, verbose=False)
    assert h[-1]["accuracy"] > 0.5, h[-1]

    # the nested dense really is part of the outer FFModel graph
    types = [op.op_type for op in outer.ffmodel.ops]
    assert types.count("linear") == 2, types

    with pytest.raises(NotImplementedError, match="weight sharing"):
        inner(outer_in)


def test_keras_reshape_layer():
    from flexflow_tpu.frontends import keras

    inp = keras.layers.Input((784,))
    t = keras.layers.Reshape((1, 28, 28))(inp)
    t = keras.layers.Conv2D(8, (3, 3), activation="relu")(t)
    t = keras.layers.Flatten()(t)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randn(64, 784).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=1)
    assert np.isfinite(hist[-1]["loss"])


def test_torchfx_layer_norm_roundtrip():
    import torch
    import torch.nn as nn

    from flexflow_tpu.frontends.torchfx import PyTorchModel
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 32)
            self.ln = nn.LayerNorm(32)
            self.out = nn.Linear(32, 4)
            self.sm = nn.Softmax(dim=-1)

        def forward(self, x):
            return self.sm(self.out(self.ln(self.fc(x))))

    mod = M()
    ptm = PyTorchModel(mod)
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    inp = ff.create_tensor((8, 16), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    ptm.import_weights(ff)
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    got = np.asarray(ff.forward({"input": x}))
    want = mod(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras_embedding_gap1d_classifier():
    """Embedding -> GlobalAveragePooling1D -> Dense: the standard keras
    text-classifier head (GAP1D lowers to the generic reduce op)."""
    m = keras.Sequential([
        keras.layers.Embedding(100, 16, input_shape=(12,)),
        keras.layers.GlobalAveragePooling1D(),
        keras.layers.Dense(4, activation="softmax"),
    ])
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    rng = np.random.RandomState(0)
    x = rng.randint(0, 100, (256, 12)).astype(np.int32)
    # every token informative: class = bucket of the mean token id —
    # exactly the signal mean pooling preserves
    y = np.clip(x.mean(axis=1) * 4 // 100, 0, 3).astype(np.int32)
    m.fit(x, y, batch_size=32, epochs=10, verbose=False)
    out = m.evaluate(x, y, batch_size=32)
    assert out["accuracy"] > 0.5, out
