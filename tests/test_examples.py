"""Example-zoo integration tests: run real example scripts through the
launcher on a small virtual CPU mesh — the reference's
tests/multi_gpu_tests.sh pattern (run ~30 example scripts through
flexflow_python; pass = clean exit), minus the need for real devices.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, *args, cpu_devices=2, timeout=240):
    cmd = [sys.executable, "-m", "flexflow_tpu",
           "--cpu-devices", str(cpu_devices),
           os.path.join(REPO, script), *args]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       timeout=timeout, env=env)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


@pytest.mark.parametrize("script,args", [
    ("examples/python/native/alexnet.py",
     ["-b", "8", "--samples", "16", "-e", "1"]),
    ("examples/python/native/transformer.py", ["-b", "8", "-e", "1"]),
    ("examples/python/native/dlrm.py", ["-b", "16", "-e", "1"]),
    ("examples/python/native/moe.py", ["-b", "16", "-e", "1"]),
    ("examples/python/native/mnist_mlp.py", ["-b", "64", "-e", "1"]),
    ("examples/python/native/mnist_cnn.py",
     ["-b", "16", "--samples", "32", "-e", "1"]),
    ("examples/python/native/cifar10_cnn.py",
     ["-b", "16", "--samples", "32", "-e", "1"]),
    ("examples/python/native/split.py", ["-b", "32", "-e", "1"]),
    ("examples/python/native/print_layers.py", ["-b", "32", "-e", "1"]),
    ("examples/python/native/reshape.py", ["-b", "32", "-e", "1"]),
    ("examples/python/native/mnist_mlp_attach.py", ["-b", "64", "-e", "1"]),
    ("examples/python/native/multi_head_attention.py",
     ["-b", "8", "-e", "1"]),
    ("examples/python/native/bert_proxy_native.py", ["-b", "8", "-e", "1"]),
    ("examples/python/native/nmt_seq2seq.py", ["-b", "8", "-e", "1"]),
    ("examples/python/native/rnn_text_classification.py",
     ["-b", "8", "-e", "1"]),
    ("examples/python/native/cifar10_cnn_concat.py",
     ["-b", "8", "--samples", "32", "-e", "1"]),
    ("examples/python/native/long_context_attention.py",
     ["-b", "4", "-e", "1", "--sp-attention", "auto"]),
    ("examples/python/native/pipelined_mlp.py",
     ["-b", "64", "-e", "1", "--pipeline-schedule", "1f1b"]),
])
def test_native_examples_run(script, args):
    out = run_example(script, *args)
    assert "loss" in out or "accuracy" in out


# the reference's multi_gpu_tests.sh Keras legs: sequential, functional,
# and misc (callback/unary) scripts, pass = clean exit + a final metric
@pytest.mark.parametrize("script", [
    "examples/python/keras/seq_mnist_mlp.py",
    "examples/python/keras/seq_mnist_cnn.py",
    "examples/python/keras/seq_cifar10_cnn.py",
    "examples/python/keras/func_mnist_mlp.py",
    "examples/python/keras/func_mnist_mlp_concat.py",
    "examples/python/keras/func_mnist_cnn_concat.py",
    "examples/python/keras/func_cifar10_alexnet.py",
    "examples/python/keras/func_cifar10_cnn_concat.py",
    "examples/python/keras/callback.py",
    "examples/python/keras/unary.py",
    "examples/python/keras/func_cifar10_cnn_nested.py",
    "examples/python/keras/seq_mnist_cnn_nested.py",
    "examples/python/keras/func_mnist_mlp_concat2.py",
    "examples/python/keras/seq_text_classification.py",
    "examples/python/keras/func_cifar10_cnn_net2net.py",
    "examples/python/keras/func_mnist_cnn.py",
    "examples/python/keras/func_cifar10_cnn.py",
    "examples/python/keras/func_mnist_mlp_net2net.py",
    "examples/python/keras/seq_mnist_cnn_net2net.py",
    "examples/python/keras/reshape.py",
    "examples/python/keras/candle_uno.py",
    "examples/python/keras/func_cifar10_cnn_concat_model.py",
    "examples/python/keras/func_cifar10_cnn_concat_seq_model.py",
])
def test_keras_examples_run(script):
    out = run_example(script, "-e", "1")
    assert "final" in out


def test_keras_net2net_example():
    out = run_example("examples/python/keras/seq_mnist_mlp_net2net.py",
                      "-e", "1")
    assert "final accuracy" in out


def test_pytorch_cnn_example():
    out = run_example("examples/python/pytorch/mnist_cnn_torch.py",
                      "-e", "1")
    assert "final loss" in out


def test_pytorch_cifar10_residual_example():
    out = run_example("examples/python/pytorch/cifar10_cnn_torch.py",
                      "-e", "1")
    assert "final loss" in out


def test_tensor_attach_example():
    out = run_example("examples/python/native/tensor_attach.py",
                      "-b", "32", "-e", "1")
    assert "attach roundtrip OK" in out


def test_bootcamp_demo():
    out = run_example("bootcamp_demo/ff_alexnet_cifar10.py",
                      "-b", "16", "--samples", "64", "-e", "1")
    assert "final accuracy" in out


@pytest.mark.parametrize("script,gate_msg", [
    ("examples/python/onnx/mnist_mlp_onnx.py", "onnx not installed"),
    ("examples/python/keras_exp/func_mnist_mlp_exp.py",
     "tensorflow not installed"),
])
def test_gated_frontend_examples(script, gate_msg):
    """Deps-gated examples exit 0 either way: a final metric when the
    dep is present, the documented skip message when it is not."""
    out = run_example(script, "-e", "1")
    assert gate_msg in out or "final accuracy" in out


def test_keras_mnist_mlp_learns():
    out = run_example("examples/python/keras/mnist_mlp.py",
                      "-e", "3", "--accuracy")
    assert "final accuracy" in out


def test_pytorch_frontend_example():
    run_example("examples/python/pytorch/mnist_mlp_torch.py", "-e", "1")


def test_launcher_code_mode():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu", "--cpu-devices", "4",
         "-c", "import jax; print('ndev', jax.device_count())"],
        capture_output=True, text=True, cwd=REPO, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "ndev 4" in r.stdout


def test_keras_reuters_mlp():
    out = run_example("examples/python/keras/reuters_mlp.py",
                      "-e", "1", "-n", "512")
    assert "final" in out


def test_keras_datasets_shapes():
    from flexflow_tpu.frontends.keras import datasets
    (xtr, ytr), (xte, yte) = datasets.mnist.load_data()
    assert xtr.shape == (60000, 28, 28) and yte.shape == (10000,)
    (xtr, ytr), (xte, yte) = datasets.cifar10.load_data()
    assert xtr.shape == (50000, 32, 32, 3) and ytr.shape == (50000, 1)
    (xtr, ytr), _ = datasets.reuters.load_data(num_words=500)
    assert len(xtr) == 8982 and max(max(s) for s in xtr) < 500
    padded = datasets.pad_sequences(xtr[:4], maxlen=50)
    assert padded.shape == (4, 50)


def test_keras_multi_branch_concat():
    out = run_example("examples/python/keras/multi_branch_concat.py",
                      "-e", "1")
    assert "final" in out
