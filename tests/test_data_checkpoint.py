"""Data loader + checkpoint/resume tests."""

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, make_mesh
from flexflow_tpu.core.checkpoint import restore_model, save_model
from flexflow_tpu.core.dataloader import (
    DataLoaderSet,
    SingleDataLoader,
    synthetic_batch,
)


def test_single_dataloader_batches_and_reset():
    data = np.arange(100).reshape(100, 1).astype(np.float32)
    dl = SingleDataLoader("x", data, batch_size=32)
    assert dl.num_batches == 3
    b1 = np.asarray(dl.next_batch())
    np.testing.assert_allclose(b1[:, 0], np.arange(32))
    dl.next_batch()
    dl.next_batch()
    with pytest.raises(StopIteration):
        dl.next_batch()
    dl.reset()
    np.testing.assert_allclose(np.asarray(dl.next_batch()), b1)


def test_dataloader_set_lockstep_shuffle(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = np.arange(64).astype(np.int32)
    ds = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                       mesh=mesh8, shuffle=True, seed=1)
    seen = []
    for batch in ds:
        xb = np.asarray(batch["input"])
        yb = np.asarray(batch["label"])
        # lockstep: labels index rows of x
        np.testing.assert_allclose(xb, x[yb])
        seen.extend(yb.tolist())
    assert sorted(seen) == list(range(64))
    assert seen != list(range(64)), "must be shuffled"


def test_synthetic_batch_shapes():
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    import jax.numpy as jnp
    ff.create_tensor((8, 16), name="x")
    ff.create_tensor((8, 3), dtype=jnp.int32, name="ids")
    t = ff.dense(ff.input_tensors[0], 4)
    batch = synthetic_batch(ff)
    assert batch["x"].shape == (8, 16)
    assert batch["ids"].dtype == np.int32
    assert batch["label"].shape == (8,)


def _mlp(cfg):
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def test_checkpoint_save_restore_roundtrip(tmp_path):
    cfg = FFConfig()
    cfg.batch_size = 32
    ff = _mlp(cfg)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    for _ in range(3):
        ff.train_batch({"input": x, "label": y})
    path = str(tmp_path / "ckpt")
    save_model(ff, path)
    w_before = ff.get_weights("dense")["kernel"].copy()
    step_before = int(ff.state.step)

    # train further, then restore and confirm rollback
    for _ in range(3):
        ff.train_batch({"input": x, "label": y})
    assert not np.allclose(ff.get_weights("dense")["kernel"], w_before)
    restore_model(ff, path)
    np.testing.assert_allclose(ff.get_weights("dense")["kernel"], w_before)
    assert int(ff.state.step) == step_before

    # resumed training continues
    m = ff.train_batch({"input": x, "label": y})
    assert np.isfinite(float(m["loss"]))
