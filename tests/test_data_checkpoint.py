"""Data loader + checkpoint/resume tests."""

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, make_mesh
from flexflow_tpu.core.checkpoint import restore_model, save_model
from flexflow_tpu.core.dataloader import (
    DataLoaderSet,
    SingleDataLoader,
    synthetic_batch,
)


def test_single_dataloader_batches_and_reset():
    data = np.arange(100).reshape(100, 1).astype(np.float32)
    dl = SingleDataLoader("x", data, batch_size=32)
    assert dl.num_batches == 3
    b1 = np.asarray(dl.next_batch())
    np.testing.assert_allclose(b1[:, 0], np.arange(32))
    dl.next_batch()
    dl.next_batch()
    with pytest.raises(StopIteration):
        dl.next_batch()
    dl.reset()
    np.testing.assert_allclose(np.asarray(dl.next_batch()), b1)


def test_dataloader_set_lockstep_shuffle(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = np.arange(64).astype(np.int32)
    ds = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                       mesh=mesh8, shuffle=True, seed=1)
    seen = []
    for batch in ds:
        xb = np.asarray(batch["input"])
        yb = np.asarray(batch["label"])
        # lockstep: labels index rows of x
        np.testing.assert_allclose(xb, x[yb])
        seen.extend(yb.tolist())
    assert sorted(seen) == list(range(64))
    assert seen != list(range(64)), "must be shuffled"


def test_synthetic_batch_shapes():
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    import jax.numpy as jnp
    ff.create_tensor((8, 16), name="x")
    ff.create_tensor((8, 3), dtype=jnp.int32, name="ids")
    t = ff.dense(ff.input_tensors[0], 4)
    batch = synthetic_batch(ff)
    assert batch["x"].shape == (8, 16)
    assert batch["ids"].dtype == np.int32
    assert batch["label"].shape == (8,)


def _mlp(cfg):
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def test_checkpoint_save_restore_roundtrip(tmp_path):
    cfg = FFConfig()
    cfg.batch_size = 32
    ff = _mlp(cfg)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    for _ in range(3):
        ff.train_batch({"input": x, "label": y})
    path = str(tmp_path / "ckpt")
    save_model(ff, path)
    w_before = ff.get_weights("dense")["kernel"].copy()
    step_before = int(ff.state.step)

    # train further, then restore and confirm rollback
    for _ in range(3):
        ff.train_batch({"input": x, "label": y})
    assert not np.allclose(ff.get_weights("dense")["kernel"], w_before)
    restore_model(ff, path)
    np.testing.assert_allclose(ff.get_weights("dense")["kernel"], w_before)
    assert int(ff.state.step) == step_before

    # resumed training continues
    m = ff.train_batch({"input": x, "label": y})
    assert np.isfinite(float(m["loss"]))


def _ckpt_model(seed=0):
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.seed = seed
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 32), name="input")
    t = ff.dense(x, 64, activation="relu")
    # dropout makes the resume test cover the per-step rng stream too:
    # _train_rng keys on the step mirror, so the resumed run replays the
    # exact dropout masks of the uninterrupted one
    t = ff.dropout(t, 0.25)
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def test_fit_checkpoint_resume_matches_uninterrupted(tmp_path):
    """The elastic-recovery contract (SURVEY 5: the reference has no
    failure handling): fit(checkpoint_dir=...) killed after epoch k and
    re-run resumes at k+1 and lands bit-for-bit where the uninterrupted
    run does (same shuffle stream, same state)."""
    rng = np.random.RandomState(0)
    x = rng.randn(64, 32).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.int32)
    ckdir = str(tmp_path / "ck")

    # uninterrupted 4-epoch run
    ff_ref = _ckpt_model()
    h_ref = ff_ref.fit({"input": x}, y, epochs=4, verbose=False)

    # "crashed" after 2 epochs...
    ff_a = _ckpt_model()
    ff_a.fit({"input": x}, y, epochs=2, verbose=False,
             checkpoint_dir=ckdir)
    # ...fresh process: new model object, same command
    ff_b = _ckpt_model()
    h_b = ff_b.fit({"input": x}, y, epochs=4, verbose=False,
                   checkpoint_dir=ckdir)
    assert [m["epoch"] for m in h_b] == [2, 3]
    assert h_b[-1]["loss"] == pytest.approx(h_ref[-1]["loss"], abs=1e-6)
    w_ref = ff_ref.get_weights("dense")["kernel"]
    w_b = ff_b.get_weights("dense")["kernel"]
    np.testing.assert_allclose(w_ref, w_b, atol=1e-6)


def test_fit_checkpoint_noop_when_complete(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(32, 32).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    ckdir = str(tmp_path / "ck")
    ff = _ckpt_model()
    ff.fit({"input": x}, y, epochs=2, verbose=False, checkpoint_dir=ckdir)
    ff2 = _ckpt_model()
    h = ff2.fit({"input": x}, y, epochs=2, verbose=False,
                checkpoint_dir=ckdir)
    assert h == []  # all epochs already done


def test_fit_checkpoint_same_object_continuation(tmp_path):
    """Same-object continuation (finding from review): a second
    fit(checkpoint_dir=...) on the SAME model must not double-advance
    the shuffle stream — epoch k must use the permutation the
    uninterrupted run used at epoch k."""
    rng = np.random.RandomState(0)
    x = rng.randn(64, 32).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.int32)

    ff_ref = _ckpt_model()
    h_ref = ff_ref.fit({"input": x}, y, epochs=4, verbose=False)

    ckdir = str(tmp_path / "ck")
    ff = _ckpt_model()
    ff.fit({"input": x}, y, epochs=2, verbose=False, checkpoint_dir=ckdir)
    h2 = ff.fit({"input": x}, y, epochs=4, verbose=False,
                checkpoint_dir=ckdir)
    assert [m["epoch"] for m in h2] == [2, 3]
    assert h2[-1]["loss"] == pytest.approx(h_ref[-1]["loss"], abs=1e-6)


def test_restore_model_resyncs_train_rng(tmp_path):
    """Manual restore path must resync the per-step rng mirror too."""
    from flexflow_tpu.core.checkpoint import restore_model, save_model
    rng = np.random.RandomState(0)
    batch = {"input": rng.randn(16, 32).astype(np.float32),
             "label": rng.randint(0, 4, 16).astype(np.int32)}
    ff = _ckpt_model()
    for _ in range(3):
        ff.train_batch(batch)
    save_model(ff, str(tmp_path / "m"))
    ff2 = _ckpt_model()
    restore_model(ff2, str(tmp_path / "m"))
    assert ff2._host_step == 3
    # next steps replay the uninterrupted stream exactly
    m_a = ff.train_batch(batch)
    m_b = ff2.train_batch(batch)
    assert float(m_a["loss"]) == pytest.approx(float(m_b["loss"]), abs=1e-7)
