"""DistributedEmbedding: the executable form of the reference's
per-device table placement (DLRM strategies pin table i to GPU i,
examples/cpp/DLRM/strategies/dlrm_strategy.cc:1-50) — E vocab-complete
tables stacked on a `table` axis and sharded over the mesh."""

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.models import build_dlrm
from flexflow_tpu.parallel.pconfig import OpStrategy
from flexflow_tpu.search.simulator import Simulator


def build_model(bs=16, tables=8, vocab=64, dim=8, mesh=None, strategy=None):
    cfg = FFConfig()
    cfg.batch_size = bs
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    ins = [ff.create_tensor((bs, 2), dtype=jnp.int32, name=f"sparse_{i}")
           for i in range(tables)]
    embs = ff.distributed_embedding(ins, vocab, dim, aggr="sum",
                                    name="tables")
    t = ff.concat(embs, axis=1)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh, strategy=strategy)
    return ff


def data(bs=16, tables=8, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    batch = {f"sparse_{i}": rng.randint(0, vocab, (bs, 2)).astype(np.int32)
             for i in range(tables)}
    batch["label"] = rng.randint(0, 4, bs).astype(np.int32)
    return batch


def test_forward_matches_per_table_gather():
    ff = build_model()
    kern = np.random.RandomState(1).randn(8, 64, 8).astype(np.float32)
    ff.set_weights("tables", {"kernel": kern})
    batch = data()
    logits_in = {k: v for k, v in batch.items() if k != "label"}
    # spot-check through the op itself: output e must equal table e's bag
    op = ff.ops[0]
    from flexflow_tpu.op import OpContext
    outs = op.forward({"kernel": jnp.asarray(kern)},
                      [jnp.asarray(logits_in[f"sparse_{i}"])
                       for i in range(8)], OpContext(training=False))
    for e in range(8):
        expect = kern[e][batch[f"sparse_{e}"]].sum(axis=1)
        np.testing.assert_allclose(np.asarray(outs[e]), expect, rtol=1e-5)
    # and the whole model runs
    m = ff.train_batch(batch)
    assert np.isfinite(float(m["loss"]))


def test_table_sharded_matches_unsharded():
    batch = data()
    ff1 = build_model()
    kern = np.asarray(ff1.get_weights("tables")["kernel"])

    mesh = make_mesh((1, 8), ("data", "model"))
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("tables", OpStrategy({"sample": "data", "table": "model"}))
    ff2 = build_model(mesh=mesh, strategy=strat)
    ff2.set_weights("tables", {"kernel": kern})
    ff2.set_weights("dense", ff1.get_weights("dense"))

    w = ff2.state.params["tables"]["kernel"]
    assert w.sharding.spec == P("model"), w.sharding.spec

    m1 = ff1.train_batch(batch)
    m2 = ff2.train_batch(batch)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-4


def test_stacked_dlrm_trains_table_sharded():
    cfg = FFConfig()
    cfg.batch_size = 32
    mesh = make_mesh((1, 8), ("data", "model"))
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    strat.set("emb_tables", OpStrategy({"sample": "data",
                                        "table": "model"}))
    ff = build_dlrm(cfg, batch_size=32,
                    embedding_vocab_sizes=(256,) * 8,
                    mesh=mesh, strategy=strat, stacked_tables=True)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="mean_squared_error", metrics=[],
               mesh=mesh, strategy=strat)
    rng = np.random.RandomState(0)
    batch = {"dense_features": rng.randn(32, 13).astype(np.float32),
             "label": (rng.rand(32, 1) > 0.5).astype(np.float32)}
    for i in range(8):
        batch[f"sparse_{i}"] = rng.randint(0, 256, (32, 1)).astype(np.int32)
    m = ff.train_batch(batch)
    assert np.isfinite(float(m["loss"]))


def test_cost_model_prefers_table_sharding():
    """Simulated: table sharding (concurrent vocab-complete lookups + an
    all-gather) must beat vocab sharding (a psum per step), and beat
    replication when the replicated tables exceed HBM (the memory
    penalty, simulator.cc:603-628 analog — which is WHY the reference
    places DLRM tables per-device; with row-level traffic pricing,
    replication of tables that FIT is legitimately free of collectives
    and wins on speed)."""
    cfg = FFConfig()
    cfg.batch_size = 1024
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    ins = [ff.create_tensor((1024, 1), dtype=jnp.int32, name=f"s{i}")
           for i in range(8)]
    # 8 x 10M x 64 f32 = 20GB replicated (+optimizer state) >> one
    # chip's HBM; sharded over 8 devices it fits
    embs = ff.distributed_embedding(ins, 10_000_000, 64, name="tables")
    t = ff.concat(embs, axis=1)
    t = ff.softmax(ff.dense(t, 4))
    mesh = make_mesh((1, 8), ("data", "model"))
    sim = Simulator(ff, mesh)

    def strat(extra):
        s = Strategy()
        s.set("tables", OpStrategy({**extra}))
        return s

    t_table = sim.simulate(strat({"table": "model"}))
    t_vocab = sim.simulate(strat({"vocab": "model"}))
    t_repl = sim.simulate(strat({}))
    assert t_table < t_vocab, (t_table, t_vocab)
    assert t_table < t_repl, (t_table, t_repl)


def test_cost_model_ignores_non_dividing_table_axis():
    """6 tables on a 4-wide axis: the executor's spec_for_axes drops the
    non-dividing axis (weight stays replicated), so the cost model must
    price it as replication rather than a phantom 4x speedup."""
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = FFModel(cfg)
    ins = [ff.create_tensor((64, 1), dtype=jnp.int32, name=f"s{i}")
           for i in range(6)]
    embs = ff.distributed_embedding(ins, 10_000, 64, name="tables")
    t = ff.concat(embs, axis=1)
    t = ff.softmax(ff.dense(t, 4))
    mesh = make_mesh((2, 4), ("data", "model"))
    sim = Simulator(ff, mesh)
    s_table = Strategy()
    s_table.set("tables", OpStrategy({"table": "model"}))
    s_repl = Strategy()
    s_repl.set("tables", OpStrategy({}))
    assert sim.simulate(s_table) == sim.simulate(s_repl)


def test_table_sharded_finite_on_combined_mesh():
    """Regression (ROADMAP open item, fixed this PR): on a mesh carrying
    a third axis (the combined dryrun mesh data2 x model2 x seq2) with
    `table` GENUINELY sharded (tables %% axis == 0), the jitted train
    step hit loss=nan. Root cause: jnp.take's default out-of-bounds
    mode is "fill" (NaN fill), and GSPMD's partitioning of the
    table-sharded gather rewrites global indices into locally-shifted
    ones, so the fill-validity select fired on in-bounds lookups —
    forward lookups came back NaN only when XLA actually partitioned
    the gather (a 2-axis mesh replicated it and masked the bug). The
    gathers now use mode="clip" (XLA's native clamp semantics).

    The combined-mesh dryrun graph shape on CPU: 3-D activations, a
    broadcast embedding bias, table+vocab+channel_out all mapped."""
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    strategy = Strategy(default=OpStrategy({
        "sample": "data", "head": "model", "channel_out": "model",
        "vocab": "model", "seq": "seq", "table": "model"}))
    batch, seq_len, hidden = 8, 16, 64
    cfg = FFConfig()
    cfg.batch_size = batch
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((batch, seq_len, hidden), name="input")
    sparse = [ff.create_tensor((batch, 1), dtype=jnp.int32,
                               name=f"cat_{i}") for i in range(2)]
    embs = ff.distributed_embedding(sparse, 32, hidden, name="cat_tables")
    bias = ff.add(embs[0], embs[1], name="bias_sum")
    bias = ff.reshape(bias, (batch, 1, hidden), name="cat_bias")
    t = ff.add(x, bias, name="res")
    head, _ = ff.split(t, [1, seq_len - 1], axis=1, name="cls_split")
    head = ff.reshape(head, (batch, hidden), name="cls_reshape")
    ff.softmax(ff.dense(head, 10, name="cls_head"), name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    bd = {"input": rng.randn(batch, seq_len, hidden).astype(np.float32),
          "label": rng.randint(0, 10, (batch,)).astype(np.int32)}
    for i in range(2):
        bd[f"cat_{i}"] = rng.randint(0, 32, (batch, 1)).astype(np.int32)
    losses = [float(ff.train_batch(bd)["loss"]) for _ in range(2)]
    assert np.isfinite(losses).all(), losses
    # and the lookups are REAL (not clamp-degenerate): match the
    # unsharded reference forward
    ref = FFModel(FFConfig(batch_size=batch))
    xr = ref.create_tensor((batch, seq_len, hidden), name="input")
    sr = [ref.create_tensor((batch, 1), dtype=jnp.int32, name=f"cat_{i}")
          for i in range(2)]
    er = ref.distributed_embedding(sr, 32, hidden, name="cat_tables")
    br = ref.add(er[0], er[1], name="bias_sum")
    br = ref.reshape(br, (batch, 1, hidden), name="cat_bias")
    tr = ref.add(xr, br, name="res")
    hr, _ = ref.split(tr, [1, seq_len - 1], axis=1, name="cls_split")
    hr = ref.reshape(hr, (batch, hidden), name="cls_reshape")
    ref.softmax(ref.dense(hr, 10, name="cls_head"), name="sm")
    ref.compile(optimizer=SGDOptimizer(lr=0.01),
                loss_type="sparse_categorical_crossentropy", metrics=[])
    ref.set_weights("cat_tables",
                    {"kernel": ff.get_weights("cat_tables")["kernel"]})
    ref.set_weights("cls_head", ff.get_weights("cls_head"))
    l_ref = float(ref.train_batch(bd)["loss"])
    l_sharded = float(ff.train_batch(bd)["loss"])
    assert np.isfinite(l_ref)
    np.testing.assert_allclose(l_sharded, l_ref, rtol=1e-4)
