"""Explainable placement search + memory ledger + metrics endpoint
(PR 11 tentpole; docs/observability.md).

Layered like the subsystem:
  * schedule — simulated-trace round-trip: the exported Perfetto JSON
    loads, every event is schema-valid, the critical-path chain is
    time-contiguous, per-resource tracks never overlap, and the
    trace's exact end time equals Simulator.simulate's returned
    makespan BIT-exactly (train) / simulate_serve_step's (serve).
  * search trace — tracing is pure observation (bit-identical results
    at the same seed, on vs off), deterministic event streams, the
    bounded ring, and the serve-placement walk's trace.
  * attribution — per-task-class drift folding: breakdown accounting,
    the share fold, the least-squares alignment recovering a rigged
    per-class scale, and the report table.
  * ledger — serve + train memory ledgers vs the actual nbytes of the
    live device buffers; explain_placement component sums exact.
  * endpoint — /metrics scrape parses, /healthz lives, close() is
    clean and idempotent.
"""

import json
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.pconfig import Strategy
from flexflow_tpu.search.cost_model import ServeArch
from flexflow_tpu.search.simulator import (Simulator,
                                           export_serve_schedule,
                                           serve_step_breakdown,
                                           simulate_serve_step)
from flexflow_tpu.search.trace import SearchTrace
from flexflow_tpu.utils.telemetry import Telemetry

VOCAB = 89


def _model(layers=2):
    from flexflow_tpu.models.transformer import build_transformer
    cfg = FFConfig(batch_size=8)
    cfg.enable_parameter_parallel = True
    cfg.enable_sequence_parallel = True
    return build_transformer(cfg, batch_size=8, seq_len=64, hidden=128,
                             num_heads=4, num_layers=layers, ff_dim=256,
                             num_classes=10)


def _mesh():
    return make_mesh((2, 2, 2), ("data", "model", "seq"))


def _lm(**cfg_kw):
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   serve_retry_backoff_s=0.0)
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    return build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


# --------------------------------------------------------- schedule
def _load_spans(path):
    with open(path) as f:
        doc = json.load(f)
    for ev in doc["traceEvents"]:
        assert isinstance(ev.get("ph"), str) and ev.get("name"), ev
        assert isinstance(ev.get("pid"), int) \
            and isinstance(ev.get("tid"), int), ev
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)), ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) \
                and ev["dur"] >= 0, ev
    return doc, [e for e in doc["traceEvents"] if e["ph"] == "X"]


def test_train_schedule_trace_round_trip(tmp_path):
    ff = _model()
    mesh = _mesh()
    sim = Simulator(ff, mesh)
    strat = Strategy()
    path = str(tmp_path / "sched.json")
    summary = sim.export_schedule(strat, path)
    full = sim.simulate(strat)
    doc, spans = _load_spans(path)
    # exact end-time equality with the priced step time
    assert summary["makespan_s"] == full
    assert doc["metadata"]["makespan_s"] == full
    assert max(e["args"]["t_end_s"] for e in spans) == full
    # per-resource tracks never overlap (resource exclusivity is the
    # event loop's contract) and stay within [0, makespan]
    by_track = {}
    for e in spans:
        by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    assert len(by_track) >= 2  # compute + ici at least
    for es in by_track.values():
        es.sort(key=lambda e: (e["args"]["t_start_s"],
                               e["args"]["t_end_s"]))
        for a, b in zip(es, es[1:]):
            assert a["args"]["t_end_s"] <= b["args"]["t_start_s"]
        for e in es:
            assert 0.0 <= e["args"]["t_start_s"] \
                <= e["args"]["t_end_s"] <= full
    # the critical path chains contiguously (each start bit-equals the
    # previous crit task's end) and reaches the event-loop end
    crit = sorted((e for e in spans if e["args"].get("crit")),
                  key=lambda e: e["args"]["t_start_s"])
    assert crit and summary["critical_tasks"] == len(crit)
    for a, b in zip(crit, crit[1:]):
        assert a["args"]["t_end_s"] == b["args"]["t_start_s"]


def test_train_schedule_trace_scaled_and_penalized(tmp_path):
    """Calibration scale, dispatch overhead and an HBM penalty all
    fold into the trace's exact end time."""
    ff = _model()
    mesh = _mesh()
    sim = Simulator(ff, mesh)
    sim.time_scale = 3.7
    sim.step_overhead = 1.25e-4
    # force a memory penalty by shrinking HBM below the model
    import dataclasses
    spec = dataclasses.replace(sim.mm.spec, hbm_capacity=1024.0)
    sim.mm = dataclasses.replace(sim.mm, spec=spec)
    sim.invalidate()
    strat = Strategy()
    path = str(tmp_path / "sched.json")
    summary = sim.export_schedule(strat, path)
    full = sim.simulate(strat)
    assert summary["hbm_penalty_s"] > 0
    assert summary["makespan_s"] == full
    _, spans = _load_spans(path)
    assert max(e["args"]["t_end_s"] for e in spans) == full
    names = {e["name"] for e in spans}
    assert "hbm_penalty" in names and "step_overhead" in names


def test_serve_schedule_trace_round_trip(tmp_path):
    arch = ServeArch(num_layers=4, hidden=512, num_heads=8,
                     head_dim=64, ff_dim=2048, vocab=32000)
    path = str(tmp_path / "serve_sched.json")
    summary = export_serve_schedule(arch, 4, path)
    ref = simulate_serve_step(arch, 4)
    doc, spans = _load_spans(path)
    assert summary["makespan_s"] == ref
    assert doc["metadata"]["makespan_s"] == ref
    assert max(e["args"]["t_end_s"] for e in spans) == ref
    # the serve chain is serial: task durations + penalty sum to the
    # makespan (chain accumulation, tight tolerance)
    total = sum(e["dur"] for e in spans) / 1e6
    assert total == pytest.approx(ref, rel=1e-9)
    # per-class breakdown sums exactly to the simulated step
    bd = serve_step_breakdown(arch, 4)
    assert sum(bd.values()) == pytest.approx(ref, rel=1e-12)
    assert bd["collective"] > 0 and bd["attention"] > 0
    # t=1 prices no collectives
    bd1 = serve_step_breakdown(arch, 1)
    assert bd1["collective"] == 0.0


# ------------------------------------------------------ search trace
def test_search_trace_determinism_and_purity():
    """Tracing on vs off at one seed: bit-identical strategies; two
    traced runs: identical event streams."""
    from flexflow_tpu.search.mcmc import optimize
    ff = _model()
    mesh = _mesh()

    def run(traced, seed=5):
        ff.config.search_trace = traced
        s = optimize(ff, budget=120, mesh=mesh, seed=seed,
                     use_native=False, chains=2)
        t = (ff.search_stats or {}).get("trace")
        return {k: dict(v.axis_map)
                for k, v in s.op_strategies.items()}, t

    s_on, t_on = run(True)
    s_off, t_off = run(False)
    s_on2, t_on2 = run(True)
    ff.config.search_trace = True
    assert s_on == s_off, "tracing changed the search result"
    assert t_off is None and t_on and t_on2
    assert t_on["proposals"] == 120 and t_on2["proposals"] == 120
    assert t_on == t_on2, "traced runs are not deterministic"
    assert t_on["accepts"] == sum(
        p["accepts"] for p in t_on["acceptance_by_phase"])
    assert sum(d["proposals"] for d in t_on["by_path"].values()) == 120
    # the best-cost curve is monotone decreasing
    curve = [c["cost_s"] for c in t_on["best_cost_curve"]]
    assert curve == sorted(curve, reverse=True)


def test_search_trace_ring_bounded():
    tr = SearchTrace(budget=100, max_events=32)
    for i in range(100):
        tr.record(i, 0, "rewrite", "op", 0.0, True, 1.0, "delta")
    s = tr.summary()
    assert s["events_recorded"] == 32 and s["events_dropped"] == 68
    assert s["proposals"] == 100 and s["accepts"] == 100
    assert [p["proposals"] for p in s["acceptance_by_phase"]] \
        == [34, 33, 33]
    assert len(tr.events_list()) == 32


def test_serve_place_trace():
    from flexflow_tpu.search.serve_place import optimize_serve
    arch = ServeArch(num_layers=4, hidden=512, num_heads=8,
                     head_dim=64, ff_dim=2048, vocab=32000)
    p1 = optimize_serve(arch, 4, budget=32, seed=7)
    p2 = optimize_serve(arch, 4, budget=32, seed=7)
    assert p1.trace and p1.trace["proposals"] > 0
    assert p1.tensor_parallel == p2.tensor_parallel
    assert p1.trace == p2.trace  # deterministic walk
    cfg = FFConfig()
    cfg.search_trace = False
    assert optimize_serve(arch, 4, budget=8, seed=7,
                          config=cfg).trace is None


def test_search_report_renders_trace():
    from flexflow_tpu.search.mcmc import optimize
    from flexflow_tpu.utils.profiling import search_report
    ff = _model()
    optimize(ff, budget=60, mesh=_mesh(), seed=1, use_native=False,
             chains=1)
    rep = search_report(ff.search_stats)
    assert "trace:" in rep and "accepted" in rep
    assert "best-cost curve" in rep


# ------------------------------------------------------- attribution
def test_task_drift_share_fold():
    tel = Telemetry()
    tel.record_drift("d", "r1", 1.0, 2.0,
                     breakdown={"a": 0.5, "b": 0.5})
    snap = tel.task_drift_snapshot()["d"]
    assert snap["regimes"] == 1
    # one regime: both classes inherit the regime's 2x ratio
    assert snap["classes"]["a"]["ratio"] == pytest.approx(2.0)
    assert snap["classes"]["b"]["ratio"] == pytest.approx(2.0)
    # regimes without breakdowns never participate
    tel2 = Telemetry()
    tel2.record_drift("d", "r1", 1.0, 2.0)
    assert tel2.task_drift_snapshot() == {}


def test_task_drift_lstsq_recovers_rigged_scales():
    """Two classes, rigged so class `a` runs 2x its prediction and
    class `b` exactly as predicted: with enough distinct regime mixes
    the alignment recovers the per-class factors — the 'which term is
    off' answer a per-regime ratio cannot give."""
    tel = Telemetry()
    mixes = [(1.0, 0.1), (0.1, 1.0), (0.5, 0.5), (0.8, 0.3)]
    for i, (pa, pb) in enumerate(mixes):
        measured = 2.0 * pa + 1.0 * pb
        tel.record_drift("d", f"regime{i}", pa + pb, measured,
                         breakdown={"a": pa, "b": pb})
    snap = tel.task_drift_snapshot()["d"]
    assert snap["method"] == "lstsq"
    assert snap["classes"]["a"]["ratio"] == pytest.approx(2.0)
    assert snap["classes"]["b"]["ratio"] == pytest.approx(1.0)
    rep = tel.drift_report()
    assert "task class" in rep and "lstsq" in rep
    assert "regime0" in rep  # named regime keys render as-is


def test_train_step_breakdown_classes():
    ff = _model()
    sim = Simulator(ff, _mesh())
    bd = sim.step_breakdown(Strategy())
    assert set(bd) == set(sim.TRAIN_TASK_CLASSES)
    assert bd["fwd"] > 0 and bd["bwd"] > 0


# ------------------------------------------------------------ ledger
def test_serve_memory_ledger_matches_live_buffers():
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(_lm(telemetry=True))
    eng.warmup()
    led = eng.memory_ledger()
    assert led["pools_live"]
    # ledger params + kv accounting vs the actual nbytes of the live
    # device buffers: every array is unsharded here, so the comparison
    # is exact (ci.sh gates <= 5% to leave room for real meshes)
    live = float(sum(
        np.prod(x.shape) * x.dtype.itemsize
        for x in [*__import__("jax").tree_util.tree_leaves(
            eng._step_params), eng._k_pages, eng._v_pages]))
    assert led["live_bytes"] == pytest.approx(live, rel=1e-9)
    assert led["params_bytes"] + led["kv_pool_bytes"] \
        == pytest.approx(live, rel=0.05)
    assert led["total_bytes"] > led["params_bytes"]
    assert led["sim_hbm_input_bytes"] > 0
    # components exported as gauges on the engine registry
    m = eng.telemetry.metrics
    for comp in ("params", "kv_pool", "total", "live"):
        assert m.gauge("serve_hbm_bytes", component=comp) > 0
    eng.close()


def test_train_memory_ledger():
    import jax
    ff = _model()
    ff.compile()
    ff.init_layers()
    led = ff.memory_ledger()
    params = float(sum(x.nbytes for x in
                       jax.tree_util.tree_leaves(ff.state.params)))
    assert led["params_bytes"] == pytest.approx(params, rel=1e-9)
    assert led["live_bytes"] >= led["params_bytes"]
    assert led["sim_hbm_input_bytes"] is not None


def test_explain_placement_components_sum_exact():
    from flexflow_tpu.search.explain import (explain_placement,
                                             explain_report)
    ff = _model()
    mesh = _mesh()
    info = explain_placement(ff, mesh=mesh, strategy=Strategy(),
                             top_k=3)
    assert info["ops"]
    searchable = 0
    for o in info["ops"]:
        assert sum(o["components"].values()) == o["total_s"]
        for a in o["alternatives"]:
            assert sum(a["components"].values()) == a["total_s"]
            assert a["delta_s"] == a["total_s"] - o["total_s"]
        searchable += bool(o["alternatives"])
    assert searchable > 0  # linear/attention ops have alternatives
    rep = explain_report(info)
    assert "rejected" in rep and "hbm:" in rep
    assert info["memory"]["sim_bytes_per_device"] > 0


# ---------------------------------------------------------- endpoint
def test_metrics_endpoint_scrape_and_close():
    from flexflow_tpu.serve import ServeEngine
    eng = ServeEngine(_lm(metrics_port=0))
    assert eng.telemetry.enabled  # metrics_port implies telemetry
    port = eng.metrics_server.port
    rng = np.random.RandomState(0)
    eng.generate([list(rng.randint(1, VOCAB, size=8))
                  for _ in range(2)], 4)
    h = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10)
    assert h.status == 200 and h.read() == b"ok\n"
    page = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    assert "serve_tokens_generated_total" in page
    for ln in page.strip().splitlines():
        if not ln.startswith("#"):
            float(ln.rpartition(" ")[2])  # every sample parses
    assert urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).status == 200
    eng.close()
    eng.close()  # idempotent
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                               timeout=2)


def test_metrics_port_validation():
    with pytest.raises(ValueError):
        FFConfig(metrics_port=70000)
    cfg = FFConfig(argv=["--metrics-port", "0"])
    assert cfg.metrics_port == 0
    assert FFConfig().metrics_port is None


def test_schedule_trace_flag_exports_through_optimize(tmp_path):
    from flexflow_tpu.search.mcmc import optimize
    ff = _model()
    path = str(tmp_path / "sched.json")
    ff.config.schedule_trace_file = path
    optimize(ff, budget=40, mesh=_mesh(), seed=0, use_native=False,
             chains=1)
    summary = ff.search_stats["schedule_trace"]
    doc, spans = _load_spans(path)
    assert doc["metadata"]["makespan_s"] == summary["makespan_s"]
