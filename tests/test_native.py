"""Native C++ runtime tests: event-loop simulator parity vs the Python
implementation, native MCMC search quality + cost parity, and the
prefetching data loader vs a plain numpy gather.

(The reference keeps all of this in C++ with no parity oracle; here the
Python implementations serve as executable specifications.)
"""

import numpy as np
import pytest

from flexflow_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def random_taskgraph(rng, n_tasks=60, n_resources=3, p_edge=0.15):
    """Random DAG with edges only from earlier to later tasks."""
    durations = rng.uniform(1e-5, 1e-3, n_tasks)
    resources = rng.randint(0, n_resources, n_tasks)
    deps = [[] for _ in range(n_tasks)]
    for i in range(n_tasks):
        for j in range(i):
            if rng.rand() < p_edge:
                deps[i].append(j)
    return durations, resources, deps


def python_simulate(durations, resources, deps):
    from flexflow_tpu.search.simulator import TaskGraph
    g = TaskGraph()
    tasks = []
    for i in range(len(durations)):
        tasks.append(g.add(f"t{i}", float(durations[i]),
                           str(int(resources[i])),
                           [tasks[j] for j in deps[i]]))
    return g.simulate()


class TestNativeSimulator:
    def test_matches_python_on_random_dags(self, rng):
        for trial in range(10):
            durations, resources, deps = random_taskgraph(rng)
            indptr = np.zeros(len(durations) + 1, np.int32)
            flat = []
            for i, d in enumerate(deps):
                flat.extend(d)
                indptr[i + 1] = len(flat)
            from flexflow_tpu.native.wrappers import simulate_taskgraph
            got = simulate_taskgraph(durations, resources, indptr, flat)
            want = python_simulate(durations, resources, deps)
            assert got == pytest.approx(want, rel=1e-12), f"trial {trial}"

    def test_chain_and_parallel(self):
        from flexflow_tpu.native.wrappers import simulate_taskgraph
        # chain of 3 on one resource: sum
        got = simulate_taskgraph([1.0, 2.0, 3.0], [0, 0, 0],
                                 [0, 0, 1, 2], [0, 1])
        assert got == pytest.approx(6.0)
        # two independent tasks on different resources: max
        got = simulate_taskgraph([5.0, 3.0], [0, 1], [0, 0, 0], [])
        assert got == pytest.approx(5.0)
        # two independent tasks sharing a resource: serialize
        got = simulate_taskgraph([5.0, 3.0], [0, 0], [0, 0, 0], [])
        assert got == pytest.approx(8.0)


def _search_model(mesh):
    from flexflow_tpu import FFConfig, FFModel
    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.enable_parameter_parallel = True
    cfg.enable_attribute_parallel = True
    # the native table lowers ONE sync task per op (pre-bucket model);
    # parity against the Python simulator requires the legacy sync
    cfg.grad_bucket_mb = 0.0
    ff = FFModel(cfg, mesh=mesh)
    x = ff.create_tensor((32, 64), name="input")
    h = ff.dense(x, 256, activation="relu", name="fc1")
    h = ff.dense(h, 256, activation="relu", name="fc2")
    h = ff.dense(h, 10, name="fc3")
    ff.softmax(h, name="sm")
    return ff


class TestNativeSearch:
    def test_assignment_cost_matches_python_simulator(self, mesh_2d):
        from flexflow_tpu.parallel.pconfig import OpStrategy, Strategy
        from flexflow_tpu.search.mcmc import candidate_maps
        from flexflow_tpu.search.native_search import lower_to_arrays
        from flexflow_tpu.search.simulator import Simulator
        from flexflow_tpu.native.wrappers import simulate_assignment

        ff = _search_model(mesh_2d)
        sim = Simulator(ff, mesh_2d)
        cands = {op.name: candidate_maps(op, mesh_2d, ff.config)
                 for op in ff.ops}
        init = Strategy()
        table, edges, _, init_assign, cand_lists = lower_to_arrays(
            ff, sim, cands, init)

        rng = np.random.RandomState(1)
        for _ in range(8):
            assign = [rng.randint(len(l)) for l in cand_lists]
            strat = Strategy()
            for i, op in enumerate(ff.ops):
                strat.set(op.name, OpStrategy(dict(cand_lists[i][assign[i]])))
            want = sim.simulate(strat)
            got = simulate_assignment(table, edges, assign, sim.overlap,
                                      sim.mm.spec.hbm_capacity,
                                      sim.time_scale)
            assert got == pytest.approx(want, rel=1e-9)

    def test_native_search_beats_or_matches_dp(self, mesh_2d):
        from flexflow_tpu.parallel.pconfig import Strategy
        from flexflow_tpu.search.mcmc import optimize
        from flexflow_tpu.search.simulator import Simulator

        ff = _search_model(mesh_2d)
        sim = Simulator(ff, mesh_2d)
        dp_cost = sim.simulate(Strategy())
        best = optimize(ff, budget=300, seed=0, simulator=sim,
                        use_native=True)
        assert sim.simulate(best) <= dp_cost * (1 + 1e-9)

    def test_python_and_native_agree_on_quality(self, mesh_2d):
        """Both engines explore the same space; their best costs should
        land close (stochastic walks, so compare loosely)."""
        from flexflow_tpu.search.mcmc import optimize
        from flexflow_tpu.search.simulator import Simulator

        ff = _search_model(mesh_2d)
        sim = Simulator(ff, mesh_2d)
        b_native = optimize(ff, budget=400, seed=0, simulator=sim,
                            use_native=True)
        b_python = optimize(ff, budget=400, seed=0, simulator=sim,
                            use_native=False)
        c_native = sim.simulate(b_native)
        c_python = sim.simulate(b_python)
        assert c_native <= c_python * 1.5
        assert c_python <= c_native * 1.5


class TestNativeDataLoader:
    def test_gather_matches_numpy(self, rng):
        from flexflow_tpu.native.wrappers import NativePrefetchLoader
        x = rng.randn(37, 5, 3).astype(np.float32)
        y = rng.randint(0, 10, 37).astype(np.int32)
        loader = NativePrefetchLoader({"x": x, "y": y}, batch_size=8)
        order = rng.permutation(37).astype(np.int64)
        loader.start_epoch(order)
        assert loader.num_batches == 4  # drop_last
        for b in range(4):
            batch = loader.next_batch()
            sel = order[b * 8:(b + 1) * 8]
            np.testing.assert_array_equal(batch["x"], x[sel])
            np.testing.assert_array_equal(batch["y"], y[sel])
        assert loader.next_batch() is None
        loader.close()

    def test_multiple_epochs_and_restart(self, rng):
        from flexflow_tpu.native.wrappers import NativePrefetchLoader
        x = np.arange(20, dtype=np.float64).reshape(20, 1)
        loader = NativePrefetchLoader({"x": x}, batch_size=4)
        for _ in range(3):
            order = rng.permutation(20).astype(np.int64)
            loader.start_epoch(order)
            seen = []
            while True:
                b = loader.next_batch()
                if b is None:
                    break
                seen.extend(b["x"][:, 0].astype(np.int64).tolist())
            assert seen == order.tolist()
        # restart mid-epoch must not deadlock or deliver stale rows
        order = np.arange(20, dtype=np.int64)
        loader.start_epoch(order)
        loader.next_batch()
        loader.start_epoch(order[::-1].copy())
        b = loader.next_batch()
        np.testing.assert_array_equal(b["x"][:, 0], order[::-1][:4])
        loader.close()

    def test_dataloaderset_native_path(self, rng, mesh8):
        from flexflow_tpu.core.dataloader import DataLoaderSet
        x = rng.randn(64, 4).astype(np.float32)
        y = rng.randint(0, 10, 64).astype(np.int32)
        ds = DataLoaderSet({"input": x, "label": y}, batch_size=16,
                           mesh=mesh8, shuffle=True, seed=3)
        assert ds._native is not None
        batches = list(ds)
        assert len(batches) == 4
        got = np.sort(np.concatenate(
            [np.asarray(b["label"]) for b in batches]))
        np.testing.assert_array_equal(got, np.sort(y))
        # epoch 2 reshuffles but preserves the set
        batches2 = list(ds)
        got2 = np.sort(np.concatenate(
            [np.asarray(b["label"]) for b in batches2]))
        np.testing.assert_array_equal(got2, np.sort(y))


def test_embedding_bag_native_vs_numpy(rng):
    from flexflow_tpu.native.wrappers import embedding_bag
    table = rng.randn(50, 16).astype(np.float32)
    idx = rng.randint(-1, 50, (8, 5)).astype(np.int64)  # -1 = padding
    for mode in ("sum", "mean"):
        got = embedding_bag(table, idx, mode=mode)
        valid = idx >= 0
        ref = np.where(valid[..., None], table[np.clip(idx, 0, 49)], 0).sum(1)
        if mode == "mean":
            ref = ref / np.maximum(valid.sum(1, keepdims=True), 1)
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
