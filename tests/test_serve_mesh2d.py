"""2-D (tensor x data) serve-mesh search (PR 20): ONE Metropolis walk
prices tensor degree x replica count x torus-axis assignment into
goodput-under-SLO, HBM-infeasible degrees rejected up front, rows
persisted in the shared CostCache under the widened mesh fingerprint,
and the searched (t, r) shape wired end to end — the pool boots it and
the autoscaler's target pricing reads the searched table.

Layers:
  * search — determinism at one seed, feasibility rejection (a pool
    that fits sharded but not unsharded), degenerate-baseline gains,
    axis-assignment dedupe on square/cubic toruses.
  * cache — disk round-trip of step rows + a guaranteed fingerprint
    miss per folded field (kv dtype, adapter rank, SLO targets,
    arrival rate).
  * serving tier — --serve-replicas auto boots the searched shape
    with token identity vs a reference engine; the autoscaler's
    priced target reads the 2-D table (a rigged table flips the
    decision); router_report renders chosen-vs-rejected cells.
"""

import dataclasses
import warnings

import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.search.cost_model import ServeArch, serve_device_bytes
from flexflow_tpu.search.machine_model import (MachineSpec,
                                               TPUMachineModel)
from flexflow_tpu.search.serve_place import (DisaggPlacement,
                                             MeshTraffic,
                                             ServeMeshPlacement,
                                             ServePlacement,
                                             _mesh_fingerprint,
                                             axis_assignments,
                                             mesh_cell_metrics,
                                             optimize_serve_mesh)


# --------------------------------------------------------------- helpers
def _arch(**over):
    kw = dict(num_layers=2, hidden=64, num_heads=4, head_dim=16,
              ff_dim=256, vocab=89, decode_lanes=4, prefill_lanes=32,
              context=96, decode_tokens=8)
    kw.update(over)
    return ServeArch(**kw)


def _traffic(**over):
    kw = dict(arrival_rps=64.0, prefix_hit=0.5,
              requests_per_preamble=8.0, slo_ttft_s=1.0,
              slo_tpot_s=0.1)
    kw.update(over)
    return MeshTraffic(**kw)


def _mm(**spec_over):
    return TPUMachineModel(MachineSpec(**spec_over))


# =======================================================================
# axis assignments (satellite: square/cubic torus dedupe)
# =======================================================================
def test_axis_assignments_dedupe_cubic_torus():
    mm = _mm(ici_torus_dims=(2, 2, 2))
    # three symmetric (2,) runs and two (2, 2) runs collapse to one
    assert axis_assignments(mm, 2) == [(), (2,)]
    assert axis_assignments(mm, 4) == [(), (2, 2)]
    assert axis_assignments(mm, 8) == [(), (2, 2, 2)]


def test_axis_assignments_dedupe_square_torus():
    mm = _mm(ici_torus_dims=(4, 4))
    assert axis_assignments(mm, 4) == [(), (4,)]
    assert axis_assignments(mm, 16) == [(), (4, 4)]
    # asymmetric runs are NOT merged
    mm2 = _mm(ici_torus_dims=(2, 4))
    assert axis_assignments(mm2, 2) == [(), (2,)]
    assert axis_assignments(mm2, 4) == [(), (4,)]
    assert axis_assignments(mm2, 8) == [(), (2, 4)]


# =======================================================================
# report-ratio degradation (satellite: warn, never KeyError)
# =======================================================================
def test_speedup_vs_single_degrades_with_warning():
    p = ServePlacement(tensor_parallel=2, axis_dims=(),
                       decode_step_s=1e-3, prefill_step_s=2e-3,
                       cost=1.5e-3, decode_by_degree={2: 1e-3})
    with pytest.warns(RuntimeWarning, match="t=1 baseline"):
        assert p.speedup_vs_single() == 1.0
    full = dataclasses.replace(p, decode_by_degree={1: 2e-3, 2: 1e-3})
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert full.speedup_vs_single() == pytest.approx(2.0)


def test_tpot_reduction_degrades_with_warning():
    d = DisaggPlacement(prefill_engines=1, prefill_tensor=1,
                        decode_engines=1, decode_tensor=1,
                        decode_step_s=1e-3, prefill_step_s=2e-3,
                        transfer_s=1e-4, bottleneck_s=2e-3,
                        cost=3e-3, unified_tpot_s=0.0)
    with pytest.warns(RuntimeWarning, match="unified"):
        assert d.tpot_reduction_vs_unified() == 1.0
    ok = dataclasses.replace(d, unified_tpot_s=2e-3)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert ok.tpot_reduction_vs_unified() == pytest.approx(2.0)


# =======================================================================
# the 2-D search
# =======================================================================
def test_mesh_search_deterministic_at_one_seed():
    arch = _arch()
    a = optimize_serve_mesh(arch, 4, mm=_mm(), traffic=_traffic(),
                            seed=3)
    b = optimize_serve_mesh(arch, 4, mm=_mm(), traffic=_traffic(),
                            seed=3)
    assert (a.tensor_parallel, a.replicas, a.tensor_axis_dims,
            a.data_axis_dims) == \
        (b.tensor_parallel, b.replicas, b.tensor_axis_dims,
         b.data_axis_dims)
    assert a.table == b.table
    assert a.cost == b.cost and a.goodput_per_s == b.goodput_per_s


def test_mesh_table_complete_and_budgeted():
    arch = _arch()
    p = optimize_serve_mesh(arch, 4, mm=_mm(), traffic=_traffic())
    # divisor degrees {1, 2, 4} x replica counts with t*r <= 4
    assert set(p.table) == {(1, 1), (1, 2), (1, 3), (1, 4),
                            (2, 1), (2, 2), (4, 1)}
    assert p.tensor_parallel * p.replicas <= 4
    assert set(p.decode_by_degree) == {1, 2, 4}
    for (t, r), cell in p.table.items():
        assert cell["tensor"] == t and cell["replicas"] == r
        assert cell["tokens_per_s"] > 0
    chosen = p.cell(p.tensor_parallel, p.replicas)
    assert chosen is not None
    assert p.goodput_per_s == chosen["goodput_per_s"]


def test_mesh_objective_prefers_replicas_under_load():
    """When one replica cannot sustain the arrival rate and every
    degree fits HBM, the searched cell multiplies replicas instead of
    burning the whole budget on tensor sharding."""
    arch = _arch()
    mm = _mm()
    step = mesh_cell_metrics(
        arch, 1, 1, 1e-3, 1e-3, 1e-3, _traffic())  # shape probe only
    assert step["capacity_rps"] > 0
    # arrival far above any single replica's capacity, SLOs loose
    # enough that every cell passes: goodput == min(arrival, capacity)
    # and capacity grows with r
    t1 = optimize_serve_mesh(
        arch, 4, mm=mm,
        traffic=_traffic(arrival_rps=1e9, prefix_hit=0.0,
                         slo_ttft_s=0.0, slo_tpot_s=0.0))
    assert t1.replicas > 1
    assert t1.goodput_gain_vs_tensor_only() > 1.0


def test_mesh_feasibility_rejection_adapter_pool():
    """The acceptance geometry: an adapter pool that fits at t=4 but
    not at t=1 — the unsharded degree is REJECTED (recorded with its
    residency), never priced into the table, and the winner shards."""
    arch = _arch(adapter_rank=8, adapter_slots=4)
    b1 = serve_device_bytes(arch, 1)
    b4 = serve_device_bytes(arch, 4)
    assert b4 < b1
    mm = _mm(hbm_capacity=(b4 + b1) / 2.0)
    p = optimize_serve_mesh(arch, 4, mm=mm, traffic=_traffic())
    assert [d["tensor"] for d in p.infeasible] == [1]
    assert "HBM" in p.infeasible[0]["reason"]
    assert p.infeasible[0]["device_bytes"] == pytest.approx(b1)
    assert all(t != 1 for (t, _r) in p.table)
    assert p.tensor_parallel > 1
    # the rejection IS the replicas-only baseline's loss
    assert p.goodput_gain_vs_replicas_only() > 1e6


def test_mesh_search_nothing_fits_raises():
    arch = _arch()
    mm = _mm(hbm_capacity=1.0)   # one byte: nothing fits
    with pytest.raises(ValueError, match="no tensor degree fits"):
        optimize_serve_mesh(arch, 4, mm=mm, traffic=_traffic())


def test_mesh_fixed_dimensions():
    arch = _arch()
    p = optimize_serve_mesh(arch, 4, mm=_mm(), traffic=_traffic(),
                            fixed_tensor=2)
    assert p.tensor_parallel == 2
    assert set(p.table) == {(2, 1), (2, 2)}
    q = optimize_serve_mesh(arch, 4, mm=_mm(), traffic=_traffic(),
                            fixed_replicas=2)
    assert q.replicas == 2
    assert set(q.table) == {(1, 2), (2, 2)}
    with pytest.raises(ValueError, match="not a feasible degree"):
        optimize_serve_mesh(arch, 4, mm=_mm(), fixed_tensor=3)


# =======================================================================
# cost-cache round-trip + fingerprint misses
# =======================================================================
def test_mesh_cache_roundtrip_on_disk(tmp_path, monkeypatch):
    from flexflow_tpu.search import serve_place
    from flexflow_tpu.search.cost_cache import CostCache

    path = str(tmp_path / "mesh_cache.json")
    cfg = FFConfig(batch_size=1, cost_cache_file=path,
                   search_trace=False)
    arch = _arch()
    traffic = _traffic()
    mm = _mm()
    p1 = optimize_serve_mesh(arch, 4, mm=mm, config=cfg,
                             traffic=traffic)

    # the rows survive on DISK: a fresh store (not the process-shared
    # instance) must return the winner's step row under the mesh
    # fingerprint + full arch signature
    fresh = CostCache(path)
    key = fresh.entry_key(
        "serve_mesh_step",
        (p1.tensor_parallel, tuple(p1.tensor_axis_dims)),
        extra=arch.signature())
    row = fresh.get(p1.fingerprint, key)
    assert row is not None
    assert row.fwd == pytest.approx(p1.decode_step_s)
    assert row.bwd == pytest.approx(p1.prefill_step_s)
    assert row.fwd_comm == pytest.approx(p1.mixed_step_s)

    # a second identical search never re-simulates — every step price
    # is a cache hit
    def _boom(*a, **kw):
        raise AssertionError("cache miss: simulate_serve_step called")
    monkeypatch.setattr(serve_place, "simulate_serve_step", _boom)
    p2 = optimize_serve_mesh(arch, 4, mm=mm, config=cfg,
                             traffic=traffic)
    assert p2.table == p1.table
    assert (p2.tensor_parallel, p2.replicas) == \
        (p1.tensor_parallel, p1.replicas)


def test_mesh_fingerprint_misses_per_folded_field():
    """Every folded field flips the fingerprint: kv dtype, adapter
    rank, and EACH traffic/SLO knob — rows can never resurrect across
    a flip (the guaranteed-miss acceptance criterion)."""
    mm = _mm()
    base_arch = _arch()
    base_tr = _traffic()
    fps = {
        "base": _mesh_fingerprint(mm, base_arch, base_tr),
        "kv_dtype": _mesh_fingerprint(
            mm, _arch(kv_dtype="int8", kv_itemsize=1.0,
                      kv_scales=True), base_tr),
        "adapter_rank": _mesh_fingerprint(
            mm, _arch(adapter_rank=8, adapter_slots=4), base_tr),
        "slo_ttft": _mesh_fingerprint(
            mm, base_arch, _traffic(slo_ttft_s=2.0)),
        "slo_tpot": _mesh_fingerprint(
            mm, base_arch, _traffic(slo_tpot_s=0.2)),
        "arrival": _mesh_fingerprint(
            mm, base_arch, _traffic(arrival_rps=128.0)),
        "prefix_hit": _mesh_fingerprint(
            mm, base_arch, _traffic(prefix_hit=0.25)),
    }
    vals = list(fps.values())
    assert len(set(vals)) == len(vals), fps
    # and searches report the fingerprint they cached under
    p = optimize_serve_mesh(base_arch, 2, mm=mm, traffic=base_tr)
    assert p.fingerprint in ("", fps["base"])


def test_mesh_traffic_from_config():
    cfg = FFConfig(batch_size=1, slo_ttft_ms=50.0, slo_tpot_ms=5.0)
    tr = MeshTraffic.from_config(cfg, arrival_rps=10.0)
    assert tr.slo_ttft_s == pytest.approx(0.05)
    assert tr.slo_tpot_s == pytest.approx(0.005)
    assert tr.arrival_rps == 10.0


# =======================================================================
# serving-tier wiring
# =======================================================================
def _lm(**cfg_kw):
    from flexflow_tpu.models.transformer import build_transformer_lm
    cfg = FFConfig(batch_size=1, kv_page_size=4, kv_num_pages=49,
                   serve_max_seqs=4, serve_prefill_budget=8,
                   serve_spec_decode=False, **cfg_kw)
    return build_transformer_lm(cfg, vocab_size=61, max_seq_len=96,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=72)


def test_serve_replicas_auto_config_and_cli():
    cfg = FFConfig(batch_size=1, serve_replicas="auto")
    assert cfg.serve_replicas == "auto"
    cfg2 = FFConfig(batch_size=1,
                    argv=["--serve-replicas", "auto"])
    assert cfg2.serve_replicas == "auto"
    cfg2.parse_args(["--serve-replicas", "2"])
    assert cfg2.serve_replicas == 2
    with pytest.raises(ValueError, match="serve_replicas"):
        FFConfig(batch_size=1, serve_replicas="many")
    with pytest.raises(ValueError, match="serve_replicas"):
        FFConfig(batch_size=1, serve_replicas=0)


def test_pool_boots_searched_placement_token_identity():
    """--serve-replicas auto: the pool resolves (t, r) through the
    2-D search, boots exactly that shape, and every completed request
    is token-identical to a single reference engine."""
    from flexflow_tpu.serve import ReplicaPool, ServeEngine
    from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic

    ff = _lm(serve_replicas="auto")
    pool = ReplicaPool(ff)
    p = pool.mesh_placement
    assert isinstance(p, ServeMeshPlacement)
    assert len(pool.replicas) == p.replicas
    assert all(r.engine.tp == p.tensor_parallel
               for r in pool.replicas)
    traffic = make_traffic(TrafficSpec(
        requests=8, seed=4, rate_rps=2000.0, tenants=2,
        prefix_tokens=16, max_prompt=48, max_new_cap=6,
        sample_frac=0.25, top_k=4, vocab=61))
    res = pool.run(traffic, slo_ttft_s=1.0, slo_tpot_s=1.0,
                   sample_seed=0)
    pool.assert_zero_recompiles()
    pool.check_drained()
    assert res["mesh_placement"]["replicas"] == p.replicas
    eng = ServeEngine(ff)
    eng.warmup()
    ref = eng.generate([t.prompt for t in traffic],
                       [t.max_new for t in traffic],
                       temperature=[t.temperature for t in traffic],
                       top_k=[t.top_k for t in traffic],
                       sample_seed=0,
                       stream_ids=[t.stream_id for t in traffic])
    for rec, r in zip(res["requests"], ref):
        if rec["outcome"] == "completed":
            assert rec["tokens"] == r
        else:
            assert rec["tokens"] == r[:len(rec["tokens"])]
    # the default autoscaler prices targets off the searched table
    scaler = pool._default_autoscaler()
    assert scaler.mesh_table == p.table
    pool.close()


def test_pool_explicit_replicas_unchanged():
    from flexflow_tpu.serve import ReplicaPool
    ff = _lm(serve_replicas=2)
    pool = ReplicaPool(ff)
    assert pool.mesh_placement is None
    assert len(pool.replicas) == 2
    assert pool.last_stats is None
    pool.close()


def test_autoscaler_target_reads_mesh_table_rigged():
    """The regression the acceptance criteria name: two autoscalers
    see IDENTICAL gauges; only the (t, r) table differs, and the
    rigged table flips the scale-up decision — proof the priced
    target reads the searched 2-D table, not the 1-D decode table."""
    from flexflow_tpu.serve import Autoscaler
    from flexflow_tpu.utils.telemetry import MetricsRegistry

    # 1-D table says one replica carries 1000 tok/s (no scale-up at
    # demand 500); the rigged mesh table prices a replica at only
    # 100 tok/s (target 5 > 1 live -> scale up)
    decode_table = {1: 0.004}        # 4 lanes / 4ms = 1000 tok/s
    weak_cells = {(1, r): {"tokens_per_s": 100.0 * r}
                  for r in range(1, 9)}
    strong_cells = {(1, r): {"tokens_per_s": 1000.0 * r}
                    for r in range(1, 9)}

    def run(mesh_table):
        m = MetricsRegistry()
        m.set("serve_pool_replicas_live", 1.0)
        m.set("serve_pool_decode_tokens_per_s_window", 500.0)
        m.set("serve_pool_occupancy_mean", 0.5)
        m.set("serve_pool_queue_depth", 0.0)
        a = Autoscaler(m, min_replicas=1, max_replicas=8,
                       interval_s=1.0, up_patience=1,
                       decode_table=decode_table, tensor_parallel=1,
                       decode_lanes=4, mesh_table=mesh_table)
        assert a.target_replicas(500.0) == (5 if mesh_table
                                            is weak_cells else 1)
        return a.evaluate(t_now=10.0)

    assert run(None) is None                      # 1-D: no pressure
    assert run(strong_cells) is None              # 2-D, same price
    decision = run(weak_cells)                    # rigged: flips
    assert decision is not None and decision["direction"] == "up"
    assert "priced target" in decision["reason"]


def test_router_report_renders_mesh_placement():
    from flexflow_tpu.utils.profiling import router_report
    stats = {
        "policy": "affinity", "requests": [], "makespan_s": 1.0,
        "goodput_per_s": 5.0,
        "mesh_placement": {
            "tensor_parallel": 2, "replicas": 2,
            "tensor_axis_dims": [2], "data_axis_dims": [],
            "goodput_per_s": 40.0, "num_devices": 4,
            "table": {
                "2x2": {"goodput_per_s": 40.0, "tokens_per_s": 900.0,
                        "tpot_s": 0.002, "ttft_s": 0.01},
                "4x1": {"goodput_per_s": 20.0, "tokens_per_s": 700.0,
                        "tpot_s": 0.001, "ttft_s": 0.02}},
            "infeasible": [{"tensor": 1,
                            "reason": "per-device residency 10.0 MiB "
                                      "> HBM 5.0 MiB"}],
        }}
    text = router_report(stats)
    assert "2-D placement: t=2 x r=2" in text
    assert "priced goodput 40.0 req/s" in text
    assert "(t x r)=4x1 20.0 req/s" in text      # rejected WITH price
    assert "infeasible: t=1" in text
