"""Gradient accumulation: one optimizer step over K microbatches must
equal one step over the concatenated K x batch (losses are batch means,
so mean-of-means with equal sizes == big-batch mean; same for grads).
No reference analog — FlexFlow grows batch by adding GPUs
(multi_gpu_tests.sh GPUS*64); accumulation is the single-chip route."""

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer


def _mlp(bs, optimizer):
    cfg = FFConfig()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=optimizer,
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def _emb(bs, optimizer, sparse=True):
    cfg = FFConfig()
    cfg.batch_size = bs
    cfg.sparse_embedding_updates = sparse
    ff = FFModel(cfg)
    idx = ff.create_tensor((bs, 2), dtype=np.int32, name="input")
    t = ff.embedding(idx, 64, 8, aggr="sum")
    ff.dense(t, 4)
    ff.compile(optimizer=optimizer,
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


@pytest.mark.parametrize("opt", [lambda: SGDOptimizer(lr=0.1),
                                 lambda: AdamOptimizer(lr=0.01)])
def test_accum_equals_big_batch(opt):
    rng = np.random.RandomState(0)
    x = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)

    big = _mlp(32, opt())
    mb = _mlp(8, opt())
    w0 = big.get_weights("dense")
    for name in ("dense", "dense_1"):
        mb.set_weights(name, big.get_weights(name))

    m_big = big.train_batch({"input": x, "label": y})
    micro = [{"input": x[i * 8:(i + 1) * 8], "label": y[i * 8:(i + 1) * 8]}
             for i in range(4)]
    m_acc = mb.train_batch_accum(micro)

    np.testing.assert_allclose(float(m_big["loss"]), float(m_acc["loss"]),
                               rtol=1e-5)
    assert int(m_acc["count"]) == 32  # folded over the group
    for name in ("dense", "dense_1"):
        wa, wb = big.get_weights(name), mb.get_weights(name)
        for k in wa:
            np.testing.assert_allclose(wa[k], wb[k], rtol=1e-4,
                                       atol=1e-6)
    # step counter advanced ONCE
    assert int(mb.state.step) == 1


def test_accum_sparse_rows_concatenate():
    """Sparse tables: rows from different microbatches (with cross-
    microbatch duplicate indices) must scatter like one big batch."""
    rng = np.random.RandomState(1)
    idx = rng.randint(0, 8, (32, 2)).astype(np.int32)  # heavy dupes
    y = rng.randint(0, 4, 32).astype(np.int32)

    big = _emb(32, SGDOptimizer(lr=0.1))
    mb = _emb(8, SGDOptimizer(lr=0.1))
    emb = next(op.name for op in big.ops if op.op_type == "embedding")
    assert emb in big.executor._sparse_table_ops()
    for op in big.ops:
        if op.weight_specs():
            mb.set_weights(op.name, big.get_weights(op.name))

    big.train_batch({"input": idx, "label": y})
    mb.train_batch_accum(
        [{"input": idx[i * 8:(i + 1) * 8], "label": y[i * 8:(i + 1) * 8]}
         for i in range(4)])
    np.testing.assert_allclose(big.get_weights(emb)["kernel"],
                               mb.get_weights(emb)["kernel"],
                               rtol=1e-4, atol=1e-6)


def test_fit_grad_accum_steps():
    rng = np.random.RandomState(0)
    x = rng.randn(256, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff = _mlp(16, SGDOptimizer(lr=0.1))
    h = ff.fit({"input": x}, y, epochs=10, verbose=False,
               grad_accum_steps=4)
    # 256/16 = 16 microbatches -> 4 optimizer steps per epoch
    assert int(ff.state.step) == 10 * 4
    assert h[-1]["loss"] < h[0]["loss"]
    assert h[-1]["accuracy"] > 0.5


def test_fit_rejects_both_groupings():
    ff = _mlp(8, SGDOptimizer(lr=0.1))
    with pytest.raises(ValueError):
        ff.fit({"input": np.zeros((16, 16), np.float32)},
               np.zeros(16, np.int32), epochs=1, verbose=False,
               grad_accum_steps=2, steps_per_dispatch=2)


def test_fit_accum_tail_is_accumulated():
    """steps % K != 0: the tail must be ONE smaller accumulation group,
    not K demoted microbatch-sized updates (the grouping IS the
    optimization semantics here, unlike steps_per_dispatch)."""
    rng = np.random.RandomState(0)
    x = rng.randn(80, 16).astype(np.float32)   # 5 microbatches of 16
    y = rng.randint(0, 4, 80).astype(np.int32)
    ff = _mlp(16, SGDOptimizer(lr=0.1))
    ff.fit({"input": x}, y, epochs=1, verbose=False, grad_accum_steps=4)
    # 5 microbatches -> 2 optimizer steps (group of 4 + tail group of 1)
    assert int(ff.state.step) == 2


def test_fit_accum_checkpoint_resume(tmp_path):
    """Resume with grad_accum_steps: _host_step mirrors OPTIMIZER steps
    (one per accum group), so the restored rng stream replays exactly.
    Model includes dropout so rng divergence would show in the loss."""
    from flexflow_tpu import FFConfig, FFModel

    def build():
        cfg = FFConfig()
        cfg.batch_size = 16
        ff = FFModel(cfg)
        xx = ff.create_tensor((16, 16), name="input")
        t = ff.dense(xx, 32, activation="relu")
        t = ff.dropout(t, 0.2)
        ff.softmax(ff.dense(t, 4))
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    rng = np.random.RandomState(1)
    x = rng.randn(128, 16).astype(np.float32)
    y = rng.randint(0, 4, 128).astype(np.int32)
    ck = str(tmp_path / "ck")

    ref = build()
    h_ref = ref.fit({"input": x}, y, epochs=4, verbose=False,
                    grad_accum_steps=2)

    a = build()
    a.fit({"input": x}, y, epochs=2, verbose=False, grad_accum_steps=2,
          checkpoint_dir=ck)
    b = build()
    h_b = b.fit({"input": x}, y, epochs=4, verbose=False,
                grad_accum_steps=2, checkpoint_dir=ck)
    assert h_b[-1]["loss"] == pytest.approx(h_ref[-1]["loss"], abs=1e-6)
    np.testing.assert_allclose(ref.get_weights("dense")["kernel"],
                               b.get_weights("dense")["kernel"],
                               atol=1e-6)
