"""End-to-end request observability (the serving-tier tentpole;
docs/observability.md "Trace-id propagation" / "Per-request latency
attribution" / "SLO burn-rate monitor" / "Failure flight recorder").

Layered like the subsystem:
  * trace propagation — ONE trace id minted at the first tier rides
    the Request / ServeSession / PageShipment, so a routed (and
    disagg-routed) request's spans reconstruct one causally-linked,
    time-ordered timeline across router/replica/role tracks on the
    shared trace clock.
  * attribution — explain_request folds a request's spans into an
    additive queue/routing/prefill/transfer/decode/preempt_stall/
    retry/other breakdown summing to its measured latency (within 1%
    by gate, exactly by construction), with the pool-level aggregate
    fold landing in the exported registry.
  * SLO burn monitor — error-budget counters from the pool, windowed
    fast/slow burn rates, deterministic fire/clear transitions that
    replay at one seed, alert spans + gauges.
  * flight recorder — chaos-aborted runs leave a loadable,
    schema-valid post-mortem bundle (fault-abort / deadline-storm /
    explicit triggers), bounded, with the engine serving on.
  * endpoints — the aggregated ReplicaPool/DisaggCluster /metrics
    endpoint survives CONCURRENT scrapes during a live run and goes
    down cleanly on close().
"""

import glob
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.serve import ServeEngine
from flexflow_tpu.serve.disagg import DisaggCluster
from flexflow_tpu.serve.router import ReplicaPool
from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic
from flexflow_tpu.utils.slo import SLOBurnMonitor
from flexflow_tpu.utils.telemetry import (REQUEST_COMPONENTS,
                                          MetricsRegistry, Telemetry,
                                          attribute_request,
                                          fold_attribution,
                                          next_trace_id)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

VOCAB = 89


def _lm(**over):
    from flexflow_tpu.models.transformer import build_transformer_lm
    kw = dict(batch_size=1, kv_page_size=8, kv_num_pages=73,
              serve_max_seqs=8, serve_prefill_budget=48,
              serve_retry_backoff_s=0.0)
    kw.update(over)
    cfg = FFConfig(**kw)
    return build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


def _small_lm(**over):
    """Router-sized model: tiny pages force interesting schedules."""
    from flexflow_tpu.models.transformer import build_transformer_lm
    kw = dict(batch_size=1, kv_page_size=4, kv_num_pages=48,
              serve_max_seqs=4, serve_prefill_budget=8,
              serve_retry_backoff_s=0.0, serve_spec_decode=False)
    kw.update(over)
    cfg = FFConfig(**kw)
    return build_transformer_lm(cfg, vocab_size=VOCAB, max_seq_len=48,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


def _prompts(rng, n, lo=4, hi=28):
    return [list(rng.randint(1, VOCAB, size=rng.randint(lo, hi)))
            for _ in range(n)]


def _traffic(n=12, seed=0, **over):
    kw = dict(requests=n, seed=seed, tenants=3, prefix_tokens=8,
              tail_mean=4, output_mean=4, max_prompt=24,
              max_new_cap=6, vocab=VOCAB)
    kw.update(over)
    return make_traffic(TrafficSpec(**kw))


# ------------------------------------------------- trace propagation
def test_trace_ids_unique_and_minted_at_submit():
    a, b = next_trace_id(), next_trace_id()
    assert isinstance(a, int) and b > a
    tel = Telemetry()
    eng = ServeEngine(_lm(), telemetry=tel)
    eng.warmup()
    rng = np.random.RandomState(0)
    eng.generate(_prompts(rng, 4), 4)
    rows = eng.last_stats["requests"]
    tids = [r["trace_id"] for r in rows]
    assert len(set(tids)) == len(tids) and all(t > b for t in tids)


def test_engine_timeline_causally_linked():
    """Every lifecycle span of one request carries its trace id and
    the timeline is time-ordered on the shared clock."""
    tel = Telemetry()
    eng = ServeEngine(_lm(), telemetry=tel)
    eng.warmup()
    rng = np.random.RandomState(1)
    eng.generate(_prompts(rng, 6), 5)
    for row in eng.last_stats["requests"]:
        evs = tel.request_events(row["trace_id"])
        names = {e[2] for e in evs}
        assert "queue_wait" in names
        assert "prefill" in names
        # the queue_wait 'b' precedes every chunk span's start
        qb = min(e[3] for e in evs if e[0] == "b")
        chunk_starts = [e[3] for e in evs if e[0] == "X"]
        assert chunk_starts and all(qb <= t for t in chunk_starts)
        # no foreign rid ever shares the trace id
        rids = {e[6]["rid"] for e in evs if e[6] and "rid" in e[6]}
        assert rids == {row["rid"]}


def test_routed_request_one_timeline():
    """The acceptance gate's first clause: a routed request's router
    decision, queue wait and chunk spans land on ONE causally-linked
    timeline (one merged clock across the pool's replica tracks)."""
    tel = Telemetry()
    pool = ReplicaPool(_small_lm(), 2, policy="affinity",
                       telemetry=tel)
    pool.run(_traffic(10))
    recs = pool.last_stats["requests"]
    assert recs
    for rec in recs:
        evs = tel.request_events(rec["trace_id"])
        names = {e[2] for e in evs}
        assert {"routing", "route"} <= names
        assert "queue_wait" in names
        assert "prefill" in names or "decode" in names
        # routing happens before the first chunk span — one clock
        t_route = min(e[3] for e in evs if e[2] == "routing")
        chunk_ts = [e[3] for e in evs
                    if e[0] == "X" and e[2] != "routing"]
        assert chunk_ts and all(t_route <= t for t in chunk_ts)
        # spans recorded on the replica's OWN track group
        procs = {e[1][0] for e in evs if e[0] == "X"
                 and e[2] in ("prefill", "decode", "spec_decode")}
        assert procs == {f"replica{rec['replica']}"}
    pool.close()


def test_disagg_request_one_timeline_with_transfer():
    """A disagg-routed request: prefill-role spans, the kv_handoff
    transfer span (trace id crossed inside the PageShipment) and
    decode-role spans share one trace id; attribution shows a
    transfer component and sums to the cross-role latency."""
    tel = Telemetry()
    cl = DisaggCluster(_lm(), prefill_engines=1, decode_engines=1,
                       telemetry=tel)
    cl.warmup()
    rng = np.random.RandomState(2)
    prompts = [list(rng.randint(1, VOCAB, size=rng.randint(12, 30)))
               for _ in range(4)]
    out = cl.generate(prompts, 6)
    assert out == cl.generate_reference(prompts, 6)
    crossed = 0
    for i in range(len(prompts)):
        tid, pre, dec = cl._last_traces[i]
        evs = tel.request_events(tid)
        names = {e[2] for e in evs}
        assert "prefill" in names and "queue_wait" in names
        b = cl.explain_request(i)
        assert abs(sum(b["components"].values()) - b["latency_s"]) \
            <= 1e-9 + 0.01 * b["latency_s"]
        if b["crossed_link"]:
            crossed += 1
            assert "kv_handoff" in names and "decode" in names
            assert b["components"]["transfer"] > 0.0
    assert crossed > 0
    cl.close()


def test_shipment_carries_trace_id():
    tel = Telemetry()
    eng = ServeEngine(_lm(), telemetry=tel)
    eng.warmup()
    got = {}

    def grab(req):
        got["ship"] = eng.export_kv(req.slot, req.context,
                                    trace_id=req.trace_id)

    rng = np.random.RandomState(3)
    eng.generate([list(rng.randint(1, VOCAB, size=20))], 1,
                 on_finish=grab)
    ship = got["ship"]
    assert ship is not None
    assert ship.trace_id == eng.last_stats["requests"][0]["trace_id"]


# ------------------------------------------------- attribution
def test_attribute_request_partition_rules():
    """Unit check of the interval sweep: overlaps resolve by priority,
    async pairs close, retry carves out of compute, and the components
    sum to the window exactly."""
    evs = [
        ("b", ("p", "q"), "queue_wait", 0.0, 0.0, 7, {"trace": 7}),
        ("e", ("p", "q"), "queue_wait", 2.0, 0.0, 7, None),
        # prefill overlapping the queue tail: compute wins the overlap
        ("X", ("p", "s"), "prefill", 1.0, 1.5, None, {"trace": 7}),
        ("X", ("p", "s"), "decode", 3.0, 2.0, None, {"trace": 7}),
        # retry backoff inside the decode span (no trace arg)
        ("X", ("p", "e"), "retry_backoff", 3.5, 0.5, None, None),
        # a foreign request's span never contributes
        ("X", ("p", "s"), "decode", 3.0, 2.0, None, {"trace": 8}),
        ("X", ("p", "c"), "kv_handoff", 5.5, 0.25, None, {"trace": 7}),
    ]
    b = attribute_request(evs, 7, t_submit=0.0, t_finish=6.0)
    c = b["components"]
    assert abs(sum(c.values()) - 6.0) < 1e-12
    assert c["queue"] == pytest.approx(1.0)       # [0, 1): pre-prefill
    assert c["prefill"] == pytest.approx(1.5)     # [1, 2.5)
    assert c["decode"] == pytest.approx(1.5)      # [3, 5) minus retry
    assert c["retry"] == pytest.approx(0.5)       # [3.5, 4)
    assert c["transfer"] == pytest.approx(0.25)
    assert c["other"] == pytest.approx(6.0 - 1.0 - 1.5 - 1.5 - 0.5
                                       - 0.25)


def test_explain_request_sums_and_errors():
    tel = Telemetry()
    eng = ServeEngine(_lm(), telemetry=tel)
    eng.warmup()
    rng = np.random.RandomState(4)
    eng.generate(_prompts(rng, 6), 6)
    for row in eng.last_stats["requests"]:
        b = eng.explain_request(row["rid"])
        assert set(b["components"]) == set(REQUEST_COMPONENTS)
        lat = b["latency_s"]
        assert abs(sum(b["components"].values()) - lat) \
            <= 1e-9 + 0.01 * lat
        assert b["components"]["prefill"] > 0.0
        assert b["components"]["decode"] > 0.0
        assert b["attributed_s"] <= lat + 1e-9
    with pytest.raises(KeyError):
        eng.explain_request(999)
    eng_off = ServeEngine(_lm())
    with pytest.raises(RuntimeError):
        eng_off.explain_request(0)


def test_preempted_request_attributes_stall():
    """Preemption leaves a preempt_stall component (the requeue_wait
    async span), and the sum contract survives the adversarial path.
    Injected page pressure (the PR-6 chaos site) makes the eviction
    deterministic."""
    from flexflow_tpu.utils.faults import FaultInjector
    tel = Telemetry()
    inj = FaultInjector("serve.page_pressure:exhaust:0.9@4-8", seed=0)
    eng = ServeEngine(_lm(kv_num_pages=17, serve_max_seqs=4,
                          serve_prefill_budget=24,
                          serve_spec_decode=False),
                      telemetry=tel, faults=inj)
    eng.warmup()
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, 8, lo=10, hi=26)
    eng.generate(prompts, 8)
    st = eng.last_stats
    preempted = [r for r in st["requests"] if r["preemptions"] > 0]
    assert preempted, "tiny pool should force preemption"
    for row in preempted:
        b = eng.explain_request(row["rid"])
        assert b["components"]["preempt_stall"] > 0.0
        lat = b["latency_s"]
        assert abs(sum(b["components"].values()) - lat) \
            <= 1e-9 + 0.01 * lat


def test_fold_attribution_registry_series():
    m = MetricsRegistry()
    fold_attribution({"latency_s": 2.0,
                      "components": {"queue": 0.5, "decode": 1.0,
                                     "other": 0.5}}, m)
    fold_attribution({"latency_s": 2.0,
                      "components": {"queue": 1.0, "decode": 0.5,
                                     "other": 0.5}}, m)
    assert m.counter("serve_latency_attributed_requests_total") == 2
    assert m.counter("serve_latency_attribution_seconds_total",
                     component="queue") == pytest.approx(1.5)
    assert m.gauge("serve_latency_attribution_fraction",
                   component="decode") == pytest.approx(1.5 / 4.0)


def test_pool_run_folds_attribution_into_registry():
    tel = Telemetry()
    pool = ReplicaPool(_small_lm(), 2, telemetry=tel)
    st = pool.run(_traffic(8, seed=1))
    att = st["attribution"]
    assert set(att) == set(REQUEST_COMPONENTS)
    assert sum(att.values()) > 0
    n = pool.metrics.counter("serve_latency_attributed_requests_total")
    assert n > 0
    # per-request explain by stream id agrees with the records
    rec = st["requests"][0]
    b = pool.explain_request(rec["stream_id"])
    assert b["replica"] == rec["replica"]
    assert abs(sum(b["components"].values()) - b["latency_s"]) \
        <= 1e-9 + 0.01 * b["latency_s"]
    pool.close()


# ------------------------------------------------- SLO burn monitor
def _drive_monitor(mon, history):
    for t, total, viol in history:
        mon.registry.counter_set("serve_slo_requests_total", total)
        mon.registry.counter_set("serve_slo_violations_total", viol)
        mon.observe(t)


def test_burn_monitor_fires_and_clears_deterministically():
    def history():
        out, total, viol = [], 0, 0
        for t in range(1, 120):
            total += 10
            if 40 <= t < 60:
                viol += 5
            out.append((float(t), total, viol))
        return out

    runs = []
    for _ in range(2):
        mon = SLOBurnMonitor(MetricsRegistry(), error_budget=0.01,
                             fast_window_s=10, slow_window_s=40,
                             interval_s=1.0)
        _drive_monitor(mon, history())
        runs.append(list(mon.events))
    assert runs[0] == runs[1]
    states = [e["state"] for e in runs[0]]
    assert states == ["firing", "ok"]
    assert 40 <= runs[0][0]["t"] < 60


def test_burn_monitor_gauges_spans_and_validation():
    tel = Telemetry()
    mon = SLOBurnMonitor(tel.metrics, error_budget=0.01,
                         fast_window_s=5, slow_window_s=20,
                         interval_s=1.0, telemetry=tel)
    hist = [(float(t), 10 * t, 5 * t if t > 3 else 0)
            for t in range(1, 30)]
    _drive_monitor(mon, hist)
    m = tel.metrics
    assert m.gauge("slo_burn_rate", window="fast") > 0
    assert m.gauge("slo_budget_remaining", 1.0) < 1.0
    assert mon.state == "firing"
    mon.finish(29.0)
    names = [e[2] for e in tel.events]
    assert "slo_alert_fire" in names and "slo_alert" in names
    assert "slo_burn_rate" in m.to_prometheus()
    with pytest.raises(ValueError):
        SLOBurnMonitor(MetricsRegistry(), error_budget=0.0)
    with pytest.raises(ValueError):
        SLOBurnMonitor(MetricsRegistry(), fast_window_s=10,
                       slow_window_s=5)
    with pytest.raises(ValueError):
        SLOBurnMonitor(MetricsRegistry(), interval_s=0)


def test_pool_exports_slo_counters_and_alerts_replay():
    """The pool's error-budget counters + auto-armed monitor: alert
    transitions are part of last_stats and replay exactly at one
    seed across two fresh pools."""
    runs = []
    for _ in range(2):
        pool = ReplicaPool(_small_lm(), 2, telemetry=Telemetry())
        price = pool.price_probe(16)
        # impossible TPOT target: every completed request violates
        st = pool.run(_traffic(10, seed=3),
                      slo_ttft_s=price * 200, slo_tpot_s=price * 1e-3)
        tot = pool.metrics.counter("serve_slo_requests_total")
        viol = pool.metrics.counter("serve_slo_violations_total")
        assert tot > 0 and viol > 0
        assert pool.metrics.counter("serve_slo_violations_total",
                                    slo="tpot") > 0
        assert 0.0 <= st["slo_attainment_budget"] <= 1.0
        runs.append([(round(e["t"], 9), e["state"])
                     for e in st["slo_alerts"]])
        pool.close()
    assert runs[0] == runs[1]
    assert runs[0] and runs[0][0][1] == "firing"


def test_no_slo_monitor_flag_disarms():
    cfg_lm = _small_lm(slo_monitor=False)
    pool = ReplicaPool(cfg_lm, 1, telemetry=Telemetry())
    price = pool.price_probe(16)
    st = pool.run(_traffic(4, seed=4), slo_ttft_s=price * 200,
                  slo_tpot_s=price * 1e-3)
    assert st["slo_alerts"] == []
    # counters still export (the monitor is the consumer, not the
    # producer)
    assert pool.metrics.counter("serve_slo_requests_total") > 0
    # the call-level disarm spelling works too (and a telemetry-off
    # engine's fold returns zeros without touching the shared
    # disabled registry)
    st2 = pool.run(_traffic(4, seed=7), slo_ttft_s=price * 200,
                   slo_tpot_s=price * 1e-3, slo_monitor=False)
    assert st2["slo_alerts"] == []
    eng_off = ServeEngine(_lm())
    eng_off.warmup()
    eng_off.generate([[1, 2, 3]], 2)
    assert all(v == 0.0 for v in eng_off.fold_attribution().values())
    assert not eng_off.telemetry.metrics.counters
    pool.close()


# ------------------------------------------------- flight recorder
def test_fault_abort_leaves_loadable_bundle(tmp_path):
    """The acceptance gate's last clause: a fault-aborted run leaves a
    loadable post-mortem bundle — under the PR-6 chaos harness, with
    invariants intact and the engine serving on."""
    from postmortem import validate
    pmdir = str(tmp_path / "pm")
    eng = ServeEngine(_lm(postmortem_dir=pmdir,
                          fault_spec="serve.mixed:fatal@4"))
    assert eng.telemetry.enabled  # postmortem_dir implies telemetry
    eng.warmup()
    rng = np.random.RandomState(6)
    prompts = _prompts(rng, 6)
    with pytest.raises(Exception):
        eng.generate(prompts, 8)
    found = glob.glob(os.path.join(pmdir,
                                   "postmortem-fault_abort-*.json"))
    assert len(found) == 1
    with open(found[0]) as f:
        bundle = json.load(f)
    assert validate(bundle) == []
    assert bundle["reason"] == "fault_abort"
    assert bundle["detail"]["failed_inflight"] > 0
    assert len(bundle["events"]) > 0
    assert len(bundle["events"]) <= eng.postmortem_events
    assert "serve.mixed" in bundle["faults"]["fired"]
    # the engine recovered and the pool is clean
    eng.cache.check_invariants()
    out = eng.generate(prompts[:2], 4)
    assert all(len(o) == 4 for o in out)


def test_deadline_storm_and_rate_limit(tmp_path):
    pmdir = str(tmp_path / "pm")
    eng = ServeEngine(_lm(postmortem_dir=pmdir))
    eng.warmup()
    rng = np.random.RandomState(7)
    prompts = _prompts(rng, 6)
    eng.generate(prompts, 8, deadline_s=1e-4)
    storms = glob.glob(
        os.path.join(pmdir, "postmortem-deadline_storm-*.json"))
    assert len(storms) == 1
    # a second storm inside the rate-limit window dumps NOTHING new
    eng.generate(prompts, 8, deadline_s=1e-4)
    assert len(glob.glob(os.path.join(pmdir, "postmortem-*.json"))) \
        == 1
    # explicit dumps bypass the limiter
    p = eng.dump_postmortem(reason="manual")
    assert os.path.exists(p)


def test_rejection_triggers_bundle(tmp_path):
    """Rung-4 rejection (injected page-pool exhaustion hides the whole
    pool from planning — the PR-6 chaos site) black-boxes: the
    scheduler state in the bundle shows the rejection."""
    from flexflow_tpu.utils.faults import FaultInjector
    from postmortem import validate
    pmdir = str(tmp_path / "pm")
    inj = FaultInjector("serve.page_pressure:exhaust:1.0@1-50", seed=0)
    eng = ServeEngine(_lm(postmortem_dir=pmdir), faults=inj)
    eng.warmup()
    rng = np.random.RandomState(8)
    big = list(rng.randint(1, VOCAB, size=30))
    out = eng.generate([big], 2)
    assert out[0] == []  # rejected, not raised
    found = glob.glob(os.path.join(pmdir,
                                   "postmortem-rejection-*.json"))
    assert len(found) == 1
    with open(found[0]) as f:
        bundle = json.load(f)
    assert validate(bundle) == []
    assert bundle["scheduler"]["stats"]["rejected"] >= 1


def test_bundle_write_is_atomic(tmp_path):
    """No partially-written bundle is ever visible: the tmp file is
    gone and the artifact parses."""
    eng = ServeEngine(_lm(telemetry=True))
    eng.warmup()
    rng = np.random.RandomState(9)
    eng.generate(_prompts(rng, 2), 3)
    path = str(tmp_path / "bundle.json")
    got = eng.dump_postmortem(path=path, reason="manual")
    assert got == path and os.path.exists(path)
    assert not glob.glob(path + ".tmp.*")
    with open(path) as f:
        json.load(f)


# ------------------------------------------------- endpoints
def _scrape(port, path="/metrics"):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5)


def test_pool_endpoint_concurrent_scrape_during_run():
    """Satellite gate: the ReplicaPool's ONE aggregated /metrics
    endpoint serves concurrent scrapes while run() is folding into
    the registry from the serving thread — every scrape 200 + parses,
    and close() takes the endpoint down."""
    import re
    lm = _small_lm(metrics_port=0)
    pool = ReplicaPool(lm, 2, telemetry=Telemetry())
    assert pool.metrics_server is not None
    port = pool.metrics_server.port
    results = {"scrapes": 0, "errors": []}
    stop = threading.Event()
    line_re = re.compile(
        r'^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* '
        r'(counter|gauge|summary)'
        r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9eE.+-]+'
        r'|)$')

    def scraper():
        while not stop.is_set():
            try:
                with _scrape(port) as resp:
                    assert resp.status == 200
                    text = resp.read().decode()
                for line in text.splitlines():
                    assert line_re.match(line), line
                results["scrapes"] += 1
            except Exception as e:   # pragma: no cover - failure path
                results["errors"].append(repr(e))
                return

    threads = [threading.Thread(target=scraper, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        price = pool.price_probe(16)
        pool.run(_traffic(16, seed=5), slo_ttft_s=price * 50,
                 slo_tpot_s=price * 4)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not results["errors"], results["errors"]
    assert results["scrapes"] > 0
    # the aggregated page carries router + SLO + attribution series
    with _scrape(port) as resp:
        page = resp.read().decode()
    assert "router_requests_total" in page
    assert "serve_pool_slo_attainment" in page
    assert "serve_latency_attribution_seconds_total" in page
    with _scrape(port, "/healthz") as resp:
        assert resp.status == 200
    pool.close()
    with pytest.raises(Exception):
        _scrape(port, "/healthz")


def test_cluster_endpoint_scrape_and_close():
    """The DisaggCluster's aggregated endpoint: one port serves both
    roles' fold + handoff counters; close() is clean + idempotent."""
    lm = _lm(metrics_port=0)
    cl = DisaggCluster(lm, prefill_engines=1, decode_engines=1)
    assert cl.metrics_server is not None
    # role engines own NO endpoint — the cluster aggregates
    for _role, eng in cl.engines():
        assert eng.metrics_server is None
    cl.warmup()
    rng = np.random.RandomState(10)
    prompts = [list(rng.randint(1, VOCAB, size=rng.randint(12, 28)))
               for _ in range(3)]
    cl.generate(prompts, 5)
    port = cl.metrics_server.port
    with _scrape(port) as resp:
        page = resp.read().decode()
    assert 'serve_ttft_seconds{quantile="0.5",role="prefill"}' in page \
        or 'role="prefill"' in page
    assert "kv_transfer_bytes_total" in page
    cl.close()
    cl.close()   # idempotent
    with pytest.raises(Exception):
        _scrape(port, "/healthz")


# ------------------------------------------------- contracts / CLI
def test_telemetry_on_off_tokens_identical_with_traces():
    """The PR-10 contract holds through the tentpole: trace minting,
    attribution stash and flight-recorder arming change NO tokens and
    compile NOTHING."""
    lm = _lm()
    rng = np.random.RandomState(11)
    prompts = _prompts(rng, 6)
    eng_off = ServeEngine(lm)
    eng_off.warmup()
    out_off = eng_off.generate(prompts, 6)
    tel = Telemetry()
    eng_on = ServeEngine(lm, telemetry=tel)
    counts = eng_on.warmup()
    out_on = eng_on.generate(prompts, 6)
    assert out_on == out_off
    assert eng_on.compile_counts() == counts
    # explicit trace ids are observability-only
    out_tid = eng_on.generate(prompts, 6,
                              trace_ids=[next_trace_id()
                                         for _ in prompts])
    assert out_tid == out_off
    assert eng_on.compile_counts() == counts


def test_config_flags_and_validation():
    cfg = FFConfig(argv=["--postmortem-dir", "/tmp/pm",
                         "--postmortem-events", "512",
                         "--slo-error-budget", "0.05",
                         "--no-slo-monitor"])
    assert cfg.postmortem_dir == "/tmp/pm"
    assert cfg.postmortem_events == 512
    assert cfg.slo_error_budget == 0.05
    assert cfg.slo_monitor is False
    with pytest.raises(ValueError):
        FFConfig(postmortem_events=0)
    with pytest.raises(ValueError):
        FFConfig(slo_error_budget=0.0)
    with pytest.raises(ValueError):
        FFConfig(slo_error_budget=1.5)
    # trace_ids length validation
    eng = ServeEngine(_lm())
    eng.warmup()
    with pytest.raises(ValueError):
        eng.generate([[1, 2, 3]], 2, trace_ids=[1, 2])


def test_router_report_renders_slo_and_attribution():
    from flexflow_tpu.utils.profiling import router_report
    tel = Telemetry()
    pool = ReplicaPool(_small_lm(), 2, telemetry=tel)
    price = pool.price_probe(16)
    st = pool.run(_traffic(10, seed=6), slo_ttft_s=price * 200,
                  slo_tpot_s=price * 1e-3)
    text = router_report(st, metrics=pool.metrics)
    assert "slo budget: attainment" in text
    assert "burn fast=" in text
    assert "latency attribution:" in text
    if st["slo_alerts"]:
        assert "slo alert -> firing" in text
    pool.close()
