"""bench.py history/fallback logic (VERDICT r2 weak #1): the driver
artifact must never lose committed TPU measurements to a dead tunnel.
Pure-host tests — no backend, no subprocess ladder."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    """Import bench.py fresh with bench_all.json redirected to a temp
    copy (so merge tests can write without touching the repo)."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    committed = {
        "transformer": {"metric": "transformer_x", "value": 964.87,
                        "unit": "samples/s", "vs_baseline": 1.10,
                        "extra": {"platform": "tpu", "mfu": 0.33,
                                  "captured": "2026-07-29T20:43:26Z"}},
        "dlrm": {"metric": "dlrm_x", "value": 100.0, "unit": "samples/s",
                 "vs_baseline": 0.5,
                 "extra": {"platform": "cpu"}},  # non-TPU: no history
    }
    p = tmp_path / "bench_all.json"
    p.write_text(json.dumps(committed))
    mod._bench_all_path = lambda: str(p)
    return mod


def fresh_tpu(v=2000.0):
    return {"metric": "m", "value": v, "unit": "samples/s",
            "vs_baseline": 2.0, "extra": {"platform": "tpu",
                                          "captured": "now"}}


def fresh_cpu():
    return {"metric": "m_cpu_fallback", "value": 3.0, "unit": "samples/s",
            "vs_baseline": 0.01,
            "extra": {"platform": "cpu", "ms_per_step": 9.0,
                      "captured": "now"}}


def test_fresh_tpu_passes_through(bench):
    res = fresh_tpu()
    assert bench.finalize("transformer", res) is res


def test_cpu_fallback_replaced_by_stale_history(bench):
    out = bench.finalize("transformer", fresh_cpu())
    assert out["value"] == 964.87
    assert out["extra"]["stale"] is True
    # ADVICE r3: parsers that ignore `extra` must still see staleness
    assert out["stale"] is True
    assert out["extra"]["captured"] == "2026-07-29T20:43:26Z"
    assert out["extra"]["cpu_liveness"]["value"] == 3.0


def test_total_failure_emits_history_with_null_liveness(bench):
    out = bench.finalize("transformer", None)
    assert out["value"] == 964.87
    assert out["extra"]["cpu_liveness"] is None


def test_no_tpu_history_keeps_cpu_fallback(bench):
    res = fresh_cpu()
    assert bench.finalize("dlrm", res) is res
    assert bench.finalize("dlrm", None) is None


def test_merge_never_overwrites_tpu_with_cpu(bench):
    merged = bench.merge_bench_all(
        {"transformer": fresh_cpu(), "dlrm": fresh_cpu()})
    # committed TPU entry survives, stale-marked, liveness attached
    assert merged["transformer"]["value"] == 964.87
    assert merged["transformer"]["extra"]["stale"] is True
    # no TPU history for dlrm: the fresh CPU number lands as-is
    assert merged["dlrm"]["value"] == 3.0
    on_disk = json.loads(open(bench._bench_all_path()).read())
    assert on_disk["transformer"]["value"] == 964.87


def test_merge_fresh_tpu_overwrites(bench):
    merged = bench.merge_bench_all({"transformer": fresh_tpu(2000.0)})
    assert merged["transformer"]["value"] == 2000.0
    assert "stale" not in merged["transformer"]["extra"]
    assert "stale" not in merged["transformer"]


def test_history_untouched_by_finalize_mutation(bench):
    """finalize must deep-enough-copy: mutating its return value cannot
    corrupt the cached committed entry the next caller reads."""
    out = bench.finalize("transformer", None)
    out["extra"]["cpu_liveness"] = {"value": 123}
    out2 = bench.finalize("transformer", fresh_cpu())
    assert out2["extra"]["cpu_liveness"]["value"] == 3.0
