"""Delta simulation + persistent cost cache + parallel annealing chains
(the search-throughput PR): simulate_delta must agree with full
simulate() across random move walks on dissimilar model graphs, the
disk cost cache must round-trip and invalidate on fingerprint changes,
and searches must be reproducible under a fixed seed."""

import json
import random

import pytest

from flexflow_tpu import FFConfig, FFModel, Strategy, make_mesh
from flexflow_tpu.models.dlrm import build_dlrm
from flexflow_tpu.models.moe import build_moe_fused
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.parallel.pconfig import OpStrategy
from flexflow_tpu.search.cost_cache import CostCache, machine_fingerprint
from flexflow_tpu.search.mcmc import candidate_maps, optimize
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.utils.profiling import search_report


def _search_cfg(**kw):
    cfg = FFConfig(batch_size=kw.pop("batch_size", 16))
    cfg.enable_parameter_parallel = True
    cfg.enable_sequence_parallel = True
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _models():
    """Three dissimilar search graphs: transformer (attention + SP
    candidates), DLRM (stacked tables + table-axis candidates), MoE
    (expert-parallel candidates)."""
    t_cfg = _search_cfg(batch_size=8)
    transformer = build_transformer(
        t_cfg, batch_size=8, seq_len=32, hidden=64, num_heads=4,
        num_layers=2, ff_dim=128, num_classes=10)
    d_cfg = _search_cfg(batch_size=32)
    dlrm = build_dlrm(d_cfg, embedding_vocab_sizes=(256,) * 4,
                      embedding_dim=16, bot_mlp=(32, 16),
                      top_mlp=(32, 1), stacked_tables=True)
    m_cfg = _search_cfg(batch_size=16, enable_expert_parallel=True)
    moe = build_moe_fused(m_cfg, input_dim=64, num_experts=4,
                          expert_hidden=64)
    return [("transformer", transformer), ("dlrm", dlrm), ("moe", moe)]


def _random_walk_equivalence(ff, mesh, moves, seed):
    """Walk random rewrite/propagate moves; every move's delta cost must
    equal the full simulation of the same strategy (the delta replay is
    exact — tolerance here is float-identity-tight, not 'close')."""
    from flexflow_tpu.search.simulator import op_edges
    cfg = ff.config
    sim = Simulator(ff, mesh)
    cands = {op.name: candidate_maps(op, mesh, cfg, i)
             for i, op in enumerate(ff.ops)}
    searchable = [op for op in ff.ops if len(cands[op.name]) > 1]
    assert searchable, "graph has no strategy choices to test"
    _, edges = op_edges(ff)
    cur = Strategy()
    for op in ff.ops:
        cur.set(op.name, cur.for_op(op.name).copy())
    assert sim.delta_rebase(cur)
    rng = random.Random(seed)
    checked = 0
    for _ in range(moves):
        if edges and rng.random() < 0.25:  # propagate move
            src, dst = rng.choice(edges)
            m = dict(cur.for_op(src.name).axis_map)
            name = dst.name
        else:  # rewrite move
            op = rng.choice(searchable)
            m = dict(rng.choice(cands[op.name]))
            name = op.name
        cur.set(name, OpStrategy(m))
        tok = sim.simulate_delta(cur, (name,))
        full = sim.simulate(cur)
        if tok is None:  # structural move: template rebuilt, not spliced
            assert sim.delta_rebase(cur) or True
            continue
        assert tok.cost == pytest.approx(full, rel=1e-12, abs=1e-18), (
            name, m, tok.cost, full)
        checked += 1
    assert sim.stats["delta_sims"] == checked
    return checked


def test_delta_equals_full_across_models():
    """ISSUE acceptance: >= 200 random move sequences across the three
    graphs, delta makespan == full makespan."""
    total = 0
    meshes = {
        "transformer": make_mesh((2, 2, 2), ("data", "model", "seq")),
        "dlrm": make_mesh((2, 4), ("data", "model")),
        "moe": make_mesh((2, 2, 2), ("data", "model", "expert")),
    }
    seeds = {"transformer": 101, "dlrm": 202, "moe": 303}
    for name, ff in _models():
        total += _random_walk_equivalence(ff, meshes[name], moves=80,
                                          seed=seeds[name])
    assert total >= 200, total


def test_delta_reject_restores_template():
    """A rejected move must leave the template pricing the base strategy
    exactly (delta cost of the base == full cost of the base)."""
    _, ff = _models()[0]
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    sim = Simulator(ff, mesh)
    cands = {op.name: candidate_maps(op, mesh, ff.config, i)
             for i, op in enumerate(ff.ops)}
    searchable = [op for op in ff.ops if len(cands[op.name]) > 1]
    base = Strategy()
    for op in ff.ops:
        base.set(op.name, base.for_op(op.name).copy())
    base_cost = sim.simulate(base)
    assert sim.delta_rebase(base)
    rng = random.Random(7)
    for _ in range(20):
        op = rng.choice(searchable)
        nxt = base.copy()
        nxt.set(op.name, OpStrategy(dict(rng.choice(cands[op.name]))))
        tok = sim.simulate_delta(nxt, (op.name,))
        if tok is not None:
            sim.delta_reject(tok)
        again = sim.simulate_delta(base, (op.name,))
        assert again is not None and again.cost == base_cost


def test_delta_falls_back_on_structural_moves():
    """A rewrite that flips an op into pipeline expansion (layer->pipe)
    changes task-graph structure; simulate_delta must refuse rather
    than splice garbage."""
    cfg = _search_cfg(batch_size=16, enable_pipeline_parallel=True)
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 32), name="input")

    def block(sub, t):
        return sub.dense(t, 32, activation="relu", name="blk_ff")

    t = ff.pipeline_blocks(x, block, 4, num_microbatches=2,
                           name="pipeline")
    ff.softmax(ff.dense(t, 4, name="head"), name="sm")
    mesh = make_mesh((2, 2, 2), ("data", "model", "pipe"))
    sim = Simulator(ff, mesh)
    base = Strategy()
    for op in ff.ops:
        base.set(op.name, base.for_op(op.name).copy())
    assert sim.delta_rebase(base)
    nxt = base.copy()
    nxt.set("pipeline", OpStrategy({"sample": "data", "layer": "pipe"}))
    assert sim.simulate_delta(nxt, ("pipeline",)) is None
    assert sim.stats["delta_fallbacks"] == 1
    # and the fallback path (full simulate + rebase) still agrees
    full = sim.simulate(nxt)
    assert sim.delta_rebase(nxt)
    tok = sim.simulate_delta(nxt, ())
    assert tok is not None and tok.cost == full


# ---------------------------------------------------------- cost cache

def test_cost_cache_roundtrip_and_fingerprint(tmp_path):
    path = str(tmp_path / "costcache.json")
    _, ff = _models()[0]
    ff.config.cost_cache_file = path
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    sim1 = Simulator(ff, mesh)
    sim1.simulate(Strategy())
    assert sim1.stats["cost_computes"] > 0
    sim1.flush_cost_cache()
    data = json.load(open(path))
    # fingerprints carry the precision policy since the mixed-precision
    # cost model (cost_model COST_MODEL_VERSION 2) and the sync-overlap
    # config since the async-runtime one (v3): external callers pass
    # the simulator's resolved dtypes + overlap signature
    fp = machine_fingerprint(sim1.mm, mesh,
                             precision=sim1._precision(),
                             overlap=sim1.overlap_sig())
    assert fp == sim1._fingerprint
    assert fp in data and len(data[fp]) > 0

    # same machine state: a fresh simulator prices from disk, computing
    # nothing, and produces identical costs
    CostCache._open.pop(path, None)  # simulate a new process
    sim2 = Simulator(ff, mesh)
    c2 = sim2.simulate(Strategy())
    assert sim2.stats["cost_computes"] == 0
    assert sim2.stats["cost_disk_hits"] > 0
    assert c2 == sim1.simulate(Strategy())

    # machine-model change => new fingerprint => stale entries unusable
    # (costs must be re-computed, and they genuinely differ; same-
    # signature ops may still share the freshly computed entries)
    CostCache._open.pop(path, None)
    sim3 = Simulator(ff, mesh)
    sim3.mm.efficiency["elementwise"] *= 0.5
    sim3.invalidate()  # re-fingerprints + drops derived caches
    assert sim3._fingerprint != fp
    c3 = sim3.simulate(Strategy())
    assert sim3.stats["cost_computes"] > 0
    assert c3 != c2


def test_invalidate_clears_derived_caches():
    _, ff = _models()[0]
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    sim = Simulator(ff, mesh)
    base = Strategy()
    sim.simulate(base)
    assert sim.delta_rebase(base)
    assert sim._cache and sim._delta is not None
    sim.invalidate()
    assert not sim._cache and sim._delta is None
    assert sim.simulate(base) > 0  # still functional


# ------------------------------------------------------- determinism

def test_search_deterministic_under_seed():
    """Satellite: cfg.seed threads through every random draw via
    per-chain random.Random instances — same seed, same strategy."""
    _, ff = _models()[0]
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))

    def run(seed):
        s = optimize(ff, budget=300, mesh=mesh, seed=seed,
                     use_native=False, chains=2)
        return {op.name: dict(s.for_op(op.name).axis_map)
                for op in ff.ops}

    assert run(11) == run(11)
    # config seed is the default source when no seed is passed
    ff.config.seed = 23
    a = optimize(ff, budget=120, mesh=mesh, use_native=False, chains=2)
    b = optimize(ff, budget=120, mesh=mesh, use_native=False, chains=2)
    assert {o.name: dict(a.for_op(o.name).axis_map) for o in ff.ops} \
        == {o.name: dict(b.for_op(o.name).axis_map) for o in ff.ops}


def test_chains_quality_no_worse_than_dp():
    _, ff = _models()[1]  # dlrm
    mesh = make_mesh((2, 4), ("data", "model"))
    best = optimize(ff, budget=400, mesh=mesh, seed=0,
                    use_native=False, chains=3)
    sim = Simulator(ff, mesh)
    assert sim.simulate(best) <= sim.simulate(Strategy()) * (1 + 1e-9)
    # stats landed on the model and render into a report
    assert ff.search_stats["chains"] == 3
    assert ff.search_stats["delta_sims"] > 0
    assert ff.search_stats["drift_resyncs"] == 0
    report = search_report(ff.search_stats)
    assert "proposals/s" in report and "delta" in report


def test_search_report_renders_schedule_table_stats():
    from flexflow_tpu.search.simulator import _schedule_tables
    _schedule_tables(2, 1, 4)  # populate the lru
    _, ff = _models()[0]
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    sim = Simulator(ff, mesh)
    stats = sim.search_stats()
    assert stats["schedule_tables"]["currsize"] >= 1
    assert "schedule tables" in search_report(stats)
