"""Pipeline parallelism tests: PipelineBlocks with and without a pipe
mesh axis must produce identical results (GPipe reorders compute but not
math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy


def pp_strategy():
    return Strategy(default=OpStrategy({"sample": "data",
                                        "layer": "pipe"}))


def mlp_block(sub, t):
    h = sub.dense(t, 32, activation="relu", name="blk_ff1")
    h = sub.dense(h, 16, name="blk_ff2")
    return sub.add(h, t, name="blk_res")


def build(cfg, mesh=None, strategy=None, num_layers=4, num_microbatches=4):
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.pipeline_blocks(x, mlp_block, num_layers,
                           num_microbatches=num_microbatches)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh, strategy=strategy)
    return ff


def data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def test_stacked_blocks_train_single_device():
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build(cfg)
    # stacked weights have leading layer dim
    w = ff.state.params["pipeline"]["blk_ff1.kernel"]
    assert w.shape == (4, 16, 32), w.shape
    # per-layer slices must be independently initialized
    assert not np.allclose(np.asarray(w[0]), np.asarray(w[1]))
    x, y = data()
    hist = ff.fit({"input": x}, y, epochs=8, verbose=False)
    assert hist[-1]["accuracy"] > 0.8, hist[-1]


def test_pp_matches_unsharded():
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()

    ff1 = build(cfg)
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((2, 4), ("data", "pipe"))
    ff2 = build(cfg, mesh=mesh, strategy=pp_strategy())
    w = ff2.state.params["pipeline"]["blk_ff1.kernel"]
    assert w.sharding.spec == P("pipe",), w.sharding.spec
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1[-1], h2[-1])
    w1 = ff1.get_weights("pipeline")["blk_ff1.kernel"]
    w2 = ff2.get_weights("pipeline")["blk_ff1.kernel"]
    np.testing.assert_allclose(w1, w2, atol=2e-4)


def test_pp_microbatch_counts():
    """Different microbatch counts give the same result (pure schedule)."""
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data(64)
    mesh = make_mesh((1, 4), ("data", "pipe"))
    outs = []
    for m in (2, 8):
        ff = build(cfg, mesh=mesh, strategy=pp_strategy(),
                   num_microbatches=m)
        logits = ff.forward({"input": x})
        outs.append(np.asarray(logits))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)


def test_moe_inside_pipeline_keeps_aux_loss():
    """Review regression: MoE aux loss must survive inside PipelineBlocks."""
    cfg = FFConfig()
    cfg.batch_size = 32

    def moe_block(sub, t):
        h = sub.moe_ffn(t, num_experts=2, k=1, hidden_dim=32,
                        capacity_factor=2.0, name="blk_moe")
        return sub.add(h, t, name="blk_res")

    ff = FFModel(cfg)
    x = ff.create_tensor((32, 16), name="input")
    t = ff.pipeline_blocks(x, moe_block, 2)
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=[])
    xd, yd = data(32)
    ff.train_batch({"input": xd, "label": yd})
    assert len(ff.executor._last_aux_losses) == 1


def test_weightless_pipeline_block():
    """Review regression: blocks without weights must not crash scan."""
    cfg = FFConfig()
    cfg.batch_size = 16
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 8), name="input")
    t = ff.pipeline_blocks(x, lambda sub, h: sub.relu(h, name="blk_relu"), 3)
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    xd = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    yd = np.zeros(16, np.int32)
    m = ff.train_batch({"input": xd, "label": yd})
    assert np.isfinite(float(m["loss"]))


def test_remat_with_moe_no_tracer_leak():
    """Review regression: remat must skip aux-loss ops (tracer leak)."""
    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.remat = True
    ff = FFModel(cfg)
    x = ff.create_tensor((32, 16), name="input")
    t = ff.dense(x, 32, activation="relu")
    t = ff.moe_ffn(t, num_experts=2, k=1, hidden_dim=32)
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    xd, yd = data(32)
    m = ff.train_batch({"input": xd, "label": yd})
    assert np.isfinite(float(m["loss"]))
