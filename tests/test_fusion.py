"""Fusion pass (reference apply_fusion / FusedOp, model.cc:1472-1549):
same-strategy chains group; executor parity with fusion on/off; simulator
folds groups into single tasks."""

import numpy as np
import jax.numpy as jnp
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.core.fusion import boundary_ops, compute_fusion_groups
from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy, Strategy


def _mlp(cfg, mesh=None, strategy=None):
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((8, 16), name="input")
    h = ff.dense(x, 32, activation="relu", name="fc1")
    h = ff.dense(h, 32, activation="relu", name="fc2")
    h = ff.dense(h, 10, name="fc3")
    ff.softmax(h, name="sm")
    return ff


def test_chain_groups_into_one():
    ff = _mlp(FFConfig())
    groups = compute_fusion_groups(ff, Strategy())
    # uniform strategy: the whole chain fuses into one group
    assert groups == [["fc1", "fc2", "fc3", "sm"]]
    assert boundary_ops(groups) == {"sm"}


def test_strategy_change_breaks_group():
    strat = Strategy(op_strategies={"fc2": OpStrategy(
                         {"sample": "data", "channel_out": "model"})},
                     default=OpStrategy({"sample": "data"}))
    ff = _mlp(FFConfig())
    groups = compute_fusion_groups(ff, strat)
    assert ["fc2"] in groups  # fc2's TP strategy isolates it
    assert boundary_ops(groups) >= {"fc2", "sm"}


def test_branch_breaks_group():
    ff = FFModel(FFConfig())
    x = ff.create_tensor((8, 16), name="input")
    h = ff.dense(x, 16, name="a")       # two consumers -> group boundary
    b1 = ff.relu(h, name="b1")
    b2 = ff.tanh(h, name="b2")
    ff.add(b1, b2, name="c")
    groups = compute_fusion_groups(ff, Strategy())
    by_head = {g[-1]: g for g in groups}
    assert by_head["a"] == ["a"]
    assert by_head["c"] == ["c"]  # two in-graph producers


def test_executor_parity_with_fusion(rng):
    x = rng.randn(16, 16).astype(np.float32)
    y = rng.randint(0, 10, 16).astype(np.int32)
    losses = []
    for fuse in (False, True):
        cfg = FFConfig()
        cfg.batch_size = 16
        cfg.perform_fusion = fuse
        mesh = make_mesh((4, 2), ("data", "model"))
        strat = Strategy(default=OpStrategy({"sample": "data",
                                             "channel_out": "model"}))
        ff = _mlp(cfg, mesh=mesh, strategy=strat)
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=["accuracy"])
        m = ff.train_batch({"input": x, "label": y})
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


def test_simulator_fused_taskgraph():
    from flexflow_tpu.search.simulator import Simulator
    mesh = make_mesh((8,), ("data",))
    for fuse in (False, True):
        cfg = FFConfig()
        cfg.perform_fusion = fuse
        ff = _mlp(cfg, mesh=mesh)
        sim = Simulator(ff, mesh)
        t = sim.simulate(Strategy(default=OpStrategy({"sample": "data"})))
        assert t > 0 and np.isfinite(t)
        if fuse:
            t_fused = t
        else:
            t_unfused = t
    # fusing drops no compute, so times stay within the comm budget
    assert t_fused <= t_unfused * 1.01
