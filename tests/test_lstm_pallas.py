"""Pallas multi-timestep LSTM kernel vs the lax.scan reference
implementation (ops/rnn.py), run through the Pallas interpreter on CPU
— the same harness pattern as tests/test_flash_attention.py; compiled
behavior is validated on hardware by tests_tpu/test_lstm_tpu.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels import lstm_scan
from flexflow_tpu.kernels.lstm_scan import scan_reference


def make_inputs(T=6, B=8, H=128, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    xg = jnp.asarray(rng.randn(T, B, 4 * H) * 0.3, dtype)
    wh = jnp.asarray(rng.randn(H, 4 * H) * 0.1, dtype)
    h0 = jnp.zeros((B, H), dtype)
    c0 = jnp.zeros((B, H), dtype)
    return xg, wh, h0, c0


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-5),
                                        (jnp.bfloat16, 3e-2)])
def test_forward_matches_scan(dtype, atol):
    xg, wh, h0, c0 = make_inputs(dtype=dtype)
    ys = lstm_scan.lstm_sequence(xg, wh, h0, c0, interpret=True)
    want = scan_reference(xg, wh, h0, c0)
    np.testing.assert_allclose(np.asarray(ys, np.float32),
                               np.asarray(want, np.float32), atol=atol)


def test_gradients_match_scan():
    xg, wh, h0, c0 = make_inputs()

    def loss_k(xg, wh):
        ys = lstm_scan.lstm_sequence(xg, wh, h0, c0, interpret=True)
        return jnp.sum(ys.astype(jnp.float32) ** 2)

    def loss_s(xg, wh):
        ys = scan_reference(xg, wh, h0, c0)
        return jnp.sum(ys.astype(jnp.float32) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1))(xg, wh)
    gs = jax.grad(loss_s, argnums=(0, 1))(xg, wh)
    for a, b, name in zip(gk, gs, ("dxg", "dwh")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_initial_state_gradients():
    rng = np.random.RandomState(1)
    xg, wh, _, _ = make_inputs()
    h0 = jnp.asarray(rng.randn(8, 128) * 0.2, jnp.float32)
    c0 = jnp.asarray(rng.randn(8, 128) * 0.2, jnp.float32)

    def loss_k(h0, c0):
        return jnp.sum(lstm_scan.lstm_sequence(
            xg, wh, h0, c0, interpret=True) ** 2)

    def loss_s(h0, c0):
        return jnp.sum(scan_reference(xg, wh, h0, c0) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1))(h0, c0)
    gs = jax.grad(loss_s, argnums=(0, 1))(h0, c0)
    for a, b, name in zip(gk, gs, ("dh0", "dc0")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_shape_gating():
    xg, wh, h0, c0 = make_inputs(B=6)  # B % 8 != 0
    with pytest.raises(NotImplementedError, match="B%8"):
        lstm_scan.lstm_sequence(xg, wh, h0, c0, interpret=True)
