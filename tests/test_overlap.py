"""Async/overlap training runtime (core/overlap.py + the simulator's
bucket-granular sync pricing): bucketed backward-overlapped grad sync
must be BIT-identical to the monolithic path, the dispatch window must
drain at epoch end and on mid-epoch faults, delta simulation must stay
bit-exact with bucketed sync tasks enabled, and a bucket-config change
must provably invalidate the cost cache."""

import random

import numpy as np
import pytest

from flexflow_tpu import FFConfig, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.core.overlap import (DispatchWindow, grad_buckets,
                                       make_bucket_tagger)
from flexflow_tpu.models.dlrm import build_dlrm
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.utils import faults


def _transformer(bucket_mb, mesh=None, depth=2):
    cfg = FFConfig(batch_size=8)
    cfg.grad_bucket_mb = bucket_mb
    cfg.train_dispatch_depth = depth
    ff = build_transformer(cfg, batch_size=8, seq_len=16, hidden=32,
                           num_heads=4, num_layers=2, ff_dim=64,
                           num_classes=10)
    ff.compile(optimizer=SGDOptimizer(lr=0.05), mesh=mesh)
    return ff


def _t_batch(rng):
    return {"input": rng.randn(8, 16, 32).astype(np.float32),
            "label": rng.randint(0, 10, (8,)).astype(np.int32)}


def _dlrm(bucket_mb, mesh=None):
    cfg = FFConfig(batch_size=16)
    cfg.grad_bucket_mb = bucket_mb
    ff = build_dlrm(cfg, batch_size=16, embedding_vocab_sizes=(64,) * 4,
                    embedding_dim=8, bot_mlp=(16, 8), top_mlp=(16, 1),
                    stacked_tables=True)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="mean_squared_error", mesh=mesh)
    return ff


def _d_batch(rng):
    b = {"dense_features": rng.randn(16, 13).astype(np.float32),
         "label": (rng.rand(16, 1) > 0.5).astype(np.float32)}
    for i in range(4):
        b[f"sparse_{i}"] = rng.randint(0, 64, (16, 1)).astype(np.int32)
    return b


# --------------------------------------------------- bucket partition

def test_bucket_partition_walk_order_and_sizes():
    ff = _transformer(0.0)
    buckets = grad_buckets(ff, 0.01)  # 10 KiB -> several buckets
    assert len(buckets) > 1
    walk = [op.name for op in ff.ops]
    flat = [n for names, _ in buckets for n in names]
    assert flat == [n for n in walk if n in set(flat)]  # walk order
    limit = 0.01 * (1 << 20)
    for names, nbytes in buckets[:-1]:  # every bucket but the tail
        assert nbytes >= limit          # closed at the threshold
    assert grad_buckets(ff, 0.0) == []  # 0 = legacy monolithic


def test_bucket_partition_excludes_sparse_tables():
    ff = _dlrm(0.001)
    sparse = set(ff.executor._sparse_table_ops())
    assert sparse  # DLRM + plain SGD routes tables sparsely
    members = {n for names, _ in ff.executor._grad_buckets()
               for n in names}
    assert members and not (members & sparse)


# --------------------------------------------- bit-identical training

@pytest.mark.parametrize("builder,mk", [(_transformer, _t_batch),
                                        (_dlrm, _d_batch)])
def test_bucketed_sync_bit_identical_on_mesh(builder, mk, mesh8):
    """Tentpole contract: bucketed overlapped sync (many tiny buckets,
    real data-axis psums on the 8-device CPU mesh) trains bit-for-bit
    the trajectory of the monolithic path."""
    rng = np.random.RandomState(0)
    batches = [mk(rng) for _ in range(4)]

    def losses(bucket_mb):
        ff = builder(bucket_mb, mesh=mesh8)
        if bucket_mb:
            assert ff.executor.grad_bucket_info()["count"] > 1
        return np.array([np.asarray(ff.train_batch(b)["loss"])
                         for b in batches])

    a = losses(0.0)
    b = losses(0.002)
    assert np.array_equal(a, b), (a, b)


def test_bucketed_sync_bit_identical_multi_step_and_accum(mesh8):
    """The sync points ride inside lax.scan bodies too: grouped
    dispatch (train_batches) and grad accumulation stay bit-identical
    to their monolithic-sync counterparts."""
    rng = np.random.RandomState(1)
    batches = [_t_batch(rng) for _ in range(4)]

    def run(bucket_mb):
        ff = _transformer(bucket_mb, mesh=mesh8)
        m1 = ff.train_batches(batches[:2])
        m2 = ff.train_batch_accum(batches[2:])
        return (np.asarray(m1["loss"]), np.asarray(m2["loss"]))

    a1, a2 = run(0.0)
    b1, b2 = run(0.002)
    assert np.array_equal(a1, b1) and np.array_equal(a2, b2)


def test_donation_still_held_with_buckets(mesh8):
    """The custom_vjp sync points must not break buffer donation: the
    previous TrainState's buffers are consumed (deleted) by the step,
    not double-materialized alongside the new state."""
    ff = _transformer(0.002, mesh=mesh8)
    old_params = [v for d in ff.state.params.values() for v in d.values()]
    ff.train_batch(_t_batch(np.random.RandomState(0)))
    assert all(v.is_deleted() for v in old_params)


def test_tagger_identity_forward():
    """The sync-point op is an identity on values (forward)."""
    import jax.numpy as jnp
    tag = make_bucket_tagger([["a"], ["b"]])
    tree = {"a": {"w": jnp.arange(4.0)}, "b": {"w": jnp.ones((2, 2))}}
    out = tag(tree)
    for k in tree:
        assert np.array_equal(np.asarray(out[k]["w"]),
                              np.asarray(tree[k]["w"]))


# ------------------------------------------------- dispatch window

def test_dispatch_window_depths():
    fetched = []

    class _Probe:
        def __init__(self, x):
            self.x = x

    win = DispatchWindow(2)
    win.push(_Probe(1))
    assert win.pending() == 1          # newest stays in flight
    win.push(_Probe(2))
    assert win.pending() == 1          # oldest retrieved on push
    out = win.drain()
    assert [p.x for p in out] == [1, 2] and win.pending() == 0

    sync = DispatchWindow(1)
    sync.push(_Probe(3))
    assert sync.pending() == 0         # fully synchronous

    unbounded = DispatchWindow(0)
    for i in range(5):
        unbounded.push(_Probe(i))
    assert unbounded.pending() == 5    # legacy epoch-bulk
    assert [p.x for p in unbounded.drain()] == list(range(5))


def test_fit_window_drains_at_epoch_end():
    ff = _transformer(0.002, depth=2)
    rng = np.random.RandomState(0)
    x = {"input": rng.randn(48, 16, 32).astype(np.float32)}
    y = rng.randint(0, 10, (48,)).astype(np.int32)
    hist = ff.fit(x, y, epochs=2, verbose=False)
    assert len(hist) == 2
    st = ff.last_train_stats
    assert st["dispatches"] == 12 and st["pending_after_drain"] == 0
    assert st["max_in_flight"] >= 2
    from flexflow_tpu.utils.profiling import train_report
    rep = train_report(st)
    assert "window depth 2" in rep and "bucket" in rep


def test_fit_window_drains_on_mid_epoch_fault():
    """A fault at the train.dispatch site fires BEFORE the jitted call
    (donated state survives), the window drains in fit's finally, and
    the model keeps training afterwards."""
    ff = _transformer(0.002, depth=2)
    rng = np.random.RandomState(0)
    x = {"input": rng.randn(48, 16, 32).astype(np.float32)}
    y = rng.randint(0, 10, (48,)).astype(np.int32)
    with faults.active("train.dispatch:fatal@3") as inj:
        with pytest.raises(faults.InjectedFault):
            ff.fit(x, y, epochs=1, verbose=False)
        assert inj.fired["train.dispatch"]["fatal"] == 1
    st = ff.last_train_stats
    assert st["dispatches"] == 2          # third dispatch never ran
    assert st["in_flight_at_exit"] == 1   # one result was in flight
    assert st["pending_after_drain"] == 0
    # the fault fired pre-dispatch: state buffers are live, fit resumes
    hist = ff.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(hist[0]["loss"])


def test_fit_losses_identical_across_depths():
    rng = np.random.RandomState(0)
    x = {"input": rng.randn(48, 16, 32).astype(np.float32)}
    y = rng.randint(0, 10, (48,)).astype(np.int32)
    got = []
    for depth in (0, 1, 2):
        ff = _transformer(4.0, depth=depth)
        hist = ff.fit(x, y, epochs=2, verbose=False)
        got.append([h["loss"] for h in hist])
    assert got[0] == got[1] == got[2]


def test_prefetch_loader_stages_identically():
    """Worker-thread device staging must yield byte-identical batches
    in the same order as the synchronous path."""
    from flexflow_tpu.core.dataloader import DataLoaderSet
    rng = np.random.RandomState(3)
    data = {"x": rng.randn(64, 7), "label": rng.randint(0, 5, (64,))}
    order = rng.permutation(64)
    out = {}
    for prefetch in (False, True):
        ds = DataLoaderSet(data, 16, shuffle=False, prefetch=prefetch,
                           use_native=False,
                           dtypes={"x": np.float32})
        out[prefetch] = [{k: np.asarray(v) for k, v in b.items()}
                         for b in ds.iter_with_order(order)]
        ds.close()
    assert len(out[False]) == len(out[True]) == 4
    for a, b in zip(out[False], out[True]):
        for k in a:
            assert a[k].dtype == b[k].dtype
            assert np.array_equal(a[k], b[k])


# ------------------------------------------- simulator: bucket pricing

def _sim_model():
    cfg = FFConfig(batch_size=8)
    cfg.enable_parameter_parallel = True
    cfg.enable_sequence_parallel = True
    cfg.grad_bucket_mb = 0.01   # several buckets on this tiny model
    return build_transformer(cfg, batch_size=8, seq_len=32, hidden=64,
                             num_heads=4, num_layers=2, ff_dim=128,
                             num_classes=10)


def test_simulator_buckets_mirror_runtime_partition():
    from flexflow_tpu.search.simulator import Simulator
    ff = _sim_model()
    mesh = make_mesh((4, 2), ("data", "model"))
    sim = Simulator(ff, mesh)
    built = sim._build_graph(Strategy())
    want = [names for names, _ in grad_buckets(ff, 0.01)]
    assert [list(m) for m in built.bucket_members] == want
    assert len(built.bucket_tasks) == len(want) > 1
    # bucketed members' per-op sync slots are transparent; the bucket
    # tasks carry the combined all-reduce (nonzero under dp=4)
    assert all(t.duration > 0 for t in built.bucket_tasks)
    for names in want:
        for n in names:
            assert built.slots[n]["sync"].duration == 0.0


def test_simulator_fused_bucket_carries_whole_unit_payload():
    """Regression: a fused group's bucket task must carry the MERGED
    unit payload (its zeroed per-unit sync task covered every member),
    not just the last member's bytes."""
    from flexflow_tpu.parallel.pconfig import OpStrategy
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.search.cost_model import op_cost
    cfg = FFConfig(batch_size=16)
    cfg.perform_fusion = True
    cfg.grad_bucket_mb = 50.0   # one bucket
    from flexflow_tpu import FFModel
    ff = FFModel(cfg)
    x = ff.create_tensor((16, 64), name="input")
    h = ff.dense(x, 128, activation="relu", name="fc1")
    h = ff.dense(h, 128, activation="relu", name="fc2")
    ff.softmax(ff.dense(h, 8, name="out"), name="sm")
    mesh = make_mesh((8,), ("data",))
    sim = Simulator(ff, mesh)
    strat = Strategy(default=OpStrategy({"sample": "data"}))
    built = sim._build_graph(strat)
    assert len(built.bucket_tasks) == 1
    s = strat.for_op("fc1")
    want = sum(op_cost(op, s, mesh, sim.mm).sync_bytes
               for op in ff.ops if op.weight_specs())
    got = built.bucket_tasks[0].duration
    assert got == pytest.approx(sim._bucket_sync_cost(want), rel=1e-12)


def test_simulator_overlap_flag_and_bucket_change_makespan():
    """Bucketed overlapped sync must price FASTER than the serialized
    monolithic path (that is what the MCMC search now rewards), and
    --no-overlap-sync must serialize."""
    from flexflow_tpu.search.simulator import Simulator
    ff = _sim_model()
    mesh = make_mesh((4, 2), ("data", "model"))
    bucketed = Simulator(ff, mesh).simulate(Strategy())
    ff.config.search_overlap_backward_sync = False
    serial = Simulator(ff, mesh).simulate(Strategy())
    ff.config.search_overlap_backward_sync = True
    assert bucketed < serial


def test_delta_exact_with_bucketed_syncs():
    """ISSUE acceptance: simulate_delta stays bit-exact vs full
    simulation under the new bucket-granular task shape, across random
    rewrite/propagate walks, including reject/rollback."""
    from flexflow_tpu.search.mcmc import candidate_maps
    from flexflow_tpu.search.simulator import Simulator, op_edges
    ff = _sim_model()
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    sim = Simulator(ff, mesh)
    assert sim.bucket_mb > 0 and sim.overlap
    cands = {op.name: candidate_maps(op, mesh, ff.config, i)
             for i, op in enumerate(ff.ops)}
    searchable = [op for op in ff.ops if len(cands[op.name]) > 1]
    _, edges = op_edges(ff)
    cur = Strategy()
    for op in ff.ops:
        cur.set(op.name, cur.for_op(op.name).copy())
    base_cost = sim.simulate(cur)
    assert sim.delta_rebase(cur)
    assert sim._delta.bucket_slot            # buckets in the template
    rng = random.Random(42)
    checked = 0
    for i in range(120):
        if edges and rng.random() < 0.25:
            src, dst = rng.choice(edges)
            m = dict(cur.for_op(src.name).axis_map)
            name = dst.name
        else:
            op = rng.choice(searchable)
            m = dict(rng.choice(cands[op.name]))
            name = op.name
        nxt = cur.copy()
        nxt.set(name, type(cur.for_op(name))(m))
        tok = sim.simulate_delta(nxt, (name,))
        full = sim.simulate(nxt)
        if tok is None:
            assert sim.delta_rebase(nxt)
            cur = nxt
            continue
        assert tok.cost == pytest.approx(full, rel=1e-12, abs=1e-18)
        checked += 1
        if rng.random() < 0.5:      # reject: template must roll back
            sim.delta_reject(tok)
            again = sim.simulate_delta(cur, (name,))
            assert again is not None
            assert again.cost == pytest.approx(sim.simulate(cur),
                                               rel=1e-12, abs=1e-18)
        else:
            cur = nxt
    assert checked >= 60
    # and the walk ends where full simulation says it should
    assert sim.simulate(cur) > 0 and base_cost > 0


def test_bucket_config_change_invalidates_cost_cache(tmp_path):
    """ISSUE acceptance: a bucket-config change provably invalidates
    the cost cache (fingerprint miss), as does an overlap flip."""
    from flexflow_tpu.search.cost_cache import machine_fingerprint
    from flexflow_tpu.search.simulator import Simulator
    ff = _sim_model()
    mesh = make_mesh((4, 2), ("data", "model"))
    sim = Simulator(ff, mesh)
    fp_base = sim._fingerprint
    assert fp_base == machine_fingerprint(
        sim.mm, mesh, precision=sim._precision(),
        overlap=sim.overlap_sig())

    ff.config.grad_bucket_mb = 25.0
    sim.invalidate()
    fp_bucket = sim._fingerprint
    assert fp_bucket != fp_base

    ff.config.search_overlap_backward_sync = False
    sim.invalidate()
    fp_serial = sim._fingerprint
    assert fp_serial not in (fp_base, fp_bucket)
    ff.config.search_overlap_backward_sync = True
    ff.config.grad_bucket_mb = 0.01


def test_cli_flags():
    cfg = FFConfig(argv=["--grad-bucket-mb", "16",
                         "--train-dispatch-depth", "3",
                         "--no-overlap-sync"])
    assert cfg.grad_bucket_mb == 16.0
    assert cfg.train_dispatch_depth == 3
    assert cfg.search_overlap_backward_sync is False
    with pytest.raises(ValueError):
        FFConfig(grad_bucket_mb=-1.0)
    with pytest.raises(ValueError):
        FFConfig(train_dispatch_depth=-1)
