"""Multi-device parallelism tests on the forced 8-CPU-device mesh.

Verifies (a) DP/TP training runs and learns, (b) shardings are actually
applied to params/activations, (c) sharded results match single-device
results — the correctness property the reference could only test with 4
real GPUs (tests/multi_gpu_tests.sh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import OpStrategy, megatron_strategy


def build_mlp(cfg, mesh=None, strategy=None):
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((cfg.batch_size, 16), name="input")
    t = ff.dense(x, 64, activation="relu")
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    return ff


def data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def test_dp_training_learns(mesh8):
    cfg = FFConfig()
    cfg.batch_size = 64
    ff = build_mlp(cfg, mesh=mesh8)
    x, y = data()
    hist = ff.fit({"input": x}, y, epochs=10, verbose=False)
    assert hist[-1]["accuracy"] > 0.8


def test_dp_matches_single_device():
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()

    ff1 = build_mlp(cfg)
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((8,), ("data",))
    ff2 = build_mlp(cfg, mesh=mesh)
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1, h2)
    w1 = ff1.get_weights("dense")["kernel"]
    w2 = ff2.get_weights("dense")["kernel"]
    np.testing.assert_allclose(w1, w2, atol=2e-4)


def test_tp_shards_params(mesh_2d):
    cfg = FFConfig()
    cfg.batch_size = 32
    strat = megatron_strategy()
    ff = build_mlp(cfg, mesh=mesh_2d, strategy=strat)
    k = ff.state.params["dense"]["kernel"]  # (16, 64), channel_out sharded
    spec = k.sharding.spec
    assert spec == P(None, "model"), spec


def test_tp_matches_single_device():
    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()

    ff1 = build_mlp(cfg)
    h1 = ff1.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)

    mesh = make_mesh((4, 2), ("data", "model"))
    ff2 = build_mlp(cfg, mesh=mesh, strategy=megatron_strategy())
    h2 = ff2.fit({"input": x}, y, epochs=2, shuffle=False, verbose=False)
    assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-3, (h1, h2)


def test_strategy_file_roundtrip(tmp_path):
    strat = megatron_strategy()
    strat.set("dense_1", OpStrategy({"sample": "data"}))
    path = str(tmp_path / "strategy.json")
    strat.save(path)
    loaded = Strategy.load(path)
    assert loaded.default.axis_map == strat.default.axis_map
    assert loaded.for_op("dense_1").axis_map == {"sample": "data"}


def test_embedding_vocab_sharding(mesh_2d):
    """DLRM-style parameter parallelism: embedding table sharded over the
    model axis (reference: per-GPU table placement, SURVEY.md 2.3)."""
    cfg = FFConfig()
    cfg.batch_size = 32
    strat = Strategy(default=OpStrategy({"sample": "data",
                                         "vocab": "model"}))
    ff = FFModel(cfg, mesh=mesh_2d, strategy=strat)
    x = ff.create_tensor((32, 4), dtype=jnp.int32, name="input")
    t = ff.embedding(x, 128, 16, aggr="sum")
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    table = ff.state.params["embedding"]["kernel"]
    assert table.sharding.spec == P("model",), table.sharding.spec
    rng = np.random.RandomState(0)
    xs = rng.randint(0, 128, (128, 4)).astype(np.int32)
    ys = (xs.sum(axis=1) % 4).astype(np.int32)
    hist = ff.fit({"input": xs}, ys, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])


def test_multi_step_dispatch_on_sharded_mesh(mesh8):
    """train_batches (lax.scan over steps) composes with GSPMD: a DP
    mesh run through the grouped dispatch must match the sequential
    single-step stream on the same mesh."""
    import jax

    cfg = FFConfig()
    cfg.batch_size = 64
    x, y = data()
    batches = [{"input": x[i * 64:(i + 1) * 64],
                "label": y[i * 64:(i + 1) * 64]} for i in range(4)]

    seq = build_mlp(cfg, mesh=mesh8)
    want = [float(seq.train_batch(b)["loss"]) for b in batches]

    grp = build_mlp(cfg, mesh=mesh8)
    got = np.asarray(jax.device_get(grp.train_batches(batches)["loss"]),
                     np.float64)
    np.testing.assert_allclose(want, got, rtol=1e-5)
    for k, v in seq.get_weights("dense").items():
        np.testing.assert_allclose(
            v, grp.get_weights("dense")[k], rtol=1e-4, atol=1e-6)


def test_fit_feature_matrix_on_mesh(mesh8):
    """prefetch + steps_per_dispatch on a DP mesh must reproduce the
    plain fit exactly (same permutation stream, same updates) — the
    full composition a real run would use."""
    cfg = FFConfig()
    cfg.batch_size = 32
    x, y = data(n=256)

    def run(**kw):
        ff = build_mlp(cfg, mesh=mesh8)
        return ff, ff.fit({"input": x}, y, epochs=3, verbose=False, **kw)

    ff_a, h_a = run()
    ff_b, h_b = run(prefetch=True, steps_per_dispatch=4)
    for ma, mb in zip(h_a, h_b):
        np.testing.assert_allclose(ma["loss"], mb["loss"], rtol=1e-5)
    np.testing.assert_allclose(ff_a.get_weights("dense")["kernel"],
                               ff_b.get_weights("dense")["kernel"],
                               rtol=1e-4, atol=1e-6)


# ------------------------------------------------- ZeRO-1 slot sharding
def _zero_model(zero: bool):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, make_mesh
    mesh = make_mesh((8,), ("data",))
    cfg = FFConfig(batch_size=64)
    cfg.zero_optimizer_sharding = zero
    ff = FFModel(cfg, mesh=mesh)
    x = ff.create_tensor((64, 256), name="input")
    t = ff.dense(x, 256, activation="relu", name="fc0")
    ff.softmax(ff.dense(t, 10, name="head"))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh)
    return ff


def test_zero_shards_slots_and_matches_numerics():
    """--zero: Adam m/v slots shard over the data axis (1/dp memory per
    device), stay sharded across steps (the update's sharding
    constraint), and numerics match the unsharded run exactly."""
    rng = np.random.RandomState(0)
    batches = [{"input": rng.randn(64, 256).astype(np.float32),
                "label": rng.randint(0, 10, 64).astype(np.int32)}
               for _ in range(3)]
    ff_z = _zero_model(True)
    ff_r = _zero_model(False)
    for n in ("fc0", "head"):
        ff_r.set_weights(n, ff_z.get_weights(n))

    m = ff_z.state.opt_state["m"]["fc0"]["kernel"]
    assert "data" in jax.tree_util.tree_leaves(
        [list(m.sharding.spec)]), m.sharding
    assert m.addressable_shards[0].data.size == m.size // 8

    for b in batches:
        lz = float(ff_z.train_batch(b)["loss"])
        lr_ = float(ff_r.train_batch(b)["loss"])
        np.testing.assert_allclose(lz, lr_, rtol=1e-6)
    # still sharded after real steps (not silently re-replicated)
    m = ff_z.state.opt_state["m"]["fc0"]["kernel"]
    assert m.addressable_shards[0].data.size == m.size // 8
    np.testing.assert_allclose(ff_z.get_weights("fc0")["kernel"],
                               ff_r.get_weights("fc0")["kernel"],
                               rtol=1e-5, atol=1e-6)


def _staged_zero_model(zero: bool):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, make_mesh
    from flexflow_tpu.parallel.pconfig import (DEVICE_KEY, OpStrategy,
                                               Strategy)
    mesh = make_mesh((4, 2), ("data", "pipe"))
    cfg = FFConfig(batch_size=32)
    cfg.zero_optimizer_sharding = zero
    strat = Strategy(default=OpStrategy({}))
    strat.set("fc0", OpStrategy({DEVICE_KEY: (0,)}))
    strat.set("head", OpStrategy({DEVICE_KEY: (1,)}))
    ff = FFModel(cfg, mesh=mesh, strategy=strat)
    x = ff.create_tensor((32, 16), name="input")
    t = ff.dense(x, 16, activation="relu", name="fc0")
    t = ff.dense(t, 16, activation="relu", name="fc1")
    ff.softmax(ff.dense(t, 10, name="head"))
    ff.compile(optimizer=AdamOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh)
    return ff


def test_zero_under_staged_pipeline():
    """--zero composes with pipelining: slot rows land (pipe, data)-
    sharded — 1/(pp*dp) optimizer memory — stay there across steps,
    and numerics match the non-zero pipelined run exactly."""
    rng = np.random.RandomState(0)
    batches = [{"input": rng.randn(32, 16).astype(np.float32),
                "label": rng.randint(0, 10, 32).astype(np.int32)}
               for _ in range(3)]
    ff_z = _staged_zero_model(True)
    ff_r = _staged_zero_model(False)
    for n in ("fc0", "fc1", "head"):
        ff_r.set_weights(n, ff_z.get_weights(n))
    m = ff_z.state.opt_state["m"]["__stages__"]["float32"]
    assert m.addressable_shards[0].data.size == m.size // 8  # pp2*dp4
    for b in batches:
        lz = float(ff_z.train_batch(b)["loss"])
        lr_ = float(ff_r.train_batch(b)["loss"])
        np.testing.assert_allclose(lz, lr_, rtol=1e-6)
    m = ff_z.state.opt_state["m"]["__stages__"]["float32"]
    assert m.addressable_shards[0].data.size == m.size // 8
    np.testing.assert_allclose(ff_z.get_weights("fc0")["kernel"],
                               ff_r.get_weights("fc0")["kernel"],
                               rtol=1e-5, atol=1e-6)


def test_zero_warns_without_data_axis():
    """--zero on a pipe-only mesh cannot shard slots over data — it
    must say so, not silently no-op."""
    from flexflow_tpu import FFConfig, FFModel, make_mesh
    from flexflow_tpu.parallel.pconfig import (DEVICE_KEY, OpStrategy,
                                               Strategy)
    mesh = make_mesh((2,), ("pipe",))
    cfg = FFConfig(batch_size=32)
    cfg.zero_optimizer_sharding = True
    strat = Strategy(default=OpStrategy({}))
    strat.set("fc0", OpStrategy({DEVICE_KEY: (0,)}))
    strat.set("head", OpStrategy({DEVICE_KEY: (1,)}))
    ff = FFModel(cfg, mesh=mesh, strategy=strat)
    x = ff.create_tensor((32, 16), name="input")
    t = ff.dense(x, 16, activation="relu", name="fc0")
    ff.softmax(ff.dense(t, 10, name="head"))
    with pytest.warns(UserWarning, match="no effect on this mesh"):
        ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh)
