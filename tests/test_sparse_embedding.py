"""Sparse embedding updates (executor fast path).

Reference: src/ops/embedding.cu scatter-add backward + per-table update —
the dense-gradient alternative materializes a full (vocab, dim) gradient
every step, which at DLRM scale (8 x 1M x 64 tables) writes GBs of HBM
per step for a few thousand touched rows. The executor's sparse path
gathers the touched rows before differentiation and scatter-applies the
optimizer rule to those rows only; it must be numerically IDENTICAL to
the dense path for eligible optimizers (SGD, momentum=0, decay=0).
"""

import jax
import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer


def _build_embedding_model(sparse: bool, optimizer, distributed=False):
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.sparse_embedding_updates = sparse
    ff = FFModel(cfg)
    if distributed:
        ids = [ff.create_tensor((16, 2), dtype=np.int32, name=f"sparse_{i}")
               for i in range(4)]
        embs = ff.distributed_embedding(ids, num_entries=64, out_dim=8)
        t = ff.concat(embs, axis=1)
    else:
        idx = ff.create_tensor((16, 2), dtype=np.int32, name="input")
        t = ff.embedding(idx, num_entries=64, out_dim=8, aggr="sum")
    t = ff.dense(t, 4)
    ff.compile(optimizer=optimizer,
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def _batches(distributed=False, n=3):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        b = {"label": rng.randint(0, 4, (16,)).astype(np.int32)}
        if distributed:
            for i in range(4):
                b[f"sparse_{i}"] = rng.randint(0, 64, (16, 2)).astype(
                    np.int32)
        else:
            # duplicate indices ON PURPOSE: scatter-add must accumulate
            # them exactly like the dense gradient does
            idx = rng.randint(0, 8, (16, 2)).astype(np.int32)
            b["input"] = idx
        out.append(b)
    return out


@pytest.mark.parametrize("distributed", [False, True])
def test_sparse_matches_dense_sgd(distributed):
    batches = _batches(distributed)
    ff_sparse = _build_embedding_model(True, SGDOptimizer(lr=0.05),
                                       distributed)
    ff_dense = _build_embedding_model(False, SGDOptimizer(lr=0.05),
                                      distributed)
    emb_name = next(op.name for op in ff_sparse.ops
                    if "embedding" in op.op_type)
    assert emb_name in ff_sparse.executor._sparse_table_ops()
    assert not ff_dense.executor._sparse_table_ops()
    for b in batches:
        ls = float(ff_sparse.train_batch(b)["loss"])
        ld = float(ff_dense.train_batch(b)["loss"])
        np.testing.assert_allclose(ls, ld, rtol=1e-6)
    ws = ff_sparse.get_weights(emb_name)["kernel"]
    wd = ff_dense.get_weights(emb_name)["kernel"]
    np.testing.assert_allclose(ws, wd, rtol=1e-5, atol=1e-6)


def test_sparse_ineligible_optimizers_fall_back():
    # Adam needs per-row m/v state -> dense path
    ff = _build_embedding_model(True, AdamOptimizer(lr=0.01))
    assert not ff.executor._sparse_table_ops()
    # SGD with momentum carries velocity for every row -> dense path
    ff = _build_embedding_model(True, SGDOptimizer(lr=0.01, momentum=0.9))
    assert not ff.executor._sparse_table_ops()
    both = _batches()[0]
    ff.train_batch(both)  # and it still trains


def test_sparse_requires_input_indices():
    """An embedding fed by a COMPUTED tensor (not a graph input) cannot
    be pre-gathered and must take the dense path."""
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    idx = ff.create_tensor((8, 4), dtype=np.int32, name="input")
    r = ff.reshape(idx, (8, 2, 2))
    r = ff.reshape(r, (8, 4))
    t = ff.embedding(r, num_entries=32, out_dim=8, aggr="sum")
    ff.dense(t, 4)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    assert not ff.executor._sparse_table_ops()
    rng = np.random.RandomState(1)
    m = ff.train_batch({"input": rng.randint(0, 32, (8, 4)),
                        "label": rng.randint(0, 4, (8,))})
    assert np.isfinite(float(m["loss"]))


def test_sparse_with_multi_step_dispatch():
    """The scanned multi-step path must route sparse updates too."""
    batches = _batches(n=4)
    seq = _build_embedding_model(True, SGDOptimizer(lr=0.05))
    grouped = _build_embedding_model(True, SGDOptimizer(lr=0.05))
    seq_losses = [float(seq.train_batch(b)["loss"]) for b in batches]
    got = jax.device_get(grouped.train_batches(batches)["loss"])
    np.testing.assert_allclose(seq_losses, got, rtol=1e-6)


def _build_small_vocab(sparse, lazy, optimizer, distributed=False):
    """vocab=8 model where every batch TOUCHES EVERY ROW (with
    duplicates): lazy sparse semantics then coincide with dense exactly
    (no stale rows), so lazy-vs-dense equality is a full-rule check of
    the coalesced stateful row updates. distributed=True routes through
    the vmap-over-slots branch (stacked tables, per-table coalescing)."""
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.sparse_embedding_updates = sparse
    cfg.sparse_embedding_lazy = lazy
    ff = FFModel(cfg)
    if distributed:
        ids = [ff.create_tensor((16, 2), dtype=np.int32,
                                name=f"sparse_{i}") for i in range(2)]
        embs = ff.distributed_embedding(ids, num_entries=8, out_dim=8)
        t = ff.concat(embs, axis=1)
    else:
        idx = ff.create_tensor((16, 2), dtype=np.int32, name="input")
        t = ff.embedding(idx, num_entries=8, out_dim=8, aggr="sum")
    t = ff.dense(t, 4)
    ff.compile(optimizer=optimizer,
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def _all_rows_batches(n=4, distributed=False):
    rng = np.random.RandomState(7)
    out = []
    for _ in range(n):
        b = {"label": rng.randint(0, 4, (16,)).astype(np.int32)}
        keys = ["sparse_0", "sparse_1"] if distributed else ["input"]
        for k in keys:
            # 32 slots over vocab 8: every row appears, dupes guaranteed
            idx = np.concatenate([np.arange(8), rng.randint(0, 8, 24)])
            rng.shuffle(idx)
            b[k] = idx.reshape(16, 2).astype(np.int32)
        out.append(b)
    return out


@pytest.mark.parametrize("distributed", [False, True])
@pytest.mark.parametrize("opt", [
    lambda: AdamOptimizer(lr=0.01),
    lambda: SGDOptimizer(lr=0.05, momentum=0.9),
    lambda: SGDOptimizer(lr=0.05, momentum=0.9, nesterov=True),
])
def test_lazy_sparse_matches_dense_when_all_rows_touched(opt, distributed):
    batches = _all_rows_batches(distributed=distributed)
    ff_lazy = _build_small_vocab(True, True, opt(), distributed)
    ff_dense = _build_small_vocab(False, False, opt(), distributed)
    emb = next(o.name for o in ff_lazy.ops
               if "embedding" in o.op_type)
    assert emb in ff_lazy.executor._sparse_table_ops()
    for b in batches:
        ll = float(ff_lazy.train_batch(b)["loss"])
        ld = float(ff_dense.train_batch(b)["loss"])
        np.testing.assert_allclose(ll, ld, rtol=1e-5)
    np.testing.assert_allclose(
        ff_lazy.get_weights(emb)["kernel"],
        ff_dense.get_weights(emb)["kernel"], rtol=1e-4, atol=1e-6)


def test_lazy_requires_opt_in():
    ff = _build_small_vocab(True, False, AdamOptimizer(lr=0.01))
    assert not ff.executor._sparse_table_ops()


def test_sparse_flag_change_rebuilds_compiled_step():
    """Mutating the sparse flags (or swapping the optimizer) AFTER the
    first dispatch must drop the compiled steps and re-route: the
    executor's routing cache is keyed on the live flags and consulted on
    every dispatch, so it cannot diverge from cost_model.py's live
    config reads (ADVICE r2)."""
    ff = _build_embedding_model(True, SGDOptimizer(lr=0.05))
    emb = next(o.name for o in ff.ops if "embedding" in o.op_type)
    b = _batches(n=1)[0]
    ff.train_batch(b)
    assert emb in ff.executor._sparse_table_ops()
    step_before = ff.executor._train_step
    # flip the flag off: next dispatch must rebuild with dense routing
    ff.config.sparse_embedding_updates = False
    ff.train_batch(b)
    assert emb not in ff.executor._sparse_table_ops()
    assert ff.executor._train_step is not step_before
    # and back on: rebuilds again, sparse routing restored
    step_dense = ff.executor._train_step
    ff.config.sparse_embedding_updates = True
    ff.train_batch(b)
    assert emb in ff.executor._sparse_table_ops()
    assert ff.executor._train_step is not step_dense
