"""Generalized pipeline parallelism / executable whole-op device
placement (core/staged.py + parallel/graph_pipeline.py).

Reference FlexFlow executes arbitrary per-op device placement through
FFMapper::slice_task (mapper.cc:346-440); the TPU-native lowering runs
pinned ops as pipeline stages over a mesh `pipe` axis (shard_map +
lax.switch + ppermute), with per-stage flat-packed parameters so each
device physically holds only its stages' weights. These tests prove:
(a) numerics identical to unpipelined execution for pin-derived and
auto-cut stage maps, across schedules/microbatch counts/optimizers and
dp x pp meshes; (b) weight residency: packed rows shard one-per-device
over pipe; (c) get/set_weights round-trip through the packing;
(d) non-executable placements fall back to replication with a warning
instead of silently misplacing; (e) the GPipe bubble model's
stage-balance arithmetic.
"""

import warnings

import jax
import numpy as np
import pytest

from flexflow_tpu import (
    AdamOptimizer,
    FFConfig,
    FFModel,
    SGDOptimizer,
    Strategy,
    make_mesh,
)
from flexflow_tpu.core.staged import StagedExecutor
from flexflow_tpu.parallel.graph_pipeline import (
    assignment_from_pins,
    balanced_stages,
    bubble_fraction,
    peak_microbatches,
    simulate_step_scaling,
)
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy

BS = 16


def build_mlp(mesh=None, strategy=None, opt=None, cfg=None,
              metrics=("accuracy",)):
    cfg = cfg or FFConfig(batch_size=BS)
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((BS, 32), name="input")
    t = ff.dense(x, 64, activation="relu", name="fc1")
    t = ff.dense(t, 64, activation="relu", name="fc2")
    t = ff.dense(t, 48, activation="relu", name="fc3")
    t = ff.dense(t, 10, name="fc4")
    ff.softmax(t)
    ff.compile(optimizer=opt or SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=list(metrics), mesh=mesh, strategy=strategy)
    return ff


def build_residual(mesh=None, strategy=None, cfg=None):
    """Residual skip crossing a stage boundary: the wire must carry TWO
    tensors over the cut."""
    cfg = cfg or FFConfig(batch_size=BS)
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((BS, 32), name="input")
    t1 = ff.dense(x, 32, activation="relu", name="fc1")
    t2 = ff.dense(t1, 32, activation="relu", name="fc2")
    t3 = ff.add(t1, t2, name="skip")  # consumes stage-0 tensor at stage 1
    t4 = ff.dense(t3, 10, name="head")
    ff.softmax(t4)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh, strategy=strategy)
    return ff


def pin(mapping):
    s = Strategy(default=OpStrategy({}))
    for name, dev in mapping.items():
        s.set(name, OpStrategy({DEVICE_KEY: (dev,)}))
    return s


def batches(n=3, seed=0, feat=32):
    rng = np.random.RandomState(seed)
    return [{"input": rng.randn(BS, feat).astype(np.float32),
             "label": rng.randint(0, 10, BS).astype(np.int32)}
            for _ in range(n)]


def copy_weights(dst, src, names):
    for n in names:
        dst.set_weights(n, src.get_weights(n))


FCS = ("fc1", "fc2", "fc3", "fc4")


# ------------------------------------------------------------- parity
@pytest.mark.parametrize("mapping", [
    {"fc1": 0, "fc2": 0, "fc3": 1, "fc4": 1},       # balanced pins
    {"fc1": 2, "fc2": 5, "fc3": 5, "fc4": 7},        # arbitrary ids
    {"fc1": 0, "fc4": 1},                            # partial: inherit
])
def test_pinned_two_stage_matches_unpinned(mapping):
    n_stages = len(set(mapping.values()))
    mesh = make_mesh((n_stages,), ("pipe",))
    ref = build_mlp()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # pins must NOT warn-replicate
        ff = build_mlp(mesh=mesh, strategy=pin(mapping))
    assert isinstance(ff.executor, StagedExecutor)
    copy_weights(ff, ref, FCS)
    for b in batches():
        mp = ff.train_batch(b)
        mr = ref.train_batch(b)
        np.testing.assert_allclose(float(mp["loss"]), float(mr["loss"]),
                                   rtol=1e-5)
        assert float(mp["correct"]) == float(mr["correct"])
        assert float(mp["count"]) == float(mr["count"])


def test_three_stage_pins_and_eval():
    mesh = make_mesh((3,), ("pipe",))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh,
                   strategy=pin({"fc1": 0, "fc2": 1, "fc3": 1,
                                 "fc4": 2}))
    assert ff.executor.plan.num_stages == 3
    copy_weights(ff, ref, FCS)
    b = batches(1)[0]
    np.testing.assert_allclose(
        np.asarray(ref.forward(b)), np.asarray(ff.forward(b)),
        rtol=1e-5, atol=1e-6)
    ev_p = ff.evaluate({"input": b["input"]}, b["label"])
    ev_r = ref.evaluate({"input": b["input"]}, b["label"])
    np.testing.assert_allclose(ev_p["loss"], ev_r["loss"], rtol=1e-5)


def test_autocut_pipeline_stages_flag():
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_stages = 2
    cfg.pipeline_microbatches = 8
    mesh = make_mesh((2,), ("pipe",))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh, cfg=cfg)
    assert isinstance(ff.executor, StagedExecutor)
    copy_weights(ff, ref, FCS)
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_dp_times_pp_mesh():
    """data x pipe mesh: microbatches shard over data inside each
    stage."""
    mesh = make_mesh((2, 2), ("data", "pipe"))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    copy_weights(ff, ref, FCS)
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_adam_and_multistep_dispatch():
    mesh = make_mesh((2,), ("pipe",))
    ref = build_mlp(opt=AdamOptimizer(lr=0.01))
    ff = build_mlp(mesh=mesh, opt=AdamOptimizer(lr=0.01),
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    copy_weights(ff, ref, FCS)
    bs = batches(4)
    got = ff.train_batches(bs)       # K steps, ONE dispatch
    want = [ref.train_batch(b) for b in bs]
    np.testing.assert_allclose(
        np.asarray(got["loss"]),
        np.asarray([float(w["loss"]) for w in want]), rtol=1e-5)


def test_grad_accum_under_pipeline():
    mesh = make_mesh((2,), ("pipe",))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    copy_weights(ff, ref, FCS)
    micro = batches(2, seed=3)
    ff.train_batch_accum(micro)
    big = {"input": np.concatenate([m["input"] for m in micro]),
           "label": np.concatenate([m["label"] for m in micro])}
    # accum(K microbatches) == one 2*BS batch on the reference
    ref2 = build_mlp(cfg=FFConfig(batch_size=2 * BS))
    copy_weights(ref2, ref, FCS)
    ref2.train_batch(big)
    for n in FCS:
        a, b = ff.get_weights(n), ref2.get_weights(n)
        np.testing.assert_allclose(a["kernel"], b["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_residual_crossing_cut():
    mesh = make_mesh((2,), ("pipe",))
    ref = build_residual()
    ff = build_residual(mesh=mesh,
                        strategy=pin({"fc1": 0, "fc2": 0, "skip": 1,
                                      "head": 1}))
    # the cut carries BOTH fc1's and fc2's outputs
    assert len(ff.executor.plan.cuts[0]) == 2
    copy_weights(ff, ref, ("fc1", "fc2", "head"))
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


@pytest.mark.parametrize("m", [2, 4, 8])
def test_microbatch_count_invariance(m):
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_microbatches = m
    mesh = make_mesh((2,), ("pipe",))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh, cfg=cfg,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    copy_weights(ff, ref, FCS)
    b = batches(1)[0]
    np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                               float(ref.train_batch(b)["loss"]),
                               rtol=1e-5)


def build_moe(mesh=None, strategy=None, cfg=None):
    """Aux-loss op (MoE balancing) inside a pipeline stage: aux must
    average over microbatches AND data shards exactly like the
    unpipelined executor's per-sample mean."""
    cfg = cfg or FFConfig(batch_size=BS)
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((BS, 32), name="input")
    t = ff.dense(x, 32, activation="relu", name="fc1")
    t = ff.moe_ffn(t, num_experts=4, k=2, hidden_dim=64, name="moe")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh, strategy=strategy)
    return ff


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_aux_loss_parity_dp_pp(schedule):
    mesh = make_mesh((2, 2), ("data", "pipe"))
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_schedule = schedule
    ref = build_moe()
    ff = build_moe(mesh=mesh, cfg=cfg,
                   strategy=pin({"fc1": 0, "moe": 1, "head": 1}))
    assert isinstance(ff.executor, StagedExecutor)
    for n in ("fc1", "moe", "head"):
        ff.set_weights(n, ref.get_weights(n))
    for b in batches(2):
        lp = float(ff.train_batch(b)["loss"])
        lr_ = float(ref.train_batch(b)["loss"])
        # aux is a nonlinear per-shard statistic: pipelined execution
        # computes the mean of per-(microbatch, shard) values — close
        # to, not identical with, the full-batch value
        np.testing.assert_allclose(lp, lr_, rtol=0.05)
    for n in ("fc1", "head"):
        # per-microbatch expert routing/capacity differs from the
        # full-batch routing, so gradients drift a little beyond the
        # aux-mean approximation — bound the drift, not equality
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   atol=5e-3)


# --------------------------------------------------------------- 1F1B
def cfg_1f1b(m=4):
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = m
    return cfg


@pytest.mark.parametrize("m", [2, 4, 8])
def test_1f1b_matches_reference(m):
    mesh = make_mesh((2,), ("pipe",))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh, cfg=cfg_1f1b(m),
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    assert ff.executor.schedule == "1f1b"
    copy_weights(ff, ref, FCS)
    for b in batches(3):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)
    for n in FCS:
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_three_stages_dp_mesh():
    mesh = make_mesh((2, 3), ("data", "pipe"))
    ref = build_mlp()
    ff = build_mlp(mesh=mesh, cfg=cfg_1f1b(4),
                   strategy=pin({"fc1": 0, "fc2": 1, "fc3": 1,
                                 "fc4": 2}))
    copy_weights(ff, ref, FCS)
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)
    for n in FCS:
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_residual_crossing_cut():
    mesh = make_mesh((2,), ("pipe",))
    ref = build_residual()
    ff = build_residual(mesh=mesh, cfg=cfg_1f1b(4),
                        strategy=pin({"fc1": 0, "fc2": 0, "skip": 1,
                                      "head": 1}))
    copy_weights(ff, ref, ("fc1", "fc2", "head"))
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_1f1b_schedule_properties():
    from flexflow_tpu.parallel.graph_pipeline import (
        BWD, FWD, one_f_one_b_schedule)
    for S, M in [(2, 4), (3, 6), (4, 4), (2, 1), (4, 16)]:
        kind, mbi = one_f_one_b_schedule(S, M)
        for s in range(S):
            fwds = [int(mbi[t, s]) for t in range(kind.shape[0])
                    if kind[t, s] == FWD]
            bwds = [int(mbi[t, s]) for t in range(kind.shape[0])
                    if kind[t, s] == BWD]
            # every microbatch exactly once, in order, each direction
            assert fwds == list(range(M)), (S, M, s, fwds)
            assert bwds == list(range(M)), (S, M, s, bwds)
            # 1F1B memory bound: in-flight fwds never exceed the window
            live = 0
            peak = 0
            for t in range(kind.shape[0]):
                if kind[t, s] == FWD:
                    live += 1
                elif kind[t, s] == BWD:
                    live -= 1
                peak = max(peak, live)
            assert peak <= min(S - s if S - s > 0 else 1, M) or \
                peak <= min(S, M)


# ------------------------------------------------- residency / packing
def test_weight_residency_one_row_per_device():
    mesh = make_mesh((2,), ("pipe",))
    ff = build_mlp(mesh=mesh,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    packed = ff.state.params["__stages__"]["float32"]
    assert packed.shape[0] == 2
    for shard in packed.addressable_shards:
        assert shard.data.shape[0] == 1  # exactly one stage row per device
    # optimizer state mirrors the packing (momentum-free SGD: empty ok)
    ff2 = build_mlp(mesh=mesh, opt=AdamOptimizer(lr=0.01),
                    strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                  "fc4": 1}))
    m = ff2.state.opt_state["m"]["__stages__"]["float32"]
    for shard in m.addressable_shards:
        assert shard.data.shape[0] == 1


def test_get_set_weights_roundtrip():
    mesh = make_mesh((2,), ("pipe",))
    ff = build_mlp(mesh=mesh,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    w = ff.get_weights("fc3")
    assert w["kernel"].shape == (64, 48)
    newk = np.full((64, 48), 0.5, np.float32)
    ff.set_weights("fc3", {**w, "kernel": newk})
    got = ff.get_weights("fc3")
    np.testing.assert_array_equal(got["kernel"], newk)
    # neighbors untouched
    np.testing.assert_allclose(ff.get_weights("fc2")["kernel"].shape,
                               (64, 64))


# ------------------------------------------------------ failure modes
def test_backward_pin_falls_back_with_warning():
    """fc1 pinned to a LATER device than its consumer fc2: no forward
    pipeline exists; compile must warn and run replicated."""
    mesh = make_mesh((2,), ("pipe",))
    with pytest.warns(UserWarning, match="cannot execute as a pipeline"):
        ff = build_mlp(mesh=mesh,
                       strategy=pin({"fc1": 1, "fc2": 0, "fc3": 0,
                                     "fc4": 0}))
    assert not isinstance(ff.executor, StagedExecutor)
    float(ff.train_batch(batches(1)[0])["loss"])  # still trains


def test_multi_device_pin_falls_back_with_warning():
    mesh = make_mesh((2,), ("pipe",))
    s = Strategy(default=OpStrategy({}))
    s.set("fc2", OpStrategy({DEVICE_KEY: (0, 1)}))
    with pytest.warns(UserWarning, match="cannot execute as a pipeline"):
        ff = build_mlp(mesh=mesh, strategy=s)
    assert not isinstance(ff.executor, StagedExecutor)


def test_no_matching_mesh_axis_warns():
    mesh = make_mesh((4,), ("data",))  # no axis of size 2 besides data
    with pytest.warns(UserWarning, match="no non-data axis"):
        ff = build_mlp(mesh=mesh,
                       strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                     "fc4": 1}))
    assert not isinstance(ff.executor, StagedExecutor)


def build_cnn_bn(mesh=None, strategy=None, cfg=None):
    cfg = cfg or FFConfig(batch_size=BS)
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((BS, 3, 8, 8), name="input")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c0")
    t = ff.batch_norm(t, name="bn0")
    t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = ff.batch_norm(t, name="bn1")
    ff.softmax(ff.dense(ff.flat(t), 10, name="head"))
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh)
    return ff


CNN_BN_PINS = {"c0": 0, "bn0": 0, "c1": 1, "bn1": 1, "head": 1}


def cnn_batches(n=3, seed=0):
    rng = np.random.RandomState(seed)
    return [{"input": rng.randn(BS, 3, 8, 8).astype(np.float32),
             "label": rng.randint(0, 10, BS).astype(np.int32)}
            for _ in range(n)]


def test_bn_pipeline_matches_grad_accum():
    """Stateful ops (BatchNorm) execute under GPipe graph pipelines:
    each stage's forward tick advances its packed state row per
    microbatch IN ORDER, so the pipelined step equals unpipelined
    gradient accumulation over the same microbatches exactly — loss,
    weights, and running stats."""
    M = 4
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_microbatches = M
    mesh = make_mesh((2,), ("pipe",))
    ref = build_cnn_bn()
    ff = build_cnn_bn(mesh=mesh, cfg=cfg, strategy=pin(CNN_BN_PINS))
    assert isinstance(ff.executor, StagedExecutor)
    copy_weights(ff, ref, ("c0", "c1", "head"))
    mb = BS // M
    for b in cnn_batches(3):
        micro = [{k: v[i * mb:(i + 1) * mb] for k, v in b.items()}
                 for i in range(M)]
        mr = ref.train_batch_accum(micro)
        mp = ff.train_batch(b)
        np.testing.assert_allclose(float(mp["loss"]), float(mr["loss"]),
                                   rtol=1e-5)
    for n in ("bn0", "bn1"):
        sp = ff.get_states(n)
        sr = ref.get_states(n)
        for k in sr:
            np.testing.assert_allclose(sp[k], sr[k], rtol=1e-5,
                                       atol=1e-6)
    for n in ("c0", "c1", "head"):
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_bn_pipeline_eval_uses_running_stats():
    M = 4
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_microbatches = M
    mesh = make_mesh((2,), ("pipe",))
    ref = build_cnn_bn()
    ff = build_cnn_bn(mesh=mesh, cfg=cfg, strategy=pin(CNN_BN_PINS))
    copy_weights(ff, ref, ("c0", "c1", "head"))
    b = cnn_batches(1)[0]
    mb = BS // M
    ref.train_batch_accum([{k: v[i * mb:(i + 1) * mb]
                            for k, v in b.items()} for i in range(M)])
    ff.train_batch(b)
    ev_p = ff.evaluate({"input": b["input"]}, b["label"])
    ev_r = ref.evaluate({"input": b["input"]}, b["label"])
    np.testing.assert_allclose(ev_p["loss"], ev_r["loss"], rtol=1e-5)


def test_bn_pipeline_dp_pp_runs():
    """On a data x pipe mesh BN computes per-shard statistics (DDP
    BatchNorm semantics) with rows mean-reduced over the data axis —
    the step must run and stay finite/deterministic."""
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_microbatches = 4
    mesh = make_mesh((2, 2), ("data", "pipe"))
    ff = build_cnn_bn(mesh=mesh, cfg=cfg, strategy=pin(CNN_BN_PINS))
    b = cnn_batches(1)[0]
    m1 = float(ff.train_batch(b)["loss"])
    assert np.isfinite(m1)
    st = ff.get_states("bn0")
    assert all(np.isfinite(v).all() for v in st.values())


def test_bn_1f1b_matches_grad_accum():
    """1F1B + stateful: fwd ticks run outside the vjp and advance
    state rows in microbatch order; the bwd recompute reads state as a
    constant. Same exact grad-accum parity as the GPipe path."""
    M = 4
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = M
    mesh = make_mesh((2,), ("pipe",))
    ref = build_cnn_bn()
    ff = build_cnn_bn(mesh=mesh, cfg=cfg, strategy=pin(CNN_BN_PINS))
    assert ff.executor.schedule == "1f1b"
    copy_weights(ff, ref, ("c0", "c1", "head"))
    mb = BS // M
    for b in cnn_batches(3):
        micro = [{k: v[i * mb:(i + 1) * mb] for k, v in b.items()}
                 for i in range(M)]
        mr = ref.train_batch_accum(micro)
        mp = ff.train_batch(b)
        np.testing.assert_allclose(float(mp["loss"]), float(mr["loss"]),
                                   rtol=1e-5)
    for n in ("bn0", "bn1"):
        sp, sr = ff.get_states(n), ref.get_states(n)
        for k in sr:
            np.testing.assert_allclose(sp[k], sr[k], rtol=1e-5,
                                       atol=1e-6)
    for n in ("c0", "c1", "head"):
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_bn_interleaved_matches_grad_accum():
    """v>1 (interleaved 1F1B) with BN: auto-cut stages host state rows
    device-major, training matches unpipelined gradient accumulation
    EXACTLY (the documented claim — finiteness alone would miss a
    chunk-indexing or microbatch-ordering bug), and eval consumes the
    advanced stats through the forward-only schedule."""
    M = 4
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_stages = 2
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = M
    cfg.pipeline_virtual_stages = 2

    def build(c=None, mesh=None):
        ff = FFModel(c or FFConfig(batch_size=BS), mesh=mesh)
        x = ff.create_tensor((BS, 3, 8, 8), name="input")
        t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c0")
        t = ff.batch_norm(t, name="bn0")
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c1")
        t = ff.batch_norm(t, name="bn1")
        ff.softmax(ff.dense(ff.flat(t), 10, name="head"))
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh)
        return ff

    mesh = make_mesh((2,), ("pipe",))
    ref = build()
    ff = build(c=cfg, mesh=mesh)
    assert ff.executor.virtual_stages == 2
    copy_weights(ff, ref, ("c0", "c1", "head"))
    mb = BS // M
    for b in cnn_batches(2):
        micro = [{k: v[i * mb:(i + 1) * mb] for k, v in b.items()}
                 for i in range(M)]
        mr = ref.train_batch_accum(micro)
        mp = ff.train_batch(b)
        np.testing.assert_allclose(float(mp["loss"]), float(mr["loss"]),
                                   rtol=1e-5)
    for n in ("bn0", "bn1"):
        sp, sr = ff.get_states(n), ref.get_states(n)
        for k in sr:
            np.testing.assert_allclose(sp[k], sr[k], rtol=1e-5,
                                       atol=1e-6)
    b = cnn_batches(1)[0]
    ev_p = ff.evaluate({"input": b["input"]}, b["label"])
    ev_r = ref.evaluate({"input": b["input"]}, b["label"])
    np.testing.assert_allclose(ev_p["loss"], ev_r["loss"], rtol=1e-5)


def test_stateful_op_reading_state_rejected_under_1f1b():
    """An op whose TRAINING output reads state_in must be rejected
    under 1f1b (the recompute would see later-microbatch state)."""
    from flexflow_tpu.ops.conv import BatchNorm
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = 4
    mesh = make_mesh((2,), ("pipe",))
    ff = FFModel(cfg, mesh=mesh, strategy=pin(CNN_BN_PINS))
    x = ff.create_tensor((BS, 3, 8, 8), name="input")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c0")
    t = ff.batch_norm(t, name="bn0")
    t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = ff.batch_norm(t, name="bn1")
    ff.softmax(ff.dense(ff.flat(t), 10, name="head"))
    bn = next(o for o in ff.ops if o.name == "bn0")
    bn.training_output_reads_state = True  # simulate an EMA-style norm
    with pytest.raises(NotImplementedError, match="gpipe"):
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh)


# ------------------------------------------------------- stage planning
def test_balanced_stages_balance():
    ff = build_mlp()
    stage_of = balanced_stages(ff, 2)
    assert set(stage_of.values()) == {0, 1}
    # contiguity in topo order
    seq = [stage_of[op.name] for op in ff.ops]
    assert seq == sorted(seq)


def test_assignment_from_pins_inherits():
    ff = build_mlp()
    st = assignment_from_pins(ff, pin({"fc1": 3, "fc4": 9}))
    # devices 3 < 9 -> stages 0, 1; fc2/fc3/softmax inherit forward
    assert st["fc1"] == 0 and st["fc2"] == 0 and st["fc3"] == 0
    assert st["fc4"] == 1 and st["softmax"] == 1


# --------------------------------------------- simulator + search
def build_deep(feat=2048, bs=256, m=8):
    cfg = FFConfig(batch_size=bs)
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_microbatches = m
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, feat), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, feat, activation="relu", name=f"fc{i}")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    return ff


def test_simulator_prices_staged_strategy():
    """The event-loop simulator runs the staged expansion for pin
    strategies: bubble shrinks with more microbatches, tracking the
    analytic tick model in the compute-dominated regime (the measurable
    form of sim-vs-bubble agreement on a 1-core host; see
    tools/pipeline_bubble_ab.py for why wall-clock cannot show it)."""
    from flexflow_tpu.search.mcmc import staged_strategies
    from flexflow_tpu.search.simulator import Simulator
    mesh = make_mesh((2,), ("pipe",))
    times = {}
    for m in (1, 2, 4):
        ff = build_deep(m=m)
        staged = staged_strategies(ff, mesh, ff.config)
        assert len(staged) == 1
        times[m] = Simulator(ff, mesh).simulate(staged[0])
    from flexflow_tpu.parallel.graph_pipeline import simulate_step_scaling
    for m in (2, 4):
        sim_speedup = times[1] / times[m]
        analytic = simulate_step_scaling(2, 1, m)
        assert abs(sim_speedup - analytic) / analytic < 0.25, (
            m, sim_speedup, analytic)


def test_search_discovers_graph_pipeline():
    """MCMC offers whole-graph staged candidates (PP beyond
    pipeline_blocks) and picks one when stages beat replication on a
    pipe-only mesh."""
    from flexflow_tpu.search.mcmc import optimize
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.parallel.pconfig import OpStrategy as OS
    ff = build_deep()
    mesh = make_mesh((2,), ("pipe",))
    best = opt_best = optimize(ff, budget=60, mesh=mesh, seed=1)
    pins = [best.for_op(f"fc{i}").device_ids for i in range(8)]
    assert any(p is not None for p in pins), pins
    sim = Simulator(ff, mesh)
    assert sim.simulate(opt_best) < sim.simulate(
        Strategy(default=OS({})))


def test_bubble_model():
    assert bubble_fraction(2, 4) == pytest.approx(1 / 5)
    assert bubble_fraction(4, 12) == pytest.approx(3 / 15)
    # fixed batch, more microbatches -> smaller step time, ratio known
    assert simulate_step_scaling(2, 1, 8) == pytest.approx(2 / (9 / 8))
    assert peak_microbatches(4, 16, "gpipe") == 16
    assert peak_microbatches(4, 16, "1f1b") == 4


# -------------------------------------------- checkpoint / recovery
def test_checkpoint_resume_under_staged_pipeline(tmp_path):
    """fit(checkpoint_dir) resumes a staged (pipelined) run bit-exact:
    packed (S, L) params + optimizer rows round-trip through orbax and
    the resumed process rebuilds the same stage layout."""
    mesh = make_mesh((2,), ("pipe",))
    strat = pin({"fc1": 0, "fc2": 0, "fc3": 1, "fc4": 1})
    rng = np.random.RandomState(0)
    x = rng.randn(64, 32).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)
    ckdir = str(tmp_path / "ck")

    ff_ref = build_mlp(mesh=mesh, strategy=strat,
                       opt=AdamOptimizer(lr=0.01))
    h_ref = ff_ref.fit({"input": x}, y, epochs=4, verbose=False)

    ff_a = build_mlp(mesh=mesh, strategy=strat,
                     opt=AdamOptimizer(lr=0.01))
    ff_a.fit({"input": x}, y, epochs=2, verbose=False,
             checkpoint_dir=ckdir)
    ff_b = build_mlp(mesh=mesh, strategy=strat,
                     opt=AdamOptimizer(lr=0.01))
    h_b = ff_b.fit({"input": x}, y, epochs=4, verbose=False,
                   checkpoint_dir=ckdir)
    assert [m["epoch"] for m in h_b] == [2, 3]
    assert abs(h_b[-1]["loss"] - h_ref[-1]["loss"]) < 1e-6
    np.testing.assert_allclose(ff_b.get_weights("fc2")["kernel"],
                               ff_ref.get_weights("fc2")["kernel"],
                               atol=1e-6)


def test_remat_under_gpipe_matches():
    """--remat recomputes stage activations in backward (GPipe path):
    numerics identical to the stored-activation run."""
    mesh = make_mesh((2,), ("pipe",))
    cfg = FFConfig(batch_size=BS)
    cfg.remat = True
    ref = build_mlp()
    ff = build_mlp(mesh=mesh, cfg=cfg,
                   strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                 "fc4": 1}))
    assert isinstance(ff.executor, StagedExecutor)
    copy_weights(ff, ref, FCS)
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_native_engine_compares_staged_candidates():
    """use_native=True works with pipeline candidates: the native
    anneal runs the per-op space and the staged pipeline wins the
    final comparison when cheaper (staged cost is independent of the
    per-op assignment, so post-comparison == annealing through it)."""
    from flexflow_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    from flexflow_tpu.search.mcmc import optimize
    ff = build_deep()
    mesh = make_mesh((2,), ("pipe",))
    best = optimize(ff, budget=40, mesh=mesh, seed=1, use_native=True)
    pins = [best.for_op(f"fc{i}").device_ids for i in range(8)]
    assert any(p is not None for p in pins), pins


def test_sibling_pins_do_not_pipeline():
    """Pins on parallel branches (DLRM-style round-robin embeddings)
    express CONCURRENCY; lowering them to pipeline stages would
    serialize independent work. They must fall back (with the
    replication warning) instead."""
    import jax.numpy as jnp
    mesh = make_mesh((4,), ("pipe",))
    s = Strategy(default=OpStrategy({}))
    cfg = FFConfig(batch_size=8)
    ff = FFModel(cfg, mesh=mesh, strategy=s)
    ins = [ff.create_tensor((8, 2), dtype=jnp.int32, name=f"s{i}")
           for i in range(4)]
    embs = [ff.embedding(x, 64, 8, aggr="sum", name=f"e{i}")
            for i, x in enumerate(ins)]
    t = ff.concat(embs, axis=1)
    ff.softmax(ff.dense(t, 4, name="head"))
    for i in range(4):
        s.set(f"e{i}", OpStrategy({DEVICE_KEY: (i,)}))
    with pytest.warns(UserWarning, match="parallel siblings"):
        ff.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh, strategy=s)
    assert not isinstance(ff.executor, StagedExecutor)
    # and the simulator prices them as concurrent placed ops, not stages
    from flexflow_tpu.search.simulator import Simulator
    assert Simulator(ff, mesh)._staged_assignment(s) is None


# ------------------------------------------- interleaved (virtual) 1F1B
def build_deep_mlp(mesh=None, cfg=None):
    cfg = cfg or FFConfig(batch_size=BS)
    ff = FFModel(cfg, mesh=mesh)
    x = ff.create_tensor((BS, 32), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, 32, activation="relu", name=f"fc{i}")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=[], mesh=mesh)
    return ff


def cfg_interleaved(v, m=8, stages=2):
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_stages = stages
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = m
    cfg.pipeline_virtual_stages = v
    return cfg


DEEP = tuple(f"fc{i}" for i in range(8)) + ("head",)


@pytest.mark.parametrize("v", [2, 4])
def test_interleaved_matches_reference(v):
    mesh = make_mesh((2,), ("pipe",))
    ref = build_deep_mlp()
    ff = build_deep_mlp(mesh=mesh, cfg=cfg_interleaved(v))
    assert ff.executor.virtual_stages == v
    assert ff.executor.plan.num_stages == 2 * v
    copy_weights(ff, ref, DEEP)
    for b in batches(3):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)
    for n in DEEP:  # device-major packed rows round-trip
        np.testing.assert_allclose(ff.get_weights(n)["kernel"],
                                   ref.get_weights(n)["kernel"],
                                   rtol=1e-4, atol=1e-6)


def test_interleaved_dp_pp_mesh():
    mesh = make_mesh((2, 2), ("data", "pipe"))
    ref = build_deep_mlp()
    ff = build_deep_mlp(mesh=mesh, cfg=cfg_interleaved(2))
    copy_weights(ff, ref, DEEP)
    for b in batches(2):
        np.testing.assert_allclose(float(ff.train_batch(b)["loss"]),
                                   float(ref.train_batch(b)["loss"]),
                                   rtol=1e-5)


def test_interleaved_packed_residency():
    """Device-major rows: device d owns rows [d*v, (d+1)*v) = its
    round-robin stages {d, d+D, ...}."""
    mesh = make_mesh((2,), ("pipe",))
    ff = build_deep_mlp(mesh=mesh, cfg=cfg_interleaved(2))
    packed = ff.state.params["__stages__"]["float32"]
    assert packed.shape[0] == 4  # v * n_dev rows
    for shard in packed.addressable_shards:
        assert shard.data.shape[0] == 2  # v rows per device


def test_interleaved_schedule_reduces_bubble():
    """The wave-policy interleaved schedule must beat plain 1F1B's
    bubble at v=4 across representative (devices, microbatches)."""
    from flexflow_tpu.parallel.graph_pipeline import (
        interleaved_schedule, schedule_bubble)
    for D, M in [(2, 8), (4, 8), (4, 16), (8, 32)]:
        b1 = schedule_bubble(interleaved_schedule(D, 1, M)[0])
        b4 = schedule_bubble(interleaved_schedule(D, 4, M)[0])
        assert b4 < b1, (D, M, b1, b4)


def test_interleaved_requires_1f1b():
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_virtual_stages = 2
    with pytest.raises(ValueError, match="1f1b"):
        cfg.validate()


@pytest.mark.parametrize("v", [2, 4])
def test_interleaved_eval_matches_reference(v):
    """Forward/evaluate under virtual stages: the forward-only
    interleaved schedule must reproduce unpipelined numerics."""
    mesh = make_mesh((2,), ("pipe",))
    ref = build_deep_mlp()
    ff = build_deep_mlp(mesh=mesh, cfg=cfg_interleaved(v))
    copy_weights(ff, ref, DEEP)
    b = batches(1)[0]
    np.testing.assert_allclose(
        np.asarray(ref.forward(b)), np.asarray(ff.forward(b)),
        rtol=1e-5, atol=1e-6)
    ev_p = ff.evaluate({"input": b["input"]}, b["label"])
    ev_r = ref.evaluate({"input": b["input"]}, b["label"])
    np.testing.assert_allclose(ev_p["loss"], ev_r["loss"], rtol=1e-5)


def test_interleaved_eval_dp_pp_mesh():
    mesh = make_mesh((2, 2), ("data", "pipe"))
    ref = build_deep_mlp()
    ff = build_deep_mlp(mesh=mesh, cfg=cfg_interleaved(2))
    copy_weights(ff, ref, DEEP)
    b = batches(1)[0]
    ev_p = ff.evaluate({"input": b["input"]}, b["label"])
    ev_r = ref.evaluate({"input": b["input"]}, b["label"])
    np.testing.assert_allclose(ev_p["loss"], ev_r["loss"], rtol=1e-5)


def test_forward_schedule_properties():
    from flexflow_tpu.parallel.graph_pipeline import (
        FWD, IDLE, interleaved_forward_schedule)
    for D, v, M in [(2, 1, 4), (2, 2, 8), (4, 4, 8), (2, 4, 16)]:
        kind, mbi, sidx, depth = interleaved_forward_schedule(D, v, M)
        S = D * v
        # every (stage, microbatch) forward runs exactly once
        runs = {}
        for t in range(kind.shape[0]):
            for d in range(D):
                if kind[t, d] == FWD:
                    s, m = int(sidx[t, d]), int(mbi[t, d])
                    assert s % D == d  # round-robin residency
                    assert (s, m) not in runs
                    runs[(s, m)] = t
        assert len(runs) == S * M
        for (s, m), t in runs.items():  # dataflow order
            if s > 0:
                assert runs[(s - 1, m)] < t
        assert 1 <= depth <= M


def _price_staged(hidden, v):
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.parallel.pconfig import Strategy as Strat, \
        OpStrategy as OS
    mesh = make_mesh((2,), ("pipe",))
    cfg = FFConfig(batch_size=256)
    cfg.pipeline_stages = 2
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = 8
    cfg.pipeline_virtual_stages = v
    ff = FFModel(cfg)
    x = ff.create_tensor((256, hidden), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, hidden, activation="relu", name=f"fc{i}")
    ff.softmax(ff.dense(t, 10, name="head"))
    sim = Simulator(ff, mesh)
    stage_of = sim._staged_assignment(Strat(default=OS({})))
    assert stage_of is not None
    assert max(stage_of.values()) + 1 == 2 * v  # compile's actual cut
    return sim._simulate_staged(Strat(default=OS({})), stage_of)[0]


def test_simulator_prices_virtual_stages():
    """1F1B strategies price from the executor's ACTUAL schedule tables
    (tick-lockstep: per-tick max unit cost + both wire ppermutes), so
    the simulator sees BOTH sides of interleaving: v=4 cuts the bubble
    (wins when per-tick compute dominates, hidden=4096) but pays ~v x
    more wire hops (loses on the hop-heavy hidden=2048 model). A
    bubble-only model would always prefer v>1."""
    assert _price_staged(4096, 4) < _price_staged(4096, 1)
    assert _price_staged(2048, 4) > _price_staged(2048, 1)


def _search_model(hidden):
    from flexflow_tpu.search.mcmc import optimize
    cfg = FFConfig(batch_size=256)
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = 8
    ff = FFModel(cfg)
    x = ff.create_tensor((256, hidden), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, hidden, activation="relu", name=f"fc{i}")
    ff.softmax(ff.dense(t, 10, name="head"))
    mesh = make_mesh((2,), ("pipe",))
    strat = optimize(ff, budget=30, mesh=mesh, seed=0)
    return cfg, strat


def test_search_discovers_virtual_stages():
    """The search explores the v dimension (auto-cut interleaved
    candidates priced through the tick tables) and records a win on
    the config knobs compile's auto-cut lowering reads — the v
    analog of optimize_with_mesh returning a mesh."""
    cfg, strat = _search_model(4096)  # compute-dominated: v>1 wins
    assert cfg.pipeline_virtual_stages in (2, 4)
    assert cfg.pipeline_stages == 2
    assert not any(strat.for_op(f"fc{i}").device_ids for i in range(8))


def test_search_keeps_v1_when_hops_dominate():
    cfg, _ = _search_model(512)  # hop-heavy: interleaving must lose
    assert cfg.pipeline_virtual_stages == 1


def test_interleaved_win_roundtrips_strategy_file(tmp_path):
    """--export after a v>1 search win must carry the pipeline block;
    --import replays it: a fresh model + config compiles into the same
    interleaved executor without re-searching."""
    from flexflow_tpu.search.mcmc import optimize
    path = str(tmp_path / "strat.json")
    cfg = FFConfig(batch_size=64)
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = 8

    def build(c, mesh=None):
        ff = FFModel(c, mesh=mesh)
        x = ff.create_tensor((64, 4096), name="input")
        t = x
        for i in range(8):
            t = ff.dense(t, 4096, activation="relu", name=f"fc{i}")
        ff.softmax(ff.dense(t, 10, name="head"))
        return ff

    mesh = make_mesh((2,), ("pipe",))
    ff = build(cfg)
    strat = optimize(ff, budget=20, mesh=mesh, seed=0)
    assert strat.pipeline and strat.pipeline["virtual_stages"] > 1
    strat.save(path)

    cfg2 = FFConfig(batch_size=64)  # fresh config: no pipeline knobs
    cfg2.import_strategy_file = path
    ff2 = build(cfg2, mesh=mesh)
    ff2.compile(optimizer=SGDOptimizer(lr=0.01),
                loss_type="sparse_categorical_crossentropy",
                metrics=[], mesh=mesh)
    assert isinstance(ff2.executor, StagedExecutor)
    assert ff2.executor.virtual_stages == strat.pipeline["virtual_stages"]
    b = batches(1, feat=4096)[0]
    assert np.isfinite(float(ff2.train_batch(b)["loss"]))


def test_interleaved_not_blocked_by_stale_viability_cache():
    """viable() verdicts depend on v (the pipe axis carries S/v
    devices), so the simulator's balanced cache must key on (S, v): a
    None cached for (S=4, v=1) on a pipe=2 mesh must not block the
    genuinely viable (D=2, v=2) candidate that also cuts 4 stages."""
    from flexflow_tpu.search.mcmc import _interleaved_upgrade
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.parallel.pconfig import Strategy as Strat, \
        OpStrategy as OS
    cfg = FFConfig(batch_size=256)
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_microbatches = 8
    cfg.pipeline_stages = 4  # no size-4 axis on this mesh
    ff = FFModel(cfg)
    x = ff.create_tensor((256, 4096), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, 4096, activation="relu", name=f"fc{i}")
    ff.softmax(ff.dense(t, 10, name="head"))
    mesh = make_mesh((2,), ("pipe",))
    sim = Simulator(ff, mesh)
    pin_free = Strat(default=OS({}))
    # primes the (S=4, v=1) cache entry with None
    assert sim._staged_assignment(pin_free) is None
    best = _interleaved_upgrade(ff, cfg, mesh, sim, pin_free)
    assert cfg.pipeline_virtual_stages in (2, 4)
    assert cfg.pipeline_stages == 2
    assert not any(best.for_op(f"fc{i}").device_ids for i in range(8))


def test_virtual_stages_warn_when_unused():
    """--pipeline-virtual-stages outside the auto-cut path must warn,
    not silently run non-interleaved."""
    mesh = make_mesh((2,), ("pipe",))
    cfg = FFConfig(batch_size=BS)
    cfg.pipeline_schedule = "1f1b"
    cfg.pipeline_virtual_stages = 2  # but stages come from PINS
    with pytest.warns(UserWarning, match="NOT applied"):
        ff = build_mlp(mesh=mesh, cfg=cfg,
                       strategy=pin({"fc1": 0, "fc2": 0, "fc3": 1,
                                     "fc4": 1}))
    assert ff.executor.virtual_stages == 1
