"""REAL multi-controller SPMD: two OS processes, one global mesh.

The reference never tests multi-node without a cluster (SURVEY §4:
"distributed coverage is single-node multi-GPU"). Here the launcher's
jax.distributed bootstrap (python -m flexflow_tpu --coordinator ...,
the mpirun-analog of python/flexflow.py) runs two CPU processes with 2
local devices each; a DP model trains over the 4-device global mesh
with each process feeding ITS shard of the global batch, and the loss
must match a single-process run on the concatenated batch exactly.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN = """
import sys
import numpy as np
import jax
import jax.numpy as jnp
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh

pid = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4 and jax.local_device_count() == 2

cfg = FFConfig()
cfg.batch_size = 16  # GLOBAL batch
ff = FFModel(cfg, mesh=make_mesh((4,), ("data",)))
x = ff.create_tensor((16, 32), name="input")
ff.softmax(ff.dense(ff.dense(x, 64, activation="relu", name="d1"), 4,
                    name="d2"))
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type="sparse_categorical_crossentropy", metrics=[])

rng = np.random.RandomState(0)  # same stream on both processes
xg = rng.randn(16, 32).astype(np.float32)
yg = rng.randint(0, 4, 16).astype(np.int32)
lo, hi = pid * 8, (pid + 1) * 8  # this process's shard of the batch
for step in range(3):
    m = ff.train_batch({"input": xg[lo:hi], "label": yg[lo:hi]})
    print(f"RESULT proc={pid} step={step} loss={float(m['loss']):.8f}",
          flush=True)

# grouped dispatch (scan of 2 steps) through the multi-process stacked
# placement path
ms = ff.train_batches([
    {"input": xg[lo:hi], "label": yg[lo:hi]},
    {"input": xg[lo:hi], "label": yg[lo:hi]},
])
print(f"RESULT proc={pid} step=group loss={float(ms['loss'][-1]):.8f}",
      flush=True)

# fit() epoch: each process feeds its local dataset half
h = ff.fit({"input": xg[lo:hi]}, yg[lo:hi], epochs=1, verbose=False,
           batch_size=8)
print(f"RESULT proc={pid} step=fit loss={h[-1]['loss']:.8f}", flush=True)
"""


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_dp_matches_single_process(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(TRAIN)
    port = free_port()
    procs = []
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flexflow_tpu",
             "--cpu-devices", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             str(script)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-3000:]

    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                losses.setdefault(int(parts["proc"]), []).append(
                    float(parts["loss"]))
    # 3 single steps + grouped dispatch + fit epoch
    assert len(losses[0]) == len(losses[1]) == 5, outs
    # the jitted step is GLOBAL: both controllers must see the same
    # losses across every path (single, grouped, fit)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-7)
    losses = {p: v[:3] for p, v in losses.items()}  # single-proc ref

    # single-process run on the full batch reproduces it exactly
    import jax

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh

    cfg = FFConfig()
    cfg.batch_size = 16
    ff = FFModel(cfg, mesh=make_mesh((4,), ("data",)))
    x = ff.create_tensor((16, 32), name="input")
    ff.softmax(ff.dense(ff.dense(x, 64, activation="relu", name="d1"),
                        4, name="d2"))
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    xg = rng.randn(16, 32).astype(np.float32)
    yg = rng.randint(0, 4, 16).astype(np.int32)
    ref = [float(ff.train_batch({"input": xg, "label": yg})["loss"])
           for _ in range(3)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-5)


PLACED = """
import sys
import numpy as np
import jax
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, Strategy, make_mesh
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy

pid = jax.process_index()
assert jax.device_count() == 4

ids = (2, 0, 3, 1, 2, 0, 3, 1)  # scattered over the GLOBAL device space
strat = Strategy(default=OpStrategy({"sample": "data"}))
strat.set("tables", OpStrategy({DEVICE_KEY: ids}))
cfg = FFConfig()
cfg.batch_size = 16
mesh = make_mesh((4,), ("data",))
ff = FFModel(cfg, mesh=mesh, strategy=strat)
ins = [ff.create_tensor((16, 2), dtype=np.int32, name=f"sparse_{i}")
       for i in range(8)]
embs = ff.distributed_embedding(ins, 64, 8, name="tables")
t = ff.concat(embs, axis=1)
ff.softmax(ff.dense(t, 4, name="dense"))
ff.compile(optimizer=SGDOptimizer(lr=0.05),
           loss_type="sparse_categorical_crossentropy", metrics=[],
           mesh=mesh, strategy=strat)
op = next(o for o in ff.ops if o.op_type == "distributed_embedding")
assert op.placement == ids, op.placement

rng = np.random.RandomState(0)
xg = {f"sparse_{i}": rng.randint(0, 64, (16, 2)).astype(np.int32)
      for i in range(8)}
yg = rng.randint(0, 4, 16).astype(np.int32)
lo, hi = pid * 8, (pid + 1) * 8
for step in range(2):
    b = {k: v[lo:hi] for k, v in xg.items()}
    b["label"] = yg[lo:hi]
    m = ff.train_batch(b)
    print(f"RESULT proc={pid} step={step} loss={float(m['loss']):.8f}",
          flush=True)

# checkpoint from BOTH controllers (orbax multihost), restore, continue
ckpt = sys.argv[1] if len(sys.argv) > 1 else None
if ckpt:
    from flexflow_tpu.core.checkpoint import restore_model, save_model
    save_model(ff, ckpt)

    def shard_sum(arr):
        # a PLACED table kernel spans both processes' devices; only the
        # local shards are fetchable — their sum is a per-process
        # consistency fingerprint
        return float(sum(np.asarray(s.data).sum()
                         for s in arr.addressable_shards))

    before = float(np.asarray(ff.get_weights("dense")["kernel"]).sum())
    # the PLACED tables are the feature under test: their restored
    # bytes must match too, not just the dense head's
    before_tab = shard_sum(ff.state.params["tables"]["kernel"])
    # get_weights all-gathers cross-process-sharded weights (collective:
    # both controllers call it together); full-table sum must agree
    full_tab = ff.get_weights("tables")["kernel"]
    assert full_tab.shape[0] == 8  # every slot, incl. remote ones
    print(f"RESULT proc={pid} step=gather loss={full_tab.sum():.8f}",
          flush=True)
    # fresh model, same graph/strategy, restore into it
    cfg2 = FFConfig()
    cfg2.batch_size = 16
    ff2 = FFModel(cfg2, mesh=mesh, strategy=strat)
    ins2 = [ff2.create_tensor((16, 2), dtype=np.int32, name=f"sparse_{i}")
            for i in range(8)]
    embs2 = ff2.distributed_embedding(ins2, 64, 8, name="tables")
    t2 = ff2.concat(embs2, axis=1)
    ff2.softmax(ff2.dense(t2, 4, name="dense"))
    ff2.compile(optimizer=SGDOptimizer(lr=0.05),
                loss_type="sparse_categorical_crossentropy", metrics=[],
                mesh=mesh, strategy=strat)
    restore_model(ff2, ckpt)
    after = float(np.asarray(ff2.get_weights("dense")["kernel"]).sum())
    after_tab = shard_sum(ff2.state.params["tables"]["kernel"])
    b = {k: v[lo:hi] for k, v in xg.items()}
    b["label"] = yg[lo:hi]
    m = ff2.train_batch(b)
    print(f"RESULT proc={pid} step=resumed loss={float(m['loss']):.8f}",
          flush=True)
    assert abs(before - after) < 1e-6, (before, after)
    assert abs(before_tab - after_tab) < 1e-6, (before_tab, after_tab)
    # the resumed step must equal the UNINTERRUPTED model's next step
    m_cont = ff.train_batch(b)
    assert abs(float(m["loss"]) - float(m_cont["loss"])) < 1e-6, (
        float(m["loss"]), float(m_cont["loss"]))
"""


def test_two_process_placed_embedding_and_checkpoint(tmp_path):
    """Device-explicit table placement + orbax checkpointing compose
    with multi-controller SPMD: tables pin to devices owned by BOTH
    processes, training agrees across controllers, and a multihost
    save/restore continues with identical state."""
    script = tmp_path / "train_placed.py"
    script.write_text(PLACED)
    ckpt = str(tmp_path / "ckpt")
    port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-m", "flexflow_tpu",
         "--cpu-devices", "2",
         "--coordinator", f"localhost:{port}",
         "--num-processes", "2", "--process-id", str(pid),
         str(script), ckpt],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-4000:]
    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                losses.setdefault(int(parts["proc"]), []).append(
                    float(parts["loss"]))
    # 2 steps + full-table gather fingerprint + resumed step
    assert len(losses[0]) == len(losses[1]) == 4, outs
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-7)


STAGED_TRAIN = """
import numpy as np
import jax
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh
from flexflow_tpu.core.staged import StagedExecutor
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy, Strategy

pid = jax.process_index()
assert jax.process_count() == 2 and jax.device_count() == 4

cfg = FFConfig()
cfg.batch_size = 16  # GLOBAL batch
cfg.pipeline_schedule = "{schedule}"
mesh = make_mesh((2, 2), ("data", "pipe"))
strat = Strategy(default=OpStrategy({{}}))
strat.set("fc1", OpStrategy({{DEVICE_KEY: (0,)}}))
strat.set("head", OpStrategy({{DEVICE_KEY: (1,)}}))
ff = FFModel(cfg, mesh=mesh, strategy=strat)
x = ff.create_tensor((16, 32), name="input")
t = ff.dense(x, 64, activation="relu", name="fc1")
t = ff.dense(t, 64, activation="relu", name="fc2")
t = ff.dense(t, 4, name="head")
ff.softmax(t)
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type="sparse_categorical_crossentropy", metrics=[])
assert isinstance(ff.executor, StagedExecutor), type(ff.executor)

rng = np.random.RandomState(0)  # same stream on both processes
xg = rng.randn(16, 32).astype(np.float32)
yg = rng.randint(0, 4, 16).astype(np.int32)
lo, hi = pid * 8, (pid + 1) * 8
for step in range(3):
    m = ff.train_batch({{"input": xg[lo:hi], "label": yg[lo:hi]}})
    print(f"RESULT proc={{pid}} step={{step}} "
          f"loss={{float(m['loss']):.8f}}", flush=True)
"""


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_two_process_staged_pipeline(tmp_path, schedule):
    """Graph pipelining under REAL multi-controller SPMD: 2 processes x
    2 local devices = a (data=2, pipe=2) global mesh. The row-major
    mesh puts one pipe coordinate on each process (stage 1 owns
    devices {1, 3} — one per process), so stage rows and hops genuinely
    span processes; both controllers observe identical losses that
    match a single-process run exactly."""
    script = tmp_path / "train.py"
    script.write_text(STAGED_TRAIN.format(schedule=schedule))
    port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-m", "flexflow_tpu", "--cpu-devices", "2",
         "--coordinator", f"localhost:{port}",
         "--num-processes", "2", "--process-id", str(pid),
         str(script)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-3000:]
    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                losses.setdefault(int(parts["proc"]), []).append(
                    float(parts["loss"]))
    assert len(losses[0]) == len(losses[1]) == 3, outs
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-7)

    # single-process reference on the same global batch, same pins
    import jax
    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh
    from flexflow_tpu.parallel.pconfig import (DEVICE_KEY, OpStrategy,
                                               Strategy)
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.pipeline_schedule = schedule
    mesh = make_mesh((2, 2), ("data", "pipe"))
    strat = Strategy(default=OpStrategy({}))
    strat.set("fc1", OpStrategy({DEVICE_KEY: (0,)}))
    strat.set("head", OpStrategy({DEVICE_KEY: (1,)}))
    ff = FFModel(cfg, mesh=mesh, strategy=strat)
    x = ff.create_tensor((16, 32), name="input")
    t = ff.dense(x, 64, activation="relu", name="fc1")
    t = ff.dense(t, 64, activation="relu", name="fc2")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    xg = rng.randn(16, 32).astype(np.float32)
    yg = rng.randint(0, 4, 16).astype(np.int32)
    ref = [float(ff.train_batch({"input": xg, "label": yg})["loss"])
           for _ in range(3)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-6)
