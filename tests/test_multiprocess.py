"""REAL multi-controller SPMD: two OS processes, one global mesh.

The reference never tests multi-node without a cluster (SURVEY §4:
"distributed coverage is single-node multi-GPU"). Here the launcher's
jax.distributed bootstrap (python -m flexflow_tpu --coordinator ...,
the mpirun-analog of python/flexflow.py) runs two CPU processes with 2
local devices each; a DP model trains over the 4-device global mesh
with each process feeding ITS shard of the global batch, and the loss
must match a single-process run on the concatenated batch exactly.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN = """
import sys
import numpy as np
import jax
import jax.numpy as jnp
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh

pid = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4 and jax.local_device_count() == 2

cfg = FFConfig()
cfg.batch_size = 16  # GLOBAL batch
ff = FFModel(cfg, mesh=make_mesh((4,), ("data",)))
x = ff.create_tensor((16, 32), name="input")
ff.softmax(ff.dense(ff.dense(x, 64, activation="relu", name="d1"), 4,
                    name="d2"))
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type="sparse_categorical_crossentropy", metrics=[])

rng = np.random.RandomState(0)  # same stream on both processes
xg = rng.randn(16, 32).astype(np.float32)
yg = rng.randint(0, 4, 16).astype(np.int32)
lo, hi = pid * 8, (pid + 1) * 8  # this process's shard of the batch
for step in range(3):
    m = ff.train_batch({"input": xg[lo:hi], "label": yg[lo:hi]})
    print(f"RESULT proc={pid} step={step} loss={float(m['loss']):.8f}",
          flush=True)

# grouped dispatch (scan of 2 steps) through the multi-process stacked
# placement path
ms = ff.train_batches([
    {"input": xg[lo:hi], "label": yg[lo:hi]},
    {"input": xg[lo:hi], "label": yg[lo:hi]},
])
print(f"RESULT proc={pid} step=group loss={float(ms['loss'][-1]):.8f}",
      flush=True)

# fit() epoch: each process feeds its local dataset half
h = ff.fit({"input": xg[lo:hi]}, yg[lo:hi], epochs=1, verbose=False,
           batch_size=8)
print(f"RESULT proc={pid} step=fit loss={h[-1]['loss']:.8f}", flush=True)
"""


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_dp_matches_single_process(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(TRAIN)
    port = free_port()
    procs = []
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flexflow_tpu",
             "--cpu-devices", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             str(script)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-3000:]

    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                losses.setdefault(int(parts["proc"]), []).append(
                    float(parts["loss"]))
    # 3 single steps + grouped dispatch + fit epoch
    assert len(losses[0]) == len(losses[1]) == 5, outs
    # the jitted step is GLOBAL: both controllers must see the same
    # losses across every path (single, grouped, fit)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-7)
    losses = {p: v[:3] for p, v in losses.items()}  # single-proc ref

    # single-process run on the full batch reproduces it exactly
    import jax

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh

    cfg = FFConfig()
    cfg.batch_size = 16
    ff = FFModel(cfg, mesh=make_mesh((4,), ("data",)))
    x = ff.create_tensor((16, 32), name="input")
    ff.softmax(ff.dense(ff.dense(x, 64, activation="relu", name="d1"),
                        4, name="d2"))
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    xg = rng.randn(16, 32).astype(np.float32)
    yg = rng.randint(0, 4, 16).astype(np.int32)
    ref = [float(ff.train_batch({"input": xg, "label": yg})["loss"])
           for _ in range(3)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-5)
