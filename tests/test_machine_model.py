"""Torus-aware collective pricing (VERDICT r3 #9).

The reference's EnhancedMachineModel routes each comm through the
physical hierarchy (get_comm_path, machine_model.cc:695). The TPU
analog: a mesh axis laid out over k physical ICI torus dims runs its
ring phases over k disjoint link sets concurrently (k x bandwidth), and
all-to-all is bisection-bound by the axis's largest physical dim —
instead of pricing every axis as one flat ring.
"""

import json

import pytest

from flexflow_tpu import make_mesh
from flexflow_tpu.parallel.mesh import MachineSpec
from flexflow_tpu.search.machine_model import (
    TPUMachineModel,
    assign_axis_topology,
    default_machine_model,
)

MB = 1 << 20


def model_with(topology, **spec_kw):
    return TPUMachineModel(spec=MachineSpec(**spec_kw),
                           axis_topology=topology)


def test_assign_axis_topology_layout():
    mesh = make_mesh((4, 2), ("data", "model"))
    # 16-chip 2D slice presented as (4, 2, 2): data covers (4),
    # model covers (2) — leftover dims unused
    topo = assign_axis_topology(mesh, (4, 2, 2))
    assert topo == {"data": (4,), "model": (2,)}


def test_assign_axis_topology_multi_dim_axis():
    mesh = make_mesh((8,), ("data",))
    topo = assign_axis_topology(mesh, (4, 2))
    assert topo == {"data": (4, 2)}  # axis spans BOTH torus dims


def test_assign_axis_topology_uncoverable_falls_back():
    mesh = make_mesh((3, 2), ("data", "model"))
    topo = assign_axis_topology(mesh, (4, 2))
    assert "data" not in topo  # 3 does not divide into (4, 2)
    # 4 was restored, so model=2 still cannot consume it exactly? 4%2:
    # remaining[0]=4, size=2: 2 % 4 != 0 -> stays a flat ring
    assert "model" not in topo


def test_multi_dim_axis_speeds_up_all_reduce():
    flat = model_with({})
    torus = model_with({"x": (8, 8)})
    t_flat = flat.all_reduce(64 * MB, 64, "x")
    t_torus = torus.all_reduce(64 * MB, 64, "x")
    # two concurrent link sets: ~2x faster (latency term differs too)
    assert t_torus < 0.6 * t_flat
    # all-gather likewise
    assert torus.all_gather(64 * MB, 64, "x") < \
        0.6 * flat.all_gather(64 * MB, 64, "x")


def test_all_to_all_is_bisection_bound():
    flat = model_with({})
    torus = model_with({"e": (8, 8)})
    t_flat = flat.all_to_all(8 * MB, 64, "e")
    t_torus = torus.all_to_all(8 * MB, 64, "e")
    # worst cut of an 8x8 torus is 8x wider than a 64-ring's
    assert t_torus < t_flat / 4
    # and the flat 64-way all-to-all must cost MORE than a flat
    # 64-way all-gather of the same payload (the old ring formula
    # priced them equal, underpricing EP dispatch ~n/4)
    assert t_flat > flat.all_gather(8 * MB, 64, "e")


def test_line_topology_doubles_all_to_all():
    wrap = model_with({"e": (8,)})
    line = TPUMachineModel(spec=MachineSpec(ici_wraparound=False),
                           axis_topology={"e": (8,)})
    assert line.all_to_all(MB, 8, "e") > 1.5 * wrap.all_to_all(MB, 8, "e")


def test_machine_file_axis_topology_override(tmp_path):
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "machine.json"
    p.write_text(json.dumps({"axis_topology": {"data": [2, 2]},
                             "ici_latency": 2e-6}))
    mm = default_machine_model(mesh, machine_file=str(p))
    assert mm.axis_topology == {"data": (2, 2)}
    assert mm.spec.ici_latency == 2e-6


def test_machine_file_torus_dims_derivation(tmp_path):
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "machine.json"
    p.write_text(json.dumps({"ici_torus_dims": [2, 2, 2]}))
    mm = default_machine_model(mesh, machine_file=str(p))
    assert mm.axis_topology == {"data": (2, 2), "model": (2,)}


def test_dcn_axis_keeps_flat_pricing():
    mm = TPUMachineModel(spec=MachineSpec(), dcn_axes=("data",),
                         axis_topology={"data": (4, 4)})
    # DCN is switched, not a torus: the multiplier must not apply
    flat_dcn = TPUMachineModel(spec=MachineSpec(), dcn_axes=("data",))
    assert mm.all_reduce(MB, 16, "data") == \
        flat_dcn.all_reduce(MB, 16, "data")
    assert mm.all_to_all(MB, 16, "data") == \
        flat_dcn.all_to_all(MB, 16, "data")


def test_line_topology_slows_ring_collectives():
    torus = model_with({"x": (8,)})
    line = TPUMachineModel(spec=MachineSpec(ici_wraparound=False),
                           axis_topology={"x": (8,)})
    big = 256 * MB  # bandwidth-dominated
    assert line.all_reduce(big, 8, "x") > 1.5 * torus.all_reduce(
        big, 8, "x")


def test_dcn_axis_consumes_no_torus_dims():
    mesh = make_mesh((4, 2), ("data", "model"))
    topo = assign_axis_topology(mesh, (2, 2), dcn_axes=("data",))
    # 'data' spans hosts: the (2, 2) dims go to 'model'... which is
    # size 2 -> consumes (2,); 'data' gets nothing
    assert "data" not in topo
    assert topo["model"] == (2,)


def test_bad_axis_topology_pin_warns_and_drops(tmp_path):
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "machine.json"
    p.write_text(json.dumps({"axis_topology": {"model": [2, 2]}}))
    with pytest.warns(UserWarning, match="does not factor"):
        mm = default_machine_model(mesh, machine_file=str(p))
    assert "model" not in mm.axis_topology


def test_pin_plus_torus_dims_mixed_semantics(tmp_path):
    """A file pin governs its axis; unmentioned axes derive from
    ici_torus_dims; an INVALID pin leaves its axis flat-ring even when
    torus dims could cover it (the warning promises flat pricing)."""
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "machine.json"
    p.write_text(json.dumps({"axis_topology": {"data": [2, 2]},
                             "ici_torus_dims": [2, 2, 2]}))
    mm = default_machine_model(mesh, machine_file=str(p))
    assert mm.axis_topology["data"] == (2, 2)   # the pin
    assert mm.axis_topology["model"] == (2,)    # derived
    p2 = tmp_path / "machine2.json"
    p2.write_text(json.dumps({"axis_topology": {"model": [2, 2]},
                              "ici_torus_dims": [2, 2, 2]}))
    with pytest.warns(UserWarning, match="does not factor"):
        mm2 = default_machine_model(mesh, machine_file=str(p2))
    assert "model" not in mm2.axis_topology     # dropped pin stays flat
    assert mm2.axis_topology["data"] == (2, 2)  # others still derive


def test_pins_consume_torus_dims_from_pool(tmp_path):
    """A pinned axis's physical dims leave the derivation pool — two
    mesh axes must never price on the same ICI dimension."""
    mesh = make_mesh((4, 2), ("data", "model"))
    p = tmp_path / "machine.json"
    p.write_text(json.dumps({"axis_topology": {"data": [4]},
                             "ici_torus_dims": [4, 2, 2]}))
    mm = default_machine_model(mesh, machine_file=str(p))
    assert mm.axis_topology["data"] == (4,)
    # model must get one of the remaining 2s, not the consumed 4
    assert mm.axis_topology["model"] == (2,)
