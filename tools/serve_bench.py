#!/usr/bin/env python
"""Offline serving throughput microbench (flexflow_tpu.serve).

Synthetic ragged prompts through ServeEngine under continuous batching;
reports aggregate tokens/sec plus p50/p99 per-token decode latency, and
emits one BENCH-convention JSON line ({"metric", "value", "unit",
"extra"}) to stdout and (by default) BENCH_serve.json next to the other
BENCH_*.json artifacts.

Runs anywhere: on CPU hosts the decode path uses the jnp gather
fallback of paged_attention_decode (force it with --cpu), on TPU the
Pallas kernel. Usage:

    python tools/serve_bench.py                       # defaults
    python tools/serve_bench.py --requests 32 --max-new 64 --cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu before importing jax")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=256)
    ap.add_argument("--max-seqs", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("-o", "--out", default="BENCH_serve.json",
                    help="output JSON path ('' = stdout only)")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    from flexflow_tpu.utils.profiling import serve_percentiles, serve_report

    # pool sized for the workload: every admitted request reserves its
    # worst case, so give the pool ~max_seqs max-length sequences
    pages_per_seq = -(-args.max_seq_len // args.page_size)
    cfg = FFConfig(
        batch_size=1, kv_page_size=args.page_size,
        kv_num_pages=1 + pages_per_seq * args.max_seqs,
        serve_max_seqs=args.max_seqs,
        serve_prefill_budget=args.max_seq_len)
    ff = build_transformer_lm(
        cfg, vocab_size=args.vocab, max_seq_len=args.max_seq_len,
        hidden=args.hidden, num_heads=args.heads, num_layers=args.layers,
        ff_dim=4 * args.hidden)
    eng = ServeEngine(ff)

    rng = np.random.RandomState(args.seed)
    max_prompt = args.max_seq_len - args.max_new
    if max_prompt < 4:
        ap.error(f"--max-seq-len ({args.max_seq_len}) must exceed "
                 f"--max-new ({args.max_new}) by at least 4 to leave "
                 f"room for prompts")
    prompts = [list(rng.randint(1, args.vocab,
                                size=rng.randint(4, max_prompt + 1)))
               for _ in range(args.requests)]

    t0 = time.perf_counter()
    eng.warmup()
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = eng.generate(prompts, args.max_new)
    wall = time.perf_counter() - t0
    stats = eng.last_stats
    print(serve_report(stats), file=sys.stderr)

    pct = serve_percentiles(stats)
    record = {
        "metric": "serve_decode_tokens_per_sec",
        "value": round(stats["tokens_per_sec"], 2),
        "unit": "tokens/s",
        "extra": {
            "platform": jax.default_backend(),
            "requests": args.requests,
            "max_new_tokens": args.max_new,
            "total_new_tokens": stats["total_new_tokens"],
            "decode_steps": stats["decode_steps"],
            "mean_decode_width": round(
                float(np.mean(stats["decode_widths"]))
                if stats["decode_widths"] else 0.0, 2),
            "per_token_latency_ms_p50": round(pct[50] * 1e3, 4),
            "per_token_latency_ms_p99": round(pct[99] * 1e3, 4),
            "warmup_s": round(warm_s, 2),
            "wall_s": round(wall, 2),
            "compile_counts": stats["compile_counts"],
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads,
                      "max_seq_len": args.max_seq_len,
                      "page_size": args.page_size,
                      "max_seqs": args.max_seqs},
        },
    }
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # sanity: every request produced tokens
    assert all(len(o) > 0 for o in out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
