#!/usr/bin/env python
"""Offline serving throughput microbench (flexflow_tpu.serve).

Three workloads through ServeEngine under continuous batching:

  * random   — synthetic ragged prompts; reports aggregate tokens/sec
    plus p50/p99 per-token decode latency (the PR 1 headline numbers).
  * shared-prefix — every request shares a long common prompt prefix
    (the few-shot / system-preamble pattern that dominates TPU serving
    traffic): measures the ALGORITHMIC win of prefix caching + chunked
    prefill as the prefill-token reduction (prompt tokens submitted /
    prefill tokens actually computed), with outputs asserted identical
    to the no-cache greedy reference.
  * repetitive-decode — speculative decoding's target regime: an LM
    whose greedy continuation is highly repetitive (built from the
    bench model by an "echo" weight surgery, see _make_echo_lm — the
    constructed analog of the shared-prefix workload's constructed
    sharing). Measures serve_decode_step_reduction: decode steps the
    non-speculative engine dispatches / decode steps the speculative
    engine dispatches for the SAME (asserted token-identical) outputs.
  * kv-capacity — int8 quantized KV pages at an EQUAL pool byte budget
    (kv_pool_mb sizing, so the page count follows the storage format's
    itemsize): f32 vs int8 engines run the same memory-pressure
    workload; int8's ~2.7-3.8x pages (head_dim-dependent) admit more
    concurrent sequences, so the same requests finish in fewer engine
    steps at higher decode concurrency. Gates (smoke): >= 1.9x
    effective page capacity, a concurrency AND step-count win, int8
    greedy outputs token-identical to the no-cache reference
    (the relaxed quantized-pages gate), zero recompiles.

  * shard — tensor-parallel sharded serving A/B on a forced
    multi-device host mesh (docs/serving.md "Sharded serving"): the
    same model served single-device and head-sharded over a "tensor"
    mesh must produce token-identical greedy outputs with zero
    recompiles and ~t× smaller per-device KV pool + dispatched FLOPs;
    the v5e decode-step latency per tensor degree is SIMULATED by the
    placement search (search/serve_place.optimize_serve) over a
    Gemma-31B-class arch and gated >= 1.5x at t=4 (ci.sh 1j).

Select with --workload {all,base,spec,kv,shard} (base = the first two).

Emits one BENCH-convention JSON line per workload ({"metric", "value",
"unit", "extra"}) to stdout and (by default) BENCH_serve.json next to
the other BENCH_*.json artifacts.

`--smoke` is the CI gate (tools/ci.sh steps 1d/1f): a small model,
hard asserts on (a) ZERO recompiles after warmup, (b) exactness vs
generate_reference, (c) >= 2x prefill-token reduction on the
shared-prefix workload (step 1d, --workload base), (d) >= 1.5x decode
step reduction on the repetitive workload (step 1f, --workload spec).

Runs anywhere: on CPU hosts the serve path uses the jnp gather
fallback of the paged-attention kernels (force it with --cpu), on TPU
the Pallas kernels. Usage:

    python tools/serve_bench.py                       # defaults
    python tools/serve_bench.py --requests 32 --max-new 64 --cpu
    python tools/serve_bench.py --smoke               # the CI gates
    python tools/serve_bench.py --smoke --workload spec   # 1f only
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _make_echo_lm(cfg, args):
    """A copy of the bench LM surgically rewired so greedy decode
    echoes the trailing token: attention/FFN residual writers zeroed
    (the stream is exactly tok+pos embeddings), position embeddings
    damped, and the head tied to the token embeddings — near-orthogonal
    random embeddings make each token its own argmax. Its continuation
    is the maximally repetitive text prompt-lookup drafting targets,
    giving the decode-step-reduction gate a DETERMINISTIC workload
    instead of hoping a random LM's greedy stream falls into a cycle
    (the same constructed-favorable-case trick as the shared-prefix
    workload)."""
    import jax.numpy as jnp
    from flexflow_tpu.config import CompMode
    from flexflow_tpu.models.transformer import build_transformer_lm
    ff = build_transformer_lm(
        cfg, vocab_size=args.vocab, max_seq_len=args.max_seq_len,
        hidden=args.hidden, num_heads=args.heads, num_layers=args.layers,
        ff_dim=4 * args.hidden)
    ff.compile(comp_mode=CompMode.INFERENCE)
    p = ff.state.params
    for i in range(args.layers):
        attn = p[f"layer{i}_attn"]
        attn["wo"] = jnp.zeros_like(attn["wo"])
        if "bo" in attn:
            attn["bo"] = jnp.zeros_like(attn["bo"])
        ff2 = p[f"layer{i}_ff2"]
        ff2["kernel"] = jnp.zeros_like(ff2["kernel"])
        if "bias" in ff2:
            ff2["bias"] = jnp.zeros_like(ff2["bias"])
    p["pos_embed"]["kernel"] = p["pos_embed"]["kernel"] * 0.15
    p["lm_head"]["kernel"] = 4.0 * p["tok_embed"]["kernel"].T
    if "bias" in p["lm_head"]:
        p["lm_head"]["bias"] = jnp.zeros_like(p["lm_head"]["bias"])
    return ff


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu before importing jax")
    ap.add_argument("--smoke", action="store_true",
                    help="small CI gate: assert zero recompiles, "
                    "exactness, >= 2x prefill reduction (base) and "
                    ">= 1.5x decode step reduction (spec)")
    ap.add_argument("--workload",
                    choices=("all", "base", "spec", "kv", "shard",
                             "telemetry", "disagg", "router", "lora",
                             "fabric", "spill", "boot", "mesh2d"),
                    default="all",
                    help="base = random + shared-prefix (ci.sh 1d), "
                    "spec = repetitive speculative decode (ci.sh 1f), "
                    "kv = int8 KV-page capacity A/B (ci.sh 1i), "
                    "shard = tensor-parallel sharded serving A/B on a "
                    "forced multi-device host mesh (ci.sh 1j), "
                    "telemetry = telemetry-on vs -off A/B gating "
                    "token identity, zero recompiles, <= 3% overhead, "
                    "trace/metrics/drift validity (ci.sh 1k), "
                    "disagg = unified vs prefill/decode-disaggregated "
                    "serving under mixed heavy-prefill + steady-decode "
                    "traffic at equal device count, gating >= 1.3x "
                    "TPOT-p99 reduction + exactness + zero recompiles "
                    "(ci.sh 1m), "
                    "router = multi-replica prefix-affinity routing "
                    "vs round-robin on a multi-tenant prefix mix "
                    "under seeded timed traffic, gating >= 1.3x "
                    "goodput-under-SLO + token exactness vs a single "
                    "replica + zero recompiles per replica + full "
                    "page reclamation, plus autoscaler determinism "
                    "(ci.sh 1n), "
                    "lora = batched multi-tenant LoRA pool vs a "
                    "sequential per-tenant weight-swap server on a "
                    "Zipf tenant mix, gating >= 1.5x goodput (mixed "
                    "steps) + token exactness vs the merged-weight "
                    "references + zero recompiles (ci.sh 1p), "
                    "fabric = wall-clock serving fabric: the same "
                    "seeded traffic on the virtual clock vs the "
                    "threaded and single-threaded wall clock, gating "
                    "token identity across all arms + >= 1.3x "
                    "threaded/single wall goodput, plus disagg "
                    "pipelined + --transport tcp token identity "
                    "(ci.sh 1q), "
                    "spill = hierarchical host-tier prefix cache on "
                    "a working-set-larger-than-pool multi-tenant "
                    "stream: host tier armed vs plain eviction vs "
                    "rung-3-style no-match, gating >= 1.3x "
                    "goodput-under-SLO over BOTH baselines + token "
                    "identity + zero recompiles + priced "
                    "spill-vs-recompute decisions (ci.sh 1r), "
                    "boot = cold vs warm replica boot A/B through the "
                    "ProgramRegistry AOT snapshot (--program-cache-dir, "
                    "core/programs.py): cold engine construction + "
                    "warmup vs one that deserializes its executables, "
                    "gating >= 2x time-to-ready reduction, ZERO "
                    "compiles + token identity on the warm arm, and "
                    "corrupt-store fallback (compile-with-warning, "
                    "never a crash) (ci.sh 1s), "
                    "mesh2d = 2-D serve-mesh placement A/B: a pool "
                    "booted from the searched (tensor degree x "
                    "replica count) vs both degenerate allocations "
                    "of the same device budget (best tp-only r=1, "
                    "best replicas-only t=1) under shared-prefix "
                    "multi-tenant traffic with the adapter pool "
                    "armed, gating >= 1.3x goodput-under-SLO over "
                    "BOTH + t=1 HBM-rejected by the search + token "
                    "identity + zero recompiles (ci.sh 1t)")
    ap.add_argument("--trace-out", default="",
                    help="write the telemetry workload's Chrome "
                    "trace-event JSON here (Perfetto-loadable; default "
                    "/tmp/flexflow_tpu_serve_trace.json)")
    ap.add_argument("--kv-dtype", default="float32",
                    choices=("float32", "bfloat16", "int8",
                             "float8_e4m3"),
                    help="KV-page storage format for the base/spec/"
                    "shard workloads (the kv workload always A/Bs f32 "
                    "vs int8 at an equal byte budget)")
    ap.add_argument("--shard-devices", type=int, default=4,
                    help="tensor-parallel degree (and forced host "
                    "device count) of the shard workload's A/B")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=256)
    ap.add_argument("--max-seqs", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--prefix-len", type=int, default=0,
                    help="shared-prefix workload's common prefix length "
                    "(0 = half the max prompt)")
    ap.add_argument("--fault-spec", default="",
                    help="seeded fault-injection spec (utils/faults.py) "
                    "armed on the random-workload engine; also runs a "
                    "cancel/deadline storm and gates survivor "
                    "exactness + invariants + zero recompiles "
                    "(tools/ci.sh step 1g)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("-o", "--out", default="BENCH_serve.json",
                    help="output JSON path ('' = stdout only)")
    args = ap.parse_args()

    if args.cpu or args.smoke:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.workload in ("all", "shard", "mesh2d"):
        # the shard and mesh2d A/Bs need a multi-device host platform;
        # XLA only reads the flag at backend init, so it must be set
        # before jax imports (ci.sh steps 1j/1t also set it in the
        # environment)
        flag = (f"--xla_force_host_platform_device_count="
                f"{args.shard_devices}")
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                flag + " " + os.environ.get("XLA_FLAGS", ""))
    import jax
    if args.cpu or args.smoke:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    from flexflow_tpu.utils.profiling import serve_percentiles, serve_report

    if args.smoke:
        args.requests = 8
        args.max_new = 4
        args.vocab, args.hidden, args.layers, args.heads = 89, 32, 2, 4
        args.max_seq_len, args.max_seqs, args.page_size = 128, 4, 8

    # pages allocate on demand now, so the pool is sized for the
    # workload's ACTUAL residency (~max_seqs concurrent sequences);
    # a prefill budget of half the max length keeps long prompts
    # chunking across steps so the bench exercises that path
    pages_per_seq = -(-args.max_seq_len // args.page_size)
    cfg = FFConfig(
        batch_size=1, kv_page_size=args.page_size,
        kv_num_pages=1 + pages_per_seq * args.max_seqs,
        kv_dtype=args.kv_dtype,
        serve_max_seqs=args.max_seqs,
        serve_prefill_budget=max(args.page_size,
                                 args.max_seq_len // 2))
    ff = build_transformer_lm(
        cfg, vocab_size=args.vocab, max_seq_len=args.max_seq_len,
        hidden=args.hidden, num_heads=args.heads, num_layers=args.layers,
        ff_dim=4 * args.hidden)

    rng = np.random.RandomState(args.seed)
    max_prompt = args.max_seq_len - args.max_new
    if max_prompt < 8:
        ap.error(f"--max-seq-len ({args.max_seq_len}) must exceed "
                 f"--max-new ({args.max_new}) by at least 8 to leave "
                 f"room for prompts")
    records = []
    gates = []

    injector = None
    if args.fault_spec:
        from flexflow_tpu.utils.faults import FaultInjector
        injector = FaultInjector(args.fault_spec, seed=args.seed)

    def _assert_survivors(eng, prompts, out, ref, stats):
        """The chaos exactness contract: every COMPLETED request is
        token-identical to the reference; every aborted/rejected one's
        partial stream is a reference prefix. On lossy pools
        (--kv-dtype bfloat16/int8) both halves relax to the engine's
        tie-margin gate — the aborted half against the reference
        truncated at the abort point."""
        recs = stats["requests"]
        refs = [r if rec["outcome"] == "completed" else r[:len(o)]
                for o, r, rec in zip(out, ref, recs)]
        eng.assert_token_parity(
            prompts, out, refs,
            what="chaos survivors / aborted prefixes")
        return sum(rec["outcome"] == "completed" for rec in recs)

    if args.workload in ("all", "base"):
        # the base engine runs with the telemetry bus attached so the
        # BENCH record carries the canonical latency percentiles +
        # drift ratios (docs/observability.md); the telemetry workload
        # below is what GATES the overhead of doing so
        from flexflow_tpu.utils.telemetry import Telemetry
        base_tel = Telemetry()
        eng = ServeEngine(ff, faults=injector, telemetry=base_tel)
        t0 = time.perf_counter()
        counts = eng.warmup()
        warm_s = time.perf_counter() - t0

        # ---- workload 1: random ragged prompts (throughput) ----------
        prompts = [list(rng.randint(1, args.vocab,
                                    size=rng.randint(4, max_prompt + 1)))
                   for _ in range(args.requests)]
        t0 = time.perf_counter()
        out = eng.generate(prompts, args.max_new)
        wall = time.perf_counter() - t0
        stats = eng.last_stats
        print(serve_report(stats), file=sys.stderr)
        if injector is None:
            assert all(len(o) > 0 for o in out)
        else:
            # under injected faults the gate is survivor exactness +
            # clean invariants, not universal completion
            _assert_survivors(eng, prompts, out, eng.generate_reference(
                prompts, args.max_new), stats)
            eng.cache.check_invariants()

        pct = serve_percentiles(stats)
        records.append({
            "metric": "serve_decode_tokens_per_sec",
            "value": round(stats["tokens_per_sec"], 2),
            "unit": "tokens/s",
            "extra": {
                "platform": jax.default_backend(),
                "requests": args.requests,
                "max_new_tokens": args.max_new,
                "total_new_tokens": stats["total_new_tokens"],
                "decode_steps": stats["decode_steps"],
                "mean_decode_width": round(
                    float(np.mean(stats["decode_widths"]))
                    if stats["decode_widths"] else 0.0, 2),
                "per_token_latency_ms_p50": round(pct[50] * 1e3, 4),
                "per_token_latency_ms_p99": round(pct[99] * 1e3, 4),
                # the telemetry snapshot's latency/drift block: TTFT
                # from the same registry serve_report renders, drift =
                # measured/predicted per serve regime (the simulator
                # calibration signal)
                "telemetry": {
                    "ttft_ms_p50": round(
                        base_tel.metrics.quantile(
                            "serve_ttft_seconds", 50) * 1e3, 4),
                    "ttft_ms_p99": round(
                        base_tel.metrics.quantile(
                            "serve_ttft_seconds", 99) * 1e3, 4),
                    "tpot_ms_p50": round(
                        base_tel.metrics.quantile(
                            "serve_tpot_seconds", 50) * 1e3, 4),
                    "tpot_ms_p99": round(
                        base_tel.metrics.quantile(
                            "serve_tpot_seconds", 99) * 1e3, 4),
                    "tokens_per_sec": round(
                        base_tel.metrics.gauge("serve_tokens_per_sec"),
                        2),
                    "drift_ratio_by_regime": {
                        reg: round(d["ratio"], 2)
                        for reg, d in base_tel.drift_snapshot().get(
                            "serve", {}).items()},
                },
                "preemptions": stats["preemptions"],
                "page_util_max": round(stats["page_util_max"], 4),
                "spec_acceptance": round(stats["spec_acceptance"], 4),
                "warmup_s": round(warm_s, 2),
                "wall_s": round(wall, 2),
                "compile_counts": stats["compile_counts"],
                "model": {"vocab": args.vocab, "hidden": args.hidden,
                          "layers": args.layers, "heads": args.heads,
                          "max_seq_len": args.max_seq_len,
                          "page_size": args.page_size,
                          "max_seqs": args.max_seqs},
            },
        })

        # ---- chaos storm (only with --fault-spec): cancels + deadlines
        # through the SAME engine the injected faults hit, gating that
        # the engine is still serving exactly, reclaiming every page,
        # and never recompiling (tools/ci.sh step 1g)
        if injector is not None:
            cprompts = [list(rng.randint(
                1, args.vocab, size=rng.randint(4, max_prompt + 1)))
                for _ in range(args.requests)]
            cref = eng.generate_reference(cprompts, args.max_new)
            deadlines = [None] * args.requests
            deadlines[1 % args.requests] = 1e-9      # expires instantly
            storm = {1: [2 % args.requests], 3: [5 % args.requests]}

            def on_step(step):
                for rid in storm.get(step, ()):
                    eng.cancel(rid)
                eng.cache.check_invariants()         # after every event

            cout = eng.generate(cprompts, args.max_new,
                                deadline_s=deadlines, on_step=on_step)
            cstats = eng.last_stats
            survivors = _assert_survivors(eng, cprompts, cout, cref,
                                          cstats)
            assert survivors > 0, "chaos storm left no survivors"
            aborted = (cstats["cancelled"] + cstats["deadline_expired"]
                       + cstats["rejected"])
            assert aborted > 0, "chaos storm aborted nothing"
            assert eng.compile_counts() == counts, (
                f"chaos recompiled: {counts} -> {eng.compile_counts()}")
            assert eng.cache.free_pages == \
                eng.cache_cfg.usable_pages, "chaos leaked pages"
            retried = stats["retries"] + cstats["retries"]
            gates.append(
                f"chaos survivors={survivors} aborted={aborted} "
                f"retried={retried} "
                f"rung_max={max(stats['degradation_rung_max'], cstats['degradation_rung_max'])}")
            records.append({
                "metric": "serve_chaos_survivor_exactness",
                "value": 1.0,
                "unit": "bool",
                "extra": {
                    "platform": jax.default_backend(),
                    "fault_spec": args.fault_spec,
                    "seed": args.seed,
                    "survivors": survivors,
                    "cancelled": cstats["cancelled"],
                    "deadline_expired": cstats["deadline_expired"],
                    "rejected": cstats["rejected"],
                    "retried_dispatches": retried,
                    "degradation_rung_max": max(
                        stats["degradation_rung_max"],
                        cstats["degradation_rung_max"]),
                    "rung_steps": cstats["rung_steps"],
                    "outputs_match_reference": True,
                    "compile_counts": eng.compile_counts(),
                },
            })

        # ---- workload 2: shared prefix (the prefix-cache win) --------
        # a FRESH engine so workload 1's committed pages cannot inflate
        # the hit rate: every hit below comes from sharing inside this
        # workload (and the fault injector stays off it — its gates
        # measure the cache, not the chaos)
        eng2 = ServeEngine(ff)
        eng2.warmup()
        prefix_len = args.prefix_len or max_prompt // 2
        tail = max(4, args.page_size // 2)
        prefix = list(rng.randint(1, args.vocab, size=prefix_len))
        sprompts = [prefix + list(rng.randint(1, args.vocab, size=tail))
                    for _ in range(args.requests)]
        before = eng2.compile_counts()
        t0 = time.perf_counter()
        sout = eng2.generate(sprompts, args.max_new)
        swall = time.perf_counter() - t0
        sstats = eng2.last_stats
        print(serve_report(sstats), file=sys.stderr)
        computed = sstats["prefill_tokens_computed"]
        submitted = sstats["prompt_tokens_total"]
        reduction = submitted / computed if computed else float("inf")

        # the serving CORRECTNESS contracts hold on every run: no
        # program compiled after warmup, and the prefix-cached (and,
        # by default, speculative) outputs are exactly the no-cache
        # greedy reference
        assert eng2.compile_counts() == before, (
            f"serving recompiled: {before} -> {eng2.compile_counts()}")
        ref = eng2.generate_reference(sprompts, args.max_new)
        eng2.assert_token_parity(sprompts, sout, ref,
                                 what="prefix-cached outputs")
        # the >= 2x reduction is a property of the DEFAULT shared-prefix
        # shapes, so it hard-gates only under --smoke (CI); a custom
        # --prefix-len/--requests sweep should report, not crash
        if reduction < 2.0:
            msg = (f"prefix caching only cut prefill tokens "
                   f"{reduction:.2f}x ({computed}/{submitted}) — "
                   f"expected >= 2x on shared prefixes")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(f"prefill_reduction={reduction:.2f}x "
                     f"compile_counts={counts}")

        records.append({
            "metric": "serve_prefill_token_reduction",
            "value": round(reduction, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": args.requests,
                "prefix_len": prefix_len,
                "tail_len": tail,
                "prompt_tokens_submitted": submitted,
                "prefill_tokens_computed": computed,
                "prefix_hit_tokens": sstats["prefix_hit_tokens"],
                "tokens_per_sec": round(sstats["tokens_per_sec"], 2),
                "outputs_match_reference": True,
                "wall_s": round(swall, 2),
                "compile_counts": sstats["compile_counts"],
            },
        })

    if args.workload in ("all", "spec"):
        # ---- workload 3: repetitive decode (speculative decoding) ----
        # one echo LM, two engines over its params: speculative (k=8)
        # vs non-speculative baseline. The win is decode STEPS — every
        # decode step is one dispatch of the same fixed-shape mixed
        # program, so steps_base / steps_spec is the dispatch-count
        # reduction for token-identical outputs.
        spec_k = 8
        prompt_hi = 17          # spec prompts draw from [4, prompt_hi)
        spec_new = min(max(24, args.max_new),
                       args.max_seq_len - prompt_hi)
        if spec_new < 8:
            ap.error(f"--max-seq-len ({args.max_seq_len}) leaves no "
                     f"room for the repetitive-decode workload "
                     f"(needs prompt + >= 8 new tokens)")
        ff_echo = _make_echo_lm(cfg, args)
        eng_s = ServeEngine(ff_echo, spec_tokens=spec_k)
        eng_s.warmup()
        eng_b = ServeEngine(ff_echo, spec_tokens=0)
        eng_b.warmup()
        rprompts = [list(rng.randint(1, args.vocab,
                                     size=rng.randint(4, prompt_hi)))
                    for _ in range(args.requests)]
        before = eng_s.compile_counts()
        t0 = time.perf_counter()
        rout = eng_s.generate(rprompts, spec_new)
        rwall = time.perf_counter() - t0
        rstats = eng_s.last_stats
        print(serve_report(rstats), file=sys.stderr)
        bout = eng_b.generate(rprompts, spec_new)
        bsteps = eng_b.last_stats["decode_steps"]
        ssteps = rstats["decode_steps"]
        step_red = bsteps / ssteps if ssteps else float("inf")

        assert eng_s.compile_counts() == before, (
            f"speculative serving recompiled: "
            f"{before} -> {eng_s.compile_counts()}")
        # speculative vs baseline is an EXACT contract at any page
        # format (both engines read the same deterministic quantized
        # content); the reference comparison relaxes for lossy formats
        assert rout == bout, (
            "speculative outputs diverged from the non-speculative "
            "engine on the same pages")
        ref = eng_s.generate_reference(rprompts, spec_new)
        eng_s.assert_token_parity(rprompts, rout, ref,
                                  what="speculative outputs")
        eng_b.assert_token_parity(rprompts, bout, ref,
                                  what="baseline outputs")
        # >= 1.5x is a property of the constructed repetitive workload
        # (echo LM + prompt-lookup drafting), hard-gated under --smoke
        if step_red < 1.5:
            msg = (f"speculative decoding only cut decode steps "
                   f"{step_red:.2f}x ({bsteps}/{ssteps}) — expected "
                   f">= 1.5x on repetitive text")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(f"decode_step_reduction={step_red:.2f}x "
                     f"compile_counts={eng_s.compile_counts()}")

        records.append({
            "metric": "serve_decode_step_reduction",
            "value": round(step_red, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": args.requests,
                "max_new_tokens": spec_new,
                "spec_tokens": spec_k,
                "decode_steps_baseline": bsteps,
                "decode_steps_speculative": ssteps,
                "spec_drafted_tokens": rstats["spec_drafted_tokens"],
                "spec_accepted_tokens": rstats["spec_accepted_tokens"],
                "spec_acceptance": round(rstats["spec_acceptance"], 4),
                "steps_per_decode_token": round(
                    rstats["steps_per_decode_token"], 4),
                "outputs_match_reference": True,
                "wall_s": round(rwall, 2),
                "compile_counts": rstats["compile_counts"],
            },
        })

    if args.workload in ("all", "kv"):
        # ---- workload 4: int8 KV-page capacity at an equal byte
        # budget (tools/ci.sh step 1i). Two engines over identically
        # initialized models, pools sized by kv_pool_mb so the page
        # count follows the storage format's itemsize: the f32 pool is
        # deliberately TIGHT (~2.2 sequences of history) so admission
        # blocks / preemption churns, while int8's ~2.7x pages (at this
        # head_dim) run the same requests at higher decode concurrency
        # in fewer engine steps. Outputs of BOTH arms must be greedy
        # token-identical to the no-cache f32 reference — the relaxed
        # quantized-pages exactness gate.
        head_dim = args.hidden // args.heads
        kv_seqs = max(args.max_seqs, 8)
        kv_new = min(max(16, args.max_new),
                     args.max_seq_len - args.page_size)
        kv_reqs = max(12, args.requests)
        from flexflow_tpu.serve.kv_cache import KVCacheConfig
        f32_page_bytes = KVCacheConfig(
            num_layers=args.layers, num_heads=args.heads,
            head_dim=head_dim, page_size=args.page_size,
            num_pages=2, max_seqs=1).f32_page_bytes
        tight_pages = max(pages_per_seq, int(2.2 * pages_per_seq))
        budget_mb = tight_pages * f32_page_bytes / float(1 << 20)

        def kv_engine(dtype):
            c = FFConfig(
                batch_size=1, kv_page_size=args.page_size,
                kv_pool_mb=budget_mb, kv_dtype=dtype,
                serve_max_seqs=kv_seqs,
                serve_prefill_budget=max(args.page_size,
                                         args.max_seq_len // 2))
            m = build_transformer_lm(
                c, vocab_size=args.vocab, max_seq_len=args.max_seq_len,
                hidden=args.hidden, num_heads=args.heads,
                num_layers=args.layers, ff_dim=4 * args.hidden)
            # speculation off in both arms: the A/B measures the page
            # pool, and drafts would add a second page consumer
            return ServeEngine(m, spec_tokens=0)

        prompt_cap = args.max_seq_len - kv_new
        kv_prompts = [list(rng.randint(
            1, args.vocab,
            size=rng.randint(args.page_size, max(args.page_size + 1,
                                                 prompt_cap // 2))))
            for _ in range(kv_reqs)]

        arms = {}
        for dtype in ("float32", "int8"):
            eng_kv = kv_engine(dtype)
            counts_kv = eng_kv.warmup()
            t0 = time.perf_counter()
            out_kv = eng_kv.generate(kv_prompts, kv_new)
            wall_kv = time.perf_counter() - t0
            st = eng_kv.last_stats
            print(serve_report(st), file=sys.stderr)
            assert eng_kv.compile_counts() == counts_kv, (
                f"{dtype} kv arm recompiled: "
                f"{counts_kv} -> {eng_kv.compile_counts()}")
            if dtype == "int8":
                eng_kv.check_kv_scales()
            eng_kv.cache.check_invariants()
            arms[dtype] = {
                "engine": eng_kv, "out": out_kv, "stats": st,
                "wall_s": wall_kv,
                "usable_pages": eng_kv.cache_cfg.usable_pages,
                "pool_bytes": eng_kv.cache_cfg.pool_bytes,
                "steps": st["steps"],
                "mean_decode_width": (
                    float(np.mean(st["decode_widths"]))
                    if st["decode_widths"] else 0.0),
                "tokens_per_sec": st["tokens_per_sec"],
                "preemptions": st["preemptions"],
            }

        f, q = arms["float32"], arms["int8"]
        # exactness gates. f32 pages are lossless: full token identity
        # with the no-cache reference. int8 pages gate the RELAXED
        # quantized contract instead (docs/serving.md): (a) greedy
        # token parity up to tie flips on both the base-shaped and the
        # long workload (ServeEngine.assert_token_parity), with most base
        # requests fully identical, (b) token identity across chunking
        # interleavings — a different prefill budget moves every chunk
        # boundary, and per-row write-local scales must make that
        # invisible — and (c) the per-element attention-output atol
        # gated in tests/test_kv_quant.py.
        kv_ref = f["engine"].generate_reference(kv_prompts, kv_new)
        assert f["out"] == kv_ref, "f32 kv arm diverged from reference"
        base_prompts = kv_prompts[:8]
        # this untimed run doubles as the mid-run scale audit: on_step
        # fires while sequences are RESIDENT, which is the only time
        # check_kv_scales can inspect live (slot, position) rows
        base_out = q["engine"].generate(
            base_prompts, 4,
            on_step=lambda s: q["engine"].check_kv_scales())
        base_ref = q["engine"].generate_reference(base_prompts, 4)
        base_exact = q["engine"].assert_token_parity(
            base_prompts, base_out, base_ref, min_exact_frac=0.75,
            what="int8 base workload")
        long_exact = q["engine"].assert_token_parity(
            kv_prompts, q["out"], kv_ref, what="int8 long workload")
        eng_alt = kv_engine("int8")
        eng_alt.prefill_budget = max(args.page_size,
                                     eng_alt.prefill_budget // 3)
        eng_alt.mixed_width = (eng_alt.prefill_budget
                               + eng_alt.cache_cfg.max_seqs)
        eng_alt.warmup()
        alt_out = eng_alt.generate(kv_prompts, kv_new)
        assert alt_out == q["out"], (
            "int8 outputs changed across chunking interleavings — "
            "quantized content must be chunk-boundary invariant")
        agree = sum(
            len(o) if d is None else d
            for o, r in zip(q["out"], kv_ref)
            for d in (ServeEngine.first_divergence(o, r),))
        total_ref = sum(len(o) for o in q["out"])
        capacity = q["usable_pages"] / f["usable_pages"]
        concurrency = (q["mean_decode_width"]
                       / max(f["mean_decode_width"], 1e-9))
        step_ratio = f["steps"] / max(q["steps"], 1)
        tput_ratio = (q["tokens_per_sec"]
                      / max(f["tokens_per_sec"], 1e-9))
        if capacity < 1.9 or concurrency <= 1.0 or step_ratio <= 1.0:
            msg = (f"int8 kv pages: capacity {capacity:.2f}x "
                   f"(want >= 1.9), concurrency {concurrency:.2f}x, "
                   f"steps {step_ratio:.2f}x (want > 1.0 each)")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(f"kv_capacity={capacity:.2f}x "
                     f"concurrency={concurrency:.2f}x "
                     f"steps={step_ratio:.2f}x")

        records.append({
            "metric": "serve_kv_page_capacity",
            "value": round(capacity, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "pool_budget_mb": round(budget_mb, 3),
                "requests": kv_reqs,
                "max_new_tokens": kv_new,
                "head_dim": head_dim,
                "pages_f32": f["usable_pages"],
                "pages_int8": q["usable_pages"],
                "pool_bytes_f32": f["pool_bytes"],
                "pool_bytes_int8": q["pool_bytes"],
                "steps_f32": f["steps"],
                "steps_int8": q["steps"],
                "step_reduction": round(step_ratio, 2),
                "mean_decode_width_f32": round(
                    f["mean_decode_width"], 2),
                "mean_decode_width_int8": round(
                    q["mean_decode_width"], 2),
                "concurrency_gain": round(concurrency, 2),
                "tokens_per_sec_f32": round(f["tokens_per_sec"], 2),
                "tokens_per_sec_int8": round(q["tokens_per_sec"], 2),
                "throughput_gain": round(tput_ratio, 2),
                "preemptions_f32": f["preemptions"],
                "preemptions_int8": q["preemptions"],
                "greedy_parity_base_exact": f"{base_exact}/"
                                            f"{len(base_prompts)}",
                "greedy_parity_long_exact": f"{long_exact}/"
                                            f"{len(kv_prompts)}",
                "chunking_invariant": True,
                "prefix_agreement_long_stream": round(
                    agree / max(total_ref, 1), 4),
                "attn_block_kv": q["stats"]["kv_pool"]["attn_block_kv"],
                "attn_dispatch_passes": q["stats"]["kv_pool"][
                    "attn_dispatch_passes"],
            },
        })

    if args.workload in ("all", "shard"):
        # ---- workload 5: tensor-parallel sharded serving (ci.sh 1j).
        # A/B on the forced multi-device host mesh: the SAME model
        # served by a single-device engine and a head-sharded
        # tensor-parallel engine — outputs must be token-identical on
        # f32 pages (tie-margin parity on quantized), zero recompiles,
        # per-device dispatched FLOPs and pool bytes reduced ~t×. The
        # measured A/B proves correctness on the CPU mesh; the SPEED
        # story is simulated on the v5e machine model by the placement
        # search (search/serve_place.optimize_serve) over a
        # production-scale arch — the PAPERS.md Gemma-31B-class
        # serving comparison — which is what the >= 1.5x decode-step
        # speedup gate at t=4 reads.
        t_deg = args.shard_devices
        ndev = len(jax.devices())
        shard_skip = None
        if t_deg < 2:
            # a t=1 "sharded" engine has no sharding block to report
            # and nothing to A/B against
            shard_skip = (f"--shard-devices ({t_deg}) must be >= 2 "
                          f"for the sharded-vs-single A/B")
        elif ndev < t_deg:
            # XLA_FLAGS only forces extra devices on the CPU host
            # platform, so a 1-chip TPU/GPU lands here under the
            # default --workload all: SKIP the A/B (keeping the other
            # workloads' records) unless shard was asked for by name
            shard_skip = (f"shard workload needs {t_deg} devices, "
                          f"have {ndev} (set XLA_FLAGS="
                          f"--xla_force_host_platform_device_count="
                          f"{t_deg})")
        elif args.heads % t_deg:
            shard_skip = (f"--heads ({args.heads}) must divide by "
                          f"--shard-devices ({t_deg})")
        if shard_skip and args.workload == "shard":
            ap.error(shard_skip)
        if shard_skip:
            print(f"WARNING: skipping shard workload: {shard_skip}",
                  file=sys.stderr)
    if args.workload in ("all", "shard") and not shard_skip:
        eng_u = ServeEngine(ff)
        cnt_u = eng_u.warmup()
        eng_t = ServeEngine(ff, tensor_parallel=t_deg)
        cnt_t = eng_t.warmup()
        hprompts = [list(rng.randint(
            1, args.vocab, size=rng.randint(4, max_prompt + 1)))
            for _ in range(args.requests)]
        t0 = time.perf_counter()
        out_u = eng_u.generate(hprompts, args.max_new)
        wall_u = time.perf_counter() - t0
        t0 = time.perf_counter()
        out_t = eng_t.generate(hprompts, args.max_new)
        wall_t = time.perf_counter() - t0
        tstats = eng_t.last_stats
        print(serve_report(tstats), file=sys.stderr)
        assert eng_u.compile_counts() == cnt_u and \
            eng_t.compile_counts() == cnt_t, (
                f"shard A/B recompiled: {cnt_u}/{cnt_t} -> "
                f"{eng_u.compile_counts()}/{eng_t.compile_counts()}")
        # sharded vs single-device is an EXACT contract at any page
        # format (per-head bit identity + exact psums); the reference
        # comparison relaxes for lossy formats as usual
        assert out_t == out_u, (
            "sharded outputs diverged from the single-device engine")
        eng_t.assert_token_parity(
            hprompts, out_t,
            eng_u.generate_reference(hprompts, args.max_new),
            what="sharded outputs")
        eng_t.cache.check_invariants()
        sh = tstats["sharding"]
        cfg_t = eng_t.cache_cfg
        # per-device reductions: pool bytes divide exactly by t (head
        # sharding carries the whole page), dispatched matmul/attention
        # FLOPs divide by t up to the replicated LN/residual tail
        pool_ratio = cfg_t.page_bytes / cfg_t.page_device_bytes
        assert pool_ratio == t_deg, (
            f"pool bytes/device reduced {pool_ratio}x, want {t_deg}x")
        # per-device dispatched FLOPs, MEASURED by XLA's cost analysis
        # of the two compiled mixed programs (the sharded one is the
        # per-device program) — not the analytic /t formula this gate
        # exists to check. Ratio < t by the replicated LN/residual/
        # sampling tail; a lost /t anywhere would collapse it to ~1.
        ca_u = eng_u.mixed_step_cost_analysis()
        ca_t = eng_t.mixed_step_cost_analysis()
        flops_ratio = None
        if ca_u and ca_t and ca_u.get("flops") and ca_t.get("flops"):
            flops_ratio = ca_u["flops"] / ca_t["flops"]
            assert flops_ratio >= 0.6 * t_deg, (
                f"per-device mixed-step FLOPs only reduced "
                f"{flops_ratio:.2f}x at t={t_deg} (want >= "
                f"{0.6 * t_deg:.1f}x)")
        elif args.smoke:
            raise AssertionError(
                "backend cost analysis unavailable: the smoke gate "
                "cannot measure the per-device FLOPs reduction")

        # the simulated v5e story: the placement search prices the
        # mixed decode step per tensor degree for (a) a Gemma-31B-class
        # serving arch — too big for one v5e chip, the PAPERS.md
        # comparison — and (b) this bench's tiny model, where the
        # search correctly keeps t=1 (collectives would dominate)
        from flexflow_tpu.parallel.mesh import MachineSpec
        from flexflow_tpu.search.cost_model import ServeArch
        from flexflow_tpu.search.machine_model import TPUMachineModel
        from flexflow_tpu.search.serve_place import optimize_serve
        big = ServeArch(
            num_layers=48, hidden=6144, num_heads=48, head_dim=128,
            ff_dim=24576, vocab=256128, decode_lanes=32,
            prefill_lanes=512, context=2048,
            kv_dtype="int8", kv_itemsize=1.0, kv_scales=True,
            act_itemsize=2.0, act_dtype="bfloat16", param_itemsize=2.0)
        mm = TPUMachineModel(spec=MachineSpec.v5e(8))
        place = optimize_serve(big, 8, mm=mm)
        table = place.decode_by_degree
        speedup4 = table[1] / table[4]
        tiny_place = optimize_serve(eng_t.serve_arch(), 8, mm=mm)
        if speedup4 < 1.5:
            msg = (f"simulated v5e decode step at t=4 only "
                   f"{speedup4:.2f}x faster than t=1 (want >= 1.5x)")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        flops_txt = ("n/a" if flops_ratio is None
                     else f"{flops_ratio:.2f}x")
        gates.append(
            f"shard parity ok, pool/device {pool_ratio:.0f}x, "
            f"flops/device {flops_txt}, sim_speedup(t=4)="
            f"{speedup4:.2f}x, auto_t={place.tensor_parallel}")

        records.append({
            "metric": "serve_shard_decode_speedup",
            "value": round(speedup4, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "tensor_parallel": t_deg,
                "requests": args.requests,
                "max_new_tokens": args.max_new,
                "outputs_match_single_device": True,
                "outputs_match_reference": True,
                "compile_counts": eng_t.compile_counts(),
                "heads_per_device": sh["heads_per_device"],
                "kv_pool_device_bytes": sh["kv_pool_device_bytes"],
                "pool_bytes_per_device_reduction": round(pool_ratio, 2),
                "flops_per_device_reduction": (
                    None if flops_ratio is None else
                    round(flops_ratio, 2)),
                "collective_bytes_per_step": sh[
                    "collective_bytes_per_step"],
                "wall_s_single": round(wall_u, 2),
                "wall_s_sharded": round(wall_t, 2),
                # simulated v5e decode-step latency per tensor degree
                # (the SOAP search applied to inference placement)
                "sim_machine": "v5e",
                "sim_arch": "gemma-31b-class int8-kv bf16",
                "sim_decode_ms_by_degree": {
                    str(t): round(d * 1e3, 3) for t, d in table.items()},
                "sim_auto_placement": {
                    "tensor_parallel": place.tensor_parallel,
                    "axis_dims": list(place.axis_dims),
                    "decode_step_ms": round(
                        place.decode_step_s * 1e3, 3)},
                "sim_bench_model_auto_t": tiny_place.tensor_parallel,
                "cost_cache_fingerprint": place.fingerprint,
            },
        })

    if args.workload in ("all", "disagg"):
        # ---- workload 7: disaggregated prefill/decode serving (ci.sh
        # step 1m, docs/serving.md "Disaggregated serving"). Mixed
        # traffic — heavy-prefill requests (long prompts, few tokens)
        # interleaved with steady decoders (short prompts, long
        # outputs) — served by (a) ONE unified mixed engine and (b) a
        # DisaggCluster at the same device count, whose decode role
        # runs a program with only a page-sized prefill stub. The
        # unified engine's fixed-width program makes every decode step
        # pay the full prefill budget's lanes; the decode role's step
        # is ~(budget/stub)x narrower, so per-token decode latency
        # (TPOT) p99 drops. Gates (smoke): disaggregated outputs
        # token-identical to the unified engine (the handoff contract;
        # reference parity relaxes on lossy pools as usual), zero
        # recompiles on every role after DisaggCluster.warmup(), and
        # >= 1.3x TPOT-p99 reduction — measured on this host OR
        # simulated by the ratio search on the v5e machine model for
        # the Gemma-31B-class arch (CPU wall clocks at toy widths are
        # noisy; the simulated number is the production claim and the
        # measured one the mechanism check — both are recorded).
        from flexflow_tpu.serve.disagg import DisaggCluster
        from flexflow_tpu.utils.profiling import disagg_report

        d_heavy = max(4, args.requests // 2)
        d_steady = max(4, args.requests // 2)
        steady_new = min(24, args.max_seq_len // 4)
        heavy_lo = max(8, int(max_prompt * 0.6))
        dprompts = []
        dnew = []
        for i in range(d_heavy + d_steady):
            if i % 2 == 0:     # heavy prefill: long prompt, FEW tokens
                # (capped so the heavy class stays prefill-dominated
                # in non-smoke runs too — the traffic shape the
                # metric's label claims)
                dprompts.append(list(rng.randint(
                    1, args.vocab,
                    size=rng.randint(heavy_lo, max_prompt + 1))))
                dnew.append(min(4, args.max_new))
            else:              # steady decode: short prompt, long output
                dprompts.append(list(rng.randint(
                    1, args.vocab, size=rng.randint(4, 17))))
                dnew.append(steady_new)

        eng_m = ServeEngine(ff, spec_tokens=0)
        cnt_m = eng_m.warmup()
        t0 = time.perf_counter()
        out_m = eng_m.generate(dprompts, dnew)
        wall_m = time.perf_counter() - t0
        mstats = eng_m.last_stats
        print(serve_report(mstats), file=sys.stderr)

        cl = DisaggCluster(ff, spec_tokens=0)
        cnt_d = cl.warmup()
        t0 = time.perf_counter()
        out_d = cl.generate(dprompts, dnew)
        wall_d = time.perf_counter() - t0
        print(disagg_report(cl.last_stats, cl.metrics),
              file=sys.stderr)

        # exactness: the cluster is token-identical to the unified
        # engine at ANY page format (the handoff moves bit-equal
        # rows); the no-cache reference comparison relaxes for lossy
        # formats through the usual tie-margin gate
        assert out_d == out_m, (
            "disaggregated outputs diverged from the unified engine")
        dref = eng_m.generate_reference(dprompts, dnew)
        eng_m.assert_token_parity(dprompts, out_d, dref,
                                  what="disaggregated outputs")
        assert eng_m.compile_counts() == cnt_m, (
            f"unified arm recompiled: {cnt_m} -> "
            f"{eng_m.compile_counts()}")
        assert cl.compile_counts() == cnt_d, (
            f"disagg cluster recompiled: {cnt_d} -> "
            f"{cl.compile_counts()}")
        cl.check_invariants()
        assert cl.stats["handoff_requests"] > 0, (
            "no pages crossed the handoff link")

        # measured TPOT p99: unified = the canonical fold over its
        # stats; disagg = the decode ROLE's role-labeled histogram
        # (the cluster's own registry — the per-role split satellite)
        uni_p99 = serve_percentiles(mstats, qs=(99,))[99]
        dec_p99 = cl.metrics.quantile("serve_tpot_seconds", 99,
                                      role="decode")
        measured = uni_p99 / dec_p99 if dec_p99 else 0.0

        # simulated: the ratio search over the Gemma-31B-class arch on
        # a 16-chip v5e — big enough that both roles fit at t=8 — with
        # the page-handoff link priced on the host link
        from flexflow_tpu.parallel.mesh import MachineSpec
        from flexflow_tpu.search.cost_model import ServeArch
        from flexflow_tpu.search.machine_model import TPUMachineModel
        from flexflow_tpu.search.serve_place import optimize_serve
        big = ServeArch(
            num_layers=48, hidden=6144, num_heads=48, head_dim=128,
            ff_dim=24576, vocab=256128, decode_lanes=32,
            prefill_lanes=512, context=2048, decode_tokens=128,
            kv_dtype="int8", kv_itemsize=1.0, kv_scales=True,
            act_itemsize=2.0, act_dtype="bfloat16",
            param_itemsize=2.0)
        mm = TPUMachineModel(spec=MachineSpec.v5e(16))
        dplace = optimize_serve(big, 16, mm=mm, disaggregated=True)
        simulated = dplace.tpot_reduction_vs_unified()

        reduction = max(measured, simulated)
        if reduction < 1.3:
            msg = (f"disaggregation only cut TPOT p99 "
                   f"{measured:.2f}x measured / {simulated:.2f}x "
                   f"simulated — expected >= 1.3x on mixed traffic")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(
            f"disagg_tpot_p99_reduction={measured:.2f}x measured / "
            f"{simulated:.2f}x simulated, ratio={dplace.ratio} "
            f"(t_pre={dplace.prefill_tensor} "
            f"t_dec={dplace.decode_tensor})")

        records.append({
            "metric": "serve_disagg_tpot_p99_reduction",
            "value": round(reduction, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": len(dprompts),
                "heavy_prefill_requests": d_heavy,
                "steady_decode_requests": d_steady,
                "unified_tpot_ms_p99": round(uni_p99 * 1e3, 4),
                "disagg_decode_tpot_ms_p99": round(dec_p99 * 1e3, 4),
                "measured_reduction": round(measured, 2),
                "outputs_match_unified": True,
                "outputs_match_reference": True,
                "zero_recompiles": True,
                "decode_budget_lanes": cl.decode_budget,
                "unified_mixed_width": eng_m.mixed_width,
                "disagg_decode_width": cl.decode[0].mixed_width,
                "handoff": {k: round(v, 6) if isinstance(v, float)
                            else v for k, v in cl.stats.items()},
                "wall_s_unified": round(wall_m, 2),
                "wall_s_disagg": round(wall_d, 2),
                # the search's production story: simulated v5e ratio
                # table + per-role degrees + priced transfer link
                "sim_machine": "v5e-16",
                "sim_arch": "gemma-31b-class int8-kv bf16",
                "sim_tpot_reduction": round(simulated, 2),
                "sim_ratio": dplace.ratio,
                "sim_prefill_tensor": dplace.prefill_tensor,
                "sim_decode_tensor": dplace.decode_tensor,
                "sim_decode_step_ms": round(
                    dplace.decode_step_s * 1e3, 3),
                "sim_unified_tpot_ms": round(
                    dplace.unified_tpot_s * 1e3, 3),
                "sim_transfer_ms_per_request": round(
                    dplace.transfer_s * 1e3, 3),
                # the search's ratio table is already numerically
                # ordered (1:1, 1:2, ... — dict order is meaningful)
                "sim_ratio_table_ms": {
                    r: round(v * 1e3, 2)
                    for r, v in list(dplace.ratio_table.items())[:12]},
                "cost_cache_fingerprint": dplace.fingerprint,
            },
        })

    if args.workload in ("all", "router"):
        # ---- workload 8: multi-replica routing A/B (tools/ci.sh step
        # 1n, docs/serving.md "Multi-replica routing"). A simulated
        # cluster of 3 ServeEngine replicas serves the SAME seeded
        # multi-tenant traffic stream (serve/traffic.py: Poisson
        # arrivals, Zipf tenants over shared prefixes, heavy-tailed
        # tails/outputs, mid-generation cancels, seeded top-k
        # sampling) twice: prefix-affinity routed vs round-robin.
        # The geometry makes the structural argument: each replica's
        # page pool is too small to MIRROR every tenant's prefix, so
        # round-robin thrashes the prefix caches (every replica keeps
        # re-prefilling every tenant) while affinity PARTITIONS
        # tenants across replicas and hits stay hits — the aggregate-
        # cache-capacity win that decides TTFT at scale. Virtual time
        # is priced by the same cost stack the placement search uses
        # (simulate_serve_step per step), so goodput-under-SLO
        # (requests meeting both the TTFT and TPOT targets, per
        # second) is deterministic at one seed. Gates (smoke):
        # >= 1.3x affinity/round-robin goodput, every completed
        # request token-identical to ONE reference engine serving the
        # same stream ids (greedy AND sampled), zero recompiles per
        # replica after its own warmup, full page reclamation after
        # drain, and autoscaler decisions that replay identically.
        from flexflow_tpu.serve.router import Autoscaler, ReplicaPool
        from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic
        from flexflow_tpu.utils.profiling import router_report
        from flexflow_tpu.utils.telemetry import Telemetry

        r_ps = 8
        r_cfg = FFConfig(
            batch_size=1, kv_page_size=r_ps, kv_num_pages=1 + 40,
            serve_max_seqs=4, serve_prefill_budget=r_ps,
            serve_spec_decode=False)
        r_ff = build_transformer_lm(
            r_cfg, vocab_size=args.vocab, max_seq_len=128,
            hidden=args.hidden, num_heads=args.heads,
            num_layers=args.layers, ff_dim=4 * args.hidden)
        r_reqs = max(48, args.requests)
        r_replicas = 3

        r_tel = Telemetry()
        pool_aff = ReplicaPool(r_ff, r_replicas, policy="affinity",
                               telemetry=r_tel)
        # every rate/SLO below is a multiple of the PRICED step, so
        # the workload scales with the engine instead of hardcoding
        # seconds (the same simulate_serve_step the search prices)
        price = pool_aff.price_probe(64)
        slo_ttft_s = 6.0 * price   # an affinity hit prefills in ~2
        slo_tpot_s = 2.0 * price   # steps; a cold 80-token prefix
        #                            needs ~10 + queueing
        spec = TrafficSpec(
            requests=r_reqs, seed=args.seed + 1, arrival="poisson",
            rate_rps=0.3 / price, tenants=6, prefix_tokens=80,
            tail_mean=5.0, output_mean=6.0, max_prompt=96,
            max_new_cap=12, cancel_frac=0.06, sample_frac=0.25,
            top_k=4, vocab=args.vocab)
        traffic = make_traffic(spec)

        res_aff = pool_aff.run(traffic, slo_ttft_s=slo_ttft_s,
                               slo_tpot_s=slo_tpot_s,
                               sample_seed=args.seed)
        print(router_report(res_aff, pool_aff.metrics),
              file=sys.stderr)
        pool_aff.assert_zero_recompiles()
        pool_aff.check_drained()

        pool_rr = ReplicaPool(r_ff, r_replicas, policy="round_robin")
        res_rr = pool_rr.run(traffic, slo_ttft_s=slo_ttft_s,
                             slo_tpot_s=slo_tpot_s,
                             sample_seed=args.seed)
        pool_rr.assert_zero_recompiles()
        pool_rr.check_drained()

        # token exactness vs a SINGLE replica serving the same stream
        # ids: completed requests identical, aborted ones a prefix —
        # for every routed arm (routing must never change tokens)
        ref_eng = ServeEngine(r_ff, spec_tokens=0)
        ref_eng.warmup()
        ref = ref_eng.generate(
            [t.prompt for t in traffic],
            [t.max_new for t in traffic],
            temperature=[t.temperature for t in traffic],
            top_k=[t.top_k for t in traffic],
            sample_seed=args.seed,
            stream_ids=[t.stream_id for t in traffic])
        for arm, res in (("affinity", res_aff),
                         ("round_robin", res_rr)):
            for rec, r in zip(res["requests"], ref):
                if rec["outcome"] == "completed":
                    assert rec["tokens"] == r, (
                        f"{arm} stream {rec['stream_id']} diverged "
                        f"from the single-replica reference")
                else:
                    assert rec["tokens"] == r[:len(rec["tokens"])], (
                        f"{arm} aborted stream {rec['stream_id']} is "
                        f"not a reference prefix")
        # traffic-shape sanity: hard under --smoke (the CI seed is
        # pinned), a warning on custom-seed sweeps — a seed whose
        # draws happen not to cancel/sample must not abort the bench
        for ok, msg in (
                (any(rec["sampled"] and rec["outcome"] == "completed"
                     for rec in res_aff["requests"]),
                 "the exactness gate never saw a completed SAMPLED "
                 "stream"),
                (res_aff["cancelled"] > 0,
                 "the cancel path never fired — cancel_frac too low")):
            if not ok:
                assert not args.smoke, msg
                print(f"WARNING: {msg}", file=sys.stderr)

        gain = (res_aff["goodput_per_s"]
                / max(res_rr["goodput_per_s"], 1e-12))
        if gain < 1.3:
            msg = (f"prefix-affinity routing only {gain:.2f}x "
                   f"round-robin goodput-under-SLO (want >= 1.3x)")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)

        # ---- autoscaler: a 1-replica pool under a seeded BURSTY
        # stream must scale up (decisions read only exported gauges,
        # priced by the search's per-degree decode table), emit spans,
        # and REPLAY identically — run twice, compare decision lists
        try:
            from flexflow_tpu.search.serve_place import optimize_serve
            table = optimize_serve(
                pool_rr.replicas[0].engine.serve_arch(), 1,
                config=r_cfg).decode_by_degree
        except Exception:
            table = None
        bspec = TrafficSpec(
            requests=r_reqs, seed=args.seed + 2, arrival="bursty",
            rate_rps=0.15 / price, burst_factor=6.0, tenants=6,
            prefix_tokens=80, tail_mean=5.0, output_mean=8.0,
            max_prompt=96, max_new_cap=16, vocab=args.vocab)
        btraffic = make_traffic(bspec)
        runs = []
        scale_tel = None
        for _trial in range(2):
            scale_tel = Telemetry()
            pool_a = ReplicaPool(r_ff, 1, policy="affinity",
                                 telemetry=scale_tel)
            scaler = Autoscaler(
                pool_a.metrics, slo_ttft_s=slo_ttft_s,
                slo_tpot_s=slo_tpot_s, min_replicas=1,
                max_replicas=2, interval_s=20 * price,
                up_patience=2, down_patience=6,
                cooldown_s=40 * price, decode_table=table,
                tensor_parallel=1,
                decode_lanes=r_cfg.serve_max_seqs)
            res_a = pool_a.run(btraffic, slo_ttft_s=slo_ttft_s,
                               slo_tpot_s=slo_tpot_s,
                               autoscaler=scaler,
                               sample_seed=args.seed)
            pool_a.assert_zero_recompiles()
            pool_a.check_drained()
            runs.append([(round(e["t"], 9), e["direction"],
                          e["replica"]) for e in res_a["scale_events"]])
            pool_a.close()
        assert runs[0] == runs[1], (
            f"autoscaler decisions did not replay: {runs[0]} vs "
            f"{runs[1]}")
        assert runs[0], "the bursty stream never triggered a scale-up"
        scale_spans = [e for e in scale_tel.events
                       if e[0] == "X" and e[2].startswith("scale_")]
        assert scale_spans, "scale events emitted no telemetry spans"

        gates.append(
            f"router_goodput_gain={gain:.2f}x "
            f"(aff {res_aff['goodput_per_s']:.0f}/s att "
            f"{res_aff['slo_attainment']:.2f} vs rr "
            f"{res_rr['goodput_per_s']:.0f}/s att "
            f"{res_rr['slo_attainment']:.2f}), autoscale "
            f"{len(runs[0])} deterministic decisions")

        records.append({
            "metric": "serve_router_goodput_gain",
            "value": round(gain, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": r_reqs,
                "replicas": r_replicas,
                "tenants": spec.tenants,
                "prefix_tokens": spec.prefix_tokens,
                "priced_step_ms": round(price * 1e3, 6),
                "slo_ttft_steps": 6.0, "slo_tpot_steps": 2.0,
                "goodput_affinity_per_s": round(
                    res_aff["goodput_per_s"], 2),
                "goodput_round_robin_per_s": round(
                    res_rr["goodput_per_s"], 2),
                "slo_attainment_affinity": round(
                    res_aff["slo_attainment"], 4),
                "slo_attainment_round_robin": round(
                    res_rr["slo_attainment"], 4),
                # the EXPORTED error-budget attainment gauge (read
                # back from the pool registry, not re-derived from
                # stat strings) + the burn monitor's replayable alert
                # transitions and the pool-level latency attribution
                # fold — what tools/perf_report.py renders from
                "slo_attainment_gauge": round(
                    pool_aff.metrics.gauge("serve_pool_slo_attainment",
                                           1.0), 4),
                "slo_alert_transitions": len(
                    res_aff.get("slo_alerts") or []),
                "latency_attribution_s": {
                    c: round(v, 6) for c, v in
                    (res_aff.get("attribution") or {}).items()},
                "affinity_hits": res_aff["routing"]["affinity_hits"],
                "fallbacks": res_aff["routing"]["fallbacks"],
                "spills": res_aff["routing"]["spills"],
                "cancelled": res_aff["cancelled"],
                "sampled_requests": sum(
                    1 for t in traffic if t.sampled),
                "outputs_match_single_replica": True,
                "zero_recompiles": True,
                "pages_reclaimed": True,
                "compile_counts": pool_aff.compile_counts(),
                "autoscale_events": runs[0],
                "autoscale_priced_by_decode_table": table is not None,
                "virtual_makespan_ms_affinity": round(
                    res_aff["makespan_s"] * 1e3, 4),
                "virtual_makespan_ms_round_robin": round(
                    res_rr["makespan_s"] * 1e3, 4),
            },
        })
        pool_aff.close()
        pool_rr.close()

    if args.workload in ("all", "fabric"):
        # ---- workload 9: wall-clock concurrent serving fabric
        # (tools/ci.sh step 1q, docs/serving.md "Wall-clock mode").
        # The SAME seeded, cancel-free traffic stream serves three
        # times on a 2-replica pool: on the virtual clock (the
        # deterministic authority every other workload gates on), on
        # the threaded wall clock (each replica stepping its session
        # on its own worker thread), and on the single-threaded wall
        # baseline. Sampling keys on stream ids, never on the clock,
        # so all three arms must be TOKEN-IDENTICAL — the property
        # that makes the wall twin debuggable by virtual replay.
        # Goodput-under-SLO becomes a measured wall number; the
        # threaded arm must clear >= 1.3x the single-threaded one
        # (per-step device dwell overlaps across replicas — on a
        # 1-core CI host `dwell_s` models the device time a real
        # accelerator spends off-host, which is exactly the time
        # threading overlaps). The disaggregated cluster rides along:
        # continuous pipelined generation and the --transport tcp
        # loopback socket must both match the phased in-process
        # handoff token-for-token.
        from flexflow_tpu.serve import DisaggCluster
        from flexflow_tpu.serve.router import ReplicaPool
        from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic

        f_ps = 8
        f_cfg = FFConfig(
            batch_size=1, kv_page_size=f_ps, kv_num_pages=1 + 40,
            serve_max_seqs=4, serve_prefill_budget=2 * f_ps,
            serve_spec_decode=False)
        f_ff = build_transformer_lm(
            f_cfg, vocab_size=args.vocab, max_seq_len=128,
            hidden=args.hidden, num_heads=args.heads,
            num_layers=args.layers, ff_dim=4 * args.hidden)
        f_reqs = max(24, args.requests)
        f_replicas = 2
        f_dwell = 0.008           # per-step wall floor (device dwell)
        f_scale = 0.1             # arrival compression: load-bound

        pool_v = ReplicaPool(f_ff, f_replicas, policy="affinity")
        price = pool_v.price_probe(64)
        fspec = TrafficSpec(
            requests=f_reqs, seed=args.seed + 3, arrival="poisson",
            rate_rps=0.3 / price, tenants=4, prefix_tokens=24,
            tail_mean=5.0, output_mean=6.0, max_prompt=64,
            max_new_cap=8, cancel_frac=0.0, sample_frac=0.25,
            top_k=4, vocab=args.vocab)
        ftraffic = make_traffic(fspec)
        step_wall = f_dwell + price        # one dispatched wall step
        f_ttft = 40.0 * step_wall
        f_tpot = 6.0 * step_wall

        def _toks(res):
            return {r["stream_id"]: r["tokens"]
                    for r in res["requests"]}

        res_v = pool_v.run(ftraffic, slo_ttft_s=6.0 * price,
                           slo_tpot_s=2.0 * price,
                           sample_seed=args.seed)
        pool_v.assert_zero_recompiles()
        pool_v.check_drained()
        pool_v.close()

        pool_t = ReplicaPool(f_ff, f_replicas, policy="affinity")
        res_t = pool_t.run(ftraffic, slo_ttft_s=f_ttft,
                           slo_tpot_s=f_tpot, sample_seed=args.seed,
                           wall_clock=True, wall_threads=True,
                           time_scale=f_scale, dwell_s=f_dwell)
        pool_t.assert_zero_recompiles()
        pool_t.check_drained()
        pool_t.close()

        pool_s = ReplicaPool(f_ff, f_replicas, policy="affinity")
        res_s = pool_s.run(ftraffic, slo_ttft_s=f_ttft,
                           slo_tpot_s=f_tpot, sample_seed=args.seed,
                           wall_clock=True, wall_threads=False,
                           time_scale=f_scale, dwell_s=f_dwell)
        pool_s.assert_zero_recompiles()
        pool_s.check_drained()
        pool_s.close()

        # THE identity gate: wall == virtual, token for token, at one
        # seed — threaded interleaving and wall pacing change when
        # steps run, never what they compute
        assert _toks(res_t) == _toks(res_v), (
            "threaded wall-clock run diverged from the virtual-clock "
            "replay of the same traffic")
        assert _toks(res_s) == _toks(res_v), (
            "single-threaded wall-clock run diverged from the "
            "virtual-clock replay")
        assert res_t["clock"] == "wall" and res_t["wall_threads"]
        assert res_s["clock"] == "wall" and not res_s["wall_threads"]

        wall_gain = (res_t["goodput_per_s"]
                     / max(res_s["goodput_per_s"], 1e-12))
        if wall_gain < 1.3:
            msg = (f"threaded wall goodput only {wall_gain:.2f}x the "
                   f"single-threaded baseline (want >= 1.3x)")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)

        # ---- disagg: continuous pipelining + cross-process shipment
        d_cfg = FFConfig(
            batch_size=1, kv_page_size=f_ps, kv_num_pages=1 + 64,
            serve_max_seqs=4, serve_prefill_budget=4 * f_ps,
            serve_spec_decode=False)
        d_ff = build_transformer_lm(
            d_cfg, vocab_size=args.vocab, max_seq_len=128,
            hidden=args.hidden, num_heads=args.heads,
            num_layers=args.layers, ff_dim=4 * args.hidden)
        d_prompts = [list(rng.randint(1, args.vocab,
                                      size=rng.randint(8, 41)))
                     for _ in range(6)]
        d_new = [int(x) for x in rng.randint(2, 7, size=6)]
        d_temps = [0.8 if i % 2 == 0 else None for i in range(6)]
        d_tks = [4 if i % 2 == 0 else None for i in range(6)]
        with DisaggCluster(d_ff) as d_cl:
            d_ref = d_cl.generate(d_prompts, d_new,
                                  temperature=d_temps, top_k=d_tks,
                                  sample_seed=args.seed)
            d_piped = d_cl.generate_pipelined(
                d_prompts, d_new, temperature=d_temps, top_k=d_tks,
                sample_seed=args.seed)
            assert d_piped == d_ref, (
                "pipelined disagg diverged from the phased path")
        d_ff_tcp = build_transformer_lm(
            dataclasses.replace(d_cfg, serve_transport="tcp"),
            vocab_size=args.vocab, max_seq_len=128,
            hidden=args.hidden, num_heads=args.heads,
            num_layers=args.layers, ff_dim=4 * args.hidden)
        with DisaggCluster(d_ff_tcp) as d_cl:
            d_tcp = d_cl.generate_pipelined(
                d_prompts, d_new, temperature=d_temps, top_k=d_tks,
                sample_seed=args.seed)
            assert d_tcp == d_ref, (
                "--transport tcp disagg diverged from the in-process "
                "handoff")
            tcp_stats = dict(d_cl._receiver.stats)
            assert tcp_stats["wire_errors"] == 0
            assert tcp_stats["accepted"] > 0

        gates.append(
            f"fabric_wall_goodput_gain={wall_gain:.2f}x (thr "
            f"{res_t['goodput_per_s']:.1f}/s vs sgl "
            f"{res_s['goodput_per_s']:.1f}/s), wall==virtual, "
            f"pipelined+tcp==inproc")

        records.append({
            "metric": "serve_fabric_wall_goodput_gain",
            "value": round(wall_gain, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": f_reqs,
                "replicas": f_replicas,
                "dwell_ms": round(f_dwell * 1e3, 3),
                "time_scale": f_scale,
                "priced_step_ms": round(price * 1e3, 6),
                "wall_slo_ttft_ms": round(f_ttft * 1e3, 3),
                "wall_slo_tpot_ms": round(f_tpot * 1e3, 3),
                "goodput_wall_threaded_per_s": round(
                    res_t["goodput_per_s"], 2),
                "goodput_wall_single_per_s": round(
                    res_s["goodput_per_s"], 2),
                "goodput_virtual_per_s": round(
                    res_v["goodput_per_s"], 2),
                "slo_attainment_wall_threaded": round(
                    res_t["slo_attainment"], 4),
                "slo_attainment_wall_single": round(
                    res_s["slo_attainment"], 4),
                "wall_makespan_ms_threaded": round(
                    res_t["makespan_s"] * 1e3, 1),
                "wall_makespan_ms_single": round(
                    res_s["makespan_s"] * 1e3, 1),
                "busy_wall_s_threaded": [
                    round(p["busy_wall_s"], 4)
                    for p in res_t["per_replica"]],
                "sampled_requests": sum(
                    1 for t in ftraffic if t.sampled),
                "wall_matches_virtual": True,
                "pipelined_matches_phased": True,
                "tcp_matches_inproc": True,
                "tcp_frames": tcp_stats["frames"],
                "tcp_accepted": tcp_stats["accepted"],
                "tcp_wire_errors": tcp_stats["wire_errors"],
                "zero_recompiles": True,
                "pages_reclaimed": True,
            },
        })

    if args.workload in ("all", "spill"):
        # ---- workload 10: hierarchical host-tier prefix cache A/B
        # (tools/ci.sh step 1r, docs/serving.md "Hierarchical prefix
        # cache"). Long tenant preambles that can never ALL stay HBM-
        # resident (6 tenants x 24 prefix pages vs 40-page pools — one
        # running sequence plus churn always evicts the parked chain
        # head, so a repeat finds nothing matchable in HBM) serve the
        # same seeded traffic on a 2-replica affinity pool
        # three ways: host tier armed (pages evicted under pressure
        # spill their bytes to the SHARED host store and reload
        # through the existing fixed-shape import scatter when the
        # priced DMA beats recompute), plain eviction (identity
        # dropped, prefix recomputed — today's behavior), and
        # rung-3-style no-match (prefix matching off, the degradation
        # ladder's worst case). The reload DMA is priced by
        # TPUMachineModel.host_transfer and rides the SAME virtual
        # clock the steps do (StepEvents.host_reload_s), so the
        # goodput comparison is honest about the transfer cost.
        # Gates (smoke): host tier >= 1.3x goodput-under-SLO over
        # BOTH baselines, every completed request token-identical to
        # one reference engine, zero recompiles after warmup (spill/
        # reload reuse the export/import handoff programs), and
        # spills + priced reload decisions actually happened.
        from flexflow_tpu.serve.router import ReplicaPool
        from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic
        from flexflow_tpu.utils.profiling import router_report

        s_ps = 8
        s_cfg = FFConfig(
            batch_size=1, kv_page_size=s_ps, kv_num_pages=1 + 40,
            serve_max_seqs=2, serve_prefill_budget=s_ps,
            serve_spec_decode=False)
        s_ff = build_transformer_lm(
            s_cfg, vocab_size=args.vocab, max_seq_len=256,
            hidden=args.hidden, num_heads=args.heads,
            num_layers=args.layers, ff_dim=4 * args.hidden)
        s_reqs = max(48, args.requests)
        s_replicas = 2

        def spill_pool(**over):
            return ReplicaPool(
                s_ff, s_replicas, policy="affinity",
                config=dataclasses.replace(s_cfg, **over))

        pool_h = spill_pool(host_tier_mb=8.0)
        assert pool_h.host_tier is not None, (
            "--host-tier-mb did not arm the pool's shared store")
        price = pool_h.price_probe(64)
        # the SLO sits BETWEEN the two repeat paths: a host reload
        # (one priced DMA event + the unshared tail, ~10-12 steps of
        # virtual time) lands inside 15x the probed step price, while
        # recomputing a 24-page preamble (24+ budget-limited prefill
        # steps) cannot — so attainment measures exactly what the
        # tier changes. Arrivals at 0.06/price keep the pool busy
        # without a standing queue: queueing delay is common-mode
        # across the arms and would otherwise wash the gap out.
        slo_ttft_s = 15.0 * price
        slo_tpot_s = 8.0 * price
        sspec = TrafficSpec(
            requests=s_reqs, seed=args.seed + 4, arrival="poisson",
            rate_rps=0.06 / price, tenants=6, prefix_tokens=192,
            tail_mean=5.0, output_mean=5.0, max_prompt=208,
            max_new_cap=8, cancel_frac=0.0, sample_frac=0.25,
            top_k=4, vocab=args.vocab)
        straffic = make_traffic(sspec)

        res_h = pool_h.run(straffic, slo_ttft_s=slo_ttft_s,
                           slo_tpot_s=slo_tpot_s,
                           sample_seed=args.seed)
        print(router_report(res_h, pool_h.metrics), file=sys.stderr)
        pool_h.assert_zero_recompiles()
        pool_h.check_drained()
        host = res_h["host_tier"] or {}

        # per-request priced decisions (the explain_request surface):
        # every decision carries both sides of the price, and at
        # least one chunk chose the DMA over recompute
        priced = [getattr(pool_h._req_refs[sid], "host_reload", None)
                  for sid in pool_h._req_refs]
        priced = [d for d in priced if d]
        for d in priced:
            assert d["dma_s"] >= 0.0 and d["recompute_s"] >= 0.0 \
                and d["chose"] in ("reload", "recompute",
                                   "store_miss"), d
            if d["chose"] == "recompute":
                assert d["dma_s"] >= d["recompute_s"], d

        pool_e = spill_pool(host_tier_mb=0.0)
        res_e = pool_e.run(straffic, slo_ttft_s=slo_ttft_s,
                           slo_tpot_s=slo_tpot_s,
                           sample_seed=args.seed)
        pool_e.assert_zero_recompiles()
        pool_e.check_drained()

        pool_n = spill_pool(serve_prefix_cache=False)
        res_n = pool_n.run(straffic, slo_ttft_s=slo_ttft_s,
                           slo_tpot_s=slo_tpot_s,
                           sample_seed=args.seed)
        pool_n.assert_zero_recompiles()
        pool_n.check_drained()

        # token identity: spilling a page to host RAM and importing
        # it back must never change a single emitted token, in any
        # arm — completed requests identical to ONE reference engine
        # serving the same stream ids, aborted ones a prefix
        ref_eng = ServeEngine(s_ff, spec_tokens=0)
        ref_eng.warmup()
        ref = ref_eng.generate(
            [t.prompt for t in straffic],
            [t.max_new for t in straffic],
            temperature=[t.temperature for t in straffic],
            top_k=[t.top_k for t in straffic],
            sample_seed=args.seed,
            stream_ids=[t.stream_id for t in straffic])
        for arm, res in (("host_tier", res_h), ("evict", res_e),
                         ("no_match", res_n)):
            for rec, r in zip(res["requests"], ref):
                if rec["outcome"] == "completed":
                    assert rec["tokens"] == r, (
                        f"{arm} stream {rec['stream_id']} diverged "
                        f"from the single-engine reference")
                else:
                    assert rec["tokens"] == r[:len(rec["tokens"])], (
                        f"{arm} aborted stream {rec['stream_id']} is "
                        f"not a reference prefix")

        # structural gates: the tier must actually have been
        # exercised — pressure spilled pages, and at least one
        # admission priced the DMA cheaper and reloaded
        for ok, msg in (
                (host.get("spills", 0) > 0,
                 "the host tier never spilled — the pool is not "
                 "under pressure"),
                (host.get("reload_pages", 0) > 0,
                 "no page was ever reloaded from the host tier"),
                (any(d["chose"] == "reload" for d in priced),
                 "no admission ever priced the reload cheaper than "
                 "recompute")):
            if not ok:
                assert not args.smoke, msg
                print(f"WARNING: {msg}", file=sys.stderr)

        gain_e = (res_h["goodput_per_s"]
                  / max(res_e["goodput_per_s"], 1e-12))
        gain_n = (res_h["goodput_per_s"]
                  / max(res_n["goodput_per_s"], 1e-12))
        gain = min(gain_e, gain_n)
        if gain < 1.3:
            msg = (f"host tier only {gain:.2f}x goodput-under-SLO "
                   f"(vs eviction {gain_e:.2f}x, vs no-match "
                   f"{gain_n:.2f}x; want >= 1.3x over both)")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)

        gates.append(
            f"host_tier_goodput={gain:.2f}x>=1.3x (evict "
            f"{gain_e:.2f}x, no-match {gain_n:.2f}x), "
            f"{host.get('spills', 0)} spills / "
            f"{host.get('reload_pages', 0)} reloaded pages, exact, "
            f"0 recompiles")

        records.append({
            "metric": "serve_host_tier_goodput_gain",
            "value": round(gain, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": s_reqs,
                "replicas": s_replicas,
                "tenants": sspec.tenants,
                "prefix_tokens": sspec.prefix_tokens,
                "hbm_pages_per_replica": s_cfg.kv_num_pages - 1,
                "host_tier_mb": 8.0,
                "priced_step_ms": round(price * 1e3, 6),
                "goodput_host_tier_per_s": round(
                    res_h["goodput_per_s"], 2),
                "goodput_evict_per_s": round(
                    res_e["goodput_per_s"], 2),
                "goodput_no_match_per_s": round(
                    res_n["goodput_per_s"], 2),
                "gain_vs_evict": round(gain_e, 2),
                "gain_vs_no_match": round(gain_n, 2),
                "slo_attainment_host_tier": round(
                    res_h["slo_attainment"], 4),
                "slo_attainment_evict": round(
                    res_e["slo_attainment"], 4),
                "slo_attainment_no_match": round(
                    res_n["slo_attainment"], 4),
                "host_spills": host.get("spills", 0),
                "host_reload_pages": host.get("reload_pages", 0),
                "host_recompute_chosen": host.get(
                    "recompute_chosen", 0),
                "host_evictions": host.get("evictions", 0),
                "host_reload_priced_ms": round(
                    host.get("reload_priced_s", 0.0) * 1e3, 4),
                "router_host_hits": res_h["routing"].get(
                    "host_hits", 0),
                "priced_decisions": len(priced),
                "outputs_match_reference": True,
                "zero_recompiles": True,
                "pages_reclaimed": True,
                "compile_counts": pool_h.compile_counts(),
            },
        })
        pool_h.close()
        pool_e.close()
        pool_n.close()

    if args.workload in ("all", "telemetry"):
        # ---- workload 6: telemetry on/off A/B (tools/ci.sh step 1k).
        # The observability contract (docs/observability.md): a
        # telemetry-on engine must produce bit-identical tokens with
        # zero recompiles at <= 3% wall overhead (all recording is
        # host-side — min of paired order-alternating on/off block
        # ratios, hard-gated under --smoke), the exported Chrome
        # trace must load with
        # well-formed per-request/per-step tracks, the Prometheus text
        # must parse, the metrics snapshot must carry the required
        # latency/robustness keys, and the drift calibrator must have
        # priced every serve regime it measured.
        import re
        from flexflow_tpu.utils.telemetry import Telemetry
        t_new = max(16, min(args.max_new, args.max_seq_len - 24))
        t_hi = args.max_seq_len - t_new
        tprompts = [list(rng.randint(1, args.vocab,
                                     size=rng.randint(4, t_hi + 1)))
                    for _ in range(args.requests)]
        eng_off = ServeEngine(ff)
        cnt_off = eng_off.warmup()
        tel = Telemetry()
        eng_on = ServeEngine(ff, telemetry=tel)
        cnt_on = eng_on.warmup()
        # Overhead statistic: the MINIMUM of paired on/off BLOCK
        # ratios — each block times GENS_PER_BLOCK back-to-back
        # generates per arm, adjacent in time and order-alternating.
        # Rationale: per-run jitter on a shared 2-core CI host is
        # +-10% at this ~200ms scale (measured), an order of magnitude
        # above the ~0.5% true recording cost, so no median/mean of
        # pair ratios resolves a 3% gate reliably. A REGRESSION in
        # recording cost shifts EVERY block ratio up uniformly, so the
        # cleanest-block minimum still detects it — while a one-sided
        # noise spike (scheduler, page cache) can no longer flap the
        # gate. The blocks average jitter internally; the min bounds
        # the intrinsic overhead from above under the least
        # interference observed (the repo's best-of-N convention for
        # this host, cf. search_bench). Block 0 also absorbs the
        # on-arm's one-time per-ctx-bucket drift predictions.
        GENS_PER_BLOCK = 3
        blocks = 5
        best_off = best_on = float("inf")
        ratios = []
        out_on = out_off = None
        for i in range(blocks):
            arms = ("off", "on") if i % 2 == 0 else ("on", "off")
            d = {}
            for arm in arms:
                t0 = time.perf_counter()
                for _ in range(GENS_PER_BLOCK):
                    if arm == "off":
                        out_off = eng_off.generate(tprompts, t_new)
                    else:
                        out_on = eng_on.generate(tprompts, t_new)
                d[arm] = time.perf_counter() - t0
            best_off = min(best_off, d["off"] / GENS_PER_BLOCK)
            best_on = min(best_on, d["on"] / GENS_PER_BLOCK)
            ratios.append(d["on"] / d["off"])
        assert out_on == out_off, (
            "telemetry-on outputs diverged from telemetry-off — "
            "recording must be pure observation")
        assert eng_on.compile_counts() == cnt_on and \
            eng_off.compile_counts() == cnt_off, (
                f"telemetry A/B recompiled: {cnt_on} -> "
                f"{eng_on.compile_counts()}")
        overhead = min(ratios)

        # metrics snapshot: the keys the router/autoscaler and the
        # perf trajectory depend on must all be present
        snap = tel.metrics_snapshot()
        met = snap["metrics"]
        for key in ("serve_tokens_generated_total",
                    "serve_engine_steps_total",
                    "serve_decode_steps_total",
                    "serve_prompt_tokens_total",
                    "serve_prefill_tokens_computed_total",
                    "serve_prefix_hit_tokens_total",
                    "serve_preemptions_total", "serve_retries_total",
                    'serve_requests_total{outcome="completed"}',
                    'serve_rung_steps_total{rung="0"}'):
            assert key in met["counters"], f"missing counter {key}"
        for key in ("serve_tokens_per_sec", "serve_pool_occupancy_peak",
                    "serve_prefix_hit_rate", "serve_spec_acceptance"):
            assert key in met["gauges"], f"missing gauge {key}"
        for key in ("serve_ttft_seconds", "serve_tpot_seconds",
                    "serve_request_latency_seconds"):
            assert key in met["histograms"], f"missing histogram {key}"

        # Prometheus text parses line by line
        line_re = re.compile(
            r'^(# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*'
            r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9eE.+-]+'
            r'|(nan|inf))$')
        for line in tel.to_prometheus().splitlines():
            if line:
                assert line_re.match(line), (
                    f"unparseable Prometheus line: {line!r}")

        # drift: every measured serve regime priced, ratios computed
        drift = snap["drift"]
        assert drift.get("serve"), "no serve drift regimes recorded"
        for reg, d in drift["serve"].items():
            assert d["count"] > 0 and d["predicted_ms_per_step"] > 0 \
                and d["measured_ms_per_step"] > 0, (reg, d)

        # Chrome trace: loads, well-formed, request + step tracks
        trace_path = (args.trace_out
                      or "/tmp/flexflow_tpu_serve_trace.json")
        tel.export_chrome_trace(trace_path)
        with open(trace_path) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert evs, "empty trace"
        for ev in evs:
            assert ev["ph"] in ("X", "i", "M", "C", "b", "e"), ev
            assert isinstance(ev["pid"], int) \
                and isinstance(ev["tid"], int), ev
            if ev["ph"] != "M":
                assert isinstance(ev["ts"], (int, float)) \
                    and ev["ts"] >= 0, ev
            if ev["ph"] == "X":
                assert isinstance(ev["dur"], (int, float)) \
                    and ev["dur"] >= 0, ev
        threads = {ev["args"]["name"] for ev in evs
                   if ev["ph"] == "M" and ev["name"] == "thread_name"}
        assert "engine" in threads and any(
            t.startswith("slot ") for t in threads), threads

        if overhead > 1.03:
            msg = (f"telemetry overhead {overhead:.4f}x > 1.03x "
                   f"(min paired block ratio, {blocks} blocks of "
                   f"{GENS_PER_BLOCK}; best on {best_on*1e3:.1f} ms "
                   f"vs off {best_off*1e3:.1f} ms per generate; "
                   f"ratios {[round(r, 3) for r in sorted(ratios)]})")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(f"telemetry_overhead={overhead:.4f}x<=1.03x "
                     f"trace+metrics+drift valid")
        print(tel.drift_report(), file=sys.stderr)

        records.append({
            "metric": "serve_telemetry_overhead",
            "value": round(overhead, 4),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": args.requests,
                "max_new_tokens": t_new,
                "blocks": blocks,
                "gens_per_block": GENS_PER_BLOCK,
                "paired_block_ratios": [round(r, 4) for r in ratios],
                "wall_ms_off": round(best_off * 1e3, 3),
                "wall_ms_on": round(best_on * 1e3, 3),
                "outputs_identical": True,
                "compile_counts": eng_on.compile_counts(),
                "trace_path": trace_path,
                "trace_events": len(evs),
                "events_buffered": snap["events_buffered"],
                "ttft_ms_p50": round(tel.metrics.quantile(
                    "serve_ttft_seconds", 50) * 1e3, 4),
                "ttft_ms_p99": round(tel.metrics.quantile(
                    "serve_ttft_seconds", 99) * 1e3, 4),
                "tpot_ms_p50": round(tel.metrics.quantile(
                    "serve_tpot_seconds", 50) * 1e3, 4),
                "tpot_ms_p99": round(tel.metrics.quantile(
                    "serve_tpot_seconds", 99) * 1e3, 4),
                "drift_ratio_by_regime": {
                    reg: round(d["ratio"], 2)
                    for reg, d in drift["serve"].items()},
            },
        })

    # ---- workload: batched LoRA pool vs sequential weight swap ------
    if args.workload in ("all", "lora"):
        from flexflow_tpu.serve.adapters import (
            make_tenant_adapters, merge_adapter_params)
        TENANTS = 4                       # adapters; tenant 0 = base
        lora_rank = 4
        lora_reqs = max(args.requests, 12)
        lora_new = args.max_new
        head_dim = args.hidden // args.heads

        def lora_cfg(rank):
            return FFConfig(
                batch_size=1, kv_page_size=args.page_size,
                kv_num_pages=1 + pages_per_seq * args.max_seqs,
                serve_max_seqs=args.max_seqs,
                serve_prefill_budget=max(args.page_size,
                                         args.max_seq_len // 2),
                adapter_rank=rank)

        def lora_engine(rank):
            m = build_transformer_lm(
                lora_cfg(rank), vocab_size=args.vocab,
                max_seq_len=args.max_seq_len, hidden=args.hidden,
                num_heads=args.heads, num_layers=args.layers,
                ff_dim=4 * args.hidden)
            # speculation off in both arms: the A/B measures tenant
            # batching, and drafts would skew the step counts
            return ServeEngine(m, spec_tokens=0)

        adapters = make_tenant_adapters(
            num_layers=args.layers, hidden=args.hidden,
            num_heads=args.heads, head_dim=head_dim,
            ff_dim=4 * args.hidden, rank=lora_rank, tenants=TENANTS,
            seed=args.seed + 5)
        # Zipf-skewed tenant mix over 0..TENANTS (0 = base lanes), the
        # traffic-harness shape (serve/traffic.py): a few tenants
        # dominate, the tail churns the pool
        w = np.array([1.0 / (t + 1) ** 1.1 for t in range(TENANTS + 1)])
        w /= w.sum()
        tenant_mix = [int(rng.choice(TENANTS + 1, p=w))
                      for _ in range(lora_reqs)]
        if len(set(tenant_mix) - {0}) < 3:   # the gate needs >= 3
            tenant_mix[:3] = [1, 2, 3]       # adapters in one batch
        prompt_cap = max(9, (args.max_seq_len - lora_new) // 2)
        lora_prompts = [list(rng.randint(
            1, args.vocab, size=rng.randint(8, prompt_cap)))
            for _ in range(lora_reqs)]

        # arm A: ONE engine, every tenant batched through the adapter
        # pool in the one mixed program
        eng_a = lora_engine(lora_rank)
        counts_a = eng_a.warmup()
        for t, (wts, sc) in adapters.items():
            eng_a.register_adapter(t, wts, scale=sc)
        t0 = time.perf_counter()
        out_a = eng_a.generate(lora_prompts, lora_new,
                               tenant_ids=tenant_mix)
        wall_a = time.perf_counter() - t0
        st_a = eng_a.last_stats
        print(serve_report(st_a), file=sys.stderr)
        assert eng_a.compile_counts() == counts_a, (
            f"lora batched arm recompiled: "
            f"{counts_a} -> {eng_a.compile_counts()}")
        eng_a.cache.check_invariants()
        eng_a.adapters.check_invariants()

        # arm B: a weight-swap server — serve tenants SEQUENTIALLY,
        # merging each tenant's delta into the weights (same shapes,
        # so the swap itself never recompiles) and flushing the
        # prefix cache between tenants (unsalted tenant-0 chains
        # would otherwise serve one tenant another's pages)
        eng_b = lora_engine(0)
        counts_b = eng_b.warmup()
        base_params = eng_b.params
        merged = {0: base_params}
        for t, (wts, sc) in adapters.items():
            merged[t] = merge_adapter_params(base_params, wts, sc)
        out_b = [None] * lora_reqs
        steps_b = 0
        wall_b = 0.0
        for t in sorted(set(tenant_mix)):
            idxs = [i for i, ti in enumerate(tenant_mix) if ti == t]
            eng_b.params = eng_b._step_params = merged[t]
            eng_b.cache.clear_prefix()
            t0 = time.perf_counter()
            group = eng_b.generate([lora_prompts[i] for i in idxs],
                                   lora_new)
            wall_b += time.perf_counter() - t0
            steps_b += eng_b.last_stats["steps"]
            for i, o in zip(idxs, group):
                out_b[i] = o
        eng_b.params = eng_b._step_params = base_params
        assert eng_b.compile_counts() == counts_b, (
            f"lora swap arm recompiled: "
            f"{counts_b} -> {eng_b.compile_counts()}")

        # exactness: both arms equal the per-tenant merged-weight
        # references (the swap arm IS the merged server, so arm A ==
        # arm B is the tenant-isolation gate)
        assert out_a == out_b, (
            "batched adapter serving diverged from the weight-swap "
            "server")
        for i in (0, 1, 2, lora_reqs - 1):
            eng_b.params = merged[tenant_mix[i]]
            ref = eng_b.generate_reference([lora_prompts[i]],
                                           [lora_new])[0]
            assert out_a[i] == ref, (
                f"request {i} (tenant {tenant_mix[i]}) diverged from "
                f"its merged-weight reference")
        eng_b.params = base_params

        steps_a = st_a["steps"]
        gain = steps_b / max(steps_a, 1)
        wall_gain = wall_b / max(wall_a, 1e-9)
        if gain < 1.5:
            msg = (f"lora goodput gain {gain:.2f}x < 1.5x "
                   f"(batched {steps_a} steps vs swap {steps_b})")
            assert not args.smoke, msg
            print(f"WARNING: {msg}", file=sys.stderr)
        gates.append(f"lora_goodput={gain:.2f}x>=1.5x exact "
                     f"0 recompiles")

        pool = st_a["adapter_pool"]
        records.append({
            "metric": "serve_lora_goodput_gain",
            "value": round(gain, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "requests": lora_reqs,
                "max_new_tokens": lora_new,
                "tenants": TENANTS,
                "adapter_rank": lora_rank,
                "adapter_slots": pool["usable_slots"],
                "steps_batched": steps_a,
                "steps_swap": steps_b,
                "wall_gain": round(wall_gain, 2),
                "wall_ms_batched": round(wall_a * 1e3, 1),
                "wall_ms_swap": round(wall_b * 1e3, 1),
                "adapter_loads": pool["loads"],
                "adapter_hits": pool["hits"],
                "adapter_evictions": pool["evictions"],
                "outputs_identical": True,
                "compile_counts": eng_a.compile_counts(),
            },
        })

    # ---------------- workload: cold vs warm replica boot --------------
    if args.workload in ("all", "boot"):
        # A/B the tentpole claim of the program registry
        # (core/programs.py): an engine whose --program-cache-dir holds
        # an AOT executable snapshot for its fingerprint must reach
        # first-token-ready >= 2x faster than a cold one, compile
        # NOTHING (compile_counts() all zero, the warm-boot contract),
        # and produce token-identical greedy output. The cold arm runs
        # FIRST so nothing (the registry's jax persistent-cache arming
        # included) can warm XLA under it.
        import glob
        import shutil
        import tempfile
        import warnings as _warnings

        boot_prompts = [list(rng.randint(1, args.vocab, size=12))
                        for _ in range(4)]
        boot_new = max(4, min(8, args.max_new))

        def _boot_arm(cache_dir):
            """(engine, seconds-to-ready, greedy outputs): construction
            + warmup is the time a scale-up waits before the replica
            can serve — the number the autoscaler's boot_s prices."""
            bcfg = dataclasses.replace(cfg,
                                       program_cache_dir=cache_dir)
            t0 = time.perf_counter()
            eng = ServeEngine(ff, config=bcfg)
            eng.warmup()
            ready_s = time.perf_counter() - t0
            out = eng.generate(boot_prompts, boot_new)
            return eng, ready_s, out

        eng_cold, cold_s, out_cold = _boot_arm(None)
        assert sum(eng_cold.compile_counts().values()) > 0, (
            "cold arm compiled nothing — the A/B is vacuous")

        boot_dir = tempfile.mkdtemp(prefix="ffprog_boot_")
        try:
            # populate: the first engine over this (fingerprint, dir)
            # compiles and writes the snapshot back (warmup's
            # read-through write-back)
            eng_pop, _, _ = _boot_arm(boot_dir)
            eng_pop.close()
            eng_warm, warm_s, out_warm = _boot_arm(boot_dir)
            warm_counts = eng_warm.compile_counts()
            assert sum(warm_counts.values()) == 0, (
                f"warm arm compiled: {warm_counts} (expected zero — "
                f"every program should deserialize from the snapshot)")
            assert out_warm == out_cold, (
                "warm-boot outputs diverged from the in-process cold "
                "engine (the snapshot must be bit-identical)")
            restored = int(eng_warm.boot_stats["restored"])
            assert restored > 0 and eng_warm.boot_stats["warm"], (
                f"warm arm restored nothing: {eng_warm.boot_stats}")
            speedup = cold_s / max(warm_s, 1e-9)
            if speedup < 2.0:
                msg = (f"warm-boot speedup {speedup:.2f}x < 2x "
                       f"(cold {cold_s:.2f}s vs warm {warm_s:.2f}s)")
                assert not args.smoke, msg
                print(f"WARNING: {msg}", file=sys.stderr)

            # stale-cache rejection: a corrupt/truncated store must
            # fall back to compile-with-warning, never crash (the
            # cost_cache.py corrupt-store discipline)
            (store,) = glob.glob(os.path.join(boot_dir, "*.ffprog"))
            with open(store, "wb") as f:
                f.write(b"not a program snapshot")
            with _warnings.catch_warnings(record=True) as wlog:
                _warnings.simplefilter("always")
                eng_bad, _, out_bad = _boot_arm(boot_dir)
            assert any("program cache" in str(w.message)
                       for w in wlog), (
                "corrupt store produced no fallback warning")
            assert sum(eng_bad.compile_counts().values()) > 0, (
                "corrupt store arm compiled nothing — fallback "
                "did not recompile")
            assert out_bad == out_cold, (
                "corrupt-store fallback diverged from the cold engine")
            eng_bad.close()
            eng_warm.close()
        finally:
            shutil.rmtree(boot_dir, ignore_errors=True)
        eng_cold.close()

        gates.append(f"boot_warm={speedup:.1f}x>=2x "
                     f"{restored} restored 0 warm compiles exact "
                     f"corrupt-fallback")
        records.append({
            "metric": "serve_boot_warm_speedup",
            "value": round(speedup, 2),
            "unit": "x",
            "extra": {
                "platform": jax.default_backend(),
                "cold_ready_s": round(cold_s, 3),
                "warm_ready_s": round(warm_s, 3),
                "programs_restored": restored,
                "cold_compile_s": round(
                    float(eng_cold.boot_stats["compile_s"]), 3),
                "warm_compile_counts": warm_counts,
                "outputs_identical": True,
                "corrupt_fallback": True,
            },
        })

    if args.workload in ("all", "mesh2d"):
        # ---- workload 11: 2-D serve-mesh placement A/B (tools/ci.sh
        # step 1t, docs/search.md "2-D serve mesh"). ONE search prices
        # tensor degree x replica count x HBM residency into goodput-
        # under-SLO, and a pool BOOTED from the searched (t, r) must
        # beat both degenerate allocations of the SAME device budget:
        # tp-only (t=N, r=1 — all silicon on latency, no capacity, so
        # arrivals queue past the TTFT SLO) and replicas-only (t=1,
        # r=N — the model does not FIT one device, so every virtual
        # step pays the reference 1ms/MB over-capacity penalty and
        # blows the TPOT SLO). The HBM squeeze is constructed: a
        # machine file pins capacity BETWEEN the t=2 and t=1 per-
        # device residency, so the search REJECTS t=1 up front (never
        # priced, recorded with its residency) while the measured
        # t=1 arm demonstrates what the rejection predicted. Tenants
        # share prefixes and the LoRA adapter pool is armed in every
        # arm. Gates (smoke): >= 1.3x goodput-under-SLO vs BOTH
        # baselines, t=1 infeasible (not a table row), every arm
        # token-identical to ONE reference engine (greedy AND
        # sampled), zero recompiles per replica after warmup.
        import tempfile

        from flexflow_tpu.search.cost_model import serve_device_bytes
        from flexflow_tpu.search.machine_model import \
            default_machine_model
        from flexflow_tpu.search.serve_place import (MeshTraffic,
                                                     mesh_cell_metrics,
                                                     optimize_serve_mesh,
                                                     price_mesh_step)
        from flexflow_tpu.serve.adapters import make_tenant_adapters
        from flexflow_tpu.serve.engine import probe_serve_arch
        from flexflow_tpu.serve.router import ReplicaPool
        from flexflow_tpu.serve.traffic import TrafficSpec, make_traffic
        from flexflow_tpu.utils.profiling import router_report

        if len(jax.devices()) < 4:
            print("mesh2d workload skipped: needs >= 4 devices "
                  f"(have {len(jax.devices())})", file=sys.stderr)
        else:
            m_devices = 4
            m_ps = 8
            m_hidden = max(64, args.hidden)
            m_rank = 4
            m_cfg = FFConfig(
                batch_size=1, kv_page_size=m_ps, kv_num_pages=1 + 40,
                serve_max_seqs=4, serve_prefill_budget=m_ps,
                serve_spec_decode=False, adapter_rank=m_rank)
            m_ff = build_transformer_lm(
                m_cfg, vocab_size=args.vocab, max_seq_len=128,
                hidden=m_hidden, num_heads=args.heads,
                num_layers=args.layers, ff_dim=4 * m_hidden)
            m_arch = probe_serve_arch(m_ff, m_cfg)
            # the squeeze, at the engine's WORST-case context so no
            # runtime ctx bucket can put the sharded arms over budget
            worst = dataclasses.replace(m_arch, context=128)
            m_b1 = serve_device_bytes(worst, 1)
            m_b2 = serve_device_bytes(worst, 2)
            hbm = m_b2 + 0.05 * (m_b1 - m_b2)
            mm_path = os.path.join(
                tempfile.mkdtemp(prefix="ffmesh_"), "machine.json")
            with open(mm_path, "w") as f:
                json.dump({"hbm_capacity": hbm}, f)
            m_cfg.machine_model_file = mm_path

            # the search's traffic model, scaled off ITS OWN step
            # price (the same simulate_serve_step the pool's virtual
            # clock uses): arrival 1.6x one sharded replica's priced
            # capacity, so every r=1 cell saturates (queueing blows
            # the TTFT SLO in the M/D/c term) and a multi-replica
            # cell is the only way to goodput
            m_mm = default_machine_model(machine_file=mm_path)
            d2, p2, x2 = price_mesh_step(m_arch, 2, m_mm)
            cap1 = mesh_cell_metrics(
                m_arch, 2, 1, d2, p2, x2,
                MeshTraffic(arrival_rps=1.0))["capacity_rps"]
            m_model_traffic = MeshTraffic(
                arrival_rps=1.6 * cap1, prefix_hit=0.5,
                requests_per_preamble=8.0,
                slo_ttft_s=60.0 * p2, slo_tpot_s=2.5 * x2)
            place = optimize_serve_mesh(
                m_arch, m_devices, config=m_cfg,
                traffic=m_model_traffic, seed=args.seed)
            assert [d["tensor"] for d in place.infeasible] == [1], (
                f"expected exactly t=1 HBM-rejected, got "
                f"{place.infeasible}")
            assert all(t != 1 for (t, _r) in place.table), (
                "a rejected degree leaked into the price table")
            assert place.replicas >= 2, (
                f"search kept one replica (t={place.tensor_parallel} "
                f"r={place.replicas}) — the saturation geometry is "
                f"broken")
            print(f"mesh2d searched placement: "
                  f"t={place.tensor_parallel} x r={place.replicas} "
                  f"goodput {place.goodput_per_s:.1f}/s "
                  f"(vs tp-only {place.goodput_gain_vs_tensor_only():.2f}x)",
                  file=sys.stderr)

            m_adapters = make_tenant_adapters(
                num_layers=args.layers, hidden=m_hidden,
                num_heads=args.heads,
                head_dim=m_hidden // args.heads,
                ff_dim=4 * m_hidden, rank=m_rank, tenants=3,
                seed=args.seed + 9)

            def m_pool(t, r):
                p = ReplicaPool(m_ff, r, policy="affinity",
                                engine_kwargs={"tensor_parallel": t})
                for ten, (w, sc) in sorted(m_adapters.items()):
                    p.register_adapter(ten, w, scale=sc)
                return p

            pool_mesh = m_pool(place.tensor_parallel, place.replicas)
            assert len(pool_mesh.replicas) == place.replicas
            assert all(r.engine.tp == place.tensor_parallel
                       for r in pool_mesh.replicas)
            # SLO targets and arrival rate as multiples of the
            # SEARCHED arm's priced step — identical across arms, so
            # the A/B measures the allocation, not the yardstick
            price = pool_mesh.price_probe(64)
            m_slo_ttft = 20.0 * price
            m_slo_tpot = 2.5 * price
            m_reqs = max(40, args.requests)
            m_spec = TrafficSpec(
                requests=m_reqs, seed=args.seed + 1,
                arrival="poisson", rate_rps=0.15 / price, tenants=4,
                prefix_tokens=48, tail_mean=4.0, output_mean=6.0,
                max_prompt=80, max_new_cap=10, sample_frac=0.25,
                top_k=4, vocab=args.vocab)
            m_traffic = make_traffic(m_spec)

            arm_shapes = {
                "searched": (place.tensor_parallel, place.replicas),
                "tp_only": (m_devices, 1),
                "replicas_only": (1, m_devices),
            }
            m_res = {}
            for arm, (t, r) in arm_shapes.items():
                p = pool_mesh if arm == "searched" else m_pool(t, r)
                m_res[arm] = p.run(m_traffic, slo_ttft_s=m_slo_ttft,
                                   slo_tpot_s=m_slo_tpot,
                                   sample_seed=args.seed)
                p.assert_zero_recompiles()
                p.check_drained()
                if arm == "searched":
                    print(router_report(m_res[arm], p.metrics),
                          file=sys.stderr)
                else:
                    p.close()

            # token identity vs ONE reference engine serving the same
            # stream ids with the same armed adapters: the allocation
            # must never change tokens (completed exact, aborted a
            # prefix) — in every arm, sharded and penalized alike
            ref_eng = ServeEngine(m_ff, spec_tokens=0)
            ref_eng.warmup()
            for ten, (w, sc) in sorted(m_adapters.items()):
                ref_eng.register_adapter(ten, w, scale=sc)
            ref = ref_eng.generate(
                [t.prompt for t in m_traffic],
                [t.max_new for t in m_traffic],
                temperature=[t.temperature for t in m_traffic],
                top_k=[t.top_k for t in m_traffic],
                sample_seed=args.seed,
                stream_ids=[t.stream_id for t in m_traffic],
                tenant_ids=[t.tenant for t in m_traffic])
            for arm, res in m_res.items():
                for rec, rtoks in zip(res["requests"], ref):
                    if rec["outcome"] == "completed":
                        assert rec["tokens"] == rtoks, (
                            f"{arm} stream {rec['stream_id']} "
                            f"diverged from the reference engine")
                    else:
                        assert rec["tokens"] == \
                            rtoks[:len(rec["tokens"])], (
                                f"{arm} aborted stream "
                                f"{rec['stream_id']} is not a "
                                f"reference prefix")
            assert any(rec["sampled"] and rec["outcome"] == "completed"
                       for rec in m_res["searched"]["requests"]), (
                "the exactness gate never saw a completed SAMPLED "
                "stream")

            g_tp = (m_res["searched"]["goodput_per_s"]
                    / max(m_res["tp_only"]["goodput_per_s"], 1e-9))
            g_rep = (m_res["searched"]["goodput_per_s"]
                     / max(m_res["replicas_only"]["goodput_per_s"],
                           1e-9))
            gain = min(g_tp, g_rep)
            if gain < 1.3:
                msg = (f"searched (t={place.tensor_parallel}, "
                       f"r={place.replicas}) only {gain:.2f}x the "
                       f"degenerate baselines (tp-only {g_tp:.2f}x, "
                       f"replicas-only {g_rep:.2f}x; want >= 1.3x "
                       f"both)")
                assert not args.smoke, msg
                print(f"WARNING: {msg}", file=sys.stderr)
            gates.append(
                f"mesh2d_goodput={gain:.2f}x>=1.3x "
                f"(t={place.tensor_parallel} r={place.replicas}: "
                f"{m_res['searched']['goodput_per_s']:.0f}/s vs "
                f"tp-only {m_res['tp_only']['goodput_per_s']:.0f}/s, "
                f"replicas-only "
                f"{m_res['replicas_only']['goodput_per_s']:.0f}/s) "
                f"t=1 HBM-rejected exact 0 recompiles")

            records.append({
                "metric": "serve_mesh2d_goodput_gain",
                "value": round(gain, 2),
                "unit": "x",
                "extra": {
                    "platform": jax.default_backend(),
                    "requests": m_reqs,
                    "devices": m_devices,
                    "searched_tensor": place.tensor_parallel,
                    "searched_replicas": place.replicas,
                    "searched_goodput_per_s": round(
                        m_res["searched"]["goodput_per_s"], 2),
                    "tp_only_goodput_per_s": round(
                        m_res["tp_only"]["goodput_per_s"], 2),
                    "replicas_only_goodput_per_s": round(
                        m_res["replicas_only"]["goodput_per_s"], 2),
                    "gain_vs_tp_only": round(g_tp, 2),
                    "gain_vs_replicas_only": round(g_rep, 2),
                    "slo_attainment_searched": round(
                        m_res["searched"]["slo_attainment"], 4),
                    "priced_step_ms": round(price * 1e3, 6),
                    "slo_ttft_steps": 20.0, "slo_tpot_steps": 2.5,
                    "hbm_capacity_bytes": round(hbm),
                    "device_bytes_t1": round(m_b1),
                    "device_bytes_t2": round(m_b2),
                    "infeasible_degrees": [
                        d["tensor"] for d in place.infeasible],
                    "model_goodput_per_s": round(
                        place.goodput_per_s, 2),
                    "model_gain_vs_tensor_only": round(
                        place.goodput_gain_vs_tensor_only(), 2),
                    "search_table_cells": len(place.table),
                    "adapter_rank": m_rank,
                    "tenants": m_spec.tenants,
                    "prefix_tokens": m_spec.prefix_tokens,
                    "outputs_match_reference": True,
                    "zero_recompiles": True,
                    "compile_counts": pool_mesh.compile_counts(),
                },
            })
            pool_mesh.close()

    print("\n".join(json.dumps(r) for r in records))
    if args.out:
        # merge-by-metric JSONL through the ONE shared writer
        # (tools/_bench_io.py, the format BENCH_search.json shares):
        # a partial --workload run refreshes ITS lines without
        # deleting the other workloads' records, tolerating
        # individually corrupt lines in the old artifact
        from _bench_io import write_records
        write_records(args.out, records)
    if args.smoke:
        print(f"serve smoke OK: {'; '.join(gates)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
