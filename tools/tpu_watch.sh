#!/bin/bash
# Detached TPU-uptime watcher: probe every ~2.5 min; on the first
# successful probe, run the full on-chip session (tools/tpu_session.sh)
# and exit. Transcript: evidence/ (session) + .scratch/tpu_watch.log
# (probe loop). Start with:
#   nohup setsid bash tools/tpu_watch.sh > .scratch/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
mkdir -p .scratch
for i in $(seq 1 288); do  # up to 12h at the fast cadence
  echo "[watch $(date -u +%FT%TZ)] probe $i"
  if timeout 90 env JAX_PLATFORMS=tpu python -c \
      "import jax; d=jax.devices()[0]; assert d.platform=='tpu'; print('TPU', d.device_kind)"; then
    echo "[watch $(date -u +%FT%TZ)] TPU UP — running full session"
    bash tools/tpu_session.sh
    echo "[watch $(date -u +%FT%TZ)] session done rc=$?"
    touch .scratch/tpu_session_complete
    # secure the artifacts even if the interactive session has ended:
    # evidence transcripts + refreshed sweep + regenerated README table
    git add evidence/ bench_all.json README.md 2>/dev/null
    git diff --cached --quiet || git commit -m "On-chip session: refreshed bench sweep + evidence transcripts"
    exit 0
  fi
  sleep 150
done
echo "[watch $(date -u +%FT%TZ)] gave up after 12h"
exit 1
