#!/bin/bash
# Detached TPU-uptime watcher: probe every ~2.5 min; at each tunnel-up
# window run the on-chip session (tools/tpu_session.sh) and commit its
# artifacts. Windows run the FULL queue until one completes cleanly —
# tpu_session.sh writes .scratch/tpu_session_full_done only then —
# after which later windows refresh quickly. The sentinel is cleared at
# watch start so a new watch (new code, new queue steps) always begins
# with a full session.
# Transcript: evidence/ (session) + .scratch/tpu_watch.log (probe loop).
# Start with:
#   nohup setsid bash tools/tpu_watch.sh > .scratch/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
mkdir -p .scratch
rm -f .scratch/tpu_session_full_done
for i in $(seq 1 288); do  # up to 12h at the fast cadence
  echo "[watch $(date -u +%FT%TZ)] probe $i"
  if timeout 90 env JAX_PLATFORMS=tpu python -c \
      "import jax; d=jax.devices()[0]; assert d.platform=='tpu'; print('TPU', d.device_kind)"; then
    mode=""
    # the sentinel is written by tpu_session.sh itself, only when the
    # FULL queue ran to the end with the tunnel still alive
    [ -f .scratch/tpu_session_full_done ] && mode="quick"
    echo "[watch $(date -u +%FT%TZ)] TPU UP — running session ${mode:-full}"
    bash tools/tpu_session.sh $mode
    rc=$?
    echo "[watch $(date -u +%FT%TZ)] session done rc=$rc"
    # secure the artifacts even if the interactive session has ended:
    # evidence transcripts + refreshed sweep + regenerated README table
    git add evidence/ bench_all.json README.md 2>/dev/null
    git diff --cached --quiet || git commit -m "On-chip session: refreshed bench sweep + evidence transcripts"
    # keep watching: tunnel windows are short (2-29 min observed) and a
    # partial session leaves queue steps uncaptured
    sleep 150
  else
    sleep 150
  fi
done
echo "[watch $(date -u +%FT%TZ)] watch budget exhausted (12h)"
exit 1
