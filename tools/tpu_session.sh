#!/bin/bash
# One-shot on-chip work queue: run whenever the TPU tunnel is up.
# Usage: bash tools/tpu_session.sh [quick]
#   quick = skip the preset sweeps, just refresh bench_all.json + tests.
set -u -o pipefail
cd "$(dirname "$0")/.."
# evidence discipline (EVIDENCE.md): every on-chip session transcript is
# a committed artifact, not scratch
LOG="evidence/tpu_session_$(date -u +%Y%m%dT%H%M%SZ).log"
mkdir -p evidence
# persistent XLA compile cache: first compiles through the tunnel are
# 20-40s each; re-runs of the same configs (A/B arms, repeat sessions)
# hit the cache instead
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-.scratch/xla_cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

# queue-step failure accounting (lives inside run_all's subshell, which
# is where the sentinel decision is made — grepping the live $LOG races
# tee and overmatches bench's benign ladder messages):
#   cmd || note_rc "label"
# logs the failure and counts rc=124/137 (timeout/kill — the
# tunnel-death signature) separately from deterministic failures.
TIMEOUTS=0
SWEEP_INCOMPLETE=0
MODE=""
PROBE_OK_AT=0
probe_tunnel() {  # probe_tunnel <timeout_s> — the one liveness probe
  if timeout "$1" python -c \
      "import jax; d=jax.devices()[0]; assert d.platform=='tpu'; print('TPU:', d.device_kind)"; then
    PROBE_OK_AT=$(date +%s)
    return 0
  fi
  return 1
}
note_rc() {
  local rc=$?
  echo "FAILED rc=$rc ($1)"
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ] || [ "$rc" -eq 75 ]; then
    # 124/137 = step timed out/killed; 75 (EX_TEMPFAIL) = the step
    # itself detected the axon->CPU silent fallback (bench.py --child /
    # tools/_platform.py) — tunnel-death signatures, not deterministic
    # failures (pytest's INTERNALERROR=3 must NOT block the sentinel)
    TIMEOUTS=$((TIMEOUTS + 1))
  elif [ "$MODE" != "quick" ] && [ "$TIMEOUTS" -eq 0 ] \
      && [ "$SWEEP_INCOMPLETE" -eq 0 ] \
      && [ $(( $(date +%s) - PROBE_OK_AT )) -gt 90 ]; then
    # a tunnel death that fails FAST with an untagged rc must also
    # block the sentinel, or the step is silently skipped forever once
    # the tunnel recovers before queue end — re-probe right after any
    # failed step and count a dead probe as a timeout-equivalent.
    # Skipped when: quick mode (never writes the sentinel), the
    # sentinel is already blocked (TIMEOUTS>0), or a probe succeeded
    # <90s ago (several deterministic failures back-to-back would
    # otherwise burn minutes of a short window re-verifying liveness).
    if ! probe_tunnel 60 >/dev/null 2>&1; then
      echo "  (tunnel probe dead after failure — counting as timeout)"
      TIMEOUTS=$((TIMEOUTS + 1))
    fi
  fi
  return 0
}

run_all() {
  MODE="${1:-}"
  echo "=== tpu session $(date -u +%FT%TZ) ==="
  if ! probe_tunnel 120; then
      echo "TPU backend not reachable; aborting"
      return 1
  fi

  # bench sweep FIRST: if the tunnel window is short, the round's
  # headline artifact (bench_all.json refresh, VERDICT #1) must land
  # before anything else
  echo "--- 1. full bench sweep -> bench_all.json"
  # bench --all exits nonzero unless ALL FIVE configs measured fresh on
  # chip this run (its internal ladder hides tunnel deaths behind
  # CPU/stale fallbacks) — an incomplete sweep must block the
  # full-queue sentinel so the next window re-runs in full
  BENCH_DEADLINE_S=2400 timeout 2600 python bench.py --all --steps 50 \
      || { SWEEP_INCOMPLETE=1; note_rc "bench sweep"; }

  echo "--- 1b. regenerate the README perf table from the fresh sweep"
  python tools/perf_report.py --write || note_rc "perf report"

  echo "--- 2. on-chip test suite (tests_tpu/)"
  # FULL output into the session log (a failure whose traceback wasn't
  # captured cost round 4 a diagnosis round trip)
  timeout 1800 python -m pytest tests_tpu/ -q -ra 2>&1 \
      || note_rc "tests_tpu"

  if [ "$MODE" != "quick" ]; then
    # Ordering principle (windows observed at 2-29 min): SHORT,
    # decision-driving A/Bs first — each lands a committed artifact in
    # minutes — then the long instrumented tables (sim validation +
    # conv table, 30-min caps each) that only pay off if the window
    # survives them.
    echo "--- 3. LSTM Pallas kernel A/B (nmt_lstm; decides use_pallas default)"
    for v in 0 1; do
      echo "· FLEXFLOW_TPU_LSTM_PALLAS=$v"
      FLEXFLOW_TPU_LSTM_PALLAS=$v timeout 600 python bench.py --child \
        --model nmt_lstm --preset full --steps 30 | tail -1 \
        || note_rc "lstm pallas=$v"
    done
    echo "--- 4. DLRM full preset (26x1M tables; scan-OOM auto-falls"
    echo "    back to unroll / per_dispatch=1)"
    timeout 900 python bench.py --child \
      --model dlrm --preset full --steps 30 | tail -1 \
      || note_rc "dlrm full"
    echo "--- 4b. DLRM stacked-vs-separate tables A/B"
    for v in 0 1; do
      echo "· BENCH_DLRM_STACKED=$v"
      BENCH_DLRM_STACKED=$v timeout 600 python bench.py --child \
        --model dlrm --preset full --steps 30 | tail -1 \
        || note_rc "dlrm stacked=$v"
    done
    echo "--- 5. conv layout A/B (inception + alexnet)"
    for m in inception alexnet; do
      for layout in NCHW NHWC; do
        echo "· $m $layout"
        # 900s: inception's NHWC variant compiles >600s cold (timed out
        # in the 10:14Z session); the XLA cache makes re-runs cheap
        BENCH_CONV_LAYOUT=$layout timeout 900 python bench.py --child \
          --model $m --preset full --steps 30 | tail -1 \
          || note_rc "$m $layout"
      done
    done
    echo "--- 5b. inception sibling-conv fusion A/B (merged 1x1 branch"
    echo "    heads vs plain; decides the default stays on)"
    for v in 1 0; do
      echo "· BENCH_SIBLING_FUSION=$v"
      BENCH_SIBLING_FUSION=$v timeout 900 python bench.py --child \
        --model inception --preset full --steps 30 | tail -1 \
        || note_rc "inception sibling=$v"
    done
    echo "--- 6. inception batch sweep (MFU is batch-sensitive on convs)"
    for b in 48 64; do
      echo "· inception batch=$b"
      BENCH_BATCH=$b timeout 600 python bench.py --child \
        --model inception --preset full --steps 30 | tail -1 \
        || note_rc "inception batch=$b"
    done
    echo "--- 7. flash dispatch-threshold sweep (EVIDENCE.md row 3)"
    FLASH_SWEEP_PLATFORM=tpu timeout 1200 python tools/flash_sweep.py \
      || note_rc "flash sweep"
    echo "--- 8. placement A/B (measured vs simulated, EVIDENCE.md row)"
    timeout 900 python tools/placement_ab.py \
      | tee evidence/placement_ab_tpu_$(date -u +%Y%m%d).json.txt \
      || note_rc "placement A/B"
    echo "--- 9. sim-vs-real validation, all five models (VERDICT r3 #6)"
    SIM_VALIDATION_PLATFORM=tpu timeout 1800 \
      python tools/sim_validation.py \
      || note_rc "sim validation"
    echo "--- 10. per-shape conv table (inception MFU diagnosis)"
    CONV_TABLE_PLATFORM=tpu timeout 1800 \
      python tools/conv_shape_table.py \
      || note_rc "conv table"
    echo "--- 11. inception conv audit (layout A/B + tiling flags)"
    timeout 1200 python tools/inception_audit.py \
      | tee evidence/inception_audit_$(date -u +%Y%m%d).log \
      || note_rc "inception audit"
  fi
  if [ "$MODE" != "quick" ]; then
    # full-queue completion sentinel for the watcher (every step above
    # is ||-protected, so reaching here proves nothing by itself).
    # Written only when (a) no step TIMED OUT — counted in $TIMEOUTS,
    # the tunnel-death signature (the tunnel may have died mid-queue
    # and recovered before this line, silently skipping steps) — and
    # (b) the tunnel is alive now. Deterministic failures (rc=1) do
    # NOT block the sentinel: re-running the full queue can't fix
    # those and would burn every future window repeating them.
    if [ "$TIMEOUTS" -gt 0 ] || [ "$SWEEP_INCOMPLETE" -ne 0 ]; then
      echo "queue incomplete (timeouts=$TIMEOUTS" \
           "sweep_incomplete=$SWEEP_INCOMPLETE); full session will" \
           "re-run at the next window"
    elif probe_tunnel 90 >/dev/null; then
      touch .scratch/tpu_session_full_done
      echo "full queue completed with live tunnel; sentinel written"
    else
      echo "tunnel dead at queue end; full session will re-run"
    fi
  fi
  echo "=== done $(date -u +%FT%TZ) ==="
}

run_all "${1:-}" 2>&1 | tee -a "$LOG"
rc="${PIPESTATUS[0]}"
# decision summary (A/B winners per step) appended to the transcript
python tools/session_report.py "$LOG" 2>&1 | tee -a "$LOG" || true
exit "$rc"
