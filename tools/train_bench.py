"""Training-runtime benchmark: the async/overlap runtime vs the
synchronous dispatch path.

Two arms over identical data, identical seeds, identical step count:

  * sync    — the legacy loop: one dispatch per step, host blocks on
              every step's metrics (depth-1 window), monolithic
              end-of-backward grad sync (grad_bucket_mb=0), main-thread
              batch staging.
  * overlap — the async runtime this repo now ships: K-step grouped
              dispatch (train_batches — ONE host round trip and ONE
              stacked staging transfer per K steps), a depth-2 dispatch
              window (group g's metrics retrieved while group g+1 is in
              flight), and bucketed backward-overlapped grad sync
              (grad_bucket_mb).

The loss trajectories must be BIT-identical between the arms (the
window changes WHEN results are fetched, the scan body is the same
step math, and the bucket sync points are custom_vjp identities), and
nothing may compile after warmup — both asserted under --smoke (CI
gate, tools/ci.sh step 1h) along with step-time reduction >= 1.10x on
the primary (dlrm) workload.

Workloads (both gated >= --gate under --smoke):
  * dlrm        — a 26-table DLRM step is dispatch/staging-bound (28
                  host arrays per step, a short memory-bound device
                  step): the regime where per-step dispatch overhead
                  dominates and grouping/pipelining pays most.
  * transformer — the flagship model; its CPU win comes from the
                  grouped dispatch amortizing the runtime's per-program
                  execution overhead over K scanned steps. On TPU the
                  transformer's additional async-runtime win is
                  comm-overlap, which the `sim` record prices (bucketed
                  overlap vs serialized sync on the TPU machine model —
                  the same pricing the MCMC search now uses) and
                  bench.py measures end to end (vs_baseline).

Writes/merges records into BENCH_train.json (merge-by-metric like
serve_bench, so partial runs never clobber other records):

    python tools/train_bench.py --smoke      # the CI gate
    python tools/train_bench.py              # full sizes
"""

import argparse
import json
import os
import sys
import time

# the image's sitecustomize routes jax at the axon TPU tunnel; this
# bench measures the host runtime — pin CPU before jax loads unless the
# caller asks for the ambient backend
if "--ambient-backend" not in sys.argv:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # virtual devices for the `sim` record's d8 pricing mesh (the
    # timed arms run single-device regardless — no mesh is passed)
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[train_bench] {msg}", file=sys.stderr, flush=True)


def _build(model, args, overlap):
    import jax
    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu.models.dlrm import build_dlrm
    from flexflow_tpu.models.transformer import build_transformer

    cfg = FFConfig(batch_size=args.batch)
    cfg.train_dispatch_depth = 2 if overlap else 1
    cfg.grad_bucket_mb = args.bucket_mb if overlap else 0.0
    rng = np.random.RandomState(0)
    n = args.batch * max(4, args.group)
    if model == "dlrm":
        vocabs = (args.vocab,) * args.tables
        ff = build_dlrm(cfg, batch_size=args.batch,
                        embedding_vocab_sizes=vocabs,
                        embedding_dim=16, bot_mlp=(64, 32, 16),
                        top_mlp=(64, 1))
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type="mean_squared_error", metrics=[])
        x = {"dense_features": rng.randn(n, 13)}
        for i in range(args.tables):
            x[f"sparse_{i}"] = rng.randint(
                0, args.vocab, (n, 1)).astype(np.int64)
        y = (rng.rand(n, 1) > 0.5).astype(np.float64)
    else:
        ff = build_transformer(
            cfg, batch_size=args.batch, seq_len=args.seq,
            hidden=args.hidden, num_heads=4, num_layers=args.layers,
            ff_dim=args.hidden * 2, num_classes=10)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        x = {"input": rng.randn(n, args.seq, args.hidden)}
        y = rng.randint(0, 10, (n,)).astype(np.int64)
    del jax  # imported for backend init side effect ordering
    return ff, x, y


def run_arm(model, args, overlap):
    """-> (sec/step best-of-repeats, losses float32 array, stats)."""
    import jax
    from flexflow_tpu.core.overlap import DispatchWindow
    from flexflow_tpu.serve.engine import _CompileEvents

    ff, x, y = _build(model, args, overlap)
    names = list(x)
    bs = args.batch
    nbatch = len(y) // bs
    K = args.group if overlap else 1

    def mk(s):
        sel = slice((s % nbatch) * bs, ((s % nbatch) + 1) * bs)
        b = {k: x[k][sel] for k in names}
        b["label"] = y[sel]
        return b

    depth = ff.config.train_dispatch_depth
    win = DispatchWindow(depth)
    losses = []
    gaps = []
    last_end = [None]

    def dispatch(step0):
        t = time.perf_counter()
        if last_end[0] is not None:
            gaps.append(t - last_end[0])
        if K > 1:
            m = ff.train_batches([mk(step0 + i) for i in range(K)])
        else:
            m = ff.train_batch(mk(step0))
        last_end[0] = time.perf_counter()
        win.push(m)

    def drain():
        for m in win.drain():
            arr = np.asarray(m["loss"], dtype=np.float32).reshape(-1)
            losses.extend(arr.tolist())

    # warmup: compile both in-flight program shapes
    warm = max(K, args.warmup - args.warmup % K or K)
    for s in range(0, warm, K):
        dispatch(s)
    drain()
    installed = _CompileEvents.install()
    compiles0 = _CompileEvents.count
    best = float("inf")
    step = warm
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        for _g in range(args.steps // K):
            dispatch(step)
            step += K
        drain()
        best = min(best, (time.perf_counter() - t0) / args.steps)
    compiles = (_CompileEvents.count - compiles0) if installed else None
    sg = sorted(gaps)
    stats = {
        "depth": depth,
        "group": K,
        "grad_bucket_mb": ff.config.grad_bucket_mb,
        "grad_buckets": ff.executor.grad_bucket_info()["count"],
        "dispatch_gap_ms_mean": round(1e3 * sum(sg) / len(sg), 4)
        if sg else 0.0,
        "dispatch_gap_ms_p50": round(1e3 * sg[len(sg) // 2], 4)
        if sg else 0.0,
        "dispatch_gap_ms_max": round(1e3 * sg[-1], 4) if sg else 0.0,
        "fetch_wait_ms_total": round(1e3 * sum(win.fetch_waits_s), 3),
        "compiles_after_warmup": compiles,
        "platform": jax.default_backend(),
    }
    return best, np.asarray(losses, dtype=np.float32), stats


def sim_overlap_record(args):
    """Simulated transformer step on the TPU machine model, bucketed
    overlap vs serialized monolithic sync — the pricing the MCMC search
    now rewards (the executor's measured win on real TPUs rides
    bench.py's vs_baseline)."""
    from flexflow_tpu import FFConfig, make_mesh
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.parallel.mesh import MachineSpec
    from flexflow_tpu.parallel.pconfig import Strategy
    from flexflow_tpu.search.cost_cache import machine_fingerprint
    from flexflow_tpu.search.machine_model import default_machine_model
    from flexflow_tpu.search.simulator import Simulator

    mesh = make_mesh((8,), ("data",))
    mm = default_machine_model(mesh, spec=MachineSpec.v5e())

    def priced(overlap_on):
        cfg = FFConfig(batch_size=64)
        cfg.search_overlap_backward_sync = overlap_on
        cfg.grad_bucket_mb = args.bucket_mb if overlap_on else 0.0
        ff = build_transformer(cfg, batch_size=64, seq_len=512,
                               hidden=512, num_heads=8, num_layers=6,
                               ff_dim=2048, num_classes=10)
        sim = Simulator(ff, mesh, mm)
        return sim.simulate(Strategy()), sim

    t_sync, _ = priced(False)
    t_ovl, sim = priced(True)
    return {
        "metric": "train_sim_overlap_step_reduction",
        "value": round(t_sync / t_ovl, 4),
        "unit": "x",
        "extra": {
            "sync_s": t_sync, "overlap_s": t_ovl,
            "machine": "v5e d8", "model": "transformer 6L h512 s512",
            "grad_bucket_mb": args.bucket_mb,
            "fingerprint": machine_fingerprint(
                sim.mm, mesh, precision=sim._precision(),
                overlap=sim.overlap_sig()),
        },
    }


def telemetry_record(args):
    """A small telemetry-on fit() over the bench transformer: exports
    the train metrics snapshot (dispatch gaps, fetch waits, window
    stats) and the train half of the simulator-drift calibration
    (measured wall/step vs the overlap-exact graph's prediction) into
    the BENCH artifact — the perf trajectory carries the numbers the
    string report renders (docs/observability.md)."""
    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu.models.transformer import build_transformer

    cfg = FFConfig(batch_size=args.batch)
    cfg.telemetry = True
    ff = build_transformer(
        cfg, batch_size=args.batch, seq_len=args.seq,
        hidden=args.hidden, num_heads=4, num_layers=args.layers,
        ff_dim=args.hidden * 2, num_classes=10)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    n = args.batch * 4
    x = {"input": rng.randn(n, args.seq, args.hidden)}
    y = rng.randint(0, 10, (n,)).astype(np.int64)
    ff.fit(x, y, epochs=2, verbose=False)
    tel = ff.telemetry
    snap = tel.metrics_snapshot()
    drift = snap["drift"].get("train", {})
    st = ff.last_train_stats
    return {
        "metric": "train_telemetry_profile",
        "value": st["dispatches"],
        "unit": "dispatches",
        "extra": {
            "dispatch_gap_ms_mean": round(
                st["dispatch_gap_s_mean"] * 1e3, 4),
            "dispatch_gap_ms_p50": round(
                st["dispatch_gap_s_p50"] * 1e3, 4),
            "dispatch_gap_ms_max": round(
                st["dispatch_gap_s_max"] * 1e3, 4),
            "fetch_wait_ms_total": round(
                st["fetch_wait_s_total"] * 1e3, 3),
            "max_in_flight": st["max_in_flight"],
            "events_buffered": snap["events_buffered"],
            "drift_ratio_by_regime": {
                reg: round(d["ratio"], 2) for reg, d in drift.items()},
            "drift_predicted_ms_per_step": {
                reg: round(d["predicted_ms_per_step"], 4)
                for reg, d in drift.items()},
            "drift_measured_ms_per_step": {
                reg: round(d["measured_ms_per_step"], 4)
                for reg, d in drift.items()},
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: small sizes; assert >= --gate "
                         "step-time reduction per workload, "
                         "bit-identical losses, zero recompiles after "
                         "warmup")
    ap.add_argument("--workload", choices=("all", "dlrm", "transformer",
                                           "sim", "telemetry"),
                    default="all")
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument("--group", type=int, default=8,
                    help="steps per grouped dispatch in the overlap arm")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=2000)
    ap.add_argument("--tables", type=int, default=26)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--bucket-mb", type=float, default=4.0)
    ap.add_argument("--gate", type=float, default=1.10)
    ap.add_argument("--ambient-backend", action="store_true",
                    help="don't pin JAX_PLATFORMS=cpu (measure on the "
                         "ambient TPU backend)")
    ap.add_argument("-o", "--out", default="BENCH_train.json")
    args = ap.parse_args()
    if args.smoke:
        args.steps = min(args.steps, 48)
        args.repeat = min(args.repeat, 3)
    args.steps -= args.steps % args.group  # one program shape per arm

    os.environ.setdefault(
        "FLEXFLOW_TPU_CACHE",
        os.path.join("/tmp", "flexflow_tpu_train_bench_cache"))

    records = []
    gates = []
    workloads = (["dlrm", "transformer"] if args.workload == "all"
                 else [args.workload]
                 if args.workload in ("dlrm", "transformer") else [])
    for model in workloads:
        log(f"{model}: sync arm ({args.steps} steps x{args.repeat})...")
        t_sync, l_sync, s_sync = run_arm(model, args, overlap=False)
        log(f"{model}: overlap arm...")
        t_ovl, l_ovl, s_ovl = run_arm(model, args, overlap=True)
        red = t_sync / t_ovl if t_ovl > 0 else 0.0
        exact = (l_sync.shape == l_ovl.shape
                 and np.array_equal(l_sync, l_ovl))
        rec = {
            "metric": f"train_overlap_step_reduction_{model}",
            "value": round(red, 4),
            "unit": "x",
            "extra": {
                "sync_ms_per_step": round(t_sync * 1e3, 3),
                "overlap_ms_per_step": round(t_ovl * 1e3, 3),
                "samples_per_sec_sync": round(args.batch / t_sync, 1),
                "samples_per_sec_overlap": round(args.batch / t_ovl, 1),
                "steps": args.steps, "batch": args.batch,
                "loss_trajectory_bit_identical": bool(exact),
                "sync": s_sync, "overlap": s_ovl,
                "captured": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
            },
        }
        records.append(rec)
        log(f"{model}: sync {t_sync*1e3:.2f} ms/step, overlap "
            f"{t_ovl*1e3:.2f} ms/step -> {red:.2f}x, exact={exact}, "
            f"compiles after warmup: sync="
            f"{s_sync['compiles_after_warmup']} "
            f"overlap={s_ovl['compiles_after_warmup']}")
        if args.smoke:
            assert exact, (
                f"{model}: overlap-arm loss trajectory diverged from "
                f"the synchronous path (must be bit-identical)")
            for arm_name, st in (("sync", s_sync), ("overlap", s_ovl)):
                c = st["compiles_after_warmup"]
                assert c in (0, None), (
                    f"{model}/{arm_name}: {c} compiles after warmup "
                    f"(zero-recompile gate)")
            assert red >= args.gate, (
                f"{model} step-time reduction {red:.3f}x < gate "
                f"{args.gate}x")
            gates.append(f"{model}_reduction={red:.2f}x>={args.gate}x")
            gates.append(f"{model}_exact+zero_recompiles")

    if args.workload in ("all", "sim"):
        log("simulated overlap pricing (TPU machine model)...")
        rec = sim_overlap_record(args)
        records.append(rec)
        log(f"sim: {rec['value']}x step reduction "
            f"(sync {rec['extra']['sync_s']*1e3:.3f} ms -> overlap "
            f"{rec['extra']['overlap_s']*1e3:.3f} ms)")
        if args.smoke:
            assert rec["value"] >= 1.0, (
                f"simulator prices overlapped sync SLOWER than "
                f"serialized ({rec['value']}x)")
            gates.append(f"sim_reduction={rec['value']}x>=1.0x")

    if args.workload in ("all", "telemetry"):
        log("telemetry profile (telemetry-on fit + drift)...")
        rec = telemetry_record(args)
        records.append(rec)
        log(f"telemetry: {rec['value']} dispatches, drift regimes: "
            f"{list(rec['extra']['drift_ratio_by_regime'])}")
        if args.smoke:
            assert rec["extra"]["events_buffered"] > 0, (
                "telemetry-on fit recorded no events")
            assert rec["extra"]["drift_ratio_by_regime"], (
                "telemetry-on fit recorded no train drift regimes")
            gates.append("telemetry_profile+drift recorded")

    # merge-by-metric (serve_bench convention): partial --workload runs
    # never clobber the other records
    merged = {}
    try:
        with open(args.out) as f:
            for line in f.read().splitlines():
                if line.strip():
                    r = json.loads(line)
                    merged[r["metric"]] = r
    except (OSError, json.JSONDecodeError):
        pass
    for r in records:
        merged[r["metric"]] = r
    with open(args.out, "w") as f:
        f.write("\n".join(json.dumps(r) for r in merged.values()) + "\n")
    print("\n".join(json.dumps(r) for r in records))
    if args.smoke:
        log("GATES PASSED: " + "; ".join(gates))
    return 0


if __name__ == "__main__":
    sys.exit(main())
