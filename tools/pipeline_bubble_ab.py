"""Pipeline bubble: simulated-vs-analytic agreement + CPU wall-clock.

Validates VERDICT r3 #7's "simulated-vs-measured bubble agreement" with
the two signals this host can actually produce:

1. SIMULATOR vs ANALYTIC: the event-loop simulator's makespan for a
   staged strategy (search/simulator.py _simulate_staged — per-stage
   resources, per-cut hops) against the closed-form GPipe tick model
   time ∝ (M + S - 1)/M (graph_pipeline.simulate_step_scaling). Agrees
   in the compute-dominated regime; diverges where per-hop latency
   binds (more microbatches = more, smaller hops) — which is the
   simulator being MORE faithful than the closed form, not less.

2. WALL-CLOCK on the forced 8-device CPU platform. CAVEAT: this box has
   ONE physical core (nproc=1), so the 8 "devices" serialize and
   wall-clock measures TOTAL work + dispatch overhead, not the critical
   path — the bubble the schedule hides is invisible here. Recorded as
   a liveness/overhead signal only; on-chip wall-clock agreement needs
   real multi-chip hardware (not available through the 1-chip tunnel).

Writes evidence/pipeline_bubble_cpu8.json. Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python tools/pipeline_bubble_ab.py
"""

import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh  # noqa: E402
from flexflow_tpu.parallel.graph_pipeline import (  # noqa: E402
    simulate_step_scaling,
)
from flexflow_tpu.search.mcmc import staged_strategies  # noqa: E402
from flexflow_tpu.search.simulator import Simulator  # noqa: E402

BS = 256
FEAT = 2048
STAGES = 2


def build_model(m, schedule="gpipe", feat=FEAT, bs=BS, compile_=False,
                mesh=None):
    cfg = FFConfig(batch_size=bs)
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_stages = STAGES if compile_ else 0
    cfg.pipeline_microbatches = m
    cfg.pipeline_schedule = schedule
    ff = FFModel(cfg, mesh=mesh)
    x = ff.create_tensor((bs, feat), name="input")
    t = x
    for i in range(8):
        t = ff.dense(t, feat, activation="relu", name=f"fc{i}")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    if compile_:
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh)
    return ff


def sim_vs_analytic():
    mesh = make_mesh((STAGES,), ("pipe",))
    rows = []
    base = None
    for m in (1, 2, 4, 8, 16):
        ff = build_model(m)
        staged = staged_strategies(ff, mesh, ff.config)[0]
        t = Simulator(ff, mesh).simulate(staged)
        if base is None:
            base = t
        rows.append({
            "microbatches": m,
            "sim_us": t * 1e6,
            "sim_speedup_vs_m1": base / t,
            "analytic_speedup_vs_m1": simulate_step_scaling(STAGES, 1, m),
        })
    return rows


def wall_clock(schedule):
    mesh = make_mesh((STAGES,), ("pipe",))
    rows = []
    rng = np.random.RandomState(0)
    bs = 64
    b = {"input": rng.randn(bs, 256).astype(np.float32),
         "label": rng.randint(0, 10, bs).astype(np.int32)}
    for m in (1, 4):
        ff = build_model(m, schedule=schedule, feat=256, bs=bs,
                         compile_=True, mesh=mesh)
        float(ff.train_batch(b)["loss"])  # compile + warmup
        t0 = time.perf_counter()
        for _ in range(10):
            r = ff.train_batch(b)
        float(r["loss"])
        rows.append({"microbatches": m,
                     "ms_per_step": (time.perf_counter() - t0) * 100})
    return rows


def interleaved_bubbles():
    """Schedule-level bubble fractions: plain 1F1B (v=1) vs the
    interleaved wave schedule at v in {2, 4} (round 4's
    --pipeline-virtual-stages), and the forward-only schedule that
    eval/predict runs (`pipeline_logits_interleaved`)."""
    from flexflow_tpu.parallel.graph_pipeline import (
        interleaved_forward_schedule, interleaved_schedule,
        schedule_bubble)
    rows = []
    for D, M in [(2, 8), (4, 8), (4, 16), (8, 32)]:
        row = {"devices": D, "microbatches": M}
        for v in (1, 2, 4):
            kind, _m, _s, depth = interleaved_schedule(D, v, M)
            row[f"bubble_v{v}"] = round(schedule_bubble(kind), 4)
            row[f"depth_v{v}"] = depth
            fkind, _fm, _fs, fdepth = interleaved_forward_schedule(
                D, v, M)
            row[f"fwd_bubble_v{v}"] = round(schedule_bubble(fkind), 4)
            row[f"fwd_depth_v{v}"] = fdepth
        rows.append(row)
    return rows


def main():
    out = {"stages": STAGES, "nproc": os.cpu_count(),
           "interleaved_schedule_bubbles": interleaved_bubbles(),
           "sim_vs_analytic": sim_vs_analytic(),
           "wall_clock_caveat": (
               "1 physical core: devices serialize; wall-clock = total "
               "work, bubble invisible (see module docstring)"),
           "wall_clock": {s: wall_clock(s) for s in ("gpipe", "1f1b")}}
    print("sim vs analytic (speedup over M=1 at fixed batch):")
    for r in out["sim_vs_analytic"]:
        print(f"  M={r['microbatches']:>2}: sim x{r['sim_speedup_vs_m1']:.3f}"
              f"  analytic x{r['analytic_speedup_vs_m1']:.3f}")
    path = os.path.join(os.path.dirname(__file__), "..", "evidence",
                        "pipeline_bubble_cpu8.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {os.path.normpath(path)}")


if __name__ == "__main__":
    main()
