"""Distill a tpu_session.sh transcript into a decision table.

Parses the per-arm JSON lines (each `bench.py --child | tail -1`
prints one) together with the `· <arm>` markers the session script
echoes before each arm, and prints winners per A/B group plus the
headline sweep deltas. Usage:

  python tools/session_report.py [evidence/tpu_session_<UTC>.log]

Defaults to the newest session log under evidence/.
"""

import glob
import json
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def newest_log():
    logs = sorted(glob.glob(os.path.join(
        ROOT, "evidence", "tpu_session_*.log")))
    if not logs:
        raise SystemExit("no evidence/tpu_session_*.log found")
    return logs[-1]


def parse(path):
    """-> (step_header, arm_label) -> result dict, in file order."""
    rows = []
    step, arm = None, None
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if line.startswith("--- "):
                step, arm = line[4:], None
            elif line.startswith("· "):
                arm = line[2:]
            elif line.startswith("{") and '"metric"' in line:
                try:
                    rows.append((step, arm, json.loads(line)))
                except json.JSONDecodeError:
                    continue
    return rows


def is_stale(res):
    """bench surfaces staleness at top level AND in extra precisely so
    summaries like this one can't misattribute a replayed historical
    number to the current session."""
    return bool(res.get("stale") or res.get("extra", {}).get("stale"))


def fmt(res):
    e = res.get("extra", {})
    util = (f"hbm {e['hbm_util']:.3f}" if e.get("util_basis", "").
            startswith("hbm") else f"mfu {e.get('mfu', 0):.3f}")
    return (f"{res.get('value', 0):>10,.0f} samples/s  {util}  "
            f"{e.get('ms_per_step', 0):6.1f} ms/step  "
            f"[{e.get('platform','?')} {e.get('preset','?')}"
            f" b{e.get('batch','?')}]"
            + ("  (STALE replay, not this session)" if is_stale(res)
               else ""))


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else newest_log()
    rows = parse(path)
    if not rows:
        print(f"{path}: no bench JSON lines found")
        return 1
    print(f"== {os.path.basename(path)} ==")
    by_step = {}
    for step, arm, res in rows:
        by_step.setdefault(step, []).append((arm, res))
    for step, arms in by_step.items():
        print(f"\n--- {step}")
        best = None
        fresh_tpu = 0
        for arm, res in arms:
            label = arm or res.get("metric", "?").split("_train")[0]
            print(f"  {label:34s} {fmt(res)}")
            v = res.get("value") or 0
            if res.get("extra", {}).get("platform") == "tpu" \
                    and not is_stale(res):
                fresh_tpu += 1
                if best is None or v > best[1]:
                    best = (label, v)
        # a WINNER line is decision-driving: only print one when at
        # least two arms actually raced fresh on chip this session
        # (stale replays and CPU fallbacks are excluded from `best`,
        # so counting them in would crown a one-sided comparison)
        if best and fresh_tpu > 1:
            print(f"  WINNER: {best[0]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
