"""Failure flight-recorder loader + the post-mortem CI gate (ci.sh 1o).

Two modes:

* Default: load a post-mortem bundle a ServeEngine / ReplicaPool /
  DisaggCluster dumped (``--postmortem-dir``, or an explicit
  ``dump_postmortem()``), validate its schema, and render the human
  summary — reason, engine shape, the event-ring tail, scheduler and
  KV-pool state at the failure, fault accounting.

      python tools/postmortem.py /tmp/pm/postmortem-fault_abort-*.json

* ``--smoke`` (tools/ci.sh step 1o): gates the flight recorder end to
  end on a real engine — a chaos run (injected FATAL dispatch fault,
  the PR-6 harness) aborts a generate mid-batch, the engine's
  fault-abort trigger must leave a bundle in --postmortem-dir, and the
  bundle must load, validate, and carry the failure's evidence (spans
  in the ring, the fired fault site, the scheduler state). An explicit
  dump and a deadline-storm trigger are gated alongside.
"""

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

select_platform("POSTMORTEM_PLATFORM")

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

SCHEMA = "flexflow_tpu.postmortem/1"
REQUIRED = ("schema", "reason", "created_unix_s", "engine",
            "compile_counts", "events", "metrics", "drift", "kv_pool",
            "faults")


def validate(bundle: dict) -> list:
    """Schema check: returns a list of problems (empty = valid)."""
    problems = []
    if bundle.get("schema") != SCHEMA:
        problems.append(f"schema is {bundle.get('schema')!r}, "
                        f"expected {SCHEMA!r}")
    for key in REQUIRED:
        if key not in bundle:
            problems.append(f"missing key {key!r}")
    evs = bundle.get("events")
    if not isinstance(evs, list):
        problems.append("events is not a list")
    else:
        for i, ev in enumerate(evs):
            if not (isinstance(ev, list) and len(ev) == 7):
                problems.append(
                    f"event {i} is not a 7-field record: {ev!r}")
                break
    m = bundle.get("metrics")
    if isinstance(m, dict) and "error" not in m:
        for part in ("counters", "gauges", "histograms"):
            if part not in m:
                problems.append(f"metrics snapshot missing {part!r}")
    kv = bundle.get("kv_pool")
    if isinstance(kv, dict) and "error" not in kv:
        for part in ("usable_pages", "free_pages", "occupancy"):
            if part not in kv:
                problems.append(f"kv_pool missing {part!r}")
    sched = bundle.get("scheduler")
    if isinstance(sched, dict) and "error" not in sched:
        for part in ("rung", "waiting", "running", "stats"):
            if part not in sched:
                problems.append(f"scheduler state missing {part!r}")
    return problems


def render(bundle: dict, tail: int = 12) -> str:
    """The human summary of a bundle."""
    import datetime
    eng = bundle.get("engine") or {}
    when = datetime.datetime.fromtimestamp(
        bundle.get("created_unix_s", 0),
        tz=datetime.timezone.utc).isoformat()
    lines = [
        f"post-mortem: reason={bundle.get('reason')!r} at {when}",
        f"engine: {eng.get('mode')} mixed_width="
        f"{eng.get('mixed_width')} tp={eng.get('tensor_parallel')} "
        f"kv={eng.get('kv_dtype')} track={eng.get('track_process')}",
        f"detail: {bundle.get('detail')}",
        f"compiled programs: {bundle.get('compile_counts')}",
    ]
    kv = bundle.get("kv_pool") or {}
    if "error" not in kv:
        lines.append(
            f"kv pool: {kv.get('free_pages')} free + "
            f"{kv.get('parked_pages')} parked / "
            f"{kv.get('usable_pages')} usable "
            f"(occupancy {kv.get('occupancy', 0.0):.1%}, "
            f"{kv.get('free_slots')} free slots)")
    sched = bundle.get("scheduler")
    if isinstance(sched, dict) and "error" not in sched:
        lines.append(
            f"scheduler: rung {sched.get('rung')}, "
            f"{sched.get('waiting_depth')} waiting / "
            f"{sched.get('running_depth')} running, "
            f"stats {sched.get('stats')}")
    faults = bundle.get("faults") or {}
    if faults.get("fired"):
        lines.append(f"faults fired: {faults['fired']}")
    for name, section in (("router", bundle.get("router")),
                          ("handoff", bundle.get("handoff"))):
        if section:
            lines.append(f"{name}: {section}")
    evs = bundle.get("events") or []
    lines.append(f"event ring: {len(evs)} events buffered "
                 f"({bundle.get('events_dropped', 0)} dropped); "
                 f"last {min(tail, len(evs))}:")
    for ph, track, name, ts, dur, ident, args in evs[-tail:]:
        lines.append(
            f"  [{track[0]}/{track[1]}] {ph} {name} @ {ts * 1e3:.3f}ms"
            + (f" +{dur * 1e3:.3f}ms" if ph == "X" else "")
            + (f" {args}" if args else ""))
    return "\n".join(lines)


def _build_engine(cfg_over: dict):
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine
    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   serve_retry_backoff_s=0.0, **cfg_over)
    ff = build_transformer_lm(cfg, vocab_size=89, max_seq_len=64,
                              hidden=32, num_heads=4, num_layers=2,
                              ff_dim=64)
    return ServeEngine(ff)


def smoke() -> int:
    import numpy as np
    fails = []

    def gate(name, ok, detail=""):
        print(f"  {'PASS' if ok else 'FAIL'}: {name}"
              + (f" ({detail})" if detail else ""))
        if not ok:
            fails.append(name)

    with tempfile.TemporaryDirectory(prefix="ff_pm_") as pmdir:
        # ---- 1. chaos-triggered bundle: a FATAL injected dispatch
        # fault aborts the batch mid-flight (the PR-6 harness), and the
        # fault-abort trigger must leave a loadable bundle behind
        eng = _build_engine({"postmortem_dir": pmdir,
                             "fault_spec": "serve.mixed:fatal@4"})
        eng.warmup()
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(1, 89, size=rng.randint(6, 24)))
                   for _ in range(6)]
        raised = False
        try:
            eng.generate(prompts, 8)
        except Exception as e:
            raised = True
            print(f"  (chaos generate aborted as injected: "
                  f"{type(e).__name__})")
        gate("injected fatal fault aborts the run", raised)
        found = sorted(glob.glob(
            os.path.join(pmdir, "postmortem-fault_abort-*.json")))
        gate("fault-abort auto-dumps a bundle", len(found) == 1,
             f"found={found}")
        if not found:
            return 1
        with open(found[0]) as f:
            bundle = json.load(f)
        problems = validate(bundle)
        gate("bundle validates", not problems, f"problems={problems}")
        gate("bundle reason is fault_abort",
             bundle.get("reason") == "fault_abort")
        gate("ring spans captured",
             len(bundle.get("events") or []) > 0)
        gate("event payload bounded",
             len(bundle["events"]) <= eng.postmortem_events)
        fired = (bundle.get("faults") or {}).get("fired") or {}
        gate("fired fault site recorded", "serve.mixed" in fired,
             f"fired={fired}")
        gate("scheduler state captured",
             isinstance(bundle.get("scheduler"), dict)
             and "rung" in bundle["scheduler"])
        print()
        print(render(bundle, tail=6))
        print()

        # ---- 2. the engine keeps serving after the abort (the @4
        # hit-list clause fired once and never again), and an explicit
        # dump works on the healthy engine
        out = eng.generate(prompts[:2], 4)
        gate("engine serves on after the black-boxed abort",
             len(out) == 2 and all(len(o) == 4 for o in out))
        p = eng.dump_postmortem(reason="manual",
                                detail={"why": "smoke"})
        with open(p) as f:
            manual = json.load(f)
        gate("explicit dump validates", not validate(manual))
        gate("explicit dumps bypass the rate limit",
             os.path.exists(p))

        # ---- 3. deadline storm: several requests expiring at one
        # chunk boundary trigger the storm bundle
        eng2 = _build_engine({"postmortem_dir": pmdir})
        eng2.warmup()
        try:
            eng2.generate(prompts, 16, deadline_s=1e-4)
        except Exception:
            pass
        storms = glob.glob(
            os.path.join(pmdir, "postmortem-deadline_storm-*.json"))
        gate("deadline storm auto-dumps", len(storms) >= 1,
             f"found={storms}")
        if storms:
            with open(storms[0]) as f:
                gate("storm bundle validates",
                     not validate(json.load(f)))
    if fails:
        print(f"\nPOSTMORTEM SMOKE FAILED: {fails}", file=sys.stderr)
        return 1
    print("\nPOSTMORTEM SMOKE PASSED")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("bundle", nargs="?",
                    help="post-mortem bundle JSON to load + render")
    ap.add_argument("--smoke", action="store_true",
                    help="run the flight-recorder CI gate (ci.sh 1o)")
    ap.add_argument("--tail", type=int, default=12,
                    help="ring events to render (default 12)")
    args = ap.parse_args()
    if args.smoke:
        return smoke()
    if not args.bundle:
        ap.print_help()
        return 0
    with open(args.bundle) as f:
        bundle = json.load(f)
    problems = validate(bundle)
    if problems:
        print("INVALID bundle:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(render(bundle, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
