"""Regenerate the README "Measured performance" table from
bench_all.json (run by tools/tpu_session.sh after a sweep so the
committed numbers and the committed table can never diverge —
VERDICT r2 weak #2: a self-admittedly stale README table).

  python tools/perf_report.py            # print the markdown table
  python tools/perf_report.py --write    # splice it into README.md
"""

import json
import math
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")

LABELS = {
    "transformer": "Transformer encoder (s512, 6L)",
    "alexnet": "AlexNet/CIFAR-10",
    "inception": "Inception-v3 299px",
    "nmt_lstm": "NMT LSTM (s40)",
    "dlrm": "DLRM",
}

# dlrm's table size is preset-dependent (bench.py vocab map) — label
# from the RECORDED preset so a small-preset capture can't masquerade
# as the 1M-row full config (r4 review finding)
DLRM_PRESET_LABEL = {
    "full": "DLRM (26x 1M-row tables)",
    "small": "DLRM (26x 100k-row tables)",
    "tiny": "DLRM (8x 1k-row tables)",
}
ORDER = ["transformer", "alexnet", "inception", "nmt_lstm", "dlrm"]

BEGIN = "| Config | samples/s/chip | utilization | ms/step |"


def row(model, entry):
    e = entry.get("extra", {})
    util = e.get("mfu")
    basis = e.get("util_basis", "mfu")
    vsb = entry.get("vs_baseline")
    if basis != "mfu":
        util_s = f"{e.get('hbm_util', 0):.2f} HBM ({vsb:.2f}x target)"
    elif "hbm_util" in e:
        # roofline WAS captured but MFU won the max() — show both
        util_s = f"{e['hbm_util']:.2f} HBM ({vsb:.2f}x target, mfu basis)"
    elif model == "dlrm":
        # bandwidth-bound: an MFU-basis number with no roofline capture
        # is meaningless — say so rather than print 0.00
        util_s = "bandwidth-bound (roofline capture pending)"
    else:
        bold = "**" if vsb and vsb >= 1.0 else ""
        util_s = f"{bold}{util:.2f}{bold} ({vsb:.2f}x target)"
    stale = " *(stale)*" if e.get("stale") else ""
    label = LABELS.get(model, model)
    if model == "dlrm":
        label = DLRM_PRESET_LABEL.get(e.get("preset"), label)
    if e.get("batch"):
        label += f" b{e['batch']}"
    return (f"| {label}{stale} | "
            f"{entry.get('value', 0):,.0f} | {util_s} | "
            f"{e.get('ms_per_step', 0):.1f} |")


def build_table(bench):
    lines = [BEGIN, "|---|---|---|---|"]
    captured = set()
    for m in ORDER:
        entry = bench.get(m)
        if not entry:
            lines.append(f"| {LABELS.get(m, m)} | — | unmeasured | — |")
            continue
        lines.append(row(m, entry))
        c = entry.get("extra", {}).get("captured")
        if c:
            captured.add(c[:10])
    if not captured:
        # pre-stamping sweeps: date the file from git via bench.py's
        # own (UTC-normalized, stderr-suppressed) helper
        try:
            sys.path.insert(0, ROOT)
            import bench
            stamp = bench._bench_all_git_stamp()
            if stamp:
                captured.add(stamp[:10])
        except Exception:
            pass
    note = (f"Captured {', '.join(sorted(captured)) or 'n/a'} "
            f"(`bench_all.json`); entries marked *stale* (and any sweep "
            f"older than the latest commits) predate current code — "
            f"`tools/tpu_session.sh` refreshes both the JSON and this "
            f"table.")
    note += search_line()
    note += mp_line()
    note += serve_line()
    return "\n".join(lines), note


def search_line() -> str:
    """Strategy-search throughput sentence from BENCH_search.json,
    keyed to the machine fingerprint of the shared cost cache
    (search/cost_cache.py) — the committed numbers are attributable to
    one machine + cost-model state without re-measuring anything
    (tools/search_bench.py refreshes the JSON)."""
    try:
        with open(os.path.join(ROOT, "BENCH_search.json")) as f:
            text = f.read()
        b = None
        try:  # pre-PR-11 whole-file dict form
            doc = json.loads(text)
            if isinstance(doc, dict) and "speedup" in doc:
                b = {"speedup": doc["speedup"], **doc}
        except json.JSONDecodeError:
            pass
        if b is None:  # merge-by-metric JSONL (tools/_bench_io.py)
            sys.path.insert(0, os.path.dirname(
                os.path.abspath(__file__)))
            from _bench_io import record_map
            r = record_map(
                os.path.join(ROOT, "BENCH_search.json")).get(
                "search_delta_speedup")
            if r is not None:
                b = {"speedup": r["value"], **r.get("extra", {})}
        if b is None:
            return ""
        return (f" Strategy search: "
                f"{b['proposals_per_sec_delta']:,.0f} proposals/s with "
                f"delta simulation vs {b['proposals_per_sec_full']:,.0f} "
                f"full ({b['speedup']:.1f}x, `BENCH_search.json`, "
                f"fingerprint `{b.get('fingerprint', 'n/a')}`).")
    except (OSError, json.JSONDecodeError, KeyError):
        return ""


def mp_line() -> str:
    """Mixed-precision sentence from BENCH_mp.json (tools/mp_bench.py):
    the simulator-priced bf16-vs-f32 step-makespan reductions and, when
    a TPU was attached at capture time, the wall-clock speedup."""
    try:
        with open(os.path.join(ROOT, "BENCH_mp.json")) as f:
            b = json.load(f)
        s = b["simulated"]
        line = (f" Mixed precision (bf16 compute, f32 masters): "
                f"{s['transformer']['reduction']:.2f}x simulated "
                f"step-makespan reduction on the transformer, "
                f"{s['dlrm']['reduction']:.2f}x on DLRM")
        wall = b.get("wallclock")
        if wall:
            line += (f"; {wall['speedup']:.2f}x wall-clock "
                     f"({wall['bfloat16']['tokens_per_sec']:,.0f} tok/s)")
        return line + " (`BENCH_mp.json`)."
    except (OSError, json.JSONDecodeError, KeyError):
        return ""


def serve_line() -> str:
    """Serving sentence from BENCH_serve.json (merge-by-metric JSONL
    via the shared reader, which also tolerates the legacy formats):
    the headline multipliers of the serving stack — prefix-cache
    prefill reduction, speculative step reduction, disaggregated
    TPOT-p99, and the multi-replica router's goodput-under-SLO gain
    (tools/serve_bench.py refreshes the JSON per --workload)."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from _bench_io import record_map
        recs = record_map(os.path.join(ROOT, "BENCH_serve.json"))
        parts = []
        pieces = (
            ("serve_prefill_token_reduction",
             "{v:.1f}x prefix-cache prefill reduction"),
            ("serve_decode_step_reduction",
             "{v:.1f}x speculative decode steps"),
            ("serve_kv_page_capacity",
             "{v:.1f}x int8 KV pages/byte"),
            ("serve_disagg_tpot_p99_reduction",
             "{v:.1f}x disaggregated TPOT p99"),
            ("serve_router_goodput_gain",
             "{v:.1f}x routed goodput-under-SLO vs round-robin"),
            ("serve_lora_goodput_gain",
             "{v:.1f}x batched-LoRA goodput vs weight swap"),
            ("serve_fabric_wall_goodput_gain",
             "{v:.1f}x threaded wall-clock goodput (wall==virtual)"),
            ("serve_host_tier_goodput_gain",
             "{v:.1f}x host-tier goodput vs eviction"),
            ("serve_boot_warm_speedup",
             "{v:.1f}x warm replica boot"),
            ("serve_mesh2d_goodput_gain",
             "{v:.1f}x 2-D mesh goodput vs best 1-D"),
        )
        for key, fmt in pieces:
            r = recs.get(key)
            if r is not None:
                parts.append(fmt.format(v=float(r["value"])))
        lora = recs.get("serve_lora_goodput_gain")
        if lora is not None:
            tenants = lora.get("extra", {}).get("tenants")
            if tenants:
                idx = [i for i, p in enumerate(parts)
                       if "batched-LoRA" in p]
                if idx:
                    parts[idx[0]] += f" ({int(tenants)} tenants)"
        # the boot record's cold-vs-warm seconds + programs restored
        # (the AOT program-cache A/B, serve_bench --workload boot)
        boot = recs.get("serve_boot_warm_speedup")
        if boot is not None:
            e = boot.get("extra", {})
            idx = [i for i, p in enumerate(parts)
                   if "warm replica boot" in p]
            if idx and "cold_ready_s" in e and "warm_ready_s" in e:
                parts[idx[0]] += (
                    f" ({e['cold_ready_s']:.2f}s cold -> "
                    f"{e['warm_ready_s']:.2f}s, "
                    f"{int(e.get('programs_restored', 0))} programs "
                    f"restored)")
        # the 2-D mesh record's searched shape (serve_bench
        # --workload mesh2d): which (t, r) the walk picked
        mesh = recs.get("serve_mesh2d_goodput_gain")
        if mesh is not None:
            e = mesh.get("extra", {})
            idx = [i for i, p in enumerate(parts)
                   if "2-D mesh goodput" in p]
            if idx and "searched_tensor" in e:
                parts[idx[0]] += (
                    f" (t={int(e['searched_tensor'])} x "
                    f"r={int(e['searched_replicas'])} over "
                    f"{int(e.get('devices', 0))} devices)")
        # SLO attainment from the EXPORTED pool registry gauge the
        # router workload recorded (serve_pool_slo_attainment — not an
        # ad-hoc stat string), and the worst simulator drift ratio
        # from the base workload's exported drift snapshot — the PR 10
        # render-from-metrics no-drift rule applied to the headline
        router = recs.get("serve_router_goodput_gain")
        if router is not None:
            att = router.get("extra", {}).get("slo_attainment_gauge")
            if att is None:
                att = router.get("extra", {}).get(
                    "slo_attainment_affinity")
            if att is not None:
                parts.append(f"{float(att):.0%} SLO attainment")
        base = recs.get("serve_decode_tokens_per_sec")
        if base is not None:
            drift = (base.get("extra", {}).get("telemetry", {})
                     or {}).get("drift_ratio_by_regime") or {}
            ratios = [float(v) for v in drift.values() if v]
            if ratios:
                worst = max(ratios, key=lambda r: abs(math.log(r))
                            if r > 0 else 0.0)
                parts.append(f"worst sim-drift ratio {worst:.2f}x "
                             f"over {len(ratios)} regimes")
        if not parts:
            return ""
        return (f" Serving: {', '.join(parts)} "
                f"(`BENCH_serve.json`).")
    except Exception:
        return ""


def main():
    with open(os.path.join(ROOT, "bench_all.json")) as f:
        bench = json.load(f)
    table, note = build_table(bench)
    if "--write" not in sys.argv:
        print(table)
        print()
        print(note)
        return 0
    path = os.path.join(ROOT, "README.md")
    with open(path) as f:
        text = f.read()
    start = text.find(BEGIN)
    if start < 0:  # legacy header variant: match on the stable prefix
        start = text.index("| Config | samples/s/chip |")
    # table ends at the first blank line after the header
    end = text.index("\n\n", start)
    # the paragraph after the table is the capture note — but ONLY
    # replace it if it really is one (starts with "Captured"); anything
    # else (a heading, a maintainer's paragraph) stays and the note is
    # inserted before it
    note_end = text.index("\n\n", end + 2)
    if not text[end + 2:note_end].lstrip().startswith("Captured"):
        note_end = end
    new = text[:start] + table + "\n\n" + note + text[note_end:]
    with open(path, "w") as f:
        f.write(new)
    print("README.md table refreshed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
