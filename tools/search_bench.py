"""Strategy-search throughput benchmark: delta simulation vs full
simulation (the perf-trajectory file for the search subsystem).

Runs the Python MCMC engine on the small-transformer config twice —
full simulation per proposal (the pre-delta baseline path,
--no-delta-sim) and delta simulation (Simulator.simulate_delta) — and
records proposals/sec for both, the speedup, and a delta-vs-full
makespan equivalence sweep (the same property tests/test_search_delta.py
asserts: the delta replay is exact, so max relative error must be ~0).

    python tools/search_bench.py            # full bench -> BENCH_search.json
    python tools/search_bench.py --smoke    # CI gate: 200-iteration
        search; FAILS (exit 1) if delta speedup < 2x or if delta/full
        makespans diverge beyond float tolerance

The JSON carries the machine-model fingerprint (search/cost_cache.py)
so committed numbers are attributable to one machine + cost-model state.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("SEARCH_BENCH_PLATFORM")
if _plat == "cpu" and "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the bench mesh is (2, 2, 2): give the virtual CPU platform 8
    # devices (must land before the first backend init)
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")

EQUIV_TOL = 1e-9  # delta replay is exact; anything above is a bug


def build_model():
    """Small-transformer search config (the acceptance-criteria graph)."""
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer

    cfg = FFConfig(batch_size=8)
    cfg.enable_parameter_parallel = True
    cfg.enable_sequence_parallel = True
    cfg.enable_propagation = True
    return build_transformer(cfg, batch_size=8, seq_len=64, hidden=128,
                             num_heads=4, num_layers=4, ff_dim=256,
                             num_classes=10)


def run_search(ff, mesh, budget, delta: bool, chains: int = 1,
               seed: int = 0):
    from flexflow_tpu.search.mcmc import optimize

    ff.config.search_delta_sim = delta
    t0 = time.perf_counter()
    strat = optimize(ff, budget=budget, mesh=mesh, seed=seed,
                     use_native=False, chains=chains)
    wall = time.perf_counter() - t0
    # proposals_per_sec comes from the annealing loop itself (stashed
    # on model.search_stats) — the fixed per-search setup (simulator
    # build, candidate enumeration, the interleaved-upgrade pricing) is
    # identical for both legs and would drown a short smoke run
    stats = dict(ff.search_stats)
    stats["optimize_wall_s"] = wall
    return strat, stats


def equivalence_sweep(ff, mesh, moves: int = 200, seed: int = 0):
    """Random rewrite walk asserting simulate_delta == simulate per
    move; returns the max relative makespan error observed."""
    import random

    from flexflow_tpu.parallel.pconfig import OpStrategy, Strategy
    from flexflow_tpu.search.mcmc import candidate_maps
    from flexflow_tpu.search.simulator import Simulator

    ff.config.search_delta_sim = True
    sim = Simulator(ff, mesh)
    cands = {op.name: candidate_maps(op, mesh, ff.config, i)
             for i, op in enumerate(ff.ops)}
    searchable = [op for op in ff.ops if len(cands[op.name]) > 1]
    cur = Strategy()
    for op in ff.ops:
        cur.set(op.name, cur.for_op(op.name).copy())
    assert sim.delta_rebase(cur), "delta template must apply here"
    rng = random.Random(seed)
    max_rel = 0.0
    for _ in range(moves):
        op = rng.choice(searchable)
        cur.set(op.name, OpStrategy(dict(rng.choice(cands[op.name]))))
        tok = sim.simulate_delta(cur, (op.name,))
        full = sim.simulate(cur)
        if tok is None:
            sim.delta_rebase(cur)
            continue
        max_rel = max(max_rel, abs(tok.cost - full) / max(full, 1e-30))
    return max_rel


def main():
    import jax

    from flexflow_tpu import make_mesh
    from flexflow_tpu.search.cost_cache import machine_fingerprint
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.utils.profiling import search_report

    smoke = "--smoke" in sys.argv
    budget = 200 if smoke else 4000
    gate = 2.0 if smoke else None

    ff = build_model()
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))

    # warm the cost caches so both legs price from the same state
    run_search(ff, mesh, 50, delta=True)

    # alternate the legs and take best-of-N per leg: the 2-core CI
    # hosts are shared, and a noisy neighbor mid-leg would skew a
    # single-shot ratio either way (observed 2x wall swings on
    # otherwise-idle containers)
    reps = 2 if smoke else 3
    full_runs, delta_runs = [], []
    for _ in range(reps):
        _, fs = run_search(ff, mesh, budget, delta=False)
        full_runs.append(fs)
        _, ds = run_search(ff, mesh, budget, delta=True)
        delta_runs.append(ds)
    full_stats = max(full_runs, key=lambda s: s["proposals_per_sec"])
    delta_stats = max(delta_runs, key=lambda s: s["proposals_per_sec"])
    max_rel = equivalence_sweep(ff, mesh,
                                moves=(60 if smoke else 200))

    pps_full = full_stats["proposals_per_sec"]
    pps_delta = delta_stats["proposals_per_sec"]
    speedup = pps_delta / pps_full if pps_full > 0 else 0.0
    sim = Simulator(ff, mesh)
    fingerprint = machine_fingerprint(sim.mm, mesh,
                                      precision=sim._precision(),
                                      overlap=sim.overlap_sig())
    records = [{
        "metric": "search_delta_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "extra": {
            "config": "small-transformer b8 s64 h128 4L, mesh d2xm2xs2",
            "platform": jax.default_backend(),
            "budget": budget,
            "proposals_per_sec_full": round(pps_full, 1),
            "proposals_per_sec_delta": round(pps_delta, 1),
            "runs_full": [round(s["proposals_per_sec"], 1)
                          for s in full_runs],
            "runs_delta": [round(s["proposals_per_sec"], 1)
                           for s in delta_runs],
            "delta_vs_full_max_rel_err": max_rel,
            "delta_stats": {k: v for k, v in delta_stats.items()
                            if isinstance(v, (int, float))},
            "fingerprint": fingerprint,
        },
    }]
    # search-trace convergence diagnostics (search/trace.SearchTrace):
    # acceptance rate (overall + by annealing phase), proposals/sec by
    # delta-vs-full simulation path, and the best-cost-curve tail
    trace = delta_stats.get("trace") or {}
    if trace:
        records.append({
            "metric": "search_trace",
            "value": round(trace.get("acceptance_rate", 0.0), 4),
            "unit": "acceptance_rate",
            "extra": {
                "platform": jax.default_backend(),
                "budget": budget,
                "acceptance_by_phase": [
                    round(p["rate"], 4)
                    for p in trace.get("acceptance_by_phase", [])],
                "by_path": trace.get("by_path", {}),
                "proposals_per_sec": {
                    "delta": round(pps_delta, 1),
                    "full": round(pps_full, 1)},
                "best_cost_curve_tail": trace.get(
                    "best_cost_curve", [])[-8:],
                "improvements": trace.get("improvements", 0),
                "events_recorded": trace.get("events_recorded", 0),
                "fingerprint": fingerprint,
            },
        })
    print(search_report(delta_stats))
    print(f"full: {pps_full:,.0f} proposals/s | "
          f"delta: {pps_delta:,.0f} proposals/s | "
          f"speedup {speedup:.2f}x | max rel err {max_rel:.2e}")

    if not smoke:
        path = os.path.join(ROOT, "BENCH_search.json")
        write_records(path, records)
        print(f"wrote {os.path.normpath(path)}")

    if gate is not None:
        ok = True
        if speedup < gate:
            print(f"FAIL: delta speedup {speedup:.2f}x < {gate}x gate")
            ok = False
        if max_rel > EQUIV_TOL:
            print(f"FAIL: delta/full makespans diverge "
                  f"(max rel err {max_rel:.2e} > {EQUIV_TOL})")
            ok = False
        if not trace:
            print("FAIL: search ran without a trace "
                  "(search_trace diagnostics missing)")
            ok = False
        if not ok:
            return 1
        print(f"smoke OK: speedup {speedup:.2f}x >= {gate}x, "
              f"delta == full within {EQUIV_TOL}, trace "
              f"{trace.get('proposals', 0)} proposals at "
              f"{trace.get('acceptance_rate', 0.0):.1%} acceptance")
    return 0


def write_records(path: str, records) -> None:
    """Merge-by-metric JSONL through the shared artifact writer
    (tools/_bench_io.py — serve_bench writes BENCH_serve.json through
    the same code): a partial run refreshes ITS records without
    clobbering others', tolerating individually corrupt lines in the
    old artifact. (Pre-PR-11 BENCH_search.json was one whole-file
    dict — such a line has no "metric" key and is simply
    superseded.)"""
    from _bench_io import write_records as _write
    _write(path, records)


if __name__ == "__main__":
    sys.exit(main())
