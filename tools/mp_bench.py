"""Mixed-precision benchmark: bf16 compute path vs f32 (BENCH_mp.json).

Three measurements, mirroring what the policy claims
(FFConfig.compute_dtype/param_dtype, docs/performance.md):

  1. SIMULATED step-makespan reduction bf16-vs-f32 on the TPU machine
     model, for the transformer (compute-bound) and a DLRM with
     MLPerf-size MLPs (gather/sync-heavy — the honest harder case).
     Pure cost-model arithmetic (search/cost_model.py prices flops at
     the per-dtype MXU rate and bytes at the actual itemsize), so it
     gates on CPU like PR 2/3's algorithmic gates.
  2. NUMERICS PARITY: train the same model f32 and bf16 (f32 master
     weights either way) for N steps on identical data and pin the
     bf16 loss curve to the f32 one within tolerance; the f32-master /
     f32-optimizer-state invariant is asserted on the live TrainState.
  3. WALL-CLOCK tokens/sec f32 vs bf16 when a real TPU backend is
     attached (skipped on CPU — XLA's CPU bf16 path is emulation and
     the number would be noise).

    python tools/mp_bench.py             # full run -> BENCH_mp.json
    python tools/mp_bench.py --smoke     # CI gate: FAILS (exit 1) if
        simulated reduction < 1.3x on either model or if the bf16
        loss curve drifts past tolerance

ci.sh runs the smoke as step 1e.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("MP_BENCH_PLATFORM")
if _plat == "cpu" and "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the simulated-reduction mesh is (4, 2): give the virtual CPU
    # platform 8 devices (must land before the first backend init)
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")

REDUCTION_GATE = 1.3
# bf16's ~8-bit mantissa wiggles each step; with f32 masters the walk
# stays on the f32 trajectory — 5% of the running loss magnitude holds
# with wide margin (observed ~0.3% on the transformer, docs/performance.md)
PARITY_TOL = 0.05


def _build_transformer(dtype_name):
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer

    cfg = FFConfig(batch_size=64)
    cfg.compute_dtype = dtype_name
    cfg.search_cost_cache = False
    return build_transformer(cfg, batch_size=64, seq_len=512, hidden=512,
                             num_heads=8, num_layers=6, ff_dim=2048,
                             num_classes=10, layer_norm=True)


def _build_dlrm(dtype_name):
    from flexflow_tpu import FFConfig
    from flexflow_tpu.core.optimizers import SGDOptimizer
    from flexflow_tpu.models.dlrm import build_dlrm

    cfg = FFConfig(batch_size=8192)
    cfg.compute_dtype = dtype_name
    cfg.search_cost_cache = False
    ff = build_dlrm(cfg, batch_size=8192,
                    embedding_vocab_sizes=(100000,) * 26,
                    embedding_dim=64, bot_mlp=(512, 256, 64),
                    top_mlp=(1024, 1024, 512, 256, 1))
    # sparse-exact row updates — what compile() will run; op_cost reads
    # the optimizer's sparse_mode through the model
    ff.optimizer = SGDOptimizer(lr=0.01)
    return ff


def simulated_reductions():
    """{model: {f32_s, bf16_s, reduction}} on the TPU machine model
    over a d4 x m2 mesh — the strategy-search view of the bf16 lever."""
    from flexflow_tpu import make_mesh
    from flexflow_tpu.parallel.pconfig import Strategy
    from flexflow_tpu.search.cost_cache import machine_fingerprint
    from flexflow_tpu.search.simulator import Simulator

    out = {}
    fingerprints = {}
    for name, build in (("transformer", _build_transformer),
                        ("dlrm", _build_dlrm)):
        times = {}
        for dt in ("float32", "bfloat16"):
            ff = build(dt)
            mesh = make_mesh((4, 2), ("data", "model"))
            sim = Simulator(ff, mesh)
            times[dt] = sim.simulate(Strategy())
            fingerprints[dt] = machine_fingerprint(
                sim.mm, mesh, precision=sim._precision(),
                overlap=sim.overlap_sig())
        out[name] = {
            "f32_s": times["float32"],
            "bf16_s": times["bfloat16"],
            "reduction": times["float32"] / times["bfloat16"],
        }
    # the two fingerprints MUST differ — same machine, different
    # precision policy — or the cost cache would replay stale entries
    out["fingerprint_f32"] = fingerprints.get("float32")
    out["fingerprint_bf16"] = fingerprints.get("bfloat16")
    return out


def _train_curve(ff, batch, steps):
    import numpy as np
    losses = []
    for _ in range(steps):
        losses.append(float(ff.train_batch(batch)["loss"]))
    assert all(np.isfinite(losses)), losses
    return losses


def _assert_master_f32(ff, model_name):
    """The invariant the policy promises: master params and optimizer
    state stay f32 while the step computes in bf16."""
    import jax
    for leaf in jax.tree_util.tree_leaves(ff.state.params):
        assert str(leaf.dtype) == "float32", (
            f"{model_name}: master param dtype {leaf.dtype}")
    for leaf in jax.tree_util.tree_leaves(ff.state.opt_state):
        assert str(leaf.dtype) == "float32", (
            f"{model_name}: optimizer slot dtype {leaf.dtype}")


def parity(steps):
    """Train f32 vs bf16 on identical data; returns per-model curves
    and the max relative loss divergence."""
    import numpy as np
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.dlrm import build_dlrm
    from flexflow_tpu.models.transformer import build_transformer

    results = {}
    rng = np.random.RandomState(0)

    def small_transformer(dt):
        cfg = FFConfig(batch_size=8)
        cfg.compute_dtype = dt
        ff = build_transformer(cfg, batch_size=8, seq_len=64, hidden=64,
                               num_heads=4, num_layers=2, ff_dim=128,
                               num_classes=10, layer_norm=True)
        ff.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    tbatch = {"input": rng.randn(8, 64, 64).astype(np.float32),
              "label": rng.randint(0, 10, 8).astype(np.int32)}

    def small_dlrm(dt):
        cfg = FFConfig(batch_size=32)
        cfg.compute_dtype = dt
        ff = build_dlrm(cfg, batch_size=32,
                        embedding_vocab_sizes=(1000,) * 8)
        ff.compile(loss_type="binary_crossentropy", metrics=[])
        return ff

    dbatch = {"dense_features": rng.randn(32, 13).astype(np.float32),
              "label": rng.randint(0, 2, (32, 1)).astype(np.float32)}
    for i in range(8):
        dbatch[f"sparse_{i}"] = rng.randint(
            0, 1000, (32, 1)).astype(np.int32)

    for name, build, batch in (("transformer", small_transformer, tbatch),
                               ("dlrm", small_dlrm, dbatch)):
        f32 = build("float32")
        bf16 = build("bfloat16")
        cf = _train_curve(f32, batch, steps)
        cb = _train_curve(bf16, batch, steps)
        _assert_master_f32(bf16, name)
        max_rel = max(abs(a - b) / max(1.0, abs(a))
                      for a, b in zip(cf, cb))
        results[name] = {"loss_f32": cf, "loss_bf16": cb,
                         "max_rel_divergence": max_rel}
    return results


def wallclock(steps=20):
    """tokens/sec f32 vs bf16 on a REAL backend; None on CPU (bf16 is
    emulated there and the ratio means nothing)."""
    import jax
    if jax.default_backend() != "tpu":
        return None
    import numpy as np
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer

    out = {}
    rng = np.random.RandomState(0)
    bs, seq = 32, 512
    batch_np = {"input": rng.randn(bs, seq, 512).astype(np.float32),
                "label": rng.randint(0, 10, bs).astype(np.int32)}
    for dt in ("float32", "bfloat16"):
        cfg = FFConfig(batch_size=bs)
        cfg.compute_dtype = dt
        ff = build_transformer(cfg, batch_size=bs, seq_len=seq,
                               hidden=512, num_heads=8, num_layers=6,
                               ff_dim=2048, num_classes=10,
                               layer_norm=True)
        ff.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        batch = ff.executor.shard_batch(batch_np)
        float(ff.train_batch(batch)["loss"])  # compile
        t0 = time.perf_counter()
        m = None
        for _ in range(steps):
            m = ff.train_batch(batch)
        float(m["loss"])  # device->host sync delimits timing
        dt_s = (time.perf_counter() - t0) / steps
        out[dt] = {"step_s": dt_s, "tokens_per_sec": bs * seq / dt_s}
    out["speedup"] = (out["float32"]["step_s"]
                      / out["bfloat16"]["step_s"])
    return out


def main():
    import jax

    smoke = "--smoke" in sys.argv
    out_path = None
    if "-o" in sys.argv:
        out_path = sys.argv[sys.argv.index("-o") + 1]

    sim = simulated_reductions()
    par = parity(steps=6 if smoke else 12)
    wall = None if smoke else wallclock()

    out = {
        "platform": jax.default_backend(),
        "simulated": sim,
        "parity": par,
        "parity_tol": PARITY_TOL,
        "reduction_gate": REDUCTION_GATE,
        "wallclock": wall,
    }
    for name in ("transformer", "dlrm"):
        s = sim[name]
        print(f"{name}: simulated f32 {s['f32_s']*1e6:.0f}us -> bf16 "
              f"{s['bf16_s']*1e6:.0f}us ({s['reduction']:.2f}x); "
              f"parity max rel divergence "
              f"{par[name]['max_rel_divergence']:.4f}")
    if wall:
        print(f"wall-clock: {wall['float32']['tokens_per_sec']:,.0f} -> "
              f"{wall['bfloat16']['tokens_per_sec']:,.0f} tok/s "
              f"({wall['speedup']:.2f}x)")

    if not smoke or out_path:
        path = out_path or os.path.join(ROOT, "BENCH_mp.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(f"wrote {os.path.normpath(path)}")

    ok = True
    for name in ("transformer", "dlrm"):
        r = sim[name]["reduction"]
        if r < REDUCTION_GATE:
            print(f"FAIL: {name} simulated bf16 reduction {r:.2f}x < "
                  f"{REDUCTION_GATE}x gate")
            ok = False
        d = par[name]["max_rel_divergence"]
        if d > PARITY_TOL:
            print(f"FAIL: {name} bf16 loss curve diverges from f32 "
                  f"({d:.4f} > {PARITY_TOL})")
            ok = False
    if sim["fingerprint_f32"] == sim["fingerprint_bf16"]:
        print("FAIL: cost-cache fingerprint does not separate "
              "precision policies")
        ok = False
    if not ok:
        return 1
    print(f"mp gates OK: reductions >= {REDUCTION_GATE}x, parity "
          f"within {PARITY_TOL}, fingerprints separate")
    return 0


if __name__ == "__main__":
    sys.exit(main())
