"""Shared platform selection for the on-chip/off-chip tools.

The one subtle invariant, in one place: "tpu" must NOT be forced into
jax_platforms — through the axon tunnel the TPU registers under the
"axon" plugin (forcing 'tpu' fails with "No jellyfish device found").
Leave the image default and verify the backend that actually came up.
"""

import os

import jax


def select_platform(env_var: str, default: str = "cpu") -> str:
    """Apply the tool's platform choice from `env_var`. Returns the
    requested platform name; raises SystemExit if tpu was requested but
    the ambient backend isn't one."""
    plat = os.environ.get(env_var, default)
    if plat != "tpu":
        jax.config.update("jax_platforms", plat)
    elif jax.devices()[0].platform != "tpu":
        # rc=75 (EX_TEMPFAIL) is the shared tunnel-signature exit
        # code: the axon plugin failed fast and jax fell back to CPU.
        # The session queue (tools/tpu_session.sh note_rc) treats it
        # like a timeout so the skipped step re-runs at the next
        # window. (Not 1-5: pytest owns those; not 124/137: timeout.)
        import sys
        print(f"{env_var}=tpu but the default backend is "
              f"{jax.devices()[0].platform} (tunnel down?)",
              file=sys.stderr)
        raise SystemExit(75)
    return plat
