#!/bin/bash
# Committed CI gate — the reference's .circleci/config.yml analog
# (build + pytest + multi-GPU script tests + accuracy tests per
# commit). Everything here runs on the virtual 8-device CPU platform,
# so it needs no hardware and cannot be blocked by the TPU tunnel.
#
#   bash tools/ci.sh          # fast gate: default pytest profile
#                             #   (<~5 min) + multichip dryrun +
#                             #   3 example smokes
#   bash tools/ci.sh --full   # + the slow remainder (-m slow):
#                             #   example zoo, model smokes,
#                             #   multiprocess, pipelines (~35 min)
#
# Writes .scratch/ci_last_green (HEAD sha + UTC stamp + mode) on
# success; EVIDENCE.md cites that file as the last green run.
set -u -o pipefail
cd "$(dirname "$0")/.."
FULL="${1:-}"
fail=0

echo "=== ci $(date -u +%FT%TZ) HEAD=$(git rev-parse --short HEAD) mode=${FULL:-fast} ==="

echo "--- 1. fast CPU suite (default profile: -m 'not slow')"
# --continue-on-collection-errors keeps one broken module from masking
# the rest of the suite, but a module that fails to COLLECT must still
# gate: pytest's "N errors" summary only appears for collection/setup
# errors, so grep the log and flip fail even when the run "passes".
python -m pytest tests/ -q --continue-on-collection-errors 2>&1 \
    | tee /tmp/ci_tier1.log || fail=1
if grep -qaE '^ERROR |^[0-9]+ errors?|[0-9]+ errors? in ' /tmp/ci_tier1.log
then
  echo "!!! pytest collection errors (see above) — failing the gate"
  fail=1
fi

echo "--- 1c. search-bench smoke (delta-sim speedup + equivalence gate)"
# fails if the delta path's speedup over full simulation is < 2x or if
# delta/full makespans diverge (tools/search_bench.py --smoke)
env JAX_PLATFORMS=cpu python tools/search_bench.py --smoke || fail=1

echo "--- 1d. serve-bench smoke (zero recompiles + prefix-cache gate)"
# fails if serving compiles anything after warmup, if prefix-cached
# outputs diverge from generate_reference, or if the shared-prefix
# workload's prefill-token reduction is < 2x (tools/serve_bench.py)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload base \
    -o /tmp/ci_bench_serve.json || fail=1

echo "--- 1e. mixed-precision smoke (bf16 makespan + parity gate)"
# fails if the simulated bf16 step-makespan reduction on the TPU
# machine model is < 1.3x (transformer or DLRM), if the bf16 loss
# curve drifts from f32 past tolerance, or if the cost-cache
# fingerprint fails to separate precision policies (tools/mp_bench.py)
env JAX_PLATFORMS=cpu python tools/mp_bench.py --smoke \
    -o /tmp/ci_bench_mp.json || fail=1

echo "--- 1f. speculative-decode smoke (step-reduction + exactness gate)"
# fails if the repetitive-text workload's decode-step reduction is
# < 1.5x, if speculative (or baseline) outputs diverge from
# generate_reference, or if anything compiles after warmup
# (tools/serve_bench.py --workload spec)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload spec \
    -o /tmp/ci_bench_serve_spec.json || fail=1

echo "--- 1g. chaos smoke (fault-injected serving gate)"
# the base workload under a SEEDED fault spec (transient dispatch
# errors + page-pool exhaustion) plus a cancel/deadline storm: fails
# unless every surviving request is token-identical to
# generate_reference, PagedKVCache.check_invariants holds after every
# step, every page is reclaimed, and nothing compiles after warmup
# (docs/robustness.md)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload base \
    --fault-spec 'serve.mixed:transient@3,6,11;serve.page_pressure:exhaust:0.9@4-9' \
    -o /tmp/ci_bench_serve_chaos.json || fail=1

echo "--- 1h. train-bench smoke (async runtime >= 1.10x + exactness gate)"
# fails if the overlapped training runtime (grouped dispatch + depth-2
# window + bucketed grad sync) is < 1.10x faster per step than the
# synchronous path on dlrm OR transformer, if the loss trajectories are
# not bit-identical, if anything compiles after warmup, or if the
# simulator prices overlapped sync slower than serialized
# (tools/train_bench.py)
env JAX_PLATFORMS=cpu python tools/train_bench.py --smoke \
    -o /tmp/ci_bench_train.json || fail=1

echo "--- 1i. kv-quantization smoke (int8 page capacity + parity gate)"
# int8 KV pages vs f32 at an EQUAL pool byte budget: fails unless the
# effective page capacity is >= 1.9x, the same requests run at higher
# decode concurrency in fewer engine steps, int8 greedy outputs hold
# token parity with the no-cache reference up to tie-margin flips
# (and are chunk-boundary invariant), and nothing compiles after
# warmup. The f32 arm also re-gates kernel-v2 bit-exactness + zero
# recompiles (tools/serve_bench.py --workload kv, docs/serving.md)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload kv \
    -o /tmp/ci_bench_serve_kv.json || fail=1

echo "--- 1j. sharded-serving smoke (tensor-parallel parity + sim speedup gate)"
# the SAME model served single-device vs head-sharded over a forced
# 4-device host mesh: fails unless greedy outputs are token-identical,
# nothing compiles after warmup, the per-device KV pool and dispatched
# FLOPs shrink ~4x, and the placement search's simulated v5e
# decode-step latency at t=4 is >= 1.5x better than t=1 on the
# Gemma-31B-class serving arch (tools/serve_bench.py --workload shard,
# docs/serving.md "Sharded serving")
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python tools/serve_bench.py --smoke --workload shard \
    -o /tmp/ci_bench_serve_shard.json || fail=1

echo "--- 1k. telemetry smoke (trace export + metrics + <=3% overhead gate)"
# telemetry-on serving must be token-identical to telemetry-off with
# zero recompiles at <= 3% wall overhead (min paired on/off block
# ratio, order-alternating interleave); the
# exported Chrome trace must load with well-formed per-request/per-step
# tracks (every ts/dur/pid/tid checked), the Prometheus text must
# parse, the metrics snapshot must carry the required TTFT/TPOT/pool/
# robustness keys, and drift_report must price every measured serve
# regime (tools/serve_bench.py --workload telemetry,
# docs/observability.md)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload telemetry \
    --trace-out /tmp/ci_serve_trace.json \
    -o /tmp/ci_bench_serve_telemetry.json || fail=1

echo "--- 1l. observability smoke (simulated-trace + search-trace + ledger + endpoint gate)"
# explainable-search tentpole (tools/explain.py --smoke,
# docs/observability.md): the exported simulated-schedule trace must be
# Perfetto-schema-valid with its end time bit-equal to the simulator's
# returned makespan (train + serve); search tracing on vs off must be
# bit-identical at the same seed with the search_trace record present
# in BENCH_search.json; the HBM memory ledger must match the live
# device buffers within 5% on a real ServeEngine (explain_placement
# component sums exact); and the --metrics-port endpoint must serve a
# parseable /metrics page + /healthz, going down cleanly on close().
# The 1k telemetry-overhead gate above is unchanged.
env JAX_PLATFORMS=cpu python tools/explain.py --smoke || fail=1

echo "--- 1m. disaggregated-serving smoke (TPOT-p99 + handoff exactness gate)"
# unified vs prefill/decode-disaggregated serving under mixed
# heavy-prefill + steady-decode traffic at equal device count: fails
# unless the cluster's outputs are token-identical to the unified
# engine (pages crossed the handoff link), nothing compiles after
# DisaggCluster.warmup() on either role, and the TPOT-p99 reduction —
# measured on this host or simulated by the ratio search (priced
# page-transfer link, Gemma-31B-class arch on 16 v5e chips) — is
# >= 1.3x (tools/serve_bench.py --workload disagg, docs/serving.md
# "Disaggregated serving")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload disagg \
    -o /tmp/ci_bench_serve_disagg.json || fail=1

echo "--- 1n. multi-replica router smoke (goodput-under-SLO + exactness gate)"
# prefix-affinity routing vs round-robin over a 3-replica simulated
# cluster on a seeded multi-tenant prefix mix (Poisson arrivals,
# heavy-tailed lengths, cancels, seeded sampling; virtual time priced
# by the cost model): fails unless affinity's goodput-under-SLO is
# >= 1.3x round-robin's, every completed request is token-identical
# to a single replica serving the same stream ids, no replica
# compiles after its own warmup, every page reclaims after drain,
# and the telemetry-driven autoscaler's decisions replay identically
# across two runs with spans emitted (tools/serve_bench.py
# --workload router, docs/serving.md "Multi-replica routing")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload router \
    -o /tmp/ci_bench_serve_router.json || fail=1

echo "--- 1o. SLO burn-rate + flight-recorder smoke (request-observability gate)"
# the request-observability tentpole (docs/observability.md): the SLO
# burn-rate monitor must fire AND clear on a deterministic outage
# history, replay bit-identically, and export parseable burn gauges
# (tools/slo_report.py --smoke, no jax — pure host python); the
# failure flight recorder must leave a loadable, schema-valid
# post-mortem bundle when a chaos-injected FATAL dispatch fault aborts
# a real engine mid-batch (plus deadline-storm and explicit triggers),
# with the engine still serving afterwards (tools/postmortem.py
# --smoke). The 1k <=1.03x telemetry-overhead gate is unchanged.
python tools/slo_report.py --smoke || fail=1
env JAX_PLATFORMS=cpu python tools/postmortem.py --smoke || fail=1

echo "--- 1p. multi-tenant LoRA smoke (batched-pool goodput + exactness gate)"
# batched multi-tenant adapter serving vs a sequential per-tenant
# weight-swap server on a Zipf tenant mix: fails unless the batched
# pool's goodput (mixed steps for the same token set) is >= 1.5x the
# swap server's, every stream is token-identical to its tenant's
# merged-weight reference, and nothing compiles after warmup on
# either arm — adapter loads are dispatches of the one scatter
# program, never recompiles (tools/serve_bench.py --workload lora,
# docs/serving.md "Multi-tenant adapters")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload lora \
    -o /tmp/ci_bench_serve_lora.json || fail=1

echo "--- 1q. wall-clock fabric smoke (wall==virtual identity + concurrency gate)"
# the wall-clock twin of the serving tier: the same seeded traffic on
# the virtual clock vs the threaded and single-threaded wall clock —
# fails unless all three arms are token-identical at one seed
# (sampling keys on stream ids, never on the clock), the threaded
# wall goodput-under-SLO is >= 1.3x the single-threaded baseline
# (per-step device dwell overlapping across replica worker threads),
# and the disaggregated cluster's continuous-pipelined and
# --transport tcp (loopback socket PageShipment frames) arms match
# the phased in-process handoff token-for-token
# (tools/serve_bench.py --workload fabric, docs/serving.md
# "Wall-clock mode")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload fabric \
    -o /tmp/ci_bench_serve_fabric.json || fail=1

echo "--- 1r. host-tier prefix-cache smoke (spill-vs-recompute goodput gate)"
# the hierarchical prefix-cache tier (serve/host_tier.py): on a
# working-set-larger-than-pool multi-tenant stream, pages evicted
# under HBM pressure spill their bytes to a shared host-RAM store and
# reload through the existing fixed-shape import scatter when the
# DMA priced by TPUMachineModel.host_transfer beats prefill recompute
# — fails unless the host-tier arm's goodput-under-SLO is >= 1.3x
# BOTH plain eviction and rung-3-style no-match degradation, every
# completed request is token-identical to a single reference engine,
# nothing compiles after warmup (spill/reload reuse the export/import
# handoff programs), and spills + priced reload decisions actually
# happened (tools/serve_bench.py --workload spill, docs/serving.md
# "Hierarchical prefix cache")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload spill \
    -o /tmp/ci_bench_serve_spill.json || fail=1

echo "--- 1s. warm replica boot smoke (AOT program-cache gate)"
# the ProgramRegistry AOT compile cache (core/programs.py,
# --program-cache-dir): a cold engine compiles + snapshots its
# executables, and a second engine over the same program fingerprint
# must boot from the deserialized snapshot — fails unless
# time-to-first-token-ready drops >= 2x, the warm arm's
# compile_counts() report ZERO compiles (the registry counts exactly,
# so a hidden compile cannot pass), its greedy tokens equal the
# in-process cold engine's bit-for-bit, and a corrupted/truncated
# store falls back to compile-with-warning instead of crashing (the
# cost_cache.py corrupt-store discipline)
# (tools/serve_bench.py --workload boot, docs/performance.md
# "Warm boot")
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload boot \
    -o /tmp/ci_bench_serve_boot.json || fail=1

echo "--- 1t. 2-D serve-mesh placement smoke (search-vs-degenerate gate)"
# the 2-D placement search (search/serve_place.optimize_serve_mesh,
# docs/search.md "2-D serve mesh"): ONE walk prices tensor degree x
# replica count x HBM residency into goodput-under-SLO, and a pool
# booted from the searched (t, r) must beat BOTH degenerate
# allocations of the same 4-device budget — best tp-only (r=1,
# arrivals queue past the TTFT SLO) and best replicas-only (t=1, the
# model over-fills one device's HBM so every step pays the reference
# 1ms/MB penalty and blows TPOT; the search rejects t=1 up front,
# never pricing it) — by >= 1.3x, with shared-prefix tenants + the
# armed LoRA adapter pool, token identity vs one reference engine,
# and zero recompiles after warmup
# (tools/serve_bench.py --workload mesh2d)
env JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke --workload mesh2d \
    -o /tmp/ci_bench_serve_mesh2d.json || fail=1

if [ "$FULL" = "--full" ]; then
  echo "--- 1b. slow remainder (-m slow)"
  python -m pytest tests/ -q -m slow --continue-on-collection-errors 2>&1 \
      | tee /tmp/ci_tier1_slow.log || fail=1
  if grep -qaE '^ERROR |^[0-9]+ errors?|[0-9]+ errors? in ' \
      /tmp/ci_tier1_slow.log
  then
    echo "!!! pytest collection errors (slow profile) — failing the gate"
    fail=1
  fi
fi

echo "--- 2. multichip dryrun (all parallel axes on 8 virtual devices)"
env XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    JAX_PLATFORMS=cpu python -c "
import jax; jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
g.dryrun_multichip(8)
fn, args = g.entry(); jax.jit(fn)(*args)
print('entry() compile OK')" || fail=1

echo "--- 3. example smokes (native / frontend / keras)"
timeout 300 python -m flexflow_tpu --cpu-devices 2 \
    examples/python/native/alexnet.py -b 8 --samples 16 -e 1 \
    >/dev/null || fail=1
timeout 300 python -m flexflow_tpu --cpu-devices 2 \
    examples/python/pytorch/mnist_mlp_torch.py -e 1 \
    >/dev/null || fail=1
timeout 300 python -m flexflow_tpu --cpu-devices 2 \
    examples/python/keras/mnist_mlp.py -e 1 >/dev/null || fail=1
echo "example smokes rc=$fail"

if [ "$fail" -eq 0 ]; then
  mkdir -p .scratch
  echo "$(git rev-parse HEAD) $(date -u +%FT%TZ) mode=${FULL:-fast}" \
      > .scratch/ci_last_green
  echo "=== ci GREEN ==="
else
  echo "=== ci RED ==="
fi
exit "$fail"
