"""Inception-v3 conv audit: where does the MFU go? (VERDICT r2 #3)

Prints, for the bench config (299px, bf16):
  1. the analytic per-op table (utils/profiling.op_profile);
  2. XLA's own cost analysis of the compiled train step per conv
     layout (NCHW vs NHWC) — flops, bytes, and the flops/byte the
     compiled program actually has after fusion;
  3. a tiling audit: convs whose channel counts miss the 128-lane MXU
     tile or whose odd spatial dims (299 -> 149 -> 74...) force
     padding, the usual culprits for conv MFU well below the GEMM
     fraction (reference conv_2d.cu:173-260 works around the cuDNN
     analog with per-shape algorithm selection);
  4. measured ms/step per layout when the backend is usable.

Run on TPU (tools/tpu_session.sh step 3 does the timed A/B); on CPU it
still prints 1-3 with a small image size.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from flexflow_tpu.utils import profiling

    import bench  # the SAME config the bench measures — no drift

    on_cpu = jax.devices()[0].platform == "cpu"
    preset = "tiny" if on_cpu else "full"

    def build(layout):
        os.environ["BENCH_CONV_LAYOUT"] = layout
        return bench.build("inception", preset)

    ff, data = build("NCHW")
    batch, size = data["input"].shape[0], data["input"].shape[-1]

    # ---- 3. tiling audit (static, layout-independent) ----
    print("=== tiling audit: convs vs the (8, 128) TPU tile ===")
    flagged = 0
    for op in ff.ops:
        if op.op_type != "conv2d":
            continue
        n, c_in, h, w = op.inputs[0].shape
        c_out = op.out_channels
        notes = []
        if c_in % 128 and c_in > 16:
            notes.append(f"cin {c_in} % 128 != 0")
        if c_out % 128:
            notes.append(f"cout {c_out} % 128 != 0")
        if h % 2 or w % 2:
            notes.append(f"odd spatial {h}x{w} (stride pads)")
        if notes:
            flagged += 1
            print(f"  {op.name:28s} ({c_in:4d}->{c_out:4d}, {h}x{w}): "
                  + "; ".join(notes))
    print(f"  {flagged} convs flagged")

    # ---- 1. analytic table ----
    print("\n=== analytic per-op profile (top of the table) ===")
    print("\n".join(profiling.op_profile(ff).splitlines()[:20]))

    # ---- 2 + 4. per-layout compiled cost + measured time ----
    # (CPU: one layout only — a second full inception compile takes
    # minutes and the layout knob is a TPU question; the timed A/B runs
    # in tools/tpu_session.sh step 3)
    results = {}
    for layout in (("NCHW",) if on_cpu else ("NCHW", "NHWC")):
        ffl = ff if layout == "NCHW" else build(layout)[0]
        cost = profiling.hlo_cost(ffl, data)
        entry = {"xla_flops": cost.get("flops"),
                 "xla_bytes": cost.get("bytes accessed")}
        if entry["xla_flops"] and entry["xla_bytes"]:
            entry["flops_per_byte"] = round(
                entry["xla_flops"] / entry["xla_bytes"], 2)
        try:
            entry["ms_per_step"] = round(
                profiling.time_train_steps(ffl, data, steps=10) * 1e3, 3)
        except Exception as e:  # pragma: no cover - backend-specific
            entry["ms_per_step"] = None
            print(f"  (timing unavailable for {layout}: {e})")
        results[layout] = entry
        print(f"\n=== {layout}: XLA cost analysis ===")
        print(json.dumps(entry))

    print("\n" + json.dumps({"audit": "inception", "batch": batch,
                             "image": size, "layouts": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
