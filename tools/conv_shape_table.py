"""Per-shape conv cost table: measured vs analytic for every distinct
conv signature in the conv-heavy bench models (VERDICT r3 #2 — the
analog of the reference's per-shape cuDNN algorithm selection,
/root/reference/src/ops/conv_2d.cu:173-260).

For each distinct Conv2D signature in Inception-v3 and AlexNet at the
EXACT bench configs (reusing bench.build, so the shapes cannot drift
from what bench.py measures): the measured isolated-kernel fwd+bwd
time (search/op_measure.py — the same memoized measurements
--measure-ops reads, so this run warms the per-machine cache for
unsharded/single-chip searches; data-sharded candidates measure at
their own sub-shape), the analytic roofline prediction, and the
implied achieved MXU fraction. Sorted by measured time: the top rows
are where Inception's MFU lives, and a row whose achieved fraction is
far below the calibrated conv efficiency is a specific shape worth a
layout/padding fix or a Pallas kernel.

Writes evidence/conv_shape_table_<platform>.json. On-chip run = step
4 of tools/tpu_session.sh (CONV_TABLE_PLATFORM=tpu).
"""

import json
import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("CONV_TABLE_PLATFORM")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from flexflow_tpu.search.machine_model import default_machine_model  # noqa: E402
from flexflow_tpu.search.measure import calibrated_machine_model  # noqa: E402
from flexflow_tpu.search.op_measure import measure_op, op_signature  # noqa: E402


def conv_rows(model, mm, repeats):
    from flexflow_tpu.search.cost_model import op_cost
    from flexflow_tpu.parallel.pconfig import OpStrategy
    from flexflow_tpu.parallel.mesh import make_mesh
    mesh = make_mesh((1,), ("data",))
    seen = {}
    for op in model.ops:
        if op.op_type != "conv2d":
            continue
        sig = op_signature(op, 1)
        if sig in seen:
            seen[sig]["count"] += 1
            continue
        c = op_cost(op, OpStrategy({}), mesh, mm)
        m = measure_op(op, sample_shard=1, repeats=repeats)
        row = {
            "example_op": op.name,
            "count": 1,
            "in_shape": list(op.inputs[0].shape),
            "out_shape": list(op.outputs[0].shape),
            "flops": op.flops(),
            "analytic_fwd_us": c.fwd * 1e6,
        }
        if m is not None:
            row["measured_fwd_us"] = m["fwd"] * 1e6
            row["measured_bwd_us"] = m["bwd"] * 1e6
            row["achieved_mxu_fraction"] = min(
                1.0, op.flops() / m["fwd"] / mm.spec.peak_flops)
            row["measured_over_analytic"] = m["fwd"] / max(c.fwd, 1e-12)
        seen[sig] = row
    return sorted(seen.values(),
                  key=lambda r: -r.get("measured_fwd_us", 0.0))


def main():
    platform = jax.default_backend()
    mm = (calibrated_machine_model() if platform == "tpu"
          else default_machine_model())
    repeats = 10 if platform == "tpu" else 3
    out = {"platform": platform,
           "conv_efficiency_factor": mm.efficiency.get("conv"),
           "models": {}}
    import bench  # the SAME configs the bench measures — no drift
    # (honors BENCH_BATCH / BENCH_CONV_LAYOUT session knobs too)
    for name in ("inception", "alexnet"):
        model, _data = bench.build(name, "full")
        rows = conv_rows(model, mm, repeats)
        out["models"][name] = rows
        print(f"[{name}] {len(rows)} distinct conv shapes")
        for r in rows[:6]:
            frac = r.get("achieved_mxu_fraction")
            print(f"  {str(r['in_shape']):24s} -> "
                  f"{str(r['out_shape']):24s} x{r['count']:<3d} "
                  f"measured {r.get('measured_fwd_us', float('nan')):9.1f}us"
                  f"  mxu {frac if frac is None else round(frac, 3)}")
    path = os.path.join(os.path.dirname(__file__), "..", "evidence",
                        f"conv_shape_table_{platform}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {os.path.normpath(path)}")


if __name__ == "__main__":
    main()
