"""Shared BENCH_*.json artifact I/O: merge-by-metric JSONL.

Every bench artifact in this repo (BENCH_serve.json, BENCH_search.json,
BENCH_train.json, ...) is one JSON record per line keyed by "metric".
``write_records`` merges new records over the old artifact so a
partial run (one ``--workload``, one smoke arm) refreshes ITS lines
without clobbering the others', and the line-by-line legacy parser
tolerates individually corrupt lines AND pre-JSONL whole-file dicts
(they carry no "metric" key and are simply superseded) — one bad line
never drops every other workload's history. serve_bench and
search_bench both write through here; tools/perf_report.py reads
through ``read_records``.
"""

from __future__ import annotations

import json
from typing import Dict, List


def read_records(path: str) -> List[dict]:
    """Every well-formed {"metric": ...} record in the artifact, in
    file order; unreadable lines (and legacy non-record lines) are
    skipped, a missing file reads as empty."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    r = json.loads(ln)
                except ValueError:
                    continue   # skip the bad line, keep the rest
                if isinstance(r, dict) and "metric" in r:
                    out.append(r)
    except OSError:
        pass
    return out


def record_map(path: str) -> Dict[str, dict]:
    """read_records folded metric -> record (last line wins)."""
    return {r["metric"]: r for r in read_records(path)}


def write_records(path: str, records: List[dict]) -> None:
    """Merge `records` into the artifact by metric name and rewrite
    it as JSONL (old records whose metric was not refreshed are
    preserved verbatim)."""
    merged = {**record_map(path), **{r["metric"]: r for r in records}}
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(r) for r in merged.values())
                + "\n")
