"""Flash-attention dispatch-threshold sweep (EVIDENCE.md row 3).

Measures the Pallas flash kernel vs the XLA einsum path, fwd+bwd, over
the (seq, head_dim) grid the `flash_profitable` gate
(kernels/flash_attention.py) claims to encode, and writes the table to
evidence/ — the committed artifact behind the heuristic's constants.
Reference analog: per-shape cuDNN algorithm selection
(/root/reference/src/ops/conv_2d.cu:173-260) — measured, not folklore.

  FLASH_SWEEP_PLATFORM=tpu python tools/flash_sweep.py   # on-chip
"""

import json
import os
import sys
import time
from datetime import datetime, timezone

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("FLASH_SWEEP_PLATFORM")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from flexflow_tpu.kernels.flash_attention import (  # noqa: E402
    flash_attention_bshd, flash_profitable)

B, H = 8, 8  # the bench transformer's batch/head scale


def xla_attention(q, k, v, causal):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        lq, lk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def timed(f, args, iters=8):
    y = f(*args)
    jnp.ravel(jax.tree_util.tree_leaves(y)[0])[0].item()  # sync (tunnel)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(*args)
    jnp.ravel(jax.tree_util.tree_leaves(y)[0])[0].item()
    return (time.perf_counter() - t0) / iters


def main():
    interpret = _plat != "tpu"
    rows = []
    grid = [(s, d, c) for s in (512, 1024, 2048) for d in (64, 128)
            for c in (False, True)]
    if interpret:
        grid = [(256, 128, False)]  # smoke-scale off-chip
    rng = np.random.RandomState(0)
    for sq, d, causal in grid:
        q, k, v = (jnp.asarray(rng.randn(B, sq, H, d) * 0.1, jnp.bfloat16)
                   for _ in range(3))

        def loss_f(q, k, v):
            return jnp.sum(flash_attention_bshd(
                q, k, v, causal=causal,
                interpret=interpret).astype(jnp.float32))

        def loss_x(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal).astype(
                jnp.float32))

        row = {"b": B, "h": H, "sq": sq, "sk": sq, "d": d,
               "causal": causal,
               "gate_says_flash": flash_profitable(B, H, sq, sq, d)}
        try:
            row["flash_fwdbwd_us"] = round(timed(
                jax.jit(jax.grad(loss_f, argnums=(0, 1, 2))),
                (q, k, v)) * 1e6)
        except Exception as e:  # unsupported shape -> XLA is the only path
            row["flash_fwdbwd_us"] = None
            row["flash_error"] = str(e)[:100]
        if d < 128:
            # the d=64 decider: pad_lanes=False hands Mosaic the raw
            # head_dim, halving the kernel's dot FLOPs vs the always-
            # safe 128-lane padding — the arm that could flip the gate
            # for the bench transformer (h512/8 heads -> d=64)
            def loss_np(q, k, v):
                return jnp.sum(flash_attention_bshd(
                    q, k, v, causal=causal, pad_lanes=False,
                    interpret=interpret).astype(jnp.float32))
            try:
                row["flash_nopad_fwdbwd_us"] = round(timed(
                    jax.jit(jax.grad(loss_np, argnums=(0, 1, 2))),
                    (q, k, v)) * 1e6)
            except Exception as e:
                row["flash_nopad_fwdbwd_us"] = None
                row["flash_nopad_error"] = str(e)[:100]
        row["xla_fwdbwd_us"] = round(timed(
            jax.jit(jax.grad(loss_x, argnums=(0, 1, 2))), (q, k, v)) * 1e6)
        # gate_correct judges ONLY the shipped (padded) dispatch the
        # gate controls; the nopad arm gets its own key so a would-be
        # win by a non-dispatchable kernel reads as a retune
        # OPPORTUNITY, not a gate error
        if row["flash_fwdbwd_us"] is not None:
            row["flash_wins"] = row["flash_fwdbwd_us"] < row["xla_fwdbwd_us"]
            row["gate_correct"] = row["flash_wins"] == row["gate_says_flash"]
        if row.get("flash_nopad_fwdbwd_us") is not None:
            row["flash_nopad_wins"] = (row["flash_nopad_fwdbwd_us"]
                                       < row["xla_fwdbwd_us"])
        print(row, flush=True)
        rows.append(row)
    out = {"platform": _plat,
           "device": str(jax.devices()[0].device_kind),
           "captured": datetime.now(timezone.utc).strftime(
               "%Y-%m-%dT%H:%M:%SZ"),
           "rows": rows}
    path = os.path.join(os.path.dirname(__file__), "..", "evidence",
                        f"flash_sweep_{_plat}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {os.path.normpath(path)}")
    mis = [r for r in rows if r.get("gate_correct") is False]
    if mis:
        print(f"GATE MISPREDICTS {len(mis)} shapes — re-tune "
              f"flash_profitable:", *mis, sep="\n")
    opp = [r for r in rows
           if r.get("flash_nopad_wins") and not r.get("gate_says_flash")]
    if opp:
        print(f"NOPAD OPPORTUNITY on {len(opp)} shapes — the d<128 "
              f"pad_lanes=False kernel beats XLA where the shipped "
              f"gate stays off:", *opp, sep="\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
