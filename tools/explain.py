"""Explainable-placement CLI + the observability CI gate.

Two modes:

* Default: run (or reuse) a strategy search on the small-transformer
  config, print the explain_placement report (per-op chosen config,
  cost breakdown, top-k rejected alternatives), the search-trace
  convergence diagnostics, and the HBM memory ledger; optionally
  export the winning strategy's simulated schedule as a
  Perfetto-loadable trace (--trace) and dump everything as JSON (-o).

      python tools/explain.py --budget 1000 --trace /tmp/sched.json
      python tools/explain.py --serve          # serve-placement side

* --smoke (tools/ci.sh step 1l): gates the observability tentpole —
    1. simulated-schedule trace validity: Perfetto schema well-formed
       AND the trace's exact end time equals Simulator.simulate's
       returned makespan bit-exactly (train) / simulate_serve_step's
       (serve);
    2. search tracing is pure observation: tracing on vs off at the
       same seed returns bit-identical strategies, with the trace
       populated — and the committed BENCH_search.json artifact
       carries the search_trace record;
    3. HBM memory ledger within 5% of the actual nbytes of the live
       device buffers on a real ServeEngine, and explain_placement
       component sums exact;
    4. /metrics + /healthz endpoint scrape success on an engine with
       --metrics-port, clean shutdown on close().
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("EXPLAIN_PLATFORM")
if _plat == "cpu" and "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def build_model(budget=0):
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer

    cfg = FFConfig(batch_size=8)
    cfg.enable_parameter_parallel = True
    cfg.enable_sequence_parallel = True
    cfg.search_budget = budget
    return build_transformer(cfg, batch_size=8, seq_len=64, hidden=128,
                             num_heads=4, num_layers=4, ff_dim=256,
                             num_classes=10)


def build_lm(metrics_port=None):
    from flexflow_tpu import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm

    cfg = FFConfig(batch_size=1, kv_page_size=8, kv_num_pages=73,
                   serve_max_seqs=8, serve_prefill_budget=48,
                   serve_retry_backoff_s=0.0)
    cfg.metrics_port = metrics_port
    return build_transformer_lm(cfg, vocab_size=89, max_seq_len=64,
                                hidden=32, num_heads=4, num_layers=2,
                                ff_dim=64)


def serve_arch():
    """The Gemma-31B-class serving arch the sharded-serving bench
    prices (tools/serve_bench.py --workload shard)."""
    from flexflow_tpu.search.cost_model import ServeArch
    return ServeArch(num_layers=48, hidden=6144, num_heads=48,
                     head_dim=128, ff_dim=24576, vocab=256000,
                     decode_lanes=8, prefill_lanes=512, context=2048,
                     act_itemsize=2.0, act_dtype="bfloat16",
                     param_itemsize=2.0)


def check_trace_schema(path):
    """Perfetto schema check shared by smoke and ci: returns the doc
    after asserting every event is well-formed."""
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc.get("traceEvents"), list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert isinstance(ev.get("ph"), str) and ev.get("name"), ev
        assert isinstance(ev.get("pid"), int) \
            and isinstance(ev.get("tid"), int), ev
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) \
                and ev["ts"] >= 0, ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) \
                and ev["dur"] >= 0, ev
    return doc


def smoke() -> int:
    import numpy as np

    from flexflow_tpu import make_mesh
    from flexflow_tpu.search.mcmc import optimize
    from flexflow_tpu.search.simulator import (Simulator,
                                               export_serve_schedule,
                                               simulate_serve_step)
    from flexflow_tpu.serve import ServeEngine

    gates = []

    # ---- 1. simulated-schedule trace validity (train + serve) ------
    ff = build_model()
    mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
    strat = optimize(ff, budget=200, mesh=mesh, seed=0,
                     use_native=False, chains=1)
    sim = Simulator(ff, mesh)
    train_trace = "/tmp/explain_smoke_train_trace.json"
    summ = sim.export_schedule(strat, train_trace)
    doc = check_trace_schema(train_trace)
    full = sim.simulate(strat)
    ends = [e["args"]["t_end_s"] for e in doc["traceEvents"]
            if e["ph"] == "X" and "t_end_s" in e.get("args", {})]
    if max(ends) != full or doc["metadata"]["makespan_s"] != full \
            or summ["makespan_s"] != full:
        print(f"FAIL: train schedule-trace end {max(ends)!r} != "
              f"simulate() makespan {full!r}")
        return 1
    arch = serve_arch()
    serve_trace = "/tmp/explain_smoke_serve_trace.json"
    ssum = export_serve_schedule(arch, 4, serve_trace)
    sdoc = check_trace_schema(serve_trace)
    sref = simulate_serve_step(arch, 4)
    sends = [e["args"]["t_end_s"] for e in sdoc["traceEvents"]
             if e["ph"] == "X" and "t_end_s" in e.get("args", {})]
    if max(sends) != sref or ssum["makespan_s"] != sref:
        print(f"FAIL: serve schedule-trace end {max(sends)!r} != "
              f"simulate_serve_step {sref!r}")
        return 1
    gates.append("schedule_trace: schema ok, makespan bit-exact "
                 "(train+serve)")

    # ---- 2. search tracing: pure observation + artifact presence ---
    trace = ff.search_stats.get("trace")
    if not trace or trace.get("proposals", 0) <= 0:
        print("FAIL: traced search recorded no proposals")
        return 1
    ff.config.search_trace = False
    strat_off = optimize(ff, budget=200, mesh=mesh, seed=0,
                         use_native=False, chains=1)
    ff.config.search_trace = True
    on = {k: dict(v.axis_map) for k, v in strat.op_strategies.items()}
    off = {k: dict(v.axis_map)
           for k, v in strat_off.op_strategies.items()}
    if on != off:
        print("FAIL: search results differ with tracing on vs off at "
              "the same seed")
        return 1
    bench = os.path.join(ROOT, "BENCH_search.json")
    have_trace_record = False
    try:
        with open(bench) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(r, dict) \
                        and r.get("metric") == "search_trace":
                    have_trace_record = True
    except OSError:
        pass
    if not have_trace_record:
        print(f"FAIL: no search_trace record in {bench} "
              f"(run python tools/search_bench.py)")
        return 1
    gates.append(f"search_trace: on==off bit-identical, "
                 f"{trace['proposals']} proposals at "
                 f"{trace['acceptance_rate']:.1%} acceptance, "
                 f"bench artifact carries the record")

    # ---- 3. memory ledger within 5% + explain sums exact -----------
    lm = build_lm(metrics_port=0)
    eng = ServeEngine(lm)
    try:
        eng.warmup()
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(1, 89, size=rng.randint(4, 24)))
                   for _ in range(4)]
        eng.generate(prompts, 4)
        led = eng.memory_ledger()
        ratio = led["ledger_vs_live"]
        if not led["pools_live"] or ratio is None \
                or abs(ratio - 1.0) > 0.05:
            print(f"FAIL: memory ledger off by more than 5% vs live "
                  f"device buffers (ratio {ratio!r})")
            return 1
        from flexflow_tpu.search.explain import explain_placement
        info = explain_placement(ff, mesh=mesh, strategy=strat,
                                 top_k=2)
        for o in info["ops"]:
            if sum(o["components"].values()) != o["total_s"]:
                print(f"FAIL: explain_placement components of "
                      f"{o['op']} do not sum to its priced cost")
                return 1
        gates.append(f"memory_ledger: ledger/live {ratio:.4f} "
                     f"(<=5%), explain sums exact over "
                     f"{len(info['ops'])} ops")

        # ---- 4. /metrics + /healthz scrape -------------------------
        port = eng.metrics_server.port
        h = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10)
        if h.status != 200 or h.read() != b"ok\n":
            print("FAIL: /healthz scrape")
            return 1
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        for ln in page.strip().splitlines():
            if ln.startswith("#"):
                continue
            name, _, val = ln.rpartition(" ")
            float(val)  # every sample line must parse
            assert name, ln
        if "serve_tokens_generated_total" not in page \
                or "serve_hbm_bytes" not in page:
            print("FAIL: /metrics page missing required series")
            return 1
    finally:
        eng.close()
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                               timeout=2)
        print("FAIL: metrics endpoint still up after close()")
        return 1
    except Exception:
        pass
    gates.append("metrics_endpoint: /metrics parses + /healthz ok, "
                 "down after close()")

    print("explain smoke OK: " + "; ".join(gates))
    return 0


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="run the observability CI gate (ci.sh 1l)")
    ap.add_argument("--serve", action="store_true",
                    help="explain the serve placement instead of the "
                         "training search")
    ap.add_argument("--budget", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--top-k", type=int, default=3)
    ap.add_argument("--trace", default=None,
                    help="also export the simulated schedule as a "
                         "Perfetto trace here")
    ap.add_argument("-o", "--out", default=None,
                    help="write the full explain JSON here")
    args = ap.parse_args()

    if args.smoke:
        return smoke()

    if args.serve:
        from flexflow_tpu.search.serve_place import optimize_serve
        from flexflow_tpu.search.simulator import (
            export_serve_schedule, serve_step_breakdown)
        arch = serve_arch()
        place = optimize_serve(arch, 4, seed=args.seed)
        bd = serve_step_breakdown(arch, place.tensor_parallel,
                                  axis_dims=place.axis_dims)
        print(f"serve placement: t={place.tensor_parallel} "
              f"dims={place.axis_dims} decode "
              f"{place.decode_step_s*1e3:.3f} ms "
              f"({place.speedup_vs_single():.2f}x vs t=1)")
        print("decode by degree: " + " ".join(
            f"t{t}={v*1e3:.3f}ms"
            for t, v in place.decode_by_degree.items()))
        print("breakdown: " + " ".join(
            f"{k}={v*1e3:.3f}ms" for k, v in bd.items()))
        if place.trace:
            print(f"walk: {place.trace['proposals']} proposals at "
                  f"{place.trace['acceptance_rate']:.1%} acceptance, "
                  f"{place.trace['improvements']} improvements")
        # the 2-D (tensor x data) pool placement over the same budget
        # (search/serve_place.optimize_serve_mesh, docs/search.md
        # "2-D serve mesh"): chosen cell, priced goodput, and every
        # rejected neighbor cell WITH its price — the same
        # chosen-vs-rejected discipline as the training explain
        from flexflow_tpu.search.serve_place import (MeshTraffic,
                                                     optimize_serve_mesh)
        # a 16-chip budget: the demo model over-fills one device's
        # HBM up through t=4, so the low degrees render as REJECTED
        # (with their residency) and only the sharded cells are priced
        mesh = optimize_serve_mesh(
            arch, 16, seed=args.seed,
            traffic=MeshTraffic(arrival_rps=0.2, prefix_hit=0.5,
                                slo_tpot_s=0.6, slo_ttft_s=120.0))
        print(f"2-D pool placement: t={mesh.tensor_parallel} x "
              f"r={mesh.replicas} over {mesh.num_devices} devices, "
              f"priced goodput {mesh.goodput_per_s:.1f} req/s "
              f"(tpot {mesh.mixed_step_s*1e3:.3f} ms)")
        chosen = (mesh.tensor_parallel, mesh.replicas)
        rejected = sorted(
            (k for k in mesh.table if k != chosen),
            key=lambda k: -mesh.table[k]["goodput_per_s"])
        if rejected:
            print("  rejected cells: " + ", ".join(
                f"t{t}xr{r} @ "
                f"{mesh.table[(t, r)]['goodput_per_s']:.1f}/s"
                for t, r in rejected))
        for d in mesh.infeasible:
            print(f"  infeasible: t={d['tensor']} ({d['reason']})")
        out = {"placement": {
            "tensor_parallel": place.tensor_parallel,
            "axis_dims": list(place.axis_dims),
            "decode_step_s": place.decode_step_s,
            "prefill_step_s": place.prefill_step_s,
            "decode_by_degree": place.decode_by_degree,
            "breakdown_s": bd, "trace": place.trace},
            "mesh_placement": {
                "tensor_parallel": mesh.tensor_parallel,
                "replicas": mesh.replicas,
                "tensor_axis_dims": list(mesh.tensor_axis_dims),
                "data_axis_dims": list(mesh.data_axis_dims),
                "goodput_per_s": mesh.goodput_per_s,
                "table": {f"{t}x{r}": c
                          for (t, r), c in mesh.table.items()},
                "infeasible": list(mesh.infeasible),
                "traffic": mesh.traffic, "trace": mesh.trace}}
        if args.trace:
            out["schedule_trace"] = export_serve_schedule(
                arch, place.tensor_parallel, args.trace,
                axis_dims=place.axis_dims)
            print(f"wrote {args.trace}")
    else:
        from flexflow_tpu import make_mesh
        from flexflow_tpu.search.explain import (explain_placement,
                                                 explain_report)
        from flexflow_tpu.search.mcmc import optimize
        from flexflow_tpu.search.simulator import Simulator
        from flexflow_tpu.utils.profiling import search_report

        ff = build_model()
        mesh = make_mesh((2, 2, 2), ("data", "model", "seq"))
        strat = optimize(ff, budget=args.budget, mesh=mesh,
                         seed=args.seed, use_native=False)
        sim = Simulator(ff, mesh)
        info = explain_placement(ff, mesh=mesh, strategy=strat,
                                 simulator=sim, top_k=args.top_k)
        print(explain_report(info))
        print()
        print(search_report(ff.search_stats))
        ledger = ff.memory_ledger()
        print("train ledger: " + " ".join(
            f"{k}={v/2**20:.2f}MiB" for k, v in ledger.items()
            if k.endswith("_bytes") and v is not None))
        out = {"explain": info, "search_stats": {
            k: v for k, v in ff.search_stats.items()
            if isinstance(v, (int, float, str, dict, list))},
            "memory_ledger": ledger}
        if args.trace:
            out["schedule_trace"] = sim.export_schedule(strat,
                                                        args.trace)
            print(f"wrote {args.trace}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2, default=str)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
