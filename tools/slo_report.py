"""SLO burn-rate report + the burn-monitor CI gate (ci.sh step 1o).

Two modes:

* Default: render the SLO burn state from a metrics snapshot
  (``Telemetry.metrics_snapshot()`` JSON or a registry ``snapshot()``)
  — burn rates per window, budget remaining, violation split by
  bound, attainment — the human view of what ``utils/slo.py``
  exported.

      python tools/slo_report.py --snapshot /tmp/snap.json

* ``--smoke`` (tools/ci.sh step 1o): gates the burn-rate monitor's
  contract with NO jax dependency (pure host Python, runs in
  milliseconds):
    1. a deterministic three-phase traffic history (healthy ->
       outage -> recovery) drives a monitor through fire AND clear —
       the alert transitions land at the expected ticks;
    2. replay determinism: a second monitor fed the identical counter
       history produces bit-identical transition events (the
       replayable-alerts contract the ReplicaPool inherits by ticking
       on its virtual clock);
    3. alert telemetry: the episode emits slo_alert_fire /
       slo_alert_clear instants and one complete slo_alert span on
       the (serve, slo) track;
    4. gauges: slo_burn_rate{window} / slo_budget_remaining /
       slo_alert_firing are present and parse in the Prometheus text;
    5. the healthy phase alone never fires (budget-level noise is not
       an alert).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from flexflow_tpu.utils.slo import SLO_DIMS, SLOBurnMonitor  # noqa: E402
from flexflow_tpu.utils.telemetry import (REQUEST_COMPONENTS,  # noqa: E402
                                          MetricsRegistry, Telemetry)


def _g(gauges: dict, name: str, default=0.0, **labels):
    key = name
    if labels:
        body = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
        key = f"{name}{{{body}}}"
    return gauges.get(key, default)


def render_snapshot(snap: dict) -> str:
    """Render the burn state from a metrics snapshot (the ``metrics``
    block of ``Telemetry.metrics_snapshot()``, or a bare registry
    snapshot)."""
    m = snap.get("metrics", snap)
    gauges = m.get("gauges", {})
    counters = m.get("counters", {})
    total = _g(counters, "serve_slo_requests_total")
    viol = _g(counters, "serve_slo_violations_total")
    lines = ["SLO burn-rate report"]
    lines.append(
        f"requests counted: {total:.0f}, violations: {viol:.0f} "
        f"(attainment "
        f"{(total - viol) / total if total else 1.0:.2%}, "
        f"error budget "
        f"{_g(gauges, 'slo_error_budget', 0.01):.2%})")
    lines.append(
        f"burn rate: fast="
        f"{_g(gauges, 'slo_burn_rate', window='fast'):.2f}x "
        f"slow={_g(gauges, 'slo_burn_rate', window='slow'):.2f}x, "
        f"budget remaining "
        f"{_g(gauges, 'slo_budget_remaining', 1.0):.1%}, "
        f"alert "
        f"{'FIRING' if _g(gauges, 'slo_alert_firing') else 'ok'}")
    split = ", ".join(
        f"{d}={_g(counters, 'serve_slo_violations_total', slo=d):.0f}"
        for d in SLO_DIMS)
    lines.append(f"violations by bound: {split}")
    fired = _g(counters, "slo_alerts_total", direction="fire")
    cleared = _g(counters, "slo_alerts_total", direction="clear")
    if fired or cleared:
        lines.append(f"alert episodes: {fired:.0f} fired / "
                     f"{cleared:.0f} cleared")
    att = {c: _g(counters, "serve_latency_attribution_seconds_total",
                 component=c)
           for c in REQUEST_COMPONENTS}
    if any(att.values()):
        tot = sum(att.values())
        lines.append("latency attribution: " + " ".join(
            f"{c}={v / tot:.1%}" for c, v in att.items() if v > 0))
    return "\n".join(lines)


def render_monitor(mon: SLOBurnMonitor) -> str:
    """Render a live monitor: the snapshot view plus its transition
    history (virtual-time, replay-exact)."""
    s = mon.snapshot()
    lines = [
        f"SLO: ttft<={s['slo'].get('ttft_s', 0) * 1e3:.2f}ms "
        f"tpot<={s['slo'].get('tpot_s', 0) * 1e3:.3f}ms, "
        f"error budget {s['error_budget']:.2%} "
        f"(windows {s['fast_window_s']:.3g}s/{s['slow_window_s']:.3g}s, "
        f"thresholds {s['fast_burn_threshold']:.1f}x/"
        f"{s['slow_burn_threshold']:.1f}x)"]
    lines.append(
        f"state: {s['state']} ({s['episodes']} episode(s)), "
        f"burn fast={s['burn_fast']:.2f}x slow={s['burn_slow']:.2f}x, "
        f"budget remaining {s['budget_remaining']:.1%}")
    lines.append(
        f"requests {s['requests']:.0f} / violations "
        f"{s['violations']:.0f} "
        f"({', '.join(f'{d}={v:.0f}' for d, v in s['violations_by_slo'].items())})")
    for e in s["events"]:
        lines.append(
            f"  t={e['t']:.4f} -> {e['state']} "
            f"(fast {e.get('burn_fast', 0):.1f}x, "
            f"slow {e.get('burn_slow', 0):.1f}x, "
            f"budget {e.get('budget_remaining', 0):.1%})")
    return "\n".join(lines)


def _drive(mon: SLOBurnMonitor, history) -> None:
    """Replay a (t, total, viol, viol_ttft, viol_tpot) counter history
    through a monitor: counters are absolute-set before each tick, so
    the monitor observes exactly the exported-registry path."""
    m = mon.registry
    for t, total, viol, vt, vp in history:
        m.counter_set("serve_slo_requests_total", total)
        m.counter_set("serve_slo_violations_total", viol)
        m.counter_set("serve_slo_violations_total", vt, slo="ttft")
        m.counter_set("serve_slo_violations_total", vp, slo="tpot")
        m.counter_set("serve_slo_violations_total", 0, slo="outcome")
        mon.observe(t)


def _history():
    """The deterministic three-phase outage story: 200 ticks at 1s,
    ~20 req/tick. Healthy (0.5% violations — half the 1% budget),
    outage at t in [60, 90) (50% violations), recovery after."""
    hist = []
    total = viol = vt = 0
    for t in range(1, 201):
        total += 20
        if 60 <= t < 90:
            viol += 10
            vt += 10
        elif t % 10 == 0:
            viol += 1
            vt += 1
        hist.append((float(t), total, viol, vt, 0))
    return hist


def smoke() -> int:
    fails = []

    def gate(name, ok, detail=""):
        print(f"  {'PASS' if ok else 'FAIL'}: {name}"
              + (f" ({detail})" if detail else ""))
        if not ok:
            fails.append(name)

    def monitor(tel=None):
        reg = tel.metrics if tel is not None else MetricsRegistry()
        return SLOBurnMonitor(
            reg, error_budget=0.01, fast_window_s=10.0,
            slow_window_s=40.0, fast_burn=14.4, slow_burn=6.0,
            interval_s=1.0, telemetry=tel,
            slo={"ttft_s": 0.1, "tpot_s": 0.01})

    hist = _history()
    tel = Telemetry()
    mon = monitor(tel)
    _drive(mon, hist)
    mon.finish(hist[-1][0])

    # 1. fire AND clear at the outage boundaries
    states = [e["state"] for e in mon.events]
    gate("alert fires and clears", states == ["firing", "ok"],
         f"events={mon.events}")
    if mon.events:
        t_fire = mon.events[0]["t"]
        gate("fires inside the outage window", 60 <= t_fire < 90,
             f"t_fire={t_fire}")
    # 2. replay determinism
    mon2 = monitor()
    _drive(mon2, hist)
    mon2.finish(hist[-1][0])
    gate("transitions replay bit-identically",
         mon.events == mon2.events)
    # 3. telemetry spans
    names = [ev[2] for ev in tel.events]
    gate("fire/clear instants + episode span emitted",
         "slo_alert_fire" in names and "slo_alert_clear" in names
         and "slo_alert" in names, f"names={sorted(set(names))}")
    # 4. gauges + Prometheus text
    g = mon.registry.gauges
    need = ['slo_burn_rate{window="fast"}',
            'slo_burn_rate{window="slow"}', "slo_budget_remaining",
            "slo_alert_firing"]
    gate("burn gauges exported", all(k in g for k in need),
         f"missing={[k for k in need if k not in g]}")
    text = mon.registry.to_prometheus()
    gate("prometheus text carries slo series",
         "slo_burn_rate" in text and "slo_budget_remaining" in text)
    # 5. the healthy phase alone never fires
    mon3 = monitor()
    _drive(mon3, [h for h in hist if h[0] < 60])
    gate("healthy traffic never alerts", mon3.events == [])

    print()
    print(render_monitor(mon))
    print()
    print(render_snapshot({"metrics": mon.registry.snapshot()}))
    if fails:
        print(f"\nSLO REPORT SMOKE FAILED: {fails}", file=sys.stderr)
        return 1
    print("\nSLO REPORT SMOKE PASSED")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="run the burn-monitor CI gate (ci.sh 1o)")
    ap.add_argument("--snapshot", metavar="PATH",
                    help="render a metrics snapshot JSON "
                         "(Telemetry.metrics_snapshot() output)")
    args = ap.parse_args()
    if args.smoke:
        return smoke()
    if args.snapshot:
        with open(args.snapshot) as f:
            print(render_snapshot(json.load(f)))
        return 0
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
