"""DLRM strategy generator — the reference ships a C++/py generator
that emits per-GPU embedding placements as strategy files
(examples/cpp/DLRM/strategies/{dlrm_strategy.cc,dlrm_strategy.py,
gen_strategy.sh}); this is the TPU-native analog, emitting the SAME
placements in both supported formats. Unlike the reference's, the
output executes here without a custom mapper: per-table device ids
lower to the slot layout (ops/embedding.py apply_placement).

  python tools/gen_dlrm_strategy.py --tables 26 --devices 8 \
      --scheme round_robin --out dlrm_strategy.json
  # --format text emits the reference text format (strategy.cc)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def assignment(tables: int, devices: int, scheme: str):
    # cheap validation BEFORE the heavyweight import; the formulas
    # themselves live in parallel/pconfig.placement_assignment so the
    # generator and the MCMC candidate space can never diverge
    if tables < 1 or devices < 1:
        raise SystemExit(
            f"--tables and --devices must be >= 1, got {tables}/{devices}")
    from flexflow_tpu.parallel.pconfig import placement_assignment
    try:
        return placement_assignment(tables, devices, scheme)
    except ValueError as e:
        raise SystemExit(str(e))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tables", type=int, default=26)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--scheme", default="round_robin",
                    choices=["round_robin", "blocked", "one_device"])
    ap.add_argument("--op-name", default="emb_tables",
                    help="distributed_embedding op name "
                         "(build_dlrm(stacked_tables=True) uses "
                         "'emb_tables')")
    ap.add_argument("--format", default="json", choices=["json", "text"])
    ap.add_argument("--out", default="dlrm_strategy.json")
    args = ap.parse_args()

    # validate + compute BEFORE the heavyweight jax import so bad
    # arguments fail instantly
    ids = assignment(args.tables, args.devices, args.scheme)

    if args.format == "json":
        from flexflow_tpu.parallel.pconfig import (
            DEVICE_KEY,
            OpStrategy,
            Strategy,
        )
        strat = Strategy(default=OpStrategy({"sample": "data"}))
        strat.set(args.op_name, OpStrategy({DEVICE_KEY: ids}))
        strat.save(args.out)
    else:
        # reference text format needs the op graph for output dims; a
        # single tpu_pin line is enough for the import path
        # (strategy_io.load_strategies_from_file keys on op name)
        with open(args.out, "w") as f:
            f.write("1\n")
            f.write(f"{args.op_name} tpu_pin 1 1 "
                    + " ".join(str(i) for i in ids) + "\n")
    print(f"{args.out}: {args.op_name} <- {args.scheme} over "
          f"{args.devices} devices: {ids}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
