"""Sim-vs-real validation across ALL FIVE bench model families
(VERDICT r3 #6 / weak #8: the <30% claim covered one model).

For each model: build a host-scale config, compile, and run
`FFModel.calibrate_simulator` — which measures real training steps and
returns the simulator's PRE-calibration prediction — twice: analytic
costs only, then with per-op measured grounding
(FFConfig.measure_top_ops, search/op_measure.py). Writes the committed
table evidence/sim_validation_<platform>.json with per-model predicted/
measured/error rows for both modes.

Platform note: on the forced-CPU mesh the machine model's TPU roofline
does not describe the executing hardware, so ANALYTIC error is
expected to be large — what this table demonstrates on CPU is that
per-op MEASURED grounding collapses the error (the mechanism VERDICT
asks for: grounding beats family factors wherever family factors are
wrong). The TPU leg (tools/tpu_session.sh step 3) produces the on-chip table
against BASELINE.md's <30% envelope.

Run: python tools/sim_validation.py [--quick]
"""

import json
import os
import sys

import jax

# default CPU (the always-available validation platform); the TPU
# session runs with SIM_VALIDATION_PLATFORM=tpu for the on-chip table
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform import select_platform  # noqa: E402

_plat = select_platform("SIM_VALIDATION_PLATFORM")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from flexflow_tpu import FFConfig, SGDOptimizer  # noqa: E402
from flexflow_tpu import models as zoo  # noqa: E402


def configs():
    """(name, builder, kwargs, batch) at host-validation scale."""
    return [
        ("alexnet", zoo.build_alexnet, {}, 16),
        ("inception", zoo.build_inception_v3, {}, 4),
        ("dlrm", zoo.build_dlrm,
         {"embedding_vocab_sizes": (10000,) * 8, "embedding_dim": 16,
          "bot_mlp": (64, 16), "top_mlp": (64, 2),
          "stacked_tables": True}, 64),
        ("transformer", zoo.build_transformer,
         {"num_layers": 2, "hidden": 128, "num_heads": 4,
          "ff_dim": 256, "seq_len": 64}, 8),
        ("nmt_lstm", zoo.build_nmt_lstm,
         {"vocab_size": 2000, "embed_dim": 128, "hidden": 128,
          "seq_len": 32, "num_layers": 1}, 16),
    ]


def one(name, builder, kw, batch, measure_ops):
    cfg = FFConfig(batch_size=batch)
    cfg.measure_top_ops = measure_ops
    ff = builder(cfg, **kw)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    measured, predicted = ff.calibrate_simulator(steps=5)
    fingerprint = None
    if ff.simulator is not None:
        # persist the per-op costs under the machine fingerprint so the
        # measured-mode pass (and any re-run of this table) prices from
        # the shared cost cache instead of re-measuring; report the
        # fingerprint the entries were actually written under
        ff.simulator.flush_cost_cache()
        fingerprint = ff.simulator._fingerprint
    if measured < 0.02:
        # sub-20ms steps: 5 steps is inside dispatch-jitter noise (the
        # dlrm row swung -7% -> -41% between otherwise-identical runs);
        # re-measure over enough steps to amortize it
        measured, predicted = ff.calibrate_simulator(steps=200)
    return {"measured_ms": measured * 1e3,
            "predicted_ms": predicted * 1e3,
            "error_pct": 100.0 * (predicted - measured) / measured,
            "fingerprint": fingerprint}


def main():
    quick = "--quick" in sys.argv
    rows = {}
    for name, builder, kw, batch in configs():
        if quick and name == "inception":
            continue  # ~5 min XLA CPU compile
        entry = {}
        # N caps measurement signatures (shape classes). Inception has
        # ~90 DISTINCT conv shapes plus a BatchNorm after every one of
        # them — the budget must reach past the convs into the
        # memory-bound BN/pool/concat signatures or they stay at the
        # (platform-mismatched) analytic price
        deep = 192 if name == "inception" else 8
        for mode, n in (("analytic", 0), ("measured", deep)):
            try:
                entry[mode] = one(name, builder, kw, batch, n)
                print(f"{name:12s} {mode:9s} "
                      f"pred {entry[mode]['predicted_ms']:9.2f} ms  "
                      f"real {entry[mode]['measured_ms']:9.2f} ms  "
                      f"err {entry[mode]['error_pct']:+7.1f}%",
                      flush=True)
            except Exception as e:  # record, keep sweeping
                entry[mode] = {"error": str(e)[:200]}
                print(f"{name:12s} {mode:9s} FAILED: {e}", flush=True)
        rows[name] = entry
    platform = jax.default_backend()
    # stamp the machine-model fingerprint (search/cost_cache.py) the
    # runs' simulators actually keyed their persistent cost-cache
    # entries under: the committed table is attributable to one
    # machine + cost-model state, and re-runs price from that cache
    # instead of re-measuring. Rows carry per-run fingerprints (they
    # should all agree — single-device meshes, one machine); the
    # top-level field is the consensus.
    fps = {e.get("fingerprint") for entry in rows.values()
           for e in entry.values() if e.get("fingerprint")}
    out = {"platform": platform,
           "fingerprint": (fps.pop() if len(fps) == 1
                           else sorted(fps) or None),
           "rows": rows,
           "note": ("CPU: analytic TPU-roofline error is expected; the "
                    "table demonstrates measured grounding collapsing "
                    "it. TPU leg via tools/tpu_session.sh.")}
    suffix = ""
    if quick:
        # a quick run covers four of the five families — it must not
        # silently shrink the committed five-model table
        out["note"] += " QUICK RUN: inception skipped."
        suffix = "_quick"
    path = os.path.join(os.path.dirname(__file__), "..", "evidence",
                        f"sim_validation_{platform}{suffix}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {os.path.normpath(path)}")


if __name__ == "__main__":
    main()
