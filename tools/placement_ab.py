"""Placement A/B: measured step time vs simulator ranking for
device-explicit embedding placement (VERDICT r2 #5).

Reference analog: DLRM's strategy generator emits per-GPU table
placements (examples/cpp/DLRM/strategies/dlrm_strategy.cc:1-50) that
FFMapper::slice_task executes; the MCMC search justified them through
the simulator. Here the same loop closes on TPU: per-table device ids
lower to an executable slot layout (ops/embedding.py apply_placement),
and this script checks the simulator's placement win against measured
wall-clock on the live mesh.

Run on the 8-CPU virtual mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/placement_ab.py
or on real multi-chip TPU (no env needed). Prints one line per variant
plus a verdict comparing measured vs simulated orderings.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(tables=8, vocab=None, dim=64, bs=None, steps=20):
    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    on_cpu = jax.devices()[0].platform == "cpu"
    # CPU mesh: keep compiles in seconds — the ranking signal (gather
    # spread over devices vs serialized on one) survives small shapes
    vocab = vocab or (20_000 if on_cpu else 200_000)
    bs = bs or (256 if on_cpu else 1024)

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, Strategy, \
        make_mesh
    from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy
    from flexflow_tpu.search.simulator import Simulator

    n = len(jax.devices())
    if n < 2:
        # single chip (e.g. the tunnel lease): placement has nothing to
        # spread over — fall back to the 8-device virtual CPU mesh so
        # the run still produces a ranking artifact
        print(json.dumps({"skipped": "1 device; re-run with "
                          "XLA_FLAGS=--xla_force_host_platform_device_"
                          "count=8 JAX_PLATFORMS=cpu"}), flush=True)
        return 0
    mesh = make_mesh((n,), ("data",))

    def build(strategy):
        cfg = FFConfig()
        cfg.batch_size = bs
        ff = FFModel(cfg, mesh=mesh, strategy=strategy)
        ins = [ff.create_tensor((bs, 1), dtype=np.int32, name=f"s{i}")
               for i in range(tables)]
        embs = ff.distributed_embedding(ins, vocab, dim, name="tables")
        t = ff.concat(embs, axis=1)
        t = ff.dense(t, 64, activation="relu", name="top1")
        t = ff.dense(t, 4, name="top2")
        ff.softmax(t)
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[], mesh=mesh, strategy=strategy)
        return ff

    def strat(extra):
        s = Strategy(default=OpStrategy({"sample": "data"}))
        s.set("tables", OpStrategy(extra))
        return s

    variants = {
        "placed_round_robin": strat(
            {DEVICE_KEY: tuple(t % n for t in range(tables))}),
        "placed_one_device": strat({DEVICE_KEY: (0,) * tables}),
        "replicated": strat({}),
    }

    rng = np.random.RandomState(0)
    batch = {f"s{i}": rng.randint(0, vocab, (bs, 1)).astype(np.int32)
             for i in range(tables)}
    batch["label"] = rng.randint(0, 4, bs).astype(np.int32)

    results = {}
    for name, s in variants.items():
        ff = build(s)
        sim = Simulator(ff, mesh)
        predicted = sim.simulate(s)
        ff.train_batch(batch)  # compile + warmup
        t0 = time.perf_counter()
        for _ in range(steps):
            m = ff.train_batch(batch)
        float(m["loss"])  # drain (tunnel: only host fetch syncs)
        dt = (time.perf_counter() - t0) / steps
        results[name] = {"measured_ms": round(dt * 1e3, 3),
                         "simulated_ms": round(predicted * 1e3, 6)}
        print(f"{name:22s} measured {dt * 1e3:9.3f} ms/step   "
              f"simulated {predicted * 1e3:9.3f} ms", flush=True)

    meas = sorted(results, key=lambda k: results[k]["measured_ms"])
    pred = sorted(results, key=lambda k: results[k]["simulated_ms"])
    verdict = {
        "measured_order": meas,
        "simulated_order": pred,
        "placement_win_measured":
            results["placed_round_robin"]["measured_ms"]
            < results["placed_one_device"]["measured_ms"],
        "placement_win_simulated":
            results["placed_round_robin"]["simulated_ms"]
            < results["placed_one_device"]["simulated_ms"],
        "results": results,
    }
    print(json.dumps(verdict), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
