"""Launcher — `python -m flexflow_tpu [options] script.py [args]`.

The TPU-native analog of the reference's `flexflow_python` interpreter
binary + `flexflow.py` launcher (python/main.cc:91-107 registers the
Python top-level task; flexflow/core/flexflow_top.py:164-220 runs the
user script in script / -c / REPL modes; python/flexflow.py translates
--nodes/--gpus into Legion -ll:* flags).  Here there is no embedded
interpreter to bootstrap — JAX is single-controller — so the launcher's
job is platform setup + script execution:

  python -m flexflow_tpu train.py -b 64 --search-budget 1000
  python -m flexflow_tpu -c "import flexflow_tpu; print(flexflow_tpu.__name__)"
  python -m flexflow_tpu --cpu-devices 8 train.py   # virtual CPU mesh

Launcher-only flags (consumed before the script sees argv):
  --cpu-devices N     force the CPU platform with N virtual devices — the
                      test rig for multi-chip sharding without TPUs
  --coordinator A:P   multi-host: jax.distributed coordinator address
                      (the analog of the reference's mpirun bootstrap,
                      python/flexflow.py — one process per host, Legion
                      control replication → JAX multi-controller SPMD)
  --num-processes N   multi-host: total process count
  --process-id I      multi-host: this process's rank
  -c CODE             run a code string instead of a script
Everything else is left on sys.argv for FFConfig.from_args().
"""

from __future__ import annotations

import os
import runpy
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    if argv and argv[0] in ("--help", "-h"):
        print("usage: flexflow-tpu [--cpu-devices N] "
              "[--coordinator HOST:PORT --num-processes N --process-id I] "
              "(SCRIPT [ARGS...] | -c CODE | <no args for REPL>)\n\n"
              "Runs a user script under the flexflow_tpu runtime "
              "(reference: flexflow_python / python/flexflow.py launcher).")
        return 0

    cpu_devices = None
    code = None
    coordinator = num_processes = process_id = None
    i = 0
    while i < len(argv):
        if argv[i] == "--cpu-devices" and i + 1 < len(argv):
            cpu_devices = int(argv[i + 1])
            del argv[i:i + 2]
        elif argv[i] == "--coordinator" and i + 1 < len(argv):
            coordinator = argv[i + 1]
            del argv[i:i + 2]
        elif argv[i] == "--num-processes" and i + 1 < len(argv):
            num_processes = int(argv[i + 1])
            del argv[i:i + 2]
        elif argv[i] == "--process-id" and i + 1 < len(argv):
            process_id = int(argv[i + 1])
            del argv[i:i + 2]
        elif argv[i] == "-c" and i + 1 < len(argv):
            code = argv[i + 1]
            del argv[i:i + 2]
        else:
            break

    if coordinator is not None:
        # outside auto-detecting cluster environments (GKE/SLURM), JAX
        # cannot infer these; fail with a launcher error, not a deep
        # jax.distributed traceback (reference launcher python/flexflow.py
        # derives ranks from mpirun for the same reason)
        if num_processes is None or process_id is None:
            print("flexflow_tpu: --coordinator requires --num-processes "
                  "and --process-id (they are only auto-detected inside "
                  "cluster environments like SLURM/GKE)", file=sys.stderr)
            return 2
        import jax
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)

    if cpu_devices is not None:
        kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")]
        os.environ["XLA_FLAGS"] = " ".join(
            kept + [f"--xla_force_host_platform_device_count={cpu_devices}"])
        import jax
        # env var alone can be overridden by image sitecustomize; force it
        jax.config.update("jax_platforms", "cpu")

    if code is not None:
        sys.argv = ["-c"] + argv
        exec(compile(code, "<string>", "exec"), {"__name__": "__main__"})
        return 0

    if not argv:
        # REPL mode (reference flexflow_top.py run_repl)
        import code as code_mod
        code_mod.interact(banner="flexflow_tpu interactive shell")
        return 0

    script, script_args = argv[0], argv[1:]
    sys.argv = [script] + script_args
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)))
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
