"""Pallas TPU kernels — the in-tree native-kernel equivalents of the
reference's src/ops/*.cu (SURVEY.md section 7 step 9)."""
